"""Multi-process signature-verification workers.

One process tops out around 3.5k verified sigs/s (PERF.md §4) and — far
worse — shares the GIL and the core budget with the epoch loop.  The
pool here moves the expensive half of admission (Poseidon message
hashing + batch EdDSA) into spawned worker processes, each owning its
own native runtime (``crypto.native`` loads per process; the
initializer pins ``OMP_NUM_THREADS=1`` so W workers are W cores, not
W×threads oversubscription).

Work items are flat integer tuples — no protocol objects cross the
process boundary, so a worker's import footprint is just the pure
crypto tree — and every batch result is per-item booleans in submit
order.  Worker death is a first-class outcome: the pool rebuilds the
executor and the caller's in-flight batch is retried up to
``max_retries`` times, after which :class:`VerifyCrashed` carries the
batch out to be *rejected with a reason code*, never silently dropped.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import BrokenExecutor, ProcessPoolExecutor
from multiprocessing import get_context

from ..obs import metrics as obs_metrics
from ..obs.journal import JOURNAL

#: Chaos hook for crash-recovery tests and the ingest-storm bench's
#: worker-crash mix: a work item equal to this string hard-kills the
#: worker mid-batch (``os._exit``), exactly like an OOM kill would.
CRASH_MARKER = "__crash-worker__"

#: (sig.R.x, sig.R.y, sig.s, pk.x, pk.y, scores tuple) — everything the
#: worker needs to bind and verify one attestation signature.
WorkItem = tuple[int, int, int, int, int, tuple[int, ...]]


def _worker_init() -> None:
    """Runs in each spawned worker before any batch: pin the native
    runtime to one OpenMP thread so the pool scales by process, and
    pre-load the crypto tree off the critical path."""
    os.environ["OMP_NUM_THREADS"] = "1"
    from ..crypto import native as cnative

    cnative.available()


def verify_batch(pks_hash: int, items: list) -> list[bool]:
    """Hash + verify one batch (runs inside a worker, or inline for
    ``workers=0``): batched Poseidon message hashes for the shared
    ``pks_hash``, then one native batch-EdDSA call (pure-Python
    fallback when the runtime is unavailable)."""
    from ..crypto import message_hash_batch
    from ..crypto import native as cnative
    from ..crypto.babyjubjub import Point
    from ..crypto.eddsa import PublicKey, Signature, verify as verify_sig

    for item in items:
        if item == CRASH_MARKER:
            os._exit(1)
    msgs = message_hash_batch(pks_hash, [list(it[5]) for it in items])
    if cnative.available():
        ok = cnative.eddsa_verify_batch(
            [it[0] for it in items],
            [it[1] for it in items],
            [it[2] for it in items],
            [it[3] for it in items],
            [it[4] for it in items],
            msgs,
        )
        return [bool(x) for x in ok]
    return [
        verify_sig(
            Signature.new(it[0], it[1], it[2]), PublicKey(Point(it[3], it[4])), m
        )
        for it, m in zip(items, msgs)
    ]


class VerifyCrashed(RuntimeError):
    """A batch's worker died ``max_retries + 1`` times; the caller must
    reject the batch's items with a distinct reason code."""


class VerifyPool:
    """Process pool façade with crash recovery.

    ``workers=0`` verifies inline on the calling thread (no processes —
    the single-node default and the pre-ISSUE-7 behavior); ``workers>0``
    spawns that many verifier processes.  :meth:`verify` blocks until
    the batch's verdicts are in, so the plane runs one dispatcher
    thread per worker to keep all processes fed.
    """

    def __init__(self, workers: int = 0, *, max_retries: int = 1):
        self.workers = int(workers)
        self.max_retries = int(max_retries)
        self._lock = threading.Lock()
        self._generation = 0
        self._executor: ProcessPoolExecutor | None = None
        if self.workers > 0:
            self._executor = self._make()

    def _make(self) -> ProcessPoolExecutor:
        return ProcessPoolExecutor(
            max_workers=self.workers,
            mp_context=get_context("spawn"),
            initializer=_worker_init,
        )

    def _snapshot(self) -> tuple[int, ProcessPoolExecutor | None]:
        with self._lock:
            return self._generation, self._executor

    def _restart(self, generation: int) -> None:
        """Rebuild the executor once per crash: concurrent batches that
        all observed the same broken generation race here, and only the
        first replaces it."""
        with self._lock:
            if self._generation != generation or self._executor is None:
                return
            old = self._executor
            self._executor = self._make()
            self._generation += 1
        old.shutdown(wait=False, cancel_futures=True)
        obs_metrics.INGEST_WORKER_RESTARTS.inc()
        JOURNAL.record("anomaly", what="ingest-worker-crashed", generation=generation)

    def verify(self, pks_hash: int, items: list) -> list[bool]:
        """Blocking batch verdict with crash retry; raises
        :class:`VerifyCrashed` when the batch outlives its retries."""
        attempts = 0
        while True:
            generation, executor = self._snapshot()
            try:
                if executor is None:
                    return verify_batch(pks_hash, items)
                return executor.submit(verify_batch, pks_hash, items).result()
            except (BrokenExecutor, RuntimeError) as exc:
                # RuntimeError covers submit() on a shutdown executor
                # racing close(); treat it like a crash for retry
                # accounting so items are never silently dropped.
                self._restart(generation)
                attempts += 1
                if attempts > self.max_retries:
                    obs_metrics.INGEST_VERIFY_BATCHES.inc(outcome="failed")
                    raise VerifyCrashed(
                        f"verify batch of {len(items)} died {attempts} time(s)"
                    ) from exc
                obs_metrics.INGEST_VERIFY_BATCHES.inc(outcome="retried")

    def close(self) -> None:
        with self._lock:
            executor, self._executor = self._executor, None
        if executor is not None:
            executor.shutdown(wait=False, cancel_futures=True)


__all__ = ["CRASH_MARKER", "VerifyCrashed", "VerifyPool", "WorkItem", "verify_batch"]
