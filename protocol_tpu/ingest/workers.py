"""Multi-process signature-verification workers.

One process tops out around 3.5k verified sigs/s (PERF.md §4) and — far
worse — shares the GIL and the core budget with the epoch loop.  The
pool here moves the expensive half of admission (Poseidon message
hashing + batch EdDSA) into spawned worker processes, each owning its
own native runtime (``crypto.native`` loads per process; the
initializer pins ``OMP_NUM_THREADS=1`` so W workers are W cores, not
W×threads oversubscription).

Work items are flat integer tuples — no protocol objects cross the
process boundary, so a worker's import footprint is just the pure
crypto tree — and every batch result is per-item booleans in submit
order.  Worker death is a first-class outcome: the pool rebuilds the
executor and the caller's in-flight batch is retried up to
``max_retries`` times, after which :class:`VerifyCrashed` carries the
batch out to be *rejected with a reason code*, never silently dropped.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from concurrent.futures import BrokenExecutor, ProcessPoolExecutor
from multiprocessing import get_context

from ..obs import metrics as obs_metrics
from ..obs.journal import (
    JOURNAL,
    collect_worker_dumps,
    install_worker_dump_handler,
)

#: Chaos hook for crash-recovery tests and the ingest-storm bench's
#: worker-crash mix: a work item equal to this string hard-kills the
#: worker mid-batch (``os._exit``), exactly like an OOM kill would.
CRASH_MARKER = "__crash-worker__"

#: (sig.R.x, sig.R.y, sig.s, pk.x, pk.y, scores tuple) — everything the
#: worker needs to bind and verify one attestation signature.
WorkItem = tuple[int, int, int, int, int, tuple[int, ...]]


def _worker_init(dump_dir: str | None = None) -> None:
    """Runs in each spawned worker before any batch: pin the native
    runtime to one OpenMP thread so the pool scales by process,
    pre-load the crypto tree off the critical path, and install the
    flight-recorder dump handler so a SIGTERM'd worker leaves its
    event ring behind for the parent's post-mortem."""
    os.environ["OMP_NUM_THREADS"] = "1"
    install_worker_dump_handler(dump_dir, pool="ingest-verify")
    from ..crypto import native as cnative

    cnative.available()


def verify_batch(pks_hash: int, items: list) -> list[bool]:
    """Hash + verify one batch (runs inside a worker, or inline for
    ``workers=0``): batched Poseidon message hashes for the shared
    ``pks_hash``, then one native batch-EdDSA call (pure-Python
    fallback when the runtime is unavailable)."""
    from ..crypto import message_hash_batch
    from ..crypto import native as cnative
    from ..crypto.babyjubjub import Point
    from ..crypto.eddsa import PublicKey, Signature, verify as verify_sig

    for item in items:
        if item == CRASH_MARKER:
            os._exit(1)
    msgs = message_hash_batch(pks_hash, [list(it[5]) for it in items])
    if cnative.available():
        ok = cnative.eddsa_verify_batch(
            [it[0] for it in items],
            [it[1] for it in items],
            [it[2] for it in items],
            [it[3] for it in items],
            [it[4] for it in items],
            msgs,
        )
        return [bool(x) for x in ok]
    return [
        verify_sig(
            Signature.new(it[0], it[1], it[2]), PublicKey(Point(it[3], it[4])), m
        )
        for it, m in zip(items, msgs)
    ]


def verify_batch_shipping(pks_hash: int, items: list) -> tuple[list, dict]:
    """Worker-process entry: verify the batch AND ship this process's
    registry snapshot back with the verdicts — the cross-process
    metric-aggregation hop.  The worker records its own sig-verify
    metrics (its registry is process-private), journals the batch into
    its flight ring, and the parent folds the snapshot into the fleet
    aggregator under a ``process`` label."""
    from ..obs.fleet import registry_snapshot

    t0 = time.perf_counter()
    verdicts = verify_batch(pks_hash, items)
    obs_metrics.SIG_VERIFY_SECONDS.observe(time.perf_counter() - t0)
    obs_metrics.SIGS_VERIFIED.inc(len(verdicts))
    JOURNAL.record("verify-batch", n=len(verdicts), ok=sum(map(bool, verdicts)))
    return verdicts, registry_snapshot(source=f"ingest-verify-{os.getpid()}")


class VerifyCrashed(RuntimeError):
    """A batch's worker died ``max_retries + 1`` times; the caller must
    reject the batch's items with a distinct reason code.
    ``flight_tail`` carries whatever per-worker flight-recorder dumps
    the pool recovered from the crash (SIGTERM'd workers dump their
    ring; hard-killed ones leave nothing)."""

    def __init__(self, *args: object) -> None:
        super().__init__(*args)
        self.flight_tail: list = []


class VerifyPool:
    """Process pool façade with crash recovery.

    ``workers=0`` verifies inline on the calling thread (no processes —
    the single-node default and the pre-ISSUE-7 behavior); ``workers>0``
    spawns that many verifier processes.  :meth:`verify` blocks until
    the batch's verdicts are in, so the plane runs one dispatcher
    thread per worker to keep all processes fed.
    """

    def __init__(self, workers: int = 0, *, max_retries: int = 1):
        self.workers = int(workers)
        self.max_retries = int(max_retries)
        self._lock = threading.Lock()
        self._generation = 0
        self._executor: ProcessPoolExecutor | None = None
        #: Flight-recorder tails recovered from crashed workers' dump
        #: files, attached to the next VerifyCrashed (under _lock).
        self._flight_tail: list = []
        self._dump_dir: str | None = (
            tempfile.mkdtemp(prefix="ingest_verify_flight_")
            if self.workers > 0
            else None
        )
        if self.workers > 0:
            self._executor = self._make()

    def _make(self) -> ProcessPoolExecutor:
        return ProcessPoolExecutor(
            max_workers=self.workers,
            mp_context=get_context("spawn"),
            initializer=_worker_init,
            initargs=(self._dump_dir,),
        )

    def _snapshot(self) -> tuple[int, ProcessPoolExecutor | None]:
        with self._lock:
            return self._generation, self._executor

    def _restart(self, generation: int) -> None:
        """Rebuild the executor once per crash: concurrent batches that
        all observed the same broken generation race here, and only the
        first replaces it.  Any flight-recorder dumps the dead workers
        left behind are journaled and kept for the next
        :class:`VerifyCrashed` so the post-mortem survives the process
        boundary."""
        with self._lock:
            if self._generation != generation or self._executor is None:
                return
            old = self._executor
            self._executor = self._make()
            self._generation += 1
        old.shutdown(wait=False, cancel_futures=True)
        tails = collect_worker_dumps(self._dump_dir, pool="ingest-verify")
        if tails:
            with self._lock:
                self._flight_tail.extend(tails)
        obs_metrics.INGEST_WORKER_RESTARTS.inc()
        JOURNAL.record("anomaly", what="ingest-worker-crashed", generation=generation)

    def take_flight_tail(self) -> list:
        """Pop the recovered worker flight-recorder events (attached to
        crashed results by :meth:`verify`)."""
        with self._lock:
            tail, self._flight_tail = self._flight_tail, []
        return tail

    def verify(self, pks_hash: int, items: list) -> list[bool]:
        """Blocking batch verdict with crash retry; raises
        :class:`VerifyCrashed` when the batch outlives its retries."""
        from ..obs.fleet import FLEET

        attempts = 0
        while True:
            generation, executor = self._snapshot()
            try:
                if executor is None:
                    return verify_batch(pks_hash, items)
                verdicts, snap = executor.submit(
                    verify_batch_shipping, pks_hash, items
                ).result()
                # The worker's registry rides back with the verdicts;
                # latest-snapshot-per-source, so cumulative counters
                # never double-count.
                FLEET.ingest(snap.get("source", "ingest-verify"), snap)
                obs_metrics.WORKER_SNAPSHOT_MERGES.inc(pool="ingest-verify")
                return verdicts
            except (BrokenExecutor, RuntimeError) as exc:
                # RuntimeError covers submit() on a shutdown executor
                # racing close(); treat it like a crash for retry
                # accounting so items are never silently dropped.
                self._restart(generation)
                attempts += 1
                if attempts > self.max_retries:
                    obs_metrics.INGEST_VERIFY_BATCHES.inc(outcome="failed")
                    crashed = VerifyCrashed(
                        f"verify batch of {len(items)} died {attempts} time(s)"
                    )
                    crashed.flight_tail = self.take_flight_tail()
                    raise crashed from exc
                obs_metrics.INGEST_VERIFY_BATCHES.inc(outcome="retried")

    def close(self) -> None:
        with self._lock:
            executor, self._executor = self._executor, None
        if executor is not None:
            executor.shutdown(wait=False, cancel_futures=True)


__all__ = [
    "CRASH_MARKER",
    "VerifyCrashed",
    "VerifyPool",
    "WorkItem",
    "verify_batch",
    "verify_batch_shipping",
]
