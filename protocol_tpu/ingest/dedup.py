"""Sharded dedup/nonce cache — replays die before the signature check.

A replayed attestation costs the node a full EdDSA verification
(~0.3 ms native) unless something cheaper rejects it first.  This cache
is that something: per-sender monotonic nonces plus a recent-message
digest set, sharded by sender so shard locks never contend across
senders, with two-generation rotation for bounded memory.

Eviction is *epoch-aligned*: the node rotates generations on every
epoch tick (``rotate_all``), so "recent" means "this epoch or the
last" — exactly the horizon inside which a replay could still perturb
the next convergence.  A shard whose current generation overflows
``hashes_per_shard`` rotates early, so a storm of unique messages
cannot grow memory without bound either.

Admission checks here are digest comparisons and dict lookups — no
field arithmetic, no Poseidon — so the cache holds the line at
intake rates far above what the verify tier can absorb.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

#: splitmix64 odd multiplier (golden-ratio constant).
_MIX = 0x9E3779B97F4A7C15
_MASK = (1 << 64) - 1


def _shard_index(sender: tuple[int, int], n_shards: int) -> int:
    """Stable shard key: a splitmix-style mix of the sender's pk
    coordinates, identical across processes and interpreter versions by
    construction.  Builtin ``hash()`` happens to be salt-free for int
    tuples in today's CPython, but shard placement is observable state
    (lock contention patterns, eviction order under ``senders_per_shard``
    pressure), and the bit-identity plane does not stand on
    implementation details — pass-13 doctrine."""
    x, y = sender
    acc = (int(x) * _MIX + int(y)) & _MASK
    acc ^= acc >> 31
    acc = (acc * 0xBF58476D1CE4E5B9) & _MASK
    acc ^= acc >> 27
    return acc % n_shards


@dataclass
class _Shard:
    """One dedup shard: lock, nonce map, and two digest generations."""

    lock: threading.Lock = field(default_factory=threading.Lock)
    #: sender key -> highest nonce admitted (monotonic-nonce senders).
    nonces: dict[tuple[int, int], int] = field(default_factory=dict)
    #: Message digests admitted this generation / the previous one.
    current: dict[bytes, None] = field(default_factory=dict)
    previous: dict[bytes, None] = field(default_factory=dict)


class ShardedDedupCache:
    """Replay/nonce filter sharded by sender hash.

    ``admit`` is the whole API surface the plane uses: it either
    rejects with a reason code (``duplicate`` / ``stale-nonce``) or
    records the digest (and nonce, when the sender supplied one) and
    admits.  Recording happens at admission time — before the
    signature verdict — so two copies of the same message racing
    through the plane cannot both reach the verify tier; the second is
    a duplicate regardless of which wins.
    """

    def __init__(
        self,
        n_shards: int = 16,
        hashes_per_shard: int = 65536,
        senders_per_shard: int = 65536,
    ):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self._shards = [_Shard() for _ in range(n_shards)]
        self.hashes_per_shard = int(hashes_per_shard)
        self.senders_per_shard = int(senders_per_shard)

    def _shard(self, sender: tuple[int, int]) -> _Shard:
        return self._shards[_shard_index(sender, len(self._shards))]

    def admit(
        self, sender: tuple[int, int], digest: bytes, nonce: int | None = None
    ) -> str | None:
        """Reason code for a rejection, or None (admitted + recorded)."""
        shard = self._shard(sender)
        with shard.lock:
            if digest in shard.current or digest in shard.previous:
                return "duplicate"
            if nonce is not None:
                last = shard.nonces.get(sender)
                if last is not None and nonce <= last:
                    return "stale-nonce"
                if (
                    sender not in shard.nonces
                    and len(shard.nonces) >= self.senders_per_shard
                ):
                    # Evict the oldest-inserted sender (dict preserves
                    # insertion order) — bounded memory under sender
                    # churn at the cost of forgetting their floor.
                    shard.nonces.pop(next(iter(shard.nonces)))
                shard.nonces[sender] = nonce
            shard.current[digest] = None
            if len(shard.current) >= self.hashes_per_shard:
                shard.previous = shard.current
                shard.current = {}
            return None

    def rotate_all(self) -> None:
        """Epoch-aligned eviction: age ``current`` into ``previous``
        and drop the old ``previous`` — after two rotations a digest is
        forgotten.  The node calls this once per epoch tick."""
        for shard in self._shards:
            with shard.lock:
                shard.previous = shard.current
                shard.current = {}

    def __len__(self) -> int:
        """Digests currently held (both generations, all shards)."""
        total = 0
        for shard in self._shards:
            with shard.lock:
                total += len(shard.current) + len(shard.previous)
        return total


__all__ = ["ShardedDedupCache"]
