"""The admission pipeline: intake → admission → verify → apply.

Four stages behind two bounded queues:

1. **intake** (:meth:`IngestPlane.submit`, any thread): O(1) —
   envelope the attestation, digest it, and ``put_nowait`` it on the
   submit queue.  A full queue **sheds**: the future resolves
   immediately with ``reason="queue-full"`` (the node maps it to HTTP
   429), the shed counter and journal record it, and the caller backs
   off.  Nothing upstream of this queue ever blocks.
2. **admission** (one thread): the cheap gates in cost order —
   structural checks, per-sender token bucket + spam score, sharded
   dedup/nonce cache — so replays and floods die for dict-lookup
   money, never reaching a signature check.  Survivors batch up
   (``batch_size`` or ``linger_s``, whichever first) onto the bounded
   batch queue; when the verify tier falls behind, the blocking put
   here backs pressure up into the submit queue, which sheds.
3. **verify** (one dispatcher thread per worker): blocking batch
   verdicts from the :class:`~protocol_tpu.ingest.workers.VerifyPool`
   — crash-retried, and rejected with ``reason="verify-crashed"``
   when a batch outlives its retries.
4. **apply**: accepted attestations land in the Manager's cache via
   :meth:`~protocol_tpu.node.manager.Manager.apply_verified` (a dict
   insert — the pk hash is already memoized for group members), and
   every verdict feeds the sender's spam history.

Every envelope resolves exactly once; ``drain`` makes that a testable
barrier.  Queue depths, shed counts, per-item admission latency, and
batch outcomes are all first-class metrics (``obs/metrics.py``), so
"the ingest tier is saturated" is a scrape, not a guess.
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field as dc_field
from typing import TYPE_CHECKING

from ..crypto import group_pks_hash
from ..obs import TRACER
from ..obs import metrics as obs_metrics
from ..obs.journal import JOURNAL
from ..obs.lineage import LINEAGE
from .dedup import ShardedDedupCache
from .ratelimit import AdmissionPolicy, RateLimitConfig
from .workers import VerifyCrashed, VerifyPool

if TYPE_CHECKING:  # heavy import (jax via trust backends); runtime-lazy
    from ..node.attestation import Attestation
    from ..node.manager import Manager

#: The shed reason code — ``node/server.py`` answers 429 for it.
SHED_REASON = "queue-full"


@dataclass(frozen=True)
class IngestPlaneConfig:
    #: Verify worker processes; 0 = verify inline on the dispatcher
    #: thread (no pool — the small-node default).
    workers: int = 0
    #: Signatures per verify batch (the native verifier's sweet spot
    #: is large batches; latency is bounded by ``linger_s``).
    batch_size: int = 64
    #: Max seconds a partial batch waits for more traffic.
    linger_s: float = 0.005
    #: Intake bound — beyond this, submissions shed with 429.
    submit_queue_max: int = 1024
    #: Admitted batches waiting for a dispatcher (the verify-stage
    #: bound; overflow backs up into the submit queue).
    batch_queue_max: int = 8
    dedup_shards: int = 16
    dedup_hashes_per_shard: int = 65536
    rate: RateLimitConfig = dc_field(default_factory=RateLimitConfig)
    #: Worker-crash retries per batch before ``verify-crashed``.
    max_batch_retries: int = 1


@dataclass
class _Envelope:
    att: "Attestation"
    sender: tuple[int, int]
    digest: bytes
    nonce: int | None
    enqueued: float
    future: Future
    #: Wire payload — the WAL record body (node/wal.py), kept so the
    #: apply stage never re-serializes what intake already had.
    raw: bytes = b""
    #: Lineage ID (obs/lineage.py) — 0 for the unsampled majority.
    lineage: int = 0


class IngestPlane:
    """The admission tier in front of one :class:`Manager`."""

    def __init__(self, manager: "Manager", config: IngestPlaneConfig | None = None):
        self.manager = manager
        self.config = config or IngestPlaneConfig()
        self.dedup = ShardedDedupCache(
            self.config.dedup_shards, self.config.dedup_hashes_per_shard
        )
        self.policy = AdmissionPolicy(self.config.rate)
        self.pool = VerifyPool(
            self.config.workers, max_retries=self.config.max_batch_retries
        )
        self._pks_hash = group_pks_hash(manager._group_pks)
        self._submit_queue: queue.Queue[_Envelope] = queue.Queue(
            maxsize=max(1, self.config.submit_queue_max)
        )
        self._batch_queue: queue.Queue[list[_Envelope]] = queue.Queue(
            maxsize=max(1, self.config.batch_queue_max)
        )
        self._stop = threading.Event()
        self._cv = threading.Condition()
        self._pending = 0  # enqueued envelopes not yet resolved
        #: Per-instance verdict tallies (the bench reads these; the
        #: process-global metrics aggregate across planes).
        self.accepted = 0
        self.shed = 0
        self.rejections: dict[str, int] = {}
        self._threads = [
            threading.Thread(
                target=self._admission_loop, name="ingest-admission", daemon=True
            )
        ] + [
            threading.Thread(
                target=self._dispatch_loop, name=f"ingest-verify-{i}", daemon=True
            )
            for i in range(max(1, self.config.workers))
        ]
        self._started = False

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "IngestPlane":
        # Flip under the condition lock: the node's boot path and a
        # bench driver can race start(), and a bare check-then-act
        # would double-start the stage threads.
        with self._cv:
            if self._started:
                return self
            self._started = True
        # Materialize the backpressure surface in /metrics from
        # boot: gauges at zero, labeled counters at zero rows.
        obs_metrics.INGEST_QUEUE_DEPTH.set(0, stage="submit")
        obs_metrics.INGEST_QUEUE_DEPTH.set(0, stage="verify")
        obs_metrics.INGEST_SHED.inc(0, stage="submit")
        obs_metrics.INGEST_VERIFY_BATCHES.inc(0, outcome="ok")
        for t in self._threads:
            t.start()
        return self

    def close(self, *, drain: bool = True, timeout: float = 30.0) -> None:
        with self._cv:
            started = self._started
        if drain and started:
            self.drain(timeout=timeout)
        self._stop.set()
        if started:
            for t in self._threads:
                t.join(timeout=5.0)
        self.pool.close()
        # Anything still unresolved (undrained close) must not leave a
        # caller waiting on a future forever.
        for q in (self._submit_queue, self._batch_queue):
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                for env in item if isinstance(item, list) else [item]:
                    self._resolve(env, False, "shutdown")

    def __enter__(self) -> "IngestPlane":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted envelope has a verdict."""
        with self._cv:
            return self._cv.wait_for(lambda: self._pending == 0, timeout=timeout)

    def advance_epoch(self) -> None:
        """Epoch-aligned dedup eviction — the node calls this once per
        epoch tick; digests age out after two epochs."""
        self.dedup.rotate_all()

    # -- stage 1: intake (any thread) -----------------------------------

    def submit(
        self,
        att: "Attestation",
        *,
        nonce: int | None = None,
        raw: bytes | None = None,
    ) -> Future:
        """Envelope + enqueue; never blocks.  Returns a future that
        resolves to the item's :class:`IngestResult`.  ``raw`` (the
        wire payload, when the caller already has it) feeds the dedup
        digest without re-serializing."""
        if raw is None:
            from ..node.attestation import AttestationData

            raw = AttestationData.from_attestation(att).to_bytes()
        env = _Envelope(
            att=att,
            sender=(att.pk.point.x, att.pk.point.y),
            digest=hashlib.sha256(raw).digest(),
            nonce=nonce,
            enqueued=time.perf_counter(),
            future=Future(),
            raw=raw,
            # Lineage sampling (obs/lineage.py): the unsampled path is
            # one counter tick; a sampled envelope carries its flat int
            # ID through every admission hop.
            lineage=LINEAGE.maybe_begin(),
        )
        with self._cv:
            self._pending += 1
        try:
            self._submit_queue.put_nowait(env)
            obs_metrics.INGEST_QUEUE_DEPTH.set(self._submit_queue.qsize(), stage="submit")
        except queue.Full:
            with self._cv:
                self.shed += 1
            obs_metrics.INGEST_SHED.inc(stage="submit")
            JOURNAL.record("ingest-shed", stage="submit")
            self._resolve(env, False, SHED_REASON)
        return env.future

    # -- stage 2: admission (one thread) --------------------------------

    def _admit(self, env: _Envelope) -> str | None:
        error = self.manager._structural_error(env.att)
        if error is not None:
            return error[0]
        reason = self.policy.check(env.sender)
        if reason is not None:
            return reason
        return self.dedup.admit(env.sender, env.digest, env.nonce)

    def _admission_loop(self) -> None:
        batch: list[_Envelope] = []
        while not self._stop.is_set():
            try:
                env = self._submit_queue.get(
                    timeout=self.config.linger_s if batch else 0.05
                )
            except queue.Empty:
                env = None
            if env is not None:
                obs_metrics.INGEST_QUEUE_DEPTH.set(
                    self._submit_queue.qsize(), stage="submit"
                )
                reason = self._admit(env)
                if reason is not None:
                    self._resolve(env, False, reason)
                else:
                    LINEAGE.mark(env.lineage, "admitted")
                    batch.append(env)
            if batch and (len(batch) >= self.config.batch_size or env is None):
                self._enqueue_batch(batch)
                batch = []
        if batch:
            self._enqueue_batch(batch)

    def _enqueue_batch(self, batch: list[_Envelope]) -> None:
        """Blocking put (in 50 ms slices so close() can interrupt) —
        THE backpressure coupling: a saturated verify tier parks the
        admission thread here, the submit queue fills, and intake
        starts shedding 429s instead of queueing without bound."""
        while not self._stop.is_set():
            try:
                self._batch_queue.put(batch, timeout=0.05)
                obs_metrics.INGEST_QUEUE_DEPTH.set(
                    self._batch_queue.qsize(), stage="verify"
                )
                return
            except queue.Full:
                continue
        for env in batch:
            self._resolve(env, False, "shutdown")

    # -- stages 3+4: verify + apply (one thread per worker) -------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                batch = self._batch_queue.get(timeout=0.05)
            except queue.Empty:
                continue
            obs_metrics.INGEST_QUEUE_DEPTH.set(
                self._batch_queue.qsize(), stage="verify"
            )
            items = [
                (
                    env.att.sig.big_r.x,
                    env.att.sig.big_r.y,
                    env.att.sig.s,
                    env.att.pk.point.x,
                    env.att.pk.point.y,
                    tuple(env.att.scores),
                )
                for env in batch
            ]
            t0 = time.perf_counter()
            try:
                with TRACER.span("ingest", batch=len(batch)):
                    verdicts = self.pool.verify(self._pks_hash, items)
            except VerifyCrashed as exc:
                # The recovered worker flight tail ships with the
                # crashed verdict: the post-mortem survives the
                # process boundary (ISSUE 11 satellite).
                JOURNAL.record(
                    "anomaly",
                    what="verify-batch-crashed",
                    batch=len(batch),
                    worker_flight_events=len(exc.flight_tail),
                    worker_flight_last=(
                        exc.flight_tail[-1] if exc.flight_tail else None
                    ),
                )
                for env in batch:
                    self._resolve(env, False, "verify-crashed")
                continue
            if len(verdicts) != len(batch):
                # A verifier that lost count is a crashed verifier:
                # zip-truncation would leave futures unresolved forever.
                for env in batch:
                    self._resolve(env, False, "verify-crashed")
                continue
            obs_metrics.SIG_VERIFY_SECONDS.observe(time.perf_counter() - t0)
            obs_metrics.SIGS_VERIFIED.inc(len(batch))
            obs_metrics.INGEST_VERIFY_BATCHES.inc(outcome="ok")
            # Apply with buffered WAL appends, then ONE fsync for the
            # whole batch (flush_wal) BEFORE any accept verdict
            # resolves: an acknowledged attestation is on disk, and the
            # fsync cost amortizes across the batch exactly like the
            # signature checks (node/wal.py durability contract).
            applied: list[_Envelope] = []
            for env, ok in zip(batch, verdicts):
                if ok:
                    LINEAGE.mark(env.lineage, "verified")
                    try:
                        self.manager.apply_verified(
                            env.att, raw=env.raw, flush=False
                        )
                    except OSError as exc:
                        JOURNAL.record(
                            "anomaly", what="wal-append-failed", error=repr(exc)
                        )
                        self._resolve(env, False, "wal-error")
                        continue
                    applied.append(env)
                else:
                    self._resolve(env, False, "bad-signature")
            if applied:
                try:
                    self.manager.flush_wal()
                except OSError as exc:
                    # The records may not have reached disk: the cache
                    # kept them (a retry overwrites harmlessly) but the
                    # verdict must not promise durability.
                    JOURNAL.record(
                        "anomaly", what="wal-flush-failed", error=repr(exc)
                    )
                    for env in applied:
                        self._resolve(env, False, "wal-error")
                else:
                    for env in applied:
                        LINEAGE.mark(env.lineage, "applied")
                        self._resolve(env, True, None)

    # -- verdicts -------------------------------------------------------

    def _resolve(self, env: _Envelope, accepted: bool, reason: str | None) -> None:
        from ..node.manager import IngestResult

        obs_metrics.INGEST_ADMISSION_SECONDS.observe(time.perf_counter() - env.enqueued)
        why = None if accepted else (reason or "unknown")
        if accepted:
            self.policy.record_outcome(env.sender, True)
        else:
            # A rejected attestation's lineage ends here: it will never
            # be in an epoch, so its entry must not wait for one.
            LINEAGE.drop(env.lineage, reason="rejected")
        if not accepted:
            obs_metrics.ATTESTATIONS_REJECTED.inc(reason=why)
            JOURNAL.record("ingest-reject", reason=why)
            # The policy already tallied its own verdicts; sheds are
            # the node's fault, not the sender's.
            if why not in ("rate-limited", "spam-score", SHED_REASON, "shutdown"):
                self.policy.record_outcome(env.sender, False)
        env.future.set_result(IngestResult(accepted, reason))
        # Verdict tallies are resolved from three roots (intake shed,
        # the admission thread, every dispatcher) — the condition lock
        # that already serializes _pending covers them too.
        with self._cv:
            if accepted:
                self.accepted += 1
            else:
                self.rejections[why] = self.rejections.get(why, 0) + 1
            self._pending -= 1
            self._cv.notify_all()

    def stats(self) -> dict:
        """Per-instance verdict snapshot (the bench's report source)."""
        with self._cv:
            return {
                "accepted": self.accepted,
                "shed": self.shed,
                "rejections": dict(self.rejections),
                "pending": self._pending,
            }


__all__ = ["IngestPlane", "IngestPlaneConfig", "SHED_REASON"]
