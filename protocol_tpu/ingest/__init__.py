"""The admission plane: a horizontally scalable ingest tier in front of
the Manager (ISSUE 7, ROADMAP item 2).

Batch-EdDSA verification tops out around 3.5k sigs/s in one process
(PERF.md §4), sharing cores and the GIL with the epoch loop — so
"millions of users" dies at the front door, not in the matvec.  This
package moves admission off the epoch loop's process and in front of
``Manager.add_attestations_bulk``:

- :mod:`~protocol_tpu.ingest.dedup` — a sharded dedup/nonce cache
  (per-sender monotonic nonces + recent-message-hash generations,
  bounded memory, epoch-aligned eviction) that rejects replays
  *before* paying for a signature check;
- :mod:`~protocol_tpu.ingest.ratelimit` — per-sender token buckets
  plus a burst/rejection-history spam score, with a pre-trust-set
  whitelist bypass;
- :mod:`~protocol_tpu.ingest.workers` — the multi-process
  signature-verification pool: spawned workers each owning a native
  batch-EdDSA verifier (and the batched Poseidon message hash), fed
  fixed-size batches, respawned on crash with in-flight batches
  retried or rejected — never silently dropped;
- :mod:`~protocol_tpu.ingest.plane` — the pipeline tying them
  together behind bounded queues (HTTP intake → admission → verify →
  manager apply) with backpressure as first-class state: queue-depth
  gauges, shed counters, journal events, and a 429-style shed verdict
  the node maps onto the HTTP response.

Every rejection flows through the existing
:class:`~protocol_tpu.node.manager.IngestResult` reason plumbing and
the ``eigentrust_attestations_rejected_total`` reason labels, so the
admission tier widens the front door without forking the ingest
accounting.  ``bench/ingest_storm.py`` is the load generator; graftlint
pass 6 (``blocking-ingest-in-epoch-loop``) pins the converse — the
epoch loop itself must never verify signatures or block on an
unbounded queue.
"""

from .dedup import ShardedDedupCache
from .plane import IngestPlane, IngestPlaneConfig
from .ratelimit import AdmissionPolicy, RateLimitConfig
from .workers import VerifyPool

__all__ = [
    "AdmissionPolicy",
    "IngestPlane",
    "IngestPlaneConfig",
    "RateLimitConfig",
    "ShardedDedupCache",
    "VerifyPool",
]
