"""Per-sender token buckets and the spam score.

The admission plane's second cheap gate (after the structural check,
before dedup recording): a classic token bucket per sender bounds
sustained rate and burst size, and a simple spam score — rejection
history plus burst ratio — catches senders whose traffic is mostly
garbage even when each individual message would fit the bucket.
Pre-trusted senders (the EigenTrust pre-trust set, the original
design's sybil anchor) can bypass both via the whitelist.

All state is O(senders) dicts with insertion-order eviction, and the
clock is injectable so tests can drain and refill buckets without
sleeping.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class RateLimitConfig:
    #: Sustained tokens/second refilled per sender.
    rate: float = 50.0
    #: Bucket capacity — the burst a quiet sender may spend at once.
    burst: float = 200.0
    #: Spam-score ceiling; scores above it reject with ``spam-score``.
    spam_threshold: float = 4.0
    #: Senders tracked before oldest-inserted eviction.
    max_senders: int = 65536
    #: Pre-trusted sender keys that bypass rate and spam checks.
    whitelist: frozenset[tuple[int, int]] = frozenset()


@dataclass
class _SenderState:
    tokens: float
    stamp: float
    accepted: int = 0
    rejected: int = 0
    window_start: float = 0.0
    window_count: int = 0


class AdmissionPolicy:
    """Token bucket + spam score, one state record per sender.

    The spam score is ``4 * rejected/(accepted+rejected) + max(0,
    burst_ratio - 1)`` where ``burst_ratio`` is this second's arrival
    count over the sustained rate — a sender whose history is mostly
    rejections, or who is arriving far above their refill rate, climbs
    past the threshold and gets cut off before spending more verify
    budget.  Outcomes are fed back via :meth:`record_outcome` so
    downstream rejections (bad signature, duplicate) raise the score
    of the sender who caused them.
    """

    def __init__(self, config: RateLimitConfig | None = None, clock=time.monotonic):
        self.config = config or RateLimitConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._senders: dict[tuple[int, int], _SenderState] = {}

    def _state(self, sender: tuple[int, int], now: float) -> _SenderState:
        state = self._senders.get(sender)
        if state is None:
            if len(self._senders) >= self.config.max_senders:
                self._senders.pop(next(iter(self._senders)))
            state = self._senders[sender] = _SenderState(
                tokens=self.config.burst, stamp=now, window_start=now
            )
        return state

    def score(self, sender: tuple[int, int]) -> float:
        """Current spam score (0.0 for unseen senders)."""
        with self._lock:
            state = self._senders.get(sender)
            if state is None:
                return 0.0
            return self._score(state)

    def _score(self, state: _SenderState) -> float:
        total = state.accepted + state.rejected
        reject_frac = state.rejected / total if total else 0.0
        burst_ratio = state.window_count / max(self.config.rate, 1.0)
        return 4.0 * reject_frac + max(0.0, burst_ratio - 1.0)

    def check(self, sender: tuple[int, int]) -> str | None:
        """Reason code (``rate-limited`` / ``spam-score``) or None."""
        if sender in self.config.whitelist:
            return None
        now = self._clock()
        with self._lock:
            state = self._state(sender, now)
            if now - state.window_start >= 1.0:
                state.window_start = now
                state.window_count = 0
            state.window_count += 1
            state.tokens = min(
                self.config.burst,
                state.tokens + (now - state.stamp) * self.config.rate,
            )
            state.stamp = now
            if state.tokens < 1.0:
                state.rejected += 1
                return "rate-limited"
            if self._score(state) > self.config.spam_threshold:
                state.rejected += 1
                return "spam-score"
            state.tokens -= 1.0
            return None

    def record_outcome(self, sender: tuple[int, int], accepted: bool) -> None:
        """Feed a downstream verdict (signature check, dedup) back into
        the sender's history — the spam score's memory."""
        now = self._clock()
        with self._lock:
            state = self._state(sender, now)
            if accepted:
                state.accepted += 1
            else:
                state.rejected += 1


__all__ = ["AdmissionPolicy", "RateLimitConfig"]
