"""Flight recorder: a bounded JSONL event journal for post-mortems.

Every interesting host-side event — closed spans, ingest rejections,
plan outcomes, coalesced ticks, recompiles, drift anomalies — writes
one small dict through :meth:`FlightRecorder.record`.  The record path
is designed for writer threads that must never block or throw: one
``deque.append`` into a bounded in-memory ring (GIL-atomic, so the
epoch executor, the asyncio ingest loop, and the pipeline worker need
no lock) plus one append into a pending queue a background writer
thread drains in batches.

The ring always runs (``GET /debug/flight`` serves its tail even on a
node with no journal path configured); the on-disk JSONL file is
opt-in via :meth:`configure` (``ProtocolConfig.journal_path``).  The
file is size-bounded: past ``max_bytes`` it is rewritten from the ring
(the journal is a flight recorder, not an archive — the recent window
is the valuable part).  On crash or SIGTERM the node calls
:meth:`dump` so the final ring survives the process.

Doctrine: journal writes are host-boundary work.  graftlint pass 5
(``journal-write-in-jit``) rejects a ``record``/``dump`` call on a
journal receiver inside any jit- or shard_map-traced function — under
a trace it would execute once at trace time and lie forever.
"""

from __future__ import annotations

import collections
import io
import json
import os
import threading
import time
from pathlib import Path
from typing import Any

from . import metrics as _metrics


class FlightRecorder:
    """Bounded in-memory event ring + optional batched JSONL writer."""

    def __init__(
        self,
        max_events: int = 4096,
        max_bytes: int = 8 * 1024 * 1024,
        flush_interval_s: float = 0.25,
    ):
        self.max_events = int(max_events)
        self.max_bytes = int(max_bytes)
        self.flush_interval_s = float(flush_interval_s)
        #: The ring: newest events, bounded — deque.append/popleft are
        #: GIL-atomic, so record() takes no lock on the hot path.
        self._ring: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=self.max_events
        )
        #: Events awaiting disk, bounded like the ring so a wedged
        #: writer thread can't grow memory; overflow increments the
        #: dropped counter instead of blocking the recorder.
        self._pending: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=self.max_events
        )
        self._seq = 0
        self._path: Path | None = None
        self._file: io.TextIOBase | None = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._writer: threading.Thread | None = None
        #: Serializes file open/rotate/close against the writer thread;
        #: record() never takes it.
        self._io_lock = threading.Lock()

    # -- configuration --------------------------------------------------

    def configure(self, path: str | os.PathLike | None) -> "FlightRecorder":
        """Attach (or detach, with None) the on-disk JSONL journal and
        start the batched writer thread.  Reconfiguring closes the
        previous file."""
        with self._io_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            self._path = None
            if path:
                p = Path(path)
                p.parent.mkdir(parents=True, exist_ok=True)
                self._file = open(p, "a", encoding="utf-8")
                self._path = p
            # Writer-thread lifecycle stays under the same lock as the
            # file handle: configure() and close() race from different
            # roots (node boot, SIGTERM handler, tests).
            if self._file is not None and (
                self._writer is None or not self._writer.is_alive()
            ):
                self._stop.clear()
                self._writer = threading.Thread(
                    target=self._writer_loop, name="flight-recorder", daemon=True
                )
                self._writer.start()
        return self

    @property
    def path(self) -> Path | None:
        with self._io_lock:
            return self._path

    # -- hot path -------------------------------------------------------

    def record(self, kind: str, **fields: Any) -> None:
        """Append one event.  Never blocks, never raises — the epoch
        executor and the ingest loop call this inline."""
        try:
            self._seq += 1  # benign race: seq is advisory ordering
            event = {"ts": round(time.time(), 6), "seq": self._seq, "kind": kind}
            event.update(fields)
            if len(self._pending) == self._pending.maxlen and self._file is not None:
                _metrics.JOURNAL_DROPPED.inc()
            self._ring.append(event)
            if self._file is not None:
                self._pending.append(event)
                self._wake.set()
            _metrics.JOURNAL_EVENTS.inc(kind=kind)
        except Exception:  # noqa: BLE001 - observability never throws
            pass

    # -- queries --------------------------------------------------------

    def tail(self, n: int | None = None) -> list[dict[str, Any]]:
        """The newest ``n`` events (all ring contents by default),
        oldest first.  A plain list() of the deque is safe against
        concurrent appends."""
        events = list(self._ring)
        if n is not None and n >= 0:
            events = events[-n:]
        return events

    def __len__(self) -> int:
        return len(self._ring)

    # -- disk -----------------------------------------------------------

    def _writer_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.flush_interval_s)
            self._wake.clear()
            self.flush()

    def flush(self) -> None:
        """Drain the pending queue to disk in one batched write, then
        rotate if the file outgrew ``max_bytes``."""
        if self._file is None:
            self._pending.clear()
            return
        batch: list[dict[str, Any]] = []
        while True:
            try:
                batch.append(self._pending.popleft())
            except IndexError:
                break
        if not batch:
            return
        lines = "".join(json.dumps(e, default=str) + "\n" for e in batch)
        with self._io_lock:
            f = self._file
            if f is None:
                return
            try:
                f.write(lines)
                f.flush()
                if f.tell() > self.max_bytes:
                    self._rotate_locked()
            except (OSError, ValueError):
                pass

    def _rotate_locked(self) -> None:
        """Rewrite the file from the ring (callers hold ``_io_lock``):
        the journal keeps the recent window, not the full history."""
        assert self._path is not None and self._file is not None
        self._file.close()
        with open(self._path, "w", encoding="utf-8") as f:
            for event in list(self._ring):
                f.write(json.dumps(event, default=str) + "\n")
        self._file = open(self._path, "a", encoding="utf-8")

    def dump(self, path: str | os.PathLike, reason: str = "dump") -> Path:
        """Write the whole ring to ``path`` as JSONL (newline-appended
        with a final marker event) — the crash/SIGTERM post-mortem
        artifact.  Safe to call from a signal handler's deferred
        callback or an excepthook."""
        out = Path(path)
        out.parent.mkdir(parents=True, exist_ok=True)
        events = list(self._ring)
        marker = {
            "ts": round(time.time(), 6),
            "seq": self._seq + 1,
            "kind": "journal-dump",
            "reason": reason,
            "events": len(events),
        }
        with open(out, "w", encoding="utf-8") as f:
            for event in events:
                f.write(json.dumps(event, default=str) + "\n")
            f.write(json.dumps(marker) + "\n")
        return out

    def close(self) -> None:
        """Flush pending events and stop the writer thread."""
        self._stop.set()
        self._wake.set()
        # Swap under the lock, join outside it: holding _io_lock across
        # the join would stall flush() (and trip pass 7's
        # blocking-call-under-lock rule) for the whole drain.
        with self._io_lock:
            writer, self._writer = self._writer, None
        if writer is not None:
            writer.join(timeout=5.0)
        self.flush()
        with self._io_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def reset(self) -> None:
        """Drop all buffered events (tests)."""
        self._ring.clear()
        self._pending.clear()


#: Process-global flight recorder (the node's /debug/flight source).
JOURNAL = FlightRecorder()


# ---------------------------------------------------------------------------
# Spawn-boundary post-mortems (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

#: Events kept per recovered worker dump when journaling its tail.
_WORKER_TAIL_EVENTS = 20


def install_worker_dump_handler(
    dump_dir: str | os.PathLike | None, pool: str
) -> None:
    """Worker-bootstrap half: install a SIGTERM handler that dumps the
    worker process's flight-recorder ring into ``dump_dir`` before the
    process dies, so a terminated worker's last events survive the
    spawn boundary (a hard ``os._exit`` kill leaves nothing — same as
    a real SIGKILL).  No-op without a dump dir or where signals are
    unavailable; never raises (this runs in every worker's init)."""
    if not dump_dir:
        return
    try:
        import signal

        directory = Path(dump_dir)

        def _dump(signum, frame):  # pragma: no cover - runs in workers
            try:
                JOURNAL.dump(
                    directory / f"flight-{pool}-{os.getpid()}.jsonl",
                    reason=f"{pool}-SIGTERM",
                )
            finally:
                os._exit(143)

        signal.signal(signal.SIGTERM, _dump)
    except (ImportError, ValueError, OSError):
        pass


def collect_worker_dumps(
    dump_dir: str | os.PathLike | None,
    pool: str,
    *,
    tail_events: int = _WORKER_TAIL_EVENTS,
) -> list[dict[str, Any]]:
    """Parent half: read (then delete) every per-worker flight dump in
    ``dump_dir``, journal each tail as a ``worker-flight-tail`` event,
    and return the recovered events — the pools attach them to their
    ``*-crashed`` results so a post-mortem sees what the worker was
    doing when it died."""
    if not dump_dir:
        return []
    recovered: list[dict[str, Any]] = []
    directory = Path(dump_dir)
    if not directory.is_dir():
        return recovered
    for path in sorted(directory.glob("flight-*.jsonl")):
        try:
            lines = path.read_text().splitlines()
        except OSError:
            continue
        events: list[dict[str, Any]] = []
        for line in lines[-(tail_events + 1) :]:
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        JOURNAL.record(
            "worker-flight-tail",
            pool=pool,
            dump=path.name,
            events=len(events),
            last=events[-1] if events else None,
        )
        recovered.extend(events)
        try:
            path.unlink()
        except OSError:
            pass
    return recovered


__all__ = [
    "JOURNAL",
    "FlightRecorder",
    "collect_worker_dumps",
    "install_worker_dump_handler",
]
