"""Exporters: Prometheus text format, JSON, and the jax.profiler hook.

``prometheus_text`` renders the registry in the Prometheus exposition
format (text/plain; version=0.0.4) the node serves at ``GET /metrics``;
``metrics_json`` is the same state for tooling that prefers JSON.
``profile_session`` is the opt-in device-timeline capture around
``converge_epoch`` (``ProtocolConfig.profile_dir``): it wraps
``jax.profiler.trace`` and degrades to a no-op when jax is absent, so
importing this module never touches the device runtime.
"""

from __future__ import annotations

import contextlib
import math
from typing import Any, Iterator

from .metrics import METRICS, Histogram, Metric, MetricsRegistry


def _escape_label_value(value: str) -> str:
    """Exposition-format label-value escaping: backslash FIRST (or the
    other escapes' backslashes double), then quote and newline."""
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition format: backslash and
    newline only (quotes are legal in help text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _labels(names: tuple[str, ...], values: tuple[str, ...], extra: str = "") -> str:
    pairs = [
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    ]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _render_metric(metric: Metric) -> list[str]:
    lines = []
    if metric.help:
        lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
    lines.append(f"# TYPE {metric.name} {metric.kind}")
    if isinstance(metric, Histogram):
        snap = metric.snapshot()
        if not snap:
            # An unobserved histogram still advertises its series.
            snap = {
                tuple("" for _ in metric.labelnames): {
                    "buckets": [0] * len(metric.bucket_bounds),
                    "sum": 0.0,
                    "count": 0,
                }
            }
        for labelvalues, state in snap.items():
            for bound, count in zip(metric.bucket_bounds, state["buckets"]):
                le = f'le="{_fmt(bound)}"'
                lines.append(
                    f"{metric.name}_bucket"
                    f"{_labels(metric.labelnames, labelvalues, le)} {count}"
                )
            lines.append(
                f"{metric.name}_sum"
                f"{_labels(metric.labelnames, labelvalues)} {_fmt(state['sum'])}"
            )
            lines.append(
                f"{metric.name}_count"
                f"{_labels(metric.labelnames, labelvalues)} {state['count']}"
            )
        return lines
    samples = metric.samples()
    if not samples and not metric.labelnames:
        samples = [((), 0.0)]
    for labelvalues, value in samples:
        lines.append(
            f"{metric.name}{_labels(metric.labelnames, labelvalues)} {_fmt(value)}"
        )
    return lines


#: Content type of the exposition format, for HTTP servers.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def prometheus_text(registry: MetricsRegistry | None = None) -> str:
    """The full registry in Prometheus exposition format."""
    registry = registry if registry is not None else METRICS
    lines: list[str] = []
    for metric in registry.collect():
        lines.extend(_render_metric(metric))
    return "\n".join(lines) + "\n"


def metrics_json(registry: MetricsRegistry | None = None) -> dict[str, Any]:
    """JSON-ready snapshot: metric name -> {kind, help, state}."""
    registry = registry if registry is not None else METRICS
    return {
        metric.name: {"kind": metric.kind, "help": metric.help, **metric.to_dict()}
        for metric in registry.collect()
    }


@contextlib.contextmanager
def profile_session(log_dir: str | None) -> Iterator[None]:
    """Opt-in ``jax.profiler`` capture: a real device-timeline trace
    (view with tensorboard/xprof) around the wrapped region when
    ``log_dir`` is set; a no-op context when it is None or jax is
    missing.  The node wraps ``converge_epoch`` with this when
    ``ProtocolConfig.profile_dir`` is configured."""
    if not log_dir:
        yield
        return
    try:
        import jax
    except ImportError:  # pragma: no cover - jax ships in every image
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


__all__ = [
    "PROMETHEUS_CONTENT_TYPE",
    "metrics_json",
    "profile_session",
    "prometheus_text",
]
