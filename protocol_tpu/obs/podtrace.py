"""Pod-wide trace stitching: N per-host span trees -> one pod epoch.

The obs plane through PR 11 is per-process: a pod epoch leaves N
independent ``epoch_tick`` trees in N tracers, with no shared clock and
no notion of which host dragged the collective.  This module closes the
gap over the same ``fleet_dir`` atomic-rename exchange the metric
snapshots ride (obs/fleet.py):

- every host serializes its stored epoch trace plus a burst of
  monotonic<->wall *clock-sync samples* into
  ``podtrace-h<host>-e<epoch>.json`` (:func:`publish_epoch_trace`);
- host 0 estimates each host's monotonic->wall offset as the median of
  its sync sample diffs (:func:`estimate_offset` — the median absorbs
  scheduler preemption between the paired clock reads, the same
  robustness argument as NTP's sample filter), rebases every tree onto
  one pod timeline, and merges them into a single ``pod_epoch`` trace
  (:func:`stitch_epoch`) served as ``GET /trace/pod/<epoch>|latest``;
- the stitch computes the pod's *skew* signals: per-phase max-median
  host duration (``eigentrust_pod_phase_skew_seconds{phase}``) for the
  four epoch phases, and the pre-collective barrier-arrival spread
  (``eigentrust_pod_barrier_wait_seconds``) from the clock-aligned
  arrival stamps ``parallel.pod.PodWindowPlan.build`` records ahead of
  its dimension-agreement allgather.  Both feed the pod SLOs
  (obs/slo.py) and the :class:`~.watchers.StragglerWatcher`.

Clock model: within one host, ``unix ~= monotonic + offset`` with the
offset constant over an epoch (wall-clock steps would break this —
which is why the offset is re-sampled and re-estimated every epoch).
Absolute span time is then ``root_start_monotonic + start_offset_s +
offset``; the stitched tree is normalized so the earliest host's root
sits at pod offset 0.

Doctrine: stdlib-only at import (the obs stance), and stitching is
best-effort host-boundary work — a torn or missing file degrades the
stitch to partial (tracked by the stitch-completeness SLO), never
raises into the epoch path.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from pathlib import Path
from typing import Any, Callable

from . import metrics as _metrics
from .journal import JOURNAL
from .trace import TRACER, Tracer
from .watchers import STRAGGLERS, StragglerWatcher

#: Per-host trace file schema version (mismatches are skipped, like
#: fleet snapshots).
PODTRACE_VERSION = 1

#: The phases whose cross-host skew the stitcher attributes — the four
#: top-level spans of a pod dryrun/node epoch.
SKEW_PHASES = ("plan", "converge", "checkpoint", "wal_flush")

#: Clock-sync sample pairs per publish: enough for a meaningful median,
#: cheap enough to take every epoch (6 clock reads).
SYNC_SAMPLES = 3


def clock_sync_samples(
    n: int = SYNC_SAMPLES,
    *,
    monotonic: Callable[[], float] = time.monotonic,
    wall: Callable[[], float] = time.time,
) -> list[dict[str, float]]:
    """Back-to-back (monotonic, unix) clock read pairs.  Each pair is
    read as tightly as Python allows; the stitcher's median over the
    diffs drops the pairs a preemption split apart."""
    return [
        {"monotonic": monotonic(), "unix": wall()} for _ in range(max(int(n), 1))
    ]


def estimate_offset(samples: list[dict[str, float]]) -> float | None:
    """The host's monotonic->wall offset: median of ``unix - monotonic``
    over its sync samples (None when there are none)."""
    diffs = [
        float(s["unix"]) - float(s["monotonic"])
        for s in samples
        if isinstance(s, dict) and "unix" in s and "monotonic" in s
    ]
    if not diffs:
        return None
    return statistics.median(diffs)


def _trace_path(directory: Path, host: int, epoch: int) -> Path:
    return directory / f"podtrace-h{int(host):03d}-e{int(epoch):06d}.json"


def publish_epoch_trace(
    directory: str | os.PathLike,
    host_id: int,
    epoch: int,
    *,
    tracer: Tracer | None = None,
    trace: dict[str, Any] | None = None,
    sync: list[dict[str, float]] | None = None,
    barrier: dict[str, float] | None = None,
    extra: dict[str, Any] | None = None,
) -> Path | None:
    """Write this host's epoch trace + clock-sync samples into the
    fleet directory (atomic tmp+rename, same contract as
    :func:`~.fleet.publish_snapshot`).  ``trace`` defaults to the
    tracer's stored trace for the epoch; publishing with none stored
    returns None (nothing to stitch).  ``barrier`` carries the
    pre-collective arrival stamp from ``PodWindowPlan.build``
    (``enter_monotonic`` / ``wait_seconds``)."""
    tracer = tracer if tracer is not None else TRACER
    if trace is None:
        trace = tracer.get_trace(epoch)
    if trace is None:
        return None
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    record: dict[str, Any] = {
        "version": PODTRACE_VERSION,
        "host": int(host_id),
        "epoch": int(epoch),
        "taken_unix": round(time.time(), 3),
        "clock_sync": sync if sync is not None else clock_sync_samples(),
        "trace": trace,
    }
    if barrier:
        record["barrier"] = dict(barrier)
    if extra:
        record.update(extra)
    path = _trace_path(directory, host_id, epoch)
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(record) + "\n")
    tmp.replace(path)
    return path


def directory_hosts(directory: str | os.PathLike, epoch: int) -> list[int]:
    """Host ids with a published trace file for ``epoch`` (sorted)."""
    directory = Path(directory)
    if not directory.is_dir():
        return []
    suffix = f"-e{int(epoch):06d}.json"
    hosts: list[int] = []
    for path in sorted(directory.glob(f"podtrace-h*{suffix}")):
        try:
            hosts.append(int(path.name[len("podtrace-h") : -len(suffix)]))
        except ValueError:
            continue
    return hosts


def directory_epochs(directory: str | os.PathLike) -> list[int]:
    """Epochs with at least one published per-host trace (sorted)."""
    directory = Path(directory)
    if not directory.is_dir():
        return []
    epochs: set[int] = set()
    for path in sorted(directory.glob("podtrace-h*-e*.json")):
        try:
            epochs.add(int(path.stem.rsplit("-e", 1)[1]))
        except (IndexError, ValueError):
            continue
    return sorted(epochs)


def phase_durations(trace: dict[str, Any]) -> dict[str, float]:
    """Shallowest-first closed-span duration per skew phase in one
    host's serialized tree — a top-level ``converge`` wins over a
    nested helper span that reused the name."""

    def find(node: dict[str, Any], name: str) -> dict[str, Any] | None:
        children = node.get("children", ())
        for child in children:
            if child.get("name") == name:
                return child
        for child in children:
            hit = find(child, name)
            if hit is not None:
                return hit
        return None

    out: dict[str, float] = {}
    for phase in SKEW_PHASES:
        span = find(trace, phase)
        if span is not None and span.get("duration_s") is not None:
            out[phase] = float(span["duration_s"])
    return out


def compute_phase_skew(
    per_host: dict[str, dict[int, float]]
) -> dict[str, float]:
    """max - median host duration per phase (``{phase: {host: s}}`` ->
    ``{phase: skew_s}``).  Phases observed on fewer than two hosts are
    skipped — skew is a cross-host quantity."""
    skew: dict[str, float] = {}
    for phase, by_host in per_host.items():
        durations = sorted(by_host.values())
        if len(durations) < 2:
            continue
        skew[phase] = max(durations) - statistics.median(durations)
    return skew


class PodTraceStore:
    """Bounded ring of stitched pod epoch traces (host 0's /trace/pod
    source), mirroring the tracer's per-epoch ring, plus the latest
    stitch-completeness verdict the pod SLO reads."""

    def __init__(self, keep_epochs: int = 16):
        self.keep_epochs = int(keep_epochs)
        self._lock = threading.Lock()
        self._traces: dict[int, dict[str, Any]] = {}
        self._last_missing: int | None = None  # None = never stitched

    def put(self, epoch: int, stitched: dict[str, Any]) -> None:
        with self._lock:
            self._traces[int(epoch)] = stitched
            self._last_missing = len(stitched.get("missing_hosts", ()))
            while len(self._traces) > self.keep_epochs:
                del self._traces[min(self._traces)]

    def get(self, epoch: int) -> dict[str, Any] | None:
        with self._lock:
            trace = self._traces.get(int(epoch))
            return dict(trace) if trace is not None else None

    def latest_epoch(self) -> int | None:
        with self._lock:
            return max(self._traces) if self._traces else None

    def epochs(self) -> list[int]:
        with self._lock:
            return sorted(self._traces)

    def last_missing_hosts(self) -> int | None:
        """Hosts missing from the newest stitch (None before any) —
        the pod-stitch-completeness SLO value."""
        with self._lock:
            return self._last_missing

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self._last_missing = None


#: Process-global stitched-trace store (the node's /trace/pod source).
POD_TRACES = PodTraceStore()


def _load_host_records(
    directory: Path, epoch: int
) -> list[dict[str, Any]]:
    records: list[dict[str, Any]] = []
    suffix = f"-e{int(epoch):06d}.json"
    for path in sorted(directory.glob(f"podtrace-h*{suffix}")):
        try:
            rec = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(rec, dict) or rec.get("version") != PODTRACE_VERSION:
            continue
        if not isinstance(rec.get("trace"), dict):
            continue
        records.append(rec)
    # Files sort lexically by host already; keep a numeric sort so a
    # future >999-host pod can't interleave, and drop duplicate hosts
    # (last write wins, matching the exchange's latest-snapshot stance).
    by_host: dict[int, dict[str, Any]] = {}
    for rec in records:
        try:
            by_host[int(rec["host"])] = rec
        except (KeyError, TypeError, ValueError):
            continue
    return [by_host[h] for h in sorted(by_host)]


def stitch_epoch(
    directory: str | os.PathLike,
    epoch: int,
    *,
    expected_hosts: int | list[int] | None = None,
    store: PodTraceStore | None = None,
    straggler_watcher: StragglerWatcher | None = None,
    graft_into: Tracer | None = None,
    monotonic: Callable[[], float] = time.monotonic,
) -> dict[str, Any] | None:
    """Align clocks and merge every published host tree for ``epoch``
    into one pod trace (see module doc).  Returns None when no host has
    published yet.  Side effects (all best-effort): the stitched trace
    lands in ``store`` (default :data:`POD_TRACES`), the skew metrics
    are fed, the straggler watcher observes the per-phase host
    durations, and — when ``graft_into`` is given — a ``pod_stitch``
    summary span grafts onto the stitching host's own epoch trace
    (parking if that root is still open, the ``Tracer.graft``
    contract)."""
    t_stitch = monotonic()
    directory = Path(directory)
    records = _load_host_records(directory, epoch)
    if not records:
        return None

    if expected_hosts is None:
        expected = [int(r["host"]) for r in records]
    elif isinstance(expected_hosts, int):
        expected = list(range(expected_hosts))
    else:
        expected = sorted(int(h) for h in expected_hosts)
    present = [int(r["host"]) for r in records]
    missing = sorted(set(expected) - set(present))

    # Per-host clock alignment: absolute wall time of each root =
    # start_monotonic + offset.  A record without sync samples (or a
    # pre-PR-19 trace without start_monotonic) anchors at its
    # publication stamp minus the root duration — degraded, but the
    # tree still lands in the stitch.
    aligned: list[dict[str, Any]] = []
    for rec in records:
        trace = rec["trace"]
        offset = estimate_offset(rec.get("clock_sync") or [])
        start_monotonic = trace.get("start_monotonic")
        if offset is not None and isinstance(start_monotonic, (int, float)):
            root_unix = float(start_monotonic) + offset
            degraded = False
        else:
            offset = None
            root_unix = float(rec.get("taken_unix", 0.0)) - float(
                trace.get("duration_s") or 0.0
            )
            degraded = True
        aligned.append(
            {
                "host": int(rec["host"]),
                "trace": trace,
                "offset": offset,
                "root_unix": root_unix,
                "degraded": degraded,
                "barrier": rec.get("barrier") or None,
            }
        )

    pod_start_unix = min(a["root_unix"] for a in aligned)
    pod_end_unix = pod_start_unix
    children: list[dict[str, Any]] = []
    per_phase: dict[str, dict[int, float]] = {}
    attribution: dict[str, float] = {}
    barrier_arrivals: dict[str, float] = {}
    barrier_waits: dict[str, float] = {}
    for a in aligned:
        shift = a["root_unix"] - pod_start_unix
        tree = _shift_tree(a["trace"], shift)
        tree.setdefault("attrs", {})["host"] = a["host"]
        if a["degraded"]:
            tree["attrs"]["clock_degraded"] = True
        children.append(tree)
        root_dur = float(a["trace"].get("duration_s") or 0.0)
        pod_end_unix = max(pod_end_unix, a["root_unix"] + root_dur)
        durations = phase_durations(a["trace"])
        for phase, dur in durations.items():
            per_phase.setdefault(phase, {})[a["host"]] = dur
        # Phase attribution: how much of the host's root the four
        # top-level phases explain (1.0 = every second accounted for).
        if root_dur > 0.0:
            attribution[str(a["host"])] = round(
                min(sum(durations.values()) / root_dur, 1.0), 4
            )
        barrier = a["barrier"]
        if barrier and a["offset"] is not None:
            enter = barrier.get("enter_monotonic")
            if isinstance(enter, (int, float)) and float(enter) > 0.0:
                barrier_arrivals[str(a["host"])] = round(
                    float(enter) + a["offset"] - pod_start_unix, 6
                )
            wait = barrier.get("wait_seconds")
            if isinstance(wait, (int, float)):
                barrier_waits[str(a["host"])] = round(float(wait), 6)

    skew = compute_phase_skew(per_phase)
    barrier_spread = (
        round(max(barrier_arrivals.values()) - min(barrier_arrivals.values()), 6)
        if len(barrier_arrivals) >= 2
        else None
    )

    stitched: dict[str, Any] = {
        "name": "pod_epoch",
        "epoch": int(epoch),
        "n_hosts": len(present),
        "hosts": present,
        "missing_hosts": missing,
        "complete": not missing,
        "start_unix": round(pod_start_unix, 6),
        "duration_s": round(pod_end_unix - pod_start_unix, 6),
        "clock_offsets_s": {
            str(a["host"]): round(a["offset"], 6)
            for a in aligned
            if a["offset"] is not None
        },
        "phase_seconds": {
            phase: {str(h): round(d, 6) for h, d in sorted(by_host.items())}
            for phase, by_host in sorted(per_phase.items())
        },
        "phase_skew_s": {p: round(s, 6) for p, s in sorted(skew.items())},
        "phase_attribution": attribution,
        "barrier": {
            "arrivals_offset_s": barrier_arrivals,
            "waits_s": barrier_waits,
            "spread_s": barrier_spread,
        },
        "children": children,
    }

    for phase, value in skew.items():
        _metrics.POD_PHASE_SKEW_SECONDS.observe(value, phase=phase)
    if barrier_spread is not None:
        _metrics.POD_BARRIER_WAIT_SECONDS.set(barrier_spread)

    watcher = straggler_watcher if straggler_watcher is not None else STRAGGLERS
    straggler = watcher.observe(int(epoch), per_phase)
    if straggler.get("flagged"):
        stitched["stragglers"] = straggler["flagged"]

    stitch_seconds = monotonic() - t_stitch
    stitched["stitch_seconds"] = round(stitch_seconds, 6)
    _metrics.POD_STITCH_SECONDS.set(stitch_seconds)

    store = store if store is not None else POD_TRACES
    store.put(int(epoch), stitched)
    JOURNAL.record(
        "pod-stitch",
        epoch=int(epoch),
        hosts=len(present),
        missing=len(missing),
        max_skew_s=round(max(skew.values()), 6) if skew else None,
        barrier_spread_s=barrier_spread,
        stitch_seconds=round(stitch_seconds, 6),
    )

    if graft_into is not None:
        graft_into.graft(
            int(epoch),
            {
                "name": "pod_stitch",
                "span_id": 0,
                "start_offset_s": 0.0,
                "duration_s": round(stitch_seconds, 6),
                "attrs": {
                    "hosts": len(present),
                    "missing": len(missing),
                    "complete": not missing,
                },
                "children": [],
            },
        )
    return stitched


def _shift_tree(trace: dict[str, Any], shift: float) -> dict[str, Any]:
    """Copy of one host's tree with every ``start_offset_s`` rebased
    from host-root-relative to pod-start-relative."""

    def walk(node: dict[str, Any]) -> dict[str, Any]:
        out = dict(node)
        out.pop("start_monotonic", None)
        out["start_offset_s"] = round(
            float(node.get("start_offset_s") or 0.0) + shift, 6
        )
        out["children"] = [walk(c) for c in node.get("children", ())]
        return out

    return walk(trace)


__all__ = [
    "POD_TRACES",
    "PODTRACE_VERSION",
    "PodTraceStore",
    "SKEW_PHASES",
    "clock_sync_samples",
    "compute_phase_skew",
    "directory_epochs",
    "directory_hosts",
    "estimate_offset",
    "phase_durations",
    "publish_epoch_trace",
    "stitch_epoch",
]
