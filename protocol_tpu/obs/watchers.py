"""Runtime invariant watchers: recompiles, memory watermarks, drift.

Three monitors that turn PR 5's *asserted* guarantees (stable shapes
never recompile, warm starts land on the same fixed point) into
*watched* ones on a running node:

- :class:`RecompileTracker` — wraps compilation-cache-miss detection
  around the jit'd converge entry points (``fn._cache_size()`` deltas,
  observed at the host boundary around each epoch's converge).  Every
  miss lands on ``eigentrust_jit_recompiles_total{fn}``; a miss during
  a *steady-state delta epoch* (warm seed + delta plan, where PR 5
  guarantees stable device shapes) is an anomaly: logged, journaled.
- :class:`MemoryWatermarkWatcher` — per-span device-memory watermarks:
  ``jax.local_devices()[*].memory_stats()`` snapshotted on span open,
  delta recorded on span close (span attrs + a per-phase gauge).
  Platforms without allocator stats (CPU) degrade to a no-op.
- :class:`ScoreDriftMonitor` — score-integrity: per-epoch L1/L∞ drift
  between consecutive fixed points (peers aligned by hash), the top-k
  mover peers, and a residual-stall detector flagging non-monotone
  convergence trajectories.  Served as ``GET /scores/drift`` and the
  drift/stall gauges.

This module imports only the standard library at import time (the obs
doctrine); jax is reached lazily inside methods, and never from traced
code — all observation happens at host boundaries.
"""

from __future__ import annotations

import logging
import statistics
import threading
from typing import Any, Iterable

from . import metrics as _metrics
from .journal import JOURNAL

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Recompile tracker
# ---------------------------------------------------------------------------


class RecompileTracker:
    """Compilation-cache-miss watcher over registered jit'd callables.

    Jit entry points register once (at module import, next to their
    definition or first construction); the epoch path then brackets
    each converge with :meth:`snapshot` / :meth:`observe`, which diffs
    ``fn._cache_size()`` — every increase is a fresh XLA compilation.
    ``observe(steady_state=True)`` marks the bracket as a steady-state
    delta epoch, where PR 5's stable-shape guarantee says the delta
    must be zero; a miss there is warned and journaled as an anomaly.
    """

    def __init__(self) -> None:
        self._fns: dict[str, Any] = {}
        self._lock = threading.Lock()

    def register(self, name: str, fn: Any) -> Any:
        """Track ``fn`` (anything exposing ``_cache_size()``) under
        ``name``; returns ``fn`` so call sites can register inline."""
        if hasattr(fn, "_cache_size"):
            with self._lock:
                self._fns[name] = fn
        return fn

    def registered(self) -> list[str]:
        with self._lock:
            return sorted(self._fns)

    def snapshot(self) -> dict[str, int]:
        """Current per-function compilation-cache sizes."""
        with self._lock:
            fns = dict(self._fns)
        sizes: dict[str, int] = {}
        for name, fn in fns.items():
            try:
                sizes[name] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 - observability never throws
                continue
        return sizes

    def observe(
        self,
        before: dict[str, int],
        *,
        steady_state: bool = False,
        epoch: int | None = None,
    ) -> dict[str, int]:
        """Diff the cache sizes against ``before``: count misses on the
        recompile metric, journal them, and (for a steady-state delta
        epoch) warn — that epoch was guaranteed recompile-free.
        Returns the per-function miss counts (empty = no recompiles)."""
        after = self.snapshot()
        misses = {
            name: after[name] - before[name]
            for name in after
            if after[name] > before.get(name, after[name])
        }
        for name, count in misses.items():
            _metrics.JIT_RECOMPILES.inc(count, fn=name)
            JOURNAL.record(
                "recompile",
                fn=name,
                count=count,
                epoch=epoch,
                steady_state=steady_state,
            )
        if misses and steady_state:
            log.warning(
                "steady-state delta epoch %s RECOMPILED (%s): the stable-shape "
                "guarantee (PERF.md §11) did not hold — a delta plan changed "
                "device shapes",
                "?" if epoch is None else epoch,
                ", ".join(f"{k}+{v}" for k, v in sorted(misses.items())),
            )
            JOURNAL.record(
                "anomaly", what="steady-state-recompile", epoch=epoch,
                fns=sorted(misses),
            )
        return misses


#: Process-global tracker; jit'd converge entry points register here.
RECOMPILES = RecompileTracker()


# ---------------------------------------------------------------------------
# Device-memory watermarks
# ---------------------------------------------------------------------------


class MemoryWatermarkWatcher:
    """Per-span device-memory watermarks via ``memory_stats()``.

    Installed as the tracer's ``on_span_open``/``on_span_close`` hook
    pair: open snapshots ``bytes_in_use`` summed over local devices,
    close records the delta (and the peak, where the allocator reports
    one) into the span's attrs and the per-phase gauge.  The first call
    probes whether the platform exposes allocator stats at all (CPU
    returns None) and disables itself when it doesn't, so the steady
    state on unsupported platforms is two no-op attribute reads."""

    def __init__(self) -> None:
        #: Guards the probe verdict: span hooks fire from every root
        #: that opens spans (epoch executor, pipeline device worker,
        #: ingest threads), so the first-probe flip must not race.
        self._probe_lock = threading.Lock()
        self._enabled: bool | None = None  # None = not probed yet
        #: Per-backend converge peaks (ISSUE 15): the highest device
        #: bytes observed across a converge span per backend, fed
        #: either from the allocator watermark at span close or
        #: explicitly (tools/mem_probe.py records the executable's
        #: buffer-assignment peak on platforms without allocator
        #: stats).  Guarded by the probe lock — writes come from span
        #: hooks on several roots.
        self._converge_peaks: dict[str, int] = {}

    def _devices(self):
        import jax

        return jax.local_devices()

    def _bytes_in_use(self) -> tuple[int, int] | None:
        """(bytes_in_use, peak_bytes_in_use) summed over local devices,
        or None when the platform has no allocator stats."""
        try:
            stats = [d.memory_stats() for d in self._devices()]
        except Exception:  # noqa: BLE001 - observability never throws
            return None
        if not stats or any(s is None for s in stats):
            return None
        return (
            sum(int(s.get("bytes_in_use", 0)) for s in stats),
            sum(int(s.get("peak_bytes_in_use", 0)) for s in stats),
        )

    def on_open(self, span) -> None:
        with self._probe_lock:
            if self._enabled is False:
                return
        snap = self._bytes_in_use()
        with self._probe_lock:
            self._enabled = snap is not None
        if snap is None:
            return
        span.attrs["_mem_open_bytes"] = snap[0]

    def on_close(self, span) -> None:
        with self._probe_lock:
            if self._enabled is not True:
                return
        opened = span.attrs.pop("_mem_open_bytes", None)
        if opened is None:
            return
        snap = self._bytes_in_use()
        if snap is None:
            return
        delta = snap[0] - int(opened)
        span.attrs["dev_mem_delta_bytes"] = delta
        span.attrs["dev_mem_peak_bytes"] = snap[1]
        _metrics.DEVICE_MEMORY_DELTA.set(delta, phase=span.name)
        # Per-backend converge peak (ISSUE 15): the converge spans the
        # trust backends open carry their backend name; the allocator's
        # high-water mark across the span is the runtime half of the
        # pass-12 static budget cross-check (tools/mem_probe.py).
        if span.name == "converge" and "backend" in span.attrs:
            self.record_converge_peak(str(span.attrs["backend"]), snap[1])

    def record_converge_peak(self, backend: str, peak_bytes: int) -> None:
        """Record one backend's converge peak (max over observations)
        onto the ``eigentrust_converge_peak_bytes`` gauge.  Called from
        the span-close hook where the platform has allocator stats, and
        explicitly by tools/mem_probe.py with the executable's
        buffer-assignment peak where it does not."""
        peak = int(peak_bytes)
        with self._probe_lock:
            if peak <= self._converge_peaks.get(backend, -1):
                return
            self._converge_peaks[backend] = peak
        _metrics.CONVERGE_PEAK_BYTES.set(peak, backend=backend)

    def converge_peaks(self) -> dict[str, int]:
        """Per-backend converge peaks recorded so far (bytes)."""
        with self._probe_lock:
            return dict(self._converge_peaks)


#: Process-global watermark watcher (wired by obs/__init__).
MEMORY_WATERMARKS = MemoryWatermarkWatcher()


# ---------------------------------------------------------------------------
# Score-integrity monitor
# ---------------------------------------------------------------------------


class ScoreDriftMonitor:
    """Per-epoch fixed-point drift + convergence-health anomalies.

    The manager feeds every landed epoch's ``(epoch, peer hashes,
    scores, residual trajectory)``; the monitor aligns consecutive
    fixed points by peer hash (joins/leaves drop out of the pairwise
    drift), computes L1/L∞ drift and the top-k movers, and flags a
    *residual stall* when the trajectory is non-monotone beyond
    ``stall_tolerance`` (a residual that *rises* mid-convergence means
    the operator or the seed changed under the iteration — exactly the
    class of bug arXiv:2606.11956-style partial matvecs can introduce,
    watched here before that work lands).  State is a scrape-ready
    dict behind a lock (``GET /scores/drift``)."""

    def __init__(self, top_k: int = 10, stall_tolerance: float = 1e-9):
        self.top_k = int(top_k)
        self.stall_tolerance = float(stall_tolerance)
        self._lock = threading.Lock()
        self._prev: tuple[list[int], Any] | None = None  # (hashes, scores)
        self._last: dict[str, Any] = {}

    def observe(
        self,
        epoch: int,
        peer_hashes: Iterable[int],
        scores,
        residuals=None,
    ) -> dict[str, Any]:
        """Record one landed epoch; returns the drift summary dict."""
        hashes = [int(h) for h in peer_hashes]
        vals = [float(s) for s in scores]
        summary: dict[str, Any] = {
            "epoch": int(epoch),
            "peers": len(hashes),
            "l1": None,
            "linf": None,
            "joined": 0,
            "departed": 0,
            "top_movers": [],
        }
        with self._lock:
            prev = self._prev
            self._prev = (hashes, vals)
        if prev is not None:
            prev_by_hash = dict(zip(prev[0], prev[1]))
            cur_set = set(hashes)
            deltas: list[tuple[float, int, float]] = []
            l1 = 0.0
            linf = 0.0
            for h, v in zip(hashes, vals):
                old = prev_by_hash.get(h)
                if old is None:
                    summary["joined"] += 1
                    continue
                d = v - old
                l1 += abs(d)
                if abs(d) > linf:
                    linf = abs(d)
                deltas.append((abs(d), h, d))
            summary["departed"] = sum(1 for h in prev[0] if h not in cur_set)
            summary["l1"] = l1
            summary["linf"] = linf
            deltas.sort(reverse=True)
            summary["top_movers"] = [
                {"peer_hash": hex(h), "delta": d}
                for absd, h, d in deltas[: self.top_k]
                if absd > 0.0
            ]
            _metrics.SCORE_DRIFT_L1.set(l1)
            _metrics.SCORE_DRIFT_LINF.set(linf)
        stall = self._check_stall(residuals)
        summary["residual_increases"] = stall[0]
        summary["stalled"] = stall[1]
        if stall[1]:
            _metrics.RESIDUAL_STALLS.inc()
            log.warning(
                "epoch %d: non-monotone convergence — residual rose %d time(s) "
                "beyond tolerance (trajectory stall)",
                epoch,
                stall[0],
            )
            JOURNAL.record(
                "anomaly", what="residual-stall", epoch=int(epoch),
                increases=stall[0],
            )
        JOURNAL.record(
            "drift",
            epoch=int(epoch),
            l1=summary["l1"],
            linf=summary["linf"],
            joined=summary["joined"],
            departed=summary["departed"],
            stalled=summary["stalled"],
        )
        with self._lock:
            self._last = summary
        return summary

    def _check_stall(self, residuals) -> tuple[int, bool]:
        """(count of beyond-tolerance residual increases, stalled?).
        One rise is tolerated (warm starts can overshoot on the first
        step); two or more is a stall."""
        if residuals is None:
            return 0, False
        vals = [float(r) for r in residuals]
        increases = sum(
            1 for a, b in zip(vals, vals[1:]) if b > a + self.stall_tolerance
        )
        return increases, increases >= 2

    def last(self) -> dict[str, Any]:
        """The newest drift summary (empty before the first epoch)."""
        with self._lock:
            return dict(self._last)

    def reset(self) -> None:
        with self._lock:
            self._prev = None
            self._last = {}


#: Process-global drift monitor (the node's /scores/drift source).
DRIFT = ScoreDriftMonitor()


# ---------------------------------------------------------------------------
# Pod straggler watcher
# ---------------------------------------------------------------------------


class StragglerWatcher:
    """Cross-host phase-time straggler detection (ISSUE 19).

    The pod trace stitcher (obs/podtrace.py) feeds every stitched
    epoch's per-phase host durations; a host *exceeds* when some
    phase's duration is over ``ratio`` times the pod median for that
    phase AND over the median by at least ``min_seconds`` (the absolute
    floor keeps microsecond jitter on tiny phases from counting).  A
    host that exceeds for ``k`` *consecutive* stitched epochs is
    flagged: journaled as an anomaly, warned, and held at 1 on
    ``eigentrust_pod_straggler{host}`` until a clean epoch clears it —
    one slow epoch is noise, k in a row is a sick host."""

    def __init__(
        self, ratio: float = 1.5, k: int = 3, min_seconds: float = 0.05
    ) -> None:
        self.ratio = float(ratio)
        self.k = int(k)
        self.min_seconds = float(min_seconds)
        self._lock = threading.Lock()
        self._streaks: dict[int, int] = {}
        self._flagged: dict[int, dict[str, Any]] = {}

    def configure(
        self,
        *,
        ratio: float | None = None,
        k: int | None = None,
        min_seconds: float | None = None,
    ) -> "StragglerWatcher":
        """Adjust thresholds (node boot from config knobs); streaks
        keep counting across a reconfigure."""
        with self._lock:
            if ratio is not None:
                self.ratio = float(ratio)
            if k is not None:
                self.k = int(k)
            if min_seconds is not None:
                self.min_seconds = float(min_seconds)
        return self

    def observe(
        self, epoch: int, per_phase: dict[str, dict[int, float]]
    ) -> dict[str, Any]:
        """Record one stitched epoch's ``{phase: {host: seconds}}``;
        returns ``{"epoch", "exceeded": {host: [phases]}, "flagged":
        [hosts]}``.  Hosts absent from every phase keep their streaks
        (a missing host is the stitch-completeness SLO's problem, not
        evidence it sped up)."""
        with self._lock:
            ratio = self.ratio
            k = self.k
            min_seconds = self.min_seconds
        exceeded: dict[int, list[str]] = {}
        observed: set[int] = set()
        for phase, by_host in per_phase.items():
            if len(by_host) < 2:
                continue
            median = statistics.median(by_host.values())
            for host, duration in by_host.items():
                observed.add(int(host))
                if (
                    duration > ratio * median
                    and duration - median > min_seconds
                ):
                    exceeded.setdefault(int(host), []).append(phase)
        newly_flagged: list[int] = []
        with self._lock:
            for host in observed:
                if host in exceeded:
                    self._streaks[host] = self._streaks.get(host, 0) + 1
                    if (
                        self._streaks[host] >= k
                        and host not in self._flagged
                    ):
                        self._flagged[host] = {
                            "epoch": int(epoch),
                            "phases": sorted(exceeded[host]),
                            "streak": self._streaks[host],
                        }
                        newly_flagged.append(host)
                else:
                    self._streaks[host] = 0
                    self._flagged.pop(host, None)
            flagged = sorted(self._flagged)
        for host in observed:
            _metrics.POD_STRAGGLER.set(
                1.0 if host in flagged else 0.0, host=str(host)
            )
        for host in newly_flagged:
            phases = ", ".join(exceeded[host])
            log.warning(
                "pod straggler: host %d exceeded the pod median by %.1fx "
                "for %d consecutive epochs (phases: %s)",
                host,
                ratio,
                k,
                phases,
            )
            JOURNAL.record(
                "anomaly",
                what="pod-straggler",
                host=host,
                epoch=int(epoch),
                phases=sorted(exceeded[host]),
                ratio=ratio,
                k=k,
            )
        return {
            "epoch": int(epoch),
            "exceeded": {h: sorted(p) for h, p in sorted(exceeded.items())},
            "flagged": flagged,
        }

    def flagged(self) -> dict[int, dict[str, Any]]:
        """Currently-flagged hosts -> the flagging evidence."""
        with self._lock:
            return {h: dict(v) for h, v in self._flagged.items()}

    def streaks(self) -> dict[int, int]:
        with self._lock:
            return dict(self._streaks)

    def reset(self) -> None:
        with self._lock:
            self._streaks.clear()
            self._flagged.clear()


#: Process-global straggler watcher (fed by the pod trace stitcher).
STRAGGLERS = StragglerWatcher()


__all__ = [
    "DRIFT",
    "MEMORY_WATERMARKS",
    "RECOMPILES",
    "STRAGGLERS",
    "MemoryWatermarkWatcher",
    "RecompileTracker",
    "ScoreDriftMonitor",
    "StragglerWatcher",
]
