"""Node-wide observability: trace spans, metrics, exporters.

The structured successor of the bare ``utils.telemetry`` timers: one
coherent instrumentation layer threaded through ingest, convergence,
proving, checkpointing, and serving.  Three pieces:

- :mod:`~protocol_tpu.obs.trace` — hierarchical spans (context
  managers, monotonic timing, contextvar nesting) collected into a
  per-epoch span tree the node serves as ``GET /trace/<epoch>``;
- :mod:`~protocol_tpu.obs.metrics` — a thread-safe registry of
  counters/gauges/histograms (ingest accept/reject by reason,
  sig-verify throughput, iterations-to-convergence, the per-iteration
  residual trajectory, dropped epoch ticks, checkpoint and
  window-plan events) served as ``GET /metrics``;
- :mod:`~protocol_tpu.obs.export` — Prometheus text + JSON renderers
  and the opt-in ``jax.profiler`` session hook.

Doctrine (enforced by graftlint pass 3, ``analysis/ast_rules.py``):
spans and metrics live at *host boundaries only*.  Nothing here may be
called from inside a jit-traced function, and the per-iteration
residual trajectory is captured device-side in the ``lax.while_loop``
carry (``ops.sparse.run_power_iteration``) and fetched ONCE after
convergence — the hot loop never syncs, logs, or reads a clock.

This package imports only the standard library, so instrumenting a
module costs nothing at import time.
"""

from __future__ import annotations

from . import metrics as _metrics
from .export import metrics_json, profile_session, prometheus_text
from .metrics import METRICS, MetricsRegistry
from .trace import (
    TRACER,
    Span,
    SpanContextFilter,
    Tracer,
    configure_logging,
)

# Every closed span feeds the phase-seconds histogram, so span timings
# (plan, converge, prove, checkpoint, sig_verify, ...) are scrapeable
# without separate timer plumbing at each site.
TRACER.on_span_close = lambda span: _metrics.PHASE_SECONDS.observe(
    span.duration_s or 0.0, phase=span.name
)

__all__ = [
    "METRICS",
    "MetricsRegistry",
    "Span",
    "SpanContextFilter",
    "TRACER",
    "Tracer",
    "configure_logging",
    "metrics_json",
    "profile_session",
    "prometheus_text",
]
