"""Node-wide observability: trace spans, metrics, exporters, the
flight recorder, and runtime invariant watchers.

The structured successor of the bare ``utils.telemetry`` timers: one
coherent instrumentation layer threaded through ingest, convergence,
proving, checkpointing, and serving.  Five pieces:

- :mod:`~protocol_tpu.obs.trace` — hierarchical spans (context
  managers, monotonic timing, contextvar nesting) collected into a
  per-epoch span tree the node serves as ``GET /trace/<epoch>``;
  ``Tracer.attach_closed`` bridges out-of-band attributions (the
  native prover's phase-timer table) into the same tree;
- :mod:`~protocol_tpu.obs.metrics` — a thread-safe registry of
  counters/gauges/histograms (ingest accept/reject by reason,
  sig-verify throughput, iterations-to-convergence, the per-iteration
  residual trajectory, dropped epoch ticks, checkpoint and
  window-plan events, jit recompiles, score drift, journal volume)
  served as ``GET /metrics``;
- :mod:`~protocol_tpu.obs.export` — Prometheus text + JSON renderers
  and the opt-in ``jax.profiler`` session hook;
- :mod:`~protocol_tpu.obs.journal` — the flight recorder: a bounded
  JSONL event journal (ring + batched writer) every closed span,
  ingest rejection, plan outcome, coalesced tick, and anomaly writes
  through; served as ``GET /debug/flight``, dumped on crash/SIGTERM;
- :mod:`~protocol_tpu.obs.watchers` — runtime invariant watchers:
  jit recompile tracking around the converge entry points, per-span
  device-memory watermarks, and the score-integrity/drift monitor
  behind ``GET /scores/drift``;
- :mod:`~protocol_tpu.obs.lineage` — attestation lineage sampling: a
  configurable fraction of submissions carry a flat int lineage ID
  through intake → admission → verify → apply → included-in-epoch →
  converged → proof-landed, feeding the per-stage
  ``eigentrust_freshness_seconds`` histograms (end-to-end freshness);
- :mod:`~protocol_tpu.obs.timeline` — the epoch timeline registry:
  one joined record per epoch (ingest watermarks, phase durations,
  proof lifecycle, freshness) served as ``GET /timeline/<epoch>``;
- :mod:`~protocol_tpu.obs.fleet` — cross-process metric aggregation:
  worker registries shipped back across the spawn boundary and
  multi-process snapshot exchange, merged into one ``process``-labeled
  exposition at ``GET /metrics/fleet``;
- :mod:`~protocol_tpu.obs.slo` — the declarative SLO engine behind
  ``GET /slo``: objectives over the registry (freshness p99, proof-lag
  p99, epoch cadence, shed rate, residual stalls) with burn-rate
  state, journaled transitions, and CI enforcement.

Doctrine (enforced by graftlint passes 3 and 5,
``analysis/ast_rules.py``): spans, metrics, and journal writes live
at *host boundaries only*.  Nothing here may be called from inside a
jit-traced function, and the per-iteration residual trajectory is
captured device-side in the ``lax.while_loop`` carry
(``ops.sparse.run_power_iteration``) and fetched ONCE after
convergence — the hot loop never syncs, logs, or reads a clock.

This package imports only the standard library at import time (the
watchers reach jax lazily, inside method calls), so instrumenting a
module costs nothing at import time.
"""

from __future__ import annotations

import time as _time

from . import metrics as _metrics
from .export import metrics_json, profile_session, prometheus_text
from .fleet import FLEET, FleetAggregator, fleet_prometheus_text, registry_snapshot
from .journal import JOURNAL, FlightRecorder
from .lineage import LINEAGE, LineageTracker
from .metrics import METRICS, MetricsRegistry
from .podtrace import (
    POD_TRACES,
    PodTraceStore,
    publish_epoch_trace,
    stitch_epoch,
)
from .slo import (
    SLO_ENGINE,
    SLOEngine,
    SLObjective,
    install_pod_defaults,
    pod_objectives,
)
from .timeline import TIMELINE, TimelineRegistry
from .trace import (
    TRACER,
    Span,
    SpanContextFilter,
    Tracer,
    configure_logging,
)
from .watchers import (
    DRIFT,
    MEMORY_WATERMARKS,
    RECOMPILES,
    STRAGGLERS,
    MemoryWatermarkWatcher,
    RecompileTracker,
    ScoreDriftMonitor,
    StragglerWatcher,
)


def _span_closed(span: Span) -> None:
    # Memory watermark first so the delta lands in the span's attrs
    # before the event is journaled.
    MEMORY_WATERMARKS.on_close(span)
    # Every closed span feeds the phase-seconds histogram, so span
    # timings (plan, converge, prove, checkpoint, sig_verify, ...) are
    # scrapeable without separate timer plumbing at each site.
    _metrics.PHASE_SECONDS.observe(span.duration_s or 0.0, phase=span.name)
    # An epoch root closing is the timeline's phase-join moment: the
    # tick wall-clock and the per-phase durations land on the epoch's
    # record in one write (children with repeated names last-win —
    # the phases here mirror /trace exactly).
    if span.name == "epoch_tick" and "epoch" in span.attrs:
        TIMELINE.record(
            span.attrs["epoch"],
            tick_seconds=round(span.duration_s or 0.0, 6),
            tick_ended_unix=round(_time.time(), 3),
            phases={
                c.name: round(c.duration_s or 0.0, 6)
                for c in span.children
                if c.duration_s is not None
            },
            error=bool(span.attrs.get("error", False)),
        )
    # ... and the flight recorder, so a post-mortem replays the span
    # sequence without the trace ring having kept the epoch.
    fields = {"name": span.name, "duration_s": round(span.duration_s or 0.0, 6)}
    for k, v in span.attrs.items():
        if k not in fields and k not in ("ts", "seq", "kind") and isinstance(
            v, (str, int, float, bool)
        ):
            fields[k] = v
    JOURNAL.record("span", **fields)


TRACER.on_span_close = _span_closed
TRACER.on_span_open = MEMORY_WATERMARKS.on_open

__all__ = [
    "DRIFT",
    "FLEET",
    "JOURNAL",
    "LINEAGE",
    "METRICS",
    "MEMORY_WATERMARKS",
    "POD_TRACES",
    "RECOMPILES",
    "SLO_ENGINE",
    "STRAGGLERS",
    "TIMELINE",
    "FleetAggregator",
    "FlightRecorder",
    "LineageTracker",
    "MemoryWatermarkWatcher",
    "MetricsRegistry",
    "PodTraceStore",
    "RecompileTracker",
    "SLOEngine",
    "SLObjective",
    "ScoreDriftMonitor",
    "Span",
    "SpanContextFilter",
    "StragglerWatcher",
    "TRACER",
    "TimelineRegistry",
    "Tracer",
    "configure_logging",
    "fleet_prometheus_text",
    "install_pod_defaults",
    "metrics_json",
    "pod_objectives",
    "profile_session",
    "prometheus_text",
    "publish_epoch_trace",
    "registry_snapshot",
    "stitch_epoch",
]
