"""Hierarchical trace spans with per-epoch span trees.

A span is a context manager timing one host-side phase; nesting is
tracked through a :mod:`contextvars` variable, so spans opened anywhere
down the call stack (manager → backend → checkpoint store) attach to
the right parent without plumbing, and concurrent epoch ticks in
executor threads keep independent stacks.

``Tracer.epoch(n)`` opens the per-epoch root span (``epoch_tick``) and,
on exit, freezes the tree into a JSON-ready dict the node serves as
``GET /trace/<epoch>``.  Spans opened with no enclosing root are still
timed (and fed to ``on_span_close``, which the package wires into the
phase-seconds histogram) but belong to no stored trace — ingest spans
on the event loop work this way.

Spans must only wrap host-boundary work: graftlint pass 3
(``analysis/ast_rules.py``) rejects clock and logging calls inside
jit-traced functions, so a span can never sneak a host sync into the
device loop.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

#: The innermost open span of the current thread/task, or None.
_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "protocol_tpu_obs_span", default=None
)
#: The epoch whose root span is open on this thread/task, or None.
_current_epoch: contextvars.ContextVar["int | None"] = contextvars.ContextVar(
    "protocol_tpu_obs_epoch", default=None
)

#: Process-wide span id source (CPython-atomic C iterator).
_span_ids = itertools.count(1)


@dataclass
class Span:
    """One timed phase.  ``duration_s`` is None while the span is open."""

    name: str
    span_id: int
    attrs: dict[str, Any] = field(default_factory=dict)
    children: list["Span"] = field(default_factory=list)
    #: Monotonic start (for durations) and offset from the root span's
    #: start (for ordering inside a serialized tree).
    start_monotonic: float = 0.0
    start_offset_s: float = 0.0
    duration_s: float | None = None

    def child_names(self) -> list[str]:
        return [c.name for c in self.children]

    def find(self, name: str) -> "Span | None":
        """First descendant (depth-first) with the given name."""
        for c in self.children:
            if c.name == name:
                return c
            hit = c.find(name)
            if hit is not None:
                return hit
        return None

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "name": self.name,
            "span_id": self.span_id,
            "start_offset_s": round(self.start_offset_s, 6),
            "duration_s": round(self.duration_s, 6)
            if self.duration_s is not None
            else None,
        }
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        out["children"] = [c.to_dict() for c in self.children]
        return out


class Tracer:
    """Span factory + per-epoch trace store.

    Thread-safe: spans nest per-thread via contextvars; the finished
    trace dicts live behind a lock so HTTP scrapes and epoch ticks can
    race freely.
    """

    def __init__(self, keep_epochs: int = 16):
        self.keep_epochs = keep_epochs
        self._traces: dict[int, dict[str, Any]] = {}
        #: Graft payloads that arrived before their epoch's trace was
        #: stored (an async proof can land while its epoch's root span
        #: is still open — e.g. a fast prove against a cold-compile
        #: converge); applied when the trace stores, bounded like the
        #: trace ring.
        self._pending_grafts: dict[int, list[tuple[dict[str, Any], str | None]]] = {}
        self._lock = threading.Lock()
        #: Called with every closed span (package wiring feeds the
        #: phase-seconds histogram).  Must be cheap and never raise.
        self.on_span_close: Callable[[Span], None] | None = None
        #: Called with every opened span (the device-memory watermark
        #: watcher snapshots allocator state here).  Same contract.
        self.on_span_open: Callable[[Span], None] | None = None

    # -- spans ----------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        parent = _current_span.get()
        now = time.monotonic()
        root_start = parent.start_monotonic - parent.start_offset_s if parent else now
        sp = Span(
            name=name,
            span_id=next(_span_ids),
            attrs=attrs,
            start_monotonic=now,
            start_offset_s=now - root_start,
        )
        if parent is not None:
            parent.children.append(sp)
        token = _current_span.set(sp)
        open_hook = self.on_span_open
        if open_hook is not None:
            try:
                open_hook(sp)
            except Exception:  # noqa: BLE001 - observability never throws
                pass
        try:
            yield sp
        finally:
            sp.duration_s = time.monotonic() - sp.start_monotonic
            _current_span.reset(token)
            hook = self.on_span_close
            if hook is not None:
                try:
                    hook(sp)
                except Exception:  # noqa: BLE001 - observability never throws
                    pass

    def attach_closed(self, name: str, duration_s: float, **attrs: Any) -> Span | None:
        """Attach an already-measured phase as a closed child of the
        current span — the bridge for sub-phase attributions gathered
        out-of-band (the native prover's phase-timer table, accumulated
        per-call timings) that have a total duration but no single
        contiguous interval.  The synthetic span starts at attach time
        minus its duration so ``start + duration`` never exceeds "now"
        and ``end >= start`` always holds; it feeds ``on_span_close``
        like a real span.  Returns None (and records nothing) when no
        span is open — sub-phases without a parent have nowhere to
        hang."""
        parent = _current_span.get()
        if parent is None:
            return None
        duration_s = max(float(duration_s), 0.0)
        now = time.monotonic()
        root_start = parent.start_monotonic - parent.start_offset_s
        sp = Span(
            name=name,
            span_id=next(_span_ids),
            attrs=attrs,
            start_monotonic=now - duration_s,
            start_offset_s=max(now - duration_s - root_start, 0.0),
            duration_s=duration_s,
        )
        parent.children.append(sp)
        hook = self.on_span_close
        if hook is not None:
            try:
                hook(sp)
            except Exception:  # noqa: BLE001 - observability never throws
                pass
        return sp

    @contextlib.contextmanager
    def epoch(self, epoch_number: int) -> Iterator[Span]:
        """Open the per-epoch root span (``epoch_tick``) and store the
        serialized tree on exit — including on exception, so a failed
        tick still leaves its partial trace behind."""
        epoch_number = int(epoch_number)
        token = _current_epoch.set(epoch_number)
        root: Span | None = None
        try:
            with self.span("epoch_tick", epoch=epoch_number) as root:
                try:
                    yield root
                except BaseException:
                    root.attrs["error"] = True
                    raise
        finally:
            _current_epoch.reset(token)
            if root is not None:
                with self._lock:
                    trace = root.to_dict()
                    # The serialized tree is relative (start_offset_s);
                    # the root's monotonic start anchors it on this
                    # host's clock so the pod stitcher (obs/podtrace)
                    # can place N trees on one aligned timeline.
                    trace["start_monotonic"] = round(root.start_monotonic, 6)
                    self._traces[epoch_number] = trace
                    # Early-arrived grafts (a proof that landed while
                    # this root span was still open) attach now.
                    for span_dict, parent_name in self._pending_grafts.pop(
                        epoch_number, ()
                    ):
                        self._graft_locked(trace, span_dict, parent_name)
                    while len(self._traces) > self.keep_epochs:
                        del self._traces[min(self._traces)]

    def graft(
        self,
        epoch_number: int,
        span_dict: dict[str, Any],
        parent_name: str | None = None,
    ) -> bool:
        """Attach an already-serialized span tree to a *stored* epoch
        trace — the bridge for work that finishes after its epoch's
        root span closed (the async proving plane: a worker process
        proves epoch k seconds after epoch k's tick stored its trace,
        and its ``prove{power_iterate, circuit_check, snark{...}}``
        tree lands here so ``GET /trace/<epoch>`` keeps the deep
        attribution).  ``parent_name`` picks a descendant to graft
        under (first match, depth-first); default is the root.
        A graft for an epoch whose trace is not stored *yet* (the root
        span may still be open — a fast prove can beat a cold-compile
        tick) is parked and applied when the trace stores; grafts for
        ring-evicted epochs are dropped.  Returns whether the graft
        landed immediately — parked/dropped grafts return False; the
        graft is best-effort, like all telemetry."""
        with self._lock:
            epoch_number = int(epoch_number)
            trace = self._traces.get(epoch_number)
            if trace is None:
                if not self._traces or epoch_number >= min(self._traces):
                    self._pending_grafts.setdefault(epoch_number, []).append(
                        (dict(span_dict), parent_name)
                    )
                    while len(self._pending_grafts) > self.keep_epochs:
                        del self._pending_grafts[min(self._pending_grafts)]
                return False
            return self._graft_locked(trace, span_dict, parent_name)

    @staticmethod
    def _graft_locked(
        trace: dict[str, Any],
        span_dict: dict[str, Any],
        parent_name: str | None,
    ) -> bool:
        def find(node: dict[str, Any], name: str) -> dict[str, Any] | None:
            for child in node.get("children", ()):
                if child.get("name") == name:
                    return child
                hit = find(child, name)
                if hit is not None:
                    return hit
            return None

        target = trace if parent_name is None else find(trace, parent_name)
        if target is None:
            return False
        target.setdefault("children", []).append(dict(span_dict))
        return True

    # -- queries --------------------------------------------------------

    def get_trace(self, epoch_number: int) -> dict[str, Any] | None:
        with self._lock:
            trace = self._traces.get(int(epoch_number))
            return dict(trace) if trace is not None else None

    def latest_epoch(self) -> int | None:
        with self._lock:
            return max(self._traces) if self._traces else None

    def epochs(self) -> list[int]:
        with self._lock:
            return sorted(self._traces)

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()


#: Process-global tracer (the node's /trace source).
TRACER = Tracer()


# ---------------------------------------------------------------------------
# Logging integration
# ---------------------------------------------------------------------------

#: Log format with the span/epoch context columns the filter injects.
LOG_FORMAT = (
    "%(asctime)s %(name)s %(levelname)s "
    "[epoch=%(epoch)s span=%(span)s] %(message)s"
)


class SpanContextFilter(logging.Filter):
    """Stamps every record with ``epoch``/``span``/``span_id`` from the
    current trace context, so any formatter may reference them."""

    def filter(self, record: logging.LogRecord) -> bool:
        span = _current_span.get()
        epoch = _current_epoch.get()
        record.epoch = "-" if epoch is None else epoch
        record.span = span.name if span is not None else "-"
        record.span_id = span.span_id if span is not None else 0
        return True


def configure_logging(level: int = logging.INFO) -> None:
    """Single logging entry point for the node (and anything embedding
    it).  Unlike a bare ``logging.basicConfig``, this respects an
    existing root handler: it only *attaches* the span-context filter
    (so the host application's own format can use ``%(epoch)s`` /
    ``%(span)s``) and never installs a second handler or clobbers the
    existing formatter.  On a pristine root it installs one stream
    handler with :data:`LOG_FORMAT`."""
    root = logging.getLogger()
    if root.handlers:
        for handler in root.handlers:
            if not any(isinstance(f, SpanContextFilter) for f in handler.filters):
                handler.addFilter(SpanContextFilter())
        return
    handler = logging.StreamHandler()
    handler.addFilter(SpanContextFilter())
    handler.setFormatter(logging.Formatter(LOG_FORMAT))
    root.addHandler(handler)
    root.setLevel(level)


__all__ = [
    "LOG_FORMAT",
    "Span",
    "SpanContextFilter",
    "TRACER",
    "Tracer",
    "configure_logging",
]
