"""Epoch timeline registry: one joined record per epoch.

The observability surfaces grown through PRs 4-10 each answer one
question well — ``/trace`` the span tree, ``/metrics`` the aggregate
counters, ``/proof`` the lifecycle — but reconstructing *one epoch's
story* ("what did epoch 41 ingest, how long did each phase take, when
did its proof land?") meant joining three endpoints by hand.  This
registry does the join at write time: every subsystem records its
fragment against the epoch number as it happens —

- the manager's host stage: ingest watermarks (accepted/rejected
  totals at graph assembly), graph size, warm/delta disposition;
- the epoch root span on close: per-phase durations and the tick
  wall-clock (wired through ``obs.__init__``'s span-close hook);
- the converge: iterations, residual, backend;
- the proving plane: the proof lifecycle with submit/land timestamps,
  prove seconds, and lag;
- the lineage tracker: the epoch cohort's end-to-end freshness
  summary when its proof lands;

and ``GET /timeline/<epoch>`` (or ``latest``) serves the merged record.
Records live in a bounded ring like the trace store.  All writes are
merge-into-dict under one lock — observability-cheap, and safe from
every root that touches an epoch (executor, pipeline worker, proving
dispatchers, HTTP scrapes).
"""

from __future__ import annotations

import threading
import time
from typing import Any


class TimelineRegistry:
    """Bounded per-epoch record store with merge-on-record semantics."""

    def __init__(self, keep_epochs: int = 32):
        self.keep_epochs = int(keep_epochs)
        self._lock = threading.Lock()
        self._epochs: dict[int, dict[str, Any]] = {}

    def record(self, epoch: int, **fields: Any) -> None:
        """Merge ``fields`` into the epoch's record (dict-valued fields
        merge one level deep, so ``proof={"state": ...}`` updates join
        instead of clobbering earlier proof fragments)."""
        epoch = int(epoch)
        with self._lock:
            rec = self._epochs.get(epoch)
            if rec is None:
                rec = self._epochs[epoch] = {
                    "epoch": epoch,
                    "first_seen_unix": round(time.time(), 3),
                }
                while len(self._epochs) > self.keep_epochs:
                    del self._epochs[min(self._epochs)]
            for key, value in fields.items():
                if (
                    isinstance(value, dict)
                    and isinstance(rec.get(key), dict)
                ):
                    rec[key].update(value)
                else:
                    rec[key] = value

    # -- queries -----------------------------------------------------------

    def get(self, epoch: int) -> dict[str, Any] | None:
        with self._lock:
            rec = self._epochs.get(int(epoch))
            return dict(rec) if rec is not None else None

    def latest_epoch(self) -> int | None:
        with self._lock:
            return max(self._epochs) if self._epochs else None

    def latest(self) -> dict[str, Any] | None:
        with self._lock:
            if not self._epochs:
                return None
            return dict(self._epochs[max(self._epochs)])

    def epochs(self) -> list[int]:
        with self._lock:
            return sorted(self._epochs)

    def seconds_since_last_tick(self) -> float | None:
        """Wall seconds since the newest epoch's tick closed (None
        before any tick, or if the newest record has no tick yet) —
        the /healthz cadence probe and the SLO engine's epoch-cadence
        source."""
        with self._lock:
            if not self._epochs:
                return None
            rec = self._epochs[max(self._epochs)]
            ended = rec.get("tick_ended_unix")
        if ended is None:
            return None
        return max(time.time() - float(ended), 0.0)

    def reset(self) -> None:
        with self._lock:
            self._epochs.clear()


#: Process-global timeline (the node's /timeline source).
TIMELINE = TimelineRegistry()


__all__ = ["TIMELINE", "TimelineRegistry"]
