"""Thread-safe metrics registry: counters, gauges, histograms.

The node's scrapeable state (``GET /metrics``), recorded from executor
threads (epoch ticks), the asyncio event loop (ingest), and read by
concurrent HTTP scrapes — one registry lock serializes every mutation
and snapshot, and all record calls are O(labels) dict work, so nothing
here belongs anywhere near a device loop (graftlint pass 3 enforces
that structurally).

Metric shapes follow the Prometheus data model so
:func:`protocol_tpu.obs.export.prometheus_text` renders them without
translation: counters are monotonic (``_total`` names), gauges are
set-to-current, histograms are cumulative-bucket with ``_sum`` and
``_count`` series.  The per-iteration convergence residuals — captured
device-side in the ``lax.while_loop`` carry and fetched once after
convergence — land in :data:`CONVERGENCE_RESIDUAL`, whose per-epoch
observation count therefore equals the iteration count.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Iterable

LabelValues = tuple[str, ...]


class Metric:
    """Base: name, help text, label names, per-labelset values."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help_text: str,
        labelnames: tuple[str, ...],
        lock: threading.Lock,
    ):
        self.name = name
        self.help = help_text
        self.labelnames = labelnames
        self._lock = lock

    def _label_key(self, labels: dict[str, Any]) -> LabelValues:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def samples(self) -> list[tuple[LabelValues, float]]:
        raise NotImplementedError

    def to_dict(self) -> dict[str, Any]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonic counter, optionally labelled (e.g. rejection reason)."""

    kind = "counter"

    def __init__(self, name, help_text, labelnames, lock):
        super().__init__(name, help_text, labelnames, lock)
        self._values: dict[LabelValues, float] = {}

    def inc(self, n: float = 1, **labels: Any) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (n={n})")
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels: Any) -> float:
        key = self._label_key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self):
        with self._lock:
            return sorted(self._values.items())

    def to_dict(self):
        with self._lock:
            if not self.labelnames:
                return {"value": self._values.get((), 0.0)}
            return {
                "values": {
                    ",".join(k): v for k, v in sorted(self._values.items())
                }
            }


class Gauge(Metric):
    """Set-to-current value (iterations of the last epoch, graph size)."""

    kind = "gauge"

    def __init__(self, name, help_text, labelnames, lock):
        super().__init__(name, help_text, labelnames, lock)
        self._values: dict[LabelValues, float] = {}

    def set(self, value: float, **labels: Any) -> None:
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = float(value)

    def value(self, **labels: Any) -> float:
        key = self._label_key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self):
        with self._lock:
            return sorted(self._values.items())

    def to_dict(self):
        return Counter.to_dict(self)  # same shape


class _HistState:
    __slots__ = ("bucket_counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * n_buckets
        self.sum = 0.0
        self.count = 0


class Histogram(Metric):
    """Cumulative-bucket histogram (Prometheus semantics): ``observe``
    increments every bucket whose upper bound is >= the value, plus
    ``_sum``/``_count``."""

    kind = "histogram"

    def __init__(self, name, help_text, labelnames, lock, buckets: Iterable[float]):
        super().__init__(name, help_text, labelnames, lock)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"{self.name}: histogram needs buckets")
        if bounds[-1] != math.inf:
            bounds.append(math.inf)
        self.bucket_bounds: tuple[float, ...] = tuple(bounds)
        self._states: dict[LabelValues, _HistState] = {}

    def observe(self, value: float, **labels: Any) -> None:
        value = float(value)
        key = self._label_key(labels)
        with self._lock:
            state = self._states.get(key)
            if state is None:
                state = self._states[key] = _HistState(len(self.bucket_bounds))
            for i, bound in enumerate(self.bucket_bounds):
                if value <= bound:
                    state.bucket_counts[i] += 1
            state.sum += value
            state.count += 1

    def count(self, **labels: Any) -> int:
        key = self._label_key(labels)
        with self._lock:
            state = self._states.get(key)
            return state.count if state is not None else 0

    def quantile(self, q: float, **labels: Any) -> float | None:
        """Estimate the q-quantile (0 < q <= 1) from the cumulative
        buckets — linear interpolation inside the covering bucket, the
        standard Prometheus ``histogram_quantile`` shape.  Returns None
        with no observations; values past the last finite bound clamp
        to it (the +Inf bucket has no interpolable width)."""
        key = self._label_key(labels)
        with self._lock:
            state = self._states.get(key)
            if state is None or state.count == 0:
                return None
            counts = list(state.bucket_counts)
            total = state.count
        rank = q * total
        prev_bound, prev_count = 0.0, 0
        for bound, cum in zip(self.bucket_bounds, counts):
            if cum >= rank:
                if bound == math.inf:
                    # No width to interpolate over: the best estimate
                    # is the largest finite bound.
                    return self.bucket_bounds[-2]
                width = cum - prev_count
                if width <= 0:
                    return bound
                return prev_bound + (bound - prev_bound) * (rank - prev_count) / width
            prev_bound, prev_count = bound, cum
        return self.bucket_bounds[-2]

    def snapshot(self) -> dict[LabelValues, dict[str, Any]]:
        with self._lock:
            return {
                k: {
                    "buckets": list(s.bucket_counts),
                    "sum": s.sum,
                    "count": s.count,
                }
                for k, s in sorted(self._states.items())
            }

    def samples(self):  # _count series, for uniform JSON summaries
        with self._lock:
            return sorted((k, float(s.count)) for k, s in self._states.items())

    def to_dict(self):
        return {
            "buckets": [b if b != math.inf else "+Inf" for b in self.bucket_bounds],
            "values": {",".join(k): v for k, v in self.snapshot().items()},
        }


class MetricsRegistry:
    """Registry with idempotent constructors: calling ``counter(name)``
    twice returns the same instance (so instrumented modules don't need
    import-order coordination), but re-registering a name as a
    different kind is an error."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}

    def _register(self, cls, name: str, help_text: str, labelnames, **kw) -> Any:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}{existing.labelnames}"
                    )
                return existing
            metric = cls(name, help_text, labelnames, self._lock, **kw)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help_text: str = "", labelnames: Iterable[str] = ()
    ) -> Counter:
        return self._register(Counter, name, help_text, labelnames)

    def gauge(
        self, name: str, help_text: str = "", labelnames: Iterable[str] = ()
    ) -> Gauge:
        return self._register(Gauge, name, help_text, labelnames)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        labelnames: Iterable[str] = (),
        buckets: Iterable[float] = (),
    ) -> Histogram:
        return self._register(
            Histogram, name, help_text, labelnames, buckets=buckets or TIME_BUCKETS
        )

    def collect(self) -> list[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def reset(self) -> None:
        """Zero every metric (tests).  Registrations survive — only the
        recorded values clear."""
        for metric in self.collect():
            with self._lock:
                if isinstance(metric, Histogram):
                    metric._states.clear()
                else:
                    metric._values.clear()  # type: ignore[attr-defined]


#: Span/phase durations in seconds (node epoch phases, sig-verify, ...).
TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
#: Convergence residuals: log-spaced around typical tol values.
RESIDUAL_BUCKETS = (
    1e-9, 1e-8, 1e-7, 1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0,
)
#: End-to-end freshness (attestation accepted -> proven servable
#: score): sub-second ingest hops up through multi-epoch proof lag.
FRESHNESS_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0,
    30.0, 60.0, 120.0, 300.0, 600.0,
)

#: Process-global registry (the node's /metrics source).
METRICS = MetricsRegistry()

# -- the node's metric catalog (README "Observability") ---------------------

ATTESTATIONS_ACCEPTED = METRICS.counter(
    "eigentrust_attestations_accepted_total",
    "Attestations that passed structural + signature checks",
)
ATTESTATIONS_REJECTED = METRICS.counter(
    "eigentrust_attestations_rejected_total",
    "Attestations rejected at ingest, by reason",
    labelnames=("reason",),
)
SIGS_VERIFIED = METRICS.counter(
    "eigentrust_signatures_verified_total",
    "EdDSA signatures checked (accepted or not)",
)
SIG_VERIFY_SECONDS = METRICS.histogram(
    "eigentrust_sig_verify_seconds",
    "Wall-clock of signature verification calls (batched or single)",
    buckets=TIME_BUCKETS,
)
CONVERGENCE_ITERATIONS = METRICS.gauge(
    "eigentrust_convergence_iterations",
    "Power iterations the last open-graph convergence took",
)
CONVERGENCE_RESIDUAL = METRICS.histogram(
    "eigentrust_convergence_residual",
    "Per-iteration L1 residuals, captured device-side in the loop "
    "carry and fetched once after convergence",
    buckets=RESIDUAL_BUCKETS,
)
LAST_RESIDUAL = METRICS.gauge(
    "eigentrust_last_residual",
    "Final L1 residual of the last open-graph convergence",
)
GRAPH_PEERS = METRICS.gauge(
    "eigentrust_graph_peers", "Peers in the last assembled trust graph"
)
GRAPH_EDGES = METRICS.gauge(
    "eigentrust_graph_edges", "Edges in the last assembled trust graph"
)
EPOCHS_TOTAL = METRICS.counter(
    "eigentrust_epochs_total", "Epoch ticks completed"
)
EPOCH_TICKS_DROPPED = METRICS.counter(
    "eigentrust_epoch_ticks_dropped_total",
    "Epoch boundaries skipped because the previous tick overran "
    "(Skip missed-tick semantics)",
)
CHECKPOINT_SAVES = METRICS.counter(
    "eigentrust_checkpoint_saves_total", "Checkpoint snapshots written"
)
CHECKPOINT_RESTORES = METRICS.counter(
    "eigentrust_checkpoint_restores_total", "Checkpoint snapshots loaded"
)
PLAN_REBUILDS = METRICS.counter(
    "eigentrust_window_plan_rebuilds_total",
    "WindowPlan constructions (cold, fingerprint miss, or stale layout)",
)
PLAN_REUSES = METRICS.counter(
    "eigentrust_window_plan_reuses_total",
    "Converges that reused a cached/restored WindowPlan",
)
PLAN_OUTCOMES = METRICS.counter(
    "eigentrust_window_plan_outcomes_total",
    "Per-converge WindowPlan resolution by outcome: reuse (fingerprint "
    "hit), delta (churn folded in via apply_delta), rebuild (full "
    "host construction)",
    labelnames=("outcome",),
)
EPOCH_TICKS_COALESCED = METRICS.counter(
    "eigentrust_epoch_ticks_coalesced_total",
    "Epoch ticks superseded by a newer one while waiting in the "
    "pipeline queue (backpressure: a slow device stage coalesces "
    "pending epochs into the latest instead of dropping them)",
)
PIPELINE_QUEUE_DEPTH = METRICS.gauge(
    "eigentrust_pipeline_queue_depth",
    "Prepared epochs waiting for the device stage (bounded queue)",
)
WARM_START_APPLIED = METRICS.counter(
    "eigentrust_warm_start_applied_total",
    "Epoch convergences seeded from the previous epoch's fixed point",
)
PHASE_SECONDS = METRICS.histogram(
    "eigentrust_phase_seconds",
    "Span durations by phase name (every closed obs span lands here)",
    labelnames=("phase",),
    buckets=TIME_BUCKETS,
)
JIT_RECOMPILES = METRICS.counter(
    "eigentrust_jit_recompiles_total",
    "Compilation-cache misses of the jit'd converge/step entry points "
    "by function — a steady-state delta epoch that recompiles broke "
    "the stable-shape guarantee (PERF.md §11)",
    labelnames=("fn",),
)
SCORE_DRIFT_L1 = METRICS.gauge(
    "eigentrust_score_drift_l1",
    "L1 distance between consecutive epochs' fixed points (surviving "
    "peers aligned by hash)",
)
SCORE_DRIFT_LINF = METRICS.gauge(
    "eigentrust_score_drift_linf",
    "L-infinity distance between consecutive epochs' fixed points",
)
RESIDUAL_STALLS = METRICS.counter(
    "eigentrust_residual_stalls_total",
    "Epochs whose residual trajectory was non-monotone past the "
    "stall threshold (convergence health anomaly)",
)
DEVICE_MEMORY_DELTA = METRICS.gauge(
    "eigentrust_device_memory_delta_bytes",
    "bytes_in_use growth across the last closed span, by phase "
    "(memory_stats watermark watcher; absent on platforms without "
    "allocator stats)",
    labelnames=("phase",),
)
CONVERGE_PEAK_BYTES = METRICS.gauge(
    "eigentrust_converge_peak_bytes",
    "Peak device bytes across the converge phase, by backend: the "
    "memory_stats watermark where the platform reports allocator "
    "stats, else the compiled executable's buffer-assignment peak "
    "(the graftlint pass-12 static view, recorded by tools/mem_probe "
    "and the watermark watcher)",
    labelnames=("backend",),
)
JOURNAL_EVENTS = METRICS.counter(
    "eigentrust_journal_events_total",
    "Flight-recorder events recorded, by kind",
    labelnames=("kind",),
)
JOURNAL_DROPPED = METRICS.counter(
    "eigentrust_journal_dropped_total",
    "Flight-recorder events evicted from the bounded ring before "
    "reaching disk (journal backpressure)",
)
INGEST_QUEUE_DEPTH = METRICS.gauge(
    "eigentrust_ingest_queue_depth",
    "Envelopes (stage=submit) or verify batches (stage=verify) waiting "
    "between admission-plane stages (bounded queues; depth at the bound "
    "means the next submit sheds)",
    labelnames=("stage",),
)
INGEST_SHED = METRICS.counter(
    "eigentrust_ingest_shed_total",
    "Submissions shed by admission-plane backpressure, by stage (a full "
    "submit queue answers 429 instead of queueing unboundedly)",
    labelnames=("stage",),
)
INGEST_ADMISSION_SECONDS = METRICS.histogram(
    "eigentrust_ingest_admission_seconds",
    "Wall-clock from admission-plane submit to the per-item verdict "
    "(accept or reject), the ingest-storm p99 headline",
    buckets=TIME_BUCKETS,
)
INGEST_VERIFY_BATCHES = METRICS.counter(
    "eigentrust_ingest_verify_batches_total",
    "Verify-worker batches by outcome: ok (completed), retried "
    "(resubmitted after a worker crash), failed (rejected with "
    "reason=verify-crashed after retries)",
    labelnames=("outcome",),
)
INGEST_WORKER_RESTARTS = METRICS.counter(
    "eigentrust_ingest_worker_restarts_total",
    "Verify worker-pool rebuilds after a worker process died",
)
PROOF_LAG_EPOCHS = METRICS.gauge(
    "eigentrust_proof_lag_epochs",
    "Newest submitted epoch minus newest proved epoch in the async "
    "proving plane — 0 when proving keeps up with the epoch cadence, "
    "growing when the prover falls behind (the decoupling's health "
    "headline: a slow prover is lag here, never epoch latency)",
)
PROOF_QUEUE_DEPTH = METRICS.gauge(
    "eigentrust_proof_queue_depth",
    "Proof jobs waiting between an epoch tick's enqueue and a prover "
    "dispatcher (bounded; at the bound the oldest queued job is "
    "superseded, latest-wins)",
)
PROVE_SECONDS = METRICS.histogram(
    "eigentrust_prove_seconds",
    "Wall-clock of one epoch proof (power_iterate + circuit check + "
    "SNARK) inside a prover worker",
    buckets=TIME_BUCKETS,
)
PROOFS_COMPLETED = METRICS.counter(
    "eigentrust_proofs_completed_total",
    "Proof jobs that reached state=proved (proof installed and served)",
)
PROOFS_FAILED = METRICS.counter(
    "eigentrust_proofs_failed_total",
    "Proof jobs that reached state=failed (prover crashed or timed "
    "out past its retries; reason=prover-crashed)",
)
PROOFS_SUPERSEDED = METRICS.counter(
    "eigentrust_proofs_superseded_total",
    "Queued proof jobs displaced by a newer epoch under proving-plane "
    "backpressure (latest-wins coalescing; explicit, never a silent "
    "drop)",
)
PROVER_WORKER_RESTARTS = METRICS.counter(
    "eigentrust_prover_worker_restarts_total",
    "Prover worker-pool rebuilds after a worker process died or hung "
    "past the per-job timeout",
)
FRESHNESS_SECONDS = METRICS.histogram(
    "eigentrust_freshness_seconds",
    "Elapsed wall-clock since intake for each lineage-sampled "
    "attestation at every hop of its life (stage label: admitted, "
    "verified, applied, included, converged, proof_landed) — "
    "stage=proof_landed is the end-to-end freshness headline: how long "
    "from POST /attestation to its effect in a proven, servable score",
    labelnames=("stage",),
    buckets=FRESHNESS_BUCKETS,
)
LINEAGE_SAMPLED = METRICS.counter(
    "eigentrust_lineage_sampled_total",
    "Attestations that drew a lineage ID at intake (the sampled "
    "fraction; unsampled submissions pay zero tracker state)",
)
LINEAGE_COMPLETED = METRICS.counter(
    "eigentrust_lineage_completed_total",
    "Lineage-sampled attestations that reached proof_landed (their "
    "including epoch's SNARK is served)",
)
LINEAGE_DROPPED = METRICS.counter(
    "eigentrust_lineage_dropped_total",
    "Lineage entries abandoned before proof_landed, by reason: "
    "rejected (the attestation failed admission/verify), evicted "
    "(tracker capacity), shutdown",
    labelnames=("reason",),
)
PROOF_LAG_SECONDS = METRICS.histogram(
    "eigentrust_proof_lag_seconds",
    "Submit-to-proved wall-clock per proof job (the per-job component "
    "of the proof-lag headline; the SLO engine gates its p99)",
    buckets=FRESHNESS_BUCKETS,
)
SLO_OK = METRICS.gauge(
    "eigentrust_slo_ok",
    "Per-objective SLO verdict at the last evaluation: 1 = meeting "
    "the objective (or no data yet), 0 = violating",
    labelnames=("objective",),
)
SLO_BURN_RATE = METRICS.gauge(
    "eigentrust_slo_burn_rate",
    "Fraction of the objective's recent evaluation window spent in "
    "violation (0 = healthy, 1 = burning the whole window)",
    labelnames=("objective",),
)
SLO_VIOLATIONS = METRICS.counter(
    "eigentrust_slo_violations_total",
    "ok->violating transitions per objective (each one is journaled "
    "with the violating value)",
    labelnames=("objective",),
)
HEALTH_STATUS = METRICS.gauge(
    "eigentrust_health_status",
    "GET /healthz verdict as a number: 0 = ok, 1 = degraded, "
    "2 = failed (load balancers read the HTTP status instead)",
)
FLEET_SOURCES = METRICS.gauge(
    "eigentrust_fleet_sources",
    "Worker/process metric snapshots currently merged into the fleet "
    "scrape (GET /metrics/fleet), beyond the node process itself",
)
WORKER_SNAPSHOT_MERGES = METRICS.counter(
    "eigentrust_worker_metric_merges_total",
    "Per-worker metric snapshots shipped back across the spawn "
    "boundary and merged into the fleet aggregator, by pool",
    labelnames=("pool",),
)
WAL_APPENDED = METRICS.counter(
    "eigentrust_wal_appended_total",
    "Attestation records appended to the write-ahead log (every "
    "accepted attestation lands here before its ingest verdict "
    "returns — the crash-consistency boundary, node/wal.py)",
)
WAL_REPLAYED = METRICS.counter(
    "eigentrust_wal_replayed_total",
    "WAL records re-applied through the apply_verified fast path "
    "during boot recovery (the tail past the newest valid "
    "checkpoint's wal_seq watermark)",
)
CHECKPOINT_FALLBACKS = METRICS.counter(
    "eigentrust_checkpoint_fallbacks_total",
    "Snapshots skipped during load because they were torn, corrupt "
    "(per-column sha256 mismatch), or unreadable — recovery fell back "
    "to the previous epoch (journaled with the failure)",
)
RECOVERY_SECONDS = METRICS.gauge(
    "eigentrust_recovery_seconds",
    "Wall-clock of the last boot recovery (checkpoint load + warm "
    "state restore + WAL tail replay); /healthz reports component "
    "state recovering while this is in progress",
)
RPC_RETRIES = METRICS.counter(
    "eigentrust_rpc_retries_total",
    "Chain RPC calls retried by the event-stream retry wall "
    "(exponential backoff + jitter + per-call timeout), by operation",
    labelnames=("op",),
)
POD_HOSTS = METRICS.gauge(
    "eigentrust_pod_hosts",
    "Hosts (jax.distributed processes) in this node's pod — 1 on a "
    "single-host deployment; the peer→host rendezvous partition "
    "(parallel/partition.py) is keyed on this count",
)
POD_HOST_ID = METRICS.gauge(
    "eigentrust_pod_host_id",
    "This process's host id inside the pod's rendezvous partition",
)
POD_OWNED_PEERS = METRICS.gauge(
    "eigentrust_pod_owned_peers",
    "Peers whose out-edges (and WAL/checkpoint shard rows) this host "
    "owns under the pod partition — tracks n/n_hosts when the "
    "rendezvous hash is balanced",
)
POD_LOCAL_EDGES = METRICS.gauge(
    "eigentrust_pod_local_edges",
    "Edges in this host's partition (source peer owned here) — the "
    "host's plan-build and WAL-volume driver; the pod total is the "
    "graph's edge count",
)
POD_PLAN_BUILD_SECONDS = METRICS.gauge(
    "eigentrust_pod_plan_build_seconds",
    "Wall-clock of this host's last LOCAL window-plan resolution "
    "(delta or rebuild over owned edges only; 0 on verbatim reuse) — "
    "the pod's plan-build critical path is the max across hosts, vs "
    "the serial full-graph build it replaces (PERF.md §20)",
)
POD_PLAN_REUSED = METRICS.counter(
    "eigentrust_pod_plan_reused_total",
    "Epochs whose churn was entirely owned by other hosts, so this "
    "host revalidated its local fingerprint and reused its plan "
    "verbatim — the partition-locality win, by outcome "
    "(reuse/delta/rebuild)",
    labelnames=("outcome",),
)
POD_EPOCH_SECONDS = METRICS.gauge(
    "eigentrust_pod_epoch_seconds",
    "Steady-state wall-clock of the last pod epoch (plan resolution + "
    "sharded converge + durability stamp) as this host measured it — "
    "the flat-vs-single-host headline series of PERF.md §20",
)
POD_MANIFESTS_SEALED = METRICS.counter(
    "eigentrust_pod_manifests_sealed_total",
    "Pod manifests sealed by this host (sealer role only): epochs "
    "whose complete per-host shard stamp set was atomically bound "
    "into pod_manifest_e<N>.json (node/pod.py)",
)
POD_PHASE_SKEW_SECONDS = METRICS.histogram(
    "eigentrust_pod_phase_skew_seconds",
    "Per-phase pod skew: max minus median host duration for one "
    "stitched pod epoch phase (plan/converge/checkpoint/wal_flush), "
    "observed by the stitching host (obs/podtrace.py) — the "
    "straggler-attribution signal behind the pod-phase-skew-p99 SLO",
    labelnames=("phase",),
    buckets=TIME_BUCKETS,
)
POD_BARRIER_WAIT_SECONDS = METRICS.gauge(
    "eigentrust_pod_barrier_wait_seconds",
    "Pre-collective barrier-arrival spread of the last stitched pod "
    "epoch: latest minus earliest host arrival at the plan "
    "dimension-agreement allgather (clock-aligned across hosts) — a "
    "fast host pays exactly this long waiting inside the collective",
)
POD_STITCH_SECONDS = METRICS.gauge(
    "eigentrust_pod_stitch_seconds",
    "Wall-clock the stitching host spent aligning clocks and merging "
    "the per-host span trees of the last pod epoch trace "
    "(GET /trace/pod) — obs-plane overhead, budgeted <1% of the epoch",
)
POD_STRAGGLER = METRICS.gauge(
    "eigentrust_pod_straggler",
    "1 while the StragglerWatcher flags this host: its phase time "
    "exceeded the pod median by the configured ratio for k consecutive "
    "epochs (journaled as an anomaly on the flagging transition)",
    labelnames=("host",),
)
FLEET_STALE_SOURCES = METRICS.gauge(
    "eigentrust_fleet_stale_sources",
    "Fleet snapshot sources evicted from the merged scrape because "
    "their newest snapshot aged past the staleness TTL — a silently "
    "dead pod host shows up here (and degrades /healthz) before any "
    "collective hangs on it",
)
LOCK_WAIT_SECONDS = METRICS.histogram(
    "eigentrust_lock_wait_seconds",
    "Lock-acquisition wait time by allocation site — recorded only "
    "under the opt-in lock-witness debug mode "
    "(analysis/concurrency/witness.py); absent on a production node",
    labelnames=("site",),
    buckets=(1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0),
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "METRICS",
    "Metric",
    "MetricsRegistry",
    "FRESHNESS_BUCKETS",
    "RESIDUAL_BUCKETS",
    "TIME_BUCKETS",
    "ATTESTATIONS_ACCEPTED",
    "ATTESTATIONS_REJECTED",
    "SIGS_VERIFIED",
    "SIG_VERIFY_SECONDS",
    "CONVERGENCE_ITERATIONS",
    "CONVERGENCE_RESIDUAL",
    "LAST_RESIDUAL",
    "GRAPH_PEERS",
    "GRAPH_EDGES",
    "EPOCHS_TOTAL",
    "EPOCH_TICKS_DROPPED",
    "CHECKPOINT_SAVES",
    "CHECKPOINT_RESTORES",
    "PLAN_REBUILDS",
    "PLAN_REUSES",
    "PLAN_OUTCOMES",
    "EPOCH_TICKS_COALESCED",
    "PIPELINE_QUEUE_DEPTH",
    "WARM_START_APPLIED",
    "PHASE_SECONDS",
    "JIT_RECOMPILES",
    "SCORE_DRIFT_L1",
    "SCORE_DRIFT_LINF",
    "RESIDUAL_STALLS",
    "DEVICE_MEMORY_DELTA",
    "CONVERGE_PEAK_BYTES",
    "JOURNAL_EVENTS",
    "JOURNAL_DROPPED",
    "INGEST_QUEUE_DEPTH",
    "INGEST_SHED",
    "INGEST_ADMISSION_SECONDS",
    "INGEST_VERIFY_BATCHES",
    "INGEST_WORKER_RESTARTS",
    "PROOF_LAG_EPOCHS",
    "PROOF_QUEUE_DEPTH",
    "PROVE_SECONDS",
    "PROOFS_COMPLETED",
    "PROOFS_FAILED",
    "PROOFS_SUPERSEDED",
    "PROVER_WORKER_RESTARTS",
    "FRESHNESS_SECONDS",
    "LINEAGE_SAMPLED",
    "LINEAGE_COMPLETED",
    "LINEAGE_DROPPED",
    "PROOF_LAG_SECONDS",
    "SLO_OK",
    "SLO_BURN_RATE",
    "SLO_VIOLATIONS",
    "HEALTH_STATUS",
    "FLEET_SOURCES",
    "WORKER_SNAPSHOT_MERGES",
    "WAL_APPENDED",
    "WAL_REPLAYED",
    "CHECKPOINT_FALLBACKS",
    "RECOVERY_SECONDS",
    "RPC_RETRIES",
    "POD_HOSTS",
    "POD_HOST_ID",
    "POD_OWNED_PEERS",
    "POD_LOCAL_EDGES",
    "POD_PLAN_BUILD_SECONDS",
    "POD_PLAN_REUSED",
    "POD_EPOCH_SECONDS",
    "POD_MANIFESTS_SEALED",
    "POD_PHASE_SKEW_SECONDS",
    "POD_BARRIER_WAIT_SECONDS",
    "POD_STITCH_SECONDS",
    "POD_STRAGGLER",
    "FLEET_STALE_SOURCES",
    "LOCK_WAIT_SECONDS",
]
