"""Cross-process metric aggregation: one coherent scrape for a fleet.

The node stopped being one process in PR 7 (spawned verify workers),
PR 9 (2-process ``jax.distributed`` runs), and PR 10 (the prover pool)
— but ``GET /metrics`` still served only the parent registry, so a
worker's signature throughput, prove-phase histograms, and flight
events were invisible.  Two mechanisms close the gap, both built on
:func:`registry_snapshot` (a JSON-able dump of a process's registry):

- **worker shipping**: verify/prover workers snapshot their own
  process-global registry after each batch/job and return it *with the
  result* — flat dicts across the spawn boundary, the PR 10 span-graft
  stance — and the parent folds it into the process-global
  :data:`FLEET` aggregator keyed by ``<pool>-<pid>``;
- **directory exchange**: multi-process runs (``jax.distributed``
  pods, the comm probe) publish snapshots into a shared directory
  (:func:`publish_snapshot`, atomic rename) and any process merges the
  directory on scrape (:func:`load_directory`).

:func:`fleet_prometheus_text` renders the union — the local registry
plus every aggregated source — as ONE exposition document in which
every series gains a ``process`` label (``process="node"`` locally,
``process="<source>"`` for the rest).  Sources keep their *latest*
snapshot (push-gateway semantics), so re-shipping a worker's cumulative
counters never double-counts.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from pathlib import Path
from typing import Any

from .export import _escape_help, _fmt, _labels
from .metrics import METRICS, Histogram, MetricsRegistry
from . import metrics as _metrics

#: Snapshot schema version (bump on shape changes; mismatched files in
#: a fleet directory are skipped, not mis-parsed).
SNAPSHOT_VERSION = 1


def registry_snapshot(
    registry: MetricsRegistry | None = None,
    *,
    skip_empty: bool = True,
    source: str | None = None,
) -> dict[str, Any]:
    """One process's registry as a flat JSON-able dict.

    ``skip_empty`` drops metrics with no recorded samples — a worker
    process registers the full catalog at import but has touched only
    a handful, and shipping zeros per batch is wasted wire."""
    registry = registry if registry is not None else METRICS
    metrics: dict[str, Any] = {}
    for metric in registry.collect():
        if isinstance(metric, Histogram):
            snap = metric.snapshot()
            if skip_empty and not snap:
                continue
            metrics[metric.name] = {
                "kind": metric.kind,
                "help": metric.help,
                "labelnames": list(metric.labelnames),
                "buckets": [
                    "+Inf" if b == math.inf else b for b in metric.bucket_bounds
                ],
                "hist": {
                    ",".join(k): v for k, v in snap.items()
                },
            }
            continue
        samples = metric.samples()
        if skip_empty and not samples:
            continue
        metrics[metric.name] = {
            "kind": metric.kind,
            "help": metric.help,
            "labelnames": list(metric.labelnames),
            "samples": [[list(k), v] for k, v in samples],
        }
    return {
        "version": SNAPSHOT_VERSION,
        "pid": os.getpid(),
        "source": source or f"pid-{os.getpid()}",
        "taken_unix": round(time.time(), 3),
        "metrics": metrics,
    }


class FleetAggregator:
    """Latest-snapshot-per-source store behind the fleet scrape.

    Sources also carry a *staleness* side-table: a source whose newest
    snapshot aged past the directory TTL (:func:`load_directory`'s
    ``max_age_s``) is evicted from the merged exposition but remembered
    here with its age, so ``/healthz`` can degrade on a silently dead
    pod host instead of trusting its last numbers forever.  A fresh
    ingest clears the mark."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: dict[str, dict[str, Any]] = {}
        self._stale: dict[str, float] = {}

    def ingest(self, source: str, snapshot: dict[str, Any]) -> None:
        """Install (or replace) one source's snapshot.  Cumulative
        counters re-shipped by a long-lived worker overwrite the prior
        snapshot, so the rendered series never double-counts."""
        if snapshot.get("version") != SNAPSHOT_VERSION:
            return
        with self._lock:
            self._sources[str(source)] = snapshot
            self._stale.pop(str(source), None)
            n, n_stale = len(self._sources), len(self._stale)
        _metrics.FLEET_SOURCES.set(n)
        _metrics.FLEET_STALE_SOURCES.set(n_stale)

    def forget(self, source: str) -> None:
        with self._lock:
            self._sources.pop(str(source), None)
            self._stale.pop(str(source), None)
            n, n_stale = len(self._sources), len(self._stale)
        _metrics.FLEET_SOURCES.set(n)
        _metrics.FLEET_STALE_SOURCES.set(n_stale)

    def mark_stale(self, source: str, age_s: float) -> None:
        """Evict one source for staleness but keep the tombstone (and
        the observed age) for the health surface."""
        with self._lock:
            self._sources.pop(str(source), None)
            self._stale[str(source)] = float(age_s)
            n, n_stale = len(self._sources), len(self._stale)
        _metrics.FLEET_SOURCES.set(n)
        _metrics.FLEET_STALE_SOURCES.set(n_stale)

    def stale(self) -> dict[str, float]:
        """Stale-evicted sources -> last observed snapshot age (s)."""
        with self._lock:
            return dict(self._stale)

    def sources(self) -> list[str]:
        with self._lock:
            return sorted(self._sources)

    def snapshots(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return dict(self._sources)

    def reset(self) -> None:
        with self._lock:
            self._sources.clear()
            self._stale.clear()
        _metrics.FLEET_SOURCES.set(0)
        _metrics.FLEET_STALE_SOURCES.set(0)


#: Process-global aggregator (the node's /metrics/fleet source).
FLEET = FleetAggregator()


# ---------------------------------------------------------------------------
# Directory exchange (multi-process jax.distributed runs)
# ---------------------------------------------------------------------------


def publish_snapshot(
    directory: str | os.PathLike,
    process_id: str | int,
    registry: MetricsRegistry | None = None,
) -> Path:
    """Write this process's snapshot into a shared fleet directory
    (atomic tmp+rename, so a concurrent merge never reads a torn
    file).  Multi-process runs call this per scrape interval; the
    merging process picks every file up via :func:`load_directory`."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    source = f"proc-{process_id}"
    snap = registry_snapshot(registry, source=source)
    path = directory / f"fleet-{process_id}.json"
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(snap) + "\n")
    tmp.replace(path)
    return path


def load_directory(
    directory: str | os.PathLike,
    aggregator: FleetAggregator | None = None,
    *,
    skip_pid: int | None = None,
    max_age_s: float | None = None,
    clock=time.time,
) -> list[str]:
    """Ingest every snapshot file in a fleet directory (skipping this
    process's own, by pid, so the local registry isn't merged twice).
    Returns the ingested source names; unreadable or version-mismatched
    files are skipped — a scrape must never fail on a half-written
    sibling.

    ``max_age_s`` is the staleness TTL: a snapshot whose ``taken_unix``
    is older than that (against ``clock()``, injectable for tests) is
    *not* ingested — it is evicted via :meth:`FleetAggregator.mark_stale`
    so the dead host drops out of the merged series but stays visible
    to ``/healthz`` and ``eigentrust_fleet_stale_sources``.  Without a
    TTL the old keep-forever behavior holds (worker pools that publish
    once and exit)."""
    aggregator = aggregator if aggregator is not None else FLEET
    directory = Path(directory)
    ingested: list[str] = []
    if not directory.is_dir():
        return ingested
    now = clock() if max_age_s is not None else 0.0
    for path in sorted(directory.glob("fleet-*.json")):
        try:
            snap = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(snap, dict):
            continue
        if skip_pid is not None and snap.get("pid") == skip_pid:
            continue
        source = str(snap.get("source") or path.stem)
        taken = snap.get("taken_unix")
        if (
            max_age_s is not None
            and isinstance(taken, (int, float))
            and now - float(taken) > float(max_age_s)
        ):
            aggregator.mark_stale(source, now - float(taken))
            continue
        aggregator.ingest(source, snap)
        ingested.append(source)
    return ingested


# ---------------------------------------------------------------------------
# Merged exposition
# ---------------------------------------------------------------------------


def _local_as_snapshot(registry: MetricsRegistry | None) -> dict[str, Any]:
    return registry_snapshot(registry, skip_empty=False, source="node")


def fleet_prometheus_text(
    registry: MetricsRegistry | None = None,
    aggregator: FleetAggregator | None = None,
    *,
    local_process: str = "node",
) -> str:
    """The merged fleet exposition: every series from the local
    registry plus every aggregated source, each stamped with a
    ``process`` label.  HELP/TYPE render once per metric name."""
    aggregator = aggregator if aggregator is not None else FLEET
    docs: list[tuple[str, dict[str, Any]]] = [
        (local_process, _local_as_snapshot(registry))
    ]
    docs.extend(sorted(aggregator.snapshots().items()))

    # metric name -> (kind, help, [(process, entry), ...]) in
    # first-seen order, local first.
    merged: dict[str, dict[str, Any]] = {}
    for process, snap in docs:
        for name, entry in snap.get("metrics", {}).items():
            slot = merged.setdefault(
                name,
                {"kind": entry["kind"], "help": entry.get("help", ""), "rows": []},
            )
            slot["rows"].append((process, entry))

    lines: list[str] = []
    for name, slot in merged.items():
        if slot["help"]:
            lines.append(f"# HELP {name} {_escape_help(slot['help'])}")
        lines.append(f"# TYPE {name} {slot['kind']}")
        for process, entry in slot["rows"]:
            labelnames = tuple(entry.get("labelnames", ())) + ("process",)
            if "hist" in entry:
                bounds = [
                    math.inf if b == "+Inf" else float(b)
                    for b in entry["buckets"]
                ]
                hist = entry["hist"] or {
                    ",".join("" for _ in entry.get("labelnames", ())): {
                        "buckets": [0] * len(bounds),
                        "sum": 0.0,
                        "count": 0,
                    }
                }
                for labelkey, state in hist.items():
                    values = tuple(labelkey.split(",")) if entry.get(
                        "labelnames"
                    ) else ()
                    values += (process,)
                    for bound, count in zip(bounds, state["buckets"]):
                        le = f'le="{_fmt(bound)}"'
                        lines.append(
                            f"{name}_bucket"
                            f"{_labels(labelnames, values, le)} {count}"
                        )
                    lines.append(
                        f"{name}_sum{_labels(labelnames, values)} "
                        f"{_fmt(state['sum'])}"
                    )
                    lines.append(
                        f"{name}_count{_labels(labelnames, values)} "
                        f"{state['count']}"
                    )
                continue
            samples = entry.get("samples") or (
                [[[], 0.0]] if not entry.get("labelnames") else []
            )
            for labelvalues, value in samples:
                values = tuple(labelvalues) + (process,)
                lines.append(
                    f"{name}{_labels(labelnames, values)} {_fmt(value)}"
                )
    return "\n".join(lines) + "\n"


__all__ = [
    "FLEET",
    "FleetAggregator",
    "SNAPSHOT_VERSION",
    "fleet_prometheus_text",
    "load_directory",
    "publish_snapshot",
    "registry_snapshot",
]
