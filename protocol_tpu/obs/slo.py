"""Declarative SLO engine: objectives evaluated continuously, enforced
in CI.

Every prior observability PR made a failure *visible*; none made one
*binding*.  This module closes that: a registry of service-level
objectives — each a name, a value source over the metrics registry, a
target, and a direction — evaluated continuously (every epoch tick and
every ``GET /slo`` scrape).  Each evaluation updates:

- ``eigentrust_slo_ok{objective}`` (1/0 verdict),
- ``eigentrust_slo_burn_rate{objective}`` (fraction of the recent
  evaluation window spent violating — the paging signal: a transient
  blip burns little, a sustained regression burns toward 1),
- ``eigentrust_slo_violations_total{objective}`` on every
  ok→violating transition, with the transition journaled to the
  flight recorder (value, target, burn state) so a post-mortem shows
  *when* the objective went red, not just that it is.

The default objective set covers the fleet-plane headline and the
convergence-health invariants (residual-stall gets its footing from
the Absolute Trust convergence analysis, arXiv:1603.00589 — a
well-posed trust operator contracts, so a rising residual trajectory
means the operator changed under the iteration):

- ``freshness-p99``: end-to-end attestation→proven-score p99,
- ``proof-lag-p99``: submit→proved p99 of the async proving plane,
- ``epoch-cadence``: wall seconds since the last landed tick,
- ``shed-rate``: fraction of admission traffic shed with 429,
- ``residual-stall``: count of non-monotone convergence trajectories.

CI enforcement: ``tools/obs_dryrun.py`` fails when any objective
violates after its dryrun epoch, and the workflow also runs it with
``--seed-slo-violation`` (an objective that cannot pass) asserting the
gate actually fails — a regressing objective fails the build, not a
human's memory.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable

from . import metrics as _metrics
from .journal import JOURNAL
from .timeline import TIMELINE

#: Objective directions: the measured value must stay at-or-under
#: (``max``) or at-or-over (``min``) the target.
MAX = "max"
MIN = "min"


@dataclass(frozen=True)
class SLObjective:
    """One declarative objective.

    ``value_fn`` reads current state (metrics registry, timeline) and
    returns the measured value — or None for "no data yet", which
    counts as meeting the objective (a node that has never ingested
    traffic is not violating its shed-rate SLO)."""

    name: str
    description: str
    target: float
    value_fn: Callable[[], float | None]
    direction: str = MAX
    #: Evaluations kept for the burn-rate window.
    window: int = 60
    #: Measurement unit, for the /slo surface.
    unit: str = ""

    def ok(self, value: float | None) -> bool:
        if value is None:
            return True
        if self.direction == MIN:
            return value >= self.target
        return value <= self.target


@dataclass
class _State:
    objective: SLObjective
    history: collections.deque = dc_field(
        default_factory=lambda: collections.deque(maxlen=60)
    )
    ok: bool = True
    last_value: float | None = None
    last_eval_unix: float | None = None

    def __post_init__(self) -> None:
        self.history = collections.deque(maxlen=self.objective.window)


class SLOEngine:
    """Objective registry + evaluator (see module doc)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._states: dict[str, _State] = {}

    # -- registry ----------------------------------------------------------

    def register(self, objective: SLObjective) -> SLObjective:
        """Install (or replace) one objective; its burn window resets."""
        with self._lock:
            self._states[objective.name] = _State(objective)
        return objective

    def unregister(self, name: str) -> None:
        with self._lock:
            self._states.pop(name, None)

    def objectives(self) -> list[str]:
        with self._lock:
            return sorted(self._states)

    def reset(self) -> None:
        with self._lock:
            self._states.clear()

    # -- evaluation --------------------------------------------------------

    def evaluate(self) -> dict[str, Any]:
        """Evaluate every objective now; returns the /slo document.
        Transitions to violating are counted and journaled; gauges
        update on every evaluation."""
        with self._lock:
            states = list(self._states.values())
        results: dict[str, Any] = {}
        all_ok = True
        for state in states:
            obj = state.objective
            try:
                value = obj.value_fn()
            except Exception:  # noqa: BLE001 - observability never throws
                value = None
            ok = obj.ok(value)
            with self._lock:
                was_ok = state.ok
                state.ok = ok
                state.last_value = value
                state.last_eval_unix = time.time()
                state.history.append(0 if ok else 1)
                burn = sum(state.history) / max(len(state.history), 1)
            _metrics.SLO_OK.set(1.0 if ok else 0.0, objective=obj.name)
            _metrics.SLO_BURN_RATE.set(burn, objective=obj.name)
            if was_ok and not ok:
                _metrics.SLO_VIOLATIONS.inc(objective=obj.name)
                JOURNAL.record(
                    "slo-violation",
                    objective=obj.name,
                    value=value,
                    target=obj.target,
                    direction=obj.direction,
                    burn_rate=round(burn, 4),
                )
            elif not was_ok and ok:
                JOURNAL.record(
                    "slo-recovered", objective=obj.name, value=value
                )
            all_ok = all_ok and ok
            results[obj.name] = {
                "description": obj.description,
                "target": obj.target,
                "direction": obj.direction,
                "unit": obj.unit,
                "value": value,
                "ok": ok,
                "burn_rate": round(burn, 4),
                "window": obj.window,
                "evaluations": len(state.history),
            }
        return {"ok": all_ok, "objectives": results}

    def last(self) -> dict[str, Any]:
        """The last verdicts without re-evaluating (tests/cheap reads)."""
        with self._lock:
            return {
                "ok": all(s.ok for s in self._states.values()),
                "objectives": {
                    name: {
                        "ok": s.ok,
                        "value": s.last_value,
                        "target": s.objective.target,
                    }
                    for name, s in sorted(self._states.items())
                },
            }


# ---------------------------------------------------------------------------
# Default objective set
# ---------------------------------------------------------------------------


def _freshness_p99() -> float | None:
    return _metrics.FRESHNESS_SECONDS.quantile(0.99, stage="proof_landed")


def _proof_lag_p99() -> float | None:
    return _metrics.PROOF_LAG_SECONDS.quantile(0.99)


def _shed_rate() -> float | None:
    shed = sum(v for _, v in _metrics.INGEST_SHED.samples())
    accepted = _metrics.ATTESTATIONS_ACCEPTED.value()
    rejected = sum(v for _, v in _metrics.ATTESTATIONS_REJECTED.samples())
    total = shed + accepted + rejected
    if total <= 0:
        return None
    return shed / total


def _residual_stalls() -> float | None:
    return _metrics.RESIDUAL_STALLS.value()


def _score_drift_linf() -> float | None:
    # 0.0 before any epoch pair — that reads as "no drift", which is
    # correct (nothing has moved).
    return _metrics.SCORE_DRIFT_LINF.value()


def default_objectives(
    *,
    epoch_interval_s: float = 10.0,
    freshness_p99_s: float = 120.0,
    proof_lag_p99_s: float = 60.0,
    shed_rate_max: float = 0.01,
    cadence_factor: float = 3.0,
    drift_linf_max: float = 0.5,
) -> list[SLObjective]:
    """The node's standing objectives, parameterized by the deployment
    cadence.  ``install_defaults`` registers them on the global
    engine."""
    return [
        SLObjective(
            name="freshness-p99",
            description=(
                "p99 end-to-end freshness: attestation accepted -> its "
                "effect in a proven, servable score"
            ),
            target=float(freshness_p99_s),
            value_fn=_freshness_p99,
            unit="seconds",
        ),
        SLObjective(
            name="proof-lag-p99",
            description="p99 submit-to-proved lag of the async proving plane",
            target=float(proof_lag_p99_s),
            value_fn=_proof_lag_p99,
            unit="seconds",
        ),
        SLObjective(
            name="epoch-cadence",
            description=(
                "wall seconds since the last landed epoch tick (a stuck "
                "epoch loop violates within a few intervals)"
            ),
            target=float(cadence_factor) * float(epoch_interval_s),
            value_fn=TIMELINE.seconds_since_last_tick,
            unit="seconds",
        ),
        SLObjective(
            name="shed-rate",
            description=(
                "fraction of admission traffic shed with 429 "
                "(queue-full backpressure)"
            ),
            target=float(shed_rate_max),
            value_fn=_shed_rate,
            unit="fraction",
        ),
        SLObjective(
            name="residual-stall",
            description=(
                "epochs whose residual trajectory was non-monotone "
                "(convergence-health invariant: a contracting trust "
                "operator never raises its residual, arXiv:1603.00589)"
            ),
            target=0.0,
            value_fn=_residual_stalls,
            unit="count",
        ),
        SLObjective(
            name="score-drift-linf",
            description=(
                "L-infinity drift between consecutive fixed points "
                "(a whole-score jump means the graph — or a bug — "
                "moved someone's trust mass wholesale)"
            ),
            target=float(drift_linf_max),
            value_fn=_score_drift_linf,
            unit="score",
        ),
    ]


def install_defaults(engine: "SLOEngine | None" = None, **kwargs: Any) -> None:
    """Register the default objective set (node boot / tools)."""
    engine = engine if engine is not None else SLO_ENGINE
    for objective in default_objectives(**kwargs):
        engine.register(objective)


# ---------------------------------------------------------------------------
# Pod objective set (multi-host runs only — a single-host node must not
# carry objectives over signals it can never produce)
# ---------------------------------------------------------------------------


def _pod_phase_skew_p99() -> float | None:
    """Worst per-phase skew p99 across the four stitched epoch phases
    (None until the first stitch feeds the histogram)."""
    from .podtrace import SKEW_PHASES

    values = [
        _metrics.POD_PHASE_SKEW_SECONDS.quantile(0.99, phase=phase)
        for phase in SKEW_PHASES
    ]
    values = [v for v in values if v is not None]
    return max(values) if values else None


def _pod_stitch_missing() -> float | None:
    """Hosts missing from the newest stitched pod trace (None before
    any stitch)."""
    from .podtrace import POD_TRACES

    missing = POD_TRACES.last_missing_hosts()
    return None if missing is None else float(missing)


def _fleet_heartbeat_age() -> float | None:
    """Age of the *stalest* fleet snapshot currently merged — per-host
    heartbeat freshness (None with no sources; already-evicted stale
    sources surface through the stale-sources gauge and /healthz)."""
    from .fleet import FLEET

    now = time.time()
    ages = [
        now - float(snap["taken_unix"])
        for snap in FLEET.snapshots().values()
        if isinstance(snap.get("taken_unix"), (int, float))
    ]
    return max(ages) if ages else None


def pod_objectives(
    *,
    phase_skew_p99_s: float = 1.0,
    heartbeat_max_age_s: float = 30.0,
) -> list[SLObjective]:
    """The pod-level objectives ISSUE 19 adds: skew, stitch
    completeness, heartbeat freshness.  ``install_pod_defaults``
    registers them alongside (not instead of) the node defaults."""
    return [
        SLObjective(
            name="pod-phase-skew-p99",
            description=(
                "p99 of the per-phase pod skew (max - median host "
                "duration, worst phase of plan/converge/checkpoint/"
                "wal_flush) — a straggling host burns the whole pod's "
                "collective time"
            ),
            target=float(phase_skew_p99_s),
            value_fn=_pod_phase_skew_p99,
            unit="seconds",
        ),
        SLObjective(
            name="pod-stitch-completeness",
            description=(
                "hosts missing from the newest stitched pod epoch "
                "trace — every live host must publish its span tree"
            ),
            target=0.0,
            value_fn=_pod_stitch_missing,
            unit="hosts",
        ),
        SLObjective(
            name="pod-heartbeat-freshness",
            description=(
                "age of the stalest per-host metric snapshot in the "
                "fleet exchange — a silently dead host violates here "
                "before any gloo collective hangs on it"
            ),
            target=float(heartbeat_max_age_s),
            value_fn=_fleet_heartbeat_age,
            unit="seconds",
        ),
    ]


def install_pod_defaults(
    engine: "SLOEngine | None" = None, **kwargs: Any
) -> None:
    """Register the pod objective set (multi-host boot / pod dryrun)."""
    engine = engine if engine is not None else SLO_ENGINE
    for objective in pod_objectives(**kwargs):
        engine.register(objective)


def seed_violation(engine: "SLOEngine | None" = None) -> SLObjective:
    """Register an objective that cannot pass — the CI self-check that
    a violating objective actually fails the dryrun gate."""
    engine = engine if engine is not None else SLO_ENGINE
    return engine.register(
        SLObjective(
            name="seeded-violation",
            description=(
                "CI self-check: always-violating objective proving the "
                "SLO gate can fail"
            ),
            target=-1.0,
            value_fn=lambda: 0.0,
            unit="count",
        )
    )


#: Process-global engine (the node's /slo source).  Empty until the
#: node (or a tool/test) installs objectives — a bare library import
#: must not impose deployment targets.
SLO_ENGINE = SLOEngine()


__all__ = [
    "MAX",
    "MIN",
    "SLOEngine",
    "SLObjective",
    "SLO_ENGINE",
    "default_objectives",
    "install_defaults",
    "install_pod_defaults",
    "pod_objectives",
    "seed_violation",
]
