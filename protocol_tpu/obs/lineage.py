"""Attestation lineage sampling: end-to-end freshness measurement.

The one question a production operator keeps asking — "how long from an
attestation hitting ``POST /attestation`` until its effect is in a
*proven, servable* score?" — is unanswerable from per-stage metrics
alone: admission latency, epoch cadence, and proof lag compose through
queues, coalescing, and proof supersession.  This module answers it by
*sampling*: a configurable fraction of submissions draw a lineage ID at
intake and carry it through every hop of their life

    intake -> admitted -> verified -> applied -> included(-in-epoch-E)
           -> converged -> proof_landed

with a landmark timestamp recorded at each hop.  Every hop observes
``eigentrust_freshness_seconds{stage=...}`` (elapsed since intake), so
the per-stage histograms decompose exactly where freshness goes, and
``stage="proof_landed"`` is the end-to-end headline the SLO engine
gates.

Cost doctrine: the *unsampled* path allocates **nothing** — with
sampling disabled ``maybe_begin`` is one attribute read and a return;
with sampling enabled it is one counter tick and a modulo, and only the
1-in-N sampled submissions build tracker state.  A lineage ID is a bare
``int`` (0 = unsampled), so it crosses the spawn boundaries flat —
:class:`~protocol_tpu.prover.jobs.ProofJob` carries the including
epoch's IDs as a plain tuple and the worker echoes them back with the
proof, the same flat-data stance as PR 10's span graft.

Epoch semantics mirror the proving plane's supersede rules: entries
bind to the epoch whose graph absorbed them (``bind_epoch`` at
``Manager.prepare_epoch``); a proof landing for epoch E completes every
entry bound to E *or earlier* (a superseded epoch's effect is proven by
the newer epoch's SNARK — scores are cumulative state).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any

from . import metrics as _metrics

#: Lineage hop names, in lifecycle order.
STAGES = (
    "intake",
    "admitted",
    "verified",
    "applied",
    "included",
    "converged",
    "proof_landed",
)

#: The "not sampled" lineage ID — falsy, flat, allocation-free.
UNSAMPLED = 0


class _Entry:
    __slots__ = ("t0", "stage", "epoch", "hops")

    def __init__(self, t0: float):
        self.t0 = t0
        self.stage = "intake"
        self.epoch: int | None = None
        #: stage -> seconds since intake.
        self.hops: dict[str, float] = {"intake": 0.0}


class LineageTracker:
    """Sampled per-attestation lifecycle tracking (see module doc).

    Thread-safe: intake/admission/verify threads, the epoch executor,
    and proving-plane dispatchers all mark hops; one lock covers the
    entry table.  The sampling decision itself takes no lock (a
    CPython-atomic ``itertools.count`` tick), so the unsampled hot
    path never contends.
    """

    def __init__(self, sample_every: int = 0, max_entries: int = 4096):
        self._every = int(sample_every)
        self.max_entries = int(max_entries)
        self._tick = itertools.count(1)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._entries: dict[int, _Entry] = {}
        #: epoch -> lineage IDs bound to it (insertion-ordered).
        self._by_epoch: dict[int, list[int]] = {}

    # -- configuration ---------------------------------------------------

    def configure(self, sample_every: int) -> "LineageTracker":
        """Set the sampling period (1 = every accepted submission,
        N = one in N, 0 = off).  Existing entries keep running."""
        self._every = int(sample_every)
        return self

    @property
    def sample_every(self) -> int:
        return self._every

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- intake (hot path) -----------------------------------------------

    def maybe_begin(self) -> int:
        """Draw a lineage ID for this submission, or :data:`UNSAMPLED`.

        The unsampled path is allocation-free: disabled sampling is one
        attribute read; enabled sampling adds one counter tick and a
        modulo.  Only the sampled 1-in-N builds an entry."""
        every = self._every
        if every <= 0:
            return UNSAMPLED
        if next(self._tick) % every:
            return UNSAMPLED
        lid = next(self._ids)
        entry = _Entry(time.monotonic())
        with self._lock:
            if len(self._entries) >= self.max_entries:
                evicted = min(self._entries)
                self._discard_locked(evicted)
                _metrics.LINEAGE_DROPPED.inc(reason="evicted")
            self._entries[lid] = entry
        _metrics.LINEAGE_SAMPLED.inc()
        return lid

    # -- hops --------------------------------------------------------------

    def mark(self, lid: int, stage: str) -> None:
        """Record one hop for a sampled entry; a falsy/unknown ID is a
        no-op (the unsampled path costs one comparison here)."""
        if not lid:
            return
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(lid)
            if entry is None:
                return
            elapsed = now - entry.t0
            entry.stage = stage
            entry.hops[stage] = elapsed
        _metrics.FRESHNESS_SECONDS.observe(elapsed, stage=stage)

    def drop(self, lid: int, reason: str = "rejected") -> None:
        """Abandon a sampled entry (its attestation was rejected, or
        the node is shutting down)."""
        if not lid:
            return
        with self._lock:
            if lid not in self._entries:
                return
            self._discard_locked(lid)
        _metrics.LINEAGE_DROPPED.inc(reason=reason)

    def _discard_locked(self, lid: int) -> None:
        entry = self._entries.pop(lid, None)
        if entry is not None and entry.epoch is not None:
            ids = self._by_epoch.get(entry.epoch)
            if ids is not None and lid in ids:
                ids.remove(lid)

    # -- epoch lifecycle ---------------------------------------------------

    def bind_epoch(self, epoch: int) -> tuple[int, ...]:
        """Bind every entry that has reached ``applied`` (and no epoch
        yet) to this epoch — called from ``Manager.prepare_epoch``, the
        moment the epoch's graph absorbs the attestation cache.
        Returns the bound IDs (the epoch's lineage cohort)."""
        epoch = int(epoch)
        now = time.monotonic()
        bound: list[int] = []
        elapsed: list[float] = []
        with self._lock:
            for lid, entry in self._entries.items():
                if entry.epoch is None and entry.stage == "applied":
                    entry.epoch = epoch
                    entry.stage = "included"
                    dt = now - entry.t0
                    entry.hops["included"] = dt
                    bound.append(lid)
                    elapsed.append(dt)
            if bound:
                self._by_epoch.setdefault(epoch, []).extend(bound)
        for dt in elapsed:
            _metrics.FRESHNESS_SECONDS.observe(dt, stage="included")
        return tuple(bound)

    def ids_for_epoch(self, epoch: int) -> tuple[int, ...]:
        """Live lineage IDs whose effect epoch ``epoch``'s proof will
        attest to: everything bound to it or an earlier epoch.  Flat
        ints — this is what :class:`ProofJob.lineage` carries across
        the spawn boundary (``()`` when nothing is sampled)."""
        epoch = int(epoch)
        with self._lock:
            return tuple(
                lid
                for e in sorted(self._by_epoch)
                if e <= epoch
                for lid in self._by_epoch[e]
            )

    def epoch_converged(self, epoch: int) -> None:
        """Mark every entry bound to ``epoch`` (or earlier — a
        coalesced epoch's cohort converges with its superseder) as
        converged."""
        epoch = int(epoch)
        now = time.monotonic()
        elapsed: list[float] = []
        with self._lock:
            for e, ids in self._by_epoch.items():
                if e > epoch:
                    continue
                for lid in ids:
                    entry = self._entries.get(lid)
                    if entry is None or entry.stage != "included":
                        continue
                    entry.stage = "converged"
                    dt = now - entry.t0
                    entry.hops["converged"] = dt
                    elapsed.append(dt)
        for dt in elapsed:
            _metrics.FRESHNESS_SECONDS.observe(dt, stage="converged")

    def epoch_proved(self, epoch: int) -> list[float]:
        """Complete every entry bound to ``epoch`` or earlier (the
        proof supersede semantics: a newer epoch's SNARK covers older
        cohorts) and return their end-to-end freshness seconds —
        ``stage="proof_landed"`` observations, the headline series."""
        epoch = int(epoch)
        now = time.monotonic()
        e2e: list[float] = []
        with self._lock:
            done_epochs = [e for e in self._by_epoch if e <= epoch]
            for e in done_epochs:
                for lid in self._by_epoch.pop(e):
                    entry = self._entries.pop(lid, None)
                    if entry is None:
                        continue
                    e2e.append(now - entry.t0)
        for dt in e2e:
            _metrics.FRESHNESS_SECONDS.observe(dt, stage="proof_landed")
        if e2e:
            _metrics.LINEAGE_COMPLETED.inc(len(e2e))
        return e2e

    # -- queries -----------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """Scrape-ready state: live entries by stage, epoch cohorts."""
        with self._lock:
            by_stage: dict[str, int] = {}
            for entry in self._entries.values():
                by_stage[entry.stage] = by_stage.get(entry.stage, 0) + 1
            return {
                "sample_every": self._every,
                "live": len(self._entries),
                "by_stage": by_stage,
                "epoch_cohorts": {
                    str(e): len(ids) for e, ids in sorted(self._by_epoch.items())
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_epoch.clear()


#: Process-global lineage tracker (configured by the node from
#: ``ProtocolConfig.lineage_sample_every``; off by default in bare
#: library use).
LINEAGE = LineageTracker()


__all__ = ["LINEAGE", "LineageTracker", "STAGES", "UNSAMPLED"]
