"""Shared 4x64-bit limb packing for the ctypes bindings to the C++
runtimes (crypto.native and zk.native): Python ints <-> (n, 4) uint64
little-endian limb arrays, plus the pointer cast helper."""

from __future__ import annotations

import ctypes

import numpy as np

U64P = ctypes.POINTER(ctypes.c_uint64)
_MASK = (1 << 64) - 1


def to_limbs(values) -> np.ndarray:
    """ints -> (n, 4) u64 canonical little-endian limb array."""
    out = np.empty((len(values), 4), dtype=np.uint64)
    for i, v in enumerate(values):
        out[i, 0] = v & _MASK
        out[i, 1] = (v >> 64) & _MASK
        out[i, 2] = (v >> 128) & _MASK
        out[i, 3] = (v >> 192) & _MASK
    return out


def from_limbs(arr: np.ndarray) -> list[int]:
    arr = arr.astype(object)
    return [
        int(r[0]) | int(r[1]) << 64 | int(r[2]) << 128 | int(r[3]) << 192 for r in arr
    ]


def to_limbs_fast(values) -> np.ndarray:
    """Bulk ints -> (n, 4) limb array via one byte buffer (the per-int
    numpy indexing in ``to_limbs`` dominates at 2^18-point domains)."""
    buf = b"".join(v.to_bytes(32, "little") for v in values)
    return np.frombuffer(buf, dtype=np.uint64).reshape(-1, 4).copy()


def from_limbs_fast(arr: np.ndarray) -> list[int]:
    buf = np.ascontiguousarray(arr, dtype=np.uint64).tobytes()
    return [
        int.from_bytes(buf[i : i + 32], "little") for i in range(0, len(buf), 32)
    ]


def ptr(arr: np.ndarray):
    return arr.ctypes.data_as(U64P)
