"""Shared utilities: byte codecs, base58, JSON data IO, logging."""

from .codec import (  # noqa: F401
    b58decode,
    b58encode,
    to_short,
    to_wide,
)
