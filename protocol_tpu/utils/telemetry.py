"""Structured telemetry: named timers/counters and a jax profiler hook.

The reference's observability is bare println (SURVEY.md §5 — proving
time, gas, kernel dumps); the rebuild makes tracing a subsystem: every
hot path records into a process-global registry the node exposes over
``GET /status``, and ``device_trace`` wraps ``jax.profiler.trace`` for
TPU timeline captures.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field


@dataclass
class TimerStats:
    count: int = 0
    total: float = 0.0
    last: float = 0.0
    max: float = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.last = seconds
        self.max = max(self.max, seconds)

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": round(self.total, 6),
            "last_s": round(self.last, 6),
            "max_s": round(self.max, 6),
            "mean_s": round(self.total / self.count, 6) if self.count else 0.0,
        }


@dataclass
class Telemetry:
    """Thread-safe: the node records from executor threads while the
    event loop snapshots for /status."""

    timers: dict[str, TimerStats] = field(default_factory=lambda: defaultdict(TimerStats))
    counters: dict[str, int] = field(default_factory=lambda: defaultdict(int))
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - t0
            with self._lock:
                self.timers[name].record(elapsed)

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "timers": {k: v.to_dict() for k, v in self.timers.items()},
                "counters": dict(self.counters),
            }

    def reset(self) -> None:
        with self._lock:
            self.timers.clear()
            self.counters.clear()


#: Process-global registry (the node's /status source).
TELEMETRY = Telemetry()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture a TPU timeline with jax.profiler (view with
    tensorboard/xprof).  No-op context if jax is unavailable."""
    try:
        import jax
    except ImportError:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
