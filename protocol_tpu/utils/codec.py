"""Byte-level codecs: base58 and fixed-width buffer helpers.

Mirrors the reference's bs58 usage (server/src/utils.rs:21-24,
manager/mod.rs:96-99) and the to_wide/to_short padding helpers
(circuit/src/utils.rs:176-188, server/src/utils.rs:7-18).
"""

from __future__ import annotations

_B58_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_B58_INDEX = {c: i for i, c in enumerate(_B58_ALPHABET)}


def b58encode(data: bytes) -> str:
    """Bitcoin-alphabet base58 (the bs58 crate's default)."""
    n = int.from_bytes(data, "big")
    out = []
    while n:
        n, rem = divmod(n, 58)
        out.append(_B58_ALPHABET[rem])
    # Leading zero bytes encode as '1's.
    n_leading = len(data) - len(data.lstrip(b"\x00"))
    return "1" * n_leading + "".join(reversed(out))


def b58decode(s: str) -> bytes:
    n = 0
    for c in s:
        if c not in _B58_INDEX:
            raise ValueError(f"invalid base58 character {c!r}")
        n = n * 58 + _B58_INDEX[c]
    body = n.to_bytes((n.bit_length() + 7) // 8, "big") if n else b""
    n_leading = len(s) - len(s.lstrip("1"))
    return b"\x00" * n_leading + body


def to_wide(b: bytes) -> bytes:
    """Zero-pad to 64 bytes (circuit/src/utils.rs:176-180)."""
    assert len(b) <= 64
    return b + b"\x00" * (64 - len(b))


def to_short(b: bytes) -> bytes:
    """Zero-pad (or pass through) to 32 bytes
    (circuit/src/utils.rs:183-188)."""
    assert len(b) <= 32
    return b + b"\x00" * (32 - len(b))
