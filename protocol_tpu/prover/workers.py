"""The spawn-based prover worker pool.

Proving is the one epoch phase that burns whole cores for seconds at a
time — the native MSM/NTT OpenMP loops plus field-level Python — and
in-process it competes with the epoch loop and the ingest dispatchers
for the GIL and the core budget.  The pool here is the ingest
verify-pool topology applied to proving: spawned worker processes
(flat :class:`~protocol_tpu.prover.jobs.ProofJob` payloads, so a child
imports only the zk/crypto tree), per-worker OpenMP thread pinning,
and crash recovery as a first-class outcome — a dead or hung worker
rebuilds the executor once per generation and the in-flight job is
retried up to ``max_retries`` times before :class:`ProverCrashed`
carries it out to be *failed with a reason code*, never silently
dropped.

Each worker process caches its compiled prover (SRS + proving key)
across jobs — :func:`~protocol_tpu.prover.jobs.prover_for` — and
:meth:`ProverPool.prewarm` builds that cache at pool start (the ingest
pool-prewarm analog), so steady-state jobs pay zero setup: the ``srs``
phase timer goes quiet after the first job (PERF.md §16).
"""

from __future__ import annotations

import os
import tempfile
import threading
from concurrent.futures import BrokenExecutor, ProcessPoolExecutor, TimeoutError
from multiprocessing import get_context

from ..obs import metrics as obs_metrics
from ..obs.journal import (
    JOURNAL,
    collect_worker_dumps,
    install_worker_dump_handler,
)
from .jobs import ProofJob, ProofResult, prove_job, prover_for


def _worker_init(omp_threads: int, dump_dir: str | None = None) -> None:
    """Runs in each spawned worker before any job: pin (or free) the
    native runtime's OpenMP width, install the flight-recorder dump
    handler (a SIGTERM'd — e.g. hung-and-terminated — worker leaves
    its event ring behind for the parent's post-mortem), and pre-load
    the zk runtime off the first job's critical path."""
    if omp_threads > 0:
        os.environ["OMP_NUM_THREADS"] = str(omp_threads)
    install_worker_dump_handler(dump_dir, pool="prover")
    from ..zk import native as zk_native

    zk_native.available()


def _worker_prewarm(
    params: tuple[int, int, int, int], prover: str, srs_path: str | None
) -> bool:
    """Build this worker's prover cache (SRS load + keygen/cached-pk
    load) ahead of the first real job."""
    prover_for(params, prover, srs_path)
    return True


def _worker_prove(job: ProofJob, verify: bool) -> ProofResult:
    return prove_job(job, verify=verify)


class ProverCrashed(RuntimeError):
    """A job's worker died (or timed out) ``max_retries + 1`` times;
    the plane must fail the job with ``reason="prover-crashed"``.
    ``flight_tail`` carries whatever per-worker flight-recorder dumps
    the pool recovered (a terminated hung worker dumps its ring on
    SIGTERM; a hard-killed one leaves nothing)."""

    def __init__(self, *args: object) -> None:
        super().__init__(*args)
        self.flight_tail: list = []


class ProverPool:
    """Process-pool façade with crash recovery and per-job timeout.

    ``workers=0`` proves inline on the calling thread (no processes —
    the small-node and unit-test default); ``workers>0`` spawns that
    many prover processes.  :meth:`prove` blocks until the job's proof
    is in, so the plane runs one dispatcher thread per worker.

    ``timeout_s`` bounds one attempt: a worker that exceeds it is
    treated exactly like a crashed worker (generation-guarded executor
    rebuild, best-effort terminate of the old processes, retry) — a
    wedged prover must never wedge the plane.
    """

    def __init__(
        self,
        workers: int = 0,
        *,
        max_retries: int = 1,
        timeout_s: float | None = None,
        omp_threads: int = 0,
        verify: bool = True,
    ):
        self.workers = int(workers)
        self.max_retries = int(max_retries)
        self.timeout_s = timeout_s
        self.omp_threads = int(omp_threads)
        self.verify = bool(verify)
        self._lock = threading.Lock()
        self._generation = 0
        self._executor: ProcessPoolExecutor | None = None
        #: Flight-recorder tails recovered from crashed workers' dump
        #: files, attached to the next ProverCrashed (under _lock).
        self._flight_tail: list = []
        self._dump_dir: str | None = (
            tempfile.mkdtemp(prefix="prover_flight_") if self.workers > 0 else None
        )
        if self.workers > 0:
            self._executor = self._make()

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def _make(self) -> ProcessPoolExecutor:
        return ProcessPoolExecutor(
            max_workers=self.workers,
            mp_context=get_context("spawn"),
            initializer=_worker_init,
            initargs=(self.omp_threads, self._dump_dir),
        )

    def _snapshot(self) -> tuple[int, ProcessPoolExecutor | None]:
        with self._lock:
            return self._generation, self._executor

    def _restart(self, generation: int) -> None:
        """Rebuild the executor once per crash generation: concurrent
        jobs that observed the same broken generation race here, and
        only the first replaces it."""
        with self._lock:
            if self._generation != generation or self._executor is None:
                return
            old = self._executor
            self._executor = self._make()
            self._generation += 1
        # A hung worker survives shutdown(cancel_futures=True); kill it
        # so a timeout doesn't leak a core-burning orphan.  SIGTERM
        # also triggers the worker's flight-dump handler, so "what was
        # the hung prover doing" survives into the dump dir.
        procs = list(getattr(old, "_processes", {}).values())
        for proc in procs:
            try:
                proc.terminate()
            except (OSError, ValueError):
                pass
        for proc in procs:
            try:
                proc.join(timeout=2.0)
            except (OSError, ValueError, AssertionError):
                pass
        old.shutdown(wait=False, cancel_futures=True)
        tails = collect_worker_dumps(self._dump_dir, pool="prover")
        if tails:
            with self._lock:
                self._flight_tail.extend(tails)
        obs_metrics.PROVER_WORKER_RESTARTS.inc()
        JOURNAL.record("anomaly", what="prover-worker-crashed", generation=generation)

    def take_flight_tail(self) -> list:
        """Pop the recovered worker flight-recorder events (attached to
        crashed jobs by :meth:`prove`)."""
        with self._lock:
            tail, self._flight_tail = self._flight_tail, []
        return tail

    def prewarm(self, params, prover: str = "plonk", srs_path: str | None = None):
        """Build every worker's prover cache now (SRS + proving key),
        so the first real job pays no setup.  Inline pools warm the
        calling process's cache instead.  Best-effort: a crash during
        prewarm surfaces on the first real job's retry path."""
        params = tuple(int(p) for p in params)
        _, executor = self._snapshot()
        if executor is None:
            prover_for(params, prover, srs_path)
            return
        futures = [
            executor.submit(_worker_prewarm, params, prover, srs_path)
            for _ in range(self.workers)
        ]
        for f in futures:
            try:
                f.result(timeout=self.timeout_s)
            except (BrokenExecutor, TimeoutError, RuntimeError, OSError):
                break

    def prove(self, job: ProofJob) -> ProofResult:
        """Blocking prove with crash/timeout retry; raises
        :class:`ProverCrashed` when the job outlives its retries."""
        attempts = 0
        while True:
            generation, executor = self._snapshot()
            try:
                if executor is None:
                    return prove_job(job, verify=self.verify)
                future = executor.submit(_worker_prove, job, self.verify)
                return future.result(timeout=self.timeout_s)
            except (BrokenExecutor, TimeoutError, RuntimeError) as exc:
                # RuntimeError covers submit() on a shutdown executor
                # racing close(); TimeoutError is a wedged worker.
                # Both rebuild and retry so jobs are never silently
                # dropped.
                self._restart(generation)
                attempts += 1
                if attempts > self.max_retries:
                    crashed = ProverCrashed(
                        f"epoch {job.epoch} proof attempt died "
                        f"{attempts} time(s): {exc!r}"
                    )
                    crashed.flight_tail = self.take_flight_tail()
                    raise crashed from exc
                JOURNAL.record(
                    "anomaly",
                    what="prove-retried",
                    epoch=job.epoch,
                    attempt=attempts,
                )

    def close(self) -> None:
        with self._lock:
            executor, self._executor = self._executor, None
        if executor is not None:
            executor.shutdown(wait=False, cancel_futures=True)


__all__ = ["ProverCrashed", "ProverPool"]
