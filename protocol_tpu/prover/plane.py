"""The asynchronous proving plane: epoch ticks enqueue, workers prove.

The epoch pipeline's device stage ends at ``converge → checkpoint`` and
hands the SNARK to this plane as a bounded-queue job — a slow prover
then shows up as *proof lag* (``eigentrust_proof_lag_epochs``), never
as epoch latency.  Topology mirrors the ingest plane: a non-blocking
submit in front of a bounded queue, dispatcher threads (one per
worker) feeding the spawn-based :class:`~protocol_tpu.prover.workers.
ProverPool`, and every job resolving to an explicit terminal state.

Lifecycle (the ``GET /proof/<epoch>`` surface)::

    queued → proving → proved
                     ↘ failed      (crashed/timed out past retries)
    queued → superseded            (displaced under backpressure)

Backpressure is *latest-wins coalescing*, the EpochPipeline's
supersede semantics applied to proofs: a full queue displaces the
oldest **queued** job (marked ``superseded`` — counted and journaled,
never silent) in favor of the newest epoch, and :meth:`submit` never
blocks the epoch tick.  A job already ``proving`` is never superseded:
its proof still lands (proofs are per-epoch facts, not cumulative
state), so under sustained overload the plane degrades to proving a
sampled subsequence of epochs — newest-first — with the gap visible as
lag and supersede counts.

When a proof lands, the worker's span tree (``prove{power_iterate,
circuit_check, snark{msm, ntt, gate_eval, ...}}``) is grafted back
into the epoch's stored trace (``Tracer.graft``), so ``GET
/trace/<epoch>`` keeps PR 6's deep attribution even though the prove
ran epochs later in another process.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable

from ..obs import TRACER
from ..obs import metrics as obs_metrics
from ..obs.fleet import FLEET
from ..obs.journal import JOURNAL
from ..obs.lineage import LINEAGE
from ..obs.timeline import TIMELINE
from .jobs import (
    FAILED,
    PROVED,
    PROVING,
    QUEUED,
    SUPERSEDED,
    ProofJob,
    ProofResult,
)
from .workers import ProverCrashed, ProverPool

log = logging.getLogger(__name__)

#: Terminal lifecycle entries kept for inspection (the /proof surface).
_STATUS_RING = 64


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(int(round(q * (len(vals) - 1))), len(vals) - 1)
    return vals[idx]


@dataclass(frozen=True)
class ProvingPlaneConfig:
    #: Prover worker processes; 0 = prove inline on the dispatcher
    #: thread (no pool — the unit-test and tiny-node default).  The
    #: plane runs one dispatcher per worker either way.
    workers: int = 1
    #: Jobs that may wait between submit and a dispatcher.  Beyond it,
    #: the oldest queued job is superseded (latest-wins) — an epoch
    #: tick never blocks on a full proof queue.
    queue_depth: int = 1
    #: Worker-crash/timeout retries per job before ``failed``.
    max_retries: int = 1
    #: Per-attempt wall-clock bound; a worker past it is treated as
    #: crashed (killed + retried).  None = unbounded.
    prove_timeout_s: float | None = 900.0
    #: OMP_NUM_THREADS for each worker's native MSM/NTT loops
    #: (0 = leave the runtime default).
    omp_threads: int = 0
    #: Verify each proof in the worker before returning it.
    verify: bool = True


@dataclass
class ProofStatus:
    """One epoch's position in the proof lifecycle."""

    epoch: int
    state: str
    reason: str | None = None
    prove_seconds: float | None = None
    #: Submit → terminal-state wall-clock (the proof-lag headline's
    #: per-job component).
    lag_seconds: float | None = None
    submitted: float = dc_field(default_factory=time.perf_counter)

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"epoch": self.epoch, "state": self.state}
        if self.reason is not None:
            out["reason"] = self.reason
        if self.prove_seconds is not None:
            out["prove_seconds"] = round(self.prove_seconds, 4)
        if self.lag_seconds is not None:
            out["lag_seconds"] = round(self.lag_seconds, 4)
        return out


class ProvingPlane:
    """The async proving tier behind one node (or bench driver).

    ``on_proved`` receives every landed :class:`ProofResult` on a
    dispatcher thread — the node installs the proof into the Manager's
    cache there.  All lifecycle state lives under one condition
    variable; submit paths, dispatchers, and HTTP status reads share
    it (graftlint pass 7 discipline).
    """

    def __init__(
        self,
        config: ProvingPlaneConfig | None = None,
        *,
        on_proved: Callable[[ProofResult], None] | None = None,
    ):
        self.config = config or ProvingPlaneConfig()
        self.pool = ProverPool(
            self.config.workers,
            max_retries=self.config.max_retries,
            timeout_s=self.config.prove_timeout_s,
            omp_threads=self.config.omp_threads,
            verify=self.config.verify,
        )
        self._on_proved = on_proved
        self._cv = threading.Condition()
        self._queue: deque[ProofJob] = deque()
        self._status: dict[int, ProofStatus] = {}
        self._pending = 0  # jobs queued or proving
        #: Highest epoch ever submitted / proved (the lag gauge pair).
        self._latest_submitted: int | None = None
        self._latest_proved: int | None = None
        self.completed = 0
        self.failed = 0
        self.superseded = 0
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(
                target=self._dispatch_loop, name=f"prover-dispatch-{i}", daemon=True
            )
            for i in range(max(1, self.config.workers))
        ]
        self._started = False

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ProvingPlane":
        # Flip under the condition lock: the node boot path and a bench
        # driver can race start(), and a bare check-then-act would
        # double-start the dispatcher threads.
        with self._cv:
            if self._started:
                return self
            self._started = True
        obs_metrics.PROOF_QUEUE_DEPTH.set(0)
        obs_metrics.PROOF_LAG_EPOCHS.set(0)
        obs_metrics.PROOFS_COMPLETED.inc(0)
        obs_metrics.PROOFS_FAILED.inc(0)
        obs_metrics.PROOFS_SUPERSEDED.inc(0)
        for t in self._threads:
            t.start()
        return self

    def prewarm(self, params, prover: str = "plonk", srs_path: str | None = None):
        """Build every worker's SRS/proving-key cache now (pool start),
        so the first epoch's job pays no setup (PERF.md §16)."""
        self.pool.prewarm(params, prover, srs_path)

    def close(self, *, drain: bool = True, timeout: float = 120.0) -> None:
        with self._cv:
            started = self._started
        if drain and started:
            self.drain(timeout=timeout)
        self._stop.set()
        if started:
            for t in self._threads:
                t.join(timeout=10.0)
        self.pool.close()
        # Anything still queued after an undrained close gets a
        # terminal state — the lifecycle never leaks a silent drop.
        with self._cv:
            stragglers = list(self._queue)
            self._queue.clear()
            for job in stragglers:
                self._set_status(job.epoch, FAILED, reason="shutdown")
                self.failed += 1
                self._pending -= 1
            self._cv.notify_all()

    def __enter__(self) -> "ProvingPlane":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def drain(self, timeout: float = 120.0) -> bool:
        """Block until every submitted job reached a terminal state."""
        with self._cv:
            return self._cv.wait_for(lambda: self._pending == 0, timeout=timeout)

    # -- submit (epoch tick thread) -------------------------------------

    def submit(self, job: ProofJob) -> ProofStatus:
        """Enqueue one epoch's proof job; never blocks.  Under
        backpressure the oldest *queued* job is superseded in favor of
        this one (latest-wins); the displaced epoch's terminal state is
        explicit and counted."""
        self.start()  # idempotent under the condition lock
        displaced: ProofJob | None = None
        with self._cv:
            if len(self._queue) >= max(1, self.config.queue_depth):
                displaced = self._queue.popleft()
                self._set_status(displaced.epoch, SUPERSEDED, by=job.epoch)
                self.superseded += 1
                self._pending -= 1
            self._queue.append(job)
            self._pending += 1
            status = self._set_status(job.epoch, QUEUED)
            if (
                self._latest_submitted is None
                or job.epoch > self._latest_submitted
            ):
                self._latest_submitted = job.epoch
            self._update_lag_locked()
            obs_metrics.PROOF_QUEUE_DEPTH.set(len(self._queue))
            self._cv.notify()
        TIMELINE.record(
            job.epoch,
            proof={
                "state": QUEUED,
                "submitted_unix": round(time.time(), 3),
                "lineage_ids": len(job.lineage),
            },
        )
        if displaced is not None:
            obs_metrics.PROOFS_SUPERSEDED.inc()
            TIMELINE.record(
                displaced.epoch,
                proof={"state": SUPERSEDED, "superseded_by": job.epoch},
            )
            JOURNAL.record(
                "proof-superseded", epoch=displaced.epoch, by=job.epoch
            )
            log.warning(
                "epoch %d proof superseded by epoch %d before reaching a "
                "prover (proving-plane backpressure)",
                displaced.epoch,
                job.epoch,
            )
        return status

    # -- dispatchers (one thread per worker) ----------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                if not self._queue:
                    self._cv.wait(timeout=0.05)
                    continue
                job = self._queue.popleft()
                self._set_status(job.epoch, PROVING)
                obs_metrics.PROOF_QUEUE_DEPTH.set(len(self._queue))
            try:
                result = self.pool.prove(job)
            except ProverCrashed as exc:
                self._finish(job.epoch, FAILED, reason="prover-crashed")
                obs_metrics.PROOFS_FAILED.inc()
                TIMELINE.record(
                    job.epoch,
                    proof={"state": FAILED, "reason": "prover-crashed"},
                )
                # The recovered worker flight tail rides with the
                # crashed result: post-mortems survive the spawn
                # boundary (ISSUE 11 satellite).
                JOURNAL.record(
                    "anomaly",
                    what="proof-failed",
                    epoch=job.epoch,
                    error=repr(exc),
                    worker_flight_events=len(exc.flight_tail),
                    worker_flight_last=(
                        exc.flight_tail[-1] if exc.flight_tail else None
                    ),
                )
                log.error("epoch %d proof failed: %r", job.epoch, exc)
                continue
            except BaseException as exc:  # noqa: BLE001 - a job must not kill the loop
                self._finish(job.epoch, FAILED, reason="prove-error")
                obs_metrics.PROOFS_FAILED.inc()
                TIMELINE.record(
                    job.epoch, proof={"state": FAILED, "reason": "prove-error"}
                )
                JOURNAL.record(
                    "anomaly",
                    what="proof-failed",
                    epoch=job.epoch,
                    error=repr(exc),
                )
                log.error("epoch %d proof failed: %r", job.epoch, exc)
                continue
            self._land(job, result)

    def _land(self, job: ProofJob, result: ProofResult) -> None:
        if self._on_proved is not None:
            try:
                self._on_proved(result)
            except Exception:  # noqa: BLE001
                log.exception("epoch %d on_proved hook failed", job.epoch)
        # Deep attribution across the process boundary: the worker's
        # prove span tree lands under the epoch's stored trace root.
        TRACER.graft(job.epoch, result.spans)
        # Cross-process metric aggregation: a pooled worker's registry
        # snapshot rides back with the proof; the parent's own snapshot
        # (inline pools) is already the local scrape, so skip it.
        if result.metrics is not None and result.metrics.get("pid") != os.getpid():
            FLEET.ingest(
                result.metrics.get("source", f"prover-{result.metrics.get('pid')}"),
                result.metrics,
            )
            obs_metrics.WORKER_SNAPSHOT_MERGES.inc(pool="prover")
        obs_metrics.PROVE_SECONDS.observe(result.prove_seconds)
        obs_metrics.PROOFS_COMPLETED.inc()
        status = self._finish(
            job.epoch, PROVED, prove_seconds=result.prove_seconds
        )
        if status.lag_seconds is not None:
            obs_metrics.PROOF_LAG_SECONDS.observe(status.lag_seconds)
        # End-to-end lineage completion: this proof covers every
        # attestation bound to this epoch or an earlier (superseded)
        # one — their freshness clocks stop here.
        e2e = LINEAGE.epoch_proved(job.epoch)
        TIMELINE.record(
            job.epoch,
            proof={
                "state": PROVED,
                "landed_unix": round(time.time(), 3),
                "prove_seconds": round(result.prove_seconds, 4),
                "lag_seconds": round(status.lag_seconds or 0.0, 4),
            },
            freshness={
                "completed": len(e2e),
                "p99_seconds": round(_percentile(e2e, 0.99), 4) if e2e else None,
                "max_seconds": round(max(e2e), 4) if e2e else None,
            },
        )
        JOURNAL.record(
            "proof-landed",
            epoch=job.epoch,
            seconds=round(result.prove_seconds, 3),
            lag_seconds=round(status.lag_seconds or 0.0, 3),
            lineage_completed=len(e2e),
        )
        log.info(
            "epoch %d proved in %.2fs (%.2fs after submit)",
            job.epoch,
            result.prove_seconds,
            status.lag_seconds or 0.0,
        )

    # -- lifecycle store (all under _cv) --------------------------------

    def _set_status(self, epoch: int, state: str, **attrs) -> ProofStatus:
        """Caller holds ``_cv`` (or is pre-start single-threaded)."""
        status = self._status.get(epoch)
        if status is None:
            status = self._status[epoch] = ProofStatus(epoch=epoch, state=state)
            while len(self._status) > _STATUS_RING:
                del self._status[min(self._status)]
        status.state = state
        if "reason" in attrs:
            status.reason = attrs["reason"]
        if state == SUPERSEDED:
            status.reason = f"superseded-by-{attrs.get('by')}"
            status.lag_seconds = time.perf_counter() - status.submitted
        return status

    def _finish(
        self,
        epoch: int,
        state: str,
        *,
        reason: str | None = None,
        prove_seconds: float | None = None,
    ) -> ProofStatus:
        with self._cv:
            status = self._set_status(epoch, state)
            status.reason = reason
            status.prove_seconds = prove_seconds
            status.lag_seconds = time.perf_counter() - status.submitted
            if state == PROVED and (
                self._latest_proved is None or epoch > self._latest_proved
            ):
                self._latest_proved = epoch
            if state == PROVED:
                self.completed += 1
            elif state == FAILED:
                self.failed += 1
            self._pending -= 1
            self._update_lag_locked()
            self._cv.notify_all()
            return status

    def _update_lag_locked(self) -> None:
        """Proof lag in epochs: newest submitted minus newest proved —
        0 when proving keeps up, growing when the prover falls behind."""
        if self._latest_submitted is None:
            lag = 0
        elif self._latest_proved is None:
            lag = self._pending
        else:
            lag = max(self._latest_submitted - self._latest_proved, 0)
        obs_metrics.PROOF_LAG_EPOCHS.set(lag)

    # -- queries --------------------------------------------------------

    def status(self, epoch: int) -> ProofStatus | None:
        with self._cv:
            return self._status.get(epoch)

    def latest_epoch(self) -> int | None:
        """Newest epoch with any lifecycle entry."""
        with self._cv:
            return max(self._status) if self._status else None

    def stats(self) -> dict[str, Any]:
        """Per-instance snapshot (the bench's report source)."""
        with self._cv:
            return {
                "completed": self.completed,
                "failed": self.failed,
                "superseded": self.superseded,
                "pending": self._pending,
                "queue_depth": len(self._queue),
                "latest_submitted": self._latest_submitted,
                "latest_proved": self._latest_proved,
                "states": {
                    e: s.to_dict() for e, s in sorted(self._status.items())
                },
            }


__all__ = ["ProofStatus", "ProvingPlane", "ProvingPlaneConfig"]
