"""Proof jobs: the flat, picklable unit of work the proving plane moves
across the process boundary.

A :class:`ProofJob` carries everything one epoch proof needs as plain
integers and tuples — signature components, public-key coordinates,
score rows, the protocol parameters — so a spawned prover worker
imports only the zk/crypto tree (no jax, no node state, no open-graph
arrays) and two jobs with equal payloads are *the same statement*.

Determinism: PLONK blinding is normally sampled from the system RNG,
which would make the pooled proof differ byte-for-byte from an
in-process proof of the same statement.  :func:`job_seed` derives the
blinding seed from the job payload itself (the RFC-6979 stance:
deterministic nonces bound to the witness), so in-process and pooled
proving are bit-identical and re-proving a superseded epoch is
idempotent.

:func:`prove_job` is the single prove entry both paths share: the
worker processes call it through :mod:`~protocol_tpu.prover.workers`,
and ``workers=0`` pools call it inline.  It rebuilds the epoch
statement (``power_iterate`` → circuit check → SNARK) under a local
span tree and returns the serialized spans with the proof, so PR 6's
prover-internal attribution (msm/ntt/gate_eval/... from
``zk.native.phase_stats``) survives the process boundary and can be
grafted back into the epoch's stored trace.
"""

from __future__ import annotations

import hashlib
import os
import time
from dataclasses import dataclass
from typing import Any

#: Chaos hooks for crash-recovery tests and the prover-storm bench's
#: crash mix (the ingest plane's ``CRASH_MARKER`` analog):
#: ``CRASH_MARKER`` hard-kills the worker on every attempt;
#: ``crash_once_marker(path)`` kills the first attempt only (the retry
#: observes the flag file and proceeds) — the "worker killed mid-MSM →
#: retry → proved" scenario.
CRASH_MARKER = "__crash-prover__"
_CRASH_ONCE_PREFIX = "__crash-prover-once__:"

#: Proof lifecycle states (the ``GET /proof/<epoch>`` surface).
QUEUED = "queued"
PROVING = "proving"
PROVED = "proved"
FAILED = "failed"
SUPERSEDED = "superseded"


def crash_once_marker(flag_path: str) -> str:
    """Chaos spec that kills the worker once: the first attempt creates
    ``flag_path`` and dies; the retry sees it and proves normally."""
    return _CRASH_ONCE_PREFIX + flag_path


@dataclass(frozen=True)
class ProofJob:
    """One epoch's proving work, flattened for the process boundary.

    ``sigs``/``pks``/``ops`` are row-aligned per fixed-set member:
    ``sigs[i] = (R.x, R.y, s)``, ``pks[i] = (x, y)``, ``ops[i]`` the
    member's score row.  ``params`` is ``(num_neighbours, num_iter,
    initial_score, scale)`` — together with ``prover``/``srs_path``
    it keys the per-worker-process prover cache.
    """

    epoch: int
    ops: tuple[tuple[int, ...], ...]
    sigs: tuple[tuple[int, int, int], ...]
    pks: tuple[tuple[int, int], ...]
    params: tuple[int, int, int, int]
    prover: str = "plonk"
    srs_path: str | None = None
    check_circuit: bool = True
    #: Fingerprint of the open graph this epoch converged (identity /
    #: bookkeeping only — the fixed-set statement is fully determined
    #: by the payload above).
    graph_fingerprint: int = 0
    #: Chaos hook (tests/bench): CRASH_MARKER or crash_once_marker().
    chaos: str | None = None
    #: Proving-kernel backend (``zk.graft.VALID_BACKENDS``): ``native``
    #: (ctypes IFMA runtime) or ``graft`` (jit MSM/NTT).  Execution
    #: selection only — both produce byte-identical proofs, so it is
    #: excluded from :func:`job_seed` like the other bookkeeping
    #: fields, and pooled proofs survive a backend switch unchanged.
    zk_backend: str = "native"
    #: Lineage IDs (obs/lineage.py) whose end-to-end freshness this
    #: epoch's proof completes — flat ints across the spawn boundary,
    #: echoed back on the :class:`ProofResult`.  ``()`` on the
    #: unsampled path.  Bookkeeping only: excluded from
    #: :func:`job_seed`, so sampling never perturbs proof bytes.
    lineage: tuple[int, ...] = ()


@dataclass
class ProofResult:
    """What a prove returns across the process boundary."""

    epoch: int
    pub_ins: tuple[int, ...]
    proof: bytes
    #: Serialized span tree of the worker-side prove
    #: (``prove{power_iterate, circuit_check, snark{msm, ntt, ...}}``)
    #: — grafted into the epoch's stored trace by the plane.
    spans: dict[str, Any]
    prove_seconds: float
    #: The job's lineage IDs, echoed back flat (spawn-boundary proof
    #: that sampling survives the worker round-trip).
    lineage: tuple[int, ...] = ()
    #: The worker process's registry snapshot
    #: (``obs.fleet.registry_snapshot``) — merged into the parent's
    #: fleet aggregator under a ``process`` label.
    metrics: dict[str, Any] | None = None


def job_seed(job: ProofJob) -> bytes:
    """Deterministic PLONK blinding seed bound to the statement: same
    (epoch, params, ops, sigs, pks) → same seed → same proof bytes."""
    h = hashlib.sha256(b"protocol_tpu.prove.seed.v1")
    h.update(job.epoch.to_bytes(8, "big"))
    for p in job.params:
        h.update(int(p).to_bytes(8, "big"))
    for row in job.ops:
        for x in row:
            h.update(int(x).to_bytes(32, "big"))
    for rx, ry, s in job.sigs:
        h.update(int(rx).to_bytes(32, "big"))
        h.update(int(ry).to_bytes(32, "big"))
        h.update(int(s).to_bytes(32, "big"))
    for x, y in job.pks:
        h.update(int(x).to_bytes(32, "big"))
        h.update(int(y).to_bytes(32, "big"))
    return h.digest()


def job_fingerprint(job: ProofJob) -> str:
    """Stable hex id of the statement (logs/journal)."""
    return job_seed(job).hex()[:16]


def _run_chaos(chaos: str | None) -> None:
    if chaos is None:
        return
    if chaos == CRASH_MARKER:
        os._exit(1)
    if chaos.startswith(_CRASH_ONCE_PREFIX):
        flag = chaos[len(_CRASH_ONCE_PREFIX) :]
        if not os.path.exists(flag):
            try:
                with open(flag, "x"):
                    pass
            except FileExistsError:
                return
            os._exit(1)
    if chaos.startswith("sleep:"):
        time.sleep(float(chaos.split(":", 1)[1]))


# Per-process prover cache (the SRS/proving-key caching satellite): one
# Prover instance per (params, prover kind, srs_path), built on first
# use — or ahead of time by the pool prewarm — so repeated jobs skip
# SRS load and keygen entirely.  Worker processes are single-threaded
# job loops (one dispatcher feeds each worker one job at a time), so a
# plain dict needs no lock; the in-process path (workers=0) calls
# prove_job from exactly one dispatcher thread per pool.
_PROVERS: dict[tuple, Any] = {}


def prover_for(
    params: tuple[int, int, int, int],
    prover: str = "plonk",
    srs_path: str | None = None,
):
    """The cached per-process Prover for these protocol parameters."""
    key = (tuple(int(p) for p in params), prover, srs_path)
    inst = _PROVERS.get(key)
    if inst is None:
        if prover == "plonk":
            from ..zk.proof import PlonkEpochProver

            n, it, init, scale = key[0]
            inst = PlonkEpochProver(
                num_neighbours=n,
                num_iter=it,
                initial_score=init,
                scale=scale,
                srs_path=srs_path,
            )
        else:
            from ..zk.proof import PoseidonCommitmentProver

            inst = PoseidonCommitmentProver()
        _PROVERS[key] = inst
    return inst


def prove_job(job: ProofJob, *, verify: bool = True) -> ProofResult:
    """Prove one epoch statement (worker-side, or inline for
    ``workers=0``): rebuild the attestations from the flat payload,
    run ``power_iterate`` → circuit check → SNARK under a local span
    tree, and return the proof with its serialized attribution."""
    _run_chaos(job.chaos)

    from ..crypto.babyjubjub import Point
    from ..crypto.eddsa import PublicKey, Signature
    from ..node.attestation import Attestation
    from ..obs import TRACER
    from ..trust.native import power_iterate

    num_neighbours, num_iter, initial_score, scale = job.params
    pks = [PublicKey(Point(x, y)) for x, y in job.pks]
    atts = [
        Attestation(
            sig=Signature.new(rx, ry, s),
            pk=pk,
            neighbours=list(pks),
            scores=list(row),
        )
        for (rx, ry, s), pk, row in zip(job.sigs, pks, job.ops)
    ]
    ops = [list(row) for row in job.ops]
    prover = prover_for(job.params, job.prover, job.srs_path)

    from ..zk.graft import use_zk_backend

    t0 = time.perf_counter()
    with TRACER.span("prove", epoch=job.epoch, pooled=True) as root:
        with TRACER.span("power_iterate"):
            pub_ins = power_iterate(
                [initial_score] * num_neighbours, ops, num_iter, scale
            )
        witness: dict[str, Any] = {"ops": ops, "attestations": atts}
        if job.check_circuit:
            from ..zk.circuit import prove_epoch_statement

            with TRACER.span("circuit_check"):
                witness["cs"] = prove_epoch_statement(
                    atts,
                    pub_ins,
                    num_neighbours=num_neighbours,
                    num_iter=num_iter,
                    initial_score=initial_score,
                    scale=scale,
                )
        with TRACER.span("snark"), use_zk_backend(job.zk_backend):
            proof_bytes = prover.prove(pub_ins, witness, seed=job_seed(job))
    if verify:
        assert prover.verify(pub_ins, proof_bytes), (
            f"epoch {job.epoch}: freshly produced proof failed verification"
        )
    from ..obs.fleet import registry_snapshot

    # PROVE_SECONDS is observed by the plane when the result lands
    # (once, whichever process proved); the worker's own registry ships
    # its span-fed phase histograms in the snapshot below.
    prove_seconds = time.perf_counter() - t0
    return ProofResult(
        epoch=job.epoch,
        pub_ins=tuple(pub_ins),
        proof=proof_bytes,
        spans=root.to_dict(),
        prove_seconds=prove_seconds,
        lineage=tuple(job.lineage),
        metrics=registry_snapshot(source=f"prover-{os.getpid()}"),
    )


__all__ = [
    "CRASH_MARKER",
    "FAILED",
    "PROVED",
    "PROVING",
    "QUEUED",
    "SUPERSEDED",
    "ProofJob",
    "ProofResult",
    "crash_once_marker",
    "job_fingerprint",
    "job_seed",
    "prove_job",
    "prover_for",
]
