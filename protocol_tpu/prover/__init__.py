"""The asynchronous proving plane (ISSUE 10, ROADMAP item 1b).

With warm start landed, ``prove{power_iterate, circuit_check, snark}``
became the dominant steady-state epoch phase — ~8-9 s of MSM-heavy
native work serialized inside every epoch tick (PERF.md §12).  This
package takes the SNARK off the epoch critical path: the device stage
ends at ``converge → checkpoint`` and *enqueues* a proving job; a
spawn-based worker pool proves it concurrently, and a slow prover
shows up as bounded, observable *proof lag* instead of epoch latency.

- :mod:`~protocol_tpu.prover.jobs` — the flat, picklable
  :class:`ProofJob` payload (ints and tuples only, so workers import
  just the zk/crypto tree), deterministic blinding seeds (pooled and
  in-process proofs are bit-identical), and :func:`prove_job`, the
  shared prove entry that returns the proof together with its
  serialized span tree (PR 6's attribution crosses the process
  boundary);
- :mod:`~protocol_tpu.prover.workers` — the ingest-style spawn pool:
  per-worker SRS/proving-key cache with pool-start prewarm, per-job
  timeout, generation-guarded executor rebuild on crash, bounded
  retries — a dead prover fails a job with a reason code, never
  silently;
- :mod:`~protocol_tpu.prover.plane` — the lifecycle
  (``queued → proving → proved | failed | superseded``) behind a
  bounded queue with latest-wins coalescing (the EpochPipeline's
  supersede semantics), dispatcher threads, proof-lag/queue-depth/
  prove-seconds metrics, and the span graft back into the epoch's
  stored trace.

``GET /proof/<epoch>`` serves the lifecycle; graftlint pass 9
(``blocking-prove-in-epoch-loop``) pins the converse — the epoch-loop
files must never call a prover synchronously again.
"""

from .jobs import (
    CRASH_MARKER,
    FAILED,
    PROVED,
    PROVING,
    QUEUED,
    SUPERSEDED,
    ProofJob,
    ProofResult,
    crash_once_marker,
    job_seed,
    prove_job,
)
from .plane import ProofStatus, ProvingPlane, ProvingPlaneConfig
from .workers import ProverCrashed, ProverPool

__all__ = [
    "CRASH_MARKER",
    "FAILED",
    "PROVED",
    "PROVING",
    "QUEUED",
    "SUPERSEDED",
    "ProofJob",
    "ProofResult",
    "ProofStatus",
    "ProverCrashed",
    "ProverPool",
    "ProvingPlane",
    "ProvingPlaneConfig",
    "crash_once_marker",
    "job_seed",
    "prove_job",
]
