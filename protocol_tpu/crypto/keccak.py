"""Keccak-256 (the Ethereum variant: original Keccak padding 0x01, not
NIST SHA-3's 0x06).

Needed for chain interop — event topics, ABI function selectors,
contract addresses (the reference gets these via ethers-rs).  hashlib
only ships NIST SHA-3, so the sponge is implemented here.
"""

from __future__ import annotations

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

_ROTATIONS = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)

_MASK = (1 << 64) - 1


def _rotl(x: int, n: int) -> int:
    n %= 64
    return ((x << n) | (x >> (64 - n))) & _MASK


def _keccak_f(state: list[int]) -> None:
    """keccak-f[1600] on a 5x5 lane state (column-major: state[x*5+y])."""
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [
            state[x * 5]
            ^ state[x * 5 + 1]
            ^ state[x * 5 + 2]
            ^ state[x * 5 + 3]
            ^ state[x * 5 + 4]
            for x in range(5)
        ]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                state[x * 5 + y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y * 5 + (2 * x + 3 * y) % 5] = _rotl(state[x * 5 + y], _ROTATIONS[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                state[x * 5 + y] = b[x * 5 + y] ^ (
                    (~b[((x + 1) % 5) * 5 + y]) & b[((x + 2) % 5) * 5 + y]
                )
        # iota
        state[0] ^= rc


def keccak256(data: bytes) -> bytes:
    rate = 136  # 1088-bit rate for 256-bit output
    state = [0] * 25

    # Pad: 0x01 ... 0x80 (multi-rate padding with Keccak domain bit).
    pad_len = rate - (len(data) % rate)
    padded = (
        data + b"\x01" + b"\x00" * (pad_len - 2) + b"\x80"
        if pad_len >= 2
        else data + b"\x81"
    )

    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[i * 8 : (i + 1) * 8], "little")
            x, y = i % 5, i // 5
            state[x * 5 + y] ^= lane
        _keccak_f(state)

    out = bytearray()
    for i in range(4):  # 32 bytes from the first 4 lanes
        x, y = i % 5, i // 5
        out += state[x * 5 + y].to_bytes(8, "little")
    return bytes(out)


def selector(signature: str) -> bytes:
    """4-byte ABI function selector, e.g. selector("attest((address,
    bytes32,bytes)[])") == 0x5eb5ea10 (client/src/att_station.rs:54)."""
    return keccak256(signature.encode())[:4]


def event_topic(signature: str) -> bytes:
    """32-byte event topic hash."""
    return keccak256(signature.encode())
