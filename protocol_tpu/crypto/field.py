"""Bn254 scalar-field (Fr) arithmetic.

Field elements are plain Python integers in ``[0, MODULUS)``.  The
reference represents them as 4x64-bit limbs behind halo2curves' ``Fr``
(used all over circuit/src); arbitrary-precision integers are the idiomatic
Python equivalent and are exact, which matters because the trust kernel's
field semantics (power iteration with SCALE-multiplied integer scores,
circuit/src/circuit.rs:425-470) must be reproduced bit-exactly on the
native path.  The TPU path computes in floating point with documented
tolerance and reconciles at the proof boundary.
"""

from __future__ import annotations

# Bn254 (alt_bn128) scalar field modulus — the order of the G1 group;
# halo2curves bn256::Fr.
MODULUS = 0x30644E72E131A029B85045B68181585D2833E84879B9709143E1F593F0000001

#: Number of bits in the modulus (Fr::NUM_BITS).
NUM_BITS = 254


def add(a: int, b: int) -> int:
    return (a + b) % MODULUS


def sub(a: int, b: int) -> int:
    return (a - b) % MODULUS


def neg(a: int) -> int:
    return (-a) % MODULUS


def mul(a: int, b: int) -> int:
    return (a * b) % MODULUS


def square(a: int) -> int:
    return (a * a) % MODULUS


def inv(a: int) -> int:
    """Multiplicative inverse; raises ZeroDivisionError on 0 like
    ``Fr::invert().unwrap()`` panics in the reference."""
    if a % MODULUS == 0:
        raise ZeroDivisionError("inverse of zero field element")
    return pow(a, -1, MODULUS)


def pow5(a: int) -> int:
    """x^5 S-box (params/poseidon sbox_f)."""
    a2 = (a * a) % MODULUS
    a4 = (a2 * a2) % MODULUS
    return (a4 * a) % MODULUS


def from_u128(v: int) -> int:
    return v % MODULUS


def to_le_bytes(a: int) -> bytes:
    """Canonical 32-byte little-endian representation (Fr::to_bytes)."""
    return (a % MODULUS).to_bytes(32, "little")


def from_le_bytes(b: bytes) -> int:
    """Parse a canonical 32-byte little-endian repr (Fr::from_bytes /
    from_repr).  Raises ValueError for non-canonical values, mirroring the
    reference's ``.unwrap()`` on a failed CtOption."""
    if len(b) != 32:
        raise ValueError(f"expected 32 bytes, got {len(b)}")
    v = int.from_bytes(b, "little")
    if v >= MODULUS:
        raise ValueError("non-canonical field representation")
    return v


def from_wide_bytes(b: bytes) -> int:
    """Reduce up to 64 little-endian bytes mod the field
    (Fr::from_bytes_wide over a zero-padded buffer, utils.rs to_wide)."""
    if len(b) > 64:
        raise ValueError(f"expected at most 64 bytes, got {len(b)}")
    return int.from_bytes(b, "little") % MODULUS


def from_hex(s: str) -> int:
    """Parse a 0x-prefixed big-endian hex string, reducing mod the field
    (params/mod.rs hex_to_field)."""
    return int(s, 16) % MODULUS


def to_bits(b: bytes) -> list[bool]:
    """LSB-first bit expansion of a byte string (utils.rs to_bits)."""
    out = []
    for i in range(len(b) * 8):
        out.append(bool(b[i // 8] & (1 << (i % 8))))
    return out


def field_to_bits(a: int, n_bits: int = NUM_BITS) -> list[int]:
    """First ``n_bits`` LSB-first bits of the canonical repr as 0/1 ints
    (utils.rs field_to_bits_vec)."""
    bits = to_bits(to_le_bytes(a))
    return [int(x) for x in bits[:n_bits]]
