"""EdDSA over BabyJubJub with Poseidon as the internal hash.

Semantics match circuit/src/eddsa/native.rs exactly:

- secret keys are two Fr elements; random generation hashes a random field
  element with BLAKE-512 and reduces each 32-byte half wide
  (eddsa/native.rs:47-56),
- ``sign``: r = Poseidon(0, sk1, m, 0, 0); R = B8*r;
  S = r + Poseidon(R‖PK‖m)*sk0 mod suborder (eddsa/native.rs:106-127),
- ``verify``: reject S > suborder, check B8*S == R + PK*H(R‖PK‖m)
  (eddsa/native.rs:130-147).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..utils.codec import b58decode, to_short
from . import field
from .babyjubjub import B8, SUBORDER, Point
from .blake512 import blake512
from .poseidon import permute


@dataclass(frozen=True)
class SecretKey:
    """Two-part secret key (sk0 signs, sk1 seeds the nonce)."""

    sk0: int
    sk1: int

    @classmethod
    def from_raw(cls, parts: tuple[bytes, bytes]) -> "SecretKey":
        return cls(field.from_le_bytes(parts[0]), field.from_le_bytes(parts[1]))

    def to_raw(self) -> tuple[bytes, bytes]:
        return (field.to_le_bytes(self.sk0), field.to_le_bytes(self.sk1))

    @classmethod
    def from_bs58(cls, sk0_b58: str, sk1_b58: str) -> "SecretKey":
        """Decode the reference's bs58 secret-key pairs
        (server/src/utils.rs:27-50: raw 32-byte canonical reprs)."""
        return cls.from_raw((to_short(b58decode(sk0_b58)), to_short(b58decode(sk1_b58))))

    @classmethod
    def random(cls, rng=secrets) -> "SecretKey":
        a = (
            rng.randbelow(field.MODULUS)
            if hasattr(rng, "randbelow")
            else rng.randrange(field.MODULUS)
        )
        h = blake512(field.to_le_bytes(a))
        return cls(field.from_wide_bytes(h[:32]), field.from_wide_bytes(h[32:]))

    def public(self) -> "PublicKey":
        return PublicKey(B8.mul_scalar(self.sk0).affine())


@dataclass(frozen=True)
class PublicKey:
    point: Point

    @classmethod
    def from_raw(cls, parts: tuple[bytes, bytes]) -> "PublicKey":
        return cls(Point(field.from_le_bytes(parts[0]), field.from_le_bytes(parts[1])))

    def to_raw(self) -> tuple[bytes, bytes]:
        return (field.to_le_bytes(self.point.x), field.to_le_bytes(self.point.y))

    @classmethod
    def null(cls) -> "PublicKey":
        """PublicKey::default() — the (0,0) sentinel for empty set slots."""
        return cls(Point(0, 0))

    def is_null(self) -> bool:
        return self.point.x == 0 and self.point.y == 0

    def hash(self) -> int:
        """Poseidon(pk.x, pk.y, 0, 0, 0) — the pk-hash used as the
        attestation cache key and group identifier
        (server/src/manager/mod.rs:101-120)."""
        return permute([self.point.x, self.point.y, 0, 0, 0])[0]


@dataclass(frozen=True)
class Signature:
    big_r: Point
    s: int

    @classmethod
    def new(cls, r_x: int, r_y: int, s: int) -> "Signature":
        return cls(Point(r_x, r_y), s)


def sign(sk: SecretKey, pk: PublicKey, m: int) -> Signature:
    r = permute([0, sk.sk1, m, 0, 0])[0]
    big_r = B8.mul_scalar(r).affine()
    m_hash = permute([big_r.x, big_r.y, pk.point.x, pk.point.y, m])[0]
    # Integer (not field) arithmetic mod the suborder, on canonical reprs.
    s = (r + sk.sk0 * m_hash) % SUBORDER
    return Signature(big_r, s)


def verify(sig: Signature, pk: PublicKey, m: int) -> bool:
    if sig.s > SUBORDER:
        return False
    cl = B8.mul_scalar(sig.s)
    m_hash = permute([sig.big_r.x, sig.big_r.y, pk.point.x, pk.point.y, m])[0]
    pk_h = pk.point.mul_scalar(m_hash)
    cr = sig.big_r.projective().add(pk_h)
    return cr.affine() == cl.affine()
