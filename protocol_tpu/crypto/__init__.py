"""Cryptographic primitives: Bn254 Fr, Poseidon, BabyJubJub EdDSA.

Every primitive has a pure-Python exact implementation here, mirroring the
reference's ``native/`` modules; the C++ fast path (``protocol_tpu.crypto
.native``) accelerates batch attestation verification and must stay
bit-compatible (the analog of the reference's native↔circuit duality).
"""

from __future__ import annotations

from . import babyjubjub, blake512, eddsa, field, poseidon  # noqa: F401
from .field import MODULUS as _P
from .poseidon import POSEIDON_5, PoseidonSponge, permute


def group_pks_hash(pks: list[eddsa.PublicKey]) -> int:
    """The sponge half of the protocol message hash that depends only
    on the neighbour group: ``sponge(xs ‖ ys)``.  Cacheable per group —
    every attestation against the same fixed set shares it, so the
    admission plane hashes it once instead of once per signature."""
    pk_sponge = PoseidonSponge()
    pk_sponge.update([pk.point.x for pk in pks])
    pk_sponge.update([pk.point.y for pk in pks])
    return pk_sponge.squeeze()


def _permute_rows(states: list[list[int]]) -> list[list[int]]:
    """Width-5 Poseidon permutation over many states at once: one
    native batch call when the C++ runtime is available, else the pure
    Python permutation per row (bit-identical either way)."""
    from . import native as cnative

    if len(states) > 1 and cnative.available():
        return cnative.poseidon_permute_batch(states)
    return [permute(s) for s in states]


def message_hash_batch(pks_hash: int, scores: list[list[int]]) -> list[int]:
    """Per-row message hashes for a precomputed ``pks_hash``:
    ``Poseidon(pks_hash, sponge(row), 0, 0, 0)`` for every row, with
    the sponge chunks and the final permutation batched across rows
    (the admission plane's verify workers hash whole batches in two or
    three native permute calls instead of ~3 Python permutes each).
    Bit-identical to :func:`calculate_message_hash`'s per-row half."""
    width = POSEIDON_5.width
    n_rows = len(scores)
    rows = [[x % _P for x in row] for row in scores]
    states = [[0] * width for _ in range(n_rows)]
    chunks = max((len(row) + width - 1) // width for row in rows) if rows else 0
    # Sponge absorb: chunk k of every row folds + permutes together —
    # rows shorter than k*width chunks sit out that round unchanged.
    for k in range(chunks):
        active = [i for i, row in enumerate(rows) if k * width < len(row)]
        merged = []
        for i in active:
            chunk = rows[i][k * width : (k + 1) * width]
            chunk = chunk + [0] * (width - len(chunk))
            merged.append([(chunk[j] + states[i][j]) % _P for j in range(width)])
        for i, state in zip(active, _permute_rows(merged)):
            states[i] = state
    # Final binding permute, batched the same way.
    finals = _permute_rows([[pks_hash, states[i][0], 0, 0, 0] for i in range(n_rows)])
    return [f[0] for f in finals]


def calculate_message_hash(
    pks: list[eddsa.PublicKey], scores: list[list[int]]
) -> tuple[int, list[int]]:
    """Protocol message hash (circuit/src/lib.rs:225-256).

    ``pks_hash = sponge(xs ‖ ys)``; for each score row,
    ``Poseidon(pks_hash, sponge(row), 0, 0, 0)``.  Returns
    ``(pks_hash, per-row message hashes)``.
    """
    n = len(pks)
    for row in scores:
        assert len(row) == n
    pks_hash = group_pks_hash(pks)
    return pks_hash, message_hash_batch(pks_hash, scores)
