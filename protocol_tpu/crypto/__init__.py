"""Cryptographic primitives: Bn254 Fr, Poseidon, BabyJubJub EdDSA.

Every primitive has a pure-Python exact implementation here, mirroring the
reference's ``native/`` modules; the C++ fast path (``protocol_tpu.crypto
.native``) accelerates batch attestation verification and must stay
bit-compatible (the analog of the reference's native↔circuit duality).
"""

from __future__ import annotations

from . import babyjubjub, blake512, eddsa, field, poseidon  # noqa: F401
from .poseidon import PoseidonSponge, permute


def calculate_message_hash(
    pks: list[eddsa.PublicKey], scores: list[list[int]]
) -> tuple[int, list[int]]:
    """Protocol message hash (circuit/src/lib.rs:225-256).

    ``pks_hash = sponge(xs ‖ ys)``; for each score row,
    ``Poseidon(pks_hash, sponge(row), 0, 0, 0)``.  Returns
    ``(pks_hash, per-row message hashes)``.
    """
    n = len(pks)
    for row in scores:
        assert len(row) == n

    pk_sponge = PoseidonSponge()
    pk_sponge.update([pk.point.x for pk in pks])
    pk_sponge.update([pk.point.y for pk in pks])
    pks_hash = pk_sponge.squeeze()

    messages = []
    for row in scores:
        score_sponge = PoseidonSponge()
        score_sponge.update(row)
        scores_hash = score_sponge.squeeze()
        messages.append(permute([pks_hash, scores_hash, 0, 0, 0])[0])

    return pks_hash, messages
