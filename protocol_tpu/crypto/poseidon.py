"""Poseidon and Rescue-Prime permutations over Bn254 Fr.

Implements the Hades design (full/partial S-box rounds + MDS mixing) with
the reference's parameter tables, matching
circuit/src/poseidon/native/mod.rs:34-98 (permutation),
circuit/src/poseidon/native/sponge.rs:29-58 (sponge), and
circuit/src/rescue_prime/native/mod.rs:28-57 (Rescue-Prime) bit-exactly —
validated by the golden vectors from those files' tests.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import field
from ._hash_params import (
    POSEIDON_BN254_5X5_FULL_ROUNDS,
    POSEIDON_BN254_5X5_MDS,
    POSEIDON_BN254_5X5_PARTIAL_ROUNDS,
    POSEIDON_BN254_5X5_ROUND_CONSTANTS,
    POSEIDON_BN254_10X5_FULL_ROUNDS,
    POSEIDON_BN254_10X5_MDS,
    POSEIDON_BN254_10X5_PARTIAL_ROUNDS,
    POSEIDON_BN254_10X5_ROUND_CONSTANTS,
    RESCUE_PRIME_BN254_5X5_FULL_ROUNDS,
    RESCUE_PRIME_BN254_5X5_MDS,
    RESCUE_PRIME_BN254_5X5_PARTIAL_ROUNDS,
    RESCUE_PRIME_BN254_5X5_ROUND_CONSTANTS,
)

P = field.MODULUS

# x^(1/5) exponent: the inverse of 5 mod (P - 1), used by the Rescue-Prime
# inverse S-box (params/poseidon sbox_inv_f's hard-coded limbs).
_INV5_EXP = pow(5, -1, P - 1)


@dataclass(frozen=True)
class HashParams:
    """Round parameters for a Hades-style permutation
    (params/mod.rs::RoundParams)."""

    width: int
    full_rounds: int
    partial_rounds: int
    round_constants: tuple[int, ...]
    mds: tuple[tuple[int, ...], ...]

    def round_constants_count(self) -> int:
        return (self.full_rounds + self.partial_rounds) * self.width


POSEIDON_5 = HashParams(
    width=5,
    full_rounds=POSEIDON_BN254_5X5_FULL_ROUNDS,
    partial_rounds=POSEIDON_BN254_5X5_PARTIAL_ROUNDS,
    round_constants=POSEIDON_BN254_5X5_ROUND_CONSTANTS,
    mds=POSEIDON_BN254_5X5_MDS,
)

POSEIDON_10 = HashParams(
    width=10,
    full_rounds=POSEIDON_BN254_10X5_FULL_ROUNDS,
    partial_rounds=POSEIDON_BN254_10X5_PARTIAL_ROUNDS,
    round_constants=POSEIDON_BN254_10X5_ROUND_CONSTANTS,
    mds=POSEIDON_BN254_10X5_MDS,
)

RESCUE_PRIME_5 = HashParams(
    width=5,
    full_rounds=RESCUE_PRIME_BN254_5X5_FULL_ROUNDS,
    partial_rounds=RESCUE_PRIME_BN254_5X5_PARTIAL_ROUNDS,
    round_constants=RESCUE_PRIME_BN254_5X5_ROUND_CONSTANTS,
    mds=RESCUE_PRIME_BN254_5X5_MDS,
)


def _apply_mds(state: list[int], mds: tuple[tuple[int, ...], ...]) -> list[int]:
    width = len(state)
    return [
        sum(state[j] * mds[i][j] for j in range(width)) % P for i in range(width)
    ]


_sbox = field.pow5


def permute(inputs: list[int] | tuple[int, ...], params: HashParams = POSEIDON_5) -> list[int]:
    """The Hades permutation: half the full rounds, then the partial
    rounds (single S-box on lane 0), then the remaining full rounds
    (poseidon/native/mod.rs:34-98)."""
    width = params.width
    assert len(inputs) == width
    half_full = params.full_rounds // 2
    rc = params.round_constants
    mds = params.mds

    state = [x % P for x in inputs]
    idx = 0
    for _ in range(half_full):
        state = [(state[i] + rc[idx + i]) % P for i in range(width)]
        idx += width
        state = [_sbox(x) for x in state]
        state = _apply_mds(state, mds)

    for _ in range(params.partial_rounds):
        state = [(state[i] + rc[idx + i]) % P for i in range(width)]
        idx += width
        state[0] = _sbox(state[0])
        state = _apply_mds(state, mds)

    for _ in range(half_full):
        state = [(state[i] + rc[idx + i]) % P for i in range(width)]
        idx += width
        state = [_sbox(x) for x in state]
        state = _apply_mds(state, mds)

    return state


def rescue_prime_permute(
    inputs: list[int] | tuple[int, ...], params: HashParams = RESCUE_PRIME_5
) -> list[int]:
    """Rescue-Prime: alternating forward/inverse S-box layers with two MDS
    applications per round (rescue_prime/native/mod.rs:28-57)."""
    width = params.width
    assert len(inputs) == width
    rc = params.round_constants
    mds = params.mds

    state = [x % P for x in inputs]
    for r in range(params.full_rounds - 1):
        state = [_sbox(x) for x in state]
        state = _apply_mds(state, mds)
        state = [(state[i] + rc[r * width + i]) % P for i in range(width)]
        state = [pow(x, _INV5_EXP, P) for x in state]
        state = _apply_mds(state, mds)
        state = [(state[i] + rc[(r + 1) * width + i]) % P for i in range(width)]
    return state


def poseidon(inputs: list[int] | tuple[int, ...]) -> int:
    """Hash a width-5 input block, returning lane 0 of the permutation —
    the usage pattern of PoseidonNativeHasher throughout the reference
    (e.g. manager/mod.rs:108, eddsa/native.rs:108)."""
    return permute(inputs, POSEIDON_5)[0]


class PoseidonSponge:
    """Absorb-then-squeeze sponge over the width-5 Poseidon
    (poseidon/native/sponge.rs).  Inputs accumulate until ``squeeze``,
    which folds WIDTH-sized chunks into the state by lane-wise addition and
    permutes after each chunk."""

    def __init__(self, params: HashParams = POSEIDON_5):
        self.params = params
        self.inputs: list[int] = []
        self.state: list[int] = [0] * params.width

    def update(self, inputs: list[int] | tuple[int, ...]) -> None:
        self.inputs.extend(x % P for x in inputs)

    def squeeze(self) -> int:
        assert self.inputs, "squeeze on empty sponge"
        width = self.params.width
        for off in range(0, len(self.inputs), width):
            chunk = self.inputs[off : off + width]
            chunk = chunk + [0] * (width - len(chunk))
            merged = [(chunk[i] + self.state[i]) % P for i in range(width)]
            self.state = permute(merged, self.params)
        self.inputs.clear()
        return self.state[0]
