"""BabyJubJub twisted Edwards curve over Bn254 Fr.

ax^2 + y^2 = 1 + d x^2 y^2 with a = 168700, d = 168696, matching the
reference's curve parameters and projective formulas
(circuit/src/edwards/params.rs:46-114 for the constants and
add/double-2008-bbjlp, circuit/src/edwards/native.rs for the point API).
Points are immutable (x, y[, z]) tuples of field ints.
"""

from __future__ import annotations

from typing import NamedTuple

from . import field
from .field import MODULUS as P

# Curve coefficients (edwards/params.rs:47-53).
A = 0x292FC
D = 0x292F8

# The prime-order subgroup generator B8 (edwards/params.rs:55-64,
# from_raw 4x64 little-endian limbs composed into integers).
B8_X = 0x0BB77A6AD63E739B4EACB2E09D6277C12AB8D8010534E0B62893F3F6BB957051
B8_Y = 0x25797203F7A0B24925572E1CD16BF9EDFCE0051FB9E133774B3C257A872D7D8B

# Full-group generator G (edwards/params.rs:66-75).
G_X = 0x023343E3445B673D38BCBA38F25645ADB494B1255B1162BB40F41A59F4D4B45E
G_Y = 0x0C19139CB84C680A6E14116DA06056174A0CFA121E6E5C2450F87D64FC000001

# Order of the prime subgroup (edwards/params.rs:77-81).
SUBORDER = 0x060C89CE5C263405370A08B6D0302B0BAB3EEDB83920EE0A677297DC392126F1
SUBORDER_SIZE = 252


class Point(NamedTuple):
    """Affine point (edwards/native.rs::Point)."""

    x: int
    y: int

    def projective(self) -> "PointProjective":
        return PointProjective(self.x, self.y, 1)

    def mul_scalar(self, scalar: int) -> "PointProjective":
        """LSB-first double-and-add over the 256-bit canonical repr of the
        scalar (edwards/native.rs:74-87)."""
        r = PointProjective(0, 1, 1)
        exp = self.projective()
        s = scalar % P
        for _ in range(256):
            if s & 1:
                r = r.add(exp)
            exp = exp.double()
            s >>= 1
        return r

    def is_identity(self) -> bool:
        return self.x == 0 and self.y == 0


#: PublicKey::default() / the "null peer" marker is the (0, 0) point,
#: which is *not* on the curve — it acts purely as a sentinel
#: (eddsa/native.rs:68, native.rs filter semantics).
IDENTITY = Point(0, 0)


class PointProjective(NamedTuple):
    """Projective point (edwards/native.rs::PointProjective)."""

    x: int
    y: int
    z: int

    def affine(self) -> Point:
        if self.z % P == 0:
            return Point(0, 0)
        zinv = field.inv(self.z)
        return Point((self.x * zinv) % P, (self.y * zinv) % P)

    def double(self) -> "PointProjective":
        # dbl-2008-bbjlp (edwards/params.rs double()).
        x1, y1, z1 = self.x, self.y, self.z
        b = pow(x1 + y1, 2, P)
        c = (x1 * x1) % P
        d = (y1 * y1) % P
        e = (A * c) % P
        f = (e + d) % P
        h = (z1 * z1) % P
        j = (f - 2 * h) % P
        x3 = ((b - c - d) * j) % P
        y3 = (f * (e - d)) % P
        z3 = (f * j) % P
        return PointProjective(x3, y3, z3)

    def add(self, q: "PointProjective") -> "PointProjective":
        # add-2008-bbjlp (edwards/params.rs:89-113).
        a = (self.z * q.z) % P
        b = (a * a) % P
        c = (self.x * q.x) % P
        d = (self.y * q.y) % P
        e = (D * c * d) % P
        f = (b - e) % P
        g = (b + e) % P
        x3 = (a * f * ((self.x + self.y) * (q.x + q.y) - c - d)) % P
        y3 = (a * g * (d - A * c)) % P
        z3 = (f * g) % P
        return PointProjective(x3, y3, z3)


B8 = Point(B8_X, B8_Y)
G = Point(G_X, G_Y)


def is_on_curve(p: Point) -> bool:
    """Check a*x^2 + y^2 == 1 + d*x^2*y^2."""
    x2 = (p.x * p.x) % P
    y2 = (p.y * p.y) % P
    return (A * x2 + y2) % P == (1 + D * x2 % P * y2) % P
