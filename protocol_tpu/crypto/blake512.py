"""BLAKE-512 (the SHA-3 finalist, not BLAKE2).

The reference derives EdDSA secret keys by hashing a random field element
with BLAKE-512 via the `blake` crate (circuit/src/eddsa/native.rs:20-24,
47-56), which wraps the reference C implementation of the SHA-3-final
BLAKE.  hashlib has no BLAKE-1, so the compression function is implemented
here from the specification: 16 rounds of the ChaCha-derived G function on
a 4x4 matrix of 64-bit words, constants from the hex digits of pi,
big-endian word encoding, and the 0x80..0x01 + 128-bit length padding.
"""

from __future__ import annotations

MASK64 = (1 << 64) - 1

# First 512 bits of the fractional part of pi (BLAKE-512 constants).
_C = (
    0x243F6A8885A308D3, 0x13198A2E03707344, 0xA4093822299F31D0, 0x082EFA98EC4E6C89,
    0x452821E638D01377, 0xBE5466CF34E90C6C, 0xC0AC29B7C97C50DD, 0x3F84D5B5B5470917,
    0x9216D5D98979FB1B, 0xD1310BA698DFB5AC, 0x2FFD72DBD01ADFB7, 0xB8E1AFED6A267E96,
    0xBA7C9045F12C7F99, 0x24A19947B3916CF7, 0x0801F2E2858EFC16, 0x636920D871574E69,
)

# SHA-512 initial values (BLAKE-512 IV).
_IV = (
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
)

_SIGMA = (
    (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    (14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3),
    (11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4),
    (7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8),
    (9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13),
    (2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9),
    (12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11),
    (13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10),
    (6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5),
    (10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0),
)

# G-function targets per round: 4 column steps then 4 diagonal steps.
_IDX = (
    (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
    (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
)


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (64 - n))) & MASK64


def _compress(h: list[int], block: bytes, t: int, salt=(0, 0, 0, 0)) -> list[int]:
    m = [int.from_bytes(block[i * 8 : (i + 1) * 8], "big") for i in range(16)]
    v = h[:] + [
        salt[0] ^ _C[0], salt[1] ^ _C[1], salt[2] ^ _C[2], salt[3] ^ _C[3],
        (t & MASK64) ^ _C[4], (t & MASK64) ^ _C[5],
        (t >> 64) ^ _C[6], (t >> 64) ^ _C[7],
    ]
    for rnd in range(16):
        s = _SIGMA[rnd % 10]
        for g, (ia, ib, ic, id_) in enumerate(_IDX):
            a, b, c, d = v[ia], v[ib], v[ic], v[id_]
            a = (a + b + (m[s[2 * g]] ^ _C[s[2 * g + 1]])) & MASK64
            d = _rotr(d ^ a, 32)
            c = (c + d) & MASK64
            b = _rotr(b ^ c, 25)
            a = (a + b + (m[s[2 * g + 1]] ^ _C[s[2 * g]])) & MASK64
            d = _rotr(d ^ a, 16)
            c = (c + d) & MASK64
            b = _rotr(b ^ c, 11)
            v[ia], v[ib], v[ic], v[id_] = a, b, c, d
    return [
        h[i] ^ salt[i % 4] ^ v[i] ^ v[i + 8] for i in range(8)
    ]


def blake512(data: bytes) -> bytes:
    """Digest of ``data`` (64 bytes)."""
    h = list(_IV)
    bit_len = len(data) * 8

    # Padding: a 1 bit (0x80), zeros to 112 bytes mod 128, a final 1 bit
    # OR'd into the last padding byte, then the 128-bit big-endian bit
    # length.  When the message length is 111 mod 128 both marker bits
    # share one byte (0x81).
    pad = bytearray(data)
    pad.append(0x80)
    while len(pad) % 128 != 112:
        pad.append(0)
    pad[-1] |= 0x01
    pad += bit_len.to_bytes(16, "big")
    assert len(pad) % 128 == 0

    for i in range(len(pad) // 128):
        # The counter t is the number of *message* (unpadded) bits hashed
        # through this block; a block containing no message bits uses 0.
        if i * 1024 >= bit_len:
            t = 0
        else:
            t = min((i + 1) * 1024, bit_len)
        h = _compress(h, bytes(pad[i * 128 : (i + 1) * 128]), t)

    return b"".join(x.to_bytes(8, "big") for x in h)
