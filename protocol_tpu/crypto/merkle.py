"""Poseidon Merkle tree (circuit/src/merkle_tree/native.rs).

Pairs of nodes are hashed as ``Poseidon(left, right, 0, 0, 0)``; missing
leaves are zero-filled to ``2**height``.
"""

from __future__ import annotations

from dataclasses import dataclass

from .poseidon import permute


def _hash_pair(left: int, right: int) -> int:
    return permute([left, right, 0, 0, 0])[0]


@dataclass
class MerkleTree:
    """Levels of the tree: ``levels[0]`` are the (padded) leaves,
    ``levels[height][0]`` the root."""

    levels: list[list[int]]
    height: int

    @property
    def root(self) -> int:
        return self.levels[self.height][0]

    @classmethod
    def build(cls, leaves: list[int], height: int) -> "MerkleTree":
        assert len(leaves) <= 2**height
        level = list(leaves) + [0] * (2**height - len(leaves))
        levels = [level]
        for _ in range(height):
            level = [
                _hash_pair(level[i], level[i + 1]) for i in range(0, len(level), 2)
            ]
            levels.append(level)
        return cls(levels=levels, height=height)


@dataclass
class Path:
    """Authentication path: per level the (left, right) sibling pair, with
    the root appended as the final row (merkle_tree/native.rs::Path)."""

    value: int
    pairs: list[tuple[int, int]]

    @classmethod
    def find(cls, tree: MerkleTree, value: int) -> "Path":
        index = tree.levels[0].index(value)
        pairs = []
        for level in range(tree.height):
            row = tree.levels[level]
            if index % 2 == 1:
                pairs.append((row[index - 1], row[index]))
            else:
                pairs.append((row[index], row[index + 1]))
            index //= 2
        pairs.append((tree.root, 0))
        return cls(value=value, pairs=pairs)

    def verify(self) -> bool:
        for i in range(len(self.pairs) - 1):
            parent = _hash_pair(*self.pairs[i])
            if parent not in self.pairs[i + 1]:
                return False
        return True
