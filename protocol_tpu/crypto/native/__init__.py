"""ctypes bindings for the C++ crypto runtime (native/).

Exposes batch Poseidon / pk-hash / EdDSA verification backed by
libprotocol_native.so; builds it on demand with ``make -C native`` when
a compiler is available.  ``available()`` gates use — every caller has a
pure-Python fallback, and parity tests assert bit-identical results.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

#: PROTOCOL_TPU_NATIVE_DIR points the loaders at an alternate build —
#: the sanitizer wall (tools/sanitize_native.py) runs the test suite
#: against ASAN/UBSAN/TSAN-instrumented variants without clobbering
#: the optimized libraries.
_NATIVE_DIR = (
    Path(os.environ["PROTOCOL_TPU_NATIVE_DIR"]).resolve()
    if os.environ.get("PROTOCOL_TPU_NATIVE_DIR")
    else Path(__file__).resolve().parents[3] / "native"
)
_LIB_PATH = _NATIVE_DIR / "libprotocol_native.so"
#: None = untried, False = load/build failed (negative cache so a
#: compiler-less host doesn't re-spawn make per call), else the CDLL.
_lib = None


def _load():
    global _lib
    if _lib is False:
        raise OSError("native library unavailable (previous build failed)")
    if _lib is not None:
        return _lib
    if not _LIB_PATH.exists():
        try:
            build()
        except Exception:
            _lib = False
            raise
    lib = ctypes.CDLL(str(_LIB_PATH))
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.poseidon5_permute_batch.argtypes = [u64p, u64p, ctypes.c_int64]
    lib.pk_hash_batch.argtypes = [u64p, u64p, u64p, ctypes.c_int64]
    lib.eddsa_verify_batch.argtypes = [u64p] * 6 + [u8p, ctypes.c_int64]
    lib.protocol_native_abi_version.restype = ctypes.c_int64
    assert lib.protocol_native_abi_version() == 1
    _lib = lib
    return lib


def build() -> None:
    subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True, capture_output=True)


def available() -> bool:
    global _lib
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError, AssertionError):
        _lib = False
        return False


from ...utils.limbs import from_limbs as _from_limbs  # noqa: E402
from ...utils.limbs import ptr as _ptr  # noqa: E402
from ...utils.limbs import to_limbs as _to_limbs  # noqa: E402


def poseidon_permute_batch(inputs: list[list[int]]) -> list[list[int]]:
    """Batch width-5 permutations; bit-identical to
    crypto.poseidon.permute."""
    lib = _load()
    n = len(inputs)
    flat = _to_limbs([x for row in inputs for x in row])
    out = np.empty((n * 5, 4), dtype=np.uint64)
    lib.poseidon5_permute_batch(_ptr(flat), _ptr(out), n)
    values = _from_limbs(out)
    return [values[i * 5 : (i + 1) * 5] for i in range(n)]


def pk_hash_batch(xs: list[int], ys: list[int]) -> list[int]:
    """Batch Poseidon(x, y, 0, 0, 0)[0]."""
    lib = _load()
    n = len(xs)
    xs_l, ys_l = _to_limbs(xs), _to_limbs(ys)
    out = np.empty((n, 4), dtype=np.uint64)
    lib.pk_hash_batch(_ptr(xs_l), _ptr(ys_l), _ptr(out), n)
    return _from_limbs(out)


def eddsa_verify_batch(
    rx: list[int], ry: list[int], s: list[int], pkx: list[int], pky: list[int], msg: list[int]
) -> np.ndarray:
    """Batch signature verification; returns a bool array."""
    lib = _load()
    n = len(rx)
    arrs = [_to_limbs(v) for v in (rx, ry, s, pkx, pky, msg)]
    ok = np.zeros(n, dtype=np.uint8)
    lib.eddsa_verify_batch(
        *(_ptr(a) for a in arrs), ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n
    )
    return ok.astype(bool)
