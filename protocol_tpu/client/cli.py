"""Client CLI (client/src/main.rs:27-216).

Subcommands: show, compile-contracts, deploy-contracts, attest, update,
verify.  Run: ``python -m protocol_tpu.client.cli <subcommand>``.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from ..node.bootstrap import read_bootstrap_csv
from ..utils.codec import b58decode
from .client import ClientConfig, EigenTrustClient

DEFAULT_DATA_DIR = Path(__file__).resolve().parents[2] / "data"

#: Validated `update` fields (client/src/main.rs:43-62).
UPDATE_FIELDS = ("as_address", "mnemonic", "node_url", "score", "sk")


def load_context(data_dir: Path, *, require_identity: bool = False):
    config = ClientConfig.load(data_dir / "client-config.json")
    nodes = read_bootstrap_csv(data_dir / "bootstrap-nodes.csv")
    # Commands that sign need the configured identity to be a bootstrap
    # identity (client/src/main.rs:70-71); config-repair commands must
    # stay usable with a bad key, or `update sk` could never fix it.
    if require_identity and not any(
        (n.sk0, n.sk1) == tuple(config.secret_key) for n in nodes
    ):
        raise SystemExit("configured secret key is not in bootstrap-nodes.csv")
    return config, nodes


def cmd_show(config: ClientConfig, _nodes) -> None:
    print(config.to_json())


def cmd_attest(config: ClientConfig, nodes) -> None:
    client = EigenTrustClient(config, nodes)
    event = client.attest()
    dest = config.event_fixture or config.as_address
    print(f"attestation submitted ({len(event.val)} bytes) -> {dest}")


def cmd_verify(config: ClientConfig, nodes) -> None:
    client = EigenTrustClient(config, nodes)
    proof_raw = client.fetch_proof()
    if client.verify(proof_raw):
        print("Successful verification!")
    else:
        raise SystemExit("verification failed")


def cmd_compile_contracts(_config, _nodes) -> None:
    """Compile contracts/ with solc when available
    (client/src/utils.rs:118-158)."""
    import shutil
    import subprocess

    solc = shutil.which("solc")
    contracts = Path(__file__).resolve().parents[2] / "contracts"
    if solc is None:
        raise SystemExit(
            "solc not found; install solc or use pre-compiled artifacts in data/"
        )
    out = contracts / "build"
    out.mkdir(exist_ok=True)
    subprocess.run(
        [solc, "--bin", "--abi", "--overwrite", "-o", str(out)]
        + [str(p) for p in contracts.glob("*.sol")],
        check=True,
    )
    print(f"Finished compiling! -> {out}")


def cmd_deploy_contracts(config: ClientConfig, _nodes, data_dir: Path) -> None:
    """Deploy AttestationStation, the raw PLONK verifier (from a
    provided bytecode artifact), and the wrapper pointing at it
    (client/src/main.rs:79-100)."""
    from .client import ClientError, _web3, web3_transact

    build = Path(__file__).resolve().parents[2] / "contracts" / "build"
    try:
        w3 = _web3(config.ethereum_node_url)
    except ClientError as e:
        raise SystemExit(str(e))

    def deploy(name: str, bytecode_hex: str) -> str:
        try:
            receipt = web3_transact(
                w3, {"from": w3.eth.accounts[0], "data": "0x" + bytecode_hex}
            )
        except ClientError:
            raise SystemExit(f"{name} deployment reverted")
        addr = receipt["contractAddress"]
        if len(w3.eth.get_code(addr)) == 0:
            raise SystemExit(f"{name} deployed no code")
        print(f"{name} deployed. Address: {addr}")
        return addr

    def load_bytecode(path: Path) -> str:
        """Accept solc hex-text output or raw binary creation bytecode
        (the generated-verifier artifact form)."""
        raw = path.read_bytes()
        try:
            text = raw.decode("ascii").strip().removeprefix("0x")
            bytes.fromhex(text)
            return text
        except (UnicodeDecodeError, ValueError):
            return raw.hex()

    as_bin = build / "AttestationStation.bin"
    if not as_bin.exists():
        raise SystemExit(f"{as_bin} missing; run compile-contracts first")
    deploy("AttestationStation", load_bytecode(as_bin))

    # The raw verifier is an external artifact (generated PLONK verifier
    # creation bytecode): data/et_verifier.bin if present.
    verifier_bin = data_dir / "et_verifier.bin"
    if not verifier_bin.exists():
        print(
            f"no raw verifier artifact at {verifier_bin}; skipping verifier + wrapper deploy"
        )
        return
    verifier_addr = deploy("EtVerifier", load_bytecode(verifier_bin))

    wrapper_bin = build / "EtVerifierWrapper.bin"
    if not wrapper_bin.exists():
        raise SystemExit(f"{wrapper_bin} missing; run compile-contracts first")
    # Constructor takes (address verifier_): append the ABI-encoded arg.
    ctor_arg = bytes.fromhex(verifier_addr.removeprefix("0x")).rjust(32, b"\x00")
    deploy("EtVerifierWrapper", load_bytecode(wrapper_bin) + ctor_arg.hex())


def cmd_update(config: ClientConfig, nodes, field: str | None, value: str | None, data_dir: Path) -> None:
    """Validated config update (client/src/main.rs:125-216)."""
    if field is None:
        raise SystemExit("Please provide a field to update.")
    if value is None:
        raise SystemExit('Please provide the update data, e.g. update score "Alice 100"')
    if field not in UPDATE_FIELDS:
        raise SystemExit(f"Invalid config field. Available: {', '.join(UPDATE_FIELDS)}")

    if field == "as_address":
        addr = value.lower().removeprefix("0x")
        if len(addr) != 40 or any(c not in "0123456789abcdef" for c in addr):
            raise SystemExit("Failed to parse address.")
        config.as_address = value
    elif field == "mnemonic":
        if len(value.split()) not in (12, 15, 18, 21, 24):
            raise SystemExit("Failed to parse mnemonic.")
        config.mnemonic = value
    elif field == "node_url":
        if not value.startswith(("http://", "https://")):
            raise SystemExit("Failed to parse node url.")
        config.ethereum_node_url = value
    elif field == "score":
        parts = value.split(" ")
        if len(parts) != 2:
            raise SystemExit('Invalid input format. Expected: "Alice 100"')
        name, score = parts
        # u128 semantics: non-negative integers only (a negative value
        # would wrap to a near-modulus field element at attest time).
        try:
            score_val = int(score)
            if score_val < 0 or score_val >= 1 << 128:
                raise ValueError
        except ValueError:
            raise SystemExit("Failed to parse score.")
        names = [n.name for n in nodes]
        if name not in names:
            raise SystemExit(f"Invalid neighbour name: {name!r}, available: {names}")
        config.ops[names.index(name)] = score_val
    elif field == "sk":
        sk_parts = value.split(",")
        if len(sk_parts) != 2:
            raise SystemExit(
                "Invalid secret key passed, expected 2 bs58 values separated by commas"
            )
        try:
            for part in sk_parts:
                if len(b58decode(part)) > 32:
                    raise ValueError
        except ValueError:
            raise SystemExit("Failed to decode secret key. Expecting bs58 encoded values.")
        # Saving a non-bootstrap key would brick attest/verify; reject
        # here while the config is still writable.
        if not any((n.sk0, n.sk1) == (sk_parts[0], sk_parts[1]) for n in nodes):
            raise SystemExit("secret key is not one of the bootstrap identities")
        config.secret_key = (sk_parts[0], sk_parts[1])

    config.save(data_dir / "client-config.json")
    print("Client configuration updated.")


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="protocol-tpu-client", description="EigenTrust client wallet")
    parser.add_argument("--data-dir", default=str(DEFAULT_DATA_DIR))
    sub = parser.add_subparsers(dest="mode", required=True)
    sub.add_parser("show")
    sub.add_parser("compile-contracts")
    sub.add_parser("deploy-contracts")
    sub.add_parser("attest")
    sub.add_parser("verify")
    update = sub.add_parser("update")
    update.add_argument("field", nargs="?")
    update.add_argument("value", nargs="?")
    args = parser.parse_args(argv)

    data_dir = Path(args.data_dir)
    config, nodes = load_context(
        data_dir, require_identity=args.mode in ("attest", "verify")
    )

    if args.mode == "show":
        cmd_show(config, nodes)
    elif args.mode == "attest":
        cmd_attest(config, nodes)
    elif args.mode == "verify":
        cmd_verify(config, nodes)
    elif args.mode == "compile-contracts":
        cmd_compile_contracts(config, nodes)
    elif args.mode == "deploy-contracts":
        cmd_deploy_contracts(config, nodes, data_dir)
    elif args.mode == "update":
        cmd_update(config, nodes, args.field, args.value, data_dir)


if __name__ == "__main__":
    main(sys.argv[1:])
