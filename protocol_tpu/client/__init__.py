"""Client wallet: build/sign/submit attestations, fetch and verify proofs.

Rebuild of the reference ``client`` crate (client/src): a CLI with
show / compile-contracts / deploy-contracts / attest / update / verify
subcommands and an EigenTrustClient that signs the configured score
vector and submits it to the AttestationStation.
"""

from .client import ClientConfig, EigenTrustClient  # noqa: F401
