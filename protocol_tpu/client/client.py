"""EigenTrustClient and its configuration (client/src/lib.rs:31-150).

Chain submission is transport-pluggable: a ``Transport`` either sends a
real transaction (web3, when installed) or appends the encoded event to
a fixture log (the zero-dependency path used in tests and air-gapped
runs — the node ingests either identically).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field as dc_field
from pathlib import Path

from ..crypto import calculate_message_hash, field
from ..crypto.eddsa import SecretKey, sign
from ..node.attestation import Attestation, AttestationData
from ..node.bootstrap import BootstrapNode, keyset_from_raw
from ..node.ethereum import AttestationCreatedEvent
from ..zk.proof import ProofRaw


class ClientError(Exception):
    pass


#: EtVerifierWrapper.NUM_PUB_INS (contracts/EtVerifierWrapper.sol).
ET_WRAPPER_NUM_PUB_INS = 5


def _web3(node_url: str):
    """Shared web3 construction (raises ClientError when absent)."""
    try:
        from web3 import Web3  # type: ignore
    except ImportError as e:
        raise ClientError("web3 is not installed; chain mode unavailable") from e
    return Web3(Web3.HTTPProvider(node_url))


def web3_transact(w3, tx: dict):
    """Send a transaction and wait for its receipt, raising ClientError
    on revert — the one transact/wait/status path used by attest,
    verify, and deploy."""
    receipt = w3.eth.wait_for_transaction_receipt(w3.eth.send_transaction(tx))
    if receipt["status"] != 1:
        raise ClientError("transaction reverted")
    return receipt


class Web3Chain:  # pragma: no cover - web3 not in image
    """Chain backend over web3.py with an unlocked dev account (e.g.
    Anvil): the live counterpart of DevChainBackend."""

    def __init__(self, node_url: str):
        self._w3 = _web3(node_url)

    def transact(self, to: str, calldata: bytes) -> bool:
        w3 = self._w3
        tx = {
            "from": w3.eth.accounts[0],
            "to": w3.to_checksum_address(to),
            "data": "0x" + calldata.hex(),
        }
        try:
            receipt = web3_transact(w3, tx)
        except ClientError:
            return False  # reverted
        except Exception as e:  # gas-estimation revert surfaces pre-send
            if "revert" in str(e).lower() or type(e).__name__ == "ContractLogicError":
                return False
            raise
        del receipt  # web3_transact already raised on status != 1
        return True


class DevChainBackend:
    """Chain backend over the in-process dev chain (evm/devchain.py) —
    the Anvil analog the chain-integration tests drive."""

    #: The unlocked "account 0" all transactions originate from.
    SENDER = 0xDE5_0000_0000_0000_0000_0000_0000_0000_0CA11

    def __init__(self, chain):
        self._chain = chain

    def transact(self, to: str, calldata: bytes) -> bool:
        r = self._chain.transact(int(to, 16), calldata, sender=self.SENDER)
        return r.success


@dataclass
class ClientConfig:
    """client-config.json shape (client/src/lib.rs:31-40)."""

    ops: list[int]
    secret_key: tuple[str, str]
    as_address: str
    et_verifier_wrapper_address: str
    mnemonic: str
    ethereum_node_url: str
    server_url: str
    event_fixture: str | None = None
    #: Path to the generated EVM verifier artifact (data/et_verifier.bin
    #: analog); enables local contract-level verification.
    et_verifier_bin: str | None = None

    @classmethod
    def from_json(cls, text: str) -> "ClientConfig":
        obj = json.loads(text)
        return cls(
            ops=[int(x) for x in obj["ops"]],
            secret_key=(obj["secret_key"][0], obj["secret_key"][1]),
            as_address=obj["as_address"],
            et_verifier_wrapper_address=obj["et_verifier_wrapper_address"],
            mnemonic=obj["mnemonic"],
            ethereum_node_url=obj["ethereum_node_url"],
            server_url=obj["server_url"],
            event_fixture=obj.get("event_fixture"),
            et_verifier_bin=obj.get("et_verifier_bin"),
        )

    def to_json(self) -> str:
        out = {
            "ops": self.ops,
            "secret_key": list(self.secret_key),
            "as_address": self.as_address,
            "et_verifier_wrapper_address": self.et_verifier_wrapper_address,
            "mnemonic": self.mnemonic,
            "ethereum_node_url": self.ethereum_node_url,
            "server_url": self.server_url,
        }
        if self.event_fixture:
            out["event_fixture"] = self.event_fixture
        if self.et_verifier_bin:
            out["et_verifier_bin"] = self.et_verifier_bin
        return json.dumps(out, indent=4)

    @classmethod
    def load(cls, path: str | Path) -> "ClientConfig":
        return cls.from_json(Path(path).read_text())

    def save(self, path: str | Path) -> None:
        Path(path).write_text(self.to_json() + "\n")


@dataclass
class EigenTrustClient:
    config: ClientConfig
    user_secrets: list[BootstrapNode] = dc_field(default_factory=list)
    #: Chain transaction backend; defaults to web3 over
    #: ethereum_node_url, tests inject a DevChainBackend.
    chain: object | None = None

    def _chain_backend(self):
        if self.chain is None:
            self.chain = Web3Chain(self.config.ethereum_node_url)
        return self.chain

    def _identity(self) -> SecretKey:
        return SecretKey.from_bs58(*self.config.secret_key)

    def _build(self) -> tuple[Attestation, int]:
        """Sign the configured score vector over the bootstrap set
        (client/src/lib.rs:54-97); returns the attestation and the
        group pks_hash (the AttestationStation key)."""
        pairs = [(n.sk0, n.sk1) for n in self.user_secrets]
        _, user_publics = keyset_from_raw(pairs)

        sk = self._identity()
        pk = sk.public()
        ops = [field.from_u128(x) for x in self.config.ops]
        pks_hash, message_hashes = calculate_message_hash(user_publics, [ops])
        sig = sign(sk, pk, message_hashes[0])
        return Attestation(sig=sig, pk=pk, neighbours=user_publics, scores=ops), pks_hash

    def build_attestation(self) -> Attestation:
        return self._build()[0]

    def attest(self) -> AttestationCreatedEvent:
        """Build, sign, and submit the attestation
        (client/src/lib.rs:54-120).  Returns the event as submitted."""
        att, pks_hash = self._build()
        payload = AttestationData.from_attestation(att).to_bytes()

        event = AttestationCreatedEvent(
            creator="0x" + "00" * 20,
            about="0x" + "00" * 20,
            key=field.to_le_bytes(pks_hash),
            val=payload,
        )
        if self.config.event_fixture:
            with open(self.config.event_fixture, "a") as f:
                f.write(event.to_json() + "\n")
            return event
        return self._attest_chain(event)

    def _attest_chain(self, event: AttestationCreatedEvent) -> AttestationCreatedEvent:
        """Submit AttestationStation.attest through the chain backend
        (client/src/lib.rs:103-119)."""
        from ..evm.devchain import encode_attest_calldata

        calldata = encode_attest_calldata([(event.about, event.key, event.val)])
        if not self._chain_backend().transact(self.config.as_address, calldata):
            raise ClientError("attest transaction reverted")
        return event

    def fetch_proof(self) -> ProofRaw:
        """GET {server_url}/score (client/src/main.rs:105-107)."""
        url = f"{self.config.server_url}/score"
        with urllib.request.urlopen(url, timeout=10) as resp:
            body = resp.read().decode()
        return ProofRaw.from_json(body)

    def use_chain(self) -> bool:
        """On-chain mode iff no fixture is configured and a wrapper
        address is set — NOT keyed on web3 importability, so the
        fixture/air-gapped path keeps working in web3-equipped
        environments."""
        has_wrapper = bool(
            self.config.et_verifier_wrapper_address.strip().removeprefix("0x").strip("0")
        )
        return self.config.event_fixture is None and has_wrapper

    def verify(self, proof_raw: ProofRaw) -> bool:
        """Verify the fetched proof: on-chain via the EtVerifierWrapper
        in chain mode (client/src/lib.rs:122-149); otherwise locally —
        through the in-process EVM when an et_verifier.bin artifact is
        available (the reference's contract-level verification,
        verifier/mod.rs:117-134), or with the commitment prover for
        commitment-backend nodes."""
        if self.use_chain():
            return self._verify_chain(proof_raw)
        proof = proof_raw.to_proof()
        # Dispatch on the explicit backend tag when the node sent one;
        # for untagged (reference-format) payloads fall back to shape:
        # commitment proofs are 32-byte digest + JSON payload.
        is_commitment = (
            proof_raw.backend == "commitment"
            if proof_raw.backend
            else proof.proof[32:33] == b"{"
        )
        if is_commitment:
            from ..zk.proof import PoseidonCommitmentProver

            return PoseidonCommitmentProver().verify(proof.pub_ins, proof.proof)
        from ..zk.evm_verifier import evm_verify

        ok, _gas = evm_verify(self._verifier_artifact(), proof.pub_ins, proof.proof)
        return ok

    def _verifier_artifact(self):
        """Load the EVM verifier artifact; a configured path that does
        not exist is a deployment error, not a silent fallback."""
        from ..zk.evm_verifier import GeneratedVerifier

        path = Path(self.config.et_verifier_bin or "data/et_verifier.bin")
        if not path.exists():
            raise ClientError(
                f"SNARK proof received but verifier artifact {path} is missing "
                "(generate it with tools/gen_et_verifier.py)"
            )
        return GeneratedVerifier.from_bytes(path.read_bytes())

    def _verify_chain(self, proof_raw: ProofRaw) -> bool:
        """Transact EtVerifierWrapper.verify(uint256[5], bytes) through
        the chain backend (client/src/lib.rs:122-149).  A reverting
        wrapper (bad proof) returns False rather than raising."""
        from ..crypto.keccak import selector

        n = len(proof_raw.pub_ins)
        if n != ET_WRAPPER_NUM_PUB_INS:
            raise ClientError(
                f"wrapper expects {ET_WRAPPER_NUM_PUB_INS} public inputs, got {n}"
            )
        pub_words = b"".join(
            int.from_bytes(x, "little").to_bytes(32, "big") for x in proof_raw.pub_ins
        )
        proof = proof_raw.proof
        # verify(uint256[N],bytes): N inline words, bytes offset, then
        # the bytes tail.
        calldata = (
            selector(f"verify(uint256[{n}],bytes)")
            + pub_words
            + ((n + 1) * 32).to_bytes(32, "big")
            + len(proof).to_bytes(32, "big")
            + proof
            + b"\x00" * ((-len(proof)) % 32)
        )
        return self._chain_backend().transact(
            self.config.et_verifier_wrapper_address, calldata
        )


def abi_encode_attest(about: str, key: bytes, val: bytes) -> bytes:
    """ABI-encode ``attest(AttestationData[])`` arguments for one entry
    — delegates to the canonical batch encoder (evm/devchain.py) so the
    layout has one definition."""
    from ..evm.devchain import encode_attest_batch

    return encode_attest_batch([(about, key, val)])
