"""Model layer: the flagship EigenTrust model and graph generators."""

from .eigentrust import EigenTrustModel  # noqa: F401
from .graphs import erdos_renyi, scale_free, sybil_stress  # noqa: F401
