"""Synthetic trust-graph generators for the BASELINE.md config ladder.

Config 2 (Erdős–Rényi 10k), config 4 (scale-free 1M peers / 50M edges)
and config 5 (10M peers with a 30% sybil collective) are generated here;
config 1 is the bootstrap CSV and config 3 an attestation-log snapshot
(see protocol_tpu.node).
"""

from __future__ import annotations

import numpy as np

from ..trust.graph import TrustGraph


def erdos_renyi(
    n: int, avg_degree: float = 8.0, *, n_pre_trusted: int = 16, seed: int = 0
) -> TrustGraph:
    """Uniform random directed graph with integer weights in [1, 100]."""
    rng = np.random.default_rng(seed)
    nnz = int(n * avg_degree)
    src = rng.integers(0, n, nnz, dtype=np.int32)
    dst = rng.integers(0, n, nnz, dtype=np.int32)
    w = rng.integers(1, 101, nnz).astype(np.float32)
    pre = np.zeros(n, bool)
    pre[rng.choice(n, min(n_pre_trusted, n), replace=False)] = True
    return TrustGraph(n, src, dst, w, pre)


def scale_free(
    n: int,
    nnz: int,
    *,
    exponent: float = 1.1,
    n_pre_trusted: int = 64,
    seed: int = 0,
    chunk: int = 1 << 22,
) -> TrustGraph:
    """Power-law attention graph: sources uniform, destinations Zipf-like
    (popularity ∝ rank^-exponent via inverse-CDF sampling on a permuted
    rank order).  This is the load-balance stress case for sharded SpMV —
    a few peers receive a large fraction of all edges.
    """
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n).astype(np.int32)

    srcs, dsts, ws = [], [], []
    remaining = nnz
    while remaining > 0:
        m = min(chunk, remaining)
        remaining -= m
        src = rng.integers(0, n, m, dtype=np.int32)
        # Inverse-CDF of a truncated Pareto over ranks [1, n].
        u = rng.random(m)
        if abs(exponent - 1.0) < 1e-9:
            ranks = np.exp(u * np.log(n))
        else:
            a = 1.0 - exponent
            ranks = (1.0 + u * (n**a - 1.0)) ** (1.0 / a)
        dst = perm[np.clip(ranks.astype(np.int64) - 1, 0, n - 1)]
        w = rng.integers(1, 101, m).astype(np.float32)
        srcs.append(src)
        dsts.append(dst)
        ws.append(w)

    pre = np.zeros(n, bool)
    pre[rng.choice(n, min(n_pre_trusted, n), replace=False)] = True
    return TrustGraph(
        n, np.concatenate(srcs), np.concatenate(dsts), np.concatenate(ws), pre
    )


def sybil_stress(
    n: int,
    nnz: int,
    *,
    sybil_fraction: float = 0.3,
    seed: int = 0,
    n_pre_trusted: int = 64,
) -> TrustGraph:
    """An honest scale-free core plus a sybil collective: the last
    ``sybil_fraction·n`` peers score only each other (a closed clique
    ring) and receive a few bridge edges from compromised honest peers.
    Used to measure how pre-trust damping bounds collective rank
    (BASELINE.md config 5)."""
    rng = np.random.default_rng(seed)
    n_sybil = int(n * sybil_fraction)
    n_honest = n - n_sybil
    honest_nnz = int(nnz * (1 - sybil_fraction))
    g = scale_free(n_honest, honest_nnz, seed=seed, n_pre_trusted=n_pre_trusted)

    sybil_nnz = nnz - honest_nnz
    s_src = n_honest + rng.integers(0, n_sybil, sybil_nnz, dtype=np.int32)
    # Ring + random intra-clique edges keep the collective strongly
    # connected so its self-reinforcement is maximal.
    s_dst = n_honest + (
        (s_src - n_honest + 1 + rng.integers(0, max(n_sybil // 8, 1), sybil_nnz)) % n_sybil
    ).astype(np.int32)
    s_w = np.full(sybil_nnz, 100.0, np.float32)

    # 0.1% of honest edges are bridges captured by the collective.
    n_bridge = max(honest_nnz // 1000, 1)
    b_src = rng.integers(0, n_honest, n_bridge, dtype=np.int32)
    b_dst = n_honest + rng.integers(0, n_sybil, n_bridge, dtype=np.int32)
    b_w = np.full(n_bridge, 100.0, np.float32)

    pre = np.zeros(n, bool)
    pre[: g.pre_trusted.shape[0]][g.pre_trusted] = True
    return TrustGraph(
        n,
        np.concatenate([g.src, s_src, b_src]),
        np.concatenate([g.dst, s_dst, b_dst]),
        np.concatenate([g.weight, s_w, b_w]),
        pre,
    )


def sybil_mass(result_scores: np.ndarray, n: int, sybil_fraction: float) -> float:
    """Fraction of total trust captured by the sybil block."""
    n_sybil = int(n * sybil_fraction)
    return float(result_scores[n - n_sybil :].sum() / result_scores.sum())
