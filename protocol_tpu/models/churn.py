"""Sender-centric churn generator (the bench.py epoch doctrine, shared).

The protocol's churn unit is a sender's out-row rewrite: a peer
re-attests, replacing its whole out-edge row (row normalization makes
the row the atomic delta unit).  The re-attesting cohort is
recency-biased — ids exponential toward the top of the id space,
mirroring production id assignment where manager peer ids are
first-seen order, so the churning cohort (recently joined / most
active users) is id-local and the plan delta's touched windows stay
far below the window count (the delta/rebuild crossover, PERF.md §11).

Extracted from ``bench.py::epochs_entry`` so the steady-state
benchmark, the partition property tests, and the pod dryrun all replay
the *identical* event stream shape — churn locality claims measured by
one tool are the claims the others verify.
"""

from __future__ import annotations

import numpy as np

from ..trust.graph import TrustGraph


def churn_cohort_dims(graph: TrustGraph, churn: float) -> tuple[int, int]:
    """``(cohort_size, deg)`` for a churn fraction: the cohort rewriting
    ``churn``·E edges at the graph's average out-degree."""
    avg_deg = max(graph.nnz / graph.n, 1.0)
    cohort_size = max(1, int(round(churn * graph.nnz / avg_deg)))
    deg = max(1, int(round(avg_deg)))
    return cohort_size, deg


def sender_centric_churn(
    rng: np.random.Generator,
    graph: TrustGraph,
    *,
    cohort_size: int,
    deg: int,
) -> tuple[np.ndarray, TrustGraph, tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """One epoch of sender-centric churn.

    Returns ``(rows, new_graph, (ns, nd, nw))``: the re-attesting row
    ids (sorted, unique), the churned graph, and the cohort's new
    out-edges as three flat arrays grouped by row — ``ns`` is
    ``np.repeat(rows, deg)``, so row ``rows[i]``'s fresh out-row is the
    slice ``[i*deg, (i+1)*deg)`` of ``nd``/``nw`` (the pod dryrun
    journals exactly these slices into per-host WAL shards).

    Draw order (exponential offsets, destinations, self-edge
    resamples, weights) is pinned: callers carrying one ``rng`` across
    epochs reproduce the historical bench.py stream bit-for-bit.
    """
    n_peers = graph.n
    offs = rng.exponential(
        scale=max(n_peers * 0.02, cohort_size), size=cohort_size
    ).astype(np.int64)
    rows = np.unique(n_peers - 1 - np.minimum(offs, n_peers - 1))
    keep = ~np.isin(graph.src, rows.astype(np.int32))
    ns = np.repeat(rows.astype(np.int32), deg)
    nd = rng.integers(0, n_peers, ns.shape[0]).astype(np.int32)
    while (bad := nd == ns).any():  # no self-edges
        nd[bad] = rng.integers(0, n_peers, int(bad.sum()))
    nw = rng.integers(1, 1000, ns.shape[0]).astype(np.float32)
    new_graph = TrustGraph(
        graph.n,
        np.concatenate([graph.src[keep], ns]),
        np.concatenate([graph.dst[keep], nd]),
        np.concatenate([graph.weight[keep], nw]),
        graph.pre_trusted,
    )
    return rows, new_graph, (ns, nd, nw)


__all__ = ["churn_cohort_dims", "sender_centric_churn"]
