"""The flagship model: EigenTrust global-trust convergence.

Bundles a TrustGraph with convergence hyper-parameters (damping α,
tolerance, iteration budget) and a backend choice — the "model" whose
"forward step" is one damped transpose-SpMV power iteration and whose
"training run" is convergence to the principal eigenvector.  This is
what `__graft_entry__` exposes and what bench.py times.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..trust.backend import ConvergenceResult, get_backend
from ..trust.graph import TrustGraph


@dataclass
class EigenTrustModel:
    graph: TrustGraph
    alpha: float = 0.1
    tol: float = 1e-6
    max_iter: int = 50
    backend: str = "tpu-sparse"
    backend_kwargs: dict = field(default_factory=dict)

    def converge(self, **overrides) -> ConvergenceResult:
        params = dict(alpha=self.alpha, tol=self.tol, max_iter=self.max_iter)
        params.update(overrides)
        return get_backend(self.backend, **self.backend_kwargs).converge(
            self.graph, **params
        )

    def top_k(self, result: ConvergenceResult, k: int = 10) -> list[tuple[int, float]]:
        idx = np.argsort(result.scores)[::-1][:k]
        return [(int(i), float(result.scores[i])) for i in idx]
