"""Pod scale-out: per-host partition plan build over a multi-process
``jax.distributed`` mesh (ROADMAP item 1, PERF.md §20).

``ShardedWindowPlan`` partitions ONE globally-built ``WindowPlan``
across one host's devices — at 500M edges that single host pays the
whole O(E) plan construction serially (the PERF.md §11 bottleneck) and
holds the whole edge set in host RAM.  The pod builder inverts the
order of operations:

1. every process computes the identical peer→host partition
   (``parallel.partition.HostPartition`` — rendezvous hash, no
   coordination round) and keeps only the edges whose **source** peer
   it owns;
2. each host builds a ``WindowPlan`` over its local edges only — N
   hosts build N partial plans concurrently, so the pod's plan-build
   critical path is ``max_h(build(E_h))`` ≈ ``build(E)/N`` instead of
   ``build(E)``;
3. each host cuts its local plan across its local devices with the
   same BLOCK_ROWS-aligned row cut as the single-host path
   (``sharded._partition_plan_arrays``), padded to pod-wide maxima so
   every global shard has the same shape;
4. the per-host shards are assembled into global arrays with
   ``jax.make_array_from_process_local_data`` — no edge bytes ever
   cross a host boundary — and the pod runs the *identical*
   ``converge_sharded`` windowed runner: per-shard fused pipeline,
   one boundary-completing f32[N] psum per step, now spanning all
   ``n_hosts * local_devices`` shards.

Churn stays partition-local by construction: the protocol's churn unit
is a sender's out-row rewrite and a source peer's edges live on exactly
one host, so a host whose peers saw no churn revalidates its local
fingerprint and reuses its plan verbatim — steady-state churn never
forces a cross-host rebuild (``ops.gather_window.partition_delta``).

The ``dangling`` vector is the one globally-coupled input (a peer with
no out-edges anywhere): here every process derives it from its copy of
the full normalized graph; a production pod exchanges per-host
out-degree bitmaps through the pod manifest (``node.pod``) — an O(N)
exchange, never O(E).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.gather_window import (
    PLAN_VERSION,
    WindowPlan,
    build_window_plan,
    graph_fingerprint,
    partition_delta,
    try_plan_delta,
)
from ..trust.graph import TrustGraph
from .mesh import SHARD_AXIS, default_mesh
from .partition import HostPartition
from .sharded import BLOCK_ROWS, ROW, _partition_plan_arrays


@dataclass(frozen=True)
class PodContext:
    """One process's view of the pod: its host id, the pod size, the
    global mesh, and the shared peer→host partition.  All processes
    construct identical contexts from their own ``jax.distributed``
    state — there is no leader election and no membership exchange."""

    host_id: int
    n_hosts: int
    mesh: Mesh
    partition: HostPartition

    @classmethod
    def current(cls, *, seed: int = 0) -> "PodContext":
        """The pod as the running jax runtime sees it: one host per
        process, the flat shard mesh over all global devices
        (``jax.devices()`` orders devices by process, so each host's
        local devices form a contiguous block of shards)."""
        return cls(
            host_id=jax.process_index(),
            n_hosts=jax.process_count(),
            mesh=default_mesh(),
            partition=HostPartition(jax.process_count(), seed=seed),
        )

    @property
    def local_shards(self) -> int:
        return self.mesh.shape[SHARD_AXIS] // self.n_hosts


def _pod_max(ctx: PodContext, values: np.ndarray) -> np.ndarray:
    """Elementwise max of an int64 vector across all pod hosts — the
    dimension-agreement exchange (every global shard must compile to
    one shape).  Single-host pods short-circuit; multi-host pods ride
    ``multihost_utils.process_allgather`` (gloo all-gather, host
    scale × 8 bytes on the wire)."""
    values = np.asarray(values, np.int64)
    if ctx.n_hosts == 1:
        return values
    from jax.experimental import multihost_utils

    gathered = np.asarray(multihost_utils.process_allgather(values))
    return gathered.max(axis=0)


@dataclass
class PodWindowPlan:
    """Pod-partitioned fused-pipeline layout.

    Field-compatible with ``ShardedWindowPlan`` (``converge_sharded``
    dispatches any non-CSR problem to the windowed runner, and the
    runner cache keys on ``(mesh, n, rows_per_shard, table_entries,
    interpret)`` — identical code paths, multi-process mesh), plus the
    pod bookkeeping the dryrun and the node durability plane read:
    which host this is, the peer→host owner map, and how long the
    *local* plan build took (the pod's plan-build critical path is the
    max of these, PERF.md §20).
    """

    mesh: Mesh
    n: int
    rows_per_shard: int
    table_entries: int
    s_max: int
    interpret: bool
    wid: jax.Array
    local: jax.Array
    weight: jax.Array
    seg_end: jax.Array
    seg_first: jax.Array
    seg_perm: jax.Array
    dst_ptr: jax.Array
    p: jax.Array
    dangling: jax.Array
    plan: WindowPlan  # this HOST's local-partition plan
    plan_outcome: str  # reuse | delta | rebuild
    host_id: int
    n_hosts: int
    owner: np.ndarray  # (n,) int32 peer→host owner map
    local_edges: int  # edges this host's partition holds
    build_seconds: float  # local plan construction wall-clock
    #: Pre-collective barrier probe (ISSUE 19): when this host entered
    #: the dimension-agreement allgather (caller's monotonic clock) and
    #: how long both agreement rounds blocked — the pod trace stitcher
    #: clock-aligns the arrival stamps into the barrier-arrival spread
    #: (eigentrust_pod_barrier_wait_seconds).  0.0 without a clock.
    barrier_enter_monotonic: float = 0.0
    barrier_wait_seconds: float = 0.0
    #: One monotonic↔wall clock-sync sample pair taken at build entry
    #: (both clocks read back-to-back) — one of the samples the
    #: stitcher's per-host offset estimation feeds on.  0.0 without
    #: injected clocks.
    sync_monotonic: float = 0.0
    sync_unix: float = 0.0

    @classmethod
    def build(
        cls,
        graph: TrustGraph,
        pod: PodContext,
        *,
        plan: WindowPlan | None = None,
        delta_rows: np.ndarray | None = None,
        interpret: bool | None = None,
        clock: Callable[[], float] | None = None,
        wall: Callable[[], float] | None = None,
    ) -> "PodWindowPlan":
        """Partition the graph by source-peer owner, resolve this
        host's local plan (reuse / delta / rebuild against the local
        fingerprint — churn owned by other hosts leaves it untouched),
        cut it across the local devices, and assemble the global
        sharded arrays.  ``plan`` is this host's cached *local* plan
        (checkpoint-shard restored); ``delta_rows`` is the global
        churn hint, clipped to owned rows here.  ``clock`` is the
        caller's monotonic clock for the ``build_seconds`` field and
        the barrier probe; ``wall`` is the caller's wall clock, read
        back-to-back with ``clock`` at entry for the pod stitcher's
        clock-sync sample — instrumentation wraps kernel trees from
        the outside (graftlint clock-in-kernel-tree doctrine), so
        without them the probe fields stay 0.0."""
        sync_monotonic = clock() if clock is not None else 0.0
        sync_unix = wall() if wall is not None else 0.0
        g = graph.drop_self_edges()
        w, dangling = g.row_normalized()
        owner = pod.partition.assign_ids(g.n)
        owned_rows, lsrc, ldst, lw = partition_delta(
            delta_rows, g.src, g.dst, w, owner, pod.host_id
        )
        fp = graph_fingerprint(g.n, lsrc, ldst, lw)
        outcome = "reuse"
        build_seconds = 0.0
        valid = plan is not None and getattr(plan, "version", 0) == PLAN_VERSION
        if not (valid and plan.fingerprint == fp):
            t_build = clock() if clock is not None else 0.0
            delta = None
            if valid and owned_rows is not None and owned_rows.size:
                delta = try_plan_delta(
                    plan, lsrc, ldst, lw, n=g.n, rows=owned_rows, fingerprint=fp
                )
            if delta is not None:
                plan, outcome = delta, "delta"
            else:
                plan = build_window_plan(lsrc, ldst, lw, n=g.n)
                outcome = "rebuild"
            if clock is not None:
                build_seconds = clock() - t_build

        # Pod-wide dimension agreement: every global shard must carry
        # the same (rows_per_shard, s_max) so the compiled runner sees
        # one shape.  Two cheap rounds: row capacity first (the segment
        # cut depends on it), then per-shard run capacity.
        L = pod.local_shards
        min_rps = -(-plan.n_rows // (L * BLOCK_ROWS)) * BLOCK_ROWS
        # Barrier probe: the first allgather below is the pod's
        # pre-collective barrier — the first host to arrive blocks
        # until the last one does, so the clock-aligned enter stamps
        # across hosts ARE the arrival spread, and the elapsed time
        # over both agreement rounds is this host's wait.
        barrier_enter = clock() if clock is not None else 0.0
        rows_per_shard = int(_pod_max(pod, np.asarray([min_rps]))[0])
        live_end = plan.seg_end[: plan.n_segments]
        counts = np.bincount(
            (live_end // ROW) // rows_per_shard, minlength=L
        )
        min_smax = -(-max(int(counts.max()), 1) // 1024) * 1024
        s_max = int(_pod_max(pod, np.asarray([min_smax]))[0])
        barrier_wait = clock() - barrier_enter if clock is not None else 0.0

        parts = _partition_plan_arrays(
            plan, L, rows_per_shard=rows_per_shard, s_max=s_max
        )

        n_shards = pod.mesh.shape[SHARD_AXIS]
        edge = NamedSharding(pod.mesh, P(SHARD_AXIS))
        edge2d = NamedSharding(pod.mesh, P(SHARD_AXIS, None))
        repl = NamedSharding(pod.mesh, P())

        def shard1d(a: np.ndarray) -> jax.Array:
            return jax.make_array_from_process_local_data(
                edge, np.ascontiguousarray(a), (n_shards * (a.shape[0] // L),)
            )

        def shard2d(a: np.ndarray) -> jax.Array:
            return jax.make_array_from_process_local_data(
                edge2d,
                np.ascontiguousarray(a),
                (n_shards * (a.shape[0] // L), a.shape[1]),
            )

        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return cls(
            mesh=pod.mesh,
            n=plan.n,
            rows_per_shard=rows_per_shard,
            table_entries=plan.table_entries,
            s_max=s_max,
            interpret=bool(interpret),
            wid=shard1d(parts["wid"]),
            local=shard2d(parts["local"]),
            weight=shard2d(parts["weight"]),
            seg_end=shard1d(parts["seg_end"].reshape(-1)),
            seg_first=shard1d(parts["seg_first"].reshape(-1)),
            seg_perm=shard1d(parts["seg_perm"].reshape(-1)),
            dst_ptr=shard2d(parts["dst_ptr"]),
            p=jax.device_put(graph.pre_trust_vector(), repl),
            dangling=jax.device_put(dangling.astype(np.float32), repl),
            plan=plan,
            plan_outcome=outcome,
            host_id=pod.host_id,
            n_hosts=pod.n_hosts,
            owner=owner,
            local_edges=int(lsrc.shape[0]),
            build_seconds=build_seconds,
            barrier_enter_monotonic=barrier_enter,
            barrier_wait_seconds=barrier_wait,
            sync_monotonic=sync_monotonic,
            sync_unix=sync_unix,
        )

    def t0(self) -> jax.Array:
        """Fresh device copy of the pre-trust vector (the runner
        donates its seed; same contract as ``ShardedWindowPlan``)."""
        return jnp.copy(self.p)


__all__ = ["PodContext", "PodWindowPlan"]
