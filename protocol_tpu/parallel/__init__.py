"""Device-mesh parallelism: sharded SpMV over ICI collectives.

The rebuild's answer to the reference's "distributed backend" (which is
an Ethereum event log + HTTP, SURVEY.md §2.5): trust convergence scales
across chips with `shard_map` over a 1-D `jax.sharding.Mesh`, edges
sharded, the score vector replicated, and `lax.psum` reducing partial
transpose-SpMV products over ICI.
"""

from .mesh import default_mesh, shard_count  # noqa: F401
from .sharded import (  # noqa: F401
    SHARDED_KERNELS,
    ShardedTrustProblem,
    ShardedWindowPlan,
    converge_sharded,
)
