"""Edge-sharded trust convergence over a device mesh.

Layout (the scaling-book recipe: pick a mesh, annotate shardings, let
XLA place collectives):

- **edges** (src, w): sharded on the leading axis across the mesh —
  each device owns a contiguous dst-sorted slice, padded with w=0 to
  equal length.  50M edges over 8 chips = 6.25M edges/chip, streamed
  sequentially from HBM.
- **row_ptr**: per-shard CSR-by-dst pointers into the local edge slice
  (``(n_shards, n+1)`` sharded on axis 0), precomputed on the host by
  clipping the global pointer array to each shard's range.  This lets
  every shard run the same scatter-free ``rowsum_sorted`` cumsum kernel
  as the single-device ``tpu-csr`` path (PERF.md §1 measured the old
  per-shard ``segment_sum`` 2.4× slower end-to-end at full scale).
- **t, p, dangling**: replicated (a 1M-peer f32 vector is 4 MB — cheap
  to replicate, expensive to re-gather per step).
- per step, inside ``shard_map``: each device computes its partial
  ``Cᵀt`` by gather-multiply-``rowsum_sorted`` over its edge slice, then
  a single ``lax.psum`` over ICI produces the full product — boundary
  destinations whose edge runs straddle a shard cut are partially
  summed on each side and completed by that same psum; damping and L1
  renorm are elementwise on the replicated result so every device stays
  consistent without further communication.

This is the distributed analog of the reference's single-threaded
5×5×10 loop (circuit/src/circuit.rs:434-454) at 10^6 peers.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..trust.graph import TrustGraph
from .mesh import SHARD_AXIS

try:  # jax >= 0.6 exposes shard_map at the top level...
    _shard_map = jax.shard_map
except AttributeError:  # ...older images still carry the experimental path
    from jax.experimental.shard_map import shard_map as _shard_map


@dataclass
class ShardedTrustProblem:
    """Device-resident, mesh-sharded graph data ready for iteration."""

    mesh: Mesh
    n: int
    src: jax.Array  # (E_pad,) int32, sharded
    w: jax.Array  # (E_pad,) f32, sharded, row-normalized
    row_ptr: jax.Array  # (n_shards, n+1) int32, sharded on axis 0
    p: jax.Array  # (n,) f32, replicated
    dangling: jax.Array  # (n,) f32, replicated

    @classmethod
    def build(cls, graph: TrustGraph, mesh: Mesh) -> "ShardedTrustProblem":
        """Host-side assembly: drop self-edges, row-normalize, sort by
        dst, pad to the mesh size, derive per-shard row pointers, and
        place arrays with explicit shardings."""
        g = graph.drop_self_edges()
        w, dangling = g.row_normalized()
        g = TrustGraph(g.n, g.src, g.dst, w, g.pre_trusted)
        g = g.sorted_by_dst()

        n_shards = mesh.shape[SHARD_AXIS]
        pad = (-g.nnz) % n_shards
        src = np.concatenate([g.src, np.zeros(pad, np.int32)])
        wpad = np.concatenate([g.weight, np.zeros(pad, np.float32)])
        # Per-shard CSR-by-dst pointers: clip the global pointer array
        # to each shard's slice.  A destination whose edges straddle a
        # shard cut gets a partial range on both sides — each shard
        # contributes its partial row sum and the psum completes it.
        # Pad-tail slots (w=0) sit beyond every clipped pointer and are
        # never differenced into any row.
        gptr = g.row_ptr_by_dst().astype(np.int64)
        m = (g.nnz + pad) // n_shards
        starts = np.arange(n_shards, dtype=np.int64)[:, None] * m
        row_ptr = (np.clip(gptr[None, :], starts, starts + m) - starts).astype(np.int32)

        edge_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        repl = NamedSharding(mesh, P())
        return cls(
            mesh=mesh,
            n=g.n,
            src=jax.device_put(src, edge_sharding),
            w=jax.device_put(wpad, edge_sharding),
            row_ptr=jax.device_put(row_ptr, NamedSharding(mesh, P(SHARD_AXIS, None))),
            p=jax.device_put(graph.pre_trust_vector(), repl),
            dangling=jax.device_put(dangling.astype(np.float32), repl),
        )

    def t0(self) -> jax.Array:
        """Initial score vector: the pre-trust distribution (the scaled
        analog of everyone starting at INITIAL_SCORE)."""
        return self.p


# Compiled runners keyed by (mesh, n): jax's jit cache is keyed on
# function identity, so rebuilding the closures per call would recompile
# the whole while_loop every epoch.
_RUN_CACHE: dict = {}


def _get_runner(mesh: Mesh, n: int):
    key = (mesh, n)
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS),
            P(SHARD_AXIS),
            P(SHARD_AXIS, None),
            P(),
            P(),
            P(),
            P(),
        ),
        out_specs=P(),
    )
    def step(src, w, row_ptr, t, p, dangling, alpha):
        from ..ops.sparse import rowsum_sorted

        # The same scatter-free cumsum rowsum as the single-device CSR
        # fast path (ops.sparse.power_step_csr); boundary rows split
        # across shards are completed by the psum below.
        contrib = w * t[src]
        partial_ct = rowsum_sorted(contrib, row_ptr[0])
        ct = lax.psum(partial_ct, SHARD_AXIS)
        dangling_mass = jnp.sum(t * dangling)
        t_new = (1.0 - alpha) * (ct + dangling_mass * p) + alpha * p
        return t_new / jnp.sum(t_new)

    @partial(jax.jit, static_argnames=("max_iter", "tol"))
    def run(src, w, row_ptr, t0, p, dangling, alpha, *, max_iter, tol):
        from ..ops.sparse import run_power_iteration

        return run_power_iteration(
            lambda t: step(src, w, row_ptr, t, p, dangling, alpha),
            t0,
            tol=tol,
            max_iter=max_iter,
        )

    _RUN_CACHE[key] = run
    return run


def converge_sharded(
    problem: ShardedTrustProblem,
    *,
    alpha: float = 0.1,
    tol: float = 1e-6,
    max_iter: int = 50,
) -> tuple[jax.Array, int, float]:
    """Damped power iteration to an L1 fixed point on the mesh.

    Returns ``(t, iterations, final residual)``.  ``tol <= 0`` runs
    exactly ``max_iter`` fixed steps (benchmark mode).
    """
    run = _get_runner(problem.mesh, problem.n)
    t, it, resid = run(
        problem.src,
        problem.w,
        problem.row_ptr,
        problem.t0(),
        problem.p,
        problem.dangling,
        jnp.float32(alpha),
        max_iter=max_iter,
        tol=tol,
    )
    return t, int(it), float(resid)
