"""Edge-sharded trust convergence over a device mesh.

Layout (the scaling-book recipe: pick a mesh, annotate shardings, let
XLA place collectives):

- **edges** (src, w): sharded on the leading axis across the mesh —
  each device owns a contiguous dst-sorted slice, padded with w=0 to
  equal length.  50M edges over 8 chips = 6.25M edges/chip, streamed
  sequentially from HBM.
- **row_ptr**: per-shard CSR-by-dst pointers into the local edge slice
  (``(n_shards, n+1)`` sharded on axis 0), precomputed on the host by
  clipping the global pointer array to each shard's range.  This lets
  every shard run the same scatter-free ``rowsum_sorted`` cumsum kernel
  as the single-device ``tpu-csr`` path (PERF.md §1 measured the old
  per-shard ``segment_sum`` 2.4× slower end-to-end at full scale).
- **t, p, dangling**: replicated (a 1M-peer f32 vector is 4 MB — cheap
  to replicate, expensive to re-gather per step).
- per step, inside ``shard_map``: each device computes its partial
  ``Cᵀt`` by gather-multiply-``rowsum_sorted`` over its edge slice, then
  a single ``lax.psum`` over ICI produces the full product — boundary
  destinations whose edge runs straddle a shard cut are partially
  summed on each side and completed by that same psum; damping and L1
  renorm are elementwise on the replicated result so every device stays
  consistent without further communication.

Two kernels share this recipe (``SHARDED_KERNELS``, selectable as
``tpu-sharded:<kernel>`` in ManagerConfig/ProtocolConfig):

- ``tpu-csr`` — ``ShardedTrustProblem``: the gather-only CSR/cumsum
  SpMV above, with the O(E) random ``t[src]`` gather per shard.
- ``tpu-windowed`` — ``ShardedWindowPlan``: the fused fixed-slot
  pipeline (PERF.md §7-8) taken multi-chip.  The one-time
  ``WindowPlan`` is partitioned by *window rows*: each shard owns a
  contiguous, BLOCK_ROWS-aligned slice of the plan's vreg-rows (runs
  never span rows, so the bucket-order segment table splits at the
  same cuts), rebased to shard-local slots and padded to the mesh
  maximum; each shard runs the identical ``windowed_ct`` step —
  windowed Pallas gather from the replicated score table, row-local
  prefix sum, single-pass boundary bridge — over its slice, and the
  per-shard partial Cᵀt vectors are completed by the same ``lax.psum``
  (dst rows whose runs land on several shards are partially summed on
  each side, exactly like CSR rows straddling a shard cut).

This is the distributed analog of the reference's single-threaded
5×5×10 loop (circuit/src/circuit.rs:434-454) at 10^6 peers.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..analysis.budget import (
    CollectiveBudget,
    CommBudget,
    GatherBudget,
    KernelBudget,
    MemBudget,
    declare,
    declare_comm,
    declare_mem,
)
from ..ops.gather_window import (
    BLOCK_ROWS,
    PLAN_VERSION,
    ROW,
    WindowPlan,
    _counting_sort,
    build_window_plan,
    graph_fingerprint,
    try_plan_delta,
    windowed_ct,
)
from ..trust.graph import TrustGraph
from .mesh import SHARD_AXIS

try:  # jax >= 0.6 exposes shard_map at the top level...
    _shard_map = jax.shard_map
except AttributeError:  # ...older images still carry the experimental path
    from jax.experimental.shard_map import shard_map as _shard_map


@dataclass
class ShardedTrustProblem:
    """Device-resident, mesh-sharded graph data ready for iteration."""

    mesh: Mesh
    n: int
    src: jax.Array  # (E_pad,) int32, sharded
    w: jax.Array  # (E_pad,) f32, sharded, row-normalized
    row_ptr: jax.Array  # (n_shards, n+1) int32, sharded on axis 0
    p: jax.Array  # (n,) f32, replicated
    dangling: jax.Array  # (n,) f32, replicated

    @classmethod
    def build(cls, graph: TrustGraph, mesh: Mesh) -> "ShardedTrustProblem":
        """Host-side assembly: drop self-edges, row-normalize, sort by
        dst, pad to the mesh size, derive per-shard row pointers, and
        place arrays with explicit shardings."""
        g = graph.drop_self_edges()
        w, dangling = g.row_normalized()
        g = TrustGraph(g.n, g.src, g.dst, w, g.pre_trusted)
        g = g.sorted_by_dst()

        n_shards = mesh.shape[SHARD_AXIS]
        pad = (-g.nnz) % n_shards
        src = np.concatenate([g.src, np.zeros(pad, np.int32)])
        wpad = np.concatenate([g.weight, np.zeros(pad, np.float32)])
        # Per-shard CSR-by-dst pointers: clip the global pointer array
        # to each shard's slice.  A destination whose edges straddle a
        # shard cut gets a partial range on both sides — each shard
        # contributes its partial row sum and the psum completes it.
        # Pad-tail slots (w=0) sit beyond every clipped pointer and are
        # never differenced into any row.
        gptr = g.row_ptr_by_dst().astype(np.int64)
        m = (g.nnz + pad) // n_shards
        starts = np.arange(n_shards, dtype=np.int64)[:, None] * m
        row_ptr = (np.clip(gptr[None, :], starts, starts + m) - starts).astype(np.int32)

        edge_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        repl = NamedSharding(mesh, P())
        return cls(
            mesh=mesh,
            n=g.n,
            src=jax.device_put(src, edge_sharding),
            w=jax.device_put(wpad, edge_sharding),
            row_ptr=jax.device_put(row_ptr, NamedSharding(mesh, P(SHARD_AXIS, None))),
            p=jax.device_put(graph.pre_trust_vector(), repl),
            dangling=jax.device_put(dangling.astype(np.float32), repl),
        )

    def t0(self) -> jax.Array:
        """Initial score vector: the pre-trust distribution (the scaled
        analog of everyone starting at INITIAL_SCORE).  A fresh device
        copy, not ``p`` itself: the runners donate ``t0`` (PERF.md §15)
        and ``p`` must survive the iteration it seeds."""
        return jnp.copy(self.p)


# Compiled runners keyed by (mesh, n) for the CSR kernel and by
# (mesh, n, rows_per_shard, table_entries, interpret) for the windowed
# kernel: jax's jit cache is keyed on function identity, so rebuilding
# the closures per call would recompile the whole while_loop every
# epoch.
_RUN_CACHE: dict = {}


def _get_runner(mesh: Mesh, n: int):
    key = (mesh, n)
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS),
            P(SHARD_AXIS),
            P(SHARD_AXIS, None),
            P(),
            P(),
            P(),
            P(),
        ),
        out_specs=P(),
    )
    def step(src, w, row_ptr, t, p, dangling, alpha):
        from ..ops.sparse import rowsum_sorted

        # The same scatter-free cumsum rowsum as the single-device CSR
        # fast path (ops.sparse.power_step_csr); boundary rows split
        # across shards are completed by the psum below.
        contrib = w * t[src]
        partial_ct = rowsum_sorted(contrib, row_ptr[0])
        ct = lax.psum(partial_ct, SHARD_AXIS)
        dangling_mass = jnp.sum(t * dangling)
        t_new = (1.0 - alpha) * (ct + dangling_mass * p) + alpha * p
        return t_new / jnp.sum(t_new)

    @partial(
        jax.jit,
        static_argnames=("max_iter", "tol", "record_residuals"),
        donate_argnames=("t0",),
    )
    def run(
        src, w, row_ptr, t0, p, dangling, alpha,
        *, max_iter, tol, record_residuals=False,
    ):
        from ..ops.sparse import run_power_iteration

        # t0 is donated (same contract as converge_csr): the iteration
        # consumes the seed in place — callers stage a fresh replicated
        # buffer per converge (problem.t0() copies, converge_sharded
        # device_puts warm seeds).  Pass 8 pins the aliasing in the
        # compiled module, not just here.
        return run_power_iteration(
            lambda t: step(src, w, row_ptr, t, p, dangling, alpha),
            t0,
            tol=tol,
            max_iter=max_iter,
            record_residuals=record_residuals,
        )

    _RUN_CACHE[key] = run
    return run


def _partition_plan_arrays(
    plan: WindowPlan,
    n_shards: int,
    *,
    rows_per_shard: int | None = None,
    s_max: int | None = None,
) -> dict:
    """Host-side partition of one ``WindowPlan`` into ``n_shards``
    contiguous, BLOCK_ROWS-aligned vreg-row slices — the shared cut
    used by the single-host ``ShardedWindowPlan`` and, per host, by the
    pod builder (``parallel.pod``).  ``rows_per_shard``/``s_max`` may
    be forced upward by the caller: a pod must pad every host's
    partition to the pod-wide maxima so the global shard shapes (and
    the compiled runner) agree across processes.  Returns the numpy
    shard tables plus the resolved dimensions."""
    min_rps = -(-plan.n_rows // (n_shards * BLOCK_ROWS)) * BLOCK_ROWS
    if rows_per_shard is None:
        rows_per_shard = min_rps
    elif rows_per_shard < min_rps or rows_per_shard % BLOCK_ROWS:
        raise ValueError(
            f"rows_per_shard={rows_per_shard} cannot hold {plan.n_rows} "
            f"plan rows over {n_shards} shards (need >= {min_rps}, "
            f"BLOCK_ROWS-aligned)"
        )
    total_rows = n_shards * rows_per_shard
    wid = np.zeros(total_rows, np.int32)
    wid[: plan.n_rows] = plan.wid
    local = np.zeros((total_rows * 8, 128), np.int32)
    local[: plan.n_rows * 8] = plan.local
    weight = np.zeros((total_rows * 8, 128), np.float32)
    weight[: plan.n_rows * 8] = plan.weight

    # Segment table: bucket order is slot order, so the row cuts give
    # contiguous per-shard slices.  Only the plan's live runs partition
    # — its device-capacity pads are regenerated here as per-shard
    # padding.
    live_end = plan.seg_end[: plan.n_segments]
    live_first = plan.seg_first[: plan.n_segments]
    shard_of = (live_end // ROW) // rows_per_shard
    counts = np.bincount(shard_of, minlength=n_shards)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    # Quantized per-shard run capacity: small per-epoch deltas keep the
    # sharded array shapes (and the compiled runner) stable.
    min_smax = -(-max(int(counts.max()), 1) // 1024) * 1024
    if s_max is None:
        s_max = min_smax
    elif s_max < min_smax:
        raise ValueError(
            f"s_max={s_max} below this plan's per-shard run count "
            f"{min_smax}"
        )
    # Bucket-order run destinations: stored on the plan since layout v3
    # (the delta-update bookkeeping keeps it current).
    seg_dst = plan.seg_dst
    seg_end = np.zeros((n_shards, s_max), np.int32)
    seg_first = np.ones((n_shards, s_max), bool)
    seg_perm = np.zeros((n_shards, s_max), np.int32)
    dst_ptr = np.zeros((n_shards, plan.n + 1), np.int32)
    for k in range(n_shards):
        beg, end = int(offsets[k]), int(offsets[k + 1])
        sk = end - beg
        seg_end[k, :sk] = live_end[beg:end] - k * rows_per_shard * ROW
        seg_first[k, :sk] = live_first[beg:end]
        # Pad runs stay a valid permutation so XLA's gather cost is
        # uniform; they land beyond dst_ptr[k, n] and are dropped.
        seg_perm[k, sk:] = np.arange(sk, s_max, dtype=np.int32)
        if sk:
            sperm, dst_counts, _ = _counting_sort(seg_dst[beg:end], plan.n)
            seg_perm[k, :sk] = sperm
            np.cumsum(dst_counts, out=dst_ptr[k, 1:])
    return {
        "rows_per_shard": rows_per_shard,
        "s_max": int(s_max),
        "wid": wid,
        "local": local,
        "weight": weight,
        "seg_end": seg_end,
        "seg_first": seg_first,
        "seg_perm": seg_perm,
        "dst_ptr": dst_ptr,
    }


@dataclass
class ShardedWindowPlan:
    """Mesh-partitioned fused-pipeline layout: the ``tpu-windowed``
    kernel of ``converge_sharded``.

    Host construction slices the single-graph ``WindowPlan`` at
    BLOCK_ROWS-aligned vreg-row boundaries — the same cuts split the
    bucket-order segment table, because runs never span rows — then
    rebases each shard's run ends to shard-local slots, re-sorts each
    shard's runs by dst (per-shard ``seg_perm``/``dst_ptr``), and pads
    rows and runs to the mesh maximum.  Pad runs point at slot 0 with
    the row-leading flag set, and the per-shard ``dst_ptr`` never
    reaches them, so their garbage partials are computed but never
    reduced into any destination.  The underlying ``plan`` is kept so
    the node's checkpoint store persists one format for both the
    single-device and sharded windowed backends.
    """

    mesh: Mesh
    n: int
    rows_per_shard: int  # BLOCK_ROWS-aligned vreg-rows per shard
    table_entries: int  # replicated score-table padding (WINDOW multiple)
    s_max: int  # padded per-shard run count
    interpret: bool  # Pallas interpret mode (CPU meshes)
    wid: jax.Array  # (n_shards*rows_per_shard,) int32, sharded
    local: jax.Array  # (n_shards*rows_per_shard*8, 128) int32, sharded
    weight: jax.Array  # (n_shards*rows_per_shard*8, 128) f32, sharded
    seg_end: jax.Array  # (n_shards*s_max,) int32 shard-local, sharded
    seg_first: jax.Array  # (n_shards*s_max,) bool, sharded
    seg_perm: jax.Array  # (n_shards*s_max,) int32 per-shard dst order, sharded
    dst_ptr: jax.Array  # (n_shards, n+1) int32, sharded on axis 0
    p: jax.Array  # (n,) f32, replicated
    dangling: jax.Array  # (n,) f32, replicated
    plan: WindowPlan  # the single-graph plan this partitions
    plan_outcome: str  # how the plan was resolved: reuse | delta | rebuild

    @classmethod
    def build(
        cls,
        graph: TrustGraph,
        mesh: Mesh,
        *,
        plan: WindowPlan | None = None,
        delta_rows: np.ndarray | None = None,
        interpret: bool | None = None,
    ) -> "ShardedWindowPlan":
        """Normalize the graph, reuse (or build) its ``WindowPlan``, and
        partition it across the mesh.  A candidate ``plan`` (e.g.
        checkpoint-restored) is revalidated by fingerprint and layout
        version, exactly like the single-device backend; on a
        fingerprint miss with a ``delta_rows`` churn hint the plan is
        delta-updated (``WindowPlan.apply_delta``) instead of rebuilt,
        and the partition is recut from the updated plan — the
        ``plan_outcome`` field reports which path ran."""
        g = graph.drop_self_edges()
        w, dangling = g.row_normalized()
        fp = graph_fingerprint(g.n, g.src, g.dst, w)
        outcome = "reuse"
        valid = plan is not None and getattr(plan, "version", 0) == PLAN_VERSION
        if not (valid and plan.fingerprint == fp):
            delta = None
            if valid and delta_rows is not None:
                delta = try_plan_delta(
                    plan, g.src, g.dst, w, n=g.n, rows=delta_rows, fingerprint=fp
                )
            if delta is not None:
                plan, outcome = delta, "delta"
            else:
                plan = build_window_plan(g.src, g.dst, w, n=g.n)
                outcome = "rebuild"

        n_shards = mesh.shape[SHARD_AXIS]
        parts = _partition_plan_arrays(plan, n_shards)
        rows_per_shard, s_max = parts["rows_per_shard"], parts["s_max"]

        edge = NamedSharding(mesh, P(SHARD_AXIS))
        edge2d = NamedSharding(mesh, P(SHARD_AXIS, None))
        repl = NamedSharding(mesh, P())
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return cls(
            mesh=mesh,
            n=plan.n,
            rows_per_shard=rows_per_shard,
            table_entries=plan.table_entries,
            s_max=s_max,
            interpret=bool(interpret),
            wid=jax.device_put(parts["wid"], edge),
            local=jax.device_put(parts["local"], edge2d),
            weight=jax.device_put(parts["weight"], edge2d),
            seg_end=jax.device_put(parts["seg_end"].reshape(-1), edge),
            seg_first=jax.device_put(parts["seg_first"].reshape(-1), edge),
            seg_perm=jax.device_put(parts["seg_perm"].reshape(-1), edge),
            dst_ptr=jax.device_put(parts["dst_ptr"], edge2d),
            p=jax.device_put(graph.pre_trust_vector(), repl),
            dangling=jax.device_put(dangling.astype(np.float32), repl),
            plan=plan,
            plan_outcome=outcome,
        )

    def t0(self) -> jax.Array:
        """Fresh device copy of the pre-trust vector (the runner
        donates its seed; see ``ShardedTrustProblem.t0``)."""
        return jnp.copy(self.p)


def _get_windowed_runner(
    mesh: Mesh, n: int, rows_per_shard: int, table_entries: int, interpret: bool
):
    key = (mesh, n, rows_per_shard, table_entries, interpret)
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS),
            P(SHARD_AXIS, None),
            P(SHARD_AXIS, None),
            P(SHARD_AXIS),
            P(SHARD_AXIS),
            P(SHARD_AXIS),
            P(SHARD_AXIS, None),
            P(),
            P(),
            P(),
            P(),
        ),
        out_specs=P(),
        # pallas_call has no shard_map replication rule; the step's
        # output replication is guaranteed by the trailing psum +
        # elementwise damping, so the static check is safely skipped.
        check_rep=False,
    )
    def step(
        wid, local, weight, seg_end, seg_first, seg_perm, dst_ptr,
        t, p, dangling, alpha,
    ):
        # The identical fused step as the single-device tpu-windowed
        # backend, over this shard's rows/runs; dst rows whose runs
        # live on several shards are completed by the psum below.
        partial_ct = windowed_ct(
            wid,
            local,
            weight,
            seg_end,
            seg_first,
            seg_perm,
            dst_ptr[0],
            t,
            n_rows=rows_per_shard,
            table_entries=table_entries,
            interpret=interpret,
        )
        ct = lax.psum(partial_ct, SHARD_AXIS)
        dangling_mass = jnp.sum(t * dangling)
        t_new = (1.0 - alpha) * (ct + dangling_mass * p) + alpha * p
        return t_new / jnp.sum(t_new)

    @partial(
        jax.jit,
        static_argnames=("max_iter", "tol", "record_residuals"),
        donate_argnames=("t0",),
    )
    def run(
        wid, local, weight, seg_end, seg_first, seg_perm, dst_ptr,
        t0, p, dangling, alpha, *, max_iter, tol, record_residuals=False,
    ):
        from ..ops.sparse import run_power_iteration

        return run_power_iteration(
            lambda t: step(
                wid, local, weight, seg_end, seg_first, seg_perm, dst_ptr,
                t, p, dangling, alpha,
            ),
            t0,
            tol=tol,
            max_iter=max_iter,
            record_residuals=record_residuals,
        )

    _RUN_CACHE[key] = run
    return run


#: Kernels selectable under ``converge_sharded`` (ManagerConfig /
#: ProtocolConfig spell them ``tpu-sharded:<kernel>``): each value
#: builds the mesh-resident problem whose type the dispatch below
#: recognizes.
SHARDED_KERNELS: dict[str, type] = {
    "tpu-csr": ShardedTrustProblem,
    "tpu-windowed": ShardedWindowPlan,
}


def converge_sharded(
    problem: ShardedTrustProblem | ShardedWindowPlan,
    *,
    alpha: float = 0.1,
    tol: float = 1e-6,
    max_iter: int = 50,
    record_residuals: bool = False,
    t0: np.ndarray | None = None,
) -> tuple:
    """Damped power iteration to an L1 fixed point on the mesh, with
    the kernel selected by the problem type (``SHARDED_KERNELS``):
    ``ShardedTrustProblem`` runs the CSR/cumsum SpMV,
    ``ShardedWindowPlan`` the fused windowed pipeline.  ``t0`` warm
    starts the iteration (mesh-replicated like ``p``); None starts
    from the pre-trust vector — the cold path.

    Returns ``(t, iterations, final residual)`` — plus the device-side
    per-iteration residual history as a fourth element when
    ``record_residuals`` is set (the history rides the replicated
    while-loop carry *outside* shard_map, so the per-shard step and its
    single psum are untouched).  ``tol <= 0`` runs exactly ``max_iter``
    fixed steps (benchmark mode).

    ``alpha`` is staged explicitly with the mesh-replicated sharding:
    a bare ``jnp.float32`` scalar (numpy's scalar type) would pay an
    implicit host→device transfer every call, and a single-device
    array an implicit device→device re-replication — both rejected by
    the transfer guard the equivalence tests run under.
    """
    alpha_dev = jax.device_put(
        np.float32(alpha), NamedSharding(problem.mesh, P())
    )
    t0_dev = (
        problem.t0()
        if t0 is None
        else jax.device_put(
            np.asarray(t0, np.float32), NamedSharding(problem.mesh, P())
        )
    )
    # Dispatch on the CSR type and treat everything else as windowed-
    # shaped: the pod builder (``parallel.pod.PodWindowPlan``) carries
    # the same field layout as ShardedWindowPlan over a multi-process
    # mesh and rides the identical runner/cache.
    if not isinstance(problem, ShardedTrustProblem):
        run = _get_windowed_runner(
            problem.mesh,
            problem.n,
            problem.rows_per_shard,
            problem.table_entries,
            problem.interpret,
        )
        out = run(
            problem.wid,
            problem.local,
            problem.weight,
            problem.seg_end,
            problem.seg_first,
            problem.seg_perm,
            problem.dst_ptr,
            t0_dev,
            problem.p,
            problem.dangling,
            alpha_dev,
            max_iter=max_iter,
            tol=tol,
            record_residuals=record_residuals,
        )
    else:
        run = _get_runner(problem.mesh, problem.n)
        out = run(
            problem.src,
            problem.w,
            problem.row_ptr,
            t0_dev,
            problem.p,
            problem.dangling,
            alpha_dev,
            max_iter=max_iter,
            tol=tol,
            record_residuals=record_residuals,
        )
    t, it, resid = out[:3]
    if record_residuals:
        return t, int(it), float(resid), out[3]
    return t, int(it), float(resid)


# ---------------------------------------------------------------------------
# Pinned kernel invariants (PERF.md §9) — checked per step by
# `python -m protocol_tpu.analysis` under the 8-device CPU mesh.
# ---------------------------------------------------------------------------

#: Per-shard CSR step under shard_map: the single-device CSR budget per
#: shard, plus EXACTLY ONE psum completing boundary destinations — and
#: that psum must sit under shard_map (outside, there is no mesh axis).
declare(
    KernelBudget(
        backend="tpu-sharded:tpu-csr",
        max_random_gathers=5,
        max_scatters=0,
        psum_count=1,
        gather_budgets=(GatherBudget(dim="edges", max_total=1, max_random=1),),
        donated_args=("t0",),
        notes="per-shard rowsum_sorted + one boundary-completing psum",
    )
)

#: Per-shard fused windowed step under shard_map: the single-device
#: windowed budget per shard (streaming boundary read, one random
#: n_segments pass, Pallas kernel present) plus the same single psum.
declare(
    KernelBudget(
        backend="tpu-sharded:tpu-windowed",
        max_random_gathers=5,
        max_scatters=0,
        psum_count=1,
        require_primitives=("pallas_call",),
        gather_budgets=(
            GatherBudget(
                dim="n_segments", max_total=2, max_random=1, boundary_sorted=True
            ),
        ),
        donated_args=("t0",),
        notes="sharded fused pipeline: per-shard windowed_ct + one psum",
    )
)


# ---------------------------------------------------------------------------
# Pinned communication budgets (PERF.md §15) — checked against the
# COMPILED (SPMD-partitioned) module by graftlint pass 8 at two problem
# scales, and at runtime by the 2-process tools/comm_probe.py smoke.
# ---------------------------------------------------------------------------

#: CSR shards: exactly one f32[N] all-reduce per iteration (the
#: boundary-completing psum — destinations whose edge runs straddle a
#: shard cut ride the same reduce, so there is no separate boundary
#: collective).  Byte allowance 8·N = the 4·N wire volume with 2x
#: slack; NO term may scale with E — the whole point of the recipe is
#: that 50M edges cross zero wires.  t0's donation must survive into
#: the executable's input_output_alias table.
declare_comm(
    CommBudget(
        backend="tpu-sharded:tpu-csr",
        collectives=(CollectiveBudget(kind="all-reduce", max_count=1),),
        bytes_n=8.0,
        bytes_const=1024.0,
        max_host_round_trips=0,
        require_full_replica_group=True,
        donated_args=("t0",),
        notes="one boundary-completing f32[N] psum per step; comm is "
        "O(N), never O(E)",
    )
)

#: Windowed shards: identical wire shape — the per-shard fused pipeline
#: reduces its partial Cᵀt into the same single f32[N] all-reduce;
#: boundary segments are folded per shard before the reduce, so the
#: segment table contributes no collective bytes (bytes_segments stays
#: 0 as a declaration that boundary traffic rides the psum).
declare_comm(
    CommBudget(
        backend="tpu-sharded:tpu-windowed",
        collectives=(CollectiveBudget(kind="all-reduce", max_count=1),),
        bytes_n=8.0,
        bytes_segments=0.0,
        bytes_const=1024.0,
        max_host_round_trips=0,
        require_full_replica_group=True,
        donated_args=("t0",),
        notes="sharded fused pipeline: per-shard windowed_ct partials "
        "completed by one f32[N] psum; comm is O(N), never O(E)",
    )
)


# ---------------------------------------------------------------------------
# Pinned memory budgets (PERF.md §19) — checked against the compiled
# module's buffer assignment by graftlint pass 12 at two problem
# scales (E x4 vs N x2), and at runtime by tools/mem_probe.py.  All
# numbers are PER DEVICE: the resident edge term is E/n_shards by
# construction, so a replicated edge operand busts the budget — the
# regression that turns into 2 GB/host at ROADMAP item 1's 500M-edge
# target.  The transient allowances were measured to track N across
# the 4x edge growth (the per-shard working set follows the replicated
# score vectors, never the edge slice), and the committed slack is
# below a 4 B/edge temporary at either scale (pinned by test).
# ---------------------------------------------------------------------------

declare_mem(
    MemBudget(
        backend="tpu-sharded:tpu-csr",
        resident_edge_bytes=8.0,  # per-shard src + w slice
        resident_n=16.0,  # replicated t0/p/dangling + clipped row_ptr
        resident_const=4096.0,
        transient_n=23.0,  # psum buffers + while carries: tracks N, not E
        transient_const=217792.0,  # runtime-fixed thunk arena, fitted
        donated_args=("t0",),
        notes="per-shard CSR slice resident; transient tracked N "
        "exactly across 4x edge growth (233→257 KB as N doubled)",
    )
)

declare_mem(
    MemBudget(
        backend="tpu-sharded:tpu-windowed",
        resident_rows=8196.0,  # per-shard local/weight/wid row tables
        resident_segments=9.0,  # per-shard seg_end/first/perm
        resident_n=16.0,  # replicated vectors + clipped dst_ptr
        resident_const=4096.0,
        transient_rows=98304.0,  # interpret-mode kernel scratch (12x8KB/row)
        transient_n=36.0,
        transient_segments=9.0,
        transient_const=1118208.0,  # runtime-fixed thunk arena, fitted
        donated_args=("t0",),
        notes="per-shard plan slice resident; interpret scratch rides "
        "rows_per_shard, transient follows N across 4x edge growth",
    )
)
