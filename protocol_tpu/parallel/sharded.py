"""Edge-sharded trust convergence over a device mesh.

Layout (the scaling-book recipe: pick a mesh, annotate shardings, let
XLA place collectives):

- **edges** (src, dst, w): sharded on the leading axis across the mesh —
  each device owns a contiguous dst-sorted slice, padded with w=0 to
  equal length.  50M edges over 8 chips = 6.25M edges/chip, streamed
  sequentially from HBM.
- **t, p, dangling**: replicated (a 1M-peer f32 vector is 4 MB — cheap
  to replicate, expensive to re-gather per step).
- per step, inside ``shard_map``: each device computes its partial
  ``Cᵀt`` by gather-multiply-``segment_sum`` over its edge slice, then a
  single ``lax.psum`` over ICI produces the full product; damping and L1
  renorm are elementwise on the replicated result so every device stays
  consistent without further communication.

This is the distributed analog of the reference's single-threaded
5×5×10 loop (circuit/src/circuit.rs:434-454) at 10^6 peers.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..trust.graph import TrustGraph
from .mesh import SHARD_AXIS


@dataclass
class ShardedTrustProblem:
    """Device-resident, mesh-sharded graph data ready for iteration."""

    mesh: Mesh
    n: int
    src: jax.Array  # (E_pad,) int32, sharded
    dst: jax.Array  # (E_pad,) int32, sharded
    w: jax.Array  # (E_pad,) f32, sharded, row-normalized
    p: jax.Array  # (n,) f32, replicated
    dangling: jax.Array  # (n,) f32, replicated

    @classmethod
    def build(cls, graph: TrustGraph, mesh: Mesh) -> "ShardedTrustProblem":
        """Host-side assembly: drop self-edges, row-normalize, sort by
        dst, pad to the mesh size, and place arrays with explicit
        shardings."""
        g = graph.drop_self_edges()
        w, dangling = g.row_normalized()
        g = TrustGraph(g.n, g.src, g.dst, w, g.pre_trusted)
        g = g.sorted_by_dst()

        n_shards = mesh.shape[SHARD_AXIS]
        pad = (-g.nnz) % n_shards
        src = np.concatenate([g.src, np.zeros(pad, np.int32)])
        dst = np.concatenate([g.dst, np.zeros(pad, np.int32)])
        wpad = np.concatenate([g.weight, np.zeros(pad, np.float32)])

        edge_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        repl = NamedSharding(mesh, P())
        return cls(
            mesh=mesh,
            n=g.n,
            src=jax.device_put(src, edge_sharding),
            dst=jax.device_put(dst, edge_sharding),
            w=jax.device_put(wpad, edge_sharding),
            p=jax.device_put(graph.pre_trust_vector(), repl),
            dangling=jax.device_put(dangling.astype(np.float32), repl),
        )

    def t0(self) -> jax.Array:
        """Initial score vector: the pre-trust distribution (the scaled
        analog of everyone starting at INITIAL_SCORE)."""
        return self.p


# Compiled runners keyed by (mesh, n): jax's jit cache is keyed on
# function identity, so rebuilding the closures per call would recompile
# the whole while_loop every epoch.
_RUN_CACHE: dict = {}


def _get_runner(mesh: Mesh, n: int):
    key = (mesh, n)
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(), P(), P(), P()),
        out_specs=P(),
    )
    def step(src, dst, w, t, p, dangling, alpha):
        contrib = w * t[src]
        partial_ct = jax.ops.segment_sum(
            contrib, dst, num_segments=n, indices_are_sorted=True
        )
        ct = lax.psum(partial_ct, SHARD_AXIS)
        dangling_mass = jnp.sum(t * dangling)
        t_new = (1.0 - alpha) * (ct + dangling_mass * p) + alpha * p
        return t_new / jnp.sum(t_new)

    @partial(jax.jit, static_argnames=("max_iter", "tol"))
    def run(src, dst, w, t0, p, dangling, alpha, *, max_iter, tol):
        from ..ops.sparse import run_power_iteration

        return run_power_iteration(
            lambda t: step(src, dst, w, t, p, dangling, alpha),
            t0,
            tol=tol,
            max_iter=max_iter,
        )

    _RUN_CACHE[key] = run
    return run


def converge_sharded(
    problem: ShardedTrustProblem,
    *,
    alpha: float = 0.1,
    tol: float = 1e-6,
    max_iter: int = 50,
) -> tuple[jax.Array, int, float]:
    """Damped power iteration to an L1 fixed point on the mesh.

    Returns ``(t, iterations, final residual)``.  ``tol <= 0`` runs
    exactly ``max_iter`` fixed steps (benchmark mode).
    """
    run = _get_runner(problem.mesh, problem.n)
    t, it, resid = run(
        problem.src,
        problem.dst,
        problem.w,
        problem.t0(),
        problem.p,
        problem.dangling,
        jnp.float32(alpha),
        max_iter=max_iter,
        tol=tol,
    )
    return t, int(it), float(resid)
