"""Mesh construction helpers."""

from __future__ import annotations

import jax
from jax.sharding import Mesh

#: Axis name used by all sharded trust kernels.
SHARD_AXIS = "shard"


def default_mesh(n_devices: int | None = None, axis: str = SHARD_AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (all by default).

    Trust convergence is a single giant SpMV, so a flat edge-parallel
    axis is the right layout: partial products travel over ICI via psum;
    there is no second axis to trade off against (no pipeline/tensor
    split as in NN workloads).
    """
    devices = jax.devices()
    if n_devices is not None:
        assert n_devices <= len(devices), (
            f"requested {n_devices} devices, have {len(devices)}"
        )
        devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices), (axis,))


def shard_count(mesh: Mesh, axis: str = SHARD_AXIS) -> int:
    return mesh.shape[axis]
