"""Deterministic peer→host partition for pod scale-out (ROADMAP item 1).

Every host in a pod must agree on which host owns which peer **without
a coordination round**: ownership decides which edges a host folds into
its local window plan, which WAL shard an attestation is acknowledged
into, and which checkpoint shard carries a peer's row.  The assignment
is rendezvous (highest-random-weight) hashing over a vectorized
splitmix64 mix:

- **deterministic** — ``owner = argmax_h mix(key ^ salt_h)`` is a pure
  function of ``(key, n_hosts, seed)``, so every process computes the
  identical partition from its own copy of the peer set (property-
  tested across process boundaries in ``tests/test_partition.py``);
- **balanced** — splitmix64 is a 64-bit finalizer-grade mixer, so the
  per-host buckets concentrate around ``n/n_hosts`` (the tests pin a
  ±20% envelope at realistic sizes);
- **minimal remap under churn** — when a host joins, only the keys
  whose new-host score beats their incumbent move (≈ ``1/(n_hosts+1)``
  of them); when a host leaves, only *its* keys move.  Nothing else
  re-shuffles, so steady-state membership churn never invalidates the
  surviving hosts' window plans (the delta path stays partition-local).

Edges are owned by their **source** peer's host: row normalization is a
per-source operation, so a host that owns every out-edge of its peers
normalizes exactly like the single-host path; and the protocol's churn
unit is the sender-centric row rewrite (one attestation replaces one
out-edge), so a dirty row is dirty on exactly one host.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

_U64 = np.uint64
#: 64-bit mask for folding arbitrary-width Python ints (Poseidon field
#: elements are ~254 bits) into the mixer's domain.
MASK64 = (1 << 64) - 1


def mix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: the avalanche stage of the
    SplitMix64 generator (Steele et al.), applied elementwise to a
    uint64 array.  Unsigned numpy arithmetic wraps mod 2^64, which is
    exactly the reference semantics."""
    z = x.astype(_U64, copy=True)
    z += _U64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> _U64(27))) * _U64(0x94D049BB133111EB)
    return z ^ (z >> _U64(31))


def keys_from_hashes(hashes) -> np.ndarray:
    """Fold an iterable of Python-int peer hashes (arbitrary width —
    the manager keys peers by Poseidon field elements) into the
    partition's uint64 key domain."""
    return np.asarray([int(h) & MASK64 for h in hashes], dtype=_U64)


@dataclass(frozen=True)
class HostPartition:
    """Rendezvous-hash peer→host assignment for an ``n_hosts`` pod.

    ``seed`` namespaces the salt chain so test pods and production pods
    with the same membership count never collide by construction.
    """

    n_hosts: int
    seed: int = 0

    def __post_init__(self) -> None:
        if self.n_hosts < 1:
            raise ValueError(f"n_hosts must be >= 1, got {self.n_hosts}")

    def _salt(self, host: int) -> np.uint64:
        # Double-mix the (seed, host) pair so adjacent host ids land in
        # unrelated salt points — a raw ``seed + host`` salt would make
        # neighboring hosts' score streams correlated.
        base = np.asarray([(self.seed * 0x9E3779B9 + host + 1) & MASK64], _U64)
        return mix64(mix64(base))[0]

    def assign(self, keys: np.ndarray) -> np.ndarray:
        """Owner host id (int32) for each uint64 key: the host whose
        salted mix scores highest — the rendezvous winner.  Runs as a
        streaming argmax over hosts, so peak memory is two extra arrays
        of ``len(keys)`` regardless of pod size."""
        keys = np.ascontiguousarray(keys, dtype=_U64)
        if self.n_hosts == 1:
            return np.zeros(keys.shape[0], np.int32)
        best_score = mix64(keys ^ self._salt(0))
        best_host = np.zeros(keys.shape[0], np.int32)
        for h in range(1, self.n_hosts):
            score = mix64(keys ^ self._salt(h))
            wins = score > best_score
            best_score[wins] = score[wins]
            best_host[wins] = h
        return best_host

    def assign_ids(self, n: int) -> np.ndarray:
        """Owners for the dense integer id space ``0..n-1`` (the
        synthetic-graph path: row ids are the peer identity)."""
        return self.assign(np.arange(n, dtype=_U64))

    def owned_mask(self, keys: np.ndarray, host: int) -> np.ndarray:
        """Boolean mask of the keys this host owns."""
        return self.assign(keys) == np.int32(host)


def remap_fraction(before: np.ndarray, after: np.ndarray) -> float:
    """Fraction of keys whose owner changed between two assignments —
    the churn metric the minimal-remap property tests pin (HRW moves
    ≈ 1/n_hosts of the keys on a membership change; a modulo partition
    would move ≈ (n_hosts-1)/n_hosts of them)."""
    before = np.asarray(before)
    after = np.asarray(after)
    if before.shape != after.shape:
        raise ValueError(f"shape mismatch: {before.shape} vs {after.shape}")
    if before.size == 0:
        return 0.0
    return float(np.mean(before != after))


__all__ = [
    "HostPartition",
    "MASK64",
    "keys_from_hashes",
    "mix64",
    "remap_fraction",
]
