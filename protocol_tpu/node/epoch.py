"""Epoch arithmetic (server/src/epoch.rs)."""

from __future__ import annotations

import time
from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class Epoch:
    """Wall-clock epoch index: unix seconds // interval."""

    number: int

    def __str__(self) -> str:
        return f"Epoch({self.number})"

    def to_be_bytes(self) -> bytes:
        return self.number.to_bytes(8, "big")

    @classmethod
    def from_be_bytes(cls, b: bytes) -> "Epoch":
        return cls(int.from_bytes(b[:8], "big"))

    @classmethod
    def current_timestamp(cls) -> int:
        return int(time.time())

    @classmethod
    def current_epoch(cls, interval: int) -> "Epoch":
        return cls(cls.current_timestamp() // interval)

    @classmethod
    def secs_until_next_epoch(cls, interval: int) -> int:
        secs = cls.current_timestamp()
        return (secs // interval + 1) * interval - secs

    def previous(self) -> "Epoch":
        return Epoch(self.number - 1)

    def next(self) -> "Epoch":
        return Epoch(self.number + 1)

    def is_zero(self) -> bool:
        return self.number == 0
