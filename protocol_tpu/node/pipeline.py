"""Double-buffered epoch pipeline (PERF.md §11).

A sequential epoch tick serializes two very different resources: the
*host* (ingest drain, graph assembly, warm-start remap, plan delta) and
the *device* (convergence) plus the prover.  Steady-state traffic keeps
both busy less than half the time.  This module overlaps them: while
epoch k holds the device (converge → prove → checkpoint), the host
prepares epoch k+1 (``Manager.prepare_epoch`` — everything up to, but
excluding, the first device dispatch), handing the prepared state over
a bounded queue.

Backpressure is *coalescing*, not dropping: when the device stage falls
behind (a slow prover, a cold-compile epoch) and the queue is full, the
newest prepared epoch replaces the stale one still waiting — safe
because an epoch's prepared state is cumulative (the attestation cache
only advances, the dirty-sender set is cleared only after a successful
converge, and the warm-start seed always remaps from the last *landed*
epoch), so processing the newer epoch subsumes the superseded one.
Superseded ticks are counted on
``eigentrust_epoch_ticks_coalesced_total`` — degradation is graceful
and observable instead of silent.

Plan mutation (``WindowPlan.apply_delta``) stays strictly in the host
stage, pre-dispatch — graftlint's ``plan-mutation-in-converge`` rule
pins the converse structurally.
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass
from typing import Any, Callable

from ..obs import metrics as obs_metrics
from ..obs.journal import JOURNAL
from ..obs.timeline import TIMELINE
from ..trust.backend import ConvergenceResult
from .epoch import Epoch
from .manager import Manager, PreparedEpoch

log = logging.getLogger(__name__)

#: Epoch outcomes the pipeline retains for inspection (matches the
#: tracer's epoch-ring depth).
_RESULT_RING = 16


@dataclass
class EpochOutcome:
    """What the device stage produced for one epoch — the result, or
    the exception that ended it (the pipeline never dies with a tick)."""

    epoch: Epoch
    result: ConvergenceResult | Any | None
    error: BaseException | None = None


class EpochPipeline:
    """Bounded host/device epoch pipeline around a :class:`Manager`.

    One producer thread (the caller of :meth:`submit` — the node's
    epoch loop, or a benchmark driver) runs host stages; one internal
    worker thread runs device stages.  ``queue_depth`` bounds how many
    prepared epochs may wait between them (1 = classic double
    buffering: one epoch on the device, one staged behind it).

    ``device_stage`` defaults to ``Manager.converge_prepared`` with the
    pipeline's convergence parameters; the node passes a richer stage
    (prove → converge → checkpoint) without changing the queueing
    semantics.
    """

    def __init__(
        self,
        manager: Manager,
        *,
        alpha: float = 0.1,
        tol: float = 1e-6,
        max_iter: int = 50,
        queue_depth: int = 1,
        device_stage: Callable[[PreparedEpoch], Any] | None = None,
        on_complete: Callable[[EpochOutcome], None] | None = None,
    ):
        self.manager = manager
        self.alpha = alpha
        self.tol = tol
        self.max_iter = max_iter
        self._queue: queue.Queue[PreparedEpoch] = queue.Queue(
            maxsize=max(int(queue_depth), 1)
        )
        self._device_stage = device_stage or self._default_device_stage
        self._on_complete = on_complete
        self._cv = threading.Condition()
        self._pending = 0  # prepared epochs queued or on the device
        self._stop = threading.Event()
        self.outcomes: dict[int, EpochOutcome] = {}
        #: Ticks superseded under backpressure (mirrors the counter
        #: metric, but per-instance — benchmarks read this).
        self.coalesced = 0
        self.completed = 0
        self._worker = threading.Thread(
            target=self._device_loop, name="epoch-pipeline-device", daemon=True
        )
        self._started = False

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "EpochPipeline":
        # The started flip happens under the condition lock: submit
        # paths and close() race this from different roots, and a bare
        # check-then-act here double-starts the worker thread.
        with self._cv:
            if self._started:
                return self
            self._started = True
        self._worker.start()
        return self

    def close(self, *, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop the device worker; with ``drain`` (default) only after
        every queued epoch has run."""
        with self._cv:
            started = self._started
        if drain and started:
            self.drain(timeout=timeout)
        self._stop.set()
        if started:
            self._worker.join(timeout=timeout)

    def __enter__(self) -> "EpochPipeline":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- host stage (producer thread) -----------------------------------

    def submit(self, epoch: Epoch) -> PreparedEpoch:
        """Run epoch's host stage on the calling thread and enqueue the
        prepared state for the device worker.  Never blocks on a busy
        device: a full queue coalesces (the stale waiting epoch is
        superseded by this one), so a slow prover stretches epoch
        latency instead of backing work up or dropping ticks."""
        self.start()  # idempotent under the condition lock
        prepared = self.manager.prepare_epoch(epoch)
        superseded: PreparedEpoch | None = None
        with self._cv:
            try:
                self._queue.put_nowait(prepared)
            except queue.Full:
                # Single producer: between this get and put nobody else
                # fills the slot (the worker only drains).
                try:
                    superseded = self._queue.get_nowait()
                except queue.Empty:
                    pass
                self._queue.put_nowait(prepared)
            self._pending += 1 if superseded is None else 0
            if superseded is not None:
                self.coalesced += 1
            obs_metrics.PIPELINE_QUEUE_DEPTH.set(self._queue.qsize())
        if superseded is not None:
            obs_metrics.EPOCH_TICKS_COALESCED.inc()
            TIMELINE.record(
                superseded.epoch.number, coalesced_by=prepared.epoch.number
            )
            JOURNAL.record(
                "coalesced-tick",
                superseded=superseded.epoch.number,
                by=prepared.epoch.number,
            )
            log.warning(
                "epoch %s superseded by %s before reaching the device "
                "(pipeline backpressure)",
                superseded.epoch,
                prepared.epoch,
            )
        return prepared

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every submitted epoch has completed (or the
        timeout passes); returns whether the pipeline is empty."""
        with self._cv:
            return self._cv.wait_for(lambda: self._pending == 0, timeout=timeout)

    # -- device stage (worker thread) -----------------------------------

    def _default_device_stage(self, prepared: PreparedEpoch):
        return self.manager.converge_prepared(
            prepared, alpha=self.alpha, tol=self.tol, max_iter=self.max_iter
        )

    def _device_loop(self) -> None:
        while not self._stop.is_set():
            try:
                prepared = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            obs_metrics.PIPELINE_QUEUE_DEPTH.set(self._queue.qsize())
            try:
                outcome = EpochOutcome(prepared.epoch, self._device_stage(prepared))
            except BaseException as exc:  # noqa: BLE001 - tick must not kill the loop
                log.error("epoch %s device stage failed: %r", prepared.epoch, exc)
                JOURNAL.record(
                    "anomaly",
                    what="epoch-device-stage-failed",
                    epoch=prepared.epoch.number,
                    error=repr(exc),
                )
                outcome = EpochOutcome(prepared.epoch, None, exc)
            with self._cv:
                self.outcomes[prepared.epoch.number] = outcome
                while len(self.outcomes) > _RESULT_RING:
                    del self.outcomes[min(self.outcomes)]
                self.completed += 1
                self._pending -= 1
                self._cv.notify_all()
            if self._on_complete is not None:
                try:
                    self._on_complete(outcome)
                except Exception:  # noqa: BLE001
                    log.exception("epoch %s on_complete hook failed", prepared.epoch)


__all__ = ["EpochOutcome", "EpochPipeline"]
