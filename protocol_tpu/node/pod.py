"""Pod durability plane: per-host WAL + checkpoint shards bound to one
epoch by a pod-level manifest (ROADMAP item 1; rides PR 12's recovery
machinery unchanged).

Each host owns a disjoint peer partition (``parallel.partition``), so
each host journals **only the attestations whose source peer it owns**
into its own ``AttestationWAL`` and checkpoints only its local
window-plan shard through its own ``CheckpointStore`` — the durability
plane shards exactly like the edge set, and a host recovers from its
own shard alone (kill -9 one process of N, replay that host's WAL
tail; the crash-matrix host-loss row drives this end to end).

What a single-host node gets for free — "the checkpoint and the WAL
watermark describe the same epoch" — a pod has to state explicitly:
host A's checkpoint at epoch 12 plus host B's at epoch 11 is not a
recoverable pod state.  The **pod manifest** closes that seam: after
an epoch's converge, every host publishes an immutable *shard stamp*
(its checkpoint column digests + WAL watermark, atomically written),
and the sealer host (host 0 by convention) binds the complete stamp
set into ``pod_manifest_e<N>.json``.  Recovery reads the newest
*sealed* manifest: a crash between publish and seal leaves a partial
stamp set that no manifest references, so every host rolls back to
the same previous epoch — torn pod states are unrepresentable, the
same tmp+fsync+rename doctrine as ``CheckpointStore`` one level up.
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path

from .checkpoint import CheckpointStore
from .wal import AttestationWAL


def _atomic_write(dest: Path, write_fn, mode: str = "w") -> None:
    """tmp + fsync + rename (the pass-11 ``non-atomic-state-write``
    discipline): the stamp/manifest bytes hit disk before the rename
    publishes the name, so a reader never sees a torn document."""
    fd, tmp = tempfile.mkstemp(dir=dest.parent, suffix=".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class PodDurability:
    """One host's handle on the pod's sharded durability tree::

        root/
          host-000/wal/...          per-host WAL segments
          host-000/checkpoints/...  per-host CheckpointStore
          manifests/
            shard-e00000012-h000.json   immutable per-host stamps
            pod_manifest_e00000012.json sealed epoch binding

    The WAL and checkpoint store are the PR 12 classes verbatim —
    sharding the plane is a directory-layout decision, not a format
    change, so single-host recovery tooling reads a pod shard as-is.
    """

    def __init__(
        self,
        root: str | Path,
        host_id: int,
        n_hosts: int,
        *,
        keep: int = 4,
        fsync: bool = True,
        segment_max_bytes: int = 4 << 20,
    ):
        if not 0 <= host_id < n_hosts:
            raise ValueError(f"host_id {host_id} outside pod of {n_hosts}")
        self.root = Path(root)
        self.host_id = int(host_id)
        self.n_hosts = int(n_hosts)
        host_dir = self.root / f"host-{host_id:03d}"
        self.wal = AttestationWAL(
            host_dir / "wal", segment_max_bytes=segment_max_bytes, fsync=fsync
        )
        self.checkpoints = CheckpointStore(host_dir / "checkpoints", keep=keep)
        self.manifest_dir = self.root / "manifests"
        self.manifest_dir.mkdir(parents=True, exist_ok=True)

    # -- per-host stamps ---------------------------------------------------

    def _stamp_path(self, epoch: int, host: int) -> Path:
        return self.manifest_dir / f"shard-e{epoch:08d}-h{host:03d}.json"

    def _manifest_path(self, epoch: int) -> Path:
        return self.manifest_dir / f"pod_manifest_e{epoch:08d}.json"

    def publish_shard(
        self,
        epoch: int,
        *,
        wal_seq: int,
        columns: dict[str, str],
        extra: dict | None = None,
    ) -> Path:
        """Atomically publish this host's stamp for ``epoch``: the
        checkpoint column digests and the WAL watermark the checkpoint
        covers.  Must be called after the host's own
        ``CheckpointStore.save`` returns (the stamp asserts durable
        local state, it does not create it)."""
        stamp = {
            "epoch": int(epoch),
            "host": self.host_id,
            "n_hosts": self.n_hosts,
            "wal_seq": int(wal_seq),
            "columns": dict(columns),
        }
        if extra:
            stamp.update(extra)
        dest = self._stamp_path(epoch, self.host_id)
        _atomic_write(dest, lambda f: json.dump(stamp, f, indent=1))
        return dest

    # -- pod-level sealing -------------------------------------------------

    def seal_epoch(self, epoch: int) -> dict | None:
        """Bind the epoch's complete stamp set into the pod manifest
        (sealer host only — host 0 by convention, but any single
        designated host works; the write is atomic and idempotent).
        Returns the manifest, or None when stamps are still missing —
        the caller retries next epoch; an unsealed epoch is simply not
        recoverable-to and every host rolls back past it."""
        stamps = {}
        for h in range(self.n_hosts):
            path = self._stamp_path(epoch, h)
            try:
                stamps[str(h)] = json.loads(path.read_text())
            except (OSError, json.JSONDecodeError):
                return None
        manifest = {
            "epoch": int(epoch),
            "n_hosts": self.n_hosts,
            "shards": stamps,
        }
        _atomic_write(
            self._manifest_path(epoch), lambda f: json.dump(manifest, f, indent=1)
        )
        return manifest

    def load_manifest(self) -> dict | None:
        """Newest sealed pod manifest (recovery entry point): every
        host resumes from ``manifest['epoch']`` using its own
        checkpoint shard and replays its own WAL tail past the
        recorded ``wal_seq`` — no cross-host reads."""
        paths = sorted(self.manifest_dir.glob("pod_manifest_e*.json"))
        for path in reversed(paths):
            try:
                manifest = json.loads(path.read_text())
            except (OSError, json.JSONDecodeError):
                continue  # torn manifests are impossible; stale tmp noise
            if len(manifest.get("shards", {})) == manifest.get("n_hosts"):
                return manifest
        return None

    def my_stamp(self, manifest: dict) -> dict | None:
        return manifest.get("shards", {}).get(str(self.host_id))


__all__ = ["PodDurability"]
