"""Write-ahead attestation log: crash-consistent node state.

The reference's durability story is "the chain is the checkpoint" —
every boot replays events from block 0 (server/src/main.rs:139-143).
At 50M attestations that replay is the recovery path's whole cost, and
between periodic snapshots a crash silently loses every accepted
attestation since the last one.  This module closes that window: every
attestation the Manager applies is first appended to an fsync'd,
size-rotated segment log, and boot recovery is deterministic —

1. load the newest *valid* checkpoint (digest-verified, falling back
   epoch by epoch — node/checkpoint.py),
2. replay the WAL tail (records past the checkpoint's ``wal_seq``
   watermark) through the existing ``apply_verified`` fast path,
3. rebuild warm state via ``restore_warm_state`` so the first epoch
   converges from the recovered fixed point (arXiv:1603.00589's
   start-independence is what makes the warm recovered state safe).

Format: segments ``wal_<first_seq>.seg``, each an 8-byte magic header
followed by records ``[u64 seq][u32 len][u32 crc32][payload]`` (crc
over seq‖len‖payload).  The payload is ``[u16 num_neighbours][wire
bytes]`` — the attestation's reference wire form plus the neighbour
count the decoder needs.  A torn tail (crash mid-append, the
``wal.append`` torn fault) fails the crc and drops exactly the tail
record: it was never acknowledged, so nothing acknowledged is lost.
Segments whose records are all ≤ the checkpointed watermark are
deleted after a successful checkpoint (``truncate_through``), bounding
disk to roughly one epoch of traffic per retained snapshot.

Durability contract: an ingest verdict is returned only after the
record's ``flush()`` (write + fsync) — the admission plane appends a
verify batch with ``flush=False`` and flushes once per batch, so the
fsync cost amortizes exactly like the signature checks do.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib
from pathlib import Path
from typing import TYPE_CHECKING, Iterator

from .. import chaos
from ..obs.journal import JOURNAL
from ..obs.metrics import (
    CHECKPOINT_FALLBACKS,
    RECOVERY_SECONDS,
    WAL_APPENDED,
    WAL_REPLAYED,
)

if TYPE_CHECKING:
    from .checkpoint import CheckpointStore
    from .manager import Manager

log = logging.getLogger(__name__)

_MAGIC = b"ETWAL001"
_HEADER = struct.Struct(">QII")  # seq, payload length, crc32

chaos.declare("wal.append", "a WAL record is serialized, pre-write (torn target)")
chaos.declare("wal.post_append", "a WAL record hit the OS (post write/fsync)")
chaos.declare("wal.pre_truncate", "before checkpointed segments are deleted")
chaos.declare("wal.replay", "one record re-applied during boot recovery")


def encode_payload(num_neighbours: int, wire: bytes) -> bytes:
    """``[u16 n][wire]`` — the neighbour count rides with the record so
    replay decodes without global config."""
    return num_neighbours.to_bytes(2, "big") + wire


def decode_payload(payload: bytes) -> tuple[int, bytes]:
    return int.from_bytes(payload[:2], "big"), payload[2:]


class AttestationWAL:
    """Append-only, fsync'd, size-rotated attestation log."""

    def __init__(
        self,
        directory: str | Path,
        *,
        segment_max_bytes: int = 4 << 20,
        fsync: bool = True,
    ):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_max_bytes = int(segment_max_bytes)
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        self._file = None  # active segment, opened lazily on append
        self._active_path: Path | None = None
        self._active_first: int | None = None
        self._active_bytes = 0
        #: Closed segments: path -> (first_seq, last_seq).
        self._segments: dict[Path, tuple[int, int]] = {}
        #: Highest sequence number ever assigned.
        self._last_seq = 0
        #: Appended-but-not-yet-applied seqs (the applied watermark is
        #: the highest seq below every pending one — records at or
        #: below it are guaranteed to be in the attestation cache).
        self._pending: set[int] = set()
        self.dropped_tail = 0
        self._scan()

    # -- boot scan ------------------------------------------------------

    def _segment_paths(self) -> list[Path]:
        return sorted(self.dir.glob("wal_*.seg"))

    def _scan(self) -> None:
        """Index existing segments and find the highest valid seq.
        Old segments stay read-only; new appends open a new segment, so
        a torn tail never needs in-place surgery.  Runs at construction
        (pre-sharing), under the lock like every other index mutation."""
        with self._lock:
            for path in self._segment_paths():
                first, last, torn = self._scan_segment(path)
                if first is None or last is None:
                    # Empty or header-only segment (crash before the
                    # first record landed): nothing to replay, drop it.
                    path.unlink(missing_ok=True)
                    continue
                self._segments[path] = (first, last)
                self._last_seq = max(self._last_seq, last)
                self.dropped_tail += torn

    @staticmethod
    def _scan_segment(path: Path) -> tuple[int | None, int | None, int]:
        """(first_seq, last_seq, torn_records) of one segment —
        validated record by record, stopping at the first torn one."""
        first = last = None
        torn = 0
        try:
            data = path.read_bytes()
        except OSError:
            return None, None, 0
        if not data.startswith(_MAGIC):
            return None, None, 1
        off = len(_MAGIC)
        while off + _HEADER.size <= len(data):
            seq, length, crc = _HEADER.unpack_from(data, off)
            start = off + _HEADER.size
            payload = data[start : start + length]
            if len(payload) < length or zlib.crc32(
                data[off : off + 12] + payload
            ) != crc:
                torn = 1
                break
            if first is None:
                first = seq
            last = seq
            off = start + length
        else:
            if off != len(data) and off < len(data):
                torn = 1
        return first, last, torn

    # -- append path ----------------------------------------------------

    def _rotate_locked(self) -> None:
        if self._file is not None:
            self._file.close()
            assert self._active_path is not None and self._active_first is not None
            self._segments[self._active_path] = (
                self._active_first,
                self._last_seq,
            )
            self._file = None
            self._active_path = None
            self._active_first = None
            self._active_bytes = 0

    def _open_segment_locked(self, first_seq: int) -> None:
        """Create the next active segment: header written and fsync'd
        before any record, so a segment file is never magic-less."""
        path = self.dir / f"wal_{first_seq:020d}.seg"
        f = open(path, "wb")
        f.write(_MAGIC)
        f.flush()
        os.fsync(f.fileno())
        self._file = f
        self._active_path = path
        self._active_first = first_seq
        self._active_bytes = len(_MAGIC)

    def append(self, payload: bytes, *, flush: bool = True) -> int:
        """Append one record; returns its sequence number.  With
        ``flush`` (the default) the record is fsync'd before return —
        batch callers pass ``flush=False`` and call :meth:`flush` once
        per batch."""
        with self._lock:
            seq = self._last_seq + 1
            if (
                self._file is not None
                and self._active_bytes >= self.segment_max_bytes
            ):
                self._rotate_locked()
            if self._file is None:
                self._open_segment_locked(seq)
            header = _HEADER.pack(
                seq, len(payload), zlib.crc32(seq.to_bytes(8, "big") + len(payload).to_bytes(4, "big") + payload)
            )
            record = header + payload
            if chaos.ACTIVE:
                record = chaos.corrupt("wal.append", record)
            self._file.write(record)
            self._active_bytes += len(record)
            self._last_seq = seq
            self._pending.add(seq)
            if flush:
                self._flush_locked()
        WAL_APPENDED.inc()
        if chaos.ACTIVE:
            chaos.fire("wal.post_append")
        return seq

    def _flush_locked(self) -> None:
        if self._file is not None:
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())

    def flush(self) -> None:
        """Flush buffered records to the OS and fsync (the durability
        boundary an ingest verdict waits on)."""
        with self._lock:
            self._flush_locked()

    def mark_applied(self, seq: int) -> None:
        """The record's attestation reached the cache — it now counts
        toward the applied watermark a checkpoint may truncate through."""
        with self._lock:
            self._pending.discard(seq)

    def applied_watermark(self) -> int:
        """Highest seq S such that every record ≤ S has been applied —
        a graph built *after* reading this absorbs all of them, so a
        checkpoint of that graph may truncate through S."""
        with self._lock:
            if not self._pending:
                return self._last_seq
            return min(self._pending) - 1

    @property
    def seq(self) -> int:
        with self._lock:
            return self._last_seq

    # -- recovery path --------------------------------------------------

    def replay(self, after_seq: int = -1) -> Iterator[tuple[int, bytes]]:
        """Yield ``(seq, payload)`` for every valid record with
        ``seq > after_seq``, oldest first.  A torn record ends its
        segment's replay (only the unacknowledged tail is lost)."""
        with self._lock:
            paths = sorted(set(self._segments) | (
                {self._active_path} if self._active_path else set()
            ))
        for path in paths:
            try:
                data = path.read_bytes()
            except OSError:
                continue
            if not data.startswith(_MAGIC):
                continue
            off = len(_MAGIC)
            while off + _HEADER.size <= len(data):
                seq, length, crc = _HEADER.unpack_from(data, off)
                start = off + _HEADER.size
                payload = data[start : start + length]
                if len(payload) < length or zlib.crc32(
                    data[off : off + 12] + payload
                ) != crc:
                    break
                if seq > after_seq:
                    if chaos.ACTIVE:
                        chaos.fire("wal.replay")
                    yield seq, payload
                off = start + length

    # -- truncation -----------------------------------------------------

    def truncate_through(self, seq: int) -> int:
        """Delete closed segments whose records are all ≤ ``seq`` (the
        checkpoint watermark).  The active segment is rotated first
        when fully covered.  Returns the number of segments removed."""
        if chaos.ACTIVE:
            chaos.fire("wal.pre_truncate")
        removed = 0
        with self._lock:
            if (
                self._file is not None
                and self._last_seq <= seq
                and self._active_bytes > len(_MAGIC)
            ):
                self._flush_locked()
                self._rotate_locked()
            for path, (_, last) in list(self._segments.items()):
                if last <= seq:
                    path.unlink(missing_ok=True)
                    del self._segments[path]
                    removed += 1
        return removed

    def segment_count(self) -> int:
        with self._lock:
            return len(self._segments) + (1 if self._file is not None else 0)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._flush_locked()
                self._rotate_locked()


def recover(
    manager: "Manager",
    store: "CheckpointStore | None",
    wal: AttestationWAL | None,
) -> dict:
    """THE boot recovery path, shared by the node daemon and the crash
    matrix: newest valid checkpoint → warm state → WAL tail replayed
    through ``apply_verified`` → WAL attached for new appends.  Returns
    a report dict (also the /healthz ``recovery`` component body)."""
    t0 = time.perf_counter()
    fallbacks0 = CHECKPOINT_FALLBACKS.value()
    snapshot = store.load_latest() if store is not None else None
    wal_seq = -1
    checkpoint_epoch = None
    restored_atts = 0
    bad_records = 0
    if snapshot is not None:
        checkpoint_epoch = snapshot.epoch.number
        if snapshot.wal_seq is not None:
            wal_seq = int(snapshot.wal_seq)
        if snapshot.attestations:
            from .attestation import AttestationData

            for n, wire_bytes in snapshot.attestations:
                try:
                    att = AttestationData.from_bytes(wire_bytes, n).to_attestation(n)
                except (ValueError, IndexError) as exc:
                    bad_records += 1
                    JOURNAL.record(
                        "anomaly", what="checkpoint-bad-attestation", error=repr(exc)
                    )
                    continue
                manager.restore_attestation(att)
                restored_atts += 1
        if snapshot.proof_json:
            from ..zk.proof import ProofRaw

            manager.cache_proof(
                snapshot.epoch,
                ProofRaw.from_json(snapshot.proof_json).to_proof(),
            )
        manager.restore_warm_state(
            graph=snapshot.graph,
            plan=snapshot.plan,
            scores=snapshot.scores,
            peer_hashes=snapshot.peer_hashes,
        )
    replayed = 0
    if wal is not None:
        from .attestation import AttestationData

        for seq, payload in wal.replay(after_seq=wal_seq):
            try:
                n, wire = decode_payload(payload)
                att = AttestationData.from_bytes(wire, n).to_attestation(n)
            except (ValueError, IndexError) as exc:
                # CRC-valid but undecodable should be impossible; skip
                # rather than abort recovery over one record.
                bad_records += 1
                JOURNAL.record("anomaly", what="wal-bad-record", seq=seq, error=repr(exc))
                continue
            manager.apply_verified(att, raw=wire, flush=False)
            WAL_REPLAYED.inc()
            replayed += 1
        # New appends go through the manager from here on.
        manager.wal = wal
    seconds = time.perf_counter() - t0
    RECOVERY_SECONDS.set(seconds)
    report = {
        "checkpoint_epoch": checkpoint_epoch,
        "checkpoint_fallbacks": int(CHECKPOINT_FALLBACKS.value() - fallbacks0),
        "attestations_restored": restored_atts,
        "wal_seq": wal_seq,
        "wal_replayed": replayed,
        "wal_dropped_tail": wal.dropped_tail if wal is not None else 0,
        "wal_bad_records": bad_records,
        "seconds": round(seconds, 6),
    }
    JOURNAL.record("recovery", **report)
    return report


__all__ = [
    "AttestationWAL",
    "decode_payload",
    "encode_payload",
    "recover",
]
