"""Epoch-indexed snapshots of the assembled trust graph and scores.

The reference has no durable node state: the chain is the checkpoint
and every boot replays events from block 0 (server/src/main.rs:139-143).
The rebuild keeps the chain as the *source of truth* but no longer
treats snapshots as a mere optimization: together with the write-ahead
attestation log (node/wal.py) they are the crash-consistent recovery
state — at 50M attestations a from-zero replay is not a viable boot
path, so a snapshot must be **provably loadable or provably skippable**.

Format: ``<dir>/epoch_<N>.npz`` (numpy arrays) + ``manifest.json``.
The manifest carries, per retained epoch, a **sha256 digest of every
column** (dtype + shape + bytes), the digests of the plan sidecar and
proof document, and the WAL watermark ``wal_seq`` (all records ≤ it
are inside this snapshot's graph); it also persists the chain-replay
``block_cursor`` so event ingestion resumes where it left off instead
of from block 0.  ``load`` verifies the digests and raises
:class:`SnapshotCorrupt` on any mismatch or decode failure;
``load_latest`` falls back epoch by epoch to the newest snapshot that
verifies (finally giving ``keep=4`` a reason to exist), counting each
skip on ``eigentrust_checkpoint_fallbacks_total`` and journaling it.
Writes are atomic (tmp + fsync + rename) and carry the
``checkpoint.write`` / ``checkpoint.pre_rename`` fault points the
crash matrix kills at.

The snapshot optionally carries a ``peer_hashes`` column (Poseidon
hash per score row, graph assembly order) — the key the warm-start
remap needs — and, on the windowed backends, the bucketing plan rides
along as ``epoch_<N>.plan.npz`` with its delta lineage; a sidecar from
a stale plan-format version (or one failing its digest) degrades to a
rebuild on first converge, never a boot failure.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from .. import chaos
from ..obs import TRACER
from ..obs.journal import JOURNAL
from ..obs.metrics import (
    CHECKPOINT_FALLBACKS,
    CHECKPOINT_RESTORES,
    CHECKPOINT_SAVES,
)
from ..ops.gather_window import WindowPlan
from ..trust.graph import TrustGraph
from .epoch import Epoch

chaos.declare("checkpoint.write", "snapshot bytes streaming into the atomic tmp (torn target)")
chaos.declare("checkpoint.pre_rename", "atomic write complete, before the rename lands")


class SnapshotCorrupt(Exception):
    """A snapshot failed digest verification or could not be decoded —
    the typed error ``load_latest`` falls back on (callers of ``load``
    see this instead of whatever npz/JSON internals raise)."""


@dataclass
class Snapshot:
    epoch: Epoch
    graph: TrustGraph
    scores: np.ndarray | None
    proof_json: str | None = None
    plan: WindowPlan | None = None
    #: Peer hash per score row (graph assembly order) — the key the
    #: warm-start remap needs, so a reboot's first epoch starts from
    #: the checkpointed fixed point instead of cold.
    peer_hashes: list[int] | None = None
    #: WAL watermark: every log record with seq ≤ this is inside the
    #: snapshot's graph, so boot replay starts just past it.  None on
    #: legacy snapshots (replay everything; apply is idempotent).
    wal_seq: int | None = None
    #: The attestation cache itself, ``(num_neighbours, wire bytes)``
    #: per sender (last-wins) — the recovery state the graph column
    #: alone cannot reconstruct: post-recovery epochs rebuild the graph
    #: FROM the cache, and the WAL only holds the tail past ``wal_seq``.
    attestations: list[tuple[int, bytes]] | None = None


def _digest(arr: np.ndarray) -> str:
    """sha256 over dtype ‖ shape ‖ bytes — a flipped bit, truncated
    column, or silently re-typed array all change it."""
    arr = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(repr(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def _text_digest(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()


class CheckpointStore:
    def __init__(self, directory: str | Path, keep: int = 4):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = keep

    def _path(self, epoch: Epoch) -> Path:
        return self.dir / f"epoch_{epoch.number}.npz"

    def _atomic_write(self, dest: Path, write_fn, mode: str) -> None:
        """tmp + fsync + rename with cleanup on failure — the data
        blocks are forced to disk *before* the rename publishes them,
        so a crash leaves either the old file or the complete new one,
        never a renamed torn body."""
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, mode) as f:
                target = f
                if chaos.ACTIVE:
                    target = chaos.wrap_file("checkpoint.write", f)
                write_fn(target)
                f.flush()
                os.fsync(f.fileno())
            if chaos.ACTIVE:
                chaos.fire("checkpoint.pre_rename")
            os.replace(tmp, dest)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # -- manifest -------------------------------------------------------

    def _read_manifest(self) -> dict:
        """Tolerant manifest read: a torn or garbage manifest is an
        empty one (the directory scan still finds snapshots; digests
        are then simply unavailable for verification)."""
        manifest = self.dir / "manifest.json"
        try:
            obj = json.loads(manifest.read_text())
        except (OSError, json.JSONDecodeError):
            return {}
        return obj if isinstance(obj, dict) else {}

    def _write_manifest(self, obj: dict) -> None:
        self._atomic_write(
            self.dir / "manifest.json", lambda f: json.dump(obj, f), "w"
        )

    def retained_wal_floor(self) -> int | None:
        """Highest WAL seq the log may truncate through: the MINIMUM
        ``wal_seq`` across every retained snapshot — fallback must be
        able to recover from the *oldest* retained epoch, so records
        its snapshot lacks have to stay replayable.  (Pre-WAL legacy
        snapshots carry no watermark and are excluded: their recovery
        replays the whole remaining log anyway.)  None = nothing to
        truncate against yet."""
        entries = self._read_manifest().get("epochs", {})
        seqs = [
            int(e["wal_seq"])
            for e in entries.values()
            if isinstance(e, dict) and e.get("wal_seq") is not None
        ]
        return min(seqs) if seqs else None

    def block_cursor(self) -> int | None:
        """The persisted chain-replay cursor (next block to fetch), or
        None for a from-genesis replay."""
        cursor = self._read_manifest().get("block_cursor")
        return int(cursor) if cursor is not None else None

    def save_block_cursor(self, next_block: int) -> None:
        """Persist the event stream's resume point — called by the
        node as the chain replay advances, so a restart resumes from
        here instead of block 0."""
        manifest = self._read_manifest()
        manifest["block_cursor"] = int(next_block)
        self._write_manifest(manifest)

    # -- save -----------------------------------------------------------

    def save(
        self,
        epoch: Epoch,
        graph: TrustGraph,
        scores=None,
        proof_json: str | None = None,
        plan: WindowPlan | None = None,
        peer_hashes: list[int] | None = None,
        wal_seq: int | None = None,
        attestations: list[tuple[int, bytes]] | None = None,
    ) -> Path:
        CHECKPOINT_SAVES.inc()
        path = self._path(epoch)
        payload = {
            "n": np.int64(graph.n),
            "src": graph.src,
            "dst": graph.dst,
            "weight": graph.weight,
        }
        if attestations is not None:
            # The cache as three flat columns: per-record neighbour
            # count, cumulative end offsets, and the concatenated wire
            # bytes — no pickling, digest-verified like every column.
            lengths = np.array([len(w) for _, w in attestations], np.int64)
            payload["att_nbrs"] = np.array(
                [n for n, _ in attestations], np.int32
            )
            payload["att_offsets"] = np.cumsum(lengths, dtype=np.int64)
            payload["att_wire"] = np.frombuffer(
                b"".join(w for _, w in attestations), np.uint8
            )
        if graph.pre_trusted is not None:
            payload["pre_trusted"] = graph.pre_trusted
        if scores is not None:
            payload["scores"] = np.asarray(scores, dtype=np.float64)
        if peer_hashes is not None:
            # Poseidon hashes are field elements < 2^254: 32 bytes each,
            # big-endian, one fixed-width bytes row per score row.
            payload["peer_hashes"] = np.array(
                [h.to_bytes(32, "big") for h in peer_hashes], dtype="S32"
            )

        entry: dict = {
            "columns": {k: _digest(np.asarray(v)) for k, v in payload.items()}
        }
        if wal_seq is not None:
            entry["wal_seq"] = int(wal_seq)
        self._atomic_write(path, lambda f: np.savez_compressed(f, **payload), "wb")
        if plan is not None:
            # Uncompressed: the plan is int/float index arrays that
            # barely compress, and the save sits on the epoch tick.
            plan_arrays = plan.to_arrays(core_only=True)
            entry["plan"] = {
                k: _digest(np.asarray(v)) for k, v in plan_arrays.items()
            }
            self._atomic_write(
                self.dir / f"epoch_{epoch.number}.plan.npz",
                lambda f: np.savez(f, **plan_arrays),
                "wb",
            )
        if proof_json is not None:
            entry["proof"] = _text_digest(proof_json)
            self._atomic_write(
                self.dir / f"epoch_{epoch.number}.proof.json",
                lambda f: f.write(proof_json),
                "w",
            )
        manifest = self._read_manifest()
        epochs_meta = manifest.get("epochs")
        if not isinstance(epochs_meta, dict):
            epochs_meta = {}
        epochs_meta[str(epoch.number)] = entry
        kept = self._prune()
        manifest.update(
            {
                "latest_epoch": epoch.number,
                "epochs": {
                    k: v for k, v in epochs_meta.items() if int(k) in kept
                },
            }
        )
        self._write_manifest(manifest)
        return path

    def _prune(self) -> set[int]:
        snaps = sorted(self.epochs())
        for number in snaps[: -self.keep]:
            self._path(Epoch(number)).unlink(missing_ok=True)
            (self.dir / f"epoch_{number}.proof.json").unlink(missing_ok=True)
            (self.dir / f"epoch_{number}.plan.npz").unlink(missing_ok=True)
        return set(snaps[-self.keep :])

    def epochs(self) -> list[int]:
        # Sidecar files (epoch_N.plan.npz) share the prefix and glob;
        # only bare epoch_N.npz snapshots define the epoch set.  The
        # scan is sorted numerically: glob order is inode-history-
        # dependent, and this list feeds prune order and the boot-time
        # latest() pick, which must match across hosts bit for bit.
        return sorted(
            int(p.stem.removeprefix("epoch_"))
            for p in self.dir.glob("epoch_*.npz")
            if p.stem.removeprefix("epoch_").isdigit()
        )

    def manifest_entry(self, epoch: Epoch) -> dict | None:
        """This epoch's manifest entry (column/plan digests + WAL
        watermark) — what a pod host binds into its shard stamp
        (``node.pod.PodDurability.publish_shard``): the stamp quotes
        the digests the store itself verifies on load, so manifest
        verification and snapshot verification can never disagree."""
        entry = self._read_manifest().get("epochs", {}).get(str(epoch.number))
        return entry if isinstance(entry, dict) else None

    # -- load -----------------------------------------------------------

    def load(self, epoch: Epoch) -> Snapshot:
        """Load one snapshot, verifying every column against its
        manifest digest.  Raises :class:`SnapshotCorrupt` on a torn,
        bit-flipped, truncated, or undecodable snapshot — callers that
        can fall back (``load_latest``) catch exactly that; nothing
        here leaks raw npz/zip internals."""
        entry = self._read_manifest().get("epochs", {}).get(str(epoch.number), {})
        digests: dict = entry.get("columns", {}) if isinstance(entry, dict) else {}
        with TRACER.span("checkpoint_restore", epoch=epoch.number):
            try:
                with np.load(self._path(epoch)) as z:
                    for name, want in digests.items():
                        if name not in z:
                            raise SnapshotCorrupt(
                                f"epoch {epoch.number}: column {name!r} missing"
                            )
                        if _digest(z[name]) != want:
                            raise SnapshotCorrupt(
                                f"epoch {epoch.number}: column {name!r} digest mismatch"
                            )
                    graph = TrustGraph(
                        n=int(z["n"]),
                        src=z["src"],
                        dst=z["dst"],
                        weight=z["weight"],
                        pre_trusted=z["pre_trusted"] if "pre_trusted" in z else None,
                    )
                    scores = np.array(z["scores"]) if "scores" in z else None
                    peer_hashes = (
                        [int.from_bytes(bytes(b), "big") for b in z["peer_hashes"]]
                        if "peer_hashes" in z
                        else None
                    )
                    attestations = None
                    if "att_wire" in z:
                        wire_all = z["att_wire"].tobytes()
                        attestations = []
                        start = 0
                        for n, end in zip(z["att_nbrs"], z["att_offsets"]):
                            attestations.append(
                                (int(n), wire_all[start : int(end)])
                            )
                            start = int(end)
            except SnapshotCorrupt:
                raise
            except Exception as exc:  # noqa: BLE001 - npz/zip/OS decode soup
                raise SnapshotCorrupt(
                    f"epoch {epoch.number}: unreadable snapshot ({exc!r})"
                ) from exc
            proof_json = self._load_proof(epoch, entry)
            plan = self._load_plan(epoch, entry)
        CHECKPOINT_RESTORES.inc()
        return Snapshot(
            epoch=epoch,
            graph=graph,
            scores=scores,
            proof_json=proof_json,
            plan=plan,
            peer_hashes=peer_hashes,
            wal_seq=entry.get("wal_seq") if isinstance(entry, dict) else None,
            attestations=attestations,
        )

    def _load_proof(self, epoch: Epoch, entry: dict) -> str | None:
        """Proof sidecar: re-derivable from the attestation stream, so
        a corrupt one degrades to None (journaled), never a failure."""
        proof_path = self.dir / f"epoch_{epoch.number}.proof.json"
        if not proof_path.exists():
            return None
        try:
            proof_json = proof_path.read_text()
        except OSError:
            return None
        want = entry.get("proof")
        if want is not None and _text_digest(proof_json) != want:
            JOURNAL.record(
                "anomaly", what="checkpoint-proof-corrupt", epoch=epoch.number
            )
            return None
        return proof_json

    def _load_plan(self, epoch: Epoch, entry: dict) -> WindowPlan | None:
        """Plan sidecar: an optimization, never a source of truth — a
        stale layout version, decode failure, or digest mismatch all
        degrade to a rebuild on first converge."""
        plan_path = self.dir / f"epoch_{epoch.number}.plan.npz"
        if not plan_path.exists():
            return None
        want = entry.get("plan")
        try:
            with np.load(plan_path) as pz:
                if want is not None:
                    for name, digest in want.items():
                        if name not in pz or _digest(pz[name]) != digest:
                            JOURNAL.record(
                                "anomaly",
                                what="checkpoint-plan-corrupt",
                                epoch=epoch.number,
                                column=name,
                            )
                            return None
                return WindowPlan.from_arrays(pz)
        except Exception:  # noqa: BLE001 - torn sidecar, stale layout, zip soup
            return None

    def load_latest(self) -> Snapshot | None:
        """Newest snapshot that *verifies*: the manifest's latest
        first, then every on-disk epoch newest-to-oldest.  Each torn or
        corrupt candidate is journaled and counted on
        ``eigentrust_checkpoint_fallbacks_total``; cold start (None)
        only when no snapshot survives."""
        candidates: list[int] = []
        latest = self._read_manifest().get("latest_epoch")
        if latest is not None and self._path(Epoch(int(latest))).exists():
            candidates.append(int(latest))
        for number in sorted(self.epochs(), reverse=True):
            if number not in candidates:
                candidates.append(number)
        for number in candidates:
            try:
                return self.load(Epoch(number))
            except SnapshotCorrupt as exc:
                CHECKPOINT_FALLBACKS.inc()
                JOURNAL.record(
                    "checkpoint-fallback", epoch=number, error=str(exc)
                )
        return None
