"""Epoch-indexed snapshots of the assembled trust graph and scores.

The reference has no durable node state: the chain is the checkpoint
and every boot replays events from block 0 (server/src/main.rs:139-143).
That stance is kept — snapshots are an *optimization*, not a source of
truth (SURVEY.md §5): at 50M attestations replay is expensive, so the
node periodically writes the assembled COO graph + the last converged
score vector and can serve scores immediately after restart while the
replay catches up.

Format: ``<dir>/epoch_<N>.npz`` (numpy arrays) + ``manifest.json``
pointing at the latest; writes are atomic (tmp + rename).  The
snapshot optionally carries a ``peer_hashes`` column (Poseidon hash
per score row, graph assembly order) — the key the warm-start remap
needs, so a reboot's first epoch converges from the checkpointed
fixed point instead of cold (PERF.md §11).  When the node converges
on a windowed backend (``tpu-windowed`` or
``tpu-sharded:tpu-windowed``), the one-time bucketing plan
(ops.gather_window.WindowPlan — the expensive host-side layout) rides
along as ``epoch_<N>.plan.npz``, including its delta lineage (the
ancestor-fingerprint chain of ``apply_delta`` updates), so a reboot
revalidates it by fingerprint + layout version instead of rebuilding
it; a sidecar from a stale plan-format version is ignored (rebuild on
first converge).
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from ..obs import TRACER
from ..obs.metrics import CHECKPOINT_RESTORES, CHECKPOINT_SAVES
from ..ops.gather_window import WindowPlan
from ..trust.graph import TrustGraph
from .epoch import Epoch


@dataclass
class Snapshot:
    epoch: Epoch
    graph: TrustGraph
    scores: np.ndarray | None
    proof_json: str | None = None
    plan: WindowPlan | None = None
    #: Peer hash per score row (graph assembly order) — the key the
    #: warm-start remap needs, so a reboot's first epoch starts from
    #: the checkpointed fixed point instead of cold.
    peer_hashes: list[int] | None = None


class CheckpointStore:
    def __init__(self, directory: str | Path, keep: int = 4):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = keep

    def _path(self, epoch: Epoch) -> Path:
        return self.dir / f"epoch_{epoch.number}.npz"

    def _atomic_write(self, dest: Path, write_fn, mode: str) -> None:
        """tmp + rename with cleanup on failure."""
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, mode) as f:
                write_fn(f)
            os.replace(tmp, dest)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def save(
        self,
        epoch: Epoch,
        graph: TrustGraph,
        scores=None,
        proof_json: str | None = None,
        plan: WindowPlan | None = None,
        peer_hashes: list[int] | None = None,
    ) -> Path:
        CHECKPOINT_SAVES.inc()
        path = self._path(epoch)
        payload = {
            "n": np.int64(graph.n),
            "src": graph.src,
            "dst": graph.dst,
            "weight": graph.weight,
        }
        if graph.pre_trusted is not None:
            payload["pre_trusted"] = graph.pre_trusted
        if scores is not None:
            payload["scores"] = np.asarray(scores, dtype=np.float64)
        if peer_hashes is not None:
            # Poseidon hashes are field elements < 2^254: 32 bytes each,
            # big-endian, one fixed-width bytes row per score row.
            payload["peer_hashes"] = np.array(
                [h.to_bytes(32, "big") for h in peer_hashes], dtype="S32"
            )

        self._atomic_write(path, lambda f: np.savez_compressed(f, **payload), "wb")
        if plan is not None:
            # Uncompressed: the plan is int/float index arrays that
            # barely compress, and the save sits on the epoch tick.
            self._atomic_write(
                self.dir / f"epoch_{epoch.number}.plan.npz",
                lambda f: np.savez(f, **plan.to_arrays(core_only=True)),
                "wb",
            )
        if proof_json is not None:
            self._atomic_write(
                self.dir / f"epoch_{epoch.number}.proof.json",
                lambda f: f.write(proof_json),
                "w",
            )
        self._atomic_write(
            self.dir / "manifest.json",
            lambda f: json.dump({"latest_epoch": epoch.number}, f),
            "w",
        )
        self._prune()
        return path

    def _prune(self) -> None:
        snaps = sorted(self.epochs())
        for number in snaps[: -self.keep]:
            self._path(Epoch(number)).unlink(missing_ok=True)
            (self.dir / f"epoch_{number}.proof.json").unlink(missing_ok=True)
            (self.dir / f"epoch_{number}.plan.npz").unlink(missing_ok=True)

    def epochs(self) -> list[int]:
        # Sidecar files (epoch_N.plan.npz) share the prefix and glob;
        # only bare epoch_N.npz snapshots define the epoch set.
        return [
            int(p.stem.removeprefix("epoch_"))
            for p in self.dir.glob("epoch_*.npz")
            if p.stem.removeprefix("epoch_").isdigit()
        ]

    def load(self, epoch: Epoch) -> Snapshot:
        with TRACER.span("checkpoint_restore", epoch=epoch.number):
            with np.load(self._path(epoch)) as z:
                graph = TrustGraph(
                    n=int(z["n"]),
                    src=z["src"],
                    dst=z["dst"],
                    weight=z["weight"],
                    pre_trusted=z["pre_trusted"] if "pre_trusted" in z else None,
                )
                scores = np.array(z["scores"]) if "scores" in z else None
                peer_hashes = (
                    [int.from_bytes(bytes(b), "big") for b in z["peer_hashes"]]
                    if "peer_hashes" in z
                    else None
                )
            proof_path = self.dir / f"epoch_{epoch.number}.proof.json"
            proof_json = proof_path.read_text() if proof_path.exists() else None
            plan_path = self.dir / f"epoch_{epoch.number}.plan.npz"
            plan = None
            if plan_path.exists():
                with np.load(plan_path) as pz:
                    try:
                        plan = WindowPlan.from_arrays(pz)
                    except (ValueError, KeyError):
                        # Plan written by an older layout version (e.g. the
                        # pre-v2 dst-sorted boundary pairs): snapshots are an
                        # optimization, never a source of truth, so a stale
                        # sidecar degrades to a rebuild on first converge.
                        plan = None
        CHECKPOINT_RESTORES.inc()
        return Snapshot(
            epoch=epoch,
            graph=graph,
            scores=scores,
            proof_json=proof_json,
            plan=plan,
            peer_hashes=peer_hashes,
        )

    def load_latest(self) -> Snapshot | None:
        manifest = self.dir / "manifest.json"
        if manifest.exists():
            number = json.loads(manifest.read_text()).get("latest_epoch")
            if number is not None and self._path(Epoch(number)).exists():
                return self.load(Epoch(number))
        epochs = self.epochs()
        if not epochs:
            return None
        return self.load(Epoch(max(epochs)))
