"""Attestation domain object and its wire codec.

The byte layout must stay interoperable with the reference
(server/src/manager/attestation.rs:22-81): fixed 32-byte little-endian
field reprs in the order ``sig.R.x ‖ sig.R.y ‖ sig.s ‖ pk.x ‖ pk.y ‖
(neighbour x,y)×N ‖ score×N`` — the payload written into the
AttestationStation ``bytes`` value.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import field
from ..crypto.eddsa import PublicKey, Signature


@dataclass
class Attestation:
    """A peer's signed score vector over its neighbours
    (attestation.rs:96-116)."""

    sig: Signature
    pk: PublicKey
    neighbours: list[PublicKey]
    scores: list[int]


@dataclass
class AttestationData:
    """Raw wire form (attestation.rs:9-18)."""

    sig_r_x: bytes
    sig_r_y: bytes
    sig_s: bytes
    pk: tuple[bytes, bytes]
    neighbours: list[tuple[bytes, bytes]]
    scores: list[bytes]

    def to_bytes(self) -> bytes:
        out = bytearray()
        out += self.sig_r_x
        out += self.sig_r_y
        out += self.sig_s
        out += self.pk[0]
        out += self.pk[1]
        for nx, ny in self.neighbours:
            out += nx
            out += ny
        for s in self.scores:
            out += s
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes, num_neighbours: int) -> "AttestationData":
        """Parse the fixed layout; score count is whatever remains
        (attestation.rs:40-81 drains scores until empty)."""
        need = 32 * (5 + 2 * num_neighbours)
        if len(data) < need or len(data) % 32 != 0:
            raise ValueError(
                f"attestation payload must be 32-byte aligned and >= {need} bytes"
            )
        fields = [data[i : i + 32] for i in range(0, len(data), 32)]
        sig_r_x, sig_r_y, sig_s, pk_x, pk_y = fields[:5]
        rest = fields[5:]
        neighbours = [
            (rest[2 * i], rest[2 * i + 1]) for i in range(num_neighbours)
        ]
        scores = rest[2 * num_neighbours :]
        return cls(
            sig_r_x=sig_r_x,
            sig_r_y=sig_r_y,
            sig_s=sig_s,
            pk=(pk_x, pk_y),
            neighbours=neighbours,
            scores=scores,
        )

    @classmethod
    def from_attestation(cls, att: Attestation) -> "AttestationData":
        return cls(
            sig_r_x=field.to_le_bytes(att.sig.big_r.x),
            sig_r_y=field.to_le_bytes(att.sig.big_r.y),
            sig_s=field.to_le_bytes(att.sig.s),
            pk=att.pk.to_raw(),
            neighbours=[pk.to_raw() for pk in att.neighbours],
            scores=[field.to_le_bytes(s) for s in att.scores],
        )

    def to_attestation(self, num_neighbours: int) -> Attestation:
        """Decode, zero-filling missing neighbours/scores and truncating
        extras (attestation.rs:118-137)."""
        sig = Signature.new(
            field.from_le_bytes(self.sig_r_x),
            field.from_le_bytes(self.sig_r_y),
            field.from_le_bytes(self.sig_s),
        )
        pk = PublicKey.from_raw(self.pk)
        neighbours = [PublicKey.null()] * num_neighbours
        scores = [0] * num_neighbours
        for i, raw in enumerate(self.neighbours[:num_neighbours]):
            neighbours[i] = PublicKey.from_raw(raw)
        for i, raw in enumerate(self.scores[:num_neighbours]):
            scores[i] = field.from_le_bytes(raw)
        return Attestation(sig=sig, pk=pk, neighbours=neighbours, scores=scores)
