"""Protocol error codes, wire-stable in both directions
(server/src/error.rs:6-56)."""

from __future__ import annotations

from enum import Enum


class EigenErrorCode(Enum):
    INVALID_BOOTSTRAP_PUBKEY = 0
    PROVING_ERROR = 1
    VERIFICATION_ERROR = 2
    CONNECTION_ERROR = 3
    LISTEN_ERROR = 4
    ATTESTATION_NOT_FOUND = 5
    PROOF_NOT_FOUND = 6
    INVALID_ATTESTATION = 7
    UNKNOWN = 255

    @classmethod
    def from_u8(cls, code: int) -> "EigenErrorCode":
        try:
            return cls(code)
        except ValueError:
            return cls.UNKNOWN


class EigenError(Exception):
    """Protocol exception carrying a stable u8 wire code."""

    def __init__(self, code: EigenErrorCode, message: str = ""):
        self.code = code
        super().__init__(message or code.name)

    def to_u8(self) -> int:
        return self.code.value

    @classmethod
    def invalid_attestation(cls, why: str = "") -> "EigenError":
        return cls(EigenErrorCode.INVALID_ATTESTATION, why)

    @classmethod
    def proof_not_found(cls) -> "EigenError":
        return cls(EigenErrorCode.PROOF_NOT_FOUND)

    @classmethod
    def attestation_not_found(cls) -> "EigenError":
        return cls(EigenErrorCode.ATTESTATION_NOT_FOUND)
