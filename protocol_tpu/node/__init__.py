"""The protocol node: attestation ingest, epoch loop, proof cache, HTTP API.

Rebuild of the reference ``server`` crate (server/src): a daemon that
replays AttestationCreated events from the chain (or a recorded fixture
log), validates and caches signed attestations, and every epoch runs
trust convergence — on a TrustBackend instead of the reference's inline
5×5 loop — caching a proof of the scores served over ``GET /score``.
"""

from .attestation import Attestation, AttestationData  # noqa: F401
from .epoch import Epoch  # noqa: F401
from .errors import EigenError, EigenErrorCode  # noqa: F401
from .manager import Manager, ManagerConfig  # noqa: F401
