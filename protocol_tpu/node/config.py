"""Node configuration (server/src/main.rs:39-45, data/protocol-config.json).

Same JSON shape as the reference so existing config files load
unchanged, with additive optional fields for the TPU rebuild (trust
backend, event fixture path)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class ProtocolConfig:
    epoch_interval: int = 10
    endpoint: tuple[tuple[int, int, int, int], int] = ((0, 0, 0, 0), 3000)
    ethereum_node_url: str = "http://localhost:8545"
    as_contract_address: str = "0x" + "0" * 40
    # Rebuild-specific (absent from reference configs; defaulted).
    # Any trust/backend.py ladder rung: native-cpu | tpu-dense |
    # tpu-sparse | tpu-csr | tpu-windowed | tpu-sharded (optionally
    # with a per-shard kernel suffix, e.g. "tpu-sharded:tpu-windowed"
    # for the fused pipeline on a real multi-chip mesh).  The windowed
    # backends additionally persist their bucketing plan with each
    # checkpoint.
    trust_backend: str = "native-cpu"
    event_fixture: str | None = None
    checkpoint_dir: str | None = None
    #: Write-ahead attestation log (node/wal.py): every accepted
    #: attestation is fsync'd to a size-rotated segment log before its
    #: ingest verdict returns, and boot recovery replays the tail past
    #: the newest valid checkpoint — ``kill -9`` at any instruction
    #: loses nothing acknowledged.  Requires ``checkpoint_dir`` (the
    #: log lives beside the snapshots); ``false`` restores the
    #: checkpoint-only (lossy between snapshots) behavior.
    wal: bool = True
    #: WAL directory override; default ``<checkpoint_dir>/wal``.
    wal_dir: str | None = None
    #: Segment rotation threshold — with per-checkpoint truncation this
    #: bounds WAL disk to roughly one epoch of traffic per retained
    #: snapshot.
    wal_segment_bytes: int = 4 << 20
    #: fsync on every durability boundary (per verdict / per verify
    #: batch).  Disable only for tests and benchmarks.
    wal_fsync: bool = True
    #: Fault-injection schedule (protocol_tpu/chaos/): a spec dict, an
    #: ``@path`` reference, or None (disabled — the hot-path cost of
    #: disabled chaos is one module-attribute read).  The
    #: PROTOCOL_TPU_CHAOS env var takes precedence; only chaos tooling
    #: (tools/crash_matrix.py, tests) should ever set either.
    chaos: dict | str | None = None
    #: Double-buffered epoch pipeline (node/pipeline.py): overlap the
    #: next epoch's host stages (ingest drain, graph build, plan delta)
    #: with the current epoch's device converge + proving, behind a
    #: bounded queue with coalescing backpressure.  Off by default —
    #: the sequential tick is easier to reason about on small nodes.
    epoch_pipeline: bool = False
    #: Seed each epoch's convergence from the previous fixed point
    #: (ManagerConfig.warm_start).
    warm_start: bool = True
    #: Dirty-row fraction above which the windowed plan cache rebuilds
    #: instead of delta-updating (ManagerConfig.plan_delta_max_churn).
    plan_delta_max_churn: float = 0.05
    #: Admission plane (protocol_tpu/ingest/): bounded-queue intake +
    #: sharded dedup/nonce cache + per-sender rate limits in front of
    #: the Manager, serving POST /attestation with 429 shed semantics.
    #: On by default; ``false`` restores direct Manager ingest.
    ingest_plane: bool = True
    #: Verify worker processes (0 = verify inline, no pool): each
    #: spawned worker owns a native batch-EdDSA verifier pinned to one
    #: OMP thread, so admission scales across cores and off the epoch
    #: loop's GIL.
    ingest_workers: int = 0
    #: Signatures per verify batch.
    ingest_batch_size: int = 64
    #: Submit-queue bound; beyond it, POST /attestation sheds with 429.
    ingest_queue_max: int = 1024
    #: Per-sender token-bucket refill (attestations/second) and burst
    #: capacity for non-whitelisted senders.
    ingest_rate_rps: float = 50.0
    ingest_rate_burst: float = 200.0
    #: Exempt the pre-trust set from rate/spam gates (dedup still
    #: applies to everyone).
    ingest_whitelist_pretrusted: bool = True
    #: "plonk" (real KZG SNARK per epoch, the reference's behavior) or
    #: "commitment" (fast Poseidon binding).
    prover: str = "plonk"
    #: Async proving plane (protocol_tpu/prover/): the epoch tick ends
    #: at converge → checkpoint and *enqueues* the SNARK onto a bounded
    #: queue drained by a prover worker pool — a slow prover becomes
    #: proof lag (eigentrust_proof_lag_epochs, GET /proof/<epoch>),
    #: never epoch latency.  Off by default: the sequential tick keeps
    #: the reference's proof-per-tick semantics on small nodes.
    async_prover: bool = False
    #: Prover worker processes (0 = prove inline on the plane's
    #: dispatcher thread — still off the epoch tick, but sharing the
    #: node process's GIL).  Each worker caches its SRS + proving key
    #: across jobs and is prewarmed at boot.
    prover_workers: int = 1
    #: Proof jobs that may wait for a dispatcher; beyond it the oldest
    #: queued job is superseded (latest-wins — an epoch tick never
    #: blocks on the proof queue).
    prover_queue_max: int = 1
    #: Per-attempt prove timeout (seconds); a worker past it is killed
    #: and the job retried, then failed with reason=prover-crashed.
    prove_timeout_s: float = 900.0
    #: OMP_NUM_THREADS for each prover worker's native MSM/NTT loops
    #: (0 = runtime default).
    prover_omp_threads: int = 0
    #: Ceremony SRS file for the PLONK prover (kzg.Setup format).
    srs_path: str | None = None
    #: Opt-in jax.profiler capture: device-timeline traces of each
    #: epoch's convergence land under ``<profile_dir>/epoch_<N>``
    #: (view with tensorboard/xprof).  None disables profiling — the
    #: default; span/metric telemetry is always on and costs no device
    #: sync either way.
    profile_dir: str | None = None
    #: On-disk flight-recorder journal (obs/journal.py): a bounded
    #: JSONL file every span close, ingest rejection, plan outcome,
    #: coalesced tick, and anomaly is appended to by a batched writer
    #: thread.  None keeps the recorder in-memory-only (the ring and
    #: ``GET /debug/flight`` work either way); on crash/SIGTERM the
    #: node dumps the ring next to this path (or to
    #: ``FLIGHT_dump.jsonl`` in the working directory).
    journal_path: str | None = None
    #: Attestation lineage sampling period (obs/lineage.py): one in N
    #: accepted submissions carries a lineage ID through
    #: intake → ... → proof-landed, feeding the per-stage
    #: eigentrust_freshness_seconds histograms.  0 disables sampling;
    #: the unsampled path costs one counter tick either way.
    lineage_sample_every: int = 32
    #: Shared directory for multi-process (jax.distributed) metric
    #: exchange: each process publishes its registry snapshot here and
    #: GET /metrics/fleet merges every sibling into one
    #: process-labeled exposition.  None = single-process fleet (spawn
    #: workers still merge through their result payloads).
    fleet_dir: str | None = None
    #: SLO targets (obs/slo.py): end-to-end freshness p99 and
    #: submit-to-proved p99, in seconds.  The epoch-cadence objective
    #: derives from epoch_interval; a violating objective flips
    #: GET /slo to ok=false and fails the CI dryrun.
    slo_freshness_p99_s: float = 120.0
    slo_proof_lag_p99_s: float = 60.0
    #: Fleet snapshot staleness TTL (obs/fleet.py): a sibling whose
    #: newest fleet_dir snapshot is older than this is evicted from
    #: the merged scrape, counted on eigentrust_fleet_stale_sources,
    #: and degrades /healthz — a silently dead pod host surfaces here
    #: before a gloo collective hangs on it.  0 disables the TTL.
    fleet_stale_after_s: float = 30.0
    #: Pod straggler watcher (obs/watchers.py StragglerWatcher): flag a
    #: host whose phase time exceeds the pod median by this ratio for
    #: this many consecutive stitched epochs.
    straggler_ratio: float = 1.5
    straggler_epochs: int = 3
    #: Pod phase-skew SLO target (obs/slo.py pod_objectives): p99 of
    #: max-median host duration per epoch phase, seconds.
    slo_pod_skew_p99_s: float = 1.0

    @property
    def host(self) -> str:
        return ".".join(str(x) for x in self.endpoint[0])

    @property
    def port(self) -> int:
        return self.endpoint[1]

    @classmethod
    def from_json(cls, text: str) -> "ProtocolConfig":
        obj = json.loads(text)
        cfg = cls()
        cfg.epoch_interval = int(obj.get("epoch_interval", cfg.epoch_interval))
        if "endpoint" in obj:
            octets, port = obj["endpoint"]
            cfg.endpoint = (tuple(int(x) for x in octets), int(port))
        cfg.ethereum_node_url = obj.get("ethereum_node_url", cfg.ethereum_node_url)
        cfg.as_contract_address = obj.get("as_contract_address", cfg.as_contract_address)
        cfg.trust_backend = obj.get("trust_backend", cfg.trust_backend)
        cfg.event_fixture = obj.get("event_fixture", cfg.event_fixture)
        cfg.checkpoint_dir = obj.get("checkpoint_dir", cfg.checkpoint_dir)
        cfg.wal = bool(obj.get("wal", cfg.wal))
        cfg.wal_dir = obj.get("wal_dir", cfg.wal_dir)
        cfg.wal_segment_bytes = int(
            obj.get("wal_segment_bytes", cfg.wal_segment_bytes)
        )
        cfg.wal_fsync = bool(obj.get("wal_fsync", cfg.wal_fsync))
        cfg.chaos = obj.get("chaos", cfg.chaos)
        cfg.epoch_pipeline = bool(obj.get("epoch_pipeline", cfg.epoch_pipeline))
        cfg.warm_start = bool(obj.get("warm_start", cfg.warm_start))
        cfg.plan_delta_max_churn = float(
            obj.get("plan_delta_max_churn", cfg.plan_delta_max_churn)
        )
        cfg.ingest_plane = bool(obj.get("ingest_plane", cfg.ingest_plane))
        cfg.ingest_workers = int(obj.get("ingest_workers", cfg.ingest_workers))
        cfg.ingest_batch_size = int(
            obj.get("ingest_batch_size", cfg.ingest_batch_size)
        )
        cfg.ingest_queue_max = int(obj.get("ingest_queue_max", cfg.ingest_queue_max))
        cfg.ingest_rate_rps = float(obj.get("ingest_rate_rps", cfg.ingest_rate_rps))
        cfg.ingest_rate_burst = float(
            obj.get("ingest_rate_burst", cfg.ingest_rate_burst)
        )
        cfg.ingest_whitelist_pretrusted = bool(
            obj.get("ingest_whitelist_pretrusted", cfg.ingest_whitelist_pretrusted)
        )
        cfg.prover = obj.get("prover", cfg.prover)
        cfg.async_prover = bool(obj.get("async_prover", cfg.async_prover))
        cfg.prover_workers = int(obj.get("prover_workers", cfg.prover_workers))
        cfg.prover_queue_max = int(
            obj.get("prover_queue_max", cfg.prover_queue_max)
        )
        cfg.prove_timeout_s = float(obj.get("prove_timeout_s", cfg.prove_timeout_s))
        cfg.prover_omp_threads = int(
            obj.get("prover_omp_threads", cfg.prover_omp_threads)
        )
        cfg.srs_path = obj.get("srs_path", cfg.srs_path)
        cfg.profile_dir = obj.get("profile_dir", cfg.profile_dir)
        cfg.journal_path = obj.get("journal_path", cfg.journal_path)
        cfg.lineage_sample_every = int(
            obj.get("lineage_sample_every", cfg.lineage_sample_every)
        )
        cfg.fleet_dir = obj.get("fleet_dir", cfg.fleet_dir)
        cfg.slo_freshness_p99_s = float(
            obj.get("slo_freshness_p99_s", cfg.slo_freshness_p99_s)
        )
        cfg.slo_proof_lag_p99_s = float(
            obj.get("slo_proof_lag_p99_s", cfg.slo_proof_lag_p99_s)
        )
        cfg.fleet_stale_after_s = float(
            obj.get("fleet_stale_after_s", cfg.fleet_stale_after_s)
        )
        cfg.straggler_ratio = float(
            obj.get("straggler_ratio", cfg.straggler_ratio)
        )
        cfg.straggler_epochs = int(
            obj.get("straggler_epochs", cfg.straggler_epochs)
        )
        cfg.slo_pod_skew_p99_s = float(
            obj.get("slo_pod_skew_p99_s", cfg.slo_pod_skew_p99_s)
        )
        return cfg

    @classmethod
    def load(cls, path: str | Path) -> "ProtocolConfig":
        return cls.from_json(Path(path).read_text())
