"""Chain event ingestion: AttestationCreated replay.

The reference's only peer-to-peer transport is the AttestationStation
contract's event log, replayed from block 0 on boot
(server/src/main.rs:139-143, data/AttestationStation.sol:13-18).  Two
sources implement that here:

- ``FixtureEventSource`` — a JSONL file of recorded events (the test
  doctrine's "recorded event-log fixtures", SURVEY.md §4 tier 6);
- ``Web3EventSource``    — live JSON-RPC via web3.py when installed
  (this image has no web3; the import is gated).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import AsyncIterator, Iterator

from ..crypto.keccak import event_topic

#: keccak256("AttestationCreated(address,address,bytes32,bytes)") — the
#: event topic emitted by AttestationStation.sol:13-18.
ATTESTATION_CREATED_TOPIC = (
    "0x" + event_topic("AttestationCreated(address,address,bytes32,bytes)").hex()
)


@dataclass
class AttestationCreatedEvent:
    """Decoded AttestationCreated(creator, about, key, val)."""

    creator: str
    about: str
    key: bytes
    val: bytes

    def to_json(self) -> str:
        return json.dumps(
            {
                "creator": self.creator,
                "about": self.about,
                "key": "0x" + self.key.hex(),
                "val": "0x" + self.val.hex(),
            }
        )

    @classmethod
    def from_json(cls, line: str) -> "AttestationCreatedEvent":
        obj = json.loads(line)
        return cls(
            creator=obj["creator"],
            about=obj["about"],
            key=bytes.fromhex(obj["key"].removeprefix("0x")),
            val=bytes.fromhex(obj["val"].removeprefix("0x")),
        )


class FixtureEventSource:
    """Replays events from a JSONL fixture, then (optionally) tails the
    file for appended events — the fixture analog of an event
    subscription."""

    def __init__(self, path: str | Path):
        self.path = Path(path)

    def replay(self) -> Iterator[AttestationCreatedEvent]:
        if not self.path.exists():
            return
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield AttestationCreatedEvent.from_json(line)

    async def stream(self, poll_interval: float = 0.5) -> AsyncIterator[AttestationCreatedEvent]:
        """Tail the fixture by byte offset — appended lines are parsed
        once, never re-reading the prefix."""
        import asyncio

        offset = 0
        pending = b""
        while True:
            if self.path.exists():
                with open(self.path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read()
                offset += len(chunk)
                pending += chunk
                while b"\n" in pending:
                    line, pending = pending.split(b"\n", 1)
                    line = line.strip()
                    if line:
                        yield AttestationCreatedEvent.from_json(line.decode())
            await asyncio.sleep(poll_interval)


class Web3EventSource:
    """Live AttestationCreated stream over JSON-RPC (ethers-equivalent
    of server/src/ethereum.rs).  Requires web3.py at runtime."""

    def __init__(self, node_url: str, contract_address: str):
        try:
            from web3 import Web3  # type: ignore
        except ImportError as e:  # pragma: no cover - web3 not in image
            raise RuntimeError(
                "web3.py is not installed; use a FixtureEventSource or "
                "install web3 for live chain ingestion"
            ) from e
        self._w3 = Web3(Web3.HTTPProvider(node_url))
        self.contract_address = contract_address

    def replay(self, from_block: int = 0, to_block=None) -> Iterator[AttestationCreatedEvent]:  # pragma: no cover
        query = {
            "fromBlock": from_block,
            "address": self._w3.to_checksum_address(self.contract_address),
            "topics": [ATTESTATION_CREATED_TOPIC],
        }
        if to_block is not None:
            query["toBlock"] = to_block
        for log in self._w3.eth.get_logs(query):
            yield self._decode(log)

    @staticmethod
    def _decode(log) -> AttestationCreatedEvent:  # pragma: no cover
        data = bytes(log["data"])
        # ABI: dynamic bytes → offset (32) + length (32) + payload.
        length = int.from_bytes(data[32:64], "big")
        return AttestationCreatedEvent(
            creator="0x" + log["topics"][1].hex()[-40:],
            about="0x" + log["topics"][2].hex()[-40:],
            key=bytes(log["topics"][3]),
            val=data[64 : 64 + length],
        )

    async def stream(self, poll_interval: float = 2.0) -> AsyncIterator[AttestationCreatedEvent]:  # pragma: no cover
        """Replay from block 0 (server/src/main.rs:139-143) then poll new
        blocks — the ethers event-stream analog over plain JSON-RPC."""
        import asyncio

        next_block = 0
        while True:
            head = self._w3.eth.block_number
            if head >= next_block:
                for ev in self.replay(from_block=next_block, to_block=head):
                    yield ev
                next_block = head + 1
            await asyncio.sleep(poll_interval)


def have_web3() -> bool:
    try:
        import web3  # noqa: F401

        return True
    except ImportError:
        return False
