"""Chain event ingestion: AttestationCreated replay.

The reference's only peer-to-peer transport is the AttestationStation
contract's event log, replayed from block 0 on boot
(server/src/main.rs:139-143, data/AttestationStation.sol:13-18).  Two
sources implement that here:

- ``FixtureEventSource`` — a JSONL file of recorded events (the test
  doctrine's "recorded event-log fixtures", SURVEY.md §4 tier 6);
- ``Web3EventSource``    — live JSON-RPC via web3.py when installed
  (this image has no web3; the import is gated).
"""

from __future__ import annotations

import json
import logging
import random
from dataclasses import dataclass
from pathlib import Path
from typing import AsyncIterator, Callable, Iterator

from .. import chaos
from ..crypto.keccak import event_topic
from ..obs.metrics import RPC_RETRIES

log = logging.getLogger(__name__)

chaos.declare("rpc.block_number", "chain head poll about to hit the RPC backend")
chaos.declare("rpc.get_logs", "event-log fetch about to hit the RPC backend")

#: keccak256("AttestationCreated(address,address,bytes32,bytes)") — the
#: event topic emitted by AttestationStation.sol:13-18.
ATTESTATION_CREATED_TOPIC = (
    "0x" + event_topic("AttestationCreated(address,address,bytes32,bytes)").hex()
)


@dataclass
class AttestationCreatedEvent:
    """Decoded AttestationCreated(creator, about, key, val)."""

    creator: str
    about: str
    key: bytes
    val: bytes

    def to_json(self) -> str:
        return json.dumps(
            {
                "creator": self.creator,
                "about": self.about,
                "key": "0x" + self.key.hex(),
                "val": "0x" + self.val.hex(),
            }
        )

    @classmethod
    def from_json(cls, line: str) -> "AttestationCreatedEvent":
        obj = json.loads(line)
        return cls(
            creator=obj["creator"],
            about=obj["about"],
            key=bytes.fromhex(obj["key"].removeprefix("0x")),
            val=bytes.fromhex(obj["val"].removeprefix("0x")),
        )


class FixtureEventSource:
    """Replays events from a JSONL fixture, then (optionally) tails the
    file for appended events — the fixture analog of an event
    subscription."""

    def __init__(self, path: str | Path):
        self.path = Path(path)

    def replay(self) -> Iterator[AttestationCreatedEvent]:
        if not self.path.exists():
            return
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield AttestationCreatedEvent.from_json(line)

    async def stream(self, poll_interval: float = 0.5) -> AsyncIterator[AttestationCreatedEvent]:
        """Tail the fixture by byte offset — appended lines are parsed
        once, never re-reading the prefix."""
        import asyncio

        offset = 0
        pending = b""
        while True:
            if self.path.exists():
                with open(self.path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read()
                offset += len(chunk)
                pending += chunk
                while b"\n" in pending:
                    line, pending = pending.split(b"\n", 1)
                    line = line.strip()
                    if line:
                        yield AttestationCreatedEvent.from_json(line.decode())
            await asyncio.sleep(poll_interval)


@dataclass(frozen=True)
class RetryPolicy:
    """The RPC retry wall's knobs: exponential backoff (full jitter)
    with a per-call timeout.  A transient transport failure becomes a
    counted retry (``eigentrust_rpc_retries_total{op}``) and a pause,
    never a dead event loop — the node's only peer-to-peer transport
    must survive an RPC endpoint that flaps for hours."""

    base_s: float = 0.5
    cap_s: float = 30.0
    #: Per-call deadline: a hung endpoint is a retry, not a stall.
    timeout_s: float = 10.0


class ChainEventSource:
    """AttestationCreated replay/stream over an abstract RPC backend —
    the ethers-equivalent of server/src/ethereum.rs, with the transport
    pluggable so the same decode/replay/poll logic runs against web3
    (live) or the in-process dev chain (evm/devchain.py, the Anvil
    analog used in tests).

    The backend needs two methods:
    ``block_number() -> int`` and
    ``get_logs(address, from_block, to_block, topic0) -> iterable`` of
    logs with ``topics: list[int]`` and ``data: bytes``.

    ``stream`` wraps both behind the retry wall (:class:`RetryPolicy`)
    and supports a **resumable block cursor**: pass ``cursor`` (the
    next block to fetch, persisted in the checkpoint manifest by the
    node) and ``on_advance`` to be told each time the cursor moves, so
    a restart resumes the replay where it left off instead of from
    block 0.
    """

    def __init__(self, rpc, contract_address: str, retry: RetryPolicy | None = None):
        self._rpc = rpc
        self.contract_address = contract_address
        self.retry = retry or RetryPolicy()
        self._rng = random.Random()

    def replay(
        self, from_block: int = 0, to_block=None
    ) -> Iterator[AttestationCreatedEvent]:
        if chaos.ACTIVE:
            chaos.fire("rpc.get_logs")
        logs = self._rpc.get_logs(
            address=int(self.contract_address, 16),
            from_block=from_block,
            to_block=to_block,
            topic0=int(ATTESTATION_CREATED_TOPIC, 16),
        )
        for log_ in logs:
            yield self._decode(log_)

    def _block_number(self) -> int:
        if chaos.ACTIVE:
            chaos.fire("rpc.block_number")
        return self._rpc.block_number()

    @staticmethod
    def _decode(log) -> AttestationCreatedEvent:
        data = bytes(log.data)
        # ABI: dynamic bytes → offset (32) + length (32) + payload.
        length = int.from_bytes(data[32:64], "big")
        mask160 = (1 << 160) - 1
        return AttestationCreatedEvent(
            creator=f"0x{log.topics[1] & mask160:040x}",
            about=f"0x{log.topics[2] & mask160:040x}",
            key=log.topics[3].to_bytes(32, "big"),
            val=data[64 : 64 + length],
        )

    async def _call(self, op: str, fn: Callable):
        """One RPC call off-loop with the policy's per-call deadline —
        a sync transport (web3, the dev chain) must never park the
        node's event loop, and a hung one must become a retry."""
        import asyncio

        return await asyncio.wait_for(
            asyncio.get_running_loop().run_in_executor(None, fn),
            timeout=self.retry.timeout_s,
        )

    async def stream(
        self,
        poll_interval: float = 2.0,
        *,
        cursor: int | None = None,
        on_advance: Callable[[int], None] | None = None,
    ) -> AsyncIterator[AttestationCreatedEvent]:
        """Replay from the cursor (default block 0,
        server/src/main.rs:139-143) then poll new blocks — the ethers
        event-stream analog over plain JSON-RPC, behind the retry
        wall: every ``block_number``/``get_logs`` failure or timeout
        backs off exponentially with full jitter, counted on
        ``eigentrust_rpc_retries_total{op}``, and the stream resumes
        from the last *delivered* block so no event is skipped."""
        import asyncio

        next_block = int(cursor) if cursor is not None else 0
        backoff = self.retry.base_s
        while True:
            op = "block_number"
            try:
                head = await self._call(op, self._block_number)
                if head >= next_block:
                    op = "get_logs"
                    lo, hi = next_block, head
                    events = await self._call(
                        op, lambda: list(self.replay(from_block=lo, to_block=hi))
                    )
                    for ev in events:
                        yield ev
                    next_block = head + 1
                    if on_advance is not None:
                        on_advance(next_block)
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except Exception as exc:  # noqa: BLE001 - the retry wall's whole job
                RPC_RETRIES.inc(op=op)
                delay = self._rng.uniform(0, backoff)
                log.warning(
                    "chain rpc %s failed (%r); retrying in %.2fs", op, exc, delay
                )
                await asyncio.sleep(delay)
                backoff = min(backoff * 2, self.retry.cap_s)
                continue
            backoff = self.retry.base_s
            await asyncio.sleep(poll_interval)


class DevChainRpc:
    """RPC backend over the in-process dev chain (evm/devchain.py)."""

    def __init__(self, chain):
        self._chain = chain

    def block_number(self) -> int:
        return self._chain.eth_block_number()

    def get_logs(self, address, from_block, to_block, topic0):
        return self._chain.eth_get_logs(
            address=address, from_block=from_block, to_block=to_block, topic0=topic0
        )


class _Web3Rpc:  # pragma: no cover - web3 not in image
    """RPC backend over web3.py, normalizing HexBytes topics to ints."""

    class _Log:
        def __init__(self, raw):
            self.topics = [int.from_bytes(bytes(t), "big") for t in raw["topics"]]
            self.data = bytes(raw["data"])

    def __init__(self, node_url: str):
        from web3 import Web3  # type: ignore

        self._w3 = Web3(Web3.HTTPProvider(node_url))

    def block_number(self) -> int:
        return self._w3.eth.block_number

    def get_logs(self, address, from_block, to_block, topic0):
        query = {
            "fromBlock": from_block,
            "address": self._w3.to_checksum_address(f"0x{address:040x}"),
            "topics": [f"0x{topic0:064x}"],
        }
        if to_block is not None:
            query["toBlock"] = to_block
        return [self._Log(raw) for raw in self._w3.eth.get_logs(query)]


class Web3EventSource(ChainEventSource):
    """Live AttestationCreated stream over JSON-RPC via web3.py."""

    def __init__(self, node_url: str, contract_address: str):
        try:
            rpc = _Web3Rpc(node_url)
        except ImportError as e:  # pragma: no cover - web3 not in image
            raise RuntimeError(
                "web3.py is not installed; use a FixtureEventSource or a "
                "DevChainRpc-backed ChainEventSource, or install web3 for "
                "live chain ingestion"
            ) from e
        super().__init__(rpc, contract_address)


def have_web3() -> bool:
    try:
        import web3  # noqa: F401

        return True
    except ImportError:
        return False
