"""The node daemon: HTTP API + epoch timer + chain-event ingestion.

Rebuild of server/src/main.rs:121-187 — the same three-way event loop as
asyncio tasks instead of tokio ``select!``:

- an HTTP listener serving ``GET /score`` → latest ProofRaw JSON
  (main.rs:85-119), keep-alive disabled like the reference;
- an epoch ticker with *Skip* missed-tick semantics (main.rs:129-131): a
  proof run longer than the interval drops ticks instead of backlogging;
- an AttestationCreated stream feeding ``Manager.add_attestation``.

Run: ``python -m protocol_tpu.node.server --config data/protocol-config.json``
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from dataclasses import dataclass, field

import json

from .. import chaos
from ..obs import (
    DRIFT,
    JOURNAL,
    LINEAGE,
    SLO_ENGINE,
    TIMELINE,
    TRACER,
    configure_logging,
    fleet_prometheus_text,
    prometheus_text,
)
from ..obs import metrics as obs_metrics
from ..obs.export import PROMETHEUS_CONTENT_TYPE, profile_session
from ..utils.telemetry import TELEMETRY
from .config import ProtocolConfig
from .epoch import Epoch
from .errors import EigenError
from .ethereum import FixtureEventSource
from .manager import Manager, ManagerConfig

log = logging.getLogger("protocol_tpu.node")

chaos.declare("checkpoint.post_save", "snapshot landed, before the WAL truncates")

BAD_REQUEST = 400
NOT_FOUND = 404
TOO_MANY_REQUESTS = 429
INTERNAL_SERVER_ERROR = 500
SERVICE_UNAVAILABLE = 503

_STATUS_TEXT = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}

#: Largest accepted POST body (an attestation payload is a few KiB).
_MAX_BODY = 1 << 20


def _backend_tag(manager: Manager) -> str:
    """Wire tag for the proof backend, declared by the Prover class
    itself — so clients dispatch on an explicit field instead of
    sniffing proof bytes.  Unknown provers serve an empty tag and
    clients fall back to shape detection."""
    return getattr(manager.prover, "wire_tag", "")


#: /healthz verdicts, in severity order (the gauge value is the index).
HEALTH_VERDICTS = ("ok", "degraded", "failed")


def node_health(node: "Node | None") -> tuple[int, dict]:
    """Aggregate component state into the load-balancer verdict:

    - ``ok``      → 200: epochs ticking, planes up, SLOs green;
    - ``degraded``→ 200: serving, but warming up (no epoch yet), an
      SLO is violating, or a plane shows backpressure/failures —
      readable by dashboards, still in rotation;
    - ``failed``  → 503: the epoch loop stalled past 3 intervals, or a
      configured plane never started — pull this node.

    Works without a node (``handle_request`` in tests/tools): the
    epoch-cadence and SLO components still evaluate from the
    process-global timeline/engine; plane components report absent."""
    problems: list[str] = []
    degraded: list[str] = []
    interval = float(node.config.epoch_interval) if node is not None else None
    since = TIMELINE.seconds_since_last_tick()
    latest = TIMELINE.latest_epoch()
    epoch_comp: dict = {
        "latest": latest,
        "seconds_since_last_tick": round(since, 3) if since is not None else None,
        "interval": interval,
    }
    if latest is None:
        degraded.append("no-epoch-yet")
    elif interval is not None and since is not None and since > 3.0 * interval:
        problems.append("epoch-loop-stalled")
    components: dict = {"epoch": epoch_comp}

    slo = SLO_ENGINE.last()
    components["slo"] = {
        "ok": bool(slo.get("ok", True)),
        "violating": sorted(
            name
            for name, o in slo.get("objectives", {}).items()
            if not o.get("ok", True)
        ),
    }
    if not components["slo"]["ok"]:
        degraded.append("slo-violating")

    if node is not None:
        # Boot recovery (node/wal.py): "recovering" while the WAL tail
        # replays — the load balancer keeps the node out of rotation's
        # hard-fail path but dashboards see exactly where boot is.
        recovering = node._recovery.get("state") == "recovering"
        components["recovery"] = dict(node._recovery)
        if recovering:
            degraded.append("recovering")
        ingest = node._ingest
        components["ingest"] = {
            "configured": bool(node.config.ingest_plane),
            "started": ingest is not None,
            "pending": ingest.stats()["pending"] if ingest is not None else None,
        }
        if (
            node.config.ingest_plane
            and ingest is None
            and node._server is not None
            and not recovering
        ):
            problems.append("ingest-plane-not-started")
        plane = node._prover_plane
        if plane is not None:
            stats = plane.stats()
            components["prover"] = {
                "configured": True,
                "generation": plane.pool.generation,
                "queue_depth": stats["queue_depth"],
                "pending": stats["pending"],
                "failed": stats["failed"],
                "lag_epochs": obs_metrics.PROOF_LAG_EPOCHS.value(),
            }
            if stats["failed"] > 0:
                degraded.append("proof-jobs-failed")
        else:
            components["prover"] = {"configured": bool(node.config.async_prover)}
        components["pipeline"] = {
            "configured": bool(node.config.epoch_pipeline),
            "queue_depth": obs_metrics.PIPELINE_QUEUE_DEPTH.value(),
        }
        if node.config.fleet_dir:
            # Pod heartbeat check (ISSUE 19): stamp our own snapshot
            # (the heartbeat other hosts' TTL reads) and re-scan the
            # exchange with the staleness TTL, so a silently dead
            # sibling degrades THIS host's /healthz before any gloo
            # collective hangs waiting for it.
            import os as _os

            from ..obs.fleet import FLEET, load_directory, publish_snapshot

            try:
                publish_snapshot(node.config.fleet_dir, _os.getpid())
                load_directory(
                    node.config.fleet_dir,
                    skip_pid=_os.getpid(),
                    max_age_s=node.config.fleet_stale_after_s or None,
                )
            except OSError:
                pass
            stale = FLEET.stale()
            components["fleet"] = {
                "configured": True,
                "sources": FLEET.sources(),
                "stale": {s: round(a, 3) for s, a in sorted(stale.items())},
            }
            if stale:
                degraded.append("fleet-stale-sources")

    if problems:
        verdict = "failed"
    elif degraded:
        verdict = "degraded"
    else:
        verdict = "ok"
    obs_metrics.HEALTH_STATUS.set(HEALTH_VERDICTS.index(verdict))
    status = SERVICE_UNAVAILABLE if verdict == "failed" else 200
    return status, {
        "status": verdict,
        "problems": problems,
        "degraded": degraded,
        "components": components,
    }


def handle_request(
    method: str, path: str, manager: Manager, plane=None, node=None
) -> tuple[int, str]:
    """Route one request (main.rs:85-119 + the rebuild's observability
    surface).  Returns (status, body).  ``plane`` is the node's async
    :class:`~protocol_tpu.prover.plane.ProvingPlane` (or None in
    sequential-prove mode) — the ``/proof`` lifecycle source; ``node``
    is the owning :class:`Node` for the component-state surfaces
    (``/healthz``, the fleet scrape's directory exchange) and may be
    None for manager-only embedding."""
    if method == "GET" and path.startswith("/proof/"):
        # /proof/<epoch> (or /proof/latest): the proof itself when it
        # landed, else the job's lifecycle state (queued / proving /
        # failed / superseded) — the async proving plane's contract
        # that every epoch resolves explicitly, never silently.
        arg = path.removeprefix("/proof/")
        if arg == "latest":
            cached = manager.cached_proofs
            if cached:
                arg = str(max(cached, key=lambda e: e.number).number)
            elif plane is not None and plane.latest_epoch() is not None:
                arg = str(plane.latest_epoch())
            else:
                return NOT_FOUND, json.dumps({"error": "no proofs yet"})
        try:
            epoch_number = int(arg)
        except ValueError:
            return BAD_REQUEST, "InvalidQuery"
        proof = manager.cached_proofs.get(Epoch(epoch_number))
        status_obj = plane.status(epoch_number) if plane is not None else None
        if proof is not None:
            body = json.loads(
                proof.to_raw(backend=_backend_tag(manager)).to_json()
            )
            body["epoch"] = epoch_number
            body["state"] = "proved"
            if status_obj is not None:
                body.update(status_obj.to_dict())
            return 200, json.dumps(body)
        if status_obj is not None:
            return 200, json.dumps(status_obj.to_dict())
        return NOT_FOUND, json.dumps(
            {"epoch": epoch_number, "error": "no proof or proof job"}
        )
    if method == "GET" and path == "/score":
        try:
            proof = manager.get_last_proof()
        except EigenError as e:
            log.info("score query failed: %s", e)
            return BAD_REQUEST, "InvalidQuery"
        return 200, proof.to_raw(backend=_backend_tag(manager)).to_json()
    if method == "GET" and path.split("?", 1)[0] == "/aggregate":
        # /aggregate?epochs=3,7 — one-pairing batch verification of
        # cached epoch SNARKs (the aggregator surface the reference
        # never finished wiring).
        from urllib.parse import parse_qs, urlsplit

        try:
            qs = parse_qs(urlsplit(path).query)
            epochs = [
                Epoch(int(x))
                for x in qs.get("epochs", [""])[0].split(",")
                if x != ""
            ]
            if not epochs:
                return BAD_REQUEST, "InvalidQuery"
            ok, acc = manager.aggregate_proofs(epochs)
        except (EigenError, ValueError) as e:
            log.info("aggregate query failed: %s", e)
            return BAD_REQUEST, "InvalidQuery"
        body = {
            "ok": bool(ok),
            "epochs": [e.number for e in epochs],
            "accumulator": acc.to_bytes().hex() if acc is not None else None,
        }
        return 200, json.dumps(body)
    if method == "GET" and path == "/status":
        status = {
            "attestations": len(manager.attestations),
            "cached_proofs": len(manager.cached_proofs),
            "latest_epoch": max(
                (e.number for e in manager.cached_proofs), default=None
            ),
            "backend": manager.config.backend,
            "telemetry": TELEMETRY.snapshot(),
            "traced_epochs": TRACER.epochs(),
        }
        return 200, json.dumps(status)
    if method == "GET" and path == "/metrics":
        # Prometheus exposition format; _handle_conn switches the
        # content type to text/plain for this path.  Never touches
        # device state — purely the host-side registry snapshot.
        return 200, prometheus_text()
    if method == "GET" and path == "/metrics/fleet":
        # The fleet-merged exposition: this process's registry plus
        # every aggregated worker snapshot (and, with a configured
        # fleet_dir, every sibling process in a jax.distributed run),
        # each series stamped with a `process` label.
        if node is not None and node.config.fleet_dir:
            import os as _os

            from ..obs.fleet import load_directory, publish_snapshot

            publish_snapshot(node.config.fleet_dir, _os.getpid())
            load_directory(
                node.config.fleet_dir,
                skip_pid=_os.getpid(),
                max_age_s=node.config.fleet_stale_after_s or None,
            )
        return 200, fleet_prometheus_text()
    if method == "GET" and path == "/slo":
        # Evaluate-on-scrape: the engine also evaluates at every epoch
        # tick, so the burn windows advance with or without scrapers.
        return 200, json.dumps(SLO_ENGINE.evaluate())
    if method == "GET" and path == "/healthz":
        status, body = node_health(node)
        return status, json.dumps(body)
    if method == "GET" and path.startswith("/timeline/"):
        # /timeline/<epoch> (or /timeline/latest): the epoch's joined
        # record — ingest watermarks, phase durations, converge stats,
        # proof lifecycle, freshness summary — merged at write time by
        # every subsystem that touched the epoch.
        arg = path.removeprefix("/timeline/")
        if arg == "latest":
            latest = TIMELINE.latest_epoch()
            if latest is None:
                return NOT_FOUND, json.dumps({"error": "no epochs yet"})
            arg = str(latest)
        try:
            epoch_number = int(arg)
        except ValueError:
            return BAD_REQUEST, "InvalidQuery"
        record = TIMELINE.get(epoch_number)
        if record is None:
            return NOT_FOUND, json.dumps(
                {
                    "error": f"no timeline for epoch {epoch_number}",
                    "epochs": TIMELINE.epochs(),
                }
            )
        return 200, json.dumps(record)
    if method == "GET" and path == "/scores/drift":
        # Score-integrity surface (obs/watchers.py): L1/L∞ drift of
        # the last landed fixed point vs its predecessor, top movers,
        # and the residual-stall flag.  Empty object before the first
        # converged epoch.
        return 200, json.dumps(DRIFT.last())
    if method == "GET" and path.split("?", 1)[0] == "/debug/flight":
        # Flight-recorder tail: /debug/flight?n=200 (default: the full
        # in-memory ring) as a JSONL body, newest last — the same
        # format the crash dump writes, so tooling reads both.
        from urllib.parse import parse_qs, urlsplit

        try:
            qs = parse_qs(urlsplit(path).query)
            n = int(qs.get("n", ["-1"])[0])
        except ValueError:
            return BAD_REQUEST, "InvalidQuery"
        events = JOURNAL.tail(None if n < 0 else n)
        return 200, "".join(json.dumps(e) + "\n" for e in events)
    if method == "GET" and path.startswith("/trace/pod"):
        # /trace/pod/<epoch> (or /trace/pod[/latest]): the stitched
        # pod epoch trace — N hosts' span trees clock-aligned onto one
        # timeline with per-phase skew, barrier-arrival spread, and
        # phase attribution (obs/podtrace.py).  Serves the stitch
        # store; a miss with a configured fleet_dir stitches on demand
        # from the published per-host files (any host can answer, not
        # just the host that stitched at tick time).
        from ..obs import podtrace

        arg = path.removeprefix("/trace/pod").lstrip("/")
        fleet_dir = (
            node.config.fleet_dir
            if node is not None and node.config.fleet_dir
            else None
        )
        if arg in ("", "latest"):
            # "latest" is the newer of the local stitch store and the
            # published exchange — a host whose store lags (it is not
            # the tick-time stitcher) must not serve a stale epoch.
            latest = podtrace.POD_TRACES.latest_epoch()
            if fleet_dir is not None:
                published = podtrace.directory_epochs(fleet_dir)
                if published and (latest is None or published[-1] > latest):
                    latest = published[-1]
            if latest is None:
                return NOT_FOUND, json.dumps({"error": "no pod epochs stitched yet"})
            arg = str(latest)
        try:
            epoch_number = int(arg)
        except ValueError:
            return BAD_REQUEST, "InvalidQuery"
        stitched = podtrace.POD_TRACES.get(epoch_number)
        if stitched is None and fleet_dir is not None:
            stitched = podtrace.stitch_epoch(fleet_dir, epoch_number)
        if stitched is None:
            return NOT_FOUND, json.dumps(
                {"error": f"no pod trace for epoch {epoch_number}",
                 "stitched_epochs": podtrace.POD_TRACES.epochs()}
            )
        return 200, json.dumps(stitched)
    if method == "GET" and path.startswith("/trace/"):
        # /trace/<epoch> (or /trace/latest): the epoch's span tree as
        # nested JSON (epoch_tick → prove/build_graph/plan/converge/
        # checkpoint), serialized once at tick end — serving it is a
        # dict copy, no sync with the epoch executor.
        arg = path.removeprefix("/trace/")
        if arg == "latest":
            latest = TRACER.latest_epoch()
            if latest is None:
                return NOT_FOUND, json.dumps({"error": "no epochs traced yet"})
            arg = str(latest)
        try:
            epoch_number = int(arg)
        except ValueError:
            return BAD_REQUEST, "InvalidQuery"
        trace = TRACER.get_trace(epoch_number)
        if trace is None:
            return NOT_FOUND, json.dumps(
                {"error": f"no trace for epoch {epoch_number}",
                 "traced_epochs": TRACER.epochs()}
            )
        return 200, json.dumps(trace)
    return NOT_FOUND, "InvalidRequest"


@dataclass
class Node:
    config: ProtocolConfig
    manager: Manager
    _server: asyncio.AbstractServer | None = field(default=None, repr=False)
    _tasks: list = field(default_factory=list, repr=False)
    #: Double-buffered epoch engine (config.epoch_pipeline): host
    #: stages of epoch k+1 overlap device converge + proving of epoch
    #: k; None in sequential mode.
    _pipeline: object | None = field(default=None, repr=False)
    #: Admission plane (config.ingest_plane, on by default): bounded
    #: intake + sharded dedup + rate limits + the verify worker pool in
    #: front of the Manager; POST /attestation and the chain-event
    #: stream both route through it.  None = legacy direct ingest.
    _ingest: object | None = field(default=None, repr=False)
    #: Async proving plane (config.async_prover): epoch ticks enqueue
    #: the SNARK; a spawn-based prover pool drains it and landed proofs
    #: install into the Manager's cache from a dispatcher thread.
    #: None = the sequential prove-per-tick path.
    _prover_plane: object | None = field(default=None, repr=False)
    #: Write-ahead attestation log (config.wal + checkpoint_dir); also
    #: reachable as ``manager.wal`` once recovery attaches it.
    _wal: object | None = field(default=None, repr=False)
    #: Boot-recovery state machine surfaced as the /healthz
    #: ``recovery`` component: ``disabled`` (no checkpoint dir),
    #: ``recovering`` (checkpoint load + WAL replay in flight — the
    #: HTTP socket is already up so the walk is scrapeable), ``ok``
    #: (plus the recovery report: checkpoint epoch, fallbacks, records
    #: replayed, seconds).
    _recovery: dict = field(
        default_factory=lambda: {"state": "disabled"}, repr=False
    )

    @classmethod
    def from_config(cls, config: ProtocolConfig) -> "Node":
        manager = Manager(
            ManagerConfig(
                backend=config.trust_backend,
                prover=config.prover,
                srs_path=config.srs_path,
                warm_start=config.warm_start,
                plan_delta_max_churn=config.plan_delta_max_churn,
            )
        )
        return cls(config=config, manager=manager)

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = request_line.decode("latin1").split()
            if len(parts) < 2:
                status, body = BAD_REQUEST, "InvalidRequest"
            else:
                # Drain headers (connection: close semantics), bounded
                # against slow-loris: at most 100 header lines within
                # one 10s total deadline.  content-length is the one
                # header the ingest POST route needs.
                async def drain_headers() -> int:
                    length = 0
                    for _ in range(100):
                        line = await reader.readline()
                        if line in (b"\r\n", b"\n", b""):
                            return length
                        name, _, value = line.decode("latin1").partition(":")
                        if name.strip().lower() == "content-length":
                            try:
                                length = int(value.strip())
                            except ValueError:
                                length = 0
                    return length

                content_length = await asyncio.wait_for(drain_headers(), timeout=10)
                if parts[0] == "POST" and parts[1].split("?", 1)[0] == "/attestation":
                    # Admission-plane intake: bounded body read, then a
                    # non-blocking submit whose verdict (or 429 shed)
                    # is awaited without holding the event loop.
                    payload_in = b""
                    if 0 < content_length <= _MAX_BODY:
                        payload_in = await asyncio.wait_for(
                            reader.readexactly(content_length), timeout=10
                        )
                    status, body = await self._handle_ingest_post(parts[1], payload_in)
                elif parts[1].split("?", 1)[0] == "/aggregate":
                    # Aggregation runs verify_deferred per member plus a
                    # pairing — seconds of crypto that must not stall the
                    # event loop (reference stance: heavy work off-loop,
                    # like _epoch_tick).
                    status, body = await asyncio.get_running_loop().run_in_executor(
                        None,
                        handle_request,
                        parts[0],
                        parts[1],
                        self.manager,
                        self._prover_plane,
                        self,
                    )
                else:
                    status, body = handle_request(
                        parts[0], parts[1], self.manager, self._prover_plane, self
                    )
            payload = body.encode()
            content_type = (
                PROMETHEUS_CONTENT_TYPE
                if len(parts) >= 2
                and parts[1].split("?", 1)[0] in ("/metrics", "/metrics/fleet")
                else "application/json"
            )
            writer.write(
                (
                    f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'Unknown')}\r\n"
                    f"content-type: {content_type}\r\n"
                    f"content-length: {len(payload)}\r\n"
                    f"connection: close\r\n\r\n"
                ).encode()
                + payload
            )
            await writer.drain()
        except (asyncio.TimeoutError, asyncio.IncompleteReadError, ConnectionError) as e:
            log.warning("error serving connection: %r", e)
        finally:
            writer.close()

    async def _handle_ingest_post(self, path: str, payload: bytes) -> tuple[int, str]:
        """POST /attestation[?nonce=N]: decode the wire payload and
        route it through the admission plane.  Verdict → status: 200
        accepted, 400 rejected (reason in the body), 429 shed (the
        submit queue is full — back off and retry).  Without a plane
        (config.ingest_plane=false) the legacy direct path runs in an
        executor so signature checks never block the event loop."""
        from urllib.parse import parse_qs, urlsplit

        from .attestation import AttestationData

        n = self.manager.config.num_neighbours
        try:
            qs = parse_qs(urlsplit(path).query)
            nonce = int(qs["nonce"][0]) if "nonce" in qs else None
            att = AttestationData.from_bytes(payload, n).to_attestation(n)
        except (ValueError, KeyError, IndexError):
            return BAD_REQUEST, json.dumps(
                {"accepted": False, "reason": "malformed-payload"}
            )
        if self._ingest is None:
            result = await asyncio.get_running_loop().run_in_executor(
                None, self.manager.add_attestation, att
            )
        else:
            from ..ingest.plane import SHED_REASON

            future = self._ingest.submit(att, nonce=nonce, raw=payload)
            try:
                result = await asyncio.wait_for(asyncio.wrap_future(future), timeout=30)
            except asyncio.TimeoutError:
                return INTERNAL_SERVER_ERROR, json.dumps(
                    {"accepted": False, "reason": "verdict-timeout"}
                )
            if not result.accepted and result.reason == SHED_REASON:
                return TOO_MANY_REQUESTS, json.dumps(
                    {"accepted": False, "reason": result.reason}
                )
        status = 200 if result.accepted else BAD_REQUEST
        return status, json.dumps(
            {"accepted": result.accepted, "reason": result.reason}
        )

    def _epoch_tick(self, epoch: Epoch) -> None:
        """One epoch of work: the fixed-set proof (reference parity) and,
        on a TPU backend, open-graph convergence at scale; snapshots the
        assembled graph + scores when a checkpoint dir is configured.

        The whole tick runs under the epoch's trace root
        (``epoch_tick`` → prove → build_graph → plan → converge →
        checkpoint): spans open and close only at these host
        boundaries, so the tree costs a few context-manager entries per
        epoch and nothing inside the jit'd loop."""
        with TRACER.epoch(epoch.number):
            if self._prover_plane is None:
                # Sequential semantics prove the cache as of tick
                # start — bind the lineage cohort now so this tick's
                # proof completes exactly what it attests to.
                LINEAGE.bind_epoch(epoch.number)
                self._prove_or_enqueue(epoch)
            scores = None
            if self.manager.config.backend != "native-cpu":
                # Opt-in jax.profiler session (ProtocolConfig.profile_dir):
                # a device-timeline capture of exactly the convergence
                # region, epoch-tagged subdirectories so ticks don't
                # overwrite each other.
                profile_dir = (
                    f"{self.config.profile_dir}/epoch_{epoch.number}"
                    if self.config.profile_dir
                    else None
                )
                with TELEMETRY.timer("epoch.converge_open_graph"):
                    with profile_session(profile_dir):
                        result = self.manager.converge_epoch(epoch, alpha=0.1)
                scores = result.scores
                log.info(
                    "epoch %s: open graph n=%d converged in %d iters (resid %.2e) on %s",
                    epoch,
                    len(result.scores),
                    result.iterations,
                    result.residual,
                    result.backend,
                )
            self._checkpoint_epoch(epoch, scores)
            if self._prover_plane is not None:
                # Async mode enqueues at tick END: the job snapshot is
                # the tick's final state, and the prove starts once the
                # tick's own CPU burst (converge + checkpoint) is done
                # — on a small host the worker gets the inter-tick gap
                # instead of time-slicing against converge.
                self._prove_or_enqueue(epoch)
        TELEMETRY.count("epochs")
        obs_metrics.EPOCHS_TOTAL.inc()
        # Continuous SLO evaluation: every landed tick advances the
        # burn windows (scrapes of GET /slo evaluate too).
        SLO_ENGINE.evaluate()
        if self._ingest is not None:
            # Epoch-aligned dedup eviction: "recent" replays are those
            # inside the horizon that could still perturb convergence.
            self._ingest.advance_epoch()

    def _prove_or_enqueue(self, epoch: Epoch) -> None:
        """The epoch tick's proof step.  Sequential mode runs the full
        prove inline (reference semantics: a proof per tick before the
        tick ends).  With the async proving plane, the tick only
        *snapshots* the statement and enqueues it — microseconds — and
        the SNARK runs in a prover worker while the epoch loop moves
        on; the landed proof installs into the cache from a dispatcher
        thread and its attribution grafts back into this epoch's
        trace."""
        if self._prover_plane is None:
            with TELEMETRY.timer("epoch.calculate_proofs"), TRACER.span("prove"):
                self.manager.calculate_proofs(epoch)
            return
        with TRACER.span("prove_enqueue"):
            if chaos.ACTIVE:
                chaos.fire("prover.pre_enqueue")
            status = self._prover_plane.submit(self.manager.build_proof_job(epoch))
        log.info("epoch %s: proof job enqueued (state=%s)", epoch, status.state)

    def _checkpoint_epoch(self, epoch: Epoch, scores) -> None:
        """Snapshot the epoch (graph + scores + proof + windowed plan +
        the peer-hash column that keys the warm-start remap) when a
        checkpoint dir is configured; shared by the sequential tick and
        the pipelined device stage."""
        if not self.config.checkpoint_dir:
            return
        from .checkpoint import CheckpointStore

        # Persist exactly the graph the scores were computed on
        # (ingest keeps mutating the attestation cache concurrently;
        # a rebuilt graph could have more peers than scores).  The WAL
        # watermark pairs with the graph: for a converged epoch it is
        # the one read before that graph's assembly; for the fixed-set
        # path it is read before the fresh build below.
        wal = self.manager.wal
        if scores is not None:
            graph = self.manager.last_graph
            wal_seq = self.manager.checkpoint_watermark()
        else:
            wal_seq = wal.applied_watermark() if wal is not None else None
            graph = self.manager.build_graph()
        # Async proving: the proof usually hasn't landed by checkpoint
        # time (that's the point) — snapshot without it; the proof is
        # re-derivable from the attestation stream and served from the
        # cache once the plane lands it.
        try:
            proof_json = (
                self.manager.get_proof(epoch)
                .to_raw(backend=_backend_tag(self.manager))
                .to_json()
            )
        except EigenError:
            proof_json = None
        with TELEMETRY.timer("epoch.checkpoint"), TRACER.span("checkpoint"):
            store = CheckpointStore(self.config.checkpoint_dir)
            store.save(
                epoch,
                graph,
                scores,
                proof_json,
                # tpu-windowed only: the one-time bucketing plan, so
                # a reboot revalidates instead of rebuilding it.
                plan=self.manager.window_plan,
                peer_hashes=(
                    self.manager.last_peer_hashes if scores is not None else None
                ),
                wal_seq=wal_seq,
                # The cache itself (senders' last wire rows): the
                # recovery state graph columns can't reconstruct, and
                # the truncated WAL no longer holds.  A superset of
                # the graph's inputs is safe; the WAL tail replays the
                # rest idempotently.
                attestations=self.manager.snapshot_attestations(),
            )
            if chaos.ACTIVE:
                # Snapshot landed, WAL not yet truncated: a crash here
                # must replay idempotently (the dedup'd cache absorbs
                # re-applied records the snapshot already holds).
                chaos.fire("checkpoint.post_save")
            if wal is not None:
                # Truncate through the OLDEST retained snapshot's
                # watermark, not this epoch's: a torn latest snapshot
                # falls back epoch by epoch, and the fallback target
                # must still find every record it lacks in the log.
                floor = store.retained_wal_floor()
                if floor is not None:
                    wal.truncate_through(floor)

    def _pipeline_device_stage(self, prepared):
        """Device half of a pipelined epoch: prove → converge (from the
        prepared graph/warm seed) → checkpoint, under the epoch's trace
        root.  Host assembly already happened in
        ``Manager.prepare_epoch`` on the submit side — by the time this
        runs, the next epoch's host stage may already be executing."""
        epoch = prepared.epoch
        with TRACER.epoch(epoch.number):
            if self._prover_plane is None:
                LINEAGE.bind_epoch(epoch.number)
                self._prove_or_enqueue(epoch)
            scores = None
            result = None
            if self.manager.config.backend != "native-cpu":
                profile_dir = (
                    f"{self.config.profile_dir}/epoch_{epoch.number}"
                    if self.config.profile_dir
                    else None
                )
                with TELEMETRY.timer("epoch.converge_open_graph"):
                    with profile_session(profile_dir):
                        result = self.manager.converge_prepared(prepared, alpha=0.1)
                scores = result.scores
                log.info(
                    "epoch %s: open graph n=%d converged in %d iters (resid %.2e) on %s%s",
                    epoch,
                    len(result.scores),
                    result.iterations,
                    result.residual,
                    result.backend,
                    " [warm]" if prepared.t0 is not None else "",
                )
            self._checkpoint_epoch(epoch, scores)
            if self._prover_plane is not None:
                # Tick-end enqueue (see _epoch_tick): the prove gets
                # the inter-tick gap, never this tick's core budget.
                self._prove_or_enqueue(epoch)
        TELEMETRY.count("epochs")
        obs_metrics.EPOCHS_TOTAL.inc()
        SLO_ENGINE.evaluate()
        if self._ingest is not None:
            self._ingest.advance_epoch()
        return result

    async def _epoch_loop(self, warm=None):
        if warm is not None:
            await warm  # boot keygen must land before the first prove
        interval = self.config.epoch_interval
        last_epoch: int | None = None
        while True:
            await asyncio.sleep(Epoch.secs_until_next_epoch(interval))
            epoch = Epoch.current_epoch(interval)
            # Skip semantics drop boundaries a long tick overran; make
            # the drops countable instead of silent (the gap between
            # consecutively processed epochs is exactly the drop count).
            if last_epoch is not None and epoch.number > last_epoch + 1:
                dropped = epoch.number - last_epoch - 1
                obs_metrics.EPOCH_TICKS_DROPPED.inc(dropped)
                log.warning(
                    "epoch %s: dropped %d epoch tick(s) (previous tick overran)",
                    epoch,
                    dropped,
                )
            last_epoch = epoch.number
            try:
                if self._pipeline is not None:
                    # Pipelined: only the host stage (graph assembly,
                    # warm remap, plan delta) runs here; the device
                    # stage overlaps with the NEXT boundary's host
                    # work.  A busy device coalesces queued epochs
                    # instead of dropping ticks.
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._pipeline.submit, epoch
                    )
                    log.info("epoch %s: submitted to pipeline", epoch)
                else:
                    # Proving may outlast the interval; the next sleep
                    # targets the *next* boundary from now = Skip
                    # semantics.
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._epoch_tick, epoch
                    )
                    log.info("epoch %s: proof cached", epoch)
            except Exception as e:
                log.error("epoch %s: %r", epoch, e)
                JOURNAL.record(
                    "anomaly", what="epoch-tick-failed", epoch=epoch.number,
                    error=repr(e),
                )

    def _event_source(self):
        if self.config.event_fixture:
            return FixtureEventSource(self.config.event_fixture)
        from .ethereum import Web3EventSource, have_web3

        if have_web3():
            return Web3EventSource(
                self.config.ethereum_node_url, self.config.as_contract_address
            )
        log.info("no event fixture configured and web3 not installed; ingest idle")
        return None

    async def _event_loop(self):
        from .ethereum import ChainEventSource

        source = self._event_source()
        if source is None:
            return
        stream_kwargs = {}
        if isinstance(source, ChainEventSource) and self.config.checkpoint_dir:
            # Resumable replay: the block cursor rides the checkpoint
            # manifest, so a restart resumes the chain replay where it
            # left off instead of from block 0 (the WAL already holds
            # everything accepted since the last snapshot).
            from .checkpoint import CheckpointStore

            store = CheckpointStore(self.config.checkpoint_dir)
            stream_kwargs = {
                "cursor": store.block_cursor(),
                "on_advance": store.save_block_cursor,
            }
        async for event in source.stream(**stream_kwargs):
            try:
                from .attestation import AttestationData

                att_data = AttestationData.from_bytes(
                    event.val, self.manager.config.num_neighbours
                )
                att = att_data.to_attestation(self.manager.config.num_neighbours)
                if self._ingest is not None:
                    # Non-blocking: the plane owns dedup/rate/verify;
                    # the verdict lands in a callback so a verify
                    # backlog never stalls the event stream.
                    future = self._ingest.submit(att, raw=event.val)
                    future.add_done_callback(
                        lambda f, creator=event.creator: self._log_ingest(f, creator)
                    )
                else:
                    result = self.manager.add_attestation(att)
                    if result.accepted:
                        log.info("attestation ingested from %s", event.creator)
                    else:
                        log.warning(
                            "rejected attestation event: %s", result.reason
                        )
            except (EigenError, ValueError) as e:
                log.warning("rejected attestation event: %s", e)

    @staticmethod
    def _log_ingest(future, creator: str) -> None:
        result = future.result()
        if result.accepted:
            log.info("attestation ingested from %s", creator)
        else:
            log.warning(
                "rejected attestation event from %s: %s", creator, result.reason
            )

    def _wal_dir(self) -> str:
        return self.config.wal_dir or f"{self.config.checkpoint_dir}/wal"

    def _recover_state(self) -> None:
        """Boot recovery (node/wal.py): newest *valid* checkpoint (torn
        or corrupt snapshots fall back epoch by epoch) → warm state →
        WAL tail replayed through ``apply_verified`` → WAL attached so
        new accepts append.  Runs in an executor while the HTTP socket
        already serves — /healthz reports the ``recovering`` component
        state until this returns.  The chain replay (the source of
        truth, main.rs:139-143) still runs afterwards, resuming from
        the persisted block cursor, and overwrites as it catches up."""
        from .checkpoint import CheckpointStore
        from .wal import AttestationWAL, recover

        store = CheckpointStore(self.config.checkpoint_dir)
        wal = None
        if self.config.wal:
            wal = AttestationWAL(
                self._wal_dir(),
                segment_max_bytes=self.config.wal_segment_bytes,
                fsync=self.config.wal_fsync,
            )
        report = recover(self.manager, store, wal)
        self._wal = wal
        self._recovery = {"state": "ok", **report}
        log.info(
            "recovered: checkpoint epoch %s (%d fallback(s)), %d WAL "
            "record(s) replayed (%d torn-tail dropped) in %.3fs",
            report["checkpoint_epoch"],
            report["checkpoint_fallbacks"],
            report["wal_replayed"],
            report["wal_dropped_tail"],
            report["seconds"],
        )

    def _flight_dump_path(self) -> str:
        """Where the flight-recorder ring lands on crash/SIGTERM."""
        if self.config.journal_path:
            return str(self.config.journal_path) + ".dump"
        return "FLIGHT_dump.jsonl"

    def dump_flight_recorder(self, reason: str) -> None:
        """Persist the flight-recorder ring for a post-mortem; never
        raises (this runs on the way down)."""
        try:
            path = JOURNAL.dump(self._flight_dump_path(), reason=reason)
            log.warning("flight recorder dumped to %s (%s)", path, reason)
        except Exception:  # noqa: BLE001 - dying anyway; don't mask the cause
            log.exception("flight recorder dump failed")

    async def start(self) -> None:
        if self.config.journal_path:
            JOURNAL.configure(self.config.journal_path)
        # Fault-injection schedule (chaos tooling only): the env var
        # wins — it is how the crash matrix drives a node it spawns.
        if self.config.chaos and not chaos.ACTIVE:
            chaos.configure(self.config.chaos)
        # Fleet-plane boot: lineage sampling period and the standing
        # SLO objectives (cadence target derives from the configured
        # epoch interval).
        LINEAGE.configure(self.config.lineage_sample_every)
        from ..obs.slo import install_defaults

        install_defaults(
            epoch_interval_s=self.config.epoch_interval,
            freshness_p99_s=self.config.slo_freshness_p99_s,
            proof_lag_p99_s=self.config.slo_proof_lag_p99_s,
        )
        # Pod objectives only where a pod exchange exists: a
        # single-process node must not carry objectives over signals
        # it can never produce (they would read None forever).
        if self.config.fleet_dir:
            from ..obs.slo import install_pod_defaults
            from ..obs.watchers import STRAGGLERS

            install_pod_defaults(
                phase_skew_p99_s=self.config.slo_pod_skew_p99_s,
                heartbeat_max_age_s=self.config.fleet_stale_after_s,
            )
            STRAGGLERS.configure(
                ratio=self.config.straggler_ratio,
                k=self.config.straggler_epochs,
            )
        # SIGTERM post-mortem: dump the event ring before the process
        # dies, so "what was the node doing" survives an orchestrator
        # kill.  Best-effort — platforms without add_signal_handler
        # (or non-main-thread loops) skip it.
        try:
            import signal

            loop = asyncio.get_running_loop()
            loop.add_signal_handler(
                signal.SIGTERM,
                lambda: (
                    self.dump_flight_recorder("SIGTERM"),
                    loop.call_soon(asyncio.ensure_future, self.stop()),
                ),
            )
        except (NotImplementedError, RuntimeError, ValueError):
            pass
        # The HTTP socket comes up BEFORE recovery so /healthz can
        # report the walk: recovering (checkpoint load + WAL replay in
        # an executor, the loop stays responsive) → ok.  The epoch and
        # event loops start strictly after recovery lands.
        self._server = await asyncio.start_server(
            self._handle_conn, self.config.host, self.config.port
        )
        # Initial self-attestations first: the WAL replay below then
        # overwrites any fixed-set row with the newer accepted state
        # (never the reverse — recovery must not resurrect defaults).
        self.manager.generate_initial_attestations()
        if self.config.checkpoint_dir:
            self._recovery = {"state": "recovering"}
            await asyncio.get_running_loop().run_in_executor(
                None, self._recover_state
            )
        if self.config.ingest_plane:
            from ..ingest import IngestPlane, IngestPlaneConfig
            from ..ingest.ratelimit import RateLimitConfig

            # The EigenTrust pre-trust set is the spam anchor: its
            # members bypass rate/spam gates (dedup still applies).
            whitelist = (
                frozenset(
                    (pk.point.x, pk.point.y) for pk in self.manager._group_pks
                )
                if self.config.ingest_whitelist_pretrusted
                else frozenset()
            )
            self._ingest = IngestPlane(
                self.manager,
                IngestPlaneConfig(
                    workers=self.config.ingest_workers,
                    batch_size=self.config.ingest_batch_size,
                    submit_queue_max=self.config.ingest_queue_max,
                    rate=RateLimitConfig(
                        rate=self.config.ingest_rate_rps,
                        burst=self.config.ingest_rate_burst,
                        whitelist=whitelist,
                    ),
                ),
            ).start()
        if self.config.epoch_pipeline:
            from .pipeline import EpochPipeline

            self._pipeline = EpochPipeline(
                self.manager, device_stage=self._pipeline_device_stage
            ).start()
        if self.config.async_prover:
            from ..prover import ProvingPlane, ProvingPlaneConfig

            manager = self.manager

            def _install(result) -> None:
                manager.install_proof(result.epoch, result.pub_ins, result.proof)

            self._prover_plane = ProvingPlane(
                ProvingPlaneConfig(
                    workers=self.config.prover_workers,
                    queue_depth=self.config.prover_queue_max,
                    prove_timeout_s=self.config.prove_timeout_s,
                    omp_threads=self.config.prover_omp_threads,
                ),
                on_proved=_install,
            ).start()
            # Worker SRS/proving-key prewarm runs off-loop with the
            # parent keygen below: the parent writes the disk key cache
            # first (so every worker loads the SAME key), then each
            # worker warms from it — steady-state jobs pay no setup.
            cfg = self.manager.config
            plane = self._prover_plane
            asyncio.get_running_loop().run_in_executor(
                None,
                lambda: (
                    manager.warm_prover(),
                    plane.prewarm(
                        (
                            cfg.num_neighbours,
                            cfg.num_iter,
                            cfg.initial_score,
                            cfg.scale,
                        ),
                        cfg.prover,
                        cfg.srs_path,
                    ),
                ),
            )
        # Boot-time keygen, like the reference's MANAGER_STORE init
        # (server/src/main.rs:70-83): runs in an executor so the HTTP
        # socket comes up while the (cached ~0.7 s / cold ~13 s) PLONK
        # key loads; the epoch loop awaits it before the first tick so
        # proving never pays keygen.
        warm = asyncio.get_running_loop().run_in_executor(
            None, self.manager.warm_prover
        )
        self._tasks = [
            asyncio.create_task(self._epoch_loop(warm)),
            asyncio.create_task(self._event_loop()),
        ]
        log.info("listening on http://%s:%s", self.config.host, self.config.port)

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        if self._ingest is not None:
            # Give in-flight admissions a bounded window to land, then
            # resolve stragglers with reason="shutdown" — off-loop so a
            # saturated verify tier can't stall stop().
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: self._ingest.close(drain=True, timeout=5.0)
            )
        if self._pipeline is not None:
            # Let in-flight device work land (bounded), then stop the
            # worker; run off-loop so a slow prover can't stall stop().
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: self._pipeline.close(drain=True, timeout=30.0)
            )
        if self._prover_plane is not None:
            # Queued/in-flight proofs get a bounded window to land;
            # stragglers resolve with an explicit terminal state.
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: self._prover_plane.close(drain=True, timeout=30.0)
            )
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        if self._wal is not None:
            # Seal the active segment (flush + rotate) — a clean stop
            # leaves no unflushed tail for the next boot to drop.
            self._wal.close()
        # Flush the journal's pending batch so the on-disk JSONL is
        # complete through the stop (the ring itself stays queryable).
        JOURNAL.flush()

    async def run_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="protocol_tpu node")
    parser.add_argument("--config", default="data/protocol-config.json")
    args = parser.parse_args(argv)
    # Single logging entry point (obs.configure_logging): installs the
    # span-aware handler only when the embedding application hasn't
    # configured the root logger already, and stamps every record with
    # the current epoch/span ids either way.
    configure_logging(level=logging.INFO)
    config = ProtocolConfig.load(args.config)
    node = Node.from_config(config)
    try:
        asyncio.run(node.run_forever())
    except (Exception, KeyboardInterrupt):
        # Crash post-mortem: the last thing the process does is
        # persist the flight-recorder ring, then re-raise so the exit
        # code and traceback are unchanged.
        node.dump_flight_recorder("crash")
        raise


if __name__ == "__main__":
    main()
