"""The Manager: attestation cache and per-epoch score/proof computation.

Rebuild of server/src/manager/mod.rs:72-237.  Differences by design:

- protocol constants are a runtime ``ManagerConfig`` instead of crate
  consts (manager/mod.rs:32-38);
- trust convergence runs on a pluggable TrustBackend; the fixed-set path
  keeps the reference's exact field semantics via ``power_iterate`` so
  public inputs match bit-for-bit;
- beyond the fixed set, every valid attestation also feeds an *open
  graph* (peer-id-indexed edge list) that the TPU backends converge at
  scale — the capability the reference caps at N=5.
"""

from __future__ import annotations

import logging
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field as dc_field

import numpy as np

from .. import chaos
from ..analysis.budget import COMM_INVARIANTS, KERNEL_INVARIANTS, NON_JAX_BACKENDS
from ..crypto import calculate_message_hash, group_pks_hash, message_hash_batch
from ..crypto.eddsa import PublicKey, sign, verify as verify_sig
from ..obs import TRACER
from ..obs import metrics as obs_metrics
from ..obs.journal import JOURNAL
from ..obs.lineage import LINEAGE
from ..obs.timeline import TIMELINE
from ..obs.watchers import DRIFT, RECOMPILES
from ..ops.gather_window import WindowPlan
from ..trust.backend import ConvergenceResult, get_backend
from ..trust.graph import TrustGraph
from ..trust.native import power_iterate
from ..zk.proof import PoseidonCommitmentProver, Proof, Prover
from .attestation import Attestation, AttestationData
from .bootstrap import FIXED_SET, INITIAL_SCORE, NUM_ITER, NUM_NEIGHBOURS, SCALE, keyset_from_raw
from .epoch import Epoch
from .errors import EigenError

logger = logging.getLogger(__name__)

chaos.declare("ingest.pre_apply", "an accepted attestation about to enter the cache/WAL")
chaos.declare("epoch.post_converge", "the fixed point landed, before state publish")
chaos.declare("prover.pre_enqueue", "the epoch proof about to be computed or enqueued")

#: Epochs the in-memory proof cache retains for ``GET /proof/<epoch>``
#: (graftlint pass 12, ``unbounded-cache-growth``): ~10 min of history
#: at a 10 s cadence.  Older proofs stay durable in checkpoints; the
#: serving cache must not grow with uptime.
PROOF_CACHE_EPOCHS = 64

#: Epochs of ConvergenceResult (full f32[N] fixed point each) kept for
#: inspection — same ring discipline as ``EpochPipeline.outcomes``.
RESULT_CACHE_EPOCHS = 16


@dataclass
class ManagerConfig:
    num_neighbours: int = NUM_NEIGHBOURS
    num_iter: int = NUM_ITER
    initial_score: int = INITIAL_SCORE
    scale: int = SCALE
    fixed_set: list[tuple[str, str]] = dc_field(default_factory=lambda: list(FIXED_SET))
    #: TrustBackend for the open-graph convergence (trust/backend.py
    #: ladder: native-cpu | tpu-dense | tpu-sparse | tpu-csr |
    #: tpu-windowed | tpu-sharded[:tpu-csr|:tpu-windowed]).
    #: tpu-windowed — and the sharded windowed kernel on real
    #: multi-chip meshes — reuses the manager's cached WindowPlan
    #: across epochs.
    backend: str = "native-cpu"
    #: Run the constraint-system statement check before each proof —
    #: the reference's always-on MockProver sanity pass.
    check_circuit: bool = True
    #: Proof backend: "plonk" (real KZG SNARK, the default — the
    #: reference always emits a real SNARK per epoch,
    #: manager/mod.rs:170-214; ~8.4 s proving at the reference's k=14
    #: circuit size, boot keygen ~13 s amortized by the on-disk key
    #: cache) or "commitment" (fast Poseidon binding for tests and
    #: proof-agnostic tooling).
    prover: str = "plonk"
    #: Optional ceremony SRS file (kzg.Setup.to_bytes format).  Without
    #: it the PLONK prover generates a fresh random setup at boot —
    #: sound only for verifiers who trust this node's keygen.
    srs_path: str | None = None
    #: Proving-kernel backend for the SNARK inner loops
    #: (zk/graft ladder: "native" — ctypes IFMA runtime with Python
    #: fallback — or "graft" — the jit multi-limb MSM/NTT).  Pure
    #: execution selection: proofs are byte-identical either way.
    zk_backend: str = "native"
    #: Seed each epoch's convergence from the previous epoch's fixed
    #: point (renormalized over joined/departed peers) — the fixed
    #: point is start-independent, so this only shortens the path
    #: (sparse power methods converge dramatically faster from a
    #: near-fixed-point start; PERF.md §11).
    warm_start: bool = True
    #: Dirty-row fraction above which the windowed plan cache skips the
    #: delta update and rebuilds from scratch: past this crossover the
    #: per-window repack costs more than the full counting sorts.
    plan_delta_max_churn: float = 0.05
    #: Pod membership (ROADMAP item 1): with ``pod_hosts > 1`` this
    #: node owns only the peers the rendezvous partition assigns to
    #: ``pod_host_id``, and ``prepare_epoch`` clips the plan-delta
    #: churn hint to owned rows — churn on other hosts' peers never
    #: touches this host's plan (``parallel.partition``).
    pod_hosts: int = 1
    pod_host_id: int = 0
    #: Salt namespace for the pod's peer→host partition; every host in
    #: one pod must configure the same value.
    pod_seed: int = 0


@dataclass(frozen=True)
class IngestResult:
    """Per-item bulk-ingest outcome: acceptance plus the structural or
    signature failure reason (the rejection-reason metric's label).
    Truthiness mirrors acceptance so boolean-style callers keep
    working."""

    accepted: bool
    #: Rejection reason code (``eigentrust_attestations_rejected_total``
    #: label) — None when accepted.
    reason: str | None = None

    def __bool__(self) -> bool:
        return self.accepted


@dataclass
class PreparedEpoch:
    """Output of the host stage of one epoch (``Manager.prepare_epoch``):
    everything ``converge_prepared`` needs to dispatch device work, and
    nothing that touches the attestation cache again — so the pipeline
    can prepare epoch k+1 while epoch k still owns the device."""

    epoch: Epoch
    graph: TrustGraph
    #: Peer hash per graph row id, assembly order (the score index map).
    id_order: list[int]
    #: Warm-start seed remapped onto this graph's id space, or None for
    #: a cold start.
    t0: np.ndarray | None
    #: Churn hint for the windowed plan cache (row ids whose out-edges
    #: changed since the cached plan), or None to force plan
    #: revalidation by fingerprint alone.
    delta_rows: np.ndarray | None
    #: The dirty-sender snapshot this graph absorbed — subtracted from
    #: the manager's dirty set only after a successful converge, so a
    #: failed epoch leaves the churn accounting intact.
    dirty_snapshot: set[int]
    #: WAL applied-watermark read *before* graph assembly: every log
    #: record ≤ it is in this graph, so the epoch's checkpoint may
    #: truncate the WAL through it.  None when no WAL is attached.
    wal_seq: int | None = None


class Manager:
    """In-memory attestation store keyed by Poseidon(pk); per-epoch score
    + proof computation with a proof cache (manager/mod.rs:72-78)."""

    def __init__(self, config: ManagerConfig | None = None, prover: Prover | None = None):
        self.config = config or ManagerConfig()
        if prover is None and self.config.prover not in ("plonk", "commitment"):
            raise ValueError(
                f"unknown prover {self.config.prover!r}: "
                "expected 'commitment' or 'plonk'"
            )
        # Lazy: PLONK keygen is ~20 s, so it runs on first use (or
        # explicitly via warm_prover() at node boot, the analog of the
        # reference's MANAGER_STORE init, server/src/main.rs:70-83)
        # rather than on every Manager construction.
        self._prover = prover
        self.cached_proofs: dict[Epoch, Proof] = {}
        self.attestations: dict[int, Attestation] = {}
        self.cached_results: dict[Epoch, ConvergenceResult] = {}
        #: The graph the most recent converge_epoch ran on.
        self.last_graph: TrustGraph | None = None
        #: Bucketing plan for the windowed backends (tpu-windowed and
        #: tpu-sharded:tpu-windowed): built on first converge,
        #: revalidated by fingerprint + layout version each epoch,
        #: seeded from a checkpoint at boot so a reboot skips
        #: reconstruction.
        self.window_plan: WindowPlan | None = None
        #: Warm-start state: the previous epoch's converged scores and
        #: the peer hash per score row (restored from checkpoints at
        #: boot, so warm start survives restart).
        self.last_scores: np.ndarray | None = None
        self.last_peer_hashes: list[int] | None = None
        #: Write-ahead attestation log (node/wal.py), attached by boot
        #: recovery AFTER the tail replay (so replay never re-appends).
        #: Accessed bare from every ingest root — attachment is a
        #: single reference publish, same GIL discipline as the
        #: attestation cache itself.
        self.wal = None
        #: WAL watermark of the last landed epoch — published with the
        #: warm-start pair under the state lock; the checkpoint
        #: truncates the log through it.
        self.last_wal_seq: int | None = None
        #: Guards the cross-epoch mutable state shared between the
        #: pipeline's host stage (prepare_epoch on the submit thread),
        #: the device stage (converge_prepared on the worker thread),
        #: and the ingest threads (apply_verified / bulk ingest): the
        #: dirty-sender set, the warm-start snapshot — scores and their
        #: peer-hash column must be read as a matched pair, or a warm
        #: seed built mid-publish maps scores onto the wrong peers —
        #: and the window-plan cache handoff.  Pinned by graftlint
        #: pass 7 (analysis/concurrency/).
        self._state_lock = threading.Lock()
        # Comm-budget pin check at config time (the kernel-budget
        # analog runs per-converge below): a sharded backend without a
        # COMM_INVARIANTS entry runs with its collective structure and
        # wire volume unpinned — graftlint pass 8 cannot gate what was
        # never declared, and at pod scale an unbudgeted all-gather is
        # the wall ROADMAP item 3 exists to avoid.
        comm_key = (
            "tpu-sharded:tpu-csr"
            if self.config.backend == "tpu-sharded"
            else self.config.backend
        )
        if comm_key.startswith("tpu-sharded"):
            # The sharded budgets are declared at parallel/sharded.py
            # import time; load it so the check reads the real table,
            # not an import-order accident (the backend itself imports
            # the same module on first converge anyway).
            from ..parallel import sharded as _sharded  # noqa: F401
        if comm_key.startswith("tpu-sharded") and comm_key not in COMM_INVARIANTS:
            logger.warning(
                "sharded trust backend %r has no COMM_INVARIANTS "
                "declaration; its collective structure is not lint-gated "
                "(PERF.md §15)",
                self.config.backend,
            )
        #: Senders whose attestation changed since the window plan last
        #: advanced — the delta-plan churn source.  Accumulates across
        #: failed epochs; cleared per successful converge.
        self._dirty_hashes: set[int] = set()
        #: Hash per peer id of the most recent build_graph call.
        self._id_order: list[int] = []
        _, self._group_pks = keyset_from_raw(self.config.fixed_set)
        self._group_hashes = [pk.hash() for pk in self._group_pks]
        #: The pk-sponge half of the protocol message hash — shared by
        #: every attestation against this group (hash it once, not once
        #: per signature; the admission plane's workers get it too).
        self._group_pks_hash = group_pks_hash(self._group_pks)
        # Poseidon pk-hash memo: hashing is 68 field-level rounds of
        # pure Python; never recompute for a seen key.
        self._hash_cache: dict[PublicKey, int] = dict(
            zip(self._group_pks, self._group_hashes)
        )

    @property
    def prover(self) -> Prover:
        if self._prover is None:
            if self.config.prover == "plonk":
                from ..zk.proof import PlonkEpochProver

                self._prover = PlonkEpochProver(
                    num_neighbours=self.config.num_neighbours,
                    num_iter=self.config.num_iter,
                    initial_score=self.config.initial_score,
                    scale=self.config.scale,
                    srs_path=self.config.srs_path,
                )
            else:
                self._prover = PoseidonCommitmentProver()
        return self._prover

    def warm_prover(self) -> None:
        """Force prover construction (PLONK keygen) now — called at node
        boot so the first epoch tick doesn't pay it."""
        _ = self.prover

    def _pk_hash(self, pk: PublicKey) -> int:
        h = self._hash_cache.get(pk)
        if h is None:
            h = pk.hash()
            self._hash_cache[pk] = h
        return h

    # -- ingest ---------------------------------------------------------

    def _structural_error(self, att: Attestation) -> tuple[str, str] | None:
        """The cheap pre-signature checks, shared by both ingest paths
        (manager/mod.rs:95-138 semantics plus score conservation).
        Returns ``(reason code, message)`` — the code labels the
        rejection-reason metric, the message goes into the error — or
        None when the attestation is structurally sound."""
        # Direct pk comparison is equivalent to the reference's
        # hash-list equality (Poseidon is injective on valid points) and
        # avoids N permutations per ingest.
        if att.neighbours != self._group_pks:
            return "group-mismatch", "neighbour group mismatch"
        if att.pk not in self._group_pks:
            return "sender-not-in-group", "sender not in group"
        # Conservation precondition: the circuit's Σscores == N·IS gate
        # means a non-SCALE-summing row would poison every future epoch
        # proof; reject it at the door instead (the reference accepts it
        # and would panic at proving time, main.rs:170 unwrap).
        if sum(att.scores) != self.config.scale:
            return (
                "non-conserving-scores",
                f"scores must sum to {self.config.scale}",
            )
        return None

    def add_attestation(self, att: Attestation) -> IngestResult:
        """Validate and cache one attestation (manager/mod.rs:95-138):
        the neighbour list must match the group, the sender must be a
        member, and the signature must verify over the protocol message
        hash.  Returns the same per-item :class:`IngestResult` as the
        bulk path (and IS the bulk path at batch size 1), so single-item
        and bulk ingestion report rejections uniformly instead of this
        path raising where the other returns."""
        return self.add_attestations_bulk([att])[0]

    def apply_verified(
        self, att: Attestation, raw: bytes | None = None, *, flush: bool = True
    ) -> IngestResult:
        """Cache an attestation whose structural AND signature checks
        already passed upstream — the admission plane's apply stage
        (ingest/plane.py) and the WAL replay path (node/wal.py).  With
        a WAL attached the record is appended (and, with ``flush``,
        fsync'd) BEFORE the cache insert: an acknowledged attestation
        survives ``kill -9`` at any instruction after this returns.
        ``raw`` is the wire payload when the caller already has it
        (skips re-serialization); batch callers pass ``flush=False``
        and call :meth:`flush_wal` once per batch."""
        if chaos.ACTIVE:
            chaos.fire("ingest.pre_apply")
        h = self._pk_hash(att.pk)
        seq = None
        if self.wal is not None:
            from .wal import encode_payload

            if raw is None:
                raw = AttestationData.from_attestation(att).to_bytes()
            # An OSError here (disk full, injected fault) propagates:
            # without the log record the attestation must NOT be
            # acknowledged — the plane maps it to reason="wal-error".
            seq = self.wal.append(
                encode_payload(len(att.neighbours), raw), flush=flush
            )
        self.attestations[h] = att
        if seq is not None:
            self.wal.mark_applied(seq)
        with self._state_lock:
            self._dirty_hashes.add(h)
        obs_metrics.ATTESTATIONS_ACCEPTED.inc()
        return IngestResult(True)

    def flush_wal(self) -> None:
        """Force buffered WAL records to disk — the batch-granular
        durability boundary (the admission plane calls this once per
        verify batch, before resolving the batch's verdicts)."""
        if self.wal is not None:
            self.wal.flush()

    def snapshot_attestations(self) -> list[tuple[int, bytes]]:
        """The cache as ``(num_neighbours, wire bytes)`` rows for the
        checkpoint: the graph column alone cannot reconstruct the cache
        post-recovery (epochs rebuild the graph FROM it), and the WAL
        only retains the tail past the checkpointed watermark."""
        return [
            (len(att.neighbours), AttestationData.from_attestation(att).to_bytes())
            for att in list(self.attestations.values())
        ]

    def restore_attestation(self, att: Attestation) -> None:
        """Re-install one checkpointed attestation at boot: cache
        insert + dirty mark only — no WAL append (it is already inside
        the snapshot's watermark), no accept metrics (it was counted
        when first accepted), no chaos hook."""
        h = self._pk_hash(att.pk)
        self.attestations[h] = att
        with self._state_lock:
            self._dirty_hashes.add(h)

    def add_attestations_bulk(self, atts: list[Attestation]) -> list[IngestResult]:
        """High-throughput ingest for event replay: run the shared
        structural checks per item, then batch the surviving signature
        verifications through the C++ runtime (one pass instead of A
        scalar-muls in Python).  Returns a per-item
        :class:`IngestResult` — acceptance plus the rejection reason,
        which also feeds the rejection-reason metric."""
        import time

        from ..crypto import native as cnative

        candidates: list[tuple[int, Attestation, int]] = []
        results: list[IngestResult | None] = [None] * len(atts)
        with TRACER.span("ingest", batch=len(atts)):
            survivors: list[tuple[int, Attestation]] = []
            for i, att in enumerate(atts):
                error = self._structural_error(att)
                if error is None:
                    survivors.append((i, att))
                else:
                    results[i] = IngestResult(False, error[0])
                    obs_metrics.ATTESTATIONS_REJECTED.inc(reason=error[0])
                    JOURNAL.record("ingest-reject", reason=error[0])
            # Every structural survivor attests against THE group, so
            # the pk-sponge half of the message hash is shared and the
            # per-row half batches through the native Poseidon runtime
            # (crypto.message_hash_batch) — ~6x over hashing each
            # attestation's message separately in Python.
            if survivors:
                mhs = message_hash_batch(
                    self._group_pks_hash, [list(a.scores) for _, a in survivors]
                )
                candidates = [(i, a, m) for (i, a), m in zip(survivors, mhs)]

            t0 = time.perf_counter()
            if candidates and cnative.available():
                sig_ok = cnative.eddsa_verify_batch(
                    [a.sig.big_r.x for _, a, _ in candidates],
                    [a.sig.big_r.y for _, a, _ in candidates],
                    [a.sig.s for _, a, _ in candidates],
                    [a.pk.point.x for _, a, _ in candidates],
                    [a.pk.point.y for _, a, _ in candidates],
                    [m for _, _, m in candidates],
                )
            else:
                sig_ok = [verify_sig(a.sig, a.pk, m) for _, a, m in candidates]
            if candidates:
                obs_metrics.SIG_VERIFY_SECONDS.observe(time.perf_counter() - t0)
                obs_metrics.SIGS_VERIFIED.inc(len(candidates))

            for (i, att, _), ok in zip(candidates, sig_ok):
                if ok:
                    try:
                        # The shared accept path: WAL append (buffered;
                        # one fsync per bulk call below) + cache insert.
                        results[i] = self.apply_verified(att, flush=False)
                    except OSError as exc:
                        results[i] = IngestResult(False, "wal-error")
                        obs_metrics.ATTESTATIONS_REJECTED.inc(reason="wal-error")
                        JOURNAL.record(
                            "ingest-reject", reason="wal-error", error=repr(exc)
                        )
                else:
                    results[i] = IngestResult(False, "bad-signature")
                    obs_metrics.ATTESTATIONS_REJECTED.inc(reason="bad-signature")
                    JOURNAL.record("ingest-reject", reason="bad-signature")
            # One fsync per bulk call: the verdicts below are durable.
            self.flush_wal()
        return [r for r in results if r is not None]

    def get_attestation(self, pk: PublicKey) -> Attestation:
        att = self.attestations.get(pk.hash())
        if att is None:
            raise EigenError.attestation_not_found()
        return att

    def generate_initial_attestations(self) -> None:
        """Self-sign uniform IS/N attestations for the whole fixed set
        (manager/mod.rs:149-167) — the circuit needs a score row from
        every participant."""
        cfg = self.config
        sks, pks = keyset_from_raw(cfg.fixed_set)
        score = cfg.initial_score // cfg.num_neighbours
        scores = [[score] * cfg.num_neighbours for _ in range(cfg.num_neighbours)]
        _, messages = calculate_message_hash(pks, scores)
        for sk, pk, msg, row in zip(sks, pks, messages, scores):
            sig = sign(sk, pk, msg)
            att = Attestation(sig=sig, pk=pk, neighbours=list(pks), scores=list(row))
            h = pk.hash()
            self.attestations[h] = att
            with self._state_lock:
                self._dirty_hashes.add(h)

    # -- per-epoch computation ------------------------------------------

    def gather_ops(self) -> list[list[int]]:
        """Score matrix in fixed-set order (manager/mod.rs:182-188);
        KeyError if a member has no attestation, like the reference's
        unwrap."""
        return [
            list(self.attestations[h].scores) for h in self._group_hashes
        ]

    def build_proof_job(self, epoch: Epoch):
        """Flatten this epoch's fixed-set statement into a
        :class:`~protocol_tpu.prover.jobs.ProofJob` for the async
        proving plane: per-member signature/pk/score integer tuples
        plus the protocol parameters — no protocol objects cross the
        worker process boundary.  The snapshot happens here, on the
        epoch tick, so later ingests never mutate an enqueued job."""
        from ..prover.jobs import ProofJob

        cfg = self.config
        atts = [self.attestations[h] for h in self._group_hashes]
        with self._state_lock:
            plan = self.window_plan
        # Plan fingerprints are hex digests; fold to an int so the job
        # payload stays flat ints (0 = no cached plan yet).
        raw_fp = getattr(plan, "fingerprint", 0) or 0
        fingerprint = int(raw_fp, 16) if isinstance(raw_fp, str) else int(raw_fp)
        return ProofJob(
            # Flat lineage IDs for the spawn boundary: the epoch's
            # sampled cohort (and earlier cohorts this proof covers);
            # () on the unsampled path.  Excluded from job_seed, so
            # sampling never perturbs proof bytes.
            lineage=LINEAGE.ids_for_epoch(epoch.number),
            epoch=epoch.number,
            ops=tuple(tuple(int(s) for s in a.scores) for a in atts),
            sigs=tuple(
                (a.sig.big_r.x, a.sig.big_r.y, a.sig.s) for a in atts
            ),
            pks=tuple((a.pk.point.x, a.pk.point.y) for a in atts),
            params=(
                cfg.num_neighbours,
                cfg.num_iter,
                cfg.initial_score,
                cfg.scale,
            ),
            prover=cfg.prover,
            srs_path=cfg.srs_path,
            check_circuit=cfg.check_circuit,
            graph_fingerprint=fingerprint,
            zk_backend=cfg.zk_backend,
        )

    def install_proof(self, epoch_number: int, pub_ins, proof_bytes: bytes) -> None:
        """Land an asynchronously produced proof in the cache (called
        from a proving-plane dispatcher thread; the dict insert is
        GIL-atomic, same discipline as the attestation cache)."""
        self.cache_proof(
            Epoch(int(epoch_number)),
            Proof(pub_ins=list(pub_ins), proof=proof_bytes),
        )

    def cache_proof(self, epoch: Epoch, proof: Proof) -> None:
        """Insert one epoch's proof and evict past the retention ring.

        The in-memory proof cache is a SERVING cache, not the durable
        record (checkpoints persist proofs; the proving plane owns the
        lifecycle) — before graftlint pass 12 it grew one entry per
        epoch forever, ~uptime x proof bytes of silent leak at a 10 s
        cadence.  Oldest-epoch eviction keeps ``GET /proof/<epoch>``
        serving the recent window while boot recovery and the ring
        agree on what "recent" means."""
        self.cached_proofs[epoch] = proof
        while len(self.cached_proofs) > PROOF_CACHE_EPOCHS:
            self.cached_proofs.pop(min(self.cached_proofs, key=lambda e: e.number))

    def checkpoint_watermark(self) -> int | None:
        """WAL seq the next checkpoint may truncate through — the last
        landed epoch's watermark, read as a pair with the warm state."""
        with self._state_lock:
            return self.last_wal_seq

    def calculate_proofs(self, epoch: Epoch) -> None:
        """Converge the fixed set exactly and cache a proof of the
        resulting public inputs (manager/mod.rs:170-214)."""
        if chaos.ACTIVE:
            chaos.fire("prover.pre_enqueue")
        cfg = self.config
        atts = [self.attestations[h] for h in self._group_hashes]
        ops = [list(a.scores) for a in atts]
        init = [cfg.initial_score] * cfg.num_neighbours
        with TRACER.span("power_iterate"):
            pub_ins = power_iterate(init, ops, cfg.num_iter, cfg.scale)

        # Constraint-level statement check before emitting the proof —
        # the reference runs MockProver::assert_satisfied inside
        # gen_proof even in release (verifier/mod.rs:62-70).  The
        # synthesized system is handed to the prover so the k=14
        # circuit isn't built twice per epoch.
        witness = {"ops": ops, "attestations": atts}
        if cfg.check_circuit:
            from ..zk.circuit import prove_epoch_statement

            with TRACER.span("circuit_check"):
                witness["cs"] = prove_epoch_statement(
                    atts,
                    pub_ins,
                    num_neighbours=cfg.num_neighbours,
                    num_iter=cfg.num_iter,
                    initial_score=cfg.initial_score,
                    scale=cfg.scale,
                )

        # Proving time lands in telemetry, the structured analog of the
        # reference's "Proving time: {:?}" print (circuit/src/utils.rs:305-321).
        from ..prover.jobs import job_seed
        from ..utils.telemetry import TELEMETRY

        # The statement-bound blinding seed keeps the synchronous path
        # byte-identical to the pooled path for the same input (the
        # async-prover equivalence contract).
        seed = job_seed(self.build_proof_job(epoch))
        with TELEMETRY.timer("epoch.prove"), TRACER.span("snark"):
            proof_bytes = self.prover.prove(pub_ins, witness, seed=seed)
        if __debug__:
            assert self.prover.verify(pub_ins, proof_bytes)
        self.cache_proof(epoch, Proof(pub_ins=pub_ins, proof=proof_bytes))
        # Sequential-prove lineage completion: this tick's proof covers
        # every cohort bound at or before this epoch (the async plane
        # does the same from its dispatcher when the proof lands).
        e2e = LINEAGE.epoch_proved(epoch.number)
        TIMELINE.record(
            epoch.number,
            proof={"state": "proved", "mode": "sync"},
            freshness={"completed": len(e2e)},
        )

    def _warm_t0(self, id_order: list[int]) -> np.ndarray | None:
        """Remap the previous epoch's fixed point onto the new graph's
        id space: surviving peers keep their score, departed peers'
        mass drops out, joined peers start at zero, and the result is
        L1-renormalized.  None (cold start) when there is no previous
        state or the overlap is empty — the backends treat None as
        "start from the pre-trust vector"."""
        # Scores and their peer-hash column publish together in
        # converge_prepared (pipeline device thread); read them as a
        # matched pair or the warm seed maps scores onto wrong peers.
        with self._state_lock:
            scores, hashes = self.last_scores, self.last_peer_hashes
        if scores is None or hashes is None or not len(hashes) or not len(scores):
            return None
        # Vectorized remap (PERF.md §20): the per-peer dict walk cost
        # ~7 s of pure Python at the pod's 10M-peer scale; folding the
        # Poseidon hashes to 64-bit keys and matching via one sorted
        # searchsorted pass is ~30x faster.  A low-64-bit collision
        # (≈ n²/2⁶⁴ odds) can only misplace one seed entry — the seed
        # is renormalized and the fixed point is start-independent, so
        # the failure mode is a marginally longer converge, never a
        # wrong score.
        from ..parallel.partition import keys_from_hashes

        prev_keys = keys_from_hashes(hashes)
        new_keys = keys_from_hashes(id_order)
        order = np.argsort(prev_keys, kind="stable")
        sorted_prev = prev_keys[order]
        pos = np.searchsorted(sorted_prev, new_keys)
        pos = np.minimum(pos, max(len(sorted_prev) - 1, 0))
        hit = (
            (sorted_prev[pos] == new_keys)
            if len(sorted_prev)
            else np.zeros(len(new_keys), bool)
        )
        j = order[pos]
        hit &= j < len(scores)
        prev_scores = np.maximum(np.asarray(scores, np.float64), 0.0)
        t0 = np.where(hit, prev_scores[np.minimum(j, len(scores) - 1)], 0.0)
        total = t0.sum()
        if not hit.any() or not np.isfinite(total) or total <= 0:
            return None
        return t0 / total

    @contextmanager
    def _plan_cache(self, backend, delta_rows: np.ndarray | None = None):
        """THE plan-cache handoff: seed the backend from the manager's
        cached WindowPlan (plus the churn hint for delta updates) and
        read back whatever plan the converge actually used, so
        checkpoints persist it.  Duck-typed — any backend exposing
        ``plan``/``delta_rows``/``last_plan`` participates, which
        covers both windowed rungs and future sharded composites
        without name dispatch."""
        if hasattr(backend, "plan"):
            with self._state_lock:
                backend.plan = self.window_plan
        if hasattr(backend, "delta_rows"):
            backend.delta_rows = delta_rows
        try:
            yield backend
        finally:
            plan = getattr(backend, "last_plan", None)
            if plan is not None:
                with self._state_lock:
                    self.window_plan = plan

    def prepare_epoch(self, epoch: Epoch) -> PreparedEpoch:
        """Host stage of one epoch: snapshot the dirty-sender set,
        assemble the open graph, remap the warm-start seed, and derive
        the plan-delta churn hint.  Touches no device state — the
        pipeline overlaps this with the previous epoch's device work."""
        # Snapshot BEFORE assembly: an ingest racing build_graph stays
        # dirty for the next epoch (supersets are safe, misses are not).
        # The cached plan is snapshotted in the same critical section so
        # the churn hint below is derived against one coherent plan.
        with self._state_lock:
            dirty = set(self._dirty_hashes)
            cached_plan = self.window_plan
        # WAL watermark BEFORE assembly: every record at or below it is
        # already in the cache, so it is inside the graph built next —
        # the checkpoint of this epoch may truncate the log through it.
        # (A record appended after this read stays in the WAL for the
        # next epoch; supersets are safe, misses are not.)
        wal_seq = self.wal.applied_watermark() if self.wal is not None else None
        with TRACER.span("build_graph"):
            graph = self.build_graph()
        # A concurrent build_graph (pipelined checkpoint path) may have
        # extended the shared order; ids are append-only, so truncating
        # to this graph's peer count restores the matching column.
        id_order = list(self._id_order)[: graph.n]
        obs_metrics.GRAPH_PEERS.set(graph.n)
        obs_metrics.GRAPH_EDGES.set(graph.nnz)
        # This graph absorbed the attestation cache: every applied
        # lineage entry is now included-in-epoch, and the timeline's
        # ingest watermark records what the epoch saw.
        included = LINEAGE.bind_epoch(epoch.number)
        TIMELINE.record(
            epoch.number,
            ingest_watermark={
                "accepted_total": obs_metrics.ATTESTATIONS_ACCEPTED.value(),
                "attestations_cached": len(self.attestations),
                "lineage_included": len(included),
            },
            graph={"peers": int(graph.n), "edges": int(graph.nnz)},
        )
        t0 = self._warm_t0(id_order) if self.config.warm_start else None
        delta_rows = None
        if cached_plan is not None and dirty:
            pos = {h: i for i, h in enumerate(id_order)}
            rows = np.array(
                sorted(pos[h] for h in dirty if h in pos), dtype=np.int64
            )
            # Pod mode: this host's plan only encodes the out-edges of
            # peers it owns, so churn on other hosts' peers is not a
            # delta against it — clip the hint to owned rows (the
            # owned-elsewhere rows are some other host's delta).
            if rows.size and self.config.pod_hosts > 1:
                from ..parallel.partition import HostPartition, keys_from_hashes

                part = HostPartition(
                    self.config.pod_hosts, seed=self.config.pod_seed
                )
                keys = keys_from_hashes(id_order[int(r)] for r in rows)
                rows = rows[part.assign(keys) == self.config.pod_host_id]
            # Above the churn crossover a full rebuild is cheaper than
            # repacking that many windows (PERF.md §11).
            if rows.size and rows.size <= self.config.plan_delta_max_churn * max(
                graph.n, 1
            ):
                delta_rows = rows
        return PreparedEpoch(
            epoch=epoch,
            graph=graph,
            id_order=id_order,
            t0=t0,
            delta_rows=delta_rows,
            dirty_snapshot=dirty,
            wal_seq=wal_seq,
        )

    def converge_prepared(
        self,
        prepared: PreparedEpoch,
        *,
        alpha: float = 0.0,
        tol: float = 1e-6,
        max_iter: int = 50,
    ) -> ConvergenceResult:
        """Device stage of one epoch: converge the prepared graph on the
        configured TrustBackend, seeded warm and with the plan cache
        handed off through :meth:`_plan_cache`."""
        graph = prepared.graph
        backend = get_backend(self.config.backend)
        # The analyzer (`python -m protocol_tpu.analysis`) hard-gates
        # every backend in KERNEL_INVARIANTS; a configured backend
        # outside the table runs with its access pattern unpinned —
        # legal (constructing it above proved it's registered) but
        # worth a loud note in the node log.
        key = (
            "tpu-sharded:tpu-csr"
            if self.config.backend == "tpu-sharded"
            else self.config.backend
        )
        if key not in NON_JAX_BACKENDS and key not in KERNEL_INVARIANTS:
            logger.warning(
                "trust backend %r has no KERNEL_INVARIANTS declaration; "
                "its kernel access pattern is not lint-gated (PERF.md §9)",
                self.config.backend,
            )
        # Recompile watch: PR 5 guarantees a steady-state delta epoch
        # (warm seed + delta-updated plan) keeps device shapes stable,
        # so the jit cache must not miss across this converge.  The
        # bracket reads _cache_size() at the host boundary only.
        steady_state = prepared.t0 is not None and prepared.delta_rows is not None
        jit_snapshot = RECOMPILES.snapshot()
        with self._plan_cache(backend, prepared.delta_rows):
            result = backend.converge(
                graph, alpha=alpha, tol=tol, max_iter=max_iter, t0=prepared.t0
            )
        RECOMPILES.observe(
            jit_snapshot,
            steady_state=steady_state,
            epoch=prepared.epoch.number,
        )
        if chaos.ACTIVE:
            # The fixed point exists but nothing is published yet — a
            # crash here must recover every accepted attestation from
            # checkpoint + WAL and reconverge to the same fixed point.
            chaos.fire("epoch.post_converge")
        if prepared.t0 is not None:
            obs_metrics.WARM_START_APPLIED.inc()
        # The epoch landed: its churn is folded into the cached plan
        # (or the plan was rebuilt), so those senders are clean now.
        # One critical section publishes the epoch's outcome: the
        # dirty-set subtraction is a read-modify-write racing ingest
        # .add()s, and scores/peer-hashes must land as a matched pair
        # for the next _warm_t0.
        with self._state_lock:
            self._dirty_hashes -= prepared.dirty_snapshot
            self.last_graph = graph
            self.last_scores = result.scores
            self.last_peer_hashes = prepared.id_order
            self.last_wal_seq = prepared.wal_seq
        self.cached_results[prepared.epoch] = result
        # Bounded inspection ring (graftlint pass 12): a ConvergenceResult
        # holds the full f32[N] fixed point — 4 MB/epoch at 1M peers —
        # and before the memory wall this dict kept every epoch's
        # forever (~34 GB/day at a 10 s cadence).  Same ring shape as
        # EpochPipeline.outcomes.
        while len(self.cached_results) > RESULT_CACHE_EPOCHS:
            self.cached_results.pop(min(self.cached_results, key=lambda e: e.number))
        # Convergence health → the /metrics surface: the iteration
        # count, the final residual, and the full device-captured
        # trajectory (one observation per iteration, so the histogram's
        # per-epoch count equals the iteration count).
        obs_metrics.CONVERGENCE_ITERATIONS.set(result.iterations)
        obs_metrics.LAST_RESIDUAL.set(result.residual)
        if result.residuals is not None:
            for r in result.residuals:
                obs_metrics.CONVERGENCE_RESIDUAL.observe(float(r))
        # Score-integrity monitor: fixed-point drift vs the previous
        # epoch (aligned by peer hash), top movers, and the stall
        # detector over the residual trajectory — the /scores/drift
        # surface.
        DRIFT.observe(
            prepared.epoch.number,
            prepared.id_order,
            result.scores,
            result.residuals,
        )
        # The epoch's lineage cohort has a converged (not yet proven)
        # fixed point; the timeline gets the converge fragment.
        LINEAGE.epoch_converged(prepared.epoch.number)
        TIMELINE.record(
            prepared.epoch.number,
            converge={
                "iterations": int(result.iterations),
                "residual": float(result.residual),
                "backend": str(result.backend),
                "warm_start": prepared.t0 is not None,
                "delta_plan": prepared.delta_rows is not None,
            },
        )
        return result

    def converge_epoch(
        self, epoch: Epoch, *, alpha: float = 0.0, tol: float = 1e-6, max_iter: int = 50
    ) -> ConvergenceResult:
        """Scaled path: build the open trust graph from every cached
        attestation and converge it on the configured TrustBackend —
        the sequential composition of :meth:`prepare_epoch` (host) and
        :meth:`converge_prepared` (device); the epoch pipeline calls
        the two halves from different stages instead.  The graph used
        is kept as ``last_graph`` so checkpointing can persist exactly
        the graph the scores belong to."""
        return self.converge_prepared(
            self.prepare_epoch(epoch), alpha=alpha, tol=tol, max_iter=max_iter
        )

    def restore_warm_state(
        self,
        *,
        graph: TrustGraph | None = None,
        plan: WindowPlan | None = None,
        scores: np.ndarray | None = None,
        peer_hashes: list[int] | None = None,
    ) -> None:
        """Seed the cross-epoch state from a checkpoint (node boot).
        Publishes under the state lock so a concurrently starting epoch
        pipeline never observes a half-restored warm snapshot; scores
        and their peer-hash column are only installed as a pair."""
        with self._state_lock:
            if graph is not None:
                self.last_graph = graph
            if plan is not None:
                self.window_plan = plan
            if scores is not None and peer_hashes is not None:
                self.last_scores = scores
                self.last_peer_hashes = peer_hashes

    def build_graph(self) -> TrustGraph:
        """Assemble the open COO graph: peer ids are discovered from
        attestation senders and neighbours in first-seen order; the
        fixed set is the pre-trusted seed."""
        ids: dict[int, int] = {}

        def peer_id(h: int) -> int:
            if h not in ids:
                ids[h] = len(ids)
            return ids[h]

        for h in self._group_hashes:
            peer_id(h)

        src, dst, w = [], [], []
        # list() is a GIL-atomic copy: the asyncio ingest thread may be
        # inserting while an executor thread assembles the graph.
        for sender_hash, att in list(self.attestations.items()):
            s_id = peer_id(sender_hash)
            for pk, score in zip(att.neighbours, att.scores):
                if score == 0 or pk.is_null():
                    continue
                d_id = peer_id(self._pk_hash(pk))
                src.append(s_id)
                dst.append(d_id)
                w.append(float(score))
        n = len(ids)
        # id -> hash, assembly order: the warm-start remap and the
        # checkpoint's peer_hashes column both key scores by this.
        self._id_order = list(ids)
        pre = np.zeros(n, bool)
        pre[: len(self._group_hashes)] = True
        return TrustGraph(
            n,
            np.array(src, np.int32),
            np.array(dst, np.int32),
            np.array(w, np.float32),
            pre,
        )

    # -- queries --------------------------------------------------------

    def get_proof(self, epoch: Epoch) -> Proof:
        proof = self.cached_proofs.get(epoch)
        if proof is None:
            raise EigenError.proof_not_found()
        return proof

    def get_last_proof(self) -> Proof:
        if not self.cached_proofs:
            raise EigenError.proof_not_found()
        return self.cached_proofs[max(self.cached_proofs, key=lambda e: e.number)]

    def aggregate_proofs(self, epochs: list[Epoch]):
        """Batch-verify cached epoch SNARKs with one pairing check
        (zk.aggregator): fold every requested epoch's proof into a KZG
        accumulator and finalize it.  Returns ``(ok, accumulator)``.

        The working half of the reference's unfinished aggregator
        surface (verifier/aggregator.rs) made node-reachable; requires
        the PLONK prover (commitment proofs have no pairing structure).
        """
        from ..zk.aggregator import Snark, accumulate, finalize

        from .errors import EigenErrorCode

        # Cheap validation first: the config string and the proof cache
        # — never trigger a lazy keygen (or wait on the boot warm-up)
        # for a request that would fail anyway.
        if self.config.prover != "plonk":
            raise EigenError(
                EigenErrorCode.VERIFICATION_ERROR,
                "aggregation requires the plonk prover",
            )
        proofs = [self.get_proof(epoch) for epoch in epochs]
        if self._prover is None:
            raise EigenError(
                EigenErrorCode.PROVING_ERROR, "prover still warming up"
            )
        prover = self.prover
        snarks = [
            Snark(
                vk=prover.vk,
                instances=proof.pub_ins,
                proof=proof.proof,
                transcript=prover.TRANSCRIPT,
            )
            for proof in proofs
        ]
        acc = accumulate(snarks)
        if acc is None:
            return False, None
        return finalize(acc, prover.vk), acc
