"""Bootstrap identities and protocol constants.

The reference hard-codes a fixed 5-peer set and its pk-hashes
(server/src/manager/mod.rs:32-69, data/bootstrap-nodes.csv); here the
same values are runtime data with CSV/JSON loaders so the set can be
swapped or scaled (SURVEY.md §5 config consolidation).
"""

from __future__ import annotations

import csv
from dataclasses import dataclass
from pathlib import Path

from ..crypto.eddsa import PublicKey, SecretKey

#: Default protocol constants (server/src/manager/mod.rs:31-38).
NUM_ITER = 10
NUM_NEIGHBOURS = 5
INITIAL_SCORE = 1000
SCALE = 1000

#: The reference's published bootstrap secret keys (bs58 pairs), also in
#: data/bootstrap-nodes.csv as Alice..Craig.
FIXED_SET: list[tuple[str, str]] = [
    ("2L9bbXNEayuRMMbrWFynPtgkrXH1iBdfryRH9Soa8M67", "9rBeBVtbN2MkHDTpeAouqkMWNFJC6Bxb6bXH9jUueWaF"),
    ("ARVqgNQtnV4JTKqgajGEpuapYEnWz93S5vwRDoRYWNh8", "2u1LC2JmKwkzUccS9hd5yS2DUUGTuYQ8MA7y28A9SgQY"),
    ("phhPpTLWJbC4RM39Ww3e6wWvZnVkk86iNAXyA1tRAHJ", "93aMkAqd7AY4c3m6ij6RuBzw3F9QYhQsAMnkKF2Ck2R8"),
    ("Bp3FqLd6Man9h7xujkbYDdhyF42F2dX871SJHvo3xsnU", "AUUqgGTvqzPetRMQdTrQ1xHnwz2BHDxPTi85wL4WYQaK"),
    ("AKo18M6YSE1dQQuXt4HfWNrXA6dKXBVkWVghEi6827u1", "ArT8Kk13Heai2UPbMbrqs3RuVm4XXFN2pVHttUnKpDoV"),
]


@dataclass
class BootstrapNode:
    name: str
    sk0: str
    sk1: str

    def secret_key(self) -> SecretKey:
        return SecretKey.from_bs58(self.sk0, self.sk1)


def keyset_from_raw(
    pairs: list[tuple[str, str]],
) -> tuple[list[SecretKey], list[PublicKey]]:
    """bs58 pairs → (secret keys, public keys)
    (server/src/utils.rs:27-50)."""
    sks = [SecretKey.from_bs58(a, b) for a, b in pairs]
    return sks, [sk.public() for sk in sks]


def read_bootstrap_csv(path: str | Path) -> list[BootstrapNode]:
    """Parse data/bootstrap-nodes.csv (client/src/utils.rs:27-53)."""
    nodes = []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            nodes.append(BootstrapNode(row["name"], row["sk0"], row["sk1"]))
    return nodes
