"""Fault injection: named fault points driven by a seeded schedule.

The durability plane (node/wal.py, node/checkpoint.py, the recovery
path) is only as trustworthy as the failures it has actually survived,
so the code that implements it carries *fault points* — named host-side
hooks (``chaos.fire("checkpoint.pre_rename")``) where a configured
schedule can crash the process (the ``kill -9`` analog), delay, tear a
write at byte k, or raise an intermittent ``OSError``/RPC error.
``tools/crash_matrix.py`` enumerates the registry and kills the node at
every point; ``tests/`` drive individual faults deterministically.

Doctrine:

- **Zero cost disabled.**  Every call site guards with
  ``if chaos.ACTIVE:`` — one module-attribute read on the hot path
  (same stance as the unsampled lineage path, PERF.md §17/§18).  The
  engine below is never touched on a production node.
- **Deterministic.**  A schedule is a seed plus a list of fault specs;
  triggers are exact hit counts (``after``/``times``) or seeded
  per-point RNG draws (``p``) — the same spec replays the same
  failure, which is what makes a crash matrix a regression test
  instead of a dice roll.
- **Host boundaries only.**  A fault point inside jit/shard_map-traced
  code would fire once at trace time and never again (or smuggle a
  host callback into the kernel) — graftlint pass 11's
  ``fault-point-in-jit`` rule pins this structurally, the same
  doctrine as spans (pass 3) and journal writes (pass 5).

Spec shape (``ProtocolConfig.chaos``, or the ``PROTOCOL_TPU_CHAOS``
env var holding inline JSON or ``@/path/to/spec.json``)::

    {"seed": 42, "faults": [
        {"point": "wal.post_append", "kind": "crash", "after": 3},
        {"point": "rpc.get_logs", "kind": "rpc-error", "times": 2},
        {"point": "wal.append", "kind": "torn", "at": 12, "after": 2},
        {"point": "ingest.pre_apply", "kind": "io-error", "p": 0.25},
        {"point": "wal.replay", "kind": "delay", "delay_s": 0.1}
    ]}

Kinds: ``crash`` (``os._exit(137)`` — no atexit, no flush: the
``kill -9`` analog), ``delay`` (``delay_s`` sleep), ``io-error``
(raises ``OSError`` with ``errno`` — default ENOSPC), ``rpc-error``
(raises :class:`ChaosRpcError`, a ``ConnectionError`` the RPC retry
wall handles like a real transport failure), and ``torn`` (a write is
truncated at byte ``at``; with ``then_crash`` — the default — the next
fired point crashes, so the torn prefix reaches disk and the process
dies, exactly the power-loss shape).  Triggers: ``after`` (the exact
Nth hit), ``times`` (hits 1..N), ``p`` (per-hit probability from the
seeded per-point stream), else every hit.

An *empty* fault list still activates the engine in counting mode —
``hits()`` then reports how often the workload reached each point,
which is how the crash matrix discovers which points a run exercises.
"""

from __future__ import annotations

import errno as _errno
import json
import os
import threading
import time
from random import Random
from typing import Any, BinaryIO

#: Hot-path guard: sites read this one module attribute and skip the
#: engine entirely when False (the default).  Flipped by configure().
ACTIVE: bool = False


class ChaosRpcError(ConnectionError):
    """Injected RPC transport failure (kind="rpc-error") — a
    ConnectionError subclass so retry walls treat it like the real
    thing."""


#: Exit code of an injected crash — distinct from SIGKILL's 137-by-
#: shell so the matrix can tell "chaos fired" from "OOM killer".
CRASH_EXIT_CODE = 117


class _Fault:
    """One parsed fault spec bound to its seeded trigger stream."""

    def __init__(self, spec: dict[str, Any], seed: int):
        self.point: str = str(spec["point"])
        self.kind: str = str(spec.get("kind", "crash"))
        self.after: int | None = (
            int(spec["after"]) if "after" in spec else None
        )
        self.times: int | None = (
            int(spec["times"]) if "times" in spec else None
        )
        self.p: float | None = float(spec["p"]) if "p" in spec else None
        self.delay_s: float = float(spec.get("delay_s", 0.05))
        self.at: int | None = int(spec["at"]) if "at" in spec else None
        self.then_crash: bool = bool(spec.get("then_crash", True))
        self.errno: int = getattr(
            _errno, str(spec.get("errno", "ENOSPC")), _errno.ENOSPC
        )
        # Per-fault deterministic stream: independent of every other
        # fault's draws, stable under spec reordering.
        self._rng = Random(f"{seed}:{self.point}:{self.kind}")

    def triggers(self, hit: int) -> bool:
        if self.after is not None:
            return hit == self.after
        if self.times is not None:
            return hit <= self.times
        if self.p is not None:
            return self._rng.random() < self.p
        return True


class _TornFile:
    """File proxy that silently drops everything past byte ``at`` —
    the torn-write shape for whole-file writers (np.savez through the
    checkpoint's atomic tmp).  With ``arm_crash`` the engine's next
    fired point crashes, so the torn prefix is all that survives."""

    def __init__(self, inner: BinaryIO, at: int, engine: "_Engine", arm: bool):
        self._inner = inner
        self._remaining = at
        self._engine = engine
        self._arm = arm
        # One wrapped file is written by one writer in practice, but
        # the budget bookkeeping is lock-guarded anyway (pass 7).
        self._lock = threading.Lock()

    def write(self, data: bytes) -> int:
        n = len(data)
        with self._lock:
            take = min(n, self._remaining)
            self._remaining -= take
            exhausted = self._remaining == 0
        if take > 0:
            self._inner.write(data[:take])
        if exhausted and take < n and self._arm:
            self._engine.arm_crash("torn-file")
        return n  # callers see a "successful" write

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


class _Engine:
    """The fault engine: registry, hit counters, trigger evaluation.
    All state under one lock — fire() is called from ingest dispatcher
    threads, the epoch executor, and the event loop alike."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._registry: dict[str, str] = {}
        self._faults: dict[str, list[_Fault]] = {}
        self._hits: dict[str, int] = {}
        self._crash_armed: str | None = None
        self.seed: int = 0

    # -- configuration --------------------------------------------------

    def configure(self, spec: dict[str, Any] | None) -> None:
        global ACTIVE
        with self._lock:
            self._faults.clear()
            self._hits.clear()
            self._crash_armed = None
            if spec is None:
                ACTIVE = False
                return
            self.seed = int(spec.get("seed", 0))
            for raw in spec.get("faults", ()):
                fault = _Fault(raw, self.seed)
                self._faults.setdefault(fault.point, []).append(fault)
            ACTIVE = True

    def declare(self, point: str, description: str) -> str:
        with self._lock:
            self._registry.setdefault(point, description)
        return point

    def registry(self) -> dict[str, str]:
        with self._lock:
            return dict(self._registry)

    def hits(self) -> dict[str, int]:
        with self._lock:
            return dict(self._hits)

    def arm_crash(self, why: str) -> None:
        with self._lock:
            self._crash_armed = why

    # -- firing ---------------------------------------------------------

    def _crash(self, point: str) -> None:
        # The kill -9 analog: no atexit hooks, no buffered-IO flush —
        # whatever the OS has is whatever recovery gets.
        os._exit(CRASH_EXIT_CODE)

    def _evaluate(self, point: str) -> list[_Fault]:
        """Count one hit at ``point`` and apply every triggered
        non-torn fault (crash / delay / io-error / rpc-error); returns
        the triggered torn faults for the caller to act on."""
        with self._lock:
            if self._crash_armed is not None:
                self._crash(point)
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            fired = [f for f in self._faults.get(point, ()) if f.triggers(hit)]
        torn: list[_Fault] = []
        for fault in fired:
            self._journal(point, fault, hit)
            if fault.kind == "crash":
                self._crash(point)
            elif fault.kind == "delay":
                time.sleep(fault.delay_s)
            elif fault.kind == "io-error":
                raise OSError(
                    fault.errno, f"chaos: injected io-error at {point}"
                )
            elif fault.kind == "rpc-error":
                raise ChaosRpcError(f"chaos: injected rpc error at {point}")
            elif fault.kind == "torn":
                torn.append(fault)
        return torn

    def fire(self, point: str) -> None:
        """Evaluate the schedule at one fault point.  May crash the
        process, sleep, or raise; returns normally otherwise."""
        self._evaluate(point)

    def corrupt(self, point: str, data: bytes) -> bytes:
        """Apply the schedule to a record about to be written; a torn
        fault truncates the payload at byte ``at`` and (by default)
        arms the next fired point to crash — so the site's write →
        fsync → fire sequence puts exactly the torn prefix on disk."""
        for fault in self._evaluate(point):
            at = fault.at if fault.at is not None else len(data) // 2
            if fault.then_crash:
                self.arm_crash(point)
            return data[:at]
        return data

    def wrap_file(self, point: str, f: BinaryIO) -> BinaryIO:
        """Apply the schedule to a whole-file write; a torn fault wraps
        the handle so everything past byte ``at`` is dropped while the
        writer believes it succeeded (``then_crash: false`` lands a
        silently-torn file — the shape checkpoint digest verification
        exists to catch)."""
        for fault in self._evaluate(point):
            at = fault.at if fault.at is not None else 64
            return _TornFile(f, at, self, fault.then_crash)  # type: ignore[return-value]
        return f

    @staticmethod
    def _journal(point: str, fault: _Fault, hit: int) -> None:
        # Observability for every *triggered* fault (hits are free):
        # the flight recorder is exactly where a post-mortem looks.
        from ..obs.journal import JOURNAL

        JOURNAL.record(
            "chaos-fault", point=point, fault=fault.kind, hit=hit
        )


_ENGINE = _Engine()

# -- module-level API (what call sites use) -----------------------------


def configure(spec: dict[str, Any] | str | None) -> None:
    """Install a fault schedule (dict, inline JSON, or ``@path``);
    None deactivates.  An empty ``faults`` list = counting mode."""
    if isinstance(spec, str):
        text = spec
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                text = f.read()
        spec = json.loads(text)
    _ENGINE.configure(spec)


def reset() -> None:
    """Deactivate and clear hit counters (tests)."""
    _ENGINE.configure(None)


def declare(point: str, description: str) -> str:
    """Register a fault point (module import time at the call site) so
    the crash matrix can enumerate every point that exists."""
    return _ENGINE.declare(point, description)


def registry() -> dict[str, str]:
    return _ENGINE.registry()


def hits() -> dict[str, int]:
    return _ENGINE.hits()


def fire(point: str) -> None:
    _ENGINE.fire(point)


def corrupt(point: str, data: bytes) -> bytes:
    return _ENGINE.corrupt(point, data)


def wrap_file(point: str, f: BinaryIO) -> BinaryIO:
    return _ENGINE.wrap_file(point, f)


def _configure_from_env() -> None:
    spec = os.environ.get("PROTOCOL_TPU_CHAOS")
    if spec:
        configure(spec)


_configure_from_env()

__all__ = [
    "ACTIVE",
    "CRASH_EXIT_CODE",
    "ChaosRpcError",
    "configure",
    "corrupt",
    "declare",
    "fire",
    "hits",
    "registry",
    "reset",
    "wrap_file",
]
