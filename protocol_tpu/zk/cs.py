"""PLONKish constraint system with a MockProver-equivalent checker.

The reference's proving stack is Halo2: circuits assign witnesses into
advice/fixed/instance columns, custom gates constrain polynomial
relations over rows (with rotations), and copy constraints tie cells
together; `MockProver` checks all of it without cryptographic proving
(the testing backbone, SURVEY.md §4 tier 2; circuit/src/lib.rs:56-163
for the chip framework this re-imagines).

This is a fresh design, not a Halo2 port: a *trace* of named columns,
gates as Python expressions evaluated row-wise over the Bn254 field, a
union-find for copy constraints, and region-free sequential row
allocation (chips return the rows they used).  Gate degree is
unconstrained because satisfaction is checked by direct evaluation —
no quotient polynomial — which keeps chip layouts simple while staying
faithful to the constrain-then-check model.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Callable

from ..crypto.field import MODULUS

P = MODULUS


@dataclass(frozen=True)
class Column:
    """A named column of one of three kinds: 'advice' (witness),
    'fixed' (circuit constants), 'instance' (public inputs)."""

    name: str
    kind: str


@dataclass(frozen=True)
class Cell:
    column: Column
    row: int


class RowView:
    """Accessor handed to gate polynomials: ``view[col]`` is the value
    at the gate's row, ``view[col, k]`` at rotation +k."""

    __slots__ = ("cs", "row")

    def __init__(self, cs: "ConstraintSystem", row: int):
        self.cs = cs
        self.row = row

    def __getitem__(self, key):
        if isinstance(key, tuple):
            col, rot = key
        else:
            col, rot = key, 0
        return self.cs.value(col, self.row + rot)


@dataclass
class Gate:
    """A named constraint: ``poly(view)`` must return 0 (or a list of
    zeros) at every row where ``selector`` is enabled."""

    name: str
    selector: str
    poly: Callable[[RowView], int | list[int]]


@dataclass
class Failure:
    gate: str
    row: int
    detail: str


@dataclass
class Lookup:
    """A lookup argument: at every row where ``selector`` is enabled,
    the tuple of ``columns`` values must be a member of ``table``
    (Halo2's lookup argument, checked by direct membership here)."""

    name: str
    selector: str
    columns: tuple[Column, ...]
    table: frozenset


class ConstraintSystem:
    """Columns + trace + gates + copy constraints."""

    def __init__(self):
        self.columns: dict[str, Column] = {}
        self.trace: dict[Column, dict[int, int]] = {}
        self.selectors: dict[str, set[int]] = {}
        self.gates: list[Gate] = []
        self.lookups: list[Lookup] = []
        self.copies: list[tuple[Cell, Cell]] = []
        self.n_rows = 0
        self._chips: dict[str, object] = {}

    def register_chip(self, key: str, fingerprint: object = None) -> bool:
        """One-time chip registration: returns True on first call for
        ``key``; later calls must carry an identical parameter
        fingerprint (a second chip instance with different parameters
        sharing columns/gates would be silently unsound)."""
        if key not in self._chips:
            self._chips[key] = fingerprint
            return True
        if self._chips[key] != fingerprint:
            raise AssertionError(
                f"chip {key!r} re-registered with different parameters"
            )
        return False

    # -- construction ---------------------------------------------------

    def column(self, name: str, kind: str = "advice") -> Column:
        assert kind in ("advice", "fixed", "instance")
        if name in self.columns:
            col = self.columns[name]
            assert col.kind == kind, f"column {name} re-declared as {kind}"
            return col
        col = Column(name, kind)
        self.columns[name] = col
        self.trace[col] = {}
        return col

    def gate(self, name: str, selector: str, poly) -> None:
        self.selectors.setdefault(selector, set())
        self.gates.append(Gate(name, selector, poly))

    def lookup(self, name: str, selector: str, columns, table) -> None:
        self.selectors.setdefault(selector, set())
        self.lookups.append(
            Lookup(name, selector, tuple(columns), frozenset(table))
        )

    def alloc_rows(self, n: int) -> int:
        """Reserve ``n`` fresh rows; returns the first row index."""
        start = self.n_rows
        self.n_rows += n
        return start

    def assign(self, col: Column, row: int, value: int) -> Cell:
        self.trace[col][row] = value % P
        self.n_rows = max(self.n_rows, row + 1)
        return Cell(col, row)

    def enable(self, selector: str, row: int) -> None:
        self.selectors.setdefault(selector, set()).add(row)

    def copy(self, a: Cell, b: Cell) -> None:
        """Constrain two cells equal (Halo2's equality/permutation
        argument, checked directly here)."""
        self.copies.append((a, b))

    # -- evaluation -----------------------------------------------------

    def value(self, col: Column, row: int) -> int:
        return self.trace[col].get(row, 0)

    def verify(self, max_failures: int = 10) -> list[Failure]:
        """Evaluate every gate at every enabled row and check copy
        constraints; returns failures (empty = satisfied), the
        MockProver::verify analog."""
        failures: list[Failure] = []
        for gate in self.gates:
            rows = self.selectors.get(gate.selector, ())
            for row in sorted(rows):
                out = gate.poly(RowView(self, row))
                values = out if isinstance(out, (list, tuple)) else [out]
                for i, v in enumerate(values):
                    if v % P != 0:
                        failures.append(
                            Failure(gate.name, row, f"poly #{i} = {v % P:#x}")
                        )
                        if len(failures) >= max_failures:
                            return failures
        for lookup in self.lookups:
            for row in sorted(self.selectors.get(lookup.selector, ())):
                entry = tuple(self.value(c, row) for c in lookup.columns)
                key = entry[0] if len(entry) == 1 else entry
                if key not in lookup.table:
                    failures.append(
                        Failure(lookup.name, row, f"{key!r} not in lookup table")
                    )
                    if len(failures) >= max_failures:
                        return failures
        for a, b in self.copies:
            va, vb = self.value(a.column, a.row), self.value(b.column, b.row)
            if va != vb:
                failures.append(
                    Failure(
                        "copy",
                        a.row,
                        f"{a.column.name}[{a.row}] = {va:#x} != "
                        f"{b.column.name}[{b.row}] = {vb:#x}",
                    )
                )
                if len(failures) >= max_failures:
                    return failures
        return failures

    def assert_satisfied(self) -> None:
        failures = self.verify()
        if failures:
            msgs = "\n".join(f"  {f.gate} @ row {f.row}: {f.detail}" for f in failures)
            raise AssertionError(f"constraint system not satisfied:\n{msgs}")

    # -- stats ----------------------------------------------------------

    def stats(self) -> dict:
        return {
            "rows": self.n_rows,
            "columns": len(self.columns),
            "gates": len(self.gates),
            "copies": len(self.copies),
            "assignments": sum(len(v) for v in self.trace.values()),
        }
