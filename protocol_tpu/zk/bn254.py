"""Bn254 (alt_bn128) G1 arithmetic over the base field Fq.

The proving stack's curve side (the reference gets this from
halo2curves; circuit/src/ecc/native.rs re-implements it over emulated
limbs for the aggregation circuit).  Used by the Poseidon transcript
(absorbing commitment points) and the future KZG layer.

y² = x³ + 3 over Fq; G1 generator (1, 2).
"""

from __future__ import annotations

from typing import NamedTuple

from .rns import FQ_MODULUS as Q

B = 3


class G1(NamedTuple):
    """Affine point; (0, 0) is the identity sentinel (matching the
    reference's EcPoint zero handling)."""

    x: int
    y: int

    def is_identity(self) -> bool:
        return self.x == 0 and self.y == 0

    def neg(self) -> "G1":
        if self.is_identity():
            return self
        return G1(self.x, (-self.y) % Q)

    def double(self) -> "G1":
        if self.is_identity() or self.y == 0:
            return IDENTITY
        lam = (3 * self.x * self.x) * pow(2 * self.y, -1, Q) % Q
        x3 = (lam * lam - 2 * self.x) % Q
        y3 = (lam * (self.x - x3) - self.y) % Q
        return G1(x3, y3)

    def add(self, other: "G1") -> "G1":
        if self.is_identity():
            return other
        if other.is_identity():
            return self
        if self.x == other.x:
            if (self.y + other.y) % Q == 0:
                return IDENTITY
            return self.double()
        lam = (other.y - self.y) * pow(other.x - self.x, -1, Q) % Q
        x3 = (lam * lam - self.x - other.x) % Q
        y3 = (lam * (self.x - x3) - self.y) % Q
        return G1(x3, y3)

    def mul(self, scalar: int) -> "G1":
        """Double-and-add over the scalar's bits (ecc/native.rs ladder
        semantics; not constant-time — verification-side use only)."""
        result = IDENTITY
        addend = self
        s = scalar
        while s:
            if s & 1:
                result = result.add(addend)
            addend = addend.double()
            s >>= 1
        return result


IDENTITY = G1(0, 0)
GENERATOR = G1(1, 2)

#: G1 group order equals the scalar field modulus Fr.
from ..crypto.field import MODULUS as GROUP_ORDER  # noqa: E402


def is_on_curve(p: G1) -> bool:
    if p.is_identity():
        return True
    return (p.y * p.y - (p.x**3 + B)) % Q == 0
