"""Gadget library: the constraint-level vocabulary every circuit builds
from (the analog of circuit/src/gadgets/ + the chip halves of poseidon/
edwards/eddsa).

All arithmetic chipsets share one *standard gate* (StdGate) — a single
row relation

    sa·a + sb·b + sc·c + sd·d + se·e + s_ab·a·b + s_cd·c·d + s_const = 0

(the same shape as the reference's main gate, gadgets/main.rs:58-91)
with per-row fixed selectors.  Higher gadgets (bit decomposition, ≤
comparison, set membership, Poseidon rounds, Edwards scalar-mul) use
dedicated columns and rotation gates.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import field
from ..crypto.babyjubjub import A as BJJ_A, D as BJJ_D
from ..crypto.poseidon import POSEIDON_5, HashParams
from .cs import Cell, ConstraintSystem

P = field.MODULUS


@dataclass
class StdGate:
    """The shared arithmetic row gate and its chipset operations.

    Each operation allocates fresh rows, assigns witnesses, sets the
    row's fixed selectors, and returns the output cell.  Inputs are
    passed as cells so equality wiring (copy constraints) keeps the
    composition sound, like the reference's chip outputs.
    """

    cs: ConstraintSystem

    def __post_init__(self):
        c = self.cs
        self.a = c.column("std_a")
        self.b = c.column("std_b")
        self.c = c.column("std_c")
        self.d = c.column("std_d")
        self.e = c.column("std_e")
        self.q = {
            name: c.column(f"std_{name}", "fixed")
            for name in ("sa", "sb", "sc", "sd", "se", "s_ab", "s_cd", "s_const")
        }
        if not any(g.name == "std" for g in c.gates):
            c.gate(
                "std",
                "std",
                lambda v: (
                    v[self.q["sa"]] * v[self.a]
                    + v[self.q["sb"]] * v[self.b]
                    + v[self.q["sc"]] * v[self.c]
                    + v[self.q["sd"]] * v[self.d]
                    + v[self.q["se"]] * v[self.e]
                    + v[self.q["s_ab"]] * v[self.a] * v[self.b]
                    + v[self.q["s_cd"]] * v[self.c] * v[self.d]
                    + v[self.q["s_const"]]
                ),
            )

    # -- row helper -----------------------------------------------------

    def row(self, assignments: dict, selectors: dict) -> int:
        """One standard-gate row.  ``assignments``: column->(value|cell)
        — cells are copied in via equality constraints."""
        r = self.cs.alloc_rows(1)
        for col, val in assignments.items():
            if isinstance(val, Cell):
                here = self.cs.assign(col, r, self.cs.value(val.column, val.row))
                self.cs.copy(here, val)
            else:
                self.cs.assign(col, r, val)
        for name, val in selectors.items():
            self.cs.assign(self.q[name], r, val)
        self.cs.enable("std", r)
        return r

    def witness(self, value: int) -> Cell:
        """An unconstrained witness cell (constrained by later use)."""
        r = self.cs.alloc_rows(1)
        return self.cs.assign(self.a, r, value)

    def constant(self, value: int) -> Cell:
        """A cell constrained to a fixed constant: a - value = 0."""
        r = self.row({self.a: value}, {"sa": 1, "s_const": -value % P})
        return Cell(self.a, r)

    def cell_value(self, cell: Cell) -> int:
        return self.cs.value(cell.column, cell.row)

    # -- chipset operations (gadgets/main.rs:131-607) -------------------

    def add(self, x: Cell, y: Cell) -> Cell:
        out = (self.cell_value(x) + self.cell_value(y)) % P
        r = self.row({self.a: x, self.b: y, self.c: out}, {"sa": 1, "sb": 1, "sc": P - 1})
        return Cell(self.c, r)

    def sub(self, x: Cell, y: Cell) -> Cell:
        out = (self.cell_value(x) - self.cell_value(y)) % P
        r = self.row({self.a: x, self.b: y, self.c: out}, {"sa": 1, "sb": P - 1, "sc": P - 1})
        return Cell(self.c, r)

    def mul(self, x: Cell, y: Cell) -> Cell:
        out = (self.cell_value(x) * self.cell_value(y)) % P
        r = self.row({self.a: x, self.b: y, self.c: out}, {"s_ab": 1, "sc": P - 1})
        return Cell(self.c, r)

    def mul_add(self, x: Cell, y: Cell, z: Cell) -> Cell:
        """x·y + z in one row."""
        out = (self.cell_value(x) * self.cell_value(y) + self.cell_value(z)) % P
        r = self.row(
            {self.a: x, self.b: y, self.c: out, self.d: z},
            {"s_ab": 1, "sc": P - 1, "sd": 1},
        )
        return Cell(self.c, r)

    def assert_bool(self, x: Cell) -> None:
        """x² − x = 0 (IsBoolChipset)."""
        self.row({self.a: x, self.b: x}, {"s_ab": 1, "sa": P - 1})

    def add_scaled(self, acc: Cell, x: Cell, k: int) -> Cell:
        """acc + k·x in one row (k a circuit constant)."""
        out = (self.cell_value(acc) + k * self.cell_value(x)) % P
        r = self.row(
            {self.a: x, self.c: out, self.d: acc},
            {"sa": k % P, "sc": P - 1, "sd": 1},
        )
        return Cell(self.c, r)

    def linear_const(self, x: Cell, k: int, c: int) -> Cell:
        """k·x + c in one row."""
        out = (k * self.cell_value(x) + c) % P
        r = self.row({self.a: x, self.c: out}, {"sa": k % P, "sc": P - 1, "s_const": c % P})
        return Cell(self.c, r)

    def assert_equal(self, x: Cell, y: Cell) -> None:
        self.cs.copy(x, y)

    def assert_zero(self, x: Cell) -> None:
        self.row({self.a: x}, {"sa": 1})

    def inverse(self, x: Cell) -> Cell:
        """Witness x⁻¹ with x·inv = 1 (InverseChipset); an unsatisfiable
        row results for x = 0, like the reference's invert().unwrap()."""
        xv = self.cell_value(x)
        inv = field.inv(xv) if xv else 0
        r = self.row({self.a: x, self.b: inv}, {"s_ab": 1, "s_const": P - 1})
        return Cell(self.b, r)

    def is_zero(self, x: Cell) -> Cell:
        """out = 1 iff x = 0 (IsZeroChipset): x·out = 0 and
        x·inv + out − 1 = 0."""
        xv = self.cell_value(x)
        inv = field.inv(xv) if xv else 0
        out_v = 1 if xv == 0 else 0
        r1 = self.row({self.a: x, self.b: out_v}, {"s_ab": 1})
        out = Cell(self.b, r1)
        self.row(
            {self.a: x, self.b: inv, self.c: out},
            {"s_ab": 1, "sc": 1, "s_const": P - 1},
        )
        return out

    def is_equal(self, x: Cell, y: Cell) -> Cell:
        return self.is_zero(self.sub(x, y))

    def select(self, cond: Cell, x: Cell, y: Cell) -> Cell:
        """cond ? x : y with boolean cond (SelectChipset)."""
        self.assert_bool(cond)
        t1 = self.mul(cond, x)
        t2 = self.mul(cond, y)
        # out = t1 + y - t2
        out_v = (self.cell_value(t1) + self.cell_value(y) - self.cell_value(t2)) % P
        r = self.row(
            {self.a: t1, self.b: y, self.c: t2, self.d: out_v},
            {"sa": 1, "sb": 1, "sc": P - 1, "sd": P - 1},
        )
        return Cell(self.d, r)

    def logical_and(self, x: Cell, y: Cell) -> Cell:
        self.assert_bool(x)
        self.assert_bool(y)
        return self.mul(x, y)


class Bits2NumChip:
    """LSB-first bit decomposition with a running weighted sum
    (gadgets/bits2num.rs re-designed as a rotation gate): per row,
    bit² − bit = 0 and acc_next = acc + bit·pw, with pw a fixed power
    of two."""

    def __init__(self, cs: ConstraintSystem):
        self.cs = cs
        self.bit = cs.column("b2n_bit")
        self.acc = cs.column("b2n_acc")
        self.pw = cs.column("b2n_pw", "fixed")
        if not any(g.name == "b2n" for g in cs.gates):
            cs.gate(
                "b2n",
                "b2n",
                lambda v: [
                    v[self.bit] * v[self.bit] - v[self.bit],
                    v[self.acc, 1] - v[self.acc] - v[self.bit] * v[self.pw],
                ],
            )
            # The running sum must start at zero, or arbitrary bit
            # patterns could "decompose" any value.
            cs.gate("b2n_init", "b2n_init", lambda v: v[self.acc])

    def decompose(self, value_cell: Cell, n_bits: int) -> list[Cell]:
        """Allocate n_bits rows; returns the bit cells and constrains
        acc_final == value."""
        cs = self.cs
        value = cs.value(value_cell.column, value_cell.row)
        bits = [(value >> i) & 1 for i in range(n_bits)]
        start = cs.alloc_rows(n_bits + 1)
        acc = 0
        cells = []
        for i, b in enumerate(bits):
            r = start + i
            cells.append(cs.assign(self.bit, r, b))
            cs.assign(self.acc, r, acc)
            cs.assign(self.pw, r, pow(2, i, P))
            cs.enable("b2n", r)
            if i == 0:
                cs.enable("b2n_init", r)
            acc = (acc + b * pow(2, i, P)) % P
        final = cs.assign(self.acc, start + n_bits, acc)
        cs.copy(final, value_cell)
        return cells


class LessEqChip:
    """x ≤ y for 252-bit operands (gadgets/lt_eq.rs's shifted-difference
    trick): decompose z = y + 2^252 − x into 253 bits and constrain the
    top bit to 1 (no borrow ⇔ x ≤ y)."""

    N_SHIFT = 252

    def __init__(self, cs: ConstraintSystem, std: StdGate, b2n: Bits2NumChip):
        self.cs = cs
        self.std = std
        self.b2n = b2n

    def assert_le(self, x: Cell, y: Cell) -> None:
        # Range-constrain both operands to 252 bits first (the reference
        # decomposes its inputs, lt_eq.rs:108+) — without this, field
        # elements near the modulus wrap the shifted difference and the
        # top-bit test passes vacuously.
        self.b2n.decompose(x, self.N_SHIFT)
        self.b2n.decompose(y, self.N_SHIFT)
        shift = self.std.constant(pow(2, self.N_SHIFT, P))
        z = self.std.add(self.std.sub(y, x), shift)
        bits = self.b2n.decompose(z, self.N_SHIFT + 1)
        one = self.std.constant(1)
        self.cs.copy(bits[self.N_SHIFT], one)

    def is_le_const(self, x: Cell, y_const: int, x_bits: int) -> Cell:
        """Boolean cell: x ≤ y_const, for x range-constrained here to
        ``x_bits`` (≤ 252) bits and a constant y_const < 2^252."""
        assert x_bits <= self.N_SHIFT and 0 <= y_const < (1 << self.N_SHIFT)
        self.b2n.decompose(x, x_bits)
        # z = y + 2^252 − x; top bit ⇔ x ≤ y.
        z = self.std.linear_const(x, P - 1, (y_const + (1 << self.N_SHIFT)) % P)
        bits = self.b2n.decompose(z, self.N_SHIFT + 1)
        return bits[self.N_SHIFT]


class SetChip:
    """Membership via product of differences (gadgets/set.rs): target ∈
    set ⇔ Π(target − item) = 0."""

    def __init__(self, std: StdGate):
        self.std = std

    def assert_member(self, target: Cell, items: list[Cell]) -> None:
        prod = self.std.constant(1)
        for item in items:
            prod = self.std.mul(prod, self.std.sub(target, item))
        self.std.assert_zero(prod)

    def is_member(self, target: Cell, items: list[Cell]) -> Cell:
        prod = self.std.constant(1)
        for item in items:
            prod = self.std.mul(prod, self.std.sub(target, item))
        return self.std.is_zero(prod)


class PoseidonChip:
    """The width-5 Hades permutation as rotation gates
    (poseidon/mod.rs FullRoundChip/PartialRoundChip re-designed):
    state lives in 5 advice columns; each round row constrains the next
    row's state to the round function of this row's."""

    def __init__(self, cs: ConstraintSystem, params: HashParams = POSEIDON_5):
        self.cs = cs
        self.params = params
        w = params.width
        pre = f"pos{w}"
        self._sel_full = f"{pre}_full"
        self._sel_partial = f"{pre}_partial"
        self.state = [cs.column(f"{pre}_s{i}") for i in range(w)]
        self.rc = [cs.column(f"{pre}_rc{i}", "fixed") for i in range(w)]
        mds = params.mds

        def pow5(x):
            x2 = x * x % P
            x4 = x2 * x2 % P
            return x4 * x % P

        def full_poly(v):
            cur = [pow5((v[self.state[j]] + v[self.rc[j]]) % P) for j in range(w)]  # noqa: B023
            return [
                (v[self.state[i], 1] - sum(mds[i][j] * cur[j] for j in range(w))) % P
                for i in range(w)
            ]

        def partial_poly(v):
            cur = [(v[self.state[j]] + v[self.rc[j]]) % P for j in range(w)]
            cur[0] = pow5(cur[0])
            return [
                (v[self.state[i], 1] - sum(mds[i][j] * cur[j] for j in range(w))) % P
                for i in range(w)
            ]

        if cs.register_chip(pre, (params.round_constants, params.mds)):
            cs.gate(f"{pre}_full", self._sel_full, full_poly)
            cs.gate(f"{pre}_partial", self._sel_partial, partial_poly)

    def permute(self, inputs: list[Cell]) -> list[Cell]:
        """Allocate the 68 round rows + result row; wires the input
        cells into row 0 and returns the final state cells."""
        cs = self.cs
        params = self.params
        w = params.width
        half_full = params.full_rounds // 2
        total_rounds = params.full_rounds + params.partial_rounds
        start = cs.alloc_rows(total_rounds + 1)

        # Row-0 state: copies of the inputs.
        values = [cs.value(c.column, c.row) for c in inputs]
        for j in range(w):
            here = cs.assign(self.state[j], start, values[j])
            cs.copy(here, inputs[j])

        rc = params.round_constants
        state = list(values)
        for rnd in range(total_rounds):
            row = start + rnd
            for j in range(w):
                cs.assign(self.rc[j], row, rc[rnd * w + j])
            if rnd < half_full or rnd >= half_full + params.partial_rounds:
                cs.enable(self._sel_full, row)
                state = [field.pow5((state[j] + rc[rnd * w + j]) % P) for j in range(w)]
            else:
                cs.enable(self._sel_partial, row)
                state = [(state[j] + rc[rnd * w + j]) % P for j in range(w)]
                state[0] = field.pow5(state[0])
            state = [
                sum(params.mds[i][j] * state[j] for j in range(w)) % P for i in range(w)
            ]
            for j in range(w):
                cs.assign(self.state[j], row + 1, state[j])

        return [Cell(self.state[j], start + total_rounds) for j in range(w)]


class PoseidonSpongeChip:
    """Absorb-chunks-and-permute sponge (poseidon/sponge.rs +
    gadgets/absorb.rs): chunk elements are added lane-wise to the
    running state with std-gate adds, then permuted."""

    def __init__(self, cs: ConstraintSystem, std: StdGate, poseidon: PoseidonChip):
        self.cs = cs
        self.std = std
        self.poseidon = poseidon

    def squeeze(self, inputs: list[Cell]) -> Cell:
        assert inputs
        w = self.poseidon.params.width
        zero = self.std.constant(0)
        state: list[Cell] = [zero] * w
        for off in range(0, len(inputs), w):
            chunk = list(inputs[off : off + w])
            chunk += [zero] * (w - len(chunk))
            merged = [self.std.add(chunk[j], state[j]) for j in range(w)]
            state = self.poseidon.permute(merged)
        return state[0]


class EdwardsChip:
    """BabyJubJub projective ops in-circuit (edwards/mod.rs re-designed).

    Point addition is one row constraining (x3,y3,z3) on the next row to
    the add-2008-bbjlp polynomials of two source points laid out across
    six advice columns; scalar multiplication is a 256-row double-and-add
    region sharing the bit column with a running scalar accumulator
    (StrictScalarMulChipset's bits2num fusion)."""

    def __init__(self, cs: ConstraintSystem):
        self.cs = cs
        # Columns: accumulator point r, doubling point e, bit, scalar acc.
        self.rx = cs.column("ed_rx")
        self.ry = cs.column("ed_ry")
        self.rz = cs.column("ed_rz")
        self.ex = cs.column("ed_ex")
        self.ey = cs.column("ed_ey")
        self.ez = cs.column("ed_ez")
        self.bit = cs.column("ed_bit")
        self.acc = cs.column("ed_acc")
        self.pw = cs.column("ed_pw", "fixed")
        # Intermediate products of the bbjlp addition (a = z1·z2,
        # c = x1·x2, d = y1·y2) witnessed per row so the add/select
        # constraints stay at degree ≤ 6 incl. selector.  Without them
        # the cleared-denominator x3 polynomial reaches degree 9 and
        # forces a 16× quotient extension domain on the whole circuit.
        self.ta = cs.column("ed_ta")
        self.tc = cs.column("ed_tc")
        self.td = cs.column("ed_td")

        def add_poly(x1, y1, z1, x2, y2, z2):
            a = z1 * z2 % P
            b = a * a % P
            c = x1 * x2 % P
            d = y1 * y2 % P
            e = BJJ_D * c % P * d % P
            f = (b - e) % P
            g = (b + e) % P
            x3 = a * f % P * ((x1 + y1) * (x2 + y2) - c - d) % P
            y3 = a * g % P * ((d - BJJ_A * c) % P) % P
            z3 = f * g % P
            return x3, y3, z3

        def double_poly(x1, y1, z1):
            b = (x1 + y1) * (x1 + y1) % P
            c = x1 * x1 % P
            d = y1 * y1 % P
            e = BJJ_A * c % P
            f = (e + d) % P
            h = z1 * z1 % P
            j = (f - 2 * h) % P
            x3 = (b - c - d) * j % P
            y3 = f * (e - d) % P
            z3 = f * j % P
            return x3, y3, z3

        self._add_poly = add_poly
        self._double_poly = double_poly

        def add_poly_witnessed(v):
            """The bbjlp addition of (rx,ry,rz)+(ex,ey,ez) expressed in
            the witnessed intermediates ta/tc/td: degree ≤ 5 instead of
            the cleared-denominator degree 8/9."""
            ta, tc, td = v[self.ta], v[self.tc], v[self.td]
            b = ta * ta % P
            e = BJJ_D * tc % P * td % P
            f = (b - e) % P
            g = (b + e) % P
            x3 = (
                ta * f % P * ((v[self.rx] + v[self.ry]) * (v[self.ex] + v[self.ey]) - tc - td)
                % P
            )
            y3 = ta * g % P * ((td - BJJ_A * tc) % P) % P
            z3 = f * g % P
            return x3, y3, z3

        def intermediate_cons(v):
            return [
                (v[self.ta] - v[self.rz] * v[self.ez]) % P,
                (v[self.tc] - v[self.rx] * v[self.ex]) % P,
                (v[self.td] - v[self.ry] * v[self.ey]) % P,
            ]

        def mul_step(v):
            bit = v[self.bit]
            ex, ey, ez = v[self.ex], v[self.ey], v[self.ez]
            rx, ry, rz = v[self.rx], v[self.ry], v[self.rz]
            dx, dy, dz = double_poly(ex, ey, ez)
            ax, ay, az = add_poly_witnessed(v)
            # select(bit, add, keep) per coordinate
            sel = [
                (bit * ax + (1 - bit) * rx) % P,
                (bit * ay + (1 - bit) * ry) % P,
                (bit * az + (1 - bit) * rz) % P,
            ]
            return intermediate_cons(v) + [
                bit * bit - bit,
                (v[self.rx, 1] - sel[0]) % P,
                (v[self.ry, 1] - sel[1]) % P,
                (v[self.rz, 1] - sel[2]) % P,
                (v[self.ex, 1] - dx) % P,
                (v[self.ey, 1] - dy) % P,
                (v[self.ez, 1] - dz) % P,
                (v[self.acc, 1] - v[self.acc] - bit * v[self.pw]) % P,
            ]

        def add_gate(v):
            ax, ay, az = add_poly_witnessed(v)
            return intermediate_cons(v) + [
                (v[self.rx, 1] - ax) % P,
                (v[self.ry, 1] - ay) % P,
                (v[self.rz, 1] - az) % P,
            ]

        def init_gate(v):
            # The double-and-add region must start from the identity
            # (0, 1, 1) with a zeroed scalar accumulator.
            return [
                v[self.rx],
                (v[self.ry] - 1) % P,
                (v[self.rz] - 1) % P,
                v[self.acc],
            ]

        if not any(g.name == "ed_mul" for g in cs.gates):
            cs.gate("ed_mul", "ed_mul", mul_step)
            cs.gate("ed_add", "ed_add", add_gate)
            cs.gate("ed_init", "ed_init", init_gate)

    def _point_values(self, pt: tuple[Cell, Cell, Cell]) -> tuple[int, int, int]:
        return tuple(self.cs.value(c.column, c.row) for c in pt)

    def scalar_mul(
        self,
        point: tuple[Cell, Cell, Cell],
        scalar: Cell,
        n_bits: int = 254,
        strict: bool = False,
        std: "StdGate | None" = None,
        lessq: "LessEqChip | None" = None,
    ) -> tuple[Cell, Cell, Cell]:
        """(point · scalar) with the scalar simultaneously re-composed
        from its bits and copy-constrained to ``scalar``.

        The recomposition is mod P, so a bit pattern encoding
        ``scalar + P`` would satisfy the copy while multiplying by a
        different integer.  Callers must either bound the scalar below
        2^n_bits for n_bits ≤ 253 (e.g. the ≤-suborder EdDSA s) or pass
        ``strict=True``, which splits the bits into low-128/high-126
        words and constrains the integer value < P (the reference's
        strict variant, edwards/mod.rs:359-410)."""
        cs = self.cs
        sval = cs.value(scalar.column, scalar.row)
        ex, ey, ez = self._point_values(point)
        start = cs.alloc_rows(n_bits + 1)
        bit_cells: list[Cell] = []

        rx, ry, rz = 0, 1, 1
        acc = 0
        for i in range(n_bits):
            row = start + i
            bit = (sval >> i) & 1
            bit_cells.append(cs.assign(self.bit, row, bit))
            cs.assign(self.rx, row, rx)
            cs.assign(self.ry, row, ry)
            cs.assign(self.rz, row, rz)
            ex_c = cs.assign(self.ex, row, ex)
            ey_c = cs.assign(self.ey, row, ey)
            ez_c = cs.assign(self.ez, row, ez)
            if i == 0:
                cs.copy(ex_c, point[0])
                cs.copy(ey_c, point[1])
                cs.copy(ez_c, point[2])
            cs.assign(self.acc, row, acc)
            cs.assign(self.pw, row, pow(2, i, P))
            cs.assign(self.ta, row, rz * ez % P)
            cs.assign(self.tc, row, rx * ex % P)
            cs.assign(self.td, row, ry * ey % P)
            cs.enable("ed_mul", row)
            if i == 0:
                cs.enable("ed_init", row)

            if bit:
                rx, ry, rz = self._add_poly(rx, ry, rz, ex, ey, ez)
            ex, ey, ez = self._double_poly(ex, ey, ez)
            acc = (acc + bit * pow(2, i, P)) % P

        last = start + n_bits
        cs.assign(self.rx, last, rx)
        cs.assign(self.ry, last, ry)
        cs.assign(self.rz, last, rz)
        cs.assign(self.ex, last, ex)
        cs.assign(self.ey, last, ey)
        cs.assign(self.ez, last, ez)
        acc_cell = cs.assign(self.acc, last, acc)
        cs.copy(acc_cell, scalar)

        if strict:
            assert std is not None and lessq is not None and n_bits == 254
            self._assert_canonical(bit_cells, std, lessq)
        return (Cell(self.rx, last), Cell(self.ry, last), Cell(self.rz, last))

    def _assert_canonical(
        self, bit_cells: list[Cell], std: "StdGate", lessq: "LessEqChip"
    ) -> None:
        """Constrain the 254-bit pattern to encode an integer < P:
        value = h·2^128 + l with l the low 128 and h the high 126 bits;
        value < P ⇔ h < PH ∨ (h = PH ∧ l < PL)."""
        ph, pl = P >> 128, P & ((1 << 128) - 1)
        low = std.constant(0)
        for i in range(128):
            low = std.add_scaled(low, bit_cells[i], pow(2, i, P))
        high = std.constant(0)
        for i in range(128, 254):
            high = std.add_scaled(high, bit_cells[i], pow(2, i - 128, P))
        lt_h = lessq.is_le_const(high, ph - 1, 126)
        eq_h = std.is_equal(high, std.constant(ph))
        lt_l = lessq.is_le_const(low, pl - 1, 128)
        ok = std.add(lt_h, std.mul(eq_h, lt_l))
        std.assert_equal(ok, std.constant(1))

    def add_points(
        self, p1: tuple[Cell, Cell, Cell], p2: tuple[Cell, Cell, Cell]
    ) -> tuple[Cell, Cell, Cell]:
        cs = self.cs
        x1, y1, z1 = self._point_values(p1)
        x2, y2, z2 = self._point_values(p2)
        row = cs.alloc_rows(2)
        for col, cell, val in (
            (self.rx, p1[0], x1),
            (self.ry, p1[1], y1),
            (self.rz, p1[2], z1),
            (self.ex, p2[0], x2),
            (self.ey, p2[1], y2),
            (self.ez, p2[2], z2),
        ):
            here = cs.assign(col, row, val)
            cs.copy(here, cell)
        cs.assign(self.ta, row, z1 * z2 % P)
        cs.assign(self.tc, row, x1 * x2 % P)
        cs.assign(self.td, row, y1 * y2 % P)
        cs.enable("ed_add", row)
        x3, y3, z3 = self._add_poly(x1, y1, z1, x2, y2, z2)
        cs.assign(self.rx, row + 1, x3)
        cs.assign(self.ry, row + 1, y3)
        cs.assign(self.rz, row + 1, z3)
        return (Cell(self.rx, row + 1), Cell(self.ry, row + 1), Cell(self.rz, row + 1))
