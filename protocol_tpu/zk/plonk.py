"""PLONK prover/verifier over the PLONKish constraint system.

The real SNARK behind the proof layer — the analog of the reference's
Halo2 KZG proving path (``create_proof``/``verify_proof`` behind
circuit/src/utils.rs:259-303 and the EVM transcript flow in
circuit/src/verifier/mod.rs:62-83).  This is a fresh TPU-era design,
not a Halo2 port: the circuit layer (protocol_tpu.zk.cs) stays a plain
trace-of-columns with black-box arithmetic gates, and this module
compiles it into a polynomial IOP:

* gates are *traced symbolically* (their Python callables run once over
  operator-overloading symbols) into expression trees, linearized to
  stack bytecode for the C++ gate evaluator (native/zk_runtime.cpp),
  which evaluates the whole y-combined constraint polynomial over the
  extended coset domain in one OpenMP pass per gate;
* copy constraints become a Halo2-style chunked permutation argument
  (grand products z_c over column chunks, chained through rotation −1,
  with the last row reserved so blinding needs no usable-region
  bookkeeping);
* boolean selectors become committed fixed columns;
* everything is committed with KZG over Bn254 and opened at the
  evaluation challenge with a GWC-style batched multi-open (one witness
  commitment per rotation point, two pairings total);
* Fiat-Shamir runs over the Poseidon transcript
  (protocol_tpu.zk.transcript), so the whole proof is one replayable
  byte string in the reference's ``Proof``/``ProofRaw`` wire shape.

Zero-knowledge: advice and z polynomials are blinded with random
multiples of the vanishing polynomial ((b0 + b1·X)·Z_H), which leaves
their evaluations on the domain — and therefore every constraint —
unchanged.

No instruction-following from the reference repo: cited lines document
behavioral parity targets only.
"""

from __future__ import annotations

import contextlib
import secrets
import time
from collections import Counter
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..crypto import field
from ..crypto.poseidon import PoseidonSponge
from ..obs import TRACER
from ..utils.limbs import from_limbs_fast, ptr as _ptr, to_limbs, to_limbs_fast
from .bn254 import G1, GENERATOR
from .cs import Column, ConstraintSystem
from . import graft as zk_graft
from .kzg import Setup, _div_by_linear, _eval_poly, msm
from .transcript import KeccakRead, KeccakWrite, PoseidonRead, PoseidonWrite

#: Fiat-Shamir backends: "poseidon" (native flow, aggregation-friendly)
#: and "keccak" (EVM flow — replayable with the KECCAK256 opcode, the
#: snark-verifier EvmTranscript analog).
_TRANSCRIPTS = {
    "poseidon": (PoseidonWrite, PoseidonRead),
    "keccak": (KeccakWrite, KeccakRead),
}

R = field.MODULUS
TWO_ADICITY = 28

#: 5 generates Fr* (5^((R-1)/2) == -1 checked below), so ROOT28 is a
#: primitive 2^28-th root of unity and DELTA = 5^(2^28) generates the
#: odd-order subgroup — its powers tag disjoint cosets k_j·H for the
#: permutation argument and shift the quotient evaluation coset off H.
_GEN = 5
ROOT28 = pow(_GEN, (R - 1) >> TWO_ADICITY, R)
DELTA = pow(_GEN, 1 << TWO_ADICITY, R)
assert pow(ROOT28, 1 << (TWO_ADICITY - 1), R) == R - 1, "ROOT28 not primitive"


def omega(k: int) -> int:
    """Primitive 2^k-th root of unity."""
    assert 0 <= k <= TWO_ADICITY
    return pow(ROOT28, 1 << (TWO_ADICITY - k), R)


# ---------------------------------------------------------------------------
# Symbolic gate tracing
# ---------------------------------------------------------------------------


class Sym:
    """Arithmetic expression node produced by tracing gate callables.

    Gate polynomials in the constraint system are plain Python
    callables over `+ - * % neg`; running them over Sym operands
    records the expression once, after which it can be linearized to
    C++ stack bytecode (coset evaluation) or evaluated scalar-wise
    (the verifier's single-point check).
    """

    __slots__ = ("op", "args", "deg")

    def __init__(self, op: str, args: tuple, deg: int):
        self.op = op
        self.args = args
        self.deg = deg

    # -- constructors ---------------------------------------------------

    @staticmethod
    def col(slot: int, rot: int = 0) -> "Sym":
        return Sym("col", (slot, rot), 1)

    @staticmethod
    def const(v: int) -> "Sym":
        return Sym("const", (v % R,), 0)

    @staticmethod
    def _wrap(x) -> "Sym":
        if isinstance(x, Sym):
            return x
        if isinstance(x, int):
            return Sym.const(x)
        return NotImplemented  # pragma: no cover

    # -- operators ------------------------------------------------------

    def __add__(self, o):
        o = Sym._wrap(o)
        return Sym("add", (self, o), max(self.deg, o.deg))

    __radd__ = __add__

    def __sub__(self, o):
        o = Sym._wrap(o)
        return Sym("sub", (self, o), max(self.deg, o.deg))

    def __rsub__(self, o):
        return Sym._wrap(o).__sub__(self)

    def __mul__(self, o):
        o = Sym._wrap(o)
        return Sym("mul", (self, o), self.deg + o.deg)

    __rmul__ = __mul__

    def __neg__(self):
        return Sym("neg", (self,), self.deg)

    def __mod__(self, o):
        assert o == R, "gate polynomials must reduce modulo the Bn254 scalar field"
        return self

    # -- analysis -------------------------------------------------------

    def used_cols(self, out: set | None = None) -> set:
        """All (slot, rot) pairs referenced."""
        if out is None:
            out = set()
        stack = [self]
        while stack:
            node = stack.pop()
            if node.op == "col":
                out.add(node.args)
            elif node.op not in ("const",):
                stack.extend(node.args)
        return out


class SymView:
    """RowView stand-in handed to gate callables during tracing:
    ``view[col]`` / ``view[col, rot]`` return column symbols."""

    def __init__(self, slot_of: dict):
        self._slot_of = slot_of

    def __getitem__(self, key):
        if isinstance(key, tuple):
            col, rot = key
        else:
            col, rot = key, 0
        return Sym.col(self._slot_of[col], rot)


def sym_eval(sym: Sym, getval, memo: dict | None = None) -> int:
    """Scalar evaluation; getval(slot, rot) -> int.  Memoized on node
    identity so shared subtrees evaluate once."""
    if memo is None:
        memo = {}
    key = id(sym)
    if key in memo:
        return memo[key]
    op = sym.op
    if op == "col":
        v = getval(*sym.args)
    elif op == "const":
        v = sym.args[0]
    elif op == "add":
        v = (sym_eval(sym.args[0], getval, memo) + sym_eval(sym.args[1], getval, memo)) % R
    elif op == "sub":
        v = (sym_eval(sym.args[0], getval, memo) - sym_eval(sym.args[1], getval, memo)) % R
    elif op == "mul":
        v = sym_eval(sym.args[0], getval, memo) * sym_eval(sym.args[1], getval, memo) % R
    else:  # neg
        v = (-sym_eval(sym.args[0], getval, memo)) % R
    memo[key] = v
    return v


_OP_COL, _OP_CONST, _OP_ADD, _OP_SUB, _OP_MUL, _OP_NEG = 0, 1, 2, 3, 4, 5


def linearize(sym: Sym, local_slot: dict, const_pool: dict, code: list) -> int:
    """Emit stack bytecode for the C++ evaluator; returns the maximum
    stack depth.  Deeper operands are emitted first so depth stays
    logarithmic (sub order is restored with a neg)."""
    op = sym.op
    if op == "col":
        slot, rot = sym.args
        code += [_OP_COL, local_slot[slot], rot]
        return 1
    if op == "const":
        idx = const_pool.setdefault(sym.args[0], len(const_pool))
        code += [_OP_CONST, idx]
        return 1
    if op == "neg":
        d = linearize(sym.args[0], local_slot, const_pool, code)
        code.append(_OP_NEG)
        return d
    a, b = sym.args
    da, db = _depth(a), _depth(b)
    swapped = db > da
    first, second = (b, a) if swapped else (a, b)
    d1 = linearize(first, local_slot, const_pool, code)
    d2 = linearize(second, local_slot, const_pool, code)
    depth = max(d1, d2 + 1)
    if op == "add":
        code.append(_OP_ADD)
    elif op == "mul":
        code.append(_OP_MUL)
    else:  # sub: stack holds first − second
        code.append(_OP_SUB)
        if swapped:  # computed b − a, want a − b
            code.append(_OP_NEG)
    return depth


def _depth(sym: Sym) -> int:
    if sym.op in ("col", "const"):
        return 1
    if sym.op == "neg":
        return _depth(sym.args[0])
    a, b = (_depth(x) for x in sym.args)
    return max(min(a, b) + 1, max(a, b))


# ---------------------------------------------------------------------------
# Domain / FFT helpers (native NTT with a pure-Python fallback)
# ---------------------------------------------------------------------------


def _native_lib():
    from . import native as zk_native

    if zk_native.available():
        return zk_native._load()
    return None


def _py_ntt(vals: list[int], root: int, inverse: bool) -> list[int]:
    n = len(vals)
    a = list(vals)
    # bit-reverse permute
    j = 0
    for i in range(1, n):
        bit = n >> 1
        while j & bit:
            j ^= bit
            bit >>= 1
        j |= bit
        if i < j:
            a[i], a[j] = a[j], a[i]
    length = 2
    while length <= n:
        w_len = pow(root, n // length, R)
        for start in range(0, n, length):
            w = 1
            half = length >> 1
            for k in range(start, start + half):
                u, t = a[k], a[k + half] * w % R
                a[k] = (u + t) % R
                a[k + half] = (u - t) % R
                w = w * w_len % R
        length <<= 1
    if inverse:
        n_inv = pow(n, R - 2, R)
        a = [x * n_inv % R for x in a]
    return a


class Domain:
    """Power-of-two evaluation domain."""

    def __init__(self, k: int):
        self.k = k
        self.n = 1 << k
        self.omega = omega(k)
        self.omega_inv = pow(self.omega, R - 2, R)

    def fft(self, coeffs: list[int]) -> list[int]:
        vals = list(coeffs) + [0] * (self.n - len(coeffs))
        return self._ntt(vals, self.omega, False)

    def ifft(self, evals: list[int]) -> list[int]:
        assert len(evals) == self.n
        return self._ntt(list(evals), self.omega_inv, True)

    def _ntt(self, vals: list[int], root: int, inverse: bool) -> list[int]:
        if zk_graft.zk_backend() == "graft":
            arr = zk_graft.ntt_limbs(to_limbs_fast(vals), root, inverse)
            return from_limbs_fast(arr)
        lib = _native_lib()
        if lib is None:
            return _py_ntt(vals, root, inverse)
        arr = to_limbs_fast(vals)
        rl = to_limbs([root])
        lib.zk_ntt(_ptr(arr), len(vals), _ptr(rl), 1 if inverse else 0)
        return from_limbs_fast(arr)

    def ifft_arr(self, values: list[int] | np.ndarray) -> np.ndarray:
        """Interpolate n evaluations into coefficient limbs without a
        Python-int round trip."""
        if isinstance(values, np.ndarray):
            arr = np.ascontiguousarray(values, dtype=np.uint64)
            assert arr.shape[0] == self.n
        else:
            assert len(values) == self.n
            arr = to_limbs_fast(values)
        return self.ntt_limbs(arr, self.omega_inv, True)

    def ntt_limbs(self, arr: np.ndarray, root: int, inverse: bool) -> np.ndarray:
        """In-place NTT over a (n, 4) limb array (``zk_backend`` path)."""
        if zk_graft.zk_backend() == "graft":
            return zk_graft.ntt_limbs(arr, root, inverse)
        lib = _native_lib()
        if lib is None:
            vals = _py_ntt(from_limbs_fast(arr), root, inverse)
            arr[:] = to_limbs_fast(vals)
            return arr
        rl = to_limbs([root])
        lib.zk_ntt(_ptr(arr), arr.shape[0], _ptr(rl), 1 if inverse else 0)
        return arr


def _powers(base: int, n: int) -> list[int]:
    out = [1] * n
    for i in range(1, n):
        out[i] = out[i - 1] * base % R
    return out


# -- limb-array helpers -----------------------------------------------------
#
# The proving hot path keeps polynomials as (n, 4) uint64 canonical-limb
# arrays end to end (ifft -> blind -> commit -> coset -> open), so the
# per-element Python big-int <-> limb conversions that dominated early
# profiles only happen at the few scalar boundaries (transcript,
# challenges, blinders).  Every helper falls back to pure Python via
# from/to_limbs_fast when the native runtime is unavailable.


def _row_int(arr: np.ndarray, i: int) -> int:
    r = arr[i]
    return int(r[0]) | int(r[1]) << 64 | int(r[2]) << 128 | int(r[3]) << 192


def _set_row(arr: np.ndarray, i: int, v: int) -> None:
    arr[i, 0] = v & 0xFFFFFFFFFFFFFFFF
    arr[i, 1] = (v >> 64) & 0xFFFFFFFFFFFFFFFF
    arr[i, 2] = (v >> 128) & 0xFFFFFFFFFFFFFFFF
    arr[i, 3] = (v >> 192) & 0xFFFFFFFFFFFFFFFF


def _powers_arr(base: int, n: int) -> np.ndarray:
    lib = _native_lib()
    if lib is None:
        return to_limbs_fast(_powers(base % R, n))
    from .native import powers as native_powers

    return native_powers(base, n)


def _poly_eval_arr(arr: np.ndarray, x: int) -> int:
    lib = _native_lib()
    if lib is None:
        acc = 0
        for c in reversed(from_limbs_fast(arr)):
            acc = (acc * x + c) % R
        return acc
    from .native import poly_eval_limbs

    return poly_eval_limbs(arr, x)


def _div_linear_arr(arr: np.ndarray, z: int) -> np.ndarray:
    """(p - p(z)) / (X - z) on limb arrays."""
    lib = _native_lib()
    if lib is None:
        coeffs = from_limbs_fast(arr)
        out = [0] * (len(coeffs) - 1)
        rem = 0
        for i in range(len(coeffs) - 1, 0, -1):
            rem = (rem * z + coeffs[i]) % R
            out[i - 1] = rem
        return to_limbs_fast(out) if out else np.zeros((1, 4), np.uint64)
    from .native import div_linear_limbs

    return div_linear_limbs(arr, z)


def _scale_add_arr(acc: np.ndarray, p: np.ndarray, s: int) -> None:
    lib = _native_lib()
    if lib is None:
        n = min(acc.shape[0], p.shape[0])
        av = from_limbs_fast(acc[:n])
        pv = from_limbs_fast(p[:n])
        acc[:n] = to_limbs_fast([(a + s * b) % R for a, b in zip(av, pv)])
        return
    from .native import scale_add

    scale_add(acc, p, s)


def _vec_mul_arr(a: np.ndarray, b: np.ndarray, out: np.ndarray) -> None:
    """out = a * b elementwise over min-length rows (canonical limbs)."""
    lib = _native_lib()
    n = min(a.shape[0], b.shape[0], out.shape[0])
    if lib is None:
        av, bv = from_limbs_fast(a[:n]), from_limbs_fast(b[:n])
        out[:n] = to_limbs_fast([(x * y) % R for x, y in zip(av, bv)])
        return
    lib.zk_vec_mul(_ptr(a[:n]), _ptr(b[:n]), _ptr(out[:n]), n)


def _batch_inv(vals: list[int]) -> list[int]:
    """Montgomery batch inversion; zeros invert to zero."""
    n = len(vals)
    prefix = [1] * n
    acc = 1
    for i, v in enumerate(vals):
        prefix[i] = acc
        if v:
            acc = acc * v % R
    inv_acc = pow(acc, R - 2, R)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        v = vals[i]
        if v:
            out[i] = prefix[i] * inv_acc % R
            inv_acc = inv_acc * v % R
    return out


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------


@dataclass
class GateSpec:
    name: str
    sel_slot: int
    constraints: list  # list[Sym]


@dataclass
class LookupSpec:
    """One compiled lookup argument (Halo2-style A'/S' permutation +
    grand product re-derived for the reserved-last-row domain layout).

    ``table_fixed_idx[j]`` indexes the synthetic fixed column holding
    tuple element j of the (sorted, padded) table; ``pad`` is the table
    entry substituted for rows where the selector is off."""

    name: str
    sel_slot: int
    input_slots: list[int]
    table_fixed_idx: list[int]
    pad: list[int]


@dataclass
class VerifyingKey:
    k: int
    ext_factor: int
    advice_names: list[str]
    instance_names: list[str]
    fixed_names: list[str]  # includes __q_* selector columns
    slot_of_name: dict[str, int]
    gates: list[GateSpec]
    gate_rots: dict[int, tuple[int, ...]]  # slot -> rotations used by gates
    perm_slots: list[int]
    perm_tags: list[int]  # k_j coset tags, aligned with perm_slots
    chunks: list[list[int]]  # chunk -> indices into perm_slots
    fixed_commits: list[G1]
    sigma_commits: list[G1]
    srs: Setup
    lookups: list[LookupSpec] = dc_field(default_factory=list)
    digest: int = 0

    @property
    def n(self) -> int:
        return 1 << self.k

    @property
    def n_advice(self) -> int:
        return len(self.advice_names)

    def omega(self) -> int:
        return omega(self.k)

    def compute_digest(self) -> int:
        t = PoseidonWrite()
        t.write_scalar(self.k)
        t.write_scalar(self.ext_factor)
        t.write_scalar(len(self.advice_names))
        t.write_scalar(len(self.gates))
        for c in self.fixed_commits:
            t.write_point(c)
        for c in self.sigma_commits:
            t.write_point(c)
        for tag in self.perm_tags:
            t.write_scalar(tag)
        t.write_scalar(len(self.lookups))
        for lk in self.lookups:
            # Width delimiter first: without it, adjacent lookups'
            # variable-length field sequences concatenate ambiguously.
            t.write_scalar(len(lk.input_slots))
            t.write_scalar(lk.sel_slot)
            for s in lk.input_slots:
                t.write_scalar(s)
            for ti in lk.table_fixed_idx:
                t.write_scalar(ti)
            for v in lk.pad:
                t.write_scalar(v)
        return t.squeeze_challenge()


@dataclass
class ProvingKey:
    vk: VerifyingKey
    fixed_values: list[list[int]]  # n evals per fixed column
    fixed_polys: list[np.ndarray]  # (n,4) canonical coefficient limbs
    sigma_values: list[list[int]]  # permutation tags sigma_j(w^i)
    sigma_polys: list[np.ndarray]
    row_tags: list[int]  # omega^i, i < n
    #: Coset-extended evaluations of every fixed/sigma polynomial,
    #: precomputed at keygen so epoch proving never re-runs their
    #: coset NTTs (they are witness-independent).
    fixed_cosets: list[np.ndarray] = dc_field(default_factory=list)
    sigma_cosets: list[np.ndarray] = dc_field(default_factory=list)


# ---------------------------------------------------------------------------
# Compilation (keygen)
# ---------------------------------------------------------------------------

# Permutation columns per grand product (degree _M_CHUNK+2 each).  5
# keeps the permutation at degree 7, matching the worst gate (ed_mul
# select at 6 + selector), so the quotient extension stays at 8× —
# one more z polynomial in exchange for half the extended domain.
_M_CHUNK = 5


def _classify_columns(cs: ConstraintSystem):
    advice = [c for c in cs.columns.values() if c.kind == "advice"]
    instance = [c for c in cs.columns.values() if c.kind == "instance"]
    fixed = [c for c in cs.columns.values() if c.kind == "fixed"]
    return advice, instance, fixed


def compile_circuit(
    cs: ConstraintSystem, srs: Setup | None = None, k: int | None = None
) -> ProvingKey:
    """Preprocess a synthesized circuit into proving/verifying keys.

    The circuit *structure* (columns, gates, selector positions, fixed
    values, copy topology) must be witness-independent — the same
    guarantee Halo2's keygen relies on (circuit/src/utils.rs:229-248).
    """
    advice, instance, fixed = _classify_columns(cs)
    sel_names = sorted(cs.selectors)

    max_table = max((len(lk.table) for lk in cs.lookups), default=0)
    required = max(cs.n_rows + 1, max_table + 1, 4)
    min_k = (required - 1).bit_length()
    if k is None:
        k = min_k
    n = 1 << k
    assert n >= required, f"k={k} too small for {cs.n_rows} rows / {max_table} table"
    assert k + 4 <= TWO_ADICITY

    # Slot assignment: advice, instance, fixed, then selector columns.
    slot_of_col: dict[Column, int] = {}
    names_adv, names_inst, names_fix = [], [], []
    for col in advice:
        slot_of_col[col] = len(slot_of_col)
        names_adv.append(col.name)
    for col in instance:
        slot_of_col[col] = len(slot_of_col)
        names_inst.append(col.name)
    for col in fixed:
        slot_of_col[col] = len(slot_of_col)
        names_fix.append(col.name)
    sel_slot: dict[str, int] = {}
    for sname in sel_names:
        qname = f"__q_{sname}"
        assert qname not in cs.columns
        sel_slot[sname] = len(slot_of_col) + len(sel_slot)
        names_fix.append(qname)
    slot_of_name = {}
    for col, slot in slot_of_col.items():
        slot_of_name[col.name] = slot
    for sname, slot in sel_slot.items():
        slot_of_name[f"__q_{sname}"] = slot

    # Trace gates symbolically.
    view = SymView(slot_of_col)
    gates: list[GateSpec] = []
    used: set[tuple[int, int]] = set()
    max_deg = 1
    for gate in cs.gates:
        out = gate.poly(view)
        cons = list(out) if isinstance(out, (list, tuple)) else [out]
        spec = GateSpec(gate.name, sel_slot[gate.selector], cons)
        gates.append(spec)
        used.add((spec.sel_slot, 0))
        for sym in cons:
            used |= sym.used_cols()
            max_deg = max(max_deg, sym.deg + 1)  # +1 boolean selector

    # Lookup arguments: materialize each (sorted, padded) table as
    # synthetic fixed columns; inputs/tables are theta-compressed at
    # prove time inside the constraints.
    lookup_specs: list[LookupSpec] = []
    lookup_tables: list[list[list[int]]] = []  # per lookup: per element, n values
    for lk in cs.lookups:
        width = len(lk.columns)
        entries = sorted(
            (e if isinstance(e, tuple) else (e,)) for e in lk.table
        )
        assert entries, f"lookup {lk.name}: empty table"
        assert all(len(e) == width for e in entries), "table tuple width mismatch"
        assert len(entries) <= n - 1, "lookup table exceeds usable rows"
        pad = [v % R for v in entries[0]]
        padded = entries + [tuple(pad)] * (n - len(entries))
        cols_vals = [[int(e[j]) % R for e in padded] for j in range(width)]
        table_idx = []
        for j in range(width):
            table_idx.append(len(names_fix))
            names_fix.append(f"__lt{len(lookup_specs)}_{j}")
        spec = LookupSpec(
            name=lk.name,
            sel_slot=sel_slot[lk.selector],
            input_slots=[slot_of_col[c] for c in lk.columns],
            table_fixed_idx=table_idx,
            pad=pad,
        )
        lookup_specs.append(spec)
        lookup_tables.append(cols_vals)
        used.add((spec.sel_slot, 0))
        for s in spec.input_slots:
            used.add((s, 0))
        for ti in table_idx:
            slot = len(advice) + len(instance) + ti
            slot_of_name[names_fix[ti]] = slot
            used.add((slot, 0))
        max_deg = max(max_deg, 5)  # grand-product constraint degree

    # Permutation: columns appearing in copy constraints.
    perm_cols: list[Column] = []
    seen = set()
    for a, b in cs.copies:
        for cell in (a, b):
            if cell.column not in seen:
                seen.add(cell.column)
                perm_cols.append(cell.column)
    perm_cols.sort(key=lambda c: slot_of_col[c])
    perm_slots = [slot_of_col[c] for c in perm_cols]
    perm_tags = [pow(DELTA, j, R) for j in range(len(perm_slots))]
    chunks = [
        list(range(i, min(i + _M_CHUNK, len(perm_slots))))
        for i in range(0, len(perm_slots), _M_CHUNK)
    ]
    max_deg = max(max_deg, (_M_CHUNK if chunks else 0) + 2)

    ext_factor = 1 << (max_deg + 1 - 1).bit_length()
    assert k + ext_factor.bit_length() - 1 <= TWO_ADICITY

    # Gate rotation sets per slot (plus rot 0 for permuted columns).
    rots: dict[int, set[int]] = {}
    for slot, rot in used:
        rots.setdefault(slot, set()).add(rot)
    for slot in perm_slots:
        rots.setdefault(slot, set()).add(0)
    gate_rots = {slot: tuple(sorted(v)) for slot, v in rots.items()}

    # Fixed column values (trace + selectors).
    domain = Domain(k)
    fixed_values: list[list[int]] = []
    for col in fixed:
        vals = [0] * n
        for row, v in cs.trace[col].items():
            vals[row] = v
        fixed_values.append(vals)
    for sname in sel_names:
        vals = [0] * n
        for row in cs.selectors[sname]:
            vals[row] = 1
        fixed_values.append(vals)
    for cols_vals in lookup_tables:
        fixed_values.extend(cols_vals)
    assert len(fixed_values) == len(names_fix)
    fixed_polys = [domain.ifft_arr(v) for v in fixed_values]

    # Permutation mapping sigma: identity tags, then rewire cycles.
    row_tags = _powers(domain.omega, n)
    sigma_values = [
        [tag * row_tags[i] % R for i in range(n)] for tag in perm_tags
    ]
    col_index = {slot: j for j, slot in enumerate(perm_slots)}
    parent: dict[tuple[int, int], tuple[int, int]] = {}

    def find(p):
        while parent.get(p, p) != p:
            parent[p] = parent.get(parent[p], parent[p])
            p = parent[p]
        return p

    def union(p, q):
        rp, rq = find(p), find(q)
        if rp != rq:
            parent[rp] = rq

    def pos(cell):
        return (col_index[slot_of_col[cell.column]], cell.row)

    for a, b in cs.copies:
        pa, pb = pos(a), pos(b)
        assert pa[1] < n and pb[1] < n
        parent.setdefault(pa, pa)
        parent.setdefault(pb, pb)
        union(pa, pb)
    cycles: dict[tuple[int, int], list[tuple[int, int]]] = {}
    for p in parent:
        cycles.setdefault(find(p), []).append(p)
    for members in cycles.values():
        members.sort()
        for i, (j, row) in enumerate(members):
            nj, nrow = members[(i + 1) % len(members)]
            sigma_values[j][row] = perm_tags[nj] * row_tags[nrow] % R
    sigma_polys = [domain.ifft_arr(v) for v in sigma_values]

    if srs is None:
        # Fresh random tau, discarded after the ladder is built: the
        # trust model is "whoever ran keygen" (a dev/test setup, or the
        # booting node operator) — a ceremony SRS should be supplied
        # via ``srs`` / loaded with Setup.from_bytes for anything whose
        # verifiers don't trust the prover's machine.
        srs = Setup.generate(k + 1, seed=secrets.token_bytes(32))
    # Headroom for blinded polynomials: advice columns get
    # len(rotations)+1 blinders (see prove), permutation z gets 4.
    max_blind = max(
        [4] + [len(rots) + 1 for slot, rots in gate_rots.items() if slot < len(advice)]
    )
    assert srs.n >= n + max_blind, (
        f"SRS too small for blinded polynomials: need {n + max_blind} powers "
        f"(degree bound n={n} + {max_blind} blinders), have {srs.n}"
    )

    fixed_commits = [srs.commit(p) for p in fixed_polys]
    sigma_commits = [srs.commit(p) for p in sigma_polys]

    vk = VerifyingKey(
        k=k,
        ext_factor=ext_factor,
        advice_names=names_adv,
        instance_names=names_inst,
        fixed_names=names_fix,
        slot_of_name=slot_of_name,
        gates=gates,
        gate_rots=gate_rots,
        perm_slots=perm_slots,
        perm_tags=perm_tags,
        chunks=chunks,
        fixed_commits=fixed_commits,
        sigma_commits=sigma_commits,
        srs=srs,
        lookups=lookup_specs,
    )
    vk.digest = vk.compute_digest()
    # Precompute coset-extended evaluations of the witness-independent
    # polynomials so prove() never re-runs their coset NTTs.
    ev = _CosetEvaluator(k, ext_factor)
    fixed_cosets = [ev._coset_fft(p) for p in fixed_polys]
    sigma_cosets = [ev._coset_fft(p) for p in sigma_polys]

    return ProvingKey(
        vk=vk,
        fixed_values=fixed_values,
        fixed_polys=fixed_polys,
        sigma_values=sigma_values,
        sigma_polys=sigma_polys,
        row_tags=row_tags,
        fixed_cosets=fixed_cosets,
        sigma_cosets=sigma_cosets,
    )


# ---------------------------------------------------------------------------
# Shared prover/verifier structure
# ---------------------------------------------------------------------------


def _perm_constraints(
    vk: VerifyingKey,
    beta: int,
    gamma: int,
    z_slots: list[int],
    sigma_slots: list[int],
    x_slot: int,
    l0_slot: int,
    llast_slot: int,
) -> list[Sym]:
    """The permutation argument's constraints, as symbols.  Order and
    content are identical for prover (coset) and verifier (scalar)."""
    if not vk.chunks:
        return []
    cons: list[Sym] = []
    one = Sym.const(1)
    l0 = Sym.col(l0_slot)
    llast = Sym.col(llast_slot)
    x = Sym.col(x_slot)
    # z_0 starts at 1.
    cons.append(l0 * (Sym.col(z_slots[0]) - one))
    # Chunk chaining: z_c(1) = z_{c-1}(omega^{-1}) (= previous chunk's
    # full product over the n-1 active rows).
    for c in range(1, len(vk.chunks)):
        cons.append(l0 * (Sym.col(z_slots[c]) - Sym.col(z_slots[c - 1], -1)))
    # Recurrence per chunk, active on rows 0..n-2.
    for c, chunk in enumerate(vk.chunks):
        num = one
        den = one
        for j in chunk:
            v = Sym.col(vk.perm_slots[j])
            num = num * (v + _c(beta * vk.perm_tags[j] % R) * x + _c(gamma))
            den = den * (v + _c(beta) * Sym.col(sigma_slots[j]) + _c(gamma))
        z, z_next = Sym.col(z_slots[c]), Sym.col(z_slots[c], 1)
        cons.append((one - llast) * (z_next * den - z * num))
    # Total product is 1.
    cons.append(llast * (Sym.col(z_slots[-1]) - one))
    return cons


def _c(v) -> Sym:
    """Wrap a scalar as a constant symbol; pass symbols through —
    challenges may arrive as ints (prover/verifier) or as runtime
    symbols (the EVM verifier codegen), and the constraint builders
    must produce identical structure either way."""
    return v if isinstance(v, Sym) else Sym.const(v)


def _theta_compress(values, theta):
    """Σ theta^j · v_j — THE tuple compression for lookups, shared by
    prover and verifier (ints in, int out; Syms in, Sym out)."""
    acc = None
    th = 1
    for v in values:
        if isinstance(v, Sym) or isinstance(th, Sym):
            term = _c(th) * v
        else:
            term = th * (v % R) % R
        acc = term if acc is None else acc + term
        th = th * theta % R
    if acc is None:
        return 0
    return acc if isinstance(acc, Sym) else acc % R


def _lookup_constraints(
    vk: VerifyingKey,
    theta: int,
    beta: int,
    gamma: int,
    lk_a_slots: list[int],
    lk_s_slots: list[int],
    lk_z_slots: list[int],
    l0_slot: int,
    llast_slot: int,
    n_adv_inst: int,
) -> list[Sym]:
    """The lookup argument's constraints (shared prover/verifier):

    for each lookup, with A the selector-gated theta-compressed input,
    T the theta-compressed table, A'/S' the committed permutations and
    Z the grand product over the n-1 active rows:

      l_0·(Z−1);  l_last·(Z−1);
      (1−l_last)·[Z(ωX)(A'+β)(S'+γ) − Z(X)(A+β)(T+γ)];
      l_0·(A'−S');  (1−l_last)·(A'−S')(A'−A'(ω⁻¹X))
    """
    cons: list[Sym] = []
    if not vk.lookups:
        return cons
    one = Sym.const(1)
    l0 = Sym.col(l0_slot)
    llast = Sym.col(llast_slot)
    for i, lk in enumerate(vk.lookups):
        sel = Sym.col(lk.sel_slot)
        # A = sel·(compressed − pad) + pad
        comp = _theta_compress([Sym.col(s) for s in lk.input_slots], theta)
        padc = _theta_compress(lk.pad, theta)
        a_expr = sel * (comp - _c(padc)) + _c(padc)
        t_expr = _theta_compress(
            [Sym.col(n_adv_inst + ti) for ti in lk.table_fixed_idx], theta
        )
        ap, sp_, z = (
            Sym.col(lk_a_slots[i]),
            Sym.col(lk_s_slots[i]),
            Sym.col(lk_z_slots[i]),
        )
        z_next = Sym.col(lk_z_slots[i], 1)
        ap_prev = Sym.col(lk_a_slots[i], -1)
        b, g = _c(beta), _c(gamma)
        cons.append(l0 * (z - one))
        cons.append(llast * (z - one))
        cons.append(
            (one - llast)
            * (z_next * ((ap + b) * (sp_ + g)) - z * ((a_expr + b) * (t_expr + g)))
        )
        cons.append(l0 * (ap - sp_))
        cons.append((one - llast) * (ap - sp_) * (ap - ap_prev))
    return cons


def _opening_entries(vk: VerifyingKey, n_t: int):
    """Deterministic list of (kind, index, rots) for every opened
    polynomial: advice, fixed (incl. selectors), sigma, z, t-chunks."""
    entries = []
    n_adv = len(vk.advice_names)
    n_inst = len(vk.instance_names)
    for i in range(n_adv):
        rots = vk.gate_rots.get(i, ())
        if rots:
            entries.append(("advice", i, rots))
    for i in range(len(vk.fixed_names)):
        slot = n_adv + n_inst + i
        rots = vk.gate_rots.get(slot, ())
        if rots:
            entries.append(("fixed", i, rots))
    for j in range(len(vk.perm_slots)):
        entries.append(("sigma", j, (0,)))
    n_chunks = len(vk.chunks)
    for c in range(n_chunks):
        rots = [0, 1]
        if c < n_chunks - 1:
            rots = [-1, 0, 1]
        entries.append(("z", c, tuple(rots)))
    for i in range(len(vk.lookups)):
        entries.append(("lkA", i, (-1, 0)))
        entries.append(("lkS", i, (0,)))
        entries.append(("lkZ", i, (0, 1)))
    for c in range(n_t):
        entries.append(("t", c, (0,)))
    return entries


def _lagrange_eval(vals: dict[int, int], x: int, k: int) -> int:
    """Evaluate the low-degree extension of sparse row values at x:
    sum_i v_i * L_i(x) with L_i(x) = w^i (x^n - 1) / (n (x - w^i))."""
    n = 1 << k
    w = omega(k)
    zh = (pow(x, n, R) - 1) % R
    if zh == 0:
        # x landed on the domain (negligible probability for a
        # Fiat-Shamir challenge); fall back to direct membership.
        for i, v in vals.items():
            if pow(w, i, R) == x % R:
                return v % R
        return 0
    n_inv = pow(n, R - 2, R)
    acc = 0
    denoms = [(x - pow(w, i, R)) % R for i in vals]
    invs = _batch_inv(denoms)
    for (i, v), inv_d in zip(vals.items(), invs):
        acc = (acc + v * pow(w, i, R) % R * inv_d) % R
    return acc * zh % R * n_inv % R


# ---------------------------------------------------------------------------
# Prover
# ---------------------------------------------------------------------------


class _ProveAttribution:
    """Deep attribution for one ``prove()`` call: where did the SNARK
    seconds go?

    Two disjoint layers, attached as closed children of the enclosing
    span (the manager's ``snark``) when the prove finishes:

    - the kernel engines' phase-timer tables (``zk.native.phase_stats``
      and ``zk.graft.phase_stats``: msm / ntt / gate_eval / field_ops /
      srs), each delta'd over the whole prove — the inner loops, with
      call counts, tagged ``engine="native"`` / ``engine="graft"`` so
      the same ``snark -> {msm, ntt, ...}`` children survive a
      ``zk_backend`` switch (tools/prover_pipe.py asserts this);
    - per-stage *host residuals* (``witness_gen`` / ``commit`` /
      ``quotient`` / ``open``): each stage's wall-clock minus whatever
      engine time ran inside it, so the stage spans and the engine
      spans partition the prove instead of double counting.

    Without either kernel runtime the engine rows are zero and the
    stage residuals are full stage wall-clock — attribution still sums
    to the prove.  The tables are process-global, so a concurrent
    engine user on another thread (e.g. an /aggregate verify) can
    inflate the engine rows of an overlapping prove; attribution is
    diagnostic, not an invariant, and the skew is bounded by that
    request's work.
    """

    def __init__(self) -> None:
        from . import native as zk_native

        self._engines = (("native", zk_native), ("graft", zk_graft))
        self._snap0 = {
            name: mod.phase_stats() for name, mod in self._engines
        }
        self._stages: dict[str, list[float]] = {}  # name -> [host_s, calls]

    @staticmethod
    def _total_seconds(stats: dict[str, dict[str, float]]) -> float:
        return sum(row["seconds"] for row in stats.values())

    def _engine_seconds(self) -> float:
        return sum(
            self._total_seconds(mod.phase_stats()) for _, mod in self._engines
        )

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        n0 = self._engine_seconds()
        try:
            yield
        finally:
            wall = time.perf_counter() - t0
            engine = self._engine_seconds() - n0
            rec = self._stages.setdefault(name, [0.0, 0])
            rec[0] += max(wall - engine, 0.0)
            rec[1] += 1

    def attach(self) -> None:
        """Bridge the attribution into the current span tree (no-op
        outside a span, e.g. direct prove() calls in tests)."""
        for name, mod in self._engines:
            delta = mod.phase_delta(self._snap0[name], mod.phase_stats())
            for phase, row in delta.items():
                if row["calls"] > 0:
                    TRACER.attach_closed(
                        phase, row["seconds"], calls=int(row["calls"]), engine=name
                    )
        for name, (host_s, calls) in self._stages.items():
            TRACER.attach_closed(name, host_s, calls=int(calls), engine="host")


class _CosetEvaluator:
    """Evaluates y-combined constraint programs over the extended coset
    domain, with per-slot lazy materialization and refcounted frees."""

    def __init__(self, k: int, ext_factor: int):
        self.k = k
        self.n = 1 << k
        self.E = ext_factor
        self.ext_k = k + ext_factor.bit_length() - 1
        self.m = 1 << self.ext_k
        self.ext = Domain(self.ext_k)
        self.shift = DELTA
        self._arrays: dict[int, np.ndarray] = {}
        self._coeffs: dict[int, np.ndarray] = {}
        self._shift_pows: np.ndarray | None = None

    def set_coeffs(self, slot: int, coeffs: list[int] | np.ndarray) -> None:
        if not isinstance(coeffs, np.ndarray):
            coeffs = to_limbs_fast(coeffs)
        self._coeffs[slot] = coeffs

    def set_values_ext(self, slot: int, arr: np.ndarray) -> None:
        self._arrays[slot] = arr

    def _coset_fft(self, coeffs: np.ndarray) -> np.ndarray:
        if self._shift_pows is None:
            self._shift_pows = _powers_arr(self.shift, self.m)
        arr = np.zeros((self.m, 4), dtype=np.uint64)
        arr[: coeffs.shape[0]] = coeffs
        _vec_mul_arr(arr, self._shift_pows, arr)
        return self.ext.ntt_limbs(arr, self.ext.omega, False)

    def array(self, slot: int) -> np.ndarray:
        if slot not in self._arrays:
            self._arrays[slot] = self._coset_fft(self._coeffs.pop(slot))
        return self._arrays[slot]

    def free(self, slot: int) -> None:
        self._arrays.pop(slot, None)

    def run(self, sym: Sym, acc: np.ndarray | None) -> np.ndarray:
        """Evaluate sym over the coset; add into acc (canonical limbs)."""
        used = sorted(sym.used_cols())
        local = {}
        for slot, _rot in used:
            if slot not in local:
                local[slot] = len(local)
        lib = _native_lib()
        if lib is not None:
            import ctypes

            const_pool: dict[int, int] = {}
            code: list[int] = []
            depth = linearize(sym, local, const_pool, code)
            assert depth <= 150, f"gate program too deep: {depth}"
            # Pointer table instead of an np.stack copy: each column is
            # passed as its own (m,4) C-contiguous array.
            arrays = [np.ascontiguousarray(self.array(slot)) for slot in local]
            ptrs = (ctypes.c_void_p * len(arrays))(
                *[a.ctypes.data for a in arrays]
            )
            consts = sorted(const_pool, key=const_pool.get)
            out = np.empty((self.m, 4), dtype=np.uint64)
            carr = to_limbs(consts) if consts else np.zeros((1, 4), dtype=np.uint64)
            code_arr = np.asarray(code, dtype=np.int64)
            from .native import _iptr

            rc = lib.zk_eval_program2(
                self.m,
                len(arrays),
                ptrs,
                self.E,
                _iptr(code_arr),
                len(code_arr),
                _ptr(carr),
                len(consts),
                _ptr(out),
            )
            assert rc == 0, "gate program rejected by native evaluator"
            if acc is None:
                return out
            lib.zk_vec_add(_ptr(acc), _ptr(out), _ptr(acc), self.m)
            return acc
        # Pure-Python fallback (small circuits only).
        cols = {slot: from_limbs_fast(self.array(slot)) for slot in local}
        out_vals = []
        for i in range(self.m):
            def getval(slot, rot, _i=i):
                return cols[slot][(_i + rot * self.E) % self.m]

            out_vals.append(sym_eval(sym, getval, {}))
        arr = to_limbs_fast(out_vals)
        if acc is None:
            return arr
        vals = from_limbs_fast(acc)
        summed = [(a + b) % R for a, b in zip(vals, out_vals)]
        return to_limbs_fast(summed)


def prove(
    pk: ProvingKey,
    cs: ConstraintSystem,
    instances: dict[str, list[int]] | list[int],
    seed: bytes | None = None,
    transcript: str = "poseidon",
) -> bytes:
    """Produce a PLONK proof that ``cs``'s trace satisfies the compiled
    circuit with the given public inputs."""
    vk = pk.vk
    k, n = vk.k, vk.n
    domain = Domain(k)
    srs = vk.srs
    advice, instance_cols, fixed = _classify_columns(cs)
    assert [c.name for c in advice] == vk.advice_names, "circuit/key mismatch"
    assert [c.name for c in instance_cols] == vk.instance_names
    assert cs.n_rows <= n - 1, "circuit overflows reserved last row"

    inst_map = _canon_instances(vk, instances)
    for col in instance_cols:
        vals = inst_map[col.name]
        for row, v in cs.trace[col].items():
            assert vals[row] == v % R, "instance values disagree with trace"

    rng = secrets.SystemRandom() if seed is None else __import__("random").Random(seed)
    # Deep attribution (PERF.md §12): native engine phase deltas + host
    # stage residuals, attached under the enclosing snark span.
    att = _ProveAttribution()

    def blind(coeffs: np.ndarray, n_blind: int) -> np.ndarray:
        """p + r(X)·Z_H with r random of n_blind coefficients.  The mask
        vanishes on the domain, so constraints are untouched; n_blind
        must be ≥ the number of rotations the polynomial is opened at,
        or the revealed evaluations over-determine the mask.  Operates
        on (len, 4) canonical-limb arrays; only the 2·n_blind touched
        rows round-trip through Python ints."""
        out = np.zeros((n + n_blind, 4), dtype=np.uint64)
        out[: coeffs.shape[0]] = coeffs
        for i in range(n_blind):
            b = rng.randrange(R)
            _set_row(out, i, (_row_int(out, i) - b) % R)
            _set_row(out, n + i, (_row_int(out, n + i) + b) % R)
        return out

    # Column value tables (n evals).
    def col_values(col: Column) -> list[int]:
        vals = [0] * n
        for row, v in cs.trace[col].items():
            vals[row] = v
        return vals

    advice_values = [col_values(c) for c in advice]
    instance_values = [
        list(inst_map[c.name]) + [0] * (n - len(inst_map[c.name]))
        for c in instance_cols
    ]

    transcript = _TRANSCRIPTS[transcript][0]()
    with att.stage("transcript"):
        transcript.common_scalar(vk.digest)
        for name in vk.instance_names:
            for v in inst_map[name]:
                transcript.common_scalar(v)

    slot_values: dict[int, list[int]] = {}
    n_adv, n_inst = len(advice), len(instance_cols)
    for i, vals in enumerate(advice_values):
        slot_values[i] = vals
    for i, vals in enumerate(instance_values):
        slot_values[n_adv + i] = vals
    for i, vals in enumerate(pk.fixed_values):
        slot_values[n_adv + n_inst + i] = vals

    # Round 1: advice commitments.  Zero-knowledge needs one blinder more
    # than the number of opening points, so derive the count from the
    # rotations each column is actually opened at instead of assuming 2.
    with att.stage("witness_gen"):
        advice_polys = [
            blind(domain.ifft_arr(v), len(vk.gate_rots.get(i, ())) + 1)
            for i, v in enumerate(advice_values)
        ]
    with att.stage("commit"):
        for c in srs.commit_batch(advice_polys):
            transcript.write_point(c)

    # Round 1.5: lookup permutations (Halo2 ordering: theta after
    # advice, A'/S' commitments before beta/gamma).
    with att.stage("transcript"):
        theta = transcript.squeeze_challenge() if vk.lookups else 0
    lk_a_vals: list[list[int]] = []  # compressed selector-gated inputs
    lk_t_vals: list[list[int]] = []  # compressed table
    lk_ap_vals: list[list[int]] = []  # A' (sorted input)
    lk_sp_vals: list[list[int]] = []  # S' (table permutation)
    lk_ap_polys: list[list[int]] = []
    lk_sp_polys: list[list[int]] = []
    with att.stage("witness_gen"):
        for lk in vk.lookups:
            sel_vals = slot_values[lk.sel_slot]
            padc = _theta_compress(lk.pad, theta)
            a_comp = [
                _theta_compress([slot_values[s][i] for s in lk.input_slots], theta)
                if sel_vals[i]
                else padc
                for i in range(n)
            ]
            t_comp = [
                _theta_compress(
                    [pk.fixed_values[ti][i] for ti in lk.table_fixed_idx], theta
                )
                for i in range(n)
            ]
            # Sort the active rows; build S' giving each first occurrence
            # its table copy.
            a_sorted = sorted(a_comp[: n - 1])
            remaining = Counter(t_comp[: n - 1])
            s_prime = [None] * (n - 1)
            fill_rows = []
            for i, val in enumerate(a_sorted):
                if i == 0 or val != a_sorted[i - 1]:
                    if remaining[val] <= 0:
                        raise AssertionError(
                            f"lookup {lk.name}: input {val:#x} not in table"
                        )
                    remaining[val] -= 1
                    s_prime[i] = val
                else:
                    fill_rows.append(i)
            leftovers = [v for v, c in sorted(remaining.items()) for _ in range(c)]
            assert len(leftovers) == len(fill_rows)
            for i, v in zip(fill_rows, leftovers):
                s_prime[i] = v
            lk_a_vals.append(a_comp)
            lk_t_vals.append(t_comp)
            lk_ap_vals.append(a_sorted + [0])
            lk_sp_vals.append(list(s_prime) + [0])
            ap_poly = blind(domain.ifft_arr(a_sorted + [0]), 3)
            sp_poly = blind(domain.ifft_arr(list(s_prime) + [0]), 3)
            lk_ap_polys.append(ap_poly)
            lk_sp_polys.append(sp_poly)
            transcript.write_point(srs.commit(ap_poly))
            transcript.write_point(srs.commit(sp_poly))

    with att.stage("transcript"):
        beta = transcript.squeeze_challenge()
        gamma = transcript.squeeze_challenge()

    with att.stage("witness_gen"):
        z_polys: list[list[int]] = []
        z_values: list[list[int]] = []
        start = 1
        for chunk in vk.chunks:  # within witness_gen accounting: host loop
            nums, dens = [1] * n, [1] * n
            for j in chunk:
                vals = slot_values[vk.perm_slots[j]]
                tag = vk.perm_tags[j]
                sig = pk.sigma_values[j]
                for i in range(n - 1):
                    nums[i] = (
                        nums[i] * ((vals[i] + beta * tag % R * pk.row_tags[i] + gamma) % R) % R
                    )
                    dens[i] = dens[i] * ((vals[i] + beta * sig[i] + gamma) % R) % R
            den_inv = _batch_inv(dens[: n - 1])
            z = [0] * n
            z[0] = start
            for i in range(n - 1):
                z[i + 1] = z[i] * nums[i] % R * den_inv[i] % R
            start = z[n - 1]
            z_values.append(z)
            # z is opened at up to 3 rotations (−1, 0, 1); 4 blinders.
            z_polys.append(blind(domain.ifft_arr(z), 4))
        if vk.chunks:
            assert start == 1, "permutation product != 1 (copy constraints broken?)"
    with att.stage("commit"):
        for c in srs.commit_batch(z_polys):
            transcript.write_point(c)

    with att.stage("witness_gen"):
        # Lookup grand products Z_i over the active rows.
        lk_z_polys: list[list[int]] = []
        for li in range(len(vk.lookups)):
            a_comp, t_comp = lk_a_vals[li], lk_t_vals[li]
            ap, sp_ = lk_ap_vals[li], lk_sp_vals[li]
            dens = [
                (ap[i] + beta) % R * ((sp_[i] + gamma) % R) % R for i in range(n - 1)
            ]
            den_inv = _batch_inv(dens)
            z = [0] * n
            z[0] = 1
            for i in range(n - 1):
                num = (a_comp[i] + beta) % R * ((t_comp[i] + gamma) % R) % R
                z[i + 1] = z[i] * num % R * den_inv[i] % R
            assert z[n - 1] == 1, "lookup product != 1 (input not a table subset?)"
            lk_z_polys.append(blind(domain.ifft_arr(z), 3))
            transcript.write_point(srs.commit(lk_z_polys[-1]))
    with att.stage("transcript"):
        y = transcript.squeeze_challenge()

    # Round 3: quotient.
    _quotient_stage = att.stage("quotient")
    _quotient_stage.__enter__()
    ev = _CosetEvaluator(k, vk.ext_factor)
    n_fixed = len(vk.fixed_names)
    base_slots = n_adv + n_inst + n_fixed
    sigma_slots = [base_slots + j for j in range(len(vk.perm_slots))]
    z_slots = [base_slots + len(sigma_slots) + c for c in range(len(vk.chunks))]
    x_slot = base_slots + len(sigma_slots) + len(z_slots)
    l0_slot, llast_slot = x_slot + 1, x_slot + 2
    n_lk = len(vk.lookups)
    lk_a_slots = [llast_slot + 1 + i for i in range(n_lk)]
    lk_s_slots = [llast_slot + 1 + n_lk + i for i in range(n_lk)]
    lk_z_slots = [llast_slot + 1 + 2 * n_lk + i for i in range(n_lk)]

    for i, p in enumerate(advice_polys):
        ev.set_coeffs(i, p)
    for i, vals in enumerate(instance_values):
        ev.set_coeffs(n_adv + i, domain.ifft_arr(vals))
    for i in range(len(pk.fixed_polys)):
        if pk.fixed_cosets:
            ev.set_values_ext(n_adv + n_inst + i, pk.fixed_cosets[i])
        else:
            ev.set_coeffs(n_adv + n_inst + i, pk.fixed_polys[i])
    for j in range(len(pk.sigma_polys)):
        if pk.sigma_cosets:
            ev.set_values_ext(sigma_slots[j], pk.sigma_cosets[j])
        else:
            ev.set_coeffs(sigma_slots[j], pk.sigma_polys[j])
    for c, p in enumerate(z_polys):
        ev.set_coeffs(z_slots[c], p)
    # Aux columns: X, l0, l_last on the coset.
    m = ev.m
    x_arr = _powers_arr(ev.ext.omega, m)
    shift_arr = np.broadcast_to(to_limbs([ev.shift]), (m, 4))
    x_out = np.empty((m, 4), dtype=np.uint64)
    _vec_mul_arr(x_arr, np.ascontiguousarray(shift_arr), x_out)
    ev.set_values_ext(x_slot, x_out)
    e0, elast = [0] * n, [0] * n
    e0[0] = 1
    elast[n - 1] = 1
    ev.set_coeffs(l0_slot, domain.ifft_arr(e0))
    ev.set_coeffs(llast_slot, domain.ifft_arr(elast))
    for i in range(n_lk):
        ev.set_coeffs(lk_a_slots[i], lk_ap_polys[i])
        ev.set_coeffs(lk_s_slots[i], lk_sp_polys[i])
        ev.set_coeffs(lk_z_slots[i], lk_z_polys[i])

    # y-combined constraint programs: one per gate, then permutation.
    programs: list[Sym] = []
    y_pow = 0
    for spec in vk.gates:
        combined = None
        for con in spec.constraints:
            term = Sym.const(pow(y, y_pow, R)) * con
            combined = term if combined is None else combined + term
            y_pow += 1
        programs.append(Sym.col(spec.sel_slot) * combined)
    for con in _perm_constraints(
        vk, beta, gamma, z_slots, sigma_slots, x_slot, l0_slot, llast_slot
    ):
        programs.append(Sym.const(pow(y, y_pow, R)) * con)
        y_pow += 1
    for con in _lookup_constraints(
        vk,
        theta,
        beta,
        gamma,
        lk_a_slots,
        lk_s_slots,
        lk_z_slots,
        l0_slot,
        llast_slot,
        n_adv + n_inst,
    ):
        programs.append(Sym.const(pow(y, y_pow, R)) * con)
        y_pow += 1

    # Refcount slots across programs for early frees (per unique slot
    # per program, matching the per-program decrement below).  Measured:
    # merging all programs into one evaluator pass is ~7% slower than
    # per-program passes (bigger working set per point), so keep them
    # separate.
    need: dict[int, int] = {}
    for prog in programs:
        for slot in {s for s, _ in prog.used_cols()}:
            need[slot] = need.get(slot, 0) + 1
    acc: np.ndarray | None = None
    for prog in programs:
        acc = ev.run(prog, acc)
        for slot in {s for s, _ in prog.used_cols()}:
            need[slot] -= 1
            if need[slot] == 0:
                ev.free(slot)

    # Divide by Z_H on the coset (E-periodic values).
    E = ev.E
    zh_period = [
        (pow(ev.shift, n, R) * pow(ev.ext.omega, (n * e) % m, R) - 1) % R
        for e in range(E)
    ]
    zh_inv = _batch_inv(zh_period)
    zh_tile = to_limbs_fast([zh_inv[i % E] for i in range(m)])
    if acc is None:
        acc = np.zeros((m, 4), dtype=np.uint64)
    _vec_mul_arr(acc, zh_tile, acc)
    t_arr = ev.ext.ntt_limbs(acc, ev.ext.omega_inv, True)
    shift_inv = pow(ev.shift, R - 2, R)
    sp_arr = _powers_arr(shift_inv, m)
    _vec_mul_arr(t_arr, sp_arr, t_arr)
    nz = np.nonzero(t_arr.any(axis=1))[0]
    t_limbs = t_arr[: int(nz[-1]) + 1] if nz.size else t_arr[:1]
    t_chunks = [t_limbs[i : i + n] for i in range(0, t_limbs.shape[0], n)]
    _quotient_stage.__exit__(None, None, None)
    with att.stage("commit"):
        for c in srs.commit_batch([np.ascontiguousarray(ch) for ch in t_chunks]):
            transcript.write_point(c)
    with att.stage("transcript"):
        x = transcript.squeeze_challenge()

    # Round 4: evaluations.
    entries = _opening_entries(vk, len(t_chunks))
    w = domain.omega

    def poly_of(kind: str, idx: int) -> np.ndarray:
        if kind == "advice":
            return advice_polys[idx]
        if kind == "fixed":
            return pk.fixed_polys[idx]
        if kind == "sigma":
            return pk.sigma_polys[idx]
        if kind == "z":
            return z_polys[idx]
        if kind == "lkA":
            return lk_ap_polys[idx]
        if kind == "lkS":
            return lk_sp_polys[idx]
        if kind == "lkZ":
            return lk_z_polys[idx]
        return t_chunks[idx]

    evals: dict[tuple[str, int, int], int] = {}
    with att.stage("open"):
        for kind, idx, rots in entries:
            p = poly_of(kind, idx)
            for rot in rots:
                pt = (
                    x * pow(w, rot, R) % R
                    if rot >= 0
                    else x * pow(domain.omega_inv, -rot, R) % R
                )
                val = _poly_eval_arr(p, pt)
                evals[(kind, idx, rot)] = val
                transcript.write_scalar(val)
    with att.stage("transcript"):
        v = transcript.squeeze_challenge()

    # Round 5: batched openings, one witness per rotation point.
    all_rots = sorted({rot for _, _, rots in entries for rot in rots})
    with att.stage("open"):
        for rot in all_rots:
            pt = (
                x * pow(w, rot, R) % R
                if rot >= 0
                else x * pow(domain.omega_inv, -rot, R) % R
            )
            group = [e for e in entries if rot in e[2]]
            max_len = max(poly_of(k, i).shape[0] for k, i, _ in group)
            agg = np.zeros((max_len, 4), dtype=np.uint64)
            v_pow = 1
            for kind, idx, _rots in group:
                _scale_add_arr(agg, poly_of(kind, idx), v_pow)
                v_pow = v_pow * v % R
            witness = _div_linear_arr(agg, pt)
            transcript.write_point(srs.commit(witness))

    att.attach()
    return transcript.finalize()


def _canon_instances(
    vk: VerifyingKey, instances: dict[str, list[int]] | list[int]
) -> dict[str, list[int]]:
    if isinstance(instances, dict):
        m = {k: [v % R for v in vals] for k, vals in instances.items()}
    else:
        assert len(vk.instance_names) <= 1, "multiple instance columns need a dict"
        m = {name: [v % R for v in instances] for name in vk.instance_names}
        if not vk.instance_names:
            assert not instances
    assert set(m) == set(vk.instance_names), "instance column mismatch"
    return m


# ---------------------------------------------------------------------------
# Verifier
# ---------------------------------------------------------------------------


def quotient_chunks(vk: VerifyingKey, proof_len: int) -> int:
    """Quotient-chunk count inferred from a proof's byte length — THE
    shared inference (Python verifier and EVM codegen must agree)."""
    pre_words = 2 * vk.n_advice + 6 * len(vk.lookups) + 2 * len(vk.chunks)
    entries_fixed = _opening_entries(vk, 0)
    n_evals_fixed = sum(len(rots) for _, _, rots in entries_fixed)
    rot_set = {rot for _, _, rots in entries_fixed for rot in rots}
    rot_set.add(0)
    remaining = proof_len - 32 * pre_words
    # Each t-chunk adds: 64 (commit) + 32 (eval). Fixed tail: evals + witnesses.
    fixed_tail = n_evals_fixed * 32 + len(rot_set) * 64
    return (remaining - fixed_tail) // 96


def verify(
    vk: VerifyingKey,
    instances: dict[str, list[int]] | list[int],
    proof: bytes,
    transcript: str = "poseidon",
) -> bool:
    _TRANSCRIPTS[transcript]  # unknown backend name must raise, not "invalid proof"
    try:
        return _verify_inner(vk, instances, proof, transcript) is True
    except (ValueError, AssertionError, IndexError, KeyError):
        return False


def verify_deferred(
    vk: VerifyingKey,
    instances: dict[str, list[int]] | list[int],
    proof: bytes,
    transcript: str = "poseidon",
):
    """Run every verifier check EXCEPT the final pairing; returns the
    accumulator pair (B, A) satisfying e(B, g2) == e(A, tau_g2) iff the
    proof is valid, or None when any non-pairing check fails.  The
    batch-verification primitive behind zk.aggregator (the reference's
    snark-verifier NativeLoader accumulation, verifier/aggregator.rs)."""
    _TRANSCRIPTS[transcript]
    try:
        out = _verify_inner(vk, instances, proof, transcript, defer_pairing=True)
    except (ValueError, AssertionError, IndexError, KeyError):
        return None
    return out if isinstance(out, tuple) else None


def _verify_inner(
    vk, instances, proof, transcript: str = "poseidon", defer_pairing: bool = False
):
    k, n = vk.k, vk.n
    domain = Domain(k)
    w = domain.omega
    inst_map = _canon_instances(vk, instances)

    t = _TRANSCRIPTS[transcript][1](proof)
    t.common_scalar(vk.digest)
    for name in vk.instance_names:
        for v in inst_map[name]:
            t.common_scalar(v)

    advice_commits = [t.read_point() for _ in vk.advice_names]
    theta = t.squeeze_challenge() if vk.lookups else 0
    lk_ap_commits, lk_sp_commits = [], []
    for _ in vk.lookups:
        lk_ap_commits.append(t.read_point())
        lk_sp_commits.append(t.read_point())
    beta = t.squeeze_challenge()
    gamma = t.squeeze_challenge()
    z_commits = [t.read_point() for _ in vk.chunks]
    lk_z_commits = [t.read_point() for _ in vk.lookups]
    y = t.squeeze_challenge()

    # t-chunk count is bounded by the extension factor (plus blinding
    # spill); read points until the count the prover committed.  The
    # count is recoverable because it is the only variable-length
    # section: infer from remaining length after fixing the rest.
    n_t = quotient_chunks(vk, len(proof))
    if n_t < 1 or n_t > 4 * vk.ext_factor:
        return False
    t_commits = [t.read_point() for _ in range(n_t)]
    x = t.squeeze_challenge()
    if pow(x, n, R) == 1:
        return False  # challenge on the domain: openings would be degenerate

    entries = _opening_entries(vk, n_t)
    evals: dict[tuple[str, int, int], int] = {}
    for kind, idx, rots in entries:
        for rot in rots:
            evals[(kind, idx, rot)] = t.read_scalar()
    v = t.squeeze_challenge()
    all_rots = sorted({rot for _, _, rots in entries for rot in rots})
    witnesses = {rot: t.read_point() for rot in all_rots}
    u = t.squeeze_challenge()
    if t._off != len(proof):
        return False  # trailing bytes

    # -- constraint check at x -----------------------------------------
    n_adv, n_inst, n_fixed = (
        len(vk.advice_names),
        len(vk.instance_names),
        len(vk.fixed_names),
    )
    base_slots = n_adv + n_inst + n_fixed
    sigma_slots = [base_slots + j for j in range(len(vk.perm_slots))]
    z_slots = [base_slots + len(sigma_slots) + c for c in range(len(vk.chunks))]
    x_slot = base_slots + len(sigma_slots) + len(z_slots)
    l0_slot, llast_slot = x_slot + 1, x_slot + 2
    n_lk = len(vk.lookups)
    lk_a_slots = [llast_slot + 1 + i for i in range(n_lk)]
    lk_s_slots = [llast_slot + 1 + n_lk + i for i in range(n_lk)]
    lk_z_slots = [llast_slot + 1 + 2 * n_lk + i for i in range(n_lk)]

    zh = (pow(x, n, R) - 1) % R
    n_inv = pow(n, R - 2, R)

    def lagrange_at(i: int) -> int:
        wi = pow(w, i, R)
        return wi * zh % R * n_inv % R * pow((x - wi) % R, R - 2, R) % R

    l0_val, llast_val = lagrange_at(0), lagrange_at(n - 1)
    inst_evals = {}
    for ci, name in enumerate(vk.instance_names):
        vals = {i: val for i, val in enumerate(inst_map[name]) if val}
        inst_evals[ci] = _lagrange_eval(vals, x, k)

    def getval(slot: int, rot: int) -> int:
        if slot == x_slot:
            assert rot == 0
            return x
        if slot == l0_slot:
            return l0_val
        if slot == llast_slot:
            return llast_val
        if slot < n_adv:
            return evals[("advice", slot, rot)]
        if slot < n_adv + n_inst:
            assert rot == 0, "instance rotations unsupported"
            return inst_evals[slot - n_adv]
        if slot < base_slots:
            return evals[("fixed", slot - n_adv - n_inst, rot)]
        if slot in sigma_slots:
            return evals[("sigma", slot - base_slots, rot)]
        if slot in lk_a_slots:
            return evals[("lkA", lk_a_slots.index(slot), rot)]
        if slot in lk_s_slots:
            return evals[("lkS", lk_s_slots.index(slot), rot)]
        if slot in lk_z_slots:
            return evals[("lkZ", lk_z_slots.index(slot), rot)]
        c = z_slots.index(slot)
        return evals[("z", c, rot)]

    combined = 0
    y_pow = 0
    memo: dict = {}
    for spec in vk.gates:
        sel = getval(spec.sel_slot, 0)
        for con in spec.constraints:
            term = sel * sym_eval(con, getval, memo) % R
            combined = (combined + pow(y, y_pow, R) * term) % R
            y_pow += 1
    for con in _perm_constraints(
        vk, beta, gamma, z_slots, sigma_slots, x_slot, l0_slot, llast_slot
    ):
        combined = (combined + pow(y, y_pow, R) * sym_eval(con, getval, {})) % R
        y_pow += 1
    for con in _lookup_constraints(
        vk,
        theta,
        beta,
        gamma,
        lk_a_slots,
        lk_s_slots,
        lk_z_slots,
        l0_slot,
        llast_slot,
        n_adv + n_inst,
    ):
        combined = (combined + pow(y, y_pow, R) * sym_eval(con, getval, {})) % R
        y_pow += 1

    t_eval = 0
    xn = pow(x, n, R)
    for c in range(n_t - 1, -1, -1):
        t_eval = (t_eval * xn + evals[("t", c, 0)]) % R
    if combined != t_eval * zh % R:
        return False

    # -- KZG batch opening check ---------------------------------------
    def commit_of(kind: str, idx: int) -> G1:
        if kind == "advice":
            return advice_commits[idx]
        if kind == "fixed":
            return vk.fixed_commits[idx]
        if kind == "sigma":
            return vk.sigma_commits[idx]
        if kind == "z":
            return z_commits[idx]
        if kind == "lkA":
            return lk_ap_commits[idx]
        if kind == "lkS":
            return lk_sp_commits[idx]
        if kind == "lkZ":
            return lk_z_commits[idx]
        return t_commits[idx]

    from .fields import pairing_check

    A = G1(0, 0)  # sum u^g W_g
    B = G1(0, 0)  # sum u^g (F_g - E_g*G + x_g*W_g)
    u_pow = 1
    for rot in all_rots:
        pt = (
            x * pow(w, rot, R) % R
            if rot >= 0
            else x * pow(domain.omega_inv, -rot, R) % R
        )
        F_scalars, F_points = [], []
        E_val = 0
        v_pow = 1
        for kind, idx, rots in entries:
            if rot not in rots:
                continue
            F_scalars.append(v_pow)
            F_points.append(commit_of(kind, idx))
            E_val = (E_val + v_pow * evals[(kind, idx, rot)]) % R
            v_pow = v_pow * v % R
        W = witnesses[rot]
        F = msm(F_scalars, F_points)
        term = F.add(GENERATOR.mul((-E_val) % R)).add(W.mul(pt))
        B = B.add(term.mul(u_pow) if u_pow != 1 else term)
        A = A.add(W.mul(u_pow) if u_pow != 1 else W)
        u_pow = u_pow * u % R
    srs = vk.srs
    if defer_pairing:
        return (B, A)
    return pairing_check([(B, srs.g2), (A.neg(), srs.tau_g2)])
