"""Bn254 extension-field tower, G2, and the optimal-ate pairing.

The reference gets all of this from `halo2curves::bn256` (used by the
KZG commitment scheme in circuit/src/utils.rs:259-303 and the
snark-verifier loaders, verifier/loader/native.rs).  This is a fresh
implementation of the public alt_bn128 parameters (EIP-196/197):

    Fq2  = Fq[u] / (u² + 1)
    Fq12 = Fq[w] / (w¹² − 18·w⁶ + 82)      (u ≡ w⁶ − 9)

G2 lives on the D-twist y² = x³ + 3/(9+u) over Fq2.  The pairing is
the ate pairing with loop count 6t+2 (t = 4965661367192848881),
implemented py_ecc-style: untwist Q into Fq12 and run the Miller loop
with affine line functions, then final-exponentiate.

Pure Python: the pairing only runs a handful of times per proof
verification (KZG check), never in the proving hot path.
"""

from __future__ import annotations

from typing import NamedTuple

from ..crypto.field import MODULUS as R  # Fr — the G1/G2 group order
from .rns import FQ_MODULUS as Q

# Curve parameter t; the ate loop count is 6t+2.
T_PARAM = 4965661367192848881
ATE_LOOP_COUNT = 6 * T_PARAM + 2  # 29793968203157093288

# Fq12 modulus polynomial w^12 - 18 w^6 + 82 as low-degree coeffs.
_FQ12_MOD = [82] + [0] * 5 + [-18] + [0] * 5


class FQP:
    """Element of Fq[w]/(m) for an arbitrary sparse monic modulus."""

    __slots__ = ("coeffs",)
    degree = 12
    mod_coeffs = _FQ12_MOD

    def __init__(self, coeffs):
        assert len(coeffs) == self.degree
        self.coeffs = [c % Q for c in coeffs]

    @classmethod
    def one(cls):
        return cls([1] + [0] * (cls.degree - 1))

    @classmethod
    def zero(cls):
        return cls([0] * cls.degree)

    def __add__(self, other):
        return type(self)([a + b for a, b in zip(self.coeffs, other.coeffs)])

    def __sub__(self, other):
        return type(self)([a - b for a, b in zip(self.coeffs, other.coeffs)])

    def __neg__(self):
        return type(self)([-a for a in self.coeffs])

    def __eq__(self, other):
        return isinstance(other, FQP) and self.coeffs == other.coeffs

    def __hash__(self):
        return hash(tuple(self.coeffs))

    def is_zero(self):
        return all(c == 0 for c in self.coeffs)

    def scale(self, k: int):
        return type(self)([c * k for c in self.coeffs])

    def __mul__(self, other):
        if isinstance(other, int):
            return self.scale(other)
        d = self.degree
        prod = [0] * (2 * d - 1)
        for i, a in enumerate(self.coeffs):
            if a:
                for j, b in enumerate(other.coeffs):
                    prod[i + j] += a * b
        # Reduce by the monic modulus: w^d = -mod_coeffs.
        for i in range(2 * d - 2, d - 1, -1):
            top = prod[i]
            if top:
                for j, m in enumerate(self.mod_coeffs):
                    if m:
                        prod[i - d + j] -= top * m
        return type(self)([c % Q for c in prod[:d]])

    def square(self):
        return self * self

    def pow(self, e: int):
        result = type(self).one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base * base
            e >>= 1
        return result

    def inv(self):
        """Extended Euclid over Fq[w] against the modulus polynomial."""
        d = self.degree
        lm, hm = [1] + [0] * d, [0] * (d + 1)
        low = list(self.coeffs) + [0]
        high = [m % Q for m in self.mod_coeffs] + [1]
        while _deg(low):
            r = _poly_div(high, low)
            r += [0] * (d + 1 - len(r))
            nm, new = list(hm), list(high)
            for i in range(d + 1):
                for j in range(d + 1 - i):
                    nm[i + j] -= lm[i] * r[j]
                    new[i + j] -= low[i] * r[j]
            nm = [x % Q for x in nm]
            new = [x % Q for x in new]
            lm, low, hm, high = nm, new, lm, low
        inv0 = pow(low[0], -1, Q)
        return type(self)([c * inv0 % Q for c in lm[:d]])

    def __repr__(self):
        return f"FQP{self.coeffs}"


def _deg(p):
    d = len(p) - 1
    while d and p[d] == 0:
        d -= 1
    return d


def _poly_div(a, b):
    """Quotient of polynomial division over Fq (py_ecc's poly_rounded_div)."""
    dega, degb = _deg(a), _deg(b)
    temp = list(a)
    out = [0] * len(a)
    binv = pow(b[degb], -1, Q)
    for i in range(dega - degb, -1, -1):
        out[i] = (out[i] + temp[degb + i] * binv) % Q
        for c in range(degb + 1):
            temp[c + i] = (temp[c + i] - out[i] * b[c]) % Q
    return [x % Q for x in out[: _deg(out) + 1]]


class FQ2(FQP):
    """Fq[u]/(u²+1) — the G2 coordinate field."""

    degree = 2
    mod_coeffs = [1, 0]


FQ2_ONE = FQ2([1, 0])
FQ2_ZERO = FQ2([0, 0])

# Twist curve constant b2 = 3 / (9 + u).
B2 = FQ2([3, 0]) * FQ2([9, 1]).inv()


class G2(NamedTuple):
    """Affine point on the twist; None coords encode the identity."""

    x: FQ2 | None
    y: FQ2 | None

    def is_identity(self) -> bool:
        return self.x is None

    def neg(self) -> "G2":
        if self.is_identity():
            return self
        return G2(self.x, -self.y)

    def double(self) -> "G2":
        if self.is_identity():
            return self
        x, y = self.x, self.y
        if y.is_zero():
            return G2_IDENTITY
        lam = x.square().scale(3) * y.scale(2).inv()
        x3 = lam.square() - x.scale(2)
        y3 = lam * (x - x3) - y
        return G2(x3, y3)

    def add(self, other: "G2") -> "G2":
        if self.is_identity():
            return other
        if other.is_identity():
            return self
        if self.x == other.x:
            if (self.y + other.y).is_zero():
                return G2_IDENTITY
            return self.double()
        lam = (other.y - self.y) * (other.x - self.x).inv()
        x3 = lam.square() - self.x - other.x
        y3 = lam * (self.x - x3) - self.y
        return G2(x3, y3)

    def mul(self, scalar: int) -> "G2":
        # No mod-R reduction: g2_in_subgroup relies on mul(R) acting as
        # the integer R on points of unknown order (the twist's cofactor
        # is > 1, so out-of-subgroup points exist on-curve).
        result = G2_IDENTITY
        addend = self
        s = scalar
        while s:
            if s & 1:
                result = result.add(addend)
            addend = addend.double()
            s >>= 1
        return result


G2_IDENTITY = G2(None, None)

#: Standard alt_bn128 G2 generator (EIP-197 / halo2curves bn256 G2Affine::generator).
G2_GENERATOR = G2(
    FQ2(
        [
            10857046999023057135944570762232829481370756359578518086990519993285655852781,
            11559732032986387107991004021392285783925812861821192530917403151452391805634,
        ]
    ),
    FQ2(
        [
            8495653923123431417604973247489272438418190587263600148770280649306958101930,
            4082367875863433681332203403145435568316851327593401208105741076214120093531,
        ]
    ),
)


def g2_is_on_curve(p: G2) -> bool:
    if p.is_identity():
        return True
    return p.y.square() == p.x.square() * p.x + B2


def g2_in_subgroup(p: G2) -> bool:
    """Order-r check (the twist has cofactor > 1, so on-curve alone is
    not enough for untrusted G2 inputs)."""
    return p.mul(R).is_identity()


# -- untwist into Fq12 -------------------------------------------------

# Embedding Fq2 -> Fq12 sends u to w^6 - 9.  An Fq2 element a + b·u maps
# to (a - 9b) + b·w^6.  The untwist scales x by w^2 and y by w^3, which
# lands on y^2 = x^3 + 3 over Fq12 (since w^6 = 9 + u = xi, the twist
# constant 3/xi picks up exactly xi).

_W2 = FQP([0] * 2 + [1] + [0] * 9)
_W3 = FQP([0] * 3 + [1] + [0] * 8)


def _embed_fq2(e: FQ2) -> FQP:
    a, b = e.coeffs
    coeffs = [0] * 12
    coeffs[0] = (a - 9 * b) % Q
    coeffs[6] = b
    return FQP(coeffs)


def untwist(p: G2) -> tuple[FQP, FQP]:
    assert not p.is_identity()
    return _embed_fq2(p.x) * _W2, _embed_fq2(p.y) * _W3


def _embed_fq(a: int) -> FQP:
    return FQP([a] + [0] * 11)


# -- Miller loop -------------------------------------------------------


def _linefunc(p1, p2, t):
    """Evaluate the line through p1, p2 (Fq12 affine pairs) at t."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if not (x1 - x2).is_zero():
        m = (y2 - y1) * (x2 - x1).inv()
        return m * (xt - x1) - (yt - y1)
    if y1 == y2:
        m = x1.square().scale(3) * y1.scale(2).inv()
        return m * (xt - x1) - (yt - y1)
    return xt - x1


def _ec_double(p):
    x, y = p
    m = x.square().scale(3) * y.scale(2).inv()
    nx = m.square() - x.scale(2)
    ny = m * (x - nx) - y
    return (nx, ny)


def _ec_add(p1, p2):
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and y1 == y2:
        return _ec_double(p1)
    m = (y2 - y1) * (x2 - x1).inv()
    nx = m.square() - x1 - x2
    ny = m * (x1 - nx) - y1
    return (nx, ny)


def _frobenius_pt(p):
    """(x^q, y^q) on the untwisted curve — the q-power endomorphism."""
    x, y = p
    return (x.pow(Q), y.pow(Q))


def miller_loop(q: G2, p) -> FQP:
    """f_{6t+2,Q}(P) with the two frobenius correction steps.

    ``p`` is a bn254.G1 affine point; identity inputs short-circuit to 1
    (pairing with identity is the unit, halo2curves semantics).
    """
    if q.is_identity() or p.is_identity():
        return FQP.one()
    qx, qy = untwist(q)
    pt = (_embed_fq(p.x), _embed_fq(p.y))
    r = (qx, qy)
    f = FQP.one()
    for i in range(ATE_LOOP_COUNT.bit_length() - 2, -1, -1):
        f = f * f * _linefunc(r, r, pt)
        r = _ec_double(r)
        if (ATE_LOOP_COUNT >> i) & 1:
            f = f * _linefunc(r, (qx, qy), pt)
            r = _ec_add(r, (qx, qy))
    q1 = _frobenius_pt((qx, qy))
    nq2 = _frobenius_pt(q1)
    nq2 = (nq2[0], -nq2[1])
    f = f * _linefunc(r, q1, pt)
    r = _ec_add(r, q1)
    f = f * _linefunc(r, nq2, pt)
    return f


_FINAL_EXP = (Q**12 - 1) // R


def final_exponentiation(f: FQP) -> FQP:
    """f^((q^12-1)/r), easy part via conjugation + inversion, hard part
    by direct square-and-multiply (short enough in Python)."""
    # Easy part: f^(q^6 - 1) = conj(f) / f, then ^(q^2 + 1).
    conj = FQP(
        [c if i % 2 == 0 else (-c) % Q for i, c in enumerate(f.coeffs)]
    )  # w -> -w is the q^6 frobenius on this tower
    f = conj * f.inv()
    f = f.pow(Q * Q) * f
    # Hard part.
    return f.pow((Q**4 - Q**2 + 1) // R)


def pairing(q: G2, p) -> FQP:
    """e(P, Q) — the full optimal-ate pairing."""
    return final_exponentiation(miller_loop(q, p))


def pairing_check(pairs) -> bool:
    """Π e(P_i, Q_i) == 1 with one shared final exponentiation — the
    multi-pairing the KZG verifier uses (2 pairs)."""
    acc = FQP.one()
    for p, q in pairs:
        acc = acc * miller_loop(q, p)
    return final_exponentiation(acc) == FQP.one()
