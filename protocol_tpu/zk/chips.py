"""Higher gadget chips: lookup range checks, Merkle path, Rescue-Prime.

Parity targets: gadgets/range.rs (LookupShortWordCheckChip /
LookupRangeCheckChip / RangeChipset), merkle_tree/mod.rs
(MerklePathChip), rescue_prime/mod.rs (the chip half of the alternate
hash) — re-built on this framework's lookup argument and rotation
gates.
"""

from __future__ import annotations

from ..crypto import field
from ..crypto.poseidon import RESCUE_PRIME_5, _INV5_EXP, HashParams
from .cs import Cell, ConstraintSystem
from .gadgets import StdGate

P = field.MODULUS


class RangeCheckChip:
    """K-bit word range checks via a lookup table, and running-sum
    decomposition for wider ranges (gadgets/range.rs re-designed).

    ``assert_word(x)`` looks x up in the [0, 2^K) table;
    ``assert_range(x, n_words)`` decomposes x into K-bit words with a
    weighted running sum (each word looked up) proving
    x < 2^(K·n_words).
    """

    def __init__(self, cs: ConstraintSystem, word_bits: int = 8):
        self.cs = cs
        self.word_bits = word_bits
        # Columns/selectors are per-width: two widths sharing one table
        # would check words against the wrong range.
        pre = f"rng{word_bits}"
        self._sel_word = f"{pre}_word"
        self._sel_sum = f"{pre}_sum"
        self._sel_init = f"{pre}_init"
        self.word = cs.column(f"{pre}_word")
        self.acc = cs.column(f"{pre}_acc")
        self.pw = cs.column(f"{pre}_pw", "fixed")
        if cs.register_chip(pre, word_bits):
            cs.lookup(
                f"{pre}_lookup", self._sel_word, [self.word], frozenset(range(1 << word_bits))
            )
            cs.gate(
                f"{pre}_sum",
                self._sel_sum,
                lambda v: (v[self.acc, 1] - v[self.acc] - v[self.word] * v[self.pw]) % P,
            )
            cs.gate(f"{pre}_init", self._sel_init, lambda v: v[self.acc])

    def assert_word(self, x: Cell) -> None:
        """x < 2^word_bits (LookupShortWordCheckChip)."""
        r = self.cs.alloc_rows(1)
        here = self.cs.assign(self.word, r, self.cs.value(x.column, x.row))
        self.cs.copy(here, x)
        self.cs.enable(self._sel_word, r)

    def assert_range(self, x: Cell, n_words: int) -> None:
        """x < 2^(word_bits·n_words) via word decomposition with every
        word table-checked (LookupRangeCheckChip)."""
        cs = self.cs
        k = self.word_bits
        value = cs.value(x.column, x.row)
        start = cs.alloc_rows(n_words + 1)
        acc = 0
        for i in range(n_words):
            word = (value >> (k * i)) & ((1 << k) - 1)
            r = start + i
            cs.assign(self.word, r, word)
            cs.assign(self.acc, r, acc)
            cs.assign(self.pw, r, pow(2, k * i, P))
            cs.enable(self._sel_word, r)
            cs.enable(self._sel_sum, r)
            if i == 0:
                cs.enable(self._sel_init, r)
            acc = (acc + word * pow(2, k * i, P)) % P
        final = cs.assign(self.acc, start + n_words, acc)
        cs.copy(final, x)


class MerklePathChip:
    """Prove a value's authentication path hashes to a root
    (merkle_tree/mod.rs:35 re-built): per level, the chip constrains
    parent = Poseidon(left, right, 0, 0, 0) and that the claimed value /
    prior parent appears among the pair — fixing the reference's
    OR-accumulator bug (its verify() is vacuously true,
    merkle_tree/native.rs:100-110)."""

    def __init__(self, cs: ConstraintSystem, std: StdGate, poseidon_chip):
        self.cs = cs
        self.std = std
        self.poseidon = poseidon_chip

    def verify_path(self, value: Cell, pairs: list[tuple[Cell, Cell]], root: Cell) -> None:
        std = self.std
        zero = std.constant(0)
        current = value
        for left, right in pairs:
            # current ∈ {left, right}: (current-left)·(current-right) = 0
            d1 = std.sub(current, left)
            d2 = std.sub(current, right)
            std.assert_zero(std.mul(d1, d2))
            current = self.poseidon.permute([left, right, zero, zero, zero])[0]
        std.assert_equal(current, root)


class RescuePrimeChip:
    """Rescue-Prime permutation in-circuit (rescue_prime/mod.rs:30).

    Each round row constrains, with S the state at the row and S' at
    the next: S' = MDS·inv5(MDS·sbox5(S) + rc_a) + rc_b.  The inverse
    S-box (x^{1/5}) is witnessed and checked forward: for the witnessed
    intermediate u, u^5 must equal the pre-inverse value — keeping the
    gate degree at 5 instead of the astronomic 1/5 exponent."""

    def __init__(self, cs: ConstraintSystem, params: HashParams = RESCUE_PRIME_5):
        self.cs = cs
        self.params = params
        w = params.width
        pre = f"rp{w}"
        self._sel = f"{pre}_round"
        self.state = [cs.column(f"{pre}_s{i}") for i in range(w)]
        # Witnessed post-inverse-sbox intermediate.
        self.mid = [cs.column(f"{pre}_m{i}") for i in range(w)]
        self.rc_a = [cs.column(f"{pre}_rca{i}", "fixed") for i in range(w)]
        self.rc_b = [cs.column(f"{pre}_rcb{i}", "fixed") for i in range(w)]
        mds = params.mds

        def round_poly(v):
            w_ = len(self.state)
            fwd = [field.pow5(v[self.state[j]]) for j in range(w_)]
            mixed = [
                (sum(mds[i][j] * fwd[j] for j in range(w_)) + v[self.rc_a[i]]) % P
                for i in range(w_)
            ]
            out = []
            # mid^5 == mixed  (the witnessed inverse S-box, checked forward)
            for i in range(w_):
                out.append((field.pow5(v[self.mid[i]]) - mixed[i]) % P)
            # next state = MDS·mid + rc_b
            for i in range(w_):
                nxt = (
                    sum(mds[i][j] * v[self.mid[j]] for j in range(w_))
                    + v[self.rc_b[i]]
                ) % P
                out.append((v[self.state[i], 1] - nxt) % P)
            return out

        if cs.register_chip(pre, (params.round_constants, params.mds)):
            cs.gate(f"{pre}_round", self._sel, round_poly)

    def permute(self, inputs: list[Cell]) -> list[Cell]:
        cs = self.cs
        params = self.params
        w = params.width
        rc = params.round_constants
        mds = params.mds
        n_rounds = params.full_rounds - 1
        start = cs.alloc_rows(n_rounds + 1)

        values = [cs.value(c.column, c.row) for c in inputs]
        for j in range(w):
            here = cs.assign(self.state[j], start, values[j])
            cs.copy(here, inputs[j])

        state = list(values)
        for rnd in range(n_rounds):
            row = start + rnd
            fwd = [field.pow5(x) for x in state]
            mixed = [
                (sum(mds[i][j] * fwd[j] for j in range(w)) + rc[rnd * w + i]) % P
                for i in range(w)
            ]
            mid = [pow(x, _INV5_EXP, P) for x in mixed]
            nxt = [
                (sum(mds[i][j] * mid[j] for j in range(w)) + rc[(rnd + 1) * w + i]) % P
                for i in range(w)
            ]
            for j in range(w):
                cs.assign(self.rc_a[j], row, rc[rnd * w + j])
                cs.assign(self.rc_b[j], row, rc[(rnd + 1) * w + j])
                cs.assign(self.mid[j], row, mid[j])
                cs.assign(self.state[j], row + 1, nxt[j])
            cs.enable(self._sel, row)
            state = nxt

        return [Cell(self.state[j], start + n_rounds) for j in range(w)]
