"""In-circuit EdDSA verification (eddsa/mod.rs::EddsaChipset re-built on
this framework's gadgets).

Constrains the native `verify` (eddsa/native.rs:130-147) exactly:
s ≤ suborder, Cl = B8·s, M = Poseidon(R ‖ PK ‖ m),
Cr = R + PK·M, affine(Cr) == affine(Cl) via cross-multiplied projective
equality.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.babyjubjub import B8, SUBORDER
from .cs import Cell, ConstraintSystem
from .gadgets import Bits2NumChip, EdwardsChip, LessEqChip, PoseidonChip, StdGate


@dataclass
class EddsaChipset:
    cs: ConstraintSystem
    std: StdGate
    edwards: EdwardsChip
    poseidon: PoseidonChip
    b2n: Bits2NumChip

    def verify(
        self,
        pk: tuple[Cell, Cell],
        big_r: tuple[Cell, Cell],
        s: Cell,
        message: Cell,
    ) -> None:
        std = self.std
        one = std.constant(1)
        lessq = LessEqChip(self.cs, std, self.b2n)

        # s ≤ suborder (the reference's lt_eq over the 252-bit suborder).
        suborder = std.constant(SUBORDER)
        lessq.assert_le(s, suborder)

        # Cl = B8 · s.  252 ladder bits: s ≤ suborder < 2^252, and
        # s + P needs 254 bits, so the bit pattern is forced canonical.
        b8 = (std.constant(B8.x), std.constant(B8.y), one)
        cl = self.edwards.scalar_mul(b8, s, n_bits=252)

        # M = Poseidon(R.x, R.y, PK.x, PK.y, m)
        m_hash = self.poseidon.permute(
            [big_r[0], big_r[1], pk[0], pk[1], message]
        )[0]

        # Cr = R + PK·M.  M is a full field element, so the ladder needs
        # the strict (< P) canonical-bits check.
        pk_proj = (pk[0], pk[1], one)
        pk_h = self.edwards.scalar_mul(
            pk_proj, m_hash, n_bits=254, strict=True, std=std, lessq=lessq
        )
        r_proj = (big_r[0], big_r[1], one)
        cr = self.edwards.add_points(r_proj, pk_h)

        # affine(Cr) == affine(Cl):  Cr.x·Cl.z = Cl.x·Cr.z  and same
        # for y (z values are nonzero for valid signatures; a zero z
        # would make both sides 0 only if the other coordinate is 0 too).
        std.assert_equal(std.mul(cr[0], cl[2]), std.mul(cl[0], cr[2]))
        std.assert_equal(std.mul(cr[1], cl[2]), std.mul(cl[1], cr[2]))
