"""Proof aggregation: KZG accumulation over the Poseidon transcript.

The working analog of the reference's aggregator (circuit/src/verifier/
aggregator.rs — left unfinished upstream: TODOs at :61-67,183-187,266
and a ``without_witnesses`` that returns self): verifying k PLONK
proofs costs 2 pairings each; accumulation folds them into ONE pairing
check.  Each proof's deferred verification yields an accumulator pair
(B_i, A_i) with e(B_i, g2) == e(A_i, τ·g2) iff the proof is valid;
a random linear combination with challenges r_i squeezed from a
Poseidon transcript over every (vk digest, instances, proof) binds the
batch: e(Σ r_i B_i, g2) == e(Σ r_i A_i, τ·g2) holds with overwhelming
probability only when every member holds.

All member proofs must share one SRS (same g2 / τ·g2), which the epoch
flow guarantees (one params file per deployment, data/params-14.bin
analog).  The in-circuit half (proving this accumulation inside another
PLONK circuit, snark-verifier's halo2 Loader) is exactly the part the
reference never finished; this module delivers the native half as a
sound, tested batch verifier.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from .bn254 import G1
from .plonk import R, VerifyingKey, verify_deferred
from .transcript import PoseidonWrite


@dataclass
class Snark:
    """One proof bundle (verifier/aggregator.rs:70-105 Snark analog)."""

    vk: VerifyingKey
    instances: list[int] | dict[str, list[int]]
    proof: bytes
    transcript: str = "poseidon"

    def instance_values(self) -> list[int]:
        if isinstance(self.instances, dict):
            out: list[int] = []
            for name in self.vk.instance_names:
                out.extend(self.instances[name])
            return out
        return list(self.instances)


@dataclass
class Accumulator:
    """Pending pairing check: e(lhs, g2) == e(rhs, tau_g2)."""

    lhs: G1
    rhs: G1

    def to_bytes(self) -> bytes:
        return b"".join(
            c.to_bytes(32, "little")
            for c in (self.lhs.x, self.lhs.y, self.rhs.x, self.rhs.y)
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "Accumulator":
        from .bn254 import is_on_curve
        from .rns import FQ_MODULUS

        if len(data) != 128:
            raise ValueError(f"accumulator must be 128 bytes, got {len(data)}")
        vals = [int.from_bytes(data[i : i + 32], "little") for i in range(0, 128, 32)]
        if any(v >= FQ_MODULUS for v in vals):
            raise ValueError("accumulator coordinate non-canonical")
        lhs, rhs = G1(vals[0], vals[1]), G1(vals[2], vals[3])
        for p in (lhs, rhs):
            if not is_on_curve(p):
                raise ValueError("accumulator point not on curve")
        return cls(lhs, rhs)


def proof_chunks(proof: bytes) -> list[int]:
    """A proof blob as 31-byte little-endian field-sized chunks — the
    transcript absorption unit shared by the native accumulator and the
    in-circuit fold (agg_circuit.synthesize_fold must absorb the exact
    same scalars)."""
    return [
        int.from_bytes(proof[i : i + 31], "little") for i in range(0, len(proof), 31)
    ]


def check_shared_srs(snarks: list[Snark]) -> None:
    """Soundness precondition — must survive python -O."""
    if not snarks:
        raise ValueError("nothing to accumulate")
    srs = snarks[0].vk.srs
    for s in snarks:
        if s.vk.srs.g2 != srs.g2 or s.vk.srs.tau_g2 != srs.tau_g2:
            raise ValueError("all member proofs must share one SRS")


def absorb_members(t, snarks: list[Snark]) -> None:
    """The member-binding absorption order (vk digest, instances,
    proof length, proof chunks) — one definition for the native
    accumulator AND the fold circuit's challenge derivation, so the
    two can never drift apart."""
    for s in snarks:
        t.common_scalar(s.vk.digest)
        for v in s.instance_values():
            t.common_scalar(v)
        t.common_scalar(len(s.proof))
        for chunk in proof_chunks(s.proof):
            t.common_scalar(chunk)


def accumulate(snarks: list[Snark]) -> Accumulator | None:
    """Fold the snarks' deferred pairing checks into one accumulator;
    None when any snark fails a non-pairing check (bad transcript,
    malformed points, constraint mismatch at the challenge)."""
    check_shared_srs(snarks)

    # Challenge transcript binds every member (Poseidon, like the
    # reference's PoseidonRead accumulation transcript).
    t = PoseidonWrite()
    absorb_members(t, snarks)

    lhs, rhs = G1(0, 0), G1(0, 0)
    for s in snarks:
        pair = verify_deferred(s.vk, s.instances, s.proof, s.transcript)
        if pair is None:
            return None
        b, a = pair
        r = t.squeeze_challenge()
        lhs = lhs.add(b.mul(r))
        rhs = rhs.add(a.mul(r))
    return Accumulator(lhs=lhs, rhs=rhs)


def finalize(acc: Accumulator, vk: VerifyingKey) -> bool:
    """The single decisive pairing check."""
    from .fields import pairing_check

    srs = vk.srs
    return pairing_check([(acc.lhs, srs.g2), (acc.rhs.neg(), srs.tau_g2)])


def aggregate_verify(snarks: list[Snark]) -> bool:
    """Batch-verify: k proofs, one pairing check."""
    acc = accumulate(snarks)
    if acc is None:
        return False
    return finalize(acc, snarks[0].vk)
