"""KZG polynomial commitments over Bn254 (the reference's commitment
scheme: halo2 `ParamsKZG` + GWC proving, circuit/src/utils.rs:198-303).

An SRS is the powers-of-tau ladder (tau^i G1 for i < n, plus tau G2).
`Setup.generate` derives tau from a seed — an insecure *test* setup,
exactly like the reference's `generate_params` which builds its SRS
from a local RNG (circuit/src/utils.rs:198-205); production would load
a ceremony transcript instead.

Commit is an MSM over the G1 ladder (native Pippenger via
zk.native when available, Python windowed fallback), open is the
quotient-witness commitment, verify is the standard two-pairing check.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ..crypto.field import MODULUS as R
from . import graft as zk_graft
from . import native as zk_native
from .bn254 import G1, GENERATOR, IDENTITY
from .fields import G2, G2_GENERATOR, pairing_check


def msm(scalars: list[int], points: list[G1]) -> G1:
    """Multi-scalar multiplication; dispatches on the ``zk_backend``
    knob — ``graft`` routes to the jit Pippenger, ``native`` to the C++
    kernel when built, else a Python windowed (4-bit bucket) method.

    Lengths must match exactly: callers that used to rely on the old
    silent ``points[: len(scalars)]`` truncation slice the ladder
    themselves now, so a mismatched call is always a bug upstream.
    """
    if len(scalars) != len(points):
        raise ValueError(
            f"msm length mismatch: {len(scalars)} scalars vs "
            f"{len(points)} points"
        )
    if zk_graft.zk_backend() == "graft":
        return zk_graft.msm(scalars, points)
    if zk_native.available() and len(scalars) >= 32:
        return zk_native.msm(scalars, points)
    return _msm_python(scalars, points)


def _msm_python(scalars: list[int], points: list[G1], window: int = 4) -> G1:
    buckets_per = 1 << window
    n_windows = (R.bit_length() + window - 1) // window
    total = IDENTITY
    for w in range(n_windows - 1, -1, -1):
        for _ in range(window):
            total = total.double()
        buckets = [IDENTITY] * buckets_per
        shift = w * window
        for s, p in zip(scalars, points):
            digit = (s >> shift) & (buckets_per - 1)
            if digit:
                buckets[digit] = buckets[digit].add(p)
        # Running-sum bucket reduction.
        acc = IDENTITY
        part = IDENTITY
        for b in reversed(buckets[1:]):
            acc = acc.add(b)
            part = part.add(acc)
        total = total.add(part)
    return total


@dataclass
class Setup:
    """The SRS: g1_powers[i] = tau^i G1; g2 generator and tau G2."""

    k: int
    g1_powers: list[G1]
    g2: G2
    tau_g2: G2

    @property
    def n(self) -> int:
        return 1 << self.k

    @classmethod
    def generate(cls, k: int, seed: bytes = b"protocol-tpu-srs") -> "Setup":
        tau = (
            int.from_bytes(hashlib.blake2b(seed, digest_size=64).digest(), "little") % R
        )
        n = 1 << k
        if zk_native.available() and n >= 64:
            powers = zk_native.srs_g1_powers(tau, n)
        else:
            powers = []
            acc = 1
            for _ in range(n):
                powers.append(GENERATOR.mul(acc))
                acc = acc * tau % R
        return cls(k, powers, G2_GENERATOR, G2_GENERATOR.mul(tau))

    def shrink(self, k: int) -> "Setup":
        """A lower-degree SRS is a prefix of a higher one (same tau) —
        the reference generates params-9..17 in one run this way."""
        assert k <= self.k
        return Setup(k, self.g1_powers[: 1 << k], self.g2, self.tau_g2)

    # -- serialization (data/params-{k}.bin equivalent) -----------------

    MAGIC = b"PTPUSRS1"

    def to_bytes(self) -> bytes:
        out = bytearray(self.MAGIC)
        out += self.k.to_bytes(4, "little")
        for p in self.g1_powers:
            out += p.x.to_bytes(32, "little") + p.y.to_bytes(32, "little")
        for pt in (self.g2, self.tau_g2):
            for coord in (pt.x, pt.y):
                for c in coord.coeffs:
                    out += c.to_bytes(32, "little")
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Setup":
        from .bn254 import is_on_curve
        from .fields import FQ2, g2_in_subgroup, g2_is_on_curve

        if data[:8] != cls.MAGIC:
            raise ValueError("bad SRS magic")
        k = int.from_bytes(data[8:12], "little")
        if k > 28:
            raise ValueError(f"implausible SRS degree k={k}")
        expected = 12 + 64 * (1 << k) + 2 * 128
        if len(data) != expected:
            raise ValueError(f"SRS length {len(data)} != expected {expected}")
        from .rns import FQ_MODULUS

        off = 12
        powers = []
        for i in range(1 << k):
            x = int.from_bytes(data[off : off + 32], "little")
            y = int.from_bytes(data[off + 32 : off + 64], "little")
            # Canonicality mirrors transcript.read_point: a coordinate
            # >= Fq aliases another point mod Q and breaks affine
            # arithmetic / native limb packing downstream.
            if x >= FQ_MODULUS or y >= FQ_MODULUS:
                raise ValueError(f"SRS G1 power {i} non-canonical")
            p = G1(x, y)
            if not is_on_curve(p):
                raise ValueError(f"SRS G1 power {i} not on curve")
            powers.append(p)
            off += 64
        g2pts = []
        for _ in range(2):
            coords = []
            for _ in range(2):
                for word_off in (off, off + 32):
                    if (
                        int.from_bytes(data[word_off : word_off + 32], "little")
                        >= FQ_MODULUS
                    ):
                        raise ValueError("SRS G2 coordinate non-canonical")
                c0 = int.from_bytes(data[off : off + 32], "little")
                c1 = int.from_bytes(data[off + 32 : off + 64], "little")
                coords.append(FQ2([c0, c1]))
                off += 64
            pt = G2(coords[0], coords[1])
            if not (g2_is_on_curve(pt) and g2_in_subgroup(pt)):
                raise ValueError("SRS G2 point invalid (curve/subgroup)")
            g2pts.append(pt)
        return cls(k, powers, g2pts[0], g2pts[1])

    # -- commitment scheme ----------------------------------------------

    def commit(self, coeffs) -> G1:
        """Commit to a coefficient-form polynomial (list of ints or an
        (n,4) canonical-limb array)."""
        import numpy as np

        if isinstance(coeffs, np.ndarray):
            return self.commit_limbs(coeffs)
        assert len(coeffs) <= self.n, "polynomial exceeds SRS degree"
        return msm([c % R for c in coeffs], self.g1_powers[: len(coeffs)])

    def _graft_cache(self):
        """Per-SRS device point cache: the once-per-prove bucket setup
        the graft Pippenger amortizes across every commit/open MSM."""
        cache = getattr(self, "_graft_points", None)
        if cache is None:
            cache = zk_graft.point_cache(self.g1_powers)
            object.__setattr__(self, "_graft_points", cache)
        return cache

    def commit_limbs(self, arr) -> G1:
        """Zero-conversion commitment: (n,4) canonical scalar limbs
        against a cached limb form of the G1 powers."""
        from . import native as zk_native

        assert arr.shape[0] <= self.n, "polynomial exceeds SRS degree"
        if zk_graft.zk_backend() == "graft":
            return zk_graft.msm_limbs(arr, self._graft_cache())
        cache = getattr(self, "_point_limbs", None)
        if cache is None:
            cache = zk_native._points_to_limbs(self.g1_powers)
            object.__setattr__(self, "_point_limbs", cache)
        return zk_native.msm_limbs(arr, cache[: arr.shape[0]])

    def commit_batch(self, arrs) -> list[G1]:
        """Commit a batch of (n_i, 4) canonical-limb polynomials.

        Under ``native`` this is exactly a loop of :meth:`commit_limbs`
        (byte-identical transcripts, trivially); under ``graft`` the
        batch shares one :class:`~.graft.pippenger.PointCache` and one
        set of compiled kernel shapes, which is where the per-prove
        bucket-setup amortization lives."""
        if zk_graft.zk_backend() == "graft":
            return zk_graft.msm_limbs_batch(arrs, self._graft_cache())
        return [self.commit(a) for a in arrs]

    def open(self, coeffs: list[int], z: int) -> tuple[int, G1]:
        """Evaluation y = p(z) and witness commitment W = [(p - y)/(X - z)]."""
        y = _eval_poly(coeffs, z)
        q = _div_by_linear(coeffs, z, y)
        return y, self.commit(q)

    def verify(self, commitment: G1, z: int, y: int, witness: G1) -> bool:
        """e(C - y G1, G2) == e(W, tau G2 - z G2)."""
        lhs = commitment.add(GENERATOR.mul((-y) % R))
        rhs_g2 = self.tau_g2.add(self.g2.mul((-z) % R))
        return pairing_check([(lhs, self.g2), (witness.neg(), rhs_g2)])


def _eval_poly(coeffs: list[int], z: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * z + c) % R
    return acc


def _div_by_linear(coeffs: list[int], z: int, y: int) -> list[int]:
    """(p(X) - y) / (X - z) by synthetic division: q_i = c_{i+1} + z q_{i+1},
    asserting the remainder matches the claimed evaluation."""
    quotient = [0] * max(len(coeffs) - 1, 0)
    acc = 0
    for i in range(len(coeffs) - 1, 0, -1):
        acc = (coeffs[i] + z * acc) % R
        quotient[i - 1] = acc
    rem = (coeffs[0] + z * acc) % R if coeffs else 0
    assert rem == y % R, "division remainder mismatch"
    return quotient
