"""Proof wire types and the Prover interface.

``Proof``/``ProofRaw`` mirror circuit/src/lib.rs:258-292: public inputs
as field elements / 32-byte LE reprs plus opaque proof bytes, JSON round-
trippable in the same shape the reference serves from ``GET /score``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from ..crypto import field
from ..crypto.poseidon import permute


@dataclass
class Proof:
    pub_ins: list[int]
    proof: bytes

    def to_raw(self) -> "ProofRaw":
        return ProofRaw(
            pub_ins=[field.to_le_bytes(x) for x in self.pub_ins], proof=self.proof
        )


@dataclass
class ProofRaw:
    pub_ins: list[bytes]
    proof: bytes

    def to_proof(self) -> Proof:
        return Proof(
            pub_ins=[field.from_le_bytes(x) for x in self.pub_ins], proof=self.proof
        )

    def to_json(self) -> str:
        # serde serializes [u8; 32] and Vec<u8> as JSON integer arrays.
        return json.dumps(
            {
                "pub_ins": [list(x) for x in self.pub_ins],
                "proof": list(self.proof),
            }
        )

    @classmethod
    def from_json(cls, s: str) -> "ProofRaw":
        obj = json.loads(s)
        return cls(
            pub_ins=[bytes(x) for x in obj["pub_ins"]],
            proof=bytes(obj["proof"]),
        )


class Prover:
    """Produces proof bytes binding public inputs to a witness."""

    name = "abstract"

    def prove(self, pub_ins: list[int], witness: dict) -> bytes:
        raise NotImplementedError

    def verify(self, pub_ins: list[int], proof: bytes) -> bool:
        raise NotImplementedError


class PoseidonCommitmentProver(Prover):
    """Poseidon commitment chain over the public inputs and witness ops.

    NOT zero-knowledge and NOT succinctness-equivalent to the reference's
    KZG proof — a deterministic binding commitment standing in for the
    PLONK prover while the circuit layer (protocol_tpu.zk.circuit)
    provides constraint-level checking.  The wire shape (opaque bytes
    alongside pub_ins) matches, so the node/client flow is end-to-end
    testable.
    """

    name = "poseidon-commitment"
    DOMAIN = int.from_bytes(b"protocol_tpu.commit.v1".ljust(32, b"\0"), "little") % field.MODULUS

    def _digest(self, pub_ins: list[int], witness: dict) -> int:
        acc = self.DOMAIN
        for x in pub_ins:
            acc = permute([acc, x, 1, 0, 0])[0]
        for row in witness.get("ops", []):
            for x in row:
                acc = permute([acc, x, 2, 0, 0])[0]
        return acc

    def prove(self, pub_ins: list[int], witness: dict) -> bytes:
        return field.to_le_bytes(self._digest(pub_ins, witness)) + json.dumps(
            {"ops": [[int(x) for x in row] for row in witness.get("ops", [])]}
        ).encode()

    def verify(self, pub_ins: list[int], proof: bytes) -> bool:
        if len(proof) < 32:
            return False
        digest, payload = proof[:32], proof[32:]
        try:
            witness = json.loads(payload.decode())
        except (UnicodeDecodeError, json.JSONDecodeError):
            return False
        return digest == field.to_le_bytes(self._digest(pub_ins, witness))
