"""Proof wire types and the Prover interface.

``Proof``/``ProofRaw`` mirror circuit/src/lib.rs:258-292: public inputs
as field elements / 32-byte LE reprs plus opaque proof bytes, JSON round-
trippable in the same shape the reference serves from ``GET /score``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from ..crypto import field
from ..crypto.poseidon import permute


@dataclass
class Proof:
    pub_ins: list[int]
    proof: bytes

    def to_raw(self, backend: str = "") -> "ProofRaw":
        return ProofRaw(
            pub_ins=[field.to_le_bytes(x) for x in self.pub_ins],
            proof=self.proof,
            backend=backend,
        )


@dataclass
class ProofRaw:
    pub_ins: list[bytes]
    proof: bytes
    #: Which prover produced ``proof``: "plonk" / "commitment" / ""
    #: (unknown — proof from a peer that predates the tag).  Serialized
    #: as an extra JSON key; absent on reference-format payloads, so
    #: round-tripping stays wire-compatible both ways.
    backend: str = ""

    def to_proof(self) -> Proof:
        return Proof(
            pub_ins=[field.from_le_bytes(x) for x in self.pub_ins], proof=self.proof
        )

    def to_json(self) -> str:
        # serde serializes [u8; 32] and Vec<u8> as JSON integer arrays.
        obj = {
            "pub_ins": [list(x) for x in self.pub_ins],
            "proof": list(self.proof),
        }
        if self.backend:
            obj["backend"] = self.backend
        return json.dumps(obj)

    @classmethod
    def from_json(cls, s: str) -> "ProofRaw":
        obj = json.loads(s)
        return cls(
            pub_ins=[bytes(x) for x in obj["pub_ins"]],
            proof=bytes(obj["proof"]),
            backend=obj.get("backend", ""),
        )


class Prover:
    """Produces proof bytes binding public inputs to a witness."""

    name = "abstract"
    #: Wire tag served in ProofRaw.backend so clients dispatch without
    #: sniffing proof bytes.  Empty = unknown; clients fall back to
    #: shape detection.
    wire_tag = ""

    def prove(
        self, pub_ins: list[int], witness: dict, *, seed: bytes | None = None
    ) -> bytes:
        """``seed`` (optional) derives the blinding randomness
        deterministically — the async proving plane passes a
        statement-bound seed (:func:`protocol_tpu.prover.jobs.job_seed`)
        so pooled and in-process proofs of the same statement are
        byte-identical.  None keeps system-RNG blinding."""
        raise NotImplementedError

    def verify(self, pub_ins: list[int], proof: bytes) -> bool:
        raise NotImplementedError


class PlonkEpochProver(Prover):
    """Real SNARK prover for the epoch statement: the EigenTrust
    circuit (zk.circuit) proved with the KZG-backed PLONK engine
    (zk.plonk) — the analog of the reference's Halo2 path behind
    ``Manager::calculate_proofs`` (manager/mod.rs:189-199 →
    verifier/mod.rs:62-83).

    Keygen runs once at construction, mirroring the reference's boot-
    time ``MANAGER_STORE`` keygen (server/src/main.rs:70-83, minutes-
    scale there, ~20 s here at the same k=14 circuit size).  The
    compiled key depends only on circuit *structure*, so any valid
    dummy statement parameterizes it.
    """

    name = "plonk-kzg"
    wire_tag = "plonk"

    def __init__(
        self,
        num_neighbours: int = 5,
        num_iter: int = 10,
        initial_score: int = 1000,
        scale: int = 1000,
        srs=None,
        srs_path: str | None = None,
        k: int | None = None,
        cache_dir: str | None = None,
    ):
        from ..crypto import calculate_message_hash
        from ..crypto.eddsa import SecretKey, sign
        from ..node.attestation import Attestation
        from ..trust.native import power_iterate
        from .circuit import prove_epoch_statement
        from . import plonk

        self._params = dict(
            num_neighbours=num_neighbours,
            num_iter=num_iter,
            initial_score=initial_score,
            scale=scale,
        )
        self._plonk = plonk
        self._prove_statement = prove_epoch_statement

        n = num_neighbours
        sks = [SecretKey.random() for _ in range(n)]
        pks = [sk.public() for sk in sks]
        # Rows must sum to `scale` for total-score conservation.
        base = scale // n
        row = [base] * (n - 1) + [scale - base * (n - 1)]
        rows = [list(row) for _ in range(n)]
        _, messages = calculate_message_hash(pks, rows)
        atts = [
            Attestation(sig=sign(sk, pk, m), pk=pk, neighbours=list(pks), scores=r)
            for sk, pk, m, r in zip(sks, pks, messages, rows)
        ]
        pub = power_iterate([initial_score] * n, rows, num_iter, scale)
        self._dummy_statement = (atts, pub)
        cs = prove_epoch_statement(atts, pub, **self._params)
        if srs is None and srs_path is None:
            # A fresh random setup is fine for development, but its
            # proofs will not verify against anyone else's
            # et_verifier.bin (different vk commitments), and its
            # toxic waste lives on this machine.  Make that loud.
            import logging

            logging.getLogger(__name__).warning(
                "PLONK prover booted WITHOUT a ceremony SRS (srs_path unset): "
                "generating a dev-only random setup (cached across boots). "
                "Proofs will only verify against artifacts generated from this "
                "same setup; do not use in production."
            )
        self._pk = self._compile_cached(cs, srs, srs_path, k, cache_dir)

    def _compile_cached(self, cs, srs, srs_path, k, cache_dir):
        """Disk-cached keygen: ``compile_circuit`` is deterministic given
        the circuit structure, SRS, and k, and takes ~13 s at k=14 —
        the reference pays its minutes-scale Halo2 keygen on every boot
        (server/src/main.rs:70-83); a node here pays it once per
        (circuit, SRS, code) triple.  The cache key folds in a hash of
        every source the compiled key depends on (the zk package, the
        crypto package it builds circuits over, and the native kernels)
        so a change to any of them invalidates it.

        Trust boundary: entries are pickles of the proving key — treat
        the cache directory like a key store (it is created 0700; a
        writer there can already substitute your proving key)."""
        import hashlib
        import json as _json
        import os
        import pickle
        import uuid
        from pathlib import Path

        from . import plonk

        root = cache_dir or os.environ.get("PROTOCOL_TPU_CACHE")
        if root is None:
            root = Path.home() / ".cache" / "protocol_tpu"
        root = Path(root)

        def load_srs():
            if srs is None and srs_path is not None:
                from .kzg import Setup

                try:
                    blob = Path(srs_path).read_bytes()
                except OSError as e:
                    raise FileNotFoundError(
                        f"SRS file {srs_path!r} (config key 'srs_path') "
                        f"could not be read: {e}"
                    ) from e
                return Setup.from_bytes(blob)
            return srs

        def open_cache_dir() -> int | None:
            """Create-then-verify the cache directory on an fd so a
            racing attacker can't swap in a loose-permission directory
            between check and use (all entry IO goes through dir_fd).
            Unpickling from a writable-by-others dir is code execution
            at boot, not just key substitution."""
            try:
                root.mkdir(parents=True, exist_ok=True, mode=0o700)
                fd = os.open(root, os.O_RDONLY | os.O_DIRECTORY)
            except OSError:
                return None
            st = os.fstat(fd)
            if st.st_uid != os.getuid() or st.st_mode & 0o077:
                try:
                    if st.st_uid == os.getuid():
                        os.fchmod(fd, 0o700)
                        return fd
                except OSError:
                    pass
                os.close(fd)
                import logging

                logging.getLogger(__name__).warning(
                    "ignoring PLONK key cache at %s: directory must be "
                    "owned by this user with mode 0700",
                    root,
                )
                return None
            return fd

        dir_fd = open_cache_dir()
        if dir_fd is None:
            return plonk.compile_circuit(cs, srs=load_srs(), k=k)

        h = hashlib.sha256()
        h.update(_json.dumps(self._params, sort_keys=True).encode())
        h.update(str(k).encode())
        if srs_path is not None and srs is None:
            h.update(b"srs-file")
            h.update(hashlib.sha256(Path(srs_path).read_bytes()).digest())
        elif srs is not None:
            # Setup objects are identified by size + a probe point (the
            # full g1 ladder is MBs; tau binds every power).
            h.update(f"srs-obj-{srs.k}-{srs.g1_powers[1]}-{srs.tau_g2}".encode())
        else:
            h.update(b"srs-dev-random")
        pkg = Path(__file__).resolve().parents[1]
        native = pkg.parent / "native"
        deps = sorted(
            str(p)
            for pat in ("zk/*.py", "crypto/*.py", "crypto/native/*.py", "utils/*.py")
            for p in pkg.glob(pat)
        ) + sorted(str(p) for pat in ("*.cpp", "*.h") for p in native.glob(pat))
        for dep in deps:
            h.update(Path(dep).read_bytes())
        key = h.hexdigest()[:32]
        name = f"plonk-pk-{key}.pkl"

        try:
            try:
                f = os.fdopen(os.open(name, os.O_RDONLY, dir_fd=dir_fd), "rb")
            except FileNotFoundError:
                pass
            else:
                try:
                    with f:
                        return pickle.load(f)
                except Exception:
                    try:
                        os.unlink(name, dir_fd=dir_fd)  # corrupt: recompute
                    except OSError:
                        pass

            pk = plonk.compile_circuit(cs, srs=load_srs(), k=k)
            try:
                tmp = f".{name}.{uuid.uuid4().hex}.tmp"
                with os.fdopen(
                    os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600, dir_fd=dir_fd),
                    "wb",
                ) as f:
                    pickle.dump(pk, f, protocol=pickle.HIGHEST_PROTOCOL)
                os.rename(tmp, name, src_dir_fd=dir_fd, dst_dir_fd=dir_fd)
            except OSError:
                pass  # cache is best-effort; proving works without it
            return pk
        finally:
            os.close(dir_fd)

    @property
    def vk(self):
        return self._pk.vk

    #: Proofs use the keccak transcript so they verify on-chain through
    #: the generated EVM verifier, like the reference's EvmTranscript
    #: proofs (verifier/mod.rs:70-83).
    TRANSCRIPT = "keccak"

    def prove(
        self, pub_ins: list[int], witness: dict, *, seed: bytes | None = None
    ) -> bytes:
        # Reuse a pre-synthesized constraint system (the manager's
        # check_circuit pass) rather than rebuilding the k=14 circuit.
        cs = witness.get("cs")
        if cs is None:
            cs = self._prove_statement(
                witness["attestations"], pub_ins, **self._params
            )
        return self._plonk.prove(
            self._pk, cs, pub_ins, seed=seed, transcript=self.TRANSCRIPT
        )

    def verify(self, pub_ins: list[int], proof: bytes) -> bool:
        return self._plonk.verify(
            self._pk.vk, pub_ins, proof, transcript=self.TRANSCRIPT
        )

    def generate_verifier_artifact(self):
        """Generate the EVM verifier contract for this circuit (the
        gen_evm_verifier_code analog): proves the keygen dummy
        statement once to pin the quotient-chunk count, then emits
        bytecode.  Returns (GeneratedVerifier, sample_pub_ins,
        sample_proof) — the sample is expensive (a full prove), so
        callers reuse it rather than proving again."""
        from .evm_verifier import generate_evm_verifier, infer_n_t

        atts, pub = self._dummy_statement
        cs = self._prove_statement(atts, pub, **self._params)
        sample = self._plonk.prove(self._pk, cs, pub, transcript=self.TRANSCRIPT)
        n_t = infer_n_t(self._pk.vk, sample)
        gen = generate_evm_verifier(
            self._pk.vk, n_t, self._params["num_neighbours"]
        )
        return gen, pub, sample


class PoseidonCommitmentProver(Prover):
    """Poseidon commitment chain over the public inputs and witness ops.

    NOT zero-knowledge and NOT succinctness-equivalent to the reference's
    KZG proof — a deterministic binding commitment standing in for the
    PLONK prover while the circuit layer (protocol_tpu.zk.circuit)
    provides constraint-level checking.  The wire shape (opaque bytes
    alongside pub_ins) matches, so the node/client flow is end-to-end
    testable.
    """

    name = "poseidon-commitment"
    wire_tag = "commitment"
    DOMAIN = (
        int.from_bytes(b"protocol_tpu.commit.v1".ljust(32, b"\0"), "little") % field.MODULUS
    )

    def _digest(self, pub_ins: list[int], witness: dict) -> int:
        acc = self.DOMAIN
        for x in pub_ins:
            acc = permute([acc, x, 1, 0, 0])[0]
        for row in witness.get("ops", []):
            for x in row:
                acc = permute([acc, x, 2, 0, 0])[0]
        return acc

    def prove(
        self, pub_ins: list[int], witness: dict, *, seed: bytes | None = None
    ) -> bytes:
        # Commitment proofs are deterministic already; seed is accepted
        # for interface uniformity and ignored.
        return field.to_le_bytes(self._digest(pub_ins, witness)) + json.dumps(
            {"ops": [[int(x) for x in row] for row in witness.get("ops", [])]}
        ).encode()

    def verify(self, pub_ins: list[int], proof: bytes) -> bool:
        if len(proof) < 32:
            return False
        digest, payload = proof[:32], proof[32:]
        try:
            witness = json.loads(payload.decode())
        except (UnicodeDecodeError, json.JSONDecodeError):
            return False
        return digest == field.to_le_bytes(self._digest(pub_ins, witness))
