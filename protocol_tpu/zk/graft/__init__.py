"""zk.graft — the jittable accelerator backend for the prover's inner
loops (PERF.md §22).

The native attribution named the enemy (msm 63.5% / ntt 7.1% of
whole-core prove time, PERF.md §16); this package is the same move the
trust kernels made in PR 2 — a jit'd, budget-pinned execution path
cross-checked bit-for-bit against the native one — applied to the
proving plane: a batched u32-limb Montgomery field layer
(:mod:`.field`), an iterative radix-2 NTT (:mod:`.ntt`), and a
vectorized Pippenger MSM whose bucket accumulation rides the repo's
sorted-segment machinery (:mod:`.pippenger`).

This module itself is **jax-free**: prover worker processes import it
for the dispatch knob and phase table, and only a worker that actually
selects ``zk_backend="graft"`` pays the jax import (the kernel modules
are loaded lazily on first use).  The math is exact — MSM and NTT
results are group elements / field vectors, not floats — so the graft
and native backends are byte-identical by construction and the parity
suite (tests/test_zk_graft.py) is the acceptance oracle.
"""

from __future__ import annotations

import contextlib
import threading

#: The registered jit kernels of the graft backend.  These names are
#: unioned into the graftlint registry walk (passes 1/8/12) and carry
#: KERNEL/COMM/MEM budget declarations next to the kernels they pin —
#: the same undeclared-budget-is-an-error policy as the trust backends.
ZK_KERNELS = (
    "zk-graft-mulmod",
    "zk-graft-ntt-stage",
    "zk-graft-msm-window",
    "zk-graft-msm-scan",
    "zk-graft-msm-bucket",
)


def registered_zk_kernels() -> list[str]:
    """Kernel names the analyzers must find budgets + recipes for."""
    return list(ZK_KERNELS)


# ---------------------------------------------------------------------------
# Backend knob
# ---------------------------------------------------------------------------

#: Process-wide default; per-thread overrides via use_zk_backend (the
#: proving plane's worker threads select per ProofJob).
_DEFAULT_BACKEND = "native"
_local = threading.local()

VALID_BACKENDS = ("native", "graft")


def zk_backend() -> str:
    """The active proving-kernel backend: ``native`` (default — the
    ctypes IFMA runtime with pure-python fallback) or ``graft``."""
    return getattr(_local, "backend", _DEFAULT_BACKEND)


def set_zk_backend(name: str) -> None:
    if name not in VALID_BACKENDS:
        raise ValueError(
            f"unknown zk_backend {name!r}; expected one of {VALID_BACKENDS}"
        )
    _local.backend = name


@contextlib.contextmanager
def use_zk_backend(name: str):
    """Scoped backend selection (what ``prove_job`` wraps the prove in,
    so pooled workers never leak a knob across jobs)."""
    if name not in VALID_BACKENDS:
        raise ValueError(
            f"unknown zk_backend {name!r}; expected one of {VALID_BACKENDS}"
        )
    prev = getattr(_local, "backend", None)
    _local.backend = name
    try:
        yield
    finally:
        if prev is None:
            del _local.backend
        else:
            _local.backend = prev


# ---------------------------------------------------------------------------
# Phase timers — same table shape as zk.native.phase_stats(), so
# plonk._ProveAttribution folds both engines into the same
# snark -> {msm, ntt, ...} span children (attribution survives a
# backend switch; tools/prover_pipe.py asserts it).
# ---------------------------------------------------------------------------

PHASES = ("msm", "ntt", "gate_eval", "field_ops", "srs")

_phase_lock = threading.Lock()
_phase_table: dict[str, dict[str, float]] = {
    p: {"calls": 0, "seconds": 0.0} for p in PHASES
}


def phase_stats() -> dict[str, dict[str, float]]:
    """Snapshot of the graft backend's per-phase host wall time (the
    kernels sync results back to host, so wall time includes device
    work — the analog of the native runtime's relaxed-atomic timers)."""
    with _phase_lock:
        return {p: dict(row) for p, row in _phase_table.items()}


def reset_phase_stats() -> None:
    with _phase_lock:
        for row in _phase_table.values():
            row["calls"] = 0
            row["seconds"] = 0.0


def _bump_phase(phase: str, seconds: float) -> None:
    with _phase_lock:
        row = _phase_table[phase]
        row["calls"] += 1
        row["seconds"] += seconds


def phase_delta(before, after):
    """Per-phase difference of two snapshots (mirrors
    ``zk.native.phase_delta`` so attribution code treats both tables
    uniformly)."""
    out = {}
    for p in PHASES:
        b = before.get(p, {"calls": 0, "seconds": 0.0})
        a = after.get(p, {"calls": 0, "seconds": 0.0})
        out[p] = {
            "calls": a["calls"] - b["calls"],
            "seconds": a["seconds"] - b["seconds"],
        }
    return out


# ---------------------------------------------------------------------------
# Lazy kernel entry points (jax imported on first graft call only)
# ---------------------------------------------------------------------------


def msm(scalars, points):
    """Graft MSM over affine G1 points; exact, identity-aware."""
    from . import pippenger as _msm

    return _msm.msm(scalars, points)


def msm_limbs(arr, cache):
    """Graft MSM over a prepared :class:`~.pippenger.PointCache` with (n, 4)
    u64 canonical scalar limbs (the ``Setup.commit_limbs`` fast path)."""
    from . import pippenger as _msm

    return _msm.msm_limbs(arr, cache)


def msm_limbs_batch(arrs, cache):
    from . import pippenger as _msm

    return _msm.msm_limbs_batch(arrs, cache)


def point_cache(points):
    """Build (and the caller caches) the device-side point
    preprocessing — the once-per-prove bucket setup."""
    from . import pippenger as _msm

    return _msm.PointCache.build(points)


def ntt_limbs(arr, root, inverse):
    """In-place-shaped NTT over (n, 4) u64 canonical Fr limbs."""
    from . import ntt as _ntt

    return _ntt.ntt_limbs(arr, root, inverse)


__all__ = [
    "PHASES",
    "VALID_BACKENDS",
    "ZK_KERNELS",
    "msm",
    "msm_limbs",
    "msm_limbs_batch",
    "ntt_limbs",
    "phase_delta",
    "phase_stats",
    "point_cache",
    "registered_zk_kernels",
    "reset_phase_stats",
    "set_zk_backend",
    "use_zk_backend",
    "zk_backend",
]
