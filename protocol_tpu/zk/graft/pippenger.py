"""Vectorized Pippenger MSM over BN254 G1 (PERF.md §22).

Window decomposition: c=8 fixed windows — byte ``w`` of the 32-byte
little-endian scalar is the window-``w`` digit, so digit extraction is
a ``view(uint8)`` and all 32 windows batch through every kernel as one
leading axis (bucket setup — point conversion + compiled kernels — is
paid once per prove via :class:`PointCache`).

Bucket accumulation rides the repo's sorted-segment machinery
(:mod:`protocol_tpu.ops.segments`, the ``ops/sparse.py`` rowsum shape):
per window, digits are argsorted, points gathered into digit order, and
per-bucket sums folded with a **two-level segmented fold** — a
block-local sequential fold (``lax.scan`` over B=64 columns) followed
by a Hillis–Steele carry scan over the block tails.  That is O(n) group
adds total instead of the O(n log n) of a flat scan — the same
hierarchy ``rowsum_sorted`` uses, with the EC group as the monoid.
Every scatter is honestly ``unique_indices``: a digit's run ends at
exactly one lane, and non-end lanes are parked at distinct
out-of-range-of-``[:256]`` slots.

Points are Jacobian over Fq in the Montgomery domain, ``Z == 0`` is the
point at infinity.  Addition is complete: identity lanes resolve by
select, ``P == -Q`` collapses to ``Z3 = 0`` automatically, and the
rare ``P == Q`` collision (a discrete-log relation between SRS sums)
is patched by a ``lax.cond`` whose double branch only executes when a
collision actually occurs — completeness at ~zero amortized cost.

The last mile — 255 bucket-weighted sums per window and the Horner
window combine — is O(windows · nonempty buckets) exact Python-int
Jacobian math on the host, ending in the single modular inversion of
the whole MSM.
"""

from __future__ import annotations

import time

import numpy as np

from ...utils.limbs import to_limbs_fast
from ..bn254 import G1
from ..rns import FQ_MODULUS as Q
from ...crypto.field import MODULUS as FR_MOD
from . import _bump_phase
from .field import FQ, NLIMBS, is_zero, limbs_to_ints, u64_to_limbs

WINDOWS = 32
C_BITS = 8
N_BUCKETS = 1 << C_BITS
BLOCK = 64


# ---------------------------------------------------------------------------
# Traced EC group law (Jacobian over Montgomery Fq), (..., 3, 16) u32
# ---------------------------------------------------------------------------


def _jdbl(p):
    """dbl-2009-l, 7 muls; Z==0 stays Z==0 (infinity is absorbing)."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = FQ.mont_mul(x, x)
    b = FQ.mont_mul(y, y)
    c = FQ.mont_mul(b, b)
    t = FQ.add(x, b)
    d = FQ.sub(FQ.sub(FQ.mont_mul(t, t), a), c)
    d = FQ.add(d, d)
    e = FQ.add(FQ.add(a, a), a)
    f = FQ.mont_mul(e, e)
    x3 = FQ.sub(f, FQ.add(d, d))
    c8 = FQ.add(c, c)
    c8 = FQ.add(c8, c8)
    c8 = FQ.add(c8, c8)
    y3 = FQ.sub(FQ.mont_mul(e, FQ.sub(d, x3)), c8)
    z3 = FQ.mont_mul(y, z)
    z3 = FQ.add(z3, z3)
    import jax.numpy as jnp

    return jnp.stack([x3, y3, z3], axis=-2)


def _jadd(p, q):
    """Complete Jacobian add (add-2007-bl shape, 16 muls).

    ``P == -Q`` needs no select: ``H == 0`` forces ``Z3 = 0``.  The
    ``P == Q`` collision is patched under ``lax.cond`` so the doubling
    formula's 7 extra muls are only paid when a collision exists in
    the batch (for MSM partial sums that is a discrete-log relation —
    essentially never — but completeness is the contract)."""
    import jax
    import jax.numpy as jnp

    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    z1z = is_zero(z1)
    z2z = is_zero(z2)
    z1z1 = FQ.mont_mul(z1, z1)
    z2z2 = FQ.mont_mul(z2, z2)
    u1 = FQ.mont_mul(x1, z2z2)
    u2 = FQ.mont_mul(x2, z1z1)
    s1 = FQ.mont_mul(y1, FQ.mont_mul(z2, z2z2))
    s2 = FQ.mont_mul(y2, FQ.mont_mul(z1, z1z1))
    h = FQ.sub(u2, u1)
    r = FQ.sub(s2, s1)
    hh = FQ.mont_mul(h, h)
    hhh = FQ.mont_mul(h, hh)
    v = FQ.mont_mul(u1, hh)
    r2 = FQ.mont_mul(r, r)
    x3 = FQ.sub(FQ.sub(r2, hhh), FQ.add(v, v))
    y3 = FQ.sub(FQ.mont_mul(r, FQ.sub(v, x3)), FQ.mont_mul(s1, hhh))
    z3 = FQ.mont_mul(FQ.mont_mul(z1, z2), h)
    gen = jnp.stack([x3, y3, z3], axis=-2)

    need_dbl = is_zero(h) & is_zero(r) & ~z1z & ~z2z
    gen = jax.lax.cond(
        jnp.any(need_dbl),
        lambda g: jnp.where(need_dbl[..., None, None], _jdbl(p), g),
        lambda g: g,
        gen,
    )
    out = jnp.where(z2z[..., None, None], p, gen)
    return jnp.where(z1z[..., None, None], q, out)


# ---------------------------------------------------------------------------
# The four jitted kernels of one MSM (names match the budget registry)
# ---------------------------------------------------------------------------


def _kernels():
    """Build (once) the jitted kernel table; jax loads lazily here."""
    global _K
    try:
        return _K
    except NameError:
        pass
    import jax
    import jax.numpy as jnp

    from ...ops.segments import run_end_mask, segmented_carry_scan

    @jax.jit
    def window(digits, points):
        # zk-graft-msm-window: per-window digit sort + point gather.
        order = jnp.argsort(digits, axis=-1)
        ds = jnp.take_along_axis(digits, order, axis=-1)
        pts = points[order]
        return ds, pts

    @jax.jit
    def fold(ptsb, dsb):
        # zk-graft-msm-scan (level 1): block-local sequential fold.
        cols = jnp.moveaxis(ptsb, 2, 0)  # (B, W, nb, 3, 16)
        sames = jnp.moveaxis(dsb[..., 1:] == dsb[..., :-1], 2, 0)

        def step(run, xs):
            col, same = xs
            nxt = jnp.where(same[..., None, None], _jadd(run, col), col)
            return nxt, nxt

        init = cols[0]
        tails, scans = jax.lax.scan(step, init, (cols[1:], sames))
        local = jnp.concatenate([init[None], scans], axis=0)
        return jnp.moveaxis(local, 0, 2), tails

    @jax.jit
    def carry(tails, flags):
        # zk-graft-msm-scan (level 2): segmented H-S over block tails.
        return segmented_carry_scan(tails, flags, _jadd, axis=1)

    @jax.jit
    def bucket(local, ds, dsb, c):
        # zk-graft-msm-bucket: run-end extraction + two unique scatters.
        w, n = ds.shape
        blk = n // c.shape[1]
        ends = run_end_mask(ds)
        lane = jnp.arange(n)
        head = jnp.repeat(dsb[:, :, 0], blk, axis=-1)
        tail_prev = jnp.repeat(jnp.roll(dsb[:, :, -1], 1, axis=-1), blk, axis=-1)
        in_head_run = (ds == head) & (lane // blk > 0) & (tail_prev == ds)
        c_prev = jnp.repeat(jnp.roll(c, 1, axis=1), blk, axis=1)

        rows = jnp.arange(w)[:, None]
        park = N_BUCKETS + lane
        idx_local = jnp.where(ends, ds, park)
        buf = jnp.zeros((w, N_BUCKETS + n, 3, NLIMBS), jnp.uint32)
        b_local = buf.at[rows, idx_local].set(local, unique_indices=True)
        idx_carry = jnp.where(ends & in_head_run, ds, park)
        b_carry = buf.at[rows, idx_carry].set(c_prev, unique_indices=True)
        # zeros are Z == 0 == infinity, so empty buckets / parked lanes
        # vanish in the combine.
        out = _jadd(b_local[:, :N_BUCKETS], b_carry[:, :N_BUCKETS])
        return FQ.from_mont(out)

    _K = {"window": window, "fold": fold, "carry": carry, "bucket": bucket}
    return _K


# ---------------------------------------------------------------------------
# Point preprocessing (once per prove)
# ---------------------------------------------------------------------------


def _points_to_u64(points) -> np.ndarray:
    if isinstance(points, np.ndarray):
        return np.ascontiguousarray(points, dtype=np.uint64)
    buf = b"".join(
        p.x.to_bytes(32, "little") + p.y.to_bytes(32, "little") for p in points
    )
    return np.frombuffer(buf, dtype=np.uint64).reshape(-1, 8).copy()


class PointCache:
    """Device-resident Montgomery-Jacobian points, padded to a power of
    two so every MSM over a prefix of the SRS reuses the same compiled
    shapes (sliced, never re-converted)."""

    __slots__ = ("n", "padded", "points")

    def __init__(self, n: int, padded: int, points):
        self.n = n
        self.padded = padded
        self.points = points

    @classmethod
    def build(cls, points) -> "PointCache":
        import jax.numpy as jnp

        raw = _points_to_u64(points)
        n = raw.shape[0]
        if n == 0:
            raise ValueError("empty point set")
        padded = 1 << max(0, (n - 1).bit_length())
        if padded > n:
            raw = np.concatenate([raw, np.repeat(raw[:1], padded - n, axis=0)])
        x = u64_to_limbs(raw[:, :4])
        y = u64_to_limbs(raw[:, 4:])
        ident = ~np.logical_or(x.any(axis=1), y.any(axis=1))
        xm = FQ.to_mont(jnp.asarray(x))
        ym = FQ.to_mont(jnp.asarray(y))
        one = np.broadcast_to(FQ.r_np, (padded, NLIMBS)).copy()
        one[ident] = 0
        cache = jnp.stack([xm, ym, jnp.asarray(one)], axis=1)  # (n, 3, 16)
        return cls(n, padded, cache)


# ---------------------------------------------------------------------------
# Host last mile: exact Python-int Jacobian bucket reduction
# ---------------------------------------------------------------------------


def _hdbl(p):
    if p is None:
        return None
    x, y, z = p
    a = x * x % Q
    b = y * y % Q
    c = b * b % Q
    d = 2 * ((x + b) * (x + b) - a - c) % Q
    e = 3 * a % Q
    f = e * e % Q
    x3 = (f - 2 * d) % Q
    y3 = (e * (d - x3) - 8 * c) % Q
    z3 = 2 * y * z % Q
    return (x3, y3, z3)


def _hadd(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = z1 * z1 % Q
    z2z2 = z2 * z2 % Q
    u1 = x1 * z2z2 % Q
    u2 = x2 * z1z1 % Q
    s1 = y1 * z2 * z2z2 % Q
    s2 = y2 * z1 * z1z1 % Q
    if u1 == u2:
        if s1 == s2:
            return _hdbl(p)
        return None
    h = (u2 - u1) % Q
    r = (s2 - s1) % Q
    hh = h * h % Q
    hhh = h * hh % Q
    v = u1 * hh % Q
    x3 = (r * r - hhh - 2 * v) % Q
    y3 = (r * (v - x3) - s1 * hhh) % Q
    z3 = z1 * z2 % Q * h % Q
    return (x3, y3, z3)


def _hmul(p, k):
    acc = None
    while k:
        if k & 1:
            acc = _hadd(acc, p)
        p = _hdbl(p)
        k >>= 1
    return acc


def _finish(buckets: np.ndarray) -> G1:
    """(32, 256, 3, 16) canonical Fq limb buckets -> affine G1.

    Per window a descending running sum (empty-gap runs collapsed into
    one scalar multiple) then Horner across windows; one inversion."""
    zmask = buckets[:, :, 2, :].any(axis=-1)
    ws, ds = np.nonzero(zmask)
    vals = {}
    if len(ws):
        flat = buckets[ws, ds].reshape(len(ws), 3 * NLIMBS)
        ints = limbs_to_ints(flat.reshape(-1, NLIMBS))
        for i, (w, d) in enumerate(zip(ws, ds)):
            vals[(int(w), int(d))] = tuple(ints[3 * i : 3 * i + 3])

    total = None
    for w in reversed(range(WINDOWS)):
        if total is not None:
            for _ in range(C_BITS):
                total = _hdbl(total)
        s = None
        acc = None
        gap = 0
        for d in range(N_BUCKETS - 1, 0, -1):
            b = vals.get((w, d))
            if b is None:
                if s is not None:
                    gap += 1
                continue
            if gap:
                acc = _hadd(acc, _hmul(s, gap))
                gap = 0
            s = _hadd(s, b)
            acc = _hadd(acc, s)
        if gap:
            acc = _hadd(acc, _hmul(s, gap))
        total = _hadd(total, acc)

    if total is None or total[2] == 0:
        return G1(0, 0)
    x, y, z = total
    zinv = pow(z, Q - 2, Q)
    zi2 = zinv * zinv % Q
    return G1(x * zi2 % Q, y * zi2 % Q * zinv % Q)


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def msm_limbs(scalars: np.ndarray, cache: PointCache) -> G1:
    """MSM of (n, 4) canonical u64 scalar limbs against a point cache.

    Scalars are zero-padded up to the compiled power-of-two shape —
    digit-0 lanes never leave bucket 0, which the reduction skips, so
    padding is free and every commit of one prove shares compilations.
    """
    t0 = time.perf_counter()
    import jax.numpy as jnp

    n = int(scalars.shape[0])
    if n > cache.n:
        raise ValueError(
            f"msm length mismatch: {n} scalars vs {cache.n} points"
        )
    if n == 0:
        return G1(0, 0)
    m = 1 << max(0, (n - 1).bit_length())
    arr = np.ascontiguousarray(scalars, dtype=np.uint64)
    if m > n:
        arr = np.concatenate([arr, np.zeros((m - n, 4), np.uint64)])
    digits = np.ascontiguousarray(arr).view(np.uint8).reshape(m, 32).T
    k = _kernels()
    ds, pts = k["window"](jnp.asarray(digits.astype(np.int32)), cache.points[:m])
    blk = min(BLOCK, m)
    nb = m // blk
    dsb = ds.reshape(WINDOWS, nb, blk)
    local, tails = k["fold"](pts.reshape(WINDOWS, nb, blk, 3, NLIMBS), dsb)
    from ...ops.segments import block_boundary_flags

    c = k["carry"](tails, block_boundary_flags(dsb))
    buckets = np.asarray(k["bucket"](local.reshape(WINDOWS, m, 3, NLIMBS), ds, dsb, c))
    out = _finish(buckets)
    _bump_phase("msm", time.perf_counter() - t0)
    return out


def msm_limbs_batch(arrs, cache: PointCache):
    """The ~37 commit/open MSMs of one prove against one shared cache;
    same-shape polynomials reuse every compiled kernel."""
    return [msm_limbs(a, cache) for a in arrs]


def msm(scalars, points) -> G1:
    """List-of-ints MSM (the ``kzg.msm`` dispatch target)."""
    if len(scalars) != len(points):
        raise ValueError(
            f"msm length mismatch: {len(scalars)} scalars vs "
            f"{len(points)} points"
        )
    if not scalars:
        return G1(0, 0)
    cache = PointCache.build(points)
    arr = to_limbs_fast([s % FR_MOD for s in scalars])
    return msm_limbs(arr, cache)


# ---------------------------------------------------------------------------
# Pinned kernel invariants (graftlint passes 1/8/12).  Rows are per
# point-lane (n); the window axis is a constant 32 factor folded into
# the coefficients.
# ---------------------------------------------------------------------------

from ...analysis.budget import (  # noqa: E402  (kept next to the kernels)
    CommBudget,
    KernelBudget,
    MemBudget,
    declare,
    declare_comm,
    declare_mem,
)

declare(
    KernelBudget(
        backend="zk-graft-msm-window",
        max_random_gathers=2,
        max_scatters=0,
        require_primitives=("sort",),
        notes="digit argsort + digit/point permute gathers; the only "
        "random gathers in the MSM",
    )
)

declare_comm(
    CommBudget(
        backend="zk-graft-msm-window",
        notes="single-device sort/permute: no wire, no host traffic",
    )
)

declare_mem(
    MemBudget(
        backend="zk-graft-msm-window",
        # Measured (buffer assignment, N=1024/2048): resident 320 B/lane
        # (digit rows + the (n,3,16) point ladder), transient 6401
        # B/lane — the (32, n, 3, 16) gathered point batch is the
        # output, plus one permute staging temp.
        resident_n=384.0,
        resident_const=8192.0,
        transient_n=8192.0,
        transient_const=32768.0,
        notes="dominated by the (32, n, 3, 16) gathered point batch",
    )
)

declare(
    KernelBudget(
        backend="zk-graft-msm-scan",
        max_random_gathers=0,
        max_scatters=0,
        require_primitives=("dot_general",),
        notes="segmented fold rounds: EC adds + where-selects; rolls "
        "lower to slices, never gathers",
    )
)

declare_comm(
    CommBudget(
        backend="zk-graft-msm-scan",
        notes="single-device group fold: no wire, no host traffic",
    )
)

declare_mem(
    MemBudget(
        backend="zk-graft-msm-scan",
        # Measured (buffer assignment, N=128/256): resident 6272 B/lane
        # (blocked points + digits in), transient 19155 B/lane — the
        # scan's per-step emit stack plus the carried fold state.
        resident_n=8192.0,
        resident_const=8192.0,
        transient_n=24576.0,
        transient_const=32768.0,
        notes="lax.scan keeps the (B, 32, nb, 3, 16) emit stack live",
    )
)

declare(
    KernelBudget(
        backend="zk-graft-msm-bucket",
        max_random_gathers=0,
        max_scatters=2,
        notes="run-end extraction: two honestly-unique scatters (run "
        "ends are unique per digit; other lanes park out of range)",
    )
)

declare_comm(
    CommBudget(
        backend="zk-graft-msm-bucket",
        notes="single-device scatter + one EC combine; the bucket "
        "array is the only device->host transfer of the MSM",
    )
)

declare_mem(
    MemBudget(
        backend="zk-graft-msm-bucket",
        # Measured (buffer assignment, N=128/256): resident 6496 B/lane
        # (local sums + digits in), transient 12288 B/lane over a
        # ~115.6 MB constant floor — the one-hot mul_full columns of
        # the final EC combine run at full bucket-grid lane count
        # (32·(256+n) lanes), so XLA materializes (lanes, 32, 16) f32
        # product planes that dwarf the (32, 256+n, 3, 16) scatter
        # buffers themselves.
        resident_n=8192.0,
        resident_const=16384.0,
        transient_n=16384.0,
        transient_const=125829120.0,
        notes="scatter buffers carry n parking slots past the 256 "
        "buckets; sliced away before the combine; the const floor is "
        "the bucket-grid EC combine's one-hot matmul temps",
    )
)
