"""Batched multi-limb modular arithmetic on u32 lanes (PERF.md §22).

The layout mirrors the native runtime's radix-52 lazy-reduction story
(native/zk_ifma.cpp) translated to what XLA vectorizes well without
64-bit integers: a 256-bit element is sixteen 16-bit limbs in a
``(..., 16)`` uint32 array, so every limb product fits a u32 exactly
(``(2^16-1)^2 < 2^32``) and column sums of one schoolbook pass stay
under ``2^21`` — carries are deferred across the whole vectorized lane
and resolved in one propagation sweep per product, the same
accumulate-then-normalize discipline the IFMA kernel (and the
wrong-field chips over 68-bit RNS limbs, zk/rns.py) use.

Reduction is word-by-word Montgomery (REDC): products live in the
Montgomery domain ``â = a·2^256 mod p`` and one multiplication is a
512-bit schoolbook product + a low-half multiply by ``-p^{-1} mod
2^256`` + one fold — ~600 vector ops total, exact by construction.
Exactness is the contract: these kernels feed bit-identity sinks
(proof bytes).  The one float appearance — column sums evaluated as an
f32 one-hot matmul — is exact by range analysis (every addend < 2^16,
every sum < 2^21 < 2^24), and the parity suite (tests/test_zk_graft.py)
pins every operation against Python ints anyway.

Import note: this module imports jax; only code paths that actually
selected ``zk_backend="graft"`` (or the analyzers) load it.
"""

from __future__ import annotations

from functools import reduce

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis.budget import (
    CommBudget,
    KernelBudget,
    MemBudget,
    declare,
    declare_comm,
    declare_mem,
)
from ...crypto.field import MODULUS as FR_MODULUS
from ..rns import FQ_MODULUS

NLIMBS = 16
LIMB_BITS = 16
MASK = (1 << LIMB_BITS) - 1
RADIX = 1 << (NLIMBS * LIMB_BITS)  # 2^256, the Montgomery R


def _int_to_limbs_np(v: int, n: int = NLIMBS) -> np.ndarray:
    return np.array([(v >> (LIMB_BITS * i)) & MASK for i in range(n)], dtype=np.uint32)


def ints_to_limbs(values) -> np.ndarray:
    """Python ints -> (n, 16) u32 little-endian 16-bit limbs."""
    buf = b"".join(v.to_bytes(32, "little") for v in values)
    return np.frombuffer(buf, dtype=np.uint16).reshape(-1, NLIMBS).astype(np.uint32)


def limbs_to_ints(arr: np.ndarray) -> list[int]:
    buf = np.ascontiguousarray(arr.astype(np.uint16)).tobytes()
    return [int.from_bytes(buf[i : i + 32], "little") for i in range(0, len(buf), 32)]


def u64_to_limbs(arr: np.ndarray) -> np.ndarray:
    """(n, 4) u64 canonical limbs (utils/limbs.py layout) -> (n, 16) u32."""
    a = np.ascontiguousarray(arr, dtype=np.uint64)
    return a.view(np.uint16).reshape(a.shape[0], NLIMBS).astype(np.uint32)


def limbs_to_u64(arr: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(np.asarray(arr).astype(np.uint16))
    return a.view(np.uint64).reshape(a.shape[0], 4).copy()


def _gp_prefix(g: jax.Array, p: jax.Array) -> jax.Array:
    """Inclusive generate/propagate prefix along the limb axis — a
    hand-rolled Kogge–Stone (log2(K) rounds of contiguous pad-shifts;
    ``lax.associative_scan`` lowers to strided odd/even slicing that
    XLA:CPU executes ~3x slower).  Returns the accumulated generate
    bit: ``gacc[i]`` is the carry out of position ``i``."""
    k = g.shape[-1]
    shift = [(0, 0)] * (g.ndim - 1)
    d = 1
    while d < k:
        gs = jnp.pad(g[..., :-d], shift + [(d, 0)])
        ps = jnp.pad(p[..., :-d], shift + [(d, 0)])
        g = g | (p & gs)
        p = p & ps
        d <<= 1
    return g


def _carry_sweep(cols: jax.Array) -> jax.Array:
    """Resolve deferred column carries: (..., K) u32 columns (each
    < 2^21) -> (..., K) clean 16-bit limbs.

    Two steps, both lane-parallel: (1) split every column hi/lo and add
    the multi-bit high parts one position up — after that each position
    holds ``s < 2^16 + 32`` so at most a single-bit carry remains; (2)
    resolve the single-bit chain with a log-depth generate/propagate
    prefix (``lax.associative_scan``) instead of a 32-step ripple.  A
    naive unrolled ripple made one EC add (16 inlined muls) cost 114 s
    of XLA time; a ``lax.scan`` ripple compiled fast but its while-loop
    blocked fusion and tripled runtime.  The prefix form is both small
    to compile and fully fusable."""
    hi = cols >> LIMB_BITS
    lo = cols & MASK
    shift = [(0, 0)] * (cols.ndim - 1) + [(1, 0)]
    s = lo + jnp.pad(hi[..., :-1], shift)
    g = (s >> LIMB_BITS).astype(bool)
    p = (s & MASK) == MASK
    cin = jnp.pad(_gp_prefix(g, p)[..., :-1], shift).astype(jnp.uint32)
    return (s + cin) & MASK


def _column_matrix(out_limbs: int) -> np.ndarray:
    """One-hot column-sum matrix: partial product (i, j) (lo half) and
    its carry half land in columns i+j and i+j+1.  The 512-bit
    schoolbook column sums then become ONE ``(N, 512) @ (512, K)``
    dot_general — the MXU-shaped formulation on a real chip, and the
    BLAS path under the CPU analyzer mesh (measured 28x over the
    elementwise pad/add chain XLA:CPU refuses to fuse, PERF.md §22)."""
    oh = np.zeros((2 * NLIMBS * NLIMBS, 2 * NLIMBS), np.float32)
    for i in range(NLIMBS):
        for j in range(NLIMBS):
            oh[i * NLIMBS + j, i + j] = 1.0
            oh[NLIMBS * NLIMBS + i * NLIMBS + j, i + j + 1] = 1.0
    return np.ascontiguousarray(oh[:, :out_limbs])


_OH_FULL = _column_matrix(2 * NLIMBS)
_OH_LOW = _column_matrix(NLIMBS)


def _mul_cols(a: jax.Array, b: jax.Array, oh: np.ndarray) -> jax.Array:
    """Deferred-carry schoolbook columns via the one-hot matmul.

    Exactness: every lo/hi half is < 2^16 and each column receives at
    most 32 of them, so the f32 accumulation stays below 2^21 — inside
    the 24-bit mantissa, bit-exact by construction (the same integers-
    in-float argument the paper's TPU path makes for i32 SpMV on the
    MXU).  No f64 anywhere; the kernel budget pins that."""
    a, b = jnp.broadcast_arrays(a, b)
    shape = a.shape[:-1]
    n2 = NLIMBS * NLIMBS
    af = a.reshape(-1, NLIMBS)
    bf = b.reshape(-1, NLIMBS)
    prod = (af[:, :, None] * bf[:, None, :]).reshape(-1, n2)
    lohi = jnp.concatenate(
        [(prod & MASK).astype(jnp.float32), (prod >> LIMB_BITS).astype(jnp.float32)],
        axis=1,
    )
    cols = (lohi @ jnp.asarray(oh)).astype(jnp.uint32)
    return cols.reshape(shape + (oh.shape[1],))


def mul_full(a: jax.Array, b: jax.Array) -> jax.Array:
    """(..., 16) x (..., 16) -> (..., 32) exact 512-bit product."""
    return _carry_sweep(_mul_cols(a, b, _OH_FULL))


def mul_low(a: jax.Array, b: jax.Array) -> jax.Array:
    """Low 256 bits of the product (mod 2^256) — the REDC m-step."""
    return _carry_sweep(_mul_cols(a, b, _OH_LOW))


def _add_limbs(a: jax.Array, b: jax.Array) -> jax.Array:
    """Limbwise add + one carry sweep (values < 2^17 per column)."""
    return _carry_sweep(a + b)


def _sub_limbs(a: jax.Array, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """a - b with a borrow chain; returns (diff limbs, borrow flag).
    Same generate/propagate prefix as :func:`_carry_sweep`: limb i
    generates a borrow when ``a_i < b_i`` and propagates when equal."""
    a, b = jnp.broadcast_arrays(a, b)
    t = a + jnp.uint32(1 << LIMB_BITS) - b
    g = t < jnp.uint32(1 << LIMB_BITS)
    p = t == jnp.uint32(1 << LIMB_BITS)
    gacc = _gp_prefix(g, p)
    shift = [(0, 0)] * (a.ndim - 1) + [(1, 0)]
    bin_ = jnp.pad(gacc[..., :-1], shift).astype(jnp.uint32)
    return (t - bin_) & MASK, gacc[..., -1].astype(jnp.uint32)


def is_zero(a: jax.Array) -> jax.Array:
    """(..., 16) -> (...,) bool; Montgomery zero is limbwise zero."""
    return jnp.all(a == 0, axis=-1)


class Field:
    """One prime field's constants + vector kernels (Fr and Fq below).

    Elements live in the Montgomery domain (``to_mont``/``from_mont``
    at the boundaries); all ops keep canonical ``< p`` limbs so
    cross-backend parity is a straight byte comparison.
    """

    def __init__(self, name: str, p: int):
        self.name = name
        self.p = p
        self.p_np = _int_to_limbs_np(p)
        # -p^{-1} mod 2^256: the REDC multiplier.
        self.nprime_np = _int_to_limbs_np((-pow(p, -1, RADIX)) % RADIX)
        self.r = RADIX % p  # Montgomery form of 1
        self.r2 = (RADIX * RADIX) % p
        self.r_np = _int_to_limbs_np(self.r)
        self.r2_np = _int_to_limbs_np(self.r2)

    # -- traced building blocks (composable inside larger kernels) ----

    def redc(self, t: jax.Array) -> jax.Array:
        """Montgomery fold: (..., 32) carried limbs T < p·2^256 ->
        (..., 16) with value T·2^-256 mod p, canonical (< p)."""
        m = mul_low(t[..., :NLIMBS], jnp.asarray(self.nprime_np))
        mp = mul_full(m, jnp.asarray(self.p_np))
        s = _add_limbs(t, mp)  # low 16 limbs cancel to zero by design
        return self.cond_sub_p(s[..., NLIMBS:])

    def mont_mul(self, a: jax.Array, b: jax.Array) -> jax.Array:
        return self.redc(mul_full(a, b))

    def cond_sub_p(self, x: jax.Array) -> jax.Array:
        d, borrow = _sub_limbs(x, jnp.asarray(self.p_np))
        return jnp.where((borrow != 0)[..., None], x, d)

    def add(self, a: jax.Array, b: jax.Array) -> jax.Array:
        # a + b < 2p < 2^256: the carry out of limb 15 is always 0.
        return self.cond_sub_p(_add_limbs(a, b))

    def sub(self, a: jax.Array, b: jax.Array) -> jax.Array:
        d, borrow = _sub_limbs(a, b)
        wrapped = _add_limbs(d, jnp.asarray(self.p_np))
        return jnp.where((borrow != 0)[..., None], wrapped, d)

    def to_mont(self, a: jax.Array) -> jax.Array:
        return self.mont_mul(a, jnp.asarray(self.r2_np))

    def from_mont(self, a: jax.Array) -> jax.Array:
        pad = [(0, 0)] * (a.ndim - 1) + [(0, NLIMBS)]
        return self.redc(jnp.pad(a, pad))

    # -- host-side exact helpers (conversion boundaries) --------------

    def to_mont_int(self, v: int) -> int:
        return (v * RADIX) % self.p

    def from_mont_int(self, v: int) -> int:
        return (v * pow(RADIX, -1, self.p)) % self.p


FR = Field("fr", FR_MODULUS)
FQ = Field("fq", FQ_MODULUS)

_FIELDS = {"fr": FR, "fq": FQ}


#: Jitted standalone entry for the registered ``zk-graft-mulmod``
#: kernel: one batched Montgomery multiply in Fr (the NTT/quotient
#: workhorse).  Larger kernels (NTT stages, EC combine rounds) inline
#: the same traced building blocks.
@jax.jit
def mulmod_fr(a: jax.Array, b: jax.Array) -> jax.Array:
    return FR.mont_mul(a, b)


@jax.jit
def mulmod_fq(a: jax.Array, b: jax.Array) -> jax.Array:
    return FQ.mont_mul(a, b)


# ---------------------------------------------------------------------------
# Pinned kernel invariants (graftlint passes 1/8/12) — the mulmod
# kernel is pure lane arithmetic: no gather, no scatter, no f64, no
# host callback, no collectives.  Memory coefficients measured from
# the compiled buffer assignment at the analyzer's two pinned scales
# (n=1024/2048): resident = two (n,16) u32 operands = 128 B/row;
# transient = the deferred-carry column accumulators + the unaliased
# (n,16) output — the per-i partial-product stream fuses, but the
# 32-column u32 accumulator and the REDC fold each hold a few
# (n,32)-shaped lives (measured 1280 B/row at both scales, slack
# under one extra (n,32) buffer).
# ---------------------------------------------------------------------------

declare(
    KernelBudget(
        backend="zk-graft-mulmod",
        max_random_gathers=0,
        max_scatters=0,
        require_primitives=("dot_general",),
        notes="batched Montgomery mul: pure lane arithmetic (one-hot "
        "column matmuls), carries deferred to one sweep per product",
    )
)

declare_comm(
    CommBudget(
        backend="zk-graft-mulmod",
        notes="single-device field kernel: no wire, no host traffic",
    )
)

declare_mem(
    MemBudget(
        backend="zk-graft-mulmod",
        resident_n=128.0,  # two (n,16) u32 operands
        resident_const=4096.0,
        transient_n=2048.0,  # carry columns + REDC fold + output
        transient_const=16384.0,
        notes="schoolbook columns live as (n,32) u32 accumulators "
        "between the deferred-carry sweeps",
    )
)
