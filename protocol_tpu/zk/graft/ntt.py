"""Iterative radix-2 NTT over the u32-limb representation (PERF.md §22).

Decimation-in-time Cooley–Tukey: one host-side bit-reverse permutation,
then ``log2(n)`` jitted butterfly stages.  Each stage is a single
batched Montgomery multiply of the odd half against the stage's twiddle
vector plus one lazy add/sub pair — the kernel the ``zk-graft-ntt-stage``
budgets pin.  Twiddle vectors are computed once per ``(n, root)`` pair
with exact Python ints, converted to the Montgomery domain, and cached
for the life of the process (a k=14 prove replays the same four plans
dozens of times).

The transform is bit-identical to ``plonk._py_ntt`` / ``native zk_ntt``
by construction: every butterfly is exact modular arithmetic, and the
parity suite round-trips ``intt(ntt(x)) == x`` against both.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ...crypto.field import MODULUS as R
from . import _bump_phase
from .field import (
    FR,
    NLIMBS,
    ints_to_limbs,
    limbs_to_u64,
    u64_to_limbs,
)

_plan_lock = threading.Lock()
_twiddle_plans: dict[tuple[int, int], list[np.ndarray]] = {}
_bitrev_cache: dict[int, np.ndarray] = {}
_ninv_cache: dict[tuple[int, bool], np.ndarray] = {}


def _bitrev_perm(n: int) -> np.ndarray:
    """Index vector for the DIT input permutation (cached per n)."""
    perm = _bitrev_cache.get(n)
    if perm is None:
        bits = n.bit_length() - 1
        idx = np.arange(n, dtype=np.int64)
        rev = np.zeros(n, dtype=np.int64)
        for b in range(bits):
            rev |= ((idx >> b) & 1) << (bits - 1 - b)
        perm = rev
        _bitrev_cache[n] = perm
    return perm


def _twiddle_plan(n: int, root: int) -> list[np.ndarray]:
    """Per-stage Montgomery twiddles ``w_len^k, k < L/2`` for
    ``L = 2, 4, ..., n`` (host ints once, then cached)."""
    key = (n, root)
    with _plan_lock:
        plan = _twiddle_plans.get(key)
    if plan is not None:
        return plan
    plan = []
    length = 2
    while length <= n:
        w_len = pow(root, n // length, R)
        half = length >> 1
        tws = [1] * half
        for k in range(1, half):
            tws[k] = tws[k - 1] * w_len % R
        plan.append(ints_to_limbs([FR.to_mont_int(w) for w in tws]))
        length <<= 1
    with _plan_lock:
        _twiddle_plans[key] = plan
    return plan


def _stage_fn():
    """The jitted butterfly stage (lazy import so this module stays
    cheap to load; jax's jit cache keys on the (blocks, L) shape)."""
    global _STAGE
    try:
        return _STAGE
    except NameError:
        pass
    import jax
    import jax.numpy as jnp

    @jax.jit
    def stage(x, tw):
        # x: (blocks, L, 16) Montgomery Fr; tw: (L//2, 16)
        half = x.shape[1] // 2
        u = x[:, :half]
        t = FR.mont_mul(x[:, half:], tw[None, :, :])
        return jnp.concatenate([FR.add(u, t), FR.sub(u, t)], axis=1)

    _STAGE = stage
    return stage


def _scale_fn():
    global _SCALE
    try:
        return _SCALE
    except NameError:
        pass
    import jax

    @jax.jit
    def scale(x, c):
        return FR.mont_mul(x, c[None, :])

    _SCALE = scale
    return scale


def ntt_limbs(arr: np.ndarray, root: int, inverse: bool) -> np.ndarray:
    """In-place NTT over (n, 4) u64 canonical Fr limbs — the graft
    analog of ``native zk_ntt`` (same signature Domain.ntt_limbs uses)."""
    t0 = time.perf_counter()
    import jax.numpy as jnp

    n = arr.shape[0]
    if n & (n - 1):
        raise ValueError(f"NTT size must be a power of two, got {n}")
    if n == 1:
        _bump_phase("ntt", time.perf_counter() - t0)
        return arr

    limbs = u64_to_limbs(arr)[_bitrev_perm(n)]
    x = FR.to_mont(jnp.asarray(limbs))

    stage = _stage_fn()
    for tw in _twiddle_plan(n, root):
        length = 2 * tw.shape[0]
        x = stage(x.reshape(n // length, length, NLIMBS), jnp.asarray(tw))
        x = x.reshape(n, NLIMBS)

    if inverse:
        key = (n, True)
        c = _ninv_cache.get(key)
        if c is None:
            c = ints_to_limbs([FR.to_mont_int(pow(n, R - 2, R))])[0]
            _ninv_cache[key] = c
        x = _scale_fn()(x, jnp.asarray(c))

    out = np.asarray(FR.from_mont(x))
    arr[:] = limbs_to_u64(out)
    _bump_phase("ntt", time.perf_counter() - t0)
    return arr


# ---------------------------------------------------------------------------
# Pinned kernel invariants (graftlint passes 1/8/12).  One butterfly
# stage is a reshape + one Montgomery multiply of the odd half against
# the broadcast twiddle vector + one lazy add/sub pair: pure lane
# arithmetic, no gather/scatter (the bit-reverse shuffle happens once
# on the host, outside the kernel).  Memory rows are per butterfly
# lane (n = number of (16,)-limb elements in the stage input).
# ---------------------------------------------------------------------------

from ...analysis.budget import (  # noqa: E402  (kept next to the kernel)
    CommBudget,
    KernelBudget,
    MemBudget,
    declare,
    declare_comm,
    declare_mem,
)

declare(
    KernelBudget(
        backend="zk-graft-ntt-stage",
        max_random_gathers=0,
        max_scatters=0,
        require_primitives=("dot_general",),
        notes="radix-2 butterfly stage: twiddle mont_mul (one-hot "
        "column matmul) + lazy add/sub; bit-reverse stays on host",
    )
)

declare_comm(
    CommBudget(
        backend="zk-graft-ntt-stage",
        notes="single-device field kernel: no wire, no host traffic",
    )
)

declare_mem(
    MemBudget(
        backend="zk-graft-ntt-stage",
        resident_n=80.0,  # stage input + twiddle slice (measured 66 B/lane)
        resident_const=8192.0,
        transient_n=1024.0,  # odd-half mont_mul columns + concat (920 B/lane)
        transient_const=16384.0,
        notes="per-stage lives: odd-half product columns, carry "
        "sweeps, and the unaliased concat output",
    )
)
