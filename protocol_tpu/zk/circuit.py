"""The EigenTrust circuit: prove that the published global scores are
the converged trust of N signed opinions.

Constraint-level rebuild of circuit/src/circuit.rs:59-421:

1. witness the N public keys, signatures, and the N×N ops matrix;
2. pks_hash = sponge(pk_xs ‖ pk_ys); per peer, scores_hash =
   sponge(ops_i) and message = Poseidon(pks_hash, scores_hash, 0, 0, 0)
   (circuit/src/lib.rs:225-256 in-circuit);
3. verify each peer's EdDSA signature over its message;
4. run the I×N×N power iteration in-constraints;
5. bind the instance column: instance·SCALE^I == computed score and
   Σ instance == N·INITIAL_SCORE (total-score conservation,
   circuit.rs:380-418).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import field
from ..node.attestation import Attestation
from .cs import Cell, ConstraintSystem
from .eddsa import EddsaChipset
from .gadgets import (
    Bits2NumChip,
    EdwardsChip,
    PoseidonChip,
    PoseidonSpongeChip,
    StdGate,
)

P = field.MODULUS


@dataclass
class EigenTrustCircuit:
    """Const-generic analog: EigenTrust<N, I, INITIAL_SCORE, SCALE> as
    runtime parameters."""

    num_neighbours: int = 5
    num_iter: int = 10
    initial_score: int = 1000
    scale: int = 1000

    def synthesize(
        self,
        cs: ConstraintSystem,
        attestations: list[Attestation],
        pub_scores: list[int],
    ) -> None:
        """Build the full witness + constraints for one epoch.

        ``attestations[i]`` is peer i's signed opinion (aligned to the
        set order); ``pub_scores`` the claimed converged scores (the
        public instance).
        """
        n, iters = self.num_neighbours, self.num_iter
        assert len(attestations) == n and len(pub_scores) == n

        std = StdGate(cs)
        poseidon = PoseidonChip(cs)
        sponge = PoseidonSpongeChip(cs, std, poseidon)
        edwards = EdwardsChip(cs)
        b2n = Bits2NumChip(cs)
        eddsa = EddsaChipset(cs, std, edwards, poseidon, b2n)

        inst_col = cs.column("instance", "instance")
        inst_cells = [cs.assign(inst_col, r, pub_scores[r]) for r in range(n)]

        zero = std.constant(0)

        # Witness keys / signatures / ops.
        pk_cells = [
            (std.witness(att.pk.point.x), std.witness(att.pk.point.y))
            for att in attestations
        ]
        sig_cells = [
            (
                std.witness(att.sig.big_r.x),
                std.witness(att.sig.big_r.y),
                std.witness(att.sig.s),
            )
            for att in attestations
        ]
        ops_cells = [
            [std.witness(score) for score in att.scores] for att in attestations
        ]

        # Message hashes (circuit/src/lib.rs:225-256).
        pks_hash = sponge.squeeze(
            [pk[0] for pk in pk_cells] + [pk[1] for pk in pk_cells]
        )
        for i in range(n):
            scores_hash = sponge.squeeze(list(ops_cells[i]))
            message = poseidon.permute([pks_hash, scores_hash, zero, zero, zero])[0]
            rx, ry, s = sig_cells[i]
            eddsa.verify(pk_cells[i], (rx, ry), s, message)

        # Power iteration (circuit.rs:347-378): I rounds of
        # new_s[i] = Σ_j ops[j][i] · s[j].
        init = std.constant(self.initial_score)
        s_vec = [init] * n
        for _ in range(iters):
            new_s = []
            for i in range(n):
                acc = zero
                for j in range(n):
                    acc = std.mul_add(ops_cells[j][i], s_vec[j], acc)
                new_s.append(acc)
            s_vec = new_s

        # Instance binding (circuit.rs:380-418): pub·SCALE^I == s and
        # Σ pub == N·INITIAL_SCORE.
        scale_pow = std.constant(pow(self.scale, iters, P))
        total = zero
        for i in range(n):
            scaled = std.mul(inst_cells[i], scale_pow)
            std.assert_equal(scaled, s_vec[i])
            total = std.add(total, inst_cells[i])
        expected_total = std.constant((n * self.initial_score) % P)
        std.assert_equal(total, expected_total)


def prove_epoch_statement(
    attestations: list[Attestation], pub_scores: list[int], **params
) -> ConstraintSystem:
    """Build and return the satisfied constraint system for an epoch (a
    MockProver-style construction; raises AssertionError on an invalid
    statement)."""
    cs = ConstraintSystem()
    EigenTrustCircuit(**params).synthesize(cs, attestations, pub_scores)
    cs.assert_satisfied()
    return cs
