"""ZK proof layer: constraint system, gadget library, EigenTrust circuit.

The reference proves each epoch's convergence with a Halo2 PLONK circuit
(circuit/src/circuit.rs) verified on-chain via a generated Yul verifier.
This package rebuilds the proving stack in stages:

- ``proof``       — Proof/ProofRaw wire types (circuit/src/lib.rs:258-292)
  and the Prover interface the node consumes.
- ``cs``          — a columnar constraint system with copy constraints and
  a MockProver-equivalent satisfiability checker (the reference's testing
  backbone, SURVEY.md §4 tier 2).
- ``gadgets``     — the arithmetic vocabulary (main gate, bits2num,
  lt_eq, set membership) as chip/chipset analogs.
- ``circuit``     — the EigenTrust circuit: message hashing, N EdDSA
  verifications, the I×N×N power iteration, score conservation.
"""

from .circuit import EigenTrustCircuit, prove_epoch_statement  # noqa: F401
from .cs import ConstraintSystem  # noqa: F401
from .proof import Proof, ProofRaw, PoseidonCommitmentProver  # noqa: F401
