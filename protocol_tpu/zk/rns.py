"""Non-native ("wrong field") arithmetic: Bn254 base-field Fq emulated
in 4×68-bit limbs over the scalar field Fr.

Parity with circuit/src/integer/{rns.rs,native.rs}: the aggregation
pipeline must express G1 coordinates (Fq elements) as Fr limb vectors
and prove add/sub/mul/div relations through quotient/residue reduction
witnesses.  This module is the native half — it produces and checks the
witnesses the future in-circuit chips will constrain.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import field

#: Bn254 base-field modulus (the curve's coordinate field Fq — the
#: "wrong" field when working over Fr).
FQ_MODULUS = 0x30644E72E131A029B85045B68181585D97816A916871CA8D3C208C16D87CFD47

NUM_LIMBS = 4
LIMB_BITS = 68
LIMB_MASK = (1 << LIMB_BITS) - 1


def decompose(value: int, n_limbs: int = NUM_LIMBS, bits: int = LIMB_BITS) -> tuple[int, ...]:
    """Split into little-endian fixed-width limbs (rns.rs decompose_big)."""
    assert 0 <= value < 1 << (n_limbs * bits)
    return tuple((value >> (bits * i)) & ((1 << bits) - 1) for i in range(n_limbs))


def compose(limbs: tuple[int, ...], bits: int = LIMB_BITS) -> int:
    """Inverse of decompose (rns.rs compose_big)."""
    return sum(limb << (bits * i) for i, limb in enumerate(limbs))


@dataclass(frozen=True)
class ReductionWitness:
    """The quotient/result pair proving ``lhs ∘ rhs ≡ result + q·Fq``
    (integer/native.rs::ReductionWitness).  ``quotient`` is small for
    add/sub and a full integer for mul/div."""

    result: "WrongFieldInteger"
    quotient: tuple[int, ...]
    op: str

    def check(self, a: "WrongFieldInteger", b: "WrongFieldInteger") -> bool:
        """Native verification of the reduction identity over the
        integers (what the in-circuit chips constrain limb-wise)."""
        q = compose(self.quotient)
        r = self.result.value()
        if self.op == "add":
            return a.value() + b.value() == q * FQ_MODULUS + r
        if self.op == "sub":
            return a.value() + q * FQ_MODULUS - b.value() == r
        if self.op == "mul":
            return a.value() * b.value() == q * FQ_MODULUS + r
        if self.op == "div":
            # a / b = r  ⇔  b·r = a + q·p
            return b.value() * r == a.value() + q * FQ_MODULUS
        raise ValueError(self.op)


@dataclass(frozen=True)
class WrongFieldInteger:
    """An Fq element as 4×68-bit limbs (integer/native.rs::Integer)."""

    limbs: tuple[int, ...]

    @classmethod
    def from_value(cls, value: int) -> "WrongFieldInteger":
        return cls(decompose(value % FQ_MODULUS))

    def value(self) -> int:
        return compose(self.limbs)

    def to_fr_limbs(self) -> tuple[int, ...]:
        """The limbs as Fr elements (each < 2^68 « Fr modulus), the form
        the loaders absorb into transcripts."""
        return tuple(limb % field.MODULUS for limb in self.limbs)

    def add(self, other: "WrongFieldInteger") -> ReductionWitness:
        total = self.value() + other.value()
        q, r = divmod(total, FQ_MODULUS)
        return ReductionWitness(
            result=WrongFieldInteger(decompose(r)), quotient=decompose(q), op="add"
        )

    def sub(self, other: "WrongFieldInteger") -> ReductionWitness:
        diff = (self.value() - other.value()) % FQ_MODULUS
        # One borrowed modulus at most, since both operands are < p.
        q = 1 if self.value() < other.value() else 0
        return ReductionWitness(
            result=WrongFieldInteger(decompose(diff)), quotient=decompose(q), op="sub"
        )

    def mul(self, other: "WrongFieldInteger") -> ReductionWitness:
        prod = self.value() * other.value()
        q, r = divmod(prod, FQ_MODULUS)
        return ReductionWitness(
            result=WrongFieldInteger(decompose(r)), quotient=decompose(q), op="mul"
        )

    def div(self, other: "WrongFieldInteger") -> ReductionWitness:
        inv = pow(other.value(), -1, FQ_MODULUS)
        r = (self.value() * inv) % FQ_MODULUS
        # b·r = a + q·p
        q = (other.value() * r - self.value()) // FQ_MODULUS
        return ReductionWitness(
            result=WrongFieldInteger(decompose(r)), quotient=decompose(q), op="div"
        )
