"""Poseidon Fiat-Shamir transcript (verifier/transcript/native.rs:
PoseidonRead / PoseidonWrite re-built).

Prover and verifier absorb the same protocol messages — Fr scalars
directly, G1 points as their Fq coordinates decomposed into 4×68-bit
Fr limbs (the loader convention, verifier/loader/native.rs) — and
squeeze challenges from the sponge.  Writing also appends a canonical
byte encoding so a proof blob can be replayed by the reader.
"""

from __future__ import annotations

from ..crypto import field
from ..crypto.poseidon import PoseidonSponge
from .bn254 import G1, is_on_curve
from .rns import WrongFieldInteger


class PoseidonTranscript:
    """Shared sponge state machine."""

    def __init__(self):
        self.sponge = PoseidonSponge()
        self._absorbed = False

    def common_scalar(self, scalar: int) -> None:
        self.sponge.update([scalar % field.MODULUS])
        self._absorbed = True

    def common_point(self, point: G1) -> None:
        if not is_on_curve(point):
            raise ValueError("point not on curve")
        for coord in (point.x, point.y):
            self.sponge.update(WrongFieldInteger.from_value(coord).to_fr_limbs())
        self._absorbed = True

    def squeeze_challenge(self) -> int:
        """Squeeze a challenge; re-absorbs it so successive challenges
        chain (the sponge keeps state across squeezes)."""
        if not self._absorbed:
            # Domain-separate an empty transcript.
            self.sponge.update([0])
        c = self.sponge.squeeze()
        self.sponge.update([c])
        self._absorbed = True
        return c


class PoseidonWrite(PoseidonTranscript):
    """Prover side: absorb + serialize."""

    def __init__(self):
        super().__init__()
        self._buf = bytearray()

    def write_scalar(self, scalar: int) -> None:
        self.common_scalar(scalar)
        self._buf += field.to_le_bytes(scalar % field.MODULUS)

    def write_point(self, point: G1) -> None:
        self.common_point(point)
        self._buf += point.x.to_bytes(32, "little")
        self._buf += point.y.to_bytes(32, "little")

    def finalize(self) -> bytes:
        return bytes(self._buf)


class KeccakTranscript:
    """Keccak Fiat-Shamir transcript — the EVM-flow analog of the
    reference's snark-verifier ``EvmTranscript`` (used by gen_proof for
    on-chain verification, verifier/mod.rs:70-83): scalars and point
    coordinates absorb as 32-byte big-endian words (EVM word order),
    and challenges are keccak256(state ‖ pending) reduced mod Fr, so a
    generated verifier contract replays the transcript with the native
    KECCAK256 opcode instead of ~60 Poseidon rounds per absorb."""

    def __init__(self):
        self.state = b"\0" * 32
        self.pending = bytearray()

    def common_scalar(self, scalar: int) -> None:
        self.pending += (scalar % field.MODULUS).to_bytes(32, "big")

    def common_point(self, point: G1) -> None:
        if not is_on_curve(point):
            raise ValueError("point not on curve")
        self.pending += point.x.to_bytes(32, "big")
        self.pending += point.y.to_bytes(32, "big")

    def squeeze_challenge(self) -> int:
        from ..crypto.keccak import keccak256

        digest = keccak256(self.state + bytes(self.pending))
        self.state = digest
        self.pending.clear()
        return int.from_bytes(digest, "big") % field.MODULUS


class KeccakWrite(KeccakTranscript):
    """Prover side: absorb + serialize (big-endian wire format)."""

    def __init__(self):
        super().__init__()
        self._buf = bytearray()

    def write_scalar(self, scalar: int) -> None:
        self.common_scalar(scalar)
        self._buf += (scalar % field.MODULUS).to_bytes(32, "big")

    def write_point(self, point: G1) -> None:
        self.common_point(point)
        self._buf += point.x.to_bytes(32, "big")
        self._buf += point.y.to_bytes(32, "big")

    def finalize(self) -> bytes:
        return bytes(self._buf)


class KeccakRead(KeccakTranscript):
    """Verifier side: replay a big-endian proof blob."""

    def __init__(self, proof: bytes):
        super().__init__()
        self._buf = proof
        self._off = 0

    def _take(self, n: int) -> bytes:
        if self._off + n > len(self._buf):
            raise ValueError("transcript exhausted")
        out = self._buf[self._off : self._off + n]
        self._off += n
        return out

    def read_scalar(self) -> int:
        raw = int.from_bytes(self._take(32), "big")
        if raw >= field.MODULUS:
            raise ValueError("non-canonical scalar encoding")
        self.common_scalar(raw)
        return raw

    def read_point(self) -> G1:
        from .rns import FQ_MODULUS

        x = int.from_bytes(self._take(32), "big")
        y = int.from_bytes(self._take(32), "big")
        if x >= FQ_MODULUS or y >= FQ_MODULUS:
            raise ValueError("non-canonical point encoding")
        point = G1(x, y)
        self.common_point(point)
        return point


class PoseidonRead(PoseidonTranscript):
    """Verifier side: replay a proof blob, re-deriving the identical
    challenge stream."""

    def __init__(self, proof: bytes):
        super().__init__()
        self._buf = proof
        self._off = 0

    def _take(self, n: int) -> bytes:
        if self._off + n > len(self._buf):
            raise ValueError("transcript exhausted")
        out = self._buf[self._off : self._off + n]
        self._off += n
        return out

    def read_scalar(self) -> int:
        scalar = field.from_le_bytes(self._take(32))
        self.common_scalar(scalar)
        return scalar

    def read_point(self) -> G1:
        x = int.from_bytes(self._take(32), "little")
        y = int.from_bytes(self._take(32), "little")
        # Canonicality mirrors field.from_le_bytes: a coordinate >= Fq
        # would alias another point mod Q (proof malleability) and break
        # affine arithmetic downstream.
        from .rns import FQ_MODULUS

        if x >= FQ_MODULUS or y >= FQ_MODULUS:
            raise ValueError("non-canonical point encoding")
        point = G1(x, y)
        self.common_point(point)
        return point
