"""In-circuit non-native ("wrong field") arithmetic and emulated-Fq
elliptic-curve chips.

The in-circuit half of zk/rns.py — parity with the reference's
`integer/mod.rs:85-650` (IntegerAdd/Sub/Mul/Div chips over the
`Bn256_4_68` RNS) and `ecc/mod.rs:50-828` (G1 in emulated Fq), rebuilt
on this framework's ConstraintSystem/StdGate stack.  An Fq element
lives as 4×68-bit limb cells over Fr; every operation constrains the
reduction identity ``a ∘ b = q·p + r`` two ways:

- **native**: composed limbs checked mod Fr with one arithmetic row;
- **binary**: 136-bit CRT chunks ``t − r ≡ 0 (mod 2^272)`` with
  witnessed, range-checked carries (the reference's
  `constrain_binary_crt_exp`, rns.rs:331-350 — the rebuild additionally
  range-checks limbs and carries, which the unfinished reference
  aggregator never wired up).

Together the two residue systems pin the identity over the integers
(values < 2^512 « 2^272·Fr), so limb equality means Fq equality for
canonical (fully-reduced) values — and every chip output here is the
canonical remainder, so equality checks are plain limb equality.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import field
from .chips import RangeCheckChip
from .cs import Cell, ConstraintSystem
from .gadgets import Bits2NumChip, StdGate
from .rns import FQ_MODULUS, LIMB_BITS, NUM_LIMBS, compose, decompose

P = field.MODULUS

#: 2^272 − FQ_MODULUS, decomposed — the reference's
#: `negative_wrong_modulus_decomposed` (rns.rs).
_P_PRIME = decompose((1 << (NUM_LIMBS * LIMB_BITS)) - FQ_MODULUS)
#: FQ_MODULUS mod Fr — the native-row modulus constant.
_P_IN_N = FQ_MODULUS % P
#: Limb weights mod Fr.
_SHIFT = [pow(2, LIMB_BITS * i, P) for i in range(NUM_LIMBS)]

#: Reduced values keep their top limb under 52 bits (254 = 3·68 + 50,
#: rounded up to the 4-bit lookup word), bounding any operand at 2^256
#: and any product at 2^512 — inside the 2^272·Fr CRT window.
_TOP_BITS = 52
#: CRT chunk carries are bounded by 2^70 (sum of ≤8 double-limb
#: products over a 136-bit chunk); checked at 72 bits.
_CARRY_BITS = 72


@dataclass(frozen=True)
class AssignedInteger:
    """An Fq element as four 68-bit limb cells (the reference's
    AssignedInteger, integer/mod.rs:650)."""

    limbs: tuple[Cell, ...]

    def values(self, std: StdGate) -> tuple[int, ...]:
        return tuple(std.cell_value(c) for c in self.limbs)

    def value(self, std: StdGate) -> int:
        return compose(self.values(std))


class IntegerChip:
    """Add/sub/mul/div over emulated Fq (integer/mod.rs chips).

    ``mul`` carries the full constraint set; ``sub`` and ``div`` are
    expressed through ``add``/``mul`` with rearranged roles (r = a−b ⇔
    b+r ≡ a; r = a/b ⇔ b·r ≡ a), which is sound because all chip
    values are canonical remainders.
    """

    def __init__(self, cs: ConstraintSystem, std: StdGate):
        self.cs = cs
        self.std = std
        self.rng8 = RangeCheckChip(cs, word_bits=8)
        self.rng4 = RangeCheckChip(cs, word_bits=4)

    # -- range helpers --------------------------------------------------

    def _assert_bits(self, cell: Cell, n_bits: int) -> None:
        """cell < 2^n_bits via 8-bit lookup words plus one 4-bit top
        word; n_bits must be ≡ 0 or 4 (mod 8)."""
        full, rem = divmod(n_bits, 8)
        if rem == 0:
            self.rng8.assert_range(cell, full)
            return
        assert rem == 4, n_bits
        v = self.std.cell_value(cell)
        lo = v & ((1 << (8 * full)) - 1)
        hi = v >> (8 * full)
        lo_c = self.std.witness(lo)
        hi_c = self.std.witness(hi)
        self.rng8.assert_range(lo_c, full)
        self.rng4.assert_word(hi_c)
        # cell = lo + hi·2^(8·full)
        acc = self.std.add_scaled(lo_c, hi_c, 1 << (8 * full))
        self.std.assert_equal(acc, cell)

    def _range_check_limbs(self, limbs: list[Cell], top_bits: int = _TOP_BITS) -> None:
        for i, c in enumerate(limbs):
            self._assert_bits(c, LIMB_BITS if i < NUM_LIMBS - 1 else top_bits)

    # -- witnessing -----------------------------------------------------

    def witness(self, value: int) -> AssignedInteger:
        """A canonical (reduced) Fq witness with range-checked limbs."""
        value %= FQ_MODULUS
        cells = [self.std.witness(v) for v in decompose(value)]
        self._range_check_limbs(cells)
        return AssignedInteger(tuple(cells))

    def constant(self, value: int) -> AssignedInteger:
        return AssignedInteger(
            tuple(self.std.constant(v) for v in decompose(value % FQ_MODULUS))
        )

    def from_limb_cells(self, limbs: list[Cell]) -> AssignedInteger:
        """Adopt externally-produced limb cells (e.g. instance columns),
        range-checking them to canonical-shape bounds."""
        assert len(limbs) == NUM_LIMBS
        self._range_check_limbs(list(limbs))
        return AssignedInteger(tuple(limbs))

    def assert_equal(self, a: AssignedInteger, b: AssignedInteger) -> None:
        for x, y in zip(a.limbs, b.limbs):
            self.std.assert_equal(x, y)

    # -- the reduction-identity core ------------------------------------

    def _compose_cell(self, limbs: tuple[Cell, ...]) -> Cell:
        acc = None
        for i, c in enumerate(limbs):
            acc = (
                self.std.add_scaled(acc, c, _SHIFT[i])
                if acc is not None
                else self.std.add_scaled(self.std.constant(0), c, _SHIFT[i])
            )
        return acc

    def _binary_crt(self, t_cells: list[Cell], r: AssignedInteger) -> None:
        """136-bit chunk identities with witnessed carries
        (rns.rs residues/constrain_binary_crt)."""
        std = self.std
        lsh1 = _SHIFT[1]
        lsh2 = pow(2, 2 * LIMB_BITS, P)
        t_vals = [std.cell_value(c) for c in t_cells]
        r_vals = r.values(std)
        carry_prev: Cell | None = None
        carry_prev_val = 0
        for i in (0, 2):
            u = (
                t_vals[i]
                + t_vals[i + 1] * (1 << LIMB_BITS)
                - r_vals[i]
                - r_vals[i + 1] * (1 << LIMB_BITS)
                + carry_prev_val
            )
            assert u % (1 << (2 * LIMB_BITS)) == 0 and u >= 0, "bad reduction witness"
            v = u >> (2 * LIMB_BITS)
            v_cell = std.witness(v)
            self._assert_bits(v_cell, _CARRY_BITS)
            # t_lo + t_hi·2^68 − r_lo − r_hi·2^68 − v·2^136 + v_prev = 0
            acc = std.add_scaled(t_cells[i], t_cells[i + 1], lsh1)
            acc = std.add_scaled(acc, r.limbs[i], P - 1)
            acc = std.add_scaled(acc, r.limbs[i + 1], (P - 1) * lsh1 % P)
            acc = std.add_scaled(acc, v_cell, (P - lsh2) % P)
            if carry_prev is not None:
                acc = std.add(acc, carry_prev)
            std.assert_zero(acc)
            carry_prev = v_cell
            carry_prev_val = v

    def add(self, a: AssignedInteger, b: AssignedInteger) -> AssignedInteger:
        """r = a + b mod p with a short quotient (IntegerAddChip)."""
        std = self.std
        total = a.value(std) + b.value(std)
        q, r_val = divmod(total, FQ_MODULUS)
        assert q <= 1  # canonical operands wrap at most once
        q_cell = std.witness(q)
        std.assert_bool(q_cell)
        r_cells = [std.witness(v) for v in decompose(r_val)]
        self._range_check_limbs(r_cells)
        r = AssignedInteger(tuple(r_cells))
        # t_i = a_i + b_i + q·p'_i
        t_cells = [
            std.add_scaled(std.add(a.limbs[i], b.limbs[i]), q_cell, _P_PRIME[i])
            for i in range(NUM_LIMBS)
        ]
        self._binary_crt(t_cells, r)
        # native: compose(a) + compose(b) − q·p − compose(r) ≡ 0 (mod Fr)
        native = std.add(self._compose_cell(a.limbs), self._compose_cell(b.limbs))
        native = std.add_scaled(native, q_cell, (P - _P_IN_N) % P)
        native = std.add_scaled(native, self._compose_cell(r.limbs), P - 1)
        std.assert_zero(native)
        return r

    def sub(self, a: AssignedInteger, b: AssignedInteger) -> AssignedInteger:
        """r = a − b mod p, constrained as b + r ≡ a."""
        std = self.std
        r_val = (a.value(std) - b.value(std)) % FQ_MODULUS
        r = self.witness(r_val)
        s = self.add(b, r)
        self.assert_equal(s, a)
        return r

    def neg(self, a: AssignedInteger) -> AssignedInteger:
        return self.sub(self.constant(0), a)

    def mul(self, a: AssignedInteger, b: AssignedInteger) -> AssignedInteger:
        """r = a·b mod p with a full-width quotient (IntegerMulChip)."""
        std = self.std
        prod = a.value(std) * b.value(std)
        q_val, r_val = divmod(prod, FQ_MODULUS)
        q_cells = [std.witness(v) for v in decompose(q_val)]
        self._range_check_limbs(q_cells)
        r_cells = [std.witness(v) for v in decompose(r_val)]
        self._range_check_limbs(r_cells)
        q = AssignedInteger(tuple(q_cells))
        r = AssignedInteger(tuple(r_cells))
        # t_k = Σ_{i+j=k} a_i·b_j + q_i·p'_j   (k < 4; mod-2^272 terms)
        t_cells: list[Cell] = []
        for k in range(NUM_LIMBS):
            acc: Cell | None = None
            for i in range(k + 1):
                j = k - i
                ab = std.mul(a.limbs[i], b.limbs[j])
                acc = ab if acc is None else std.add(acc, ab)
                acc = std.add_scaled(acc, q.limbs[i], _P_PRIME[j])
            t_cells.append(acc)
        self._binary_crt(t_cells, r)
        # native row
        an = self._compose_cell(a.limbs)
        bn = self._compose_cell(b.limbs)
        qn = self._compose_cell(q.limbs)
        rn = self._compose_cell(r.limbs)
        prod_cell = std.mul(an, bn)
        acc = std.add_scaled(prod_cell, qn, (P - _P_IN_N) % P)
        acc = std.add_scaled(acc, rn, P - 1)
        std.assert_zero(acc)
        return r

    def div(self, a: AssignedInteger, b: AssignedInteger) -> AssignedInteger:
        """r = a / b mod p, constrained as b·r ≡ a (IntegerDivChip);
        unsatisfiable when b = 0 and a ≠ 0."""
        std = self.std
        inv = pow(b.value(std), -1, FQ_MODULUS)
        r = self.witness(a.value(std) * inv % FQ_MODULUS)
        prod = self.mul(b, r)
        self.assert_equal(prod, a)
        return r

    def select(self, cond: Cell, a: AssignedInteger, b: AssignedInteger) -> AssignedInteger:
        """cond ? a : b, limbwise (cond boolean-constrained by caller)."""
        return AssignedInteger(
            tuple(
                self.std.select(cond, x, y) for x, y in zip(a.limbs, b.limbs)
            )
        )


@dataclass(frozen=True)
class AssignedPoint:
    """Affine G1 point in emulated Fq (ecc/mod.rs AssignedPoint)."""

    x: AssignedInteger
    y: AssignedInteger

    def values(self, std: StdGate) -> tuple[int, int]:
        return (self.x.value(std), self.y.value(std))


class EccChip:
    """Emulated-Fq G1 arithmetic (ecc/mod.rs:50-828 re-designed):
    incomplete affine add/double (division forces the exceptional
    x₁ = x₂ cases unsatisfiable) and double-and-add scalar
    multiplication over challenge scalars.

    Completeness caveat (documented, matching halo2wrong-style
    incomplete addition): scalar_mul uses an accumulator offset so the
    incomplete add never sees ±P collisions for Fiat-Shamir-derived
    scalars; an adversarial scalar choice can only make the *prover*
    fail, never admit a wrong result.
    """

    def __init__(self, cs: ConstraintSystem, std: StdGate, integer: IntegerChip):
        self.cs = cs
        self.std = std
        self.int = integer
        self.b2n = Bits2NumChip(cs)

    def witness(self, x: int, y: int) -> AssignedPoint:
        pt = AssignedPoint(self.int.witness(x), self.int.witness(y))
        self.assert_on_curve(pt)
        return pt

    def constant(self, x: int, y: int) -> AssignedPoint:
        return AssignedPoint(self.int.constant(x), self.int.constant(y))

    def assert_on_curve(self, p: AssignedPoint) -> None:
        """y² = x³ + 3."""
        y2 = self.int.mul(p.y, p.y)
        x2 = self.int.mul(p.x, p.x)
        x3 = self.int.mul(x2, p.x)
        rhs = self.int.add(x3, self.int.constant(3))
        self.int.assert_equal(y2, rhs)

    def add_incomplete(self, p: AssignedPoint, q: AssignedPoint) -> AssignedPoint:
        """P + Q for P ≠ ±Q (EccAddConfig): λ = (y₂−y₁)/(x₂−x₁)."""
        dy = self.int.sub(q.y, p.y)
        dx = self.int.sub(q.x, p.x)
        lam = self.int.div(dy, dx)
        lam2 = self.int.mul(lam, lam)
        x3 = self.int.sub(self.int.sub(lam2, p.x), q.x)
        y3 = self.int.sub(self.int.mul(lam, self.int.sub(p.x, x3)), p.y)
        return AssignedPoint(x3, y3)

    def double(self, p: AssignedPoint) -> AssignedPoint:
        """2P (EccDoubleConfig): λ = 3x²/2y."""
        x2 = self.int.mul(p.x, p.x)
        three_x2 = self.int.add(self.int.add(x2, x2), x2)
        two_y = self.int.add(p.y, p.y)
        lam = self.int.div(three_x2, two_y)
        lam2 = self.int.mul(lam, lam)
        x3 = self.int.sub(self.int.sub(lam2, p.x), p.x)
        y3 = self.int.sub(self.int.mul(lam, self.int.sub(p.x, x3)), p.y)
        return AssignedPoint(x3, y3)

    def select(self, cond: Cell, a: AssignedPoint, b: AssignedPoint) -> AssignedPoint:
        return AssignedPoint(
            self.int.select(cond, a.x, b.x), self.int.select(cond, a.y, b.y)
        )

    def _aux(self) -> tuple[int, int]:
        """A deterministic non-trivial curve point (x³+3 a QR) scanned
        from a fixed seed — not any input's known multiple."""
        x = int.from_bytes(b"protocol-tpu-ecc-aux".ljust(32, b"\0"), "little")
        while True:
            x %= FQ_MODULUS
            rhs = (pow(x, 3, FQ_MODULUS) + 3) % FQ_MODULUS
            y = pow(rhs, (FQ_MODULUS + 1) // 4, FQ_MODULUS)
            if y * y % FQ_MODULUS == rhs:
                return x, y
            x += 1

    def scalar_mul(
        self, p: AssignedPoint, scalar: Cell, n_bits: int
    ) -> AssignedPoint:
        """scalar·P by left-to-right double-and-(select)-add
        (EccMulConfig re-designed).  The accumulator starts at the AUX
        offset and finishes with a constrained subtraction of
        AUX·2^n_bits, so the incomplete adds never meet the identity."""
        std = self.std
        bits = self.b2n.decompose(scalar, n_bits)  # little-endian bit cells
        ax, ay = self._aux()
        acc = self.constant(ax, ay)
        for bit in reversed(bits):
            acc = self.double(acc)
            with_p = self.add_incomplete(acc, p)
            acc = self.select(bit, with_p, acc)
        # Subtract AUX·2^n_bits (a constant point).
        off = _g1_mul_native((ax, ay), 1 << n_bits)
        neg_off = self.constant(off[0], (FQ_MODULUS - off[1]) % FQ_MODULUS)
        return self.add_incomplete(acc, neg_off)


def _g1_mul_native(pt: tuple[int, int], k: int) -> tuple[int, int]:
    """Native affine scalar mul for constant-point offsets."""
    from .bn254 import G1

    r = G1(pt[0], pt[1]).mul(k)
    return (r.x, r.y)
