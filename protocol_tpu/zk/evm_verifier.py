"""EVM verifier contract generation for PLONK proofs.

The analog of the reference's ``gen_evm_verifier_code`` →
``compile_yul`` → ``evm_verify`` pipeline (circuit/src/verifier/
mod.rs:94-134): given a compiled verifying key, emit runtime EVM
bytecode that verifies keccak-transcript proofs fully in-contract —
transcript replay with KECCAK256, point/scalar canonicality checks,
gate + permutation + lookup constraint evaluation at the challenge
(compiled straight from the same Sym constraint builders the Python
prover/verifier use, so the three can never diverge), the quotient
check, and the GWC batch-opening pairing check through precompiles
0x06/0x07/0x08 (field inverses via 0x05 modexp).

Calldata layout (matching the reference's EtVerifierWrapper forwarding
of ``pub_ins ‖ proof``, EtVerifierWrapper.sol:35-89): instance values
as 32-byte big-endian words in verifying-key column order, then the
proof bytes exactly as produced by ``plonk.prove(...,
transcript="keccak")``.  On acceptance the contract returns one word 1;
any malformed or invalid proof reverts.

The generated contract is straight-line (no loops), so large circuits
exceed mainnet's EIP-170 code-size cap — fine for the in-process EVM
this framework ships (and for gas measurement); a public-chain deploy
would need the looped/chunked layout.

Stack conventions (both this generator and the interpreter follow real
EVM semantics): binary ops consume the TOP as their first operand, so
``ADDMOD(a, b, m)`` is emitted as push-m, push-b, push-a; ``SUB``
computes top − next.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..evm.machine import asm
from .bn254 import GENERATOR
from .plonk import (
    R,
    Domain,
    Sym,
    VerifyingKey,
    _lookup_constraints,
    _opening_entries,
    _perm_constraints,
)
from .rns import FQ_MODULUS as Q

# -- static memory map -------------------------------------------------

M_R = 0x000
M_Q = 0x020
ECIN = 0x040  # 4 words: ecAdd input (ecMul uses 3)
ECOUT = 0x0C0  # 2 words
PAIR = 0x100  # 12 words
ACC_A = 0x280  # 2 words
ACC_B = 0x2C0  # 2 words
MODEXP_IN = 0x300  # 6 words
MODEXP_OUT = 0x3C0
T_STATE = 0x400
T_PEND = 0x420


def infer_n_t(vk: VerifyingKey, proof: bytes) -> int:
    """Quotient-chunk count from a sample proof's byte length — the
    Python verifier's own inference, re-exported for codegen callers."""
    from .plonk import quotient_chunks

    n_t = quotient_chunks(vk, len(proof))
    assert n_t >= 1, "proof too short"
    return n_t


@dataclass
class GeneratedVerifier:
    runtime: bytes
    n_t: int
    calldata_len: int

    MAGIC = b"ETVRFY01"

    def calldata(self, pub_ins: list[int], proof: bytes) -> bytes:
        out = b"".join((v % R).to_bytes(32, "big") for v in pub_ins)
        return out + proof

    def to_bytes(self) -> bytes:
        """The et_verifier.bin artifact format (data/et_verifier.bin
        analog): magic, n_t, expected calldata length, runtime code."""
        return (
            self.MAGIC
            + self.n_t.to_bytes(4, "little")
            + self.calldata_len.to_bytes(4, "little")
            + self.runtime
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "GeneratedVerifier":
        if data[:8] != cls.MAGIC:
            raise ValueError("bad verifier artifact magic")
        n_t = int.from_bytes(data[8:12], "little")
        calldata_len = int.from_bytes(data[12:16], "little")
        return cls(runtime=data[16:], n_t=n_t, calldata_len=calldata_len)


class _Gen:
    def __init__(self):
        self.items: list = []
        self.consts: dict[int, int] = {}  # raw 256-bit value -> blob index
        self._labels = 0
        self.slot_top = 0

    def emit(self, *items):
        self.items.extend(items)

    def label(self) -> str:
        self._labels += 1
        return f"L{self._labels}"

    def mload(self, off: int):
        self.emit(off, "MLOAD")

    def mstore(self, off: int):
        """Stack [value] -> mem[off]."""
        self.emit(off, "MSTORE")

    def cdload(self, off: int):
        self.emit(off, "CALLDATALOAD")

    def const(self, value: int):
        """Push a pooled constant via the data blob (raw, NOT reduced —
        the pool holds Fr scalars and Fq coordinates alike)."""
        assert 0 <= value < (1 << 256)
        idx = self.consts.setdefault(value, len(self.consts))
        self.emit(("cref", idx))

    def alloc_slot(self) -> int:
        off = self.slot_top
        self.slot_top += 32
        return off

    def require(self):
        """Stack [cond]: revert when zero."""
        ok = self.label()
        self.emit(("ref", ok), "JUMPI", 0, 0, "REVERT", ("label", ok))


def generate_evm_verifier(
    vk: VerifyingKey, n_t: int, n_instance_values: int, _debug: str | None = None
) -> GeneratedVerifier:
    """Emit runtime bytecode verifying this circuit's keccak-flow
    proofs; ``n_instance_values`` fixes the public-input word count.
    ``_debug``: name of an internal slot (e.g. "combined", "x") to
    RETURN right after constraint evaluation instead of verifying —
    codegen diagnosis only."""
    assert len(vk.instance_names) == 1, "expects exactly one instance column"
    g = _Gen()
    domain = Domain(vk.k)
    n, w, w_inv = vk.n, domain.omega, domain.omega_inv
    n_inv = pow(n, R - 2, R)

    entries = _opening_entries(vk, n_t)
    all_rots = sorted({rot for _, _, rots in entries for rot in rots})

    # ---- static calldata layout ---------------------------------------
    inst_words = n_instance_values
    off = 32 * inst_words
    layout: dict[tuple, int] = {}

    def take(words: int) -> int:
        nonlocal off
        o = off
        off += 32 * words
        return o

    for i in range(vk.n_advice):
        layout[("commit", "advice", i)] = take(2)
    for i in range(len(vk.lookups)):
        layout[("commit", "lkA", i)] = take(2)
        layout[("commit", "lkS", i)] = take(2)
    for c in range(len(vk.chunks)):
        layout[("commit", "z", c)] = take(2)
    for i in range(len(vk.lookups)):
        layout[("commit", "lkZ", i)] = take(2)
    for c in range(n_t):
        layout[("commit", "t", c)] = take(2)
    n_evals = 0
    for kind, idx, rots in entries:
        for rot in rots:
            layout[("eval", kind, idx, rot)] = take(1)
            n_evals += 1
    for rot in all_rots:
        layout[("commit", "W", rot)] = take(2)
    calldata_len = off

    # ---- slot allocation ----------------------------------------------
    max_pend = max(
        32 * (1 + inst_words) + 64 * vk.n_advice,  # digest+inst+advice run
        64 * 2 * len(vk.lookups),
        64 * (len(vk.chunks) + len(vk.lookups)),
        64 * n_t,
        32 * n_evals,
        64 * len(all_rots),
    )
    g.slot_top = T_PEND + max_pend + 32

    S = {
        name: g.alloc_slot()
        for name in (
            "theta", "beta", "gamma", "y", "x", "v", "u",
            "xn", "zh", "l0", "llast", "combined", "y_pow", "t_eval",
            "v_pow", "u_pow", "E", "x_g", "F", "F2", "term", "term2",
            "dbg_gates", "dbg_perm",
        )
    }
    assert S["F2"] == S["F"] + 32 and S["term2"] == S["term"] + 32
    inst_eval_slot = g.alloc_slot()

    # ---- init ---------------------------------------------------------
    g.emit(R)
    g.mstore(M_R)
    g.emit(Q)
    g.mstore(M_Q)
    g.emit(calldata_len, "CALLDATASIZE", "EQ")
    g.require()

    # ---- transcript replay --------------------------------------------
    pending = [0]

    def absorb(load):
        load()
        g.mstore(T_PEND + pending[0])
        pending[0] += 32

    def squeeze(dest: int):
        g.emit(32 + pending[0], T_STATE, "KECCAK256")
        g.emit("DUP1")
        g.mstore(T_STATE)
        g.mload(M_R)
        g.emit("SWAP1", "MOD")  # [R, digest] -> digest % R
        g.mstore(dest)
        pending[0] = 0

    def check_scalar(o: int):
        g.mload(M_R)
        g.cdload(o)
        g.emit("LT")  # top(x) < next(R)
        g.require()

    def check_point(o: int):
        g.mload(M_Q)
        g.cdload(o)
        g.emit("LT")
        g.mload(M_Q)
        g.cdload(o + 32)
        g.emit("LT", "AND")
        g.require()
        # y^2 == x^3 + 3 (mod Q), or (x, y) == (0, 0)
        g.mload(M_Q)
        g.emit(3)
        g.mload(M_Q)
        g.mload(M_Q)
        g.cdload(o)
        g.cdload(o)
        g.emit("MULMOD")  # x^2
        g.cdload(o)
        g.emit("MULMOD")  # x^3
        g.emit("ADDMOD")  # x^3 + 3
        g.mload(M_Q)
        g.cdload(o + 32)
        g.cdload(o + 32)
        g.emit("MULMOD")  # y^2
        g.emit("EQ")
        g.cdload(o)
        g.emit("ISZERO")
        g.cdload(o + 32)
        g.emit("ISZERO", "AND", "OR")
        g.require()

    def absorb_point(o: int):
        check_point(o)
        absorb(lambda: g.cdload(o))
        absorb(lambda: g.cdload(o + 32))

    absorb(lambda: g.const(vk.digest))
    for i in range(inst_words):
        check_scalar(32 * i)
        absorb(lambda o=32 * i: g.cdload(o))
    for i in range(vk.n_advice):
        absorb_point(layout[("commit", "advice", i)])
    if vk.lookups:
        squeeze(S["theta"])
        for i in range(len(vk.lookups)):
            absorb_point(layout[("commit", "lkA", i)])
            absorb_point(layout[("commit", "lkS", i)])
    squeeze(S["beta"])
    squeeze(S["gamma"])
    for c in range(len(vk.chunks)):
        absorb_point(layout[("commit", "z", c)])
    for i in range(len(vk.lookups)):
        absorb_point(layout[("commit", "lkZ", i)])
    squeeze(S["y"])
    for c in range(n_t):
        absorb_point(layout[("commit", "t", c)])
    squeeze(S["x"])
    for kind, idx, rots in entries:
        for rot in rots:
            o = layout[("eval", kind, idx, rot)]
            check_scalar(o)
            absorb(lambda o=o: g.cdload(o))
    squeeze(S["v"])
    for rot in all_rots:
        absorb_point(layout[("commit", "W", rot)])
    squeeze(S["u"])

    # ---- x^n, Z_H(x), l0, l_last, instance eval -----------------------
    g.mload(S["x"])
    for _ in range(vk.k):
        g.mload(M_R)
        g.emit("SWAP1", "DUP1", "MULMOD")  # [v] -> [v^2 mod R]
    g.emit("DUP1")
    g.mstore(S["xn"])
    # zh = (xn + (R-1)) % R; require != 0
    g.mload(M_R)
    g.emit("SWAP1")  # [R, xn]
    g.const(R - 1)
    g.emit("ADDMOD")  # (R-1 + xn) % R
    g.emit("DUP1")
    g.mstore(S["zh"])
    g.emit("ISZERO", "ISZERO")
    g.require()

    def f_inv_of(load_value):
        """Stack result: inverse of the loaded value (0x05 modexp)."""
        for i in range(3):
            g.emit(32)
            g.mstore(MODEXP_IN + 32 * i)
        load_value()
        g.mstore(MODEXP_IN + 96)
        g.const(R - 2)
        g.mstore(MODEXP_IN + 128)
        g.mload(M_R)
        g.mstore(MODEXP_IN + 160)
        g.emit(32, MODEXP_OUT, 192, MODEXP_IN, 0x05, "GAS", "STATICCALL")
        g.require()
        g.mload(MODEXP_OUT)

    def x_minus(wi: int):
        """Stack result: (x - wi) mod R."""
        g.mload(M_R)
        g.const((R - wi) % R)
        g.mload(S["x"])
        g.emit("ADDMOD")

    def lagrange_to(dest: int, wi: int):
        """dest = wi * n_inv * zh * inv(x - wi)."""
        f_inv_of(lambda: x_minus(wi))  # [inv]
        g.mload(M_R)
        g.emit("SWAP1")  # [R, inv]
        g.const(wi * n_inv % R)
        g.emit("MULMOD")  # [inv * c]
        g.mload(M_R)
        g.emit("SWAP1")
        g.mload(S["zh"])
        g.emit("MULMOD")
        g.mstore(dest)

    lagrange_to(S["l0"], 1)
    lagrange_to(S["llast"], pow(w, n - 1, R))

    g.emit(0)
    g.mstore(inst_eval_slot)
    for i in range(inst_words):
        f_inv_of(lambda i=i: x_minus(pow(w, i, R)))  # [inv]
        g.mload(M_R)
        g.emit("SWAP1")
        g.const(pow(w, i, R) * n_inv % R)
        g.emit("MULMOD")
        g.mload(M_R)
        g.emit("SWAP1")
        g.mload(S["zh"])
        g.emit("MULMOD")
        g.mload(M_R)
        g.emit("SWAP1")
        g.cdload(32 * i)
        g.emit("MULMOD")
        g.mload(M_R)
        g.emit("SWAP1")
        g.mload(inst_eval_slot)
        g.emit("ADDMOD")
        g.mstore(inst_eval_slot)

    # ---- constraint evaluation at x -----------------------------------
    n_adv, n_inst = vk.n_advice, len(vk.instance_names)
    n_fixed = len(vk.fixed_names)
    base_slots = n_adv + n_inst + n_fixed
    sigma_slots = [base_slots + j for j in range(len(vk.perm_slots))]
    z_slots = [base_slots + len(sigma_slots) + c for c in range(len(vk.chunks))]
    x_slot = base_slots + len(sigma_slots) + len(z_slots)
    l0_slot, llast_slot = x_slot + 1, x_slot + 2
    n_lk = len(vk.lookups)
    lk_a_slots = [llast_slot + 1 + i for i in range(n_lk)]
    lk_s_slots = [llast_slot + 1 + n_lk + i for i in range(n_lk)]
    lk_z_slots = [llast_slot + 1 + 2 * n_lk + i for i in range(n_lk)]
    CH = 1 << 40
    ch_theta, ch_beta, ch_gamma = CH, CH + 1, CH + 2

    def load_leaf(slot: int, rot: int):
        if slot == x_slot:
            return g.mload(S["x"])
        if slot == l0_slot:
            return g.mload(S["l0"])
        if slot == llast_slot:
            return g.mload(S["llast"])
        if slot == ch_theta:
            return g.mload(S["theta"])
        if slot == ch_beta:
            return g.mload(S["beta"])
        if slot == ch_gamma:
            return g.mload(S["gamma"])
        if slot < n_adv:
            return g.cdload(layout[("eval", "advice", slot, rot)])
        if slot < n_adv + n_inst:
            assert rot == 0, "instance rotations unsupported"
            return g.mload(inst_eval_slot)
        if slot < base_slots:
            return g.cdload(layout[("eval", "fixed", slot - n_adv - n_inst, rot)])
        if slot in sigma_slots:
            return g.cdload(layout[("eval", "sigma", slot - base_slots, rot)])
        if slot in lk_a_slots:
            return g.cdload(layout[("eval", "lkA", lk_a_slots.index(slot), rot)])
        if slot in lk_s_slots:
            return g.cdload(layout[("eval", "lkS", lk_s_slots.index(slot), rot)])
        if slot in lk_z_slots:
            return g.cdload(layout[("eval", "lkZ", lk_z_slots.index(slot), rot)])
        return g.cdload(layout[("eval", "z", z_slots.index(slot), rot)])

    memo: dict[int, int] = {}

    def emit_expr(sym: Sym):
        """Leave sym's value (mod R) on the stack."""
        if sym.op == "col":
            return load_leaf(*sym.args)
        if sym.op == "const":
            return g.const(sym.args[0])
        key = id(sym)
        if key in memo:
            return g.mload(memo[key])
        if sym.op == "neg":
            # (0 + (R - a)) % R
            g.mload(M_R)
            g.emit(0)
            emit_expr(sym.args[0])
            g.mload(M_R)
            g.emit("SUB")  # top(R) - next(a) = R - a
            g.emit("ADDMOD")
        elif sym.op == "sub":
            # (a + (R - b)) % R
            g.mload(M_R)
            emit_expr(sym.args[1])
            g.mload(M_R)
            g.emit("SUB")  # R - b
            emit_expr(sym.args[0])
            g.emit("ADDMOD")
        else:
            g.mload(M_R)
            emit_expr(sym.args[1])
            emit_expr(sym.args[0])
            g.emit("ADDMOD" if sym.op == "add" else "MULMOD")
        slot = g.alloc_slot()
        memo[key] = slot
        g.emit("DUP1")
        g.mstore(slot)

    # Build (and hold alive) every constraint list before any emission:
    # the expression memo is keyed by id(), so letting one list die
    # would let a later Sym reuse a freed id and alias a stale slot.
    perm_cons = _perm_constraints(
        vk,
        Sym.col(ch_beta),
        Sym.col(ch_gamma),
        z_slots,
        sigma_slots,
        x_slot,
        l0_slot,
        llast_slot,
    )
    lookup_cons = _lookup_constraints(
        vk,
        Sym.col(ch_theta),
        Sym.col(ch_beta),
        Sym.col(ch_gamma),
        lk_a_slots,
        lk_s_slots,
        lk_z_slots,
        l0_slot,
        llast_slot,
        n_adv + n_inst,
    )

    g.emit(0)
    g.mstore(S["combined"])
    g.emit(1)
    g.mstore(S["y_pow"])

    def add_constraint(emit_term):
        g.mload(M_R)  # for ADDMOD
        g.mload(M_R)  # for MULMOD
        emit_term()
        g.mload(S["y_pow"])
        g.emit("MULMOD")
        g.mload(S["combined"])
        g.emit("ADDMOD")
        g.mstore(S["combined"])
        g.mload(M_R)
        g.mload(S["y"])
        g.mload(S["y_pow"])
        g.emit("MULMOD")
        g.mstore(S["y_pow"])

    for spec in vk.gates:
        sel_off = layout[("eval", "fixed", spec.sel_slot - n_adv - n_inst, 0)]
        for con in spec.constraints:

            def term(con=con, sel_off=sel_off):
                g.mload(M_R)
                emit_expr(con)
                g.cdload(sel_off)
                g.emit("MULMOD")

            add_constraint(term)
    g.mload(S["combined"])
    g.mstore(S["dbg_gates"])
    for con in perm_cons:
        add_constraint(lambda con=con: emit_expr(con))
    g.mload(S["combined"])
    g.mstore(S["dbg_perm"])
    for con in lookup_cons:
        add_constraint(lambda con=con: emit_expr(con))

    if _debug is not None:
        g.mload(S[_debug])
        g.emit(0, "MSTORE", 32, 0, "RETURN")

    # ---- quotient check -----------------------------------------------
    g.emit(0)
    g.mstore(S["t_eval"])
    for c in range(n_t - 1, -1, -1):
        g.mload(M_R)
        g.mload(M_R)
        g.mload(S["xn"])
        g.mload(S["t_eval"])
        g.emit("MULMOD")
        g.cdload(layout[("eval", "t", c, 0)])
        g.emit("ADDMOD")
        g.mstore(S["t_eval"])
    g.mload(M_R)
    g.mload(S["zh"])
    g.mload(S["t_eval"])
    g.emit("MULMOD")
    g.mload(S["combined"])
    g.emit("EQ")
    g.require()

    # ---- GWC batch opening --------------------------------------------
    def ec_mul(load_point, load_scalar):
        """ECOUT = point * scalar (0x07)."""
        load_point(ECIN)
        load_scalar()
        g.mstore(ECIN + 64)
        g.emit(64, ECOUT, 96, ECIN, 0x07, "GAS", "STATICCALL")
        g.require()

    def ec_add_into(acc: int):
        """acc += ECOUT (0x06)."""
        for src, dst in (
            (acc, ECIN),
            (acc + 32, ECIN + 32),
            (ECOUT, ECIN + 64),
            (ECOUT + 32, ECIN + 96),
        ):
            g.mload(src)
            g.mstore(dst)
        g.emit(64, ECOUT, 128, ECIN, 0x06, "GAS", "STATICCALL")
        g.require()
        g.mload(ECOUT)
        g.mstore(acc)
        g.mload(ECOUT + 32)
        g.mstore(acc + 32)

    def commit_loader(kind: str, idx):
        if kind in ("fixed", "sigma"):
            pt = (vk.fixed_commits if kind == "fixed" else vk.sigma_commits)[idx]

            def load(dst, pt=pt):
                g.const(pt.x)
                g.mstore(dst)
                g.const(pt.y)
                g.mstore(dst + 32)

            return load
        o = layout[("commit", kind, idx)]

        def load(dst, o=o):
            g.cdload(o)
            g.mstore(dst)
            g.cdload(o + 32)
            g.mstore(dst + 32)

        return load

    for acc in (ACC_A, ACC_A + 32, ACC_B, ACC_B + 32):
        g.emit(0)
        g.mstore(acc)
    g.emit(1)
    g.mstore(S["u_pow"])

    for rot in all_rots:
        wr = pow(w, rot, R) if rot >= 0 else pow(w_inv, -rot, R)
        g.mload(M_R)
        g.const(wr)
        g.mload(S["x"])
        g.emit("MULMOD")
        g.mstore(S["x_g"])
        for acc in (S["F"], S["F2"]):
            g.emit(0)
            g.mstore(acc)
        g.emit(0)
        g.mstore(S["E"])
        g.emit(1)
        g.mstore(S["v_pow"])
        for kind, idx, rots in entries:
            if rot not in rots:
                continue
            ec_mul(commit_loader(kind, idx), lambda: g.mload(S["v_pow"]))
            ec_add_into(S["F"])
            g.mload(M_R)
            g.mload(M_R)
            g.cdload(layout[("eval", kind, idx, rot)])
            g.mload(S["v_pow"])
            g.emit("MULMOD")
            g.mload(S["E"])
            g.emit("ADDMOD")
            g.mstore(S["E"])
            g.mload(M_R)
            g.mload(S["v"])
            g.mload(S["v_pow"])
            g.emit("MULMOD")
            g.mstore(S["v_pow"])

        def load_G(dst):
            g.emit(GENERATOR.x)
            g.mstore(dst)
            g.emit(GENERATOR.y)
            g.mstore(dst + 32)

        def neg_E():
            # (0 + (R - E)) % R
            g.mload(M_R)
            g.emit(0)
            g.mload(S["E"])
            g.mload(M_R)
            g.emit("SUB")  # R - E
            g.emit("ADDMOD")

        # term = F + (-E)*G + x_g*W
        ec_mul(load_G, neg_E)
        for i in (0, 32):
            g.mload(S["F"] + i)
            g.mstore(S["term"] + i)
        ec_add_into(S["term"])
        ec_mul(commit_loader("W", rot), lambda: g.mload(S["x_g"]))
        ec_add_into(S["term"])

        def load_term(dst):
            g.mload(S["term"])
            g.mstore(dst)
            g.mload(S["term2"])
            g.mstore(dst + 32)

        ec_mul(load_term, lambda: g.mload(S["u_pow"]))
        ec_add_into(ACC_B)
        ec_mul(commit_loader("W", rot), lambda: g.mload(S["u_pow"]))
        ec_add_into(ACC_A)
        g.mload(M_R)
        g.mload(S["u"])
        g.mload(S["u_pow"])
        g.emit("MULMOD")
        g.mstore(S["u_pow"])

    # ---- pairing: e(B, g2) * e(-A, tau_g2) == 1 -----------------------
    def g2_words(pt):
        return [pt.x.coeffs[1], pt.x.coeffs[0], pt.y.coeffs[1], pt.y.coeffs[0]]

    g.mload(ACC_B)
    g.mstore(PAIR)
    g.mload(ACC_B + 32)
    g.mstore(PAIR + 32)
    for i, word in enumerate(g2_words(vk.srs.g2)):
        g.const(word)
        g.mstore(PAIR + 64 + 32 * i)
    g.mload(ACC_A)
    g.mstore(PAIR + 192)
    # -A.y = (Q - y) % Q  (identity stays identity)
    g.mload(M_Q)  # modulus for MOD
    g.mload(ACC_A + 32)
    g.mload(M_Q)
    g.emit("SUB", "MOD")  # (Q - y) % Q
    g.mstore(PAIR + 224)
    for i, word in enumerate(g2_words(vk.srs.tau_g2)):
        g.const(word)
        g.mstore(PAIR + 256 + 32 * i)
    g.emit(32, ECOUT, 384, PAIR, 0x08, "GAS", "STATICCALL")
    g.require()
    g.mload(ECOUT)
    g.emit(1, "EQ")
    g.require()
    g.emit(1, 0, "MSTORE", 32, 0, "RETURN")

    # ---- finalize: CODECOPY const blob, resolve crefs -----------------
    c_mem = g.slot_top
    blob_words = sorted(g.consts, key=g.consts.get)
    blob = b"".join(v.to_bytes(32, "big") for v in blob_words)
    blob_off = 0
    code = b""
    for _ in range(6):
        full_items: list = [len(blob), blob_off, c_mem, "CODECOPY"]
        for it in g.items:
            if isinstance(it, tuple) and it[0] == "cref":
                full_items.extend([c_mem + 32 * it[1], "MLOAD"])
            else:
                full_items.append(it)
        code = asm(*full_items)
        if len(code) == blob_off:
            break
        blob_off = len(code)
    assert len(code) == blob_off, "blob offset failed to converge"
    return GeneratedVerifier(
        runtime=code + blob, n_t=n_t, calldata_len=calldata_len
    )


def _revert_with(msg: bytes) -> list:
    """asm items: revert with Error(string) ABI encoding."""
    items: list = [0x08C379A0 << 224, 0, "MSTORE", 0x20, 4, "MSTORE", len(msg), 36, "MSTORE"]
    padded = msg.ljust((len(msg) + 31) // 32 * 32, b"\0")
    for i in range(0, len(padded), 32):
        items += [int.from_bytes(padded[i : i + 32], "big"), 68 + i, "MSTORE"]
    items += [4 + 64 + len(padded), 0, "REVERT"]
    return items


def generate_wrapper(verifier_addr: int) -> bytes:
    """The EtVerifierWrapper analog (EtVerifierWrapper.sol:35-89):
    forwards its entire calldata (pub_ins ‖ proof) to the raw verifier
    via STATICCALL, reverting "verifier-missing" when no code is
    deployed there and "verification-failed" when the proof is bad."""
    return asm(
        verifier_addr,
        "EXTCODESIZE",
        ("ref", "present"),
        "JUMPI",
        *_revert_with(b"verifier-missing"),
        ("label", "present"),
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        32, 0, "CALLDATASIZE", 0, verifier_addr, "GAS", "STATICCALL",
        ("ref", "ok"),
        "JUMPI",
        *_revert_with(b"verification-failed"),
        ("label", "ok"),
        32, 0, "RETURN",
    )


def evm_verify(
    gen: GeneratedVerifier, pub_ins: list[int], proof: bytes, gas: int = 500_000_000
):
    """Deploy the generated verifier behind a wrapper in a fresh
    in-process EVM and verify — the reference's ``evm_verify``
    (verifier/mod.rs:117-134).  Returns (accepted, gas_used)."""
    from ..evm.machine import EVM

    evm = EVM()
    verifier = evm.deploy_runtime(gen.runtime)
    wrapper = evm.deploy_runtime(generate_wrapper(verifier))
    r = evm.call(wrapper, gen.calldata(pub_ins, proof), gas=gas)
    accepted = r.success and int.from_bytes(r.returndata, "big") == 1
    return accepted, r.gas_used
