"""The in-circuit aggregation stack: Poseidon transcript chipset and
the accumulation-fold circuit.

This is the rebuild of the half the reference never finished — the
in-circuit side of proof aggregation (`verifier/transcript/mod.rs:35`
PoseidonReadChipset, `verifier/loader/mod.rs` Halo2 loader,
`verifier/aggregator.rs:178-322` — all left with TODOs and a
`without_witnesses` that returns `self`, so upstream keygen cannot even
run).  Scope here, honestly stated:

- **PoseidonTranscriptChip**: exact in-circuit mirror of the native
  `PoseidonTranscript` (zk/transcript.py) — chunked absorb, chained
  squeezes with challenge re-absorption.
- **fold circuit**: given k member snarks whose deferred pairing pairs
  (Bᵢ, Aᵢ) were produced natively by `verify_deferred`, prove that the
  Fiat-Shamir challenges cᵢ derive from the member data through the
  in-circuit transcript and that the revealed accumulator is the
  scalar fold ``lhs = Σ rᵢ·Bᵢ, rhs = Σ rᵢ·Aᵢ`` computed with the
  in-circuit emulated-Fq ECC chips (zk/wrong_field.py).

The fold scalars rᵢ are the low ``challenge_bits`` of cᵢ and enter the
circuit as *public inputs*: a truncation constrained in-circuit would
need a canonical 254-bit range proof (the classic mod-P decomposition
ambiguity), so the native/EVM wrapper checks ``rᵢ == cᵢ mod 2^bits``
instead — one public-input comparison.  Batching soundness is
2^-challenge_bits.  Full succinct verification of each member inside
the circuit (deriving Bᵢ/Aᵢ in-circuit) is future work beyond both
this rebuild and the reference.

Public instance layout (one instance column):
``[per member: cᵢ, rᵢ, Bᵢ.x·4, Bᵢ.y·4, Aᵢ.x·4, Aᵢ.y·4] ++
[lhs.x·4, lhs.y·4, rhs.x·4, rhs.y·4]``.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import field
from .aggregator import (
    Accumulator,
    Snark,
    absorb_members,
    check_shared_srs,
    proof_chunks,
)
from .bn254 import G1
from .cs import Cell, ConstraintSystem
from .gadgets import PoseidonChip, StdGate
from .plonk import verify_deferred
from .rns import decompose
from .transcript import PoseidonTranscript
from .wrong_field import AssignedPoint, EccChip, IntegerChip

P = field.MODULUS


class PoseidonTranscriptChip:
    """In-circuit Fiat-Shamir transcript with the native semantics of
    ``PoseidonTranscript`` (verifier/transcript/mod.rs:35 analog):
    scalars buffer until a squeeze folds them into the sponge state in
    width-5 chunks; each squeezed challenge is re-absorbed so
    successive challenges chain."""

    def __init__(self, std: StdGate, poseidon: PoseidonChip):
        self.std = std
        self.poseidon = poseidon
        self.zero = std.constant(0)
        self.state: list[Cell] = [self.zero] * poseidon.params.width
        self.pending: list[Cell] = []
        self._absorbed = False

    def common_scalar(self, cell: Cell) -> None:
        self.pending.append(cell)
        self._absorbed = True

    def squeeze_challenge(self) -> Cell:
        std, w = self.std, self.poseidon.params.width
        if not self._absorbed:
            self.pending = [self.zero]
        assert self.pending, "squeeze on empty transcript chip"
        for off in range(0, len(self.pending), w):
            chunk = list(self.pending[off : off + w])
            chunk += [self.zero] * (w - len(chunk))
            merged = [std.add(chunk[j], self.state[j]) for j in range(w)]
            self.state = self.poseidon.permute(merged)
        c = self.state[0]
        self.pending = [c]
        self._absorbed = True
        return c


@dataclass
class FoldWitness:
    """Everything the fold circuit needs about one member, produced
    natively by ``prepare_fold``."""

    vk_digest: int
    instances: list[int]
    proof: bytes
    b: G1  # deferred pair lhs
    a: G1  # deferred pair rhs
    challenge: int  # full Fr transcript challenge c_i
    scalar: int  # r_i = c_i mod 2^challenge_bits


@dataclass
class FoldStatement:
    """Native result bundle: member witnesses + folded accumulator +
    the circuit's public-instance vector."""

    members: list[FoldWitness]
    accumulator: Accumulator
    challenge_bits: int

    def public_inputs(self) -> list[int]:
        pub: list[int] = []
        for m in self.members:
            pub.append(m.challenge)
            pub.append(m.scalar)
            for coord in (m.b.x, m.b.y, m.a.x, m.a.y):
                pub.extend(decompose(coord))
        for coord in (
            self.accumulator.lhs.x,
            self.accumulator.lhs.y,
            self.accumulator.rhs.x,
            self.accumulator.rhs.y,
        ):
            pub.extend(decompose(coord))
        return pub


def prepare_fold(snarks: list[Snark], challenge_bits: int = 128) -> FoldStatement:
    """Native half of the fold (the same member-binding transcript as
    aggregator.accumulate, with the truncated fold scalars the circuit
    uses): derive per-member deferred pairs and transcript challenges,
    fold with rᵢ."""
    check_shared_srs(snarks)
    t = PoseidonTranscript()
    absorb_members(t, snarks)

    members: list[FoldWitness] = []
    lhs, rhs = G1(0, 0), G1(0, 0)
    mask = (1 << challenge_bits) - 1
    for s in snarks:
        pair = verify_deferred(s.vk, s.instances, s.proof, s.transcript)
        if pair is None:
            raise ValueError("member proof failed deferred verification")
        b, a = pair
        c = t.squeeze_challenge()
        r = c & mask
        members.append(
            FoldWitness(
                vk_digest=s.vk.digest,
                instances=s.instance_values(),
                proof=s.proof,
                b=b,
                a=a,
                challenge=c,
                scalar=r,
            )
        )
        lhs = lhs.add(b.mul(r))
        rhs = rhs.add(a.mul(r))
    return FoldStatement(
        members=members,
        accumulator=Accumulator(lhs=lhs, rhs=rhs),
        challenge_bits=challenge_bits,
    )


def synthesize_fold(stmt: FoldStatement) -> ConstraintSystem:
    """Build the fold circuit for a prepared statement (the working
    analog of Aggregator::synthesize, verifier/aggregator.rs:225-322)."""
    cs = ConstraintSystem()
    std = StdGate(cs)
    poseidon = PoseidonChip(cs)
    integer = IntegerChip(cs, std)
    ecc = EccChip(cs, std, integer)
    transcript = PoseidonTranscriptChip(std, poseidon)

    pub = stmt.public_inputs()
    inst_col = cs.column("instance", "instance")
    inst_cells = [cs.assign(inst_col, r, v) for r, v in enumerate(pub)]
    inst_iter = iter(inst_cells)

    # Absorb every member exactly like the native transcript.
    for m in stmt.members:
        transcript.common_scalar(std.witness(m.vk_digest))
        for v in m.instances:
            transcript.common_scalar(std.witness(v))
        transcript.common_scalar(std.constant(len(m.proof)))
        for chunk in proof_chunks(m.proof):
            transcript.common_scalar(std.witness(chunk))

    # Per member: challenge equality, pair points, scalar mul, fold.
    acc_lhs: AssignedPoint | None = None
    acc_rhs: AssignedPoint | None = None
    member_points: list[tuple[Cell, AssignedPoint, AssignedPoint]] = []
    for m in stmt.members:
        c = transcript.squeeze_challenge()
        c_inst = next(inst_iter)
        cs.copy(c_inst, c)
        r_inst = next(inst_iter)
        b_pt = ecc.witness(m.b.x, m.b.y)
        a_pt = ecc.witness(m.a.x, m.a.y)
        for pt in (b_pt, a_pt):
            for coord in (pt.x, pt.y):
                for limb in coord.limbs:
                    cs.copy(next(inst_iter), limb)
        member_points.append((r_inst, b_pt, a_pt))

    for r_inst, b_pt, a_pt in member_points:
        rb = ecc.scalar_mul(b_pt, r_inst, stmt.challenge_bits)
        ra = ecc.scalar_mul(a_pt, r_inst, stmt.challenge_bits)
        acc_lhs = rb if acc_lhs is None else ecc.add_incomplete(acc_lhs, rb)
        acc_rhs = ra if acc_rhs is None else ecc.add_incomplete(acc_rhs, ra)

    for pt in (acc_lhs, acc_rhs):
        for coord in (pt.x, pt.y):
            for limb in coord.limbs:
                cs.copy(next(inst_iter), limb)
    assert next(inst_iter, None) is None, "instance layout mismatch"
    return cs


def verify_fold(
    fold_vk,
    snarks: list[Snark],
    fold_proof: bytes,
    challenge_bits: int = 128,
    transcript: str = "poseidon",
) -> bool:
    """Full verification of a fold proof: recompute the expected public
    inputs natively (transcript challenges, deferred pairs, truncated
    scalars, folded accumulator), check the PLONK proof against them,
    then run the one decisive pairing check."""
    from . import plonk
    from .aggregator import finalize

    try:
        stmt = prepare_fold(snarks, challenge_bits)
    except ValueError:
        return False
    pub = stmt.public_inputs()
    if not plonk.verify(fold_vk, pub, fold_proof, transcript=transcript):
        return False
    return finalize(stmt.accumulator, snarks[0].vk)
