"""ctypes bindings for the C++ ZK proving runtime (native/zk_runtime.cpp).

NTT, Pippenger MSM, SRS ladder, vectorized field ops, and the gate
bytecode evaluator — the hot loops of KZG/PLONK proving (the analog of
halo2's Rust backend behind create_proof, circuit/src/utils.rs:259-281).
Every caller has a pure-Python fallback gated on ``available()``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

from ..crypto.field import MODULUS as R
from ..utils.limbs import (
    _MASK,
    U64P as _U64P,
    from_limbs,
    ptr as _ptr,
    to_limbs,
    to_limbs_fast,
)
from .bn254 import G1

#: PROTOCOL_TPU_NATIVE_DIR points the loader at an alternate build
#: (the sanitizer wall's instrumented variants — tools/sanitize_native.py).
_NATIVE_DIR = (
    Path(os.environ["PROTOCOL_TPU_NATIVE_DIR"]).resolve()
    if os.environ.get("PROTOCOL_TPU_NATIVE_DIR")
    else Path(__file__).resolve().parents[2] / "native"
)
_LIB_PATH = _NATIVE_DIR / "libzk_runtime.so"
_lib = None  # None = untried, False = failed, else CDLL
#: One-time loader guard.  zk/ stopped being thread-confined at the
#: prover pool (ISSUE 10): the proving plane's dispatcher threads, the
#: ingest dispatchers (via batch crypto), and the /aggregate executor
#: all race the first ``_load()`` — two unguarded loaders could each
#: run the make rebuild and publish different CDLL objects mid-setup.
#: The double-checked fast path keeps steady-state calls lock-free.
_load_lock = threading.Lock()

_I64P = ctypes.POINTER(ctypes.c_int64)


#: Bump together with zk_abi_version() in native/zk_runtime.cpp whenever
#: symbols are added or signatures change; _load() rebuilds a stale .so.
_ABI_VERSION = 4

#: Phase-timer table order — must match the ZkPhase enum in
#: native/zk_runtime.cpp.
PHASES = ("msm", "ntt", "gate_eval", "field_ops", "srs")


def _rebuild():
    subprocess.run(
        ["make", "-C", str(_NATIVE_DIR), "-B", "libzk_runtime.so"],
        check=True,
        capture_output=True,
    )


def _load():
    global _lib
    # Lock-free fast path: after the one-time publish, _lib is a
    # fully-initialized CDLL and readers never contend.
    if _lib is False:
        raise OSError("zk native runtime unavailable (previous build failed)")
    if _lib is not None:
        return _lib
    with _load_lock:
        return _load_locked()


def _load_locked():
    """The slow path, serialized: build/ABI-check/bind exactly once —
    callers re-check ``_lib`` under the lock (double-checked init)."""
    global _lib
    if _lib is False:
        raise OSError("zk native runtime unavailable (previous build failed)")
    if _lib is not None:
        return _lib
    if not _LIB_PATH.exists():
        try:
            _rebuild()
        except Exception:
            _lib = False
            raise
    lib = ctypes.CDLL(str(_LIB_PATH))
    try:
        lib.zk_abi_version.restype = ctypes.c_int64
        stale = lib.zk_abi_version() != _ABI_VERSION
    except AttributeError:
        stale = True
    if stale:
        # A .so from an older checkout: rebuild in place.  dlopen caches
        # by path — re-opening the same path returns the already-mapped
        # old object — so load the fresh build through a unique temp
        # copy to guarantee the new symbols are visible in this process.
        try:
            _rebuild()
        except Exception:
            _lib = False
            raise
        import shutil
        import tempfile

        tmp = tempfile.NamedTemporaryFile(
            prefix="libzk_runtime_", suffix=".so", delete=False
        )
        tmp.close()
        shutil.copy2(_LIB_PATH, tmp.name)
        lib = ctypes.CDLL(tmp.name)
    lib.zk_ntt.argtypes = [_U64P, ctypes.c_int64, _U64P, ctypes.c_int]
    lib.zk_vec_mul.argtypes = [_U64P, _U64P, _U64P, ctypes.c_int64]
    lib.zk_vec_add.argtypes = [_U64P, _U64P, _U64P, ctypes.c_int64]
    lib.zk_vec_sub.argtypes = [_U64P, _U64P, _U64P, ctypes.c_int64]
    lib.zk_batch_inv.argtypes = [_U64P, _U64P, ctypes.c_int64]
    lib.zk_msm.argtypes = [_U64P, _U64P, ctypes.c_int64, _U64P]
    lib.zk_srs_powers.argtypes = [_U64P, ctypes.c_int64, _U64P]
    lib.zk_eval_program.argtypes = [
        ctypes.c_int64,
        ctypes.c_int64,
        _U64P,
        ctypes.c_int64,
        _I64P,
        ctypes.c_int64,
        _U64P,
        ctypes.c_int64,
        _U64P,
    ]
    lib.zk_eval_program.restype = ctypes.c_int64
    lib.zk_eval_program2.argtypes = [
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int64,
        _I64P,
        ctypes.c_int64,
        _U64P,
        ctypes.c_int64,
        _U64P,
    ]
    lib.zk_eval_program2.restype = ctypes.c_int64
    lib.zk_powers.argtypes = [_U64P, ctypes.c_int64, _U64P]
    lib.zk_scale_add.argtypes = [_U64P, _U64P, _U64P, ctypes.c_int64]
    lib.zk_poly_eval.argtypes = [_U64P, ctypes.c_int64, _U64P, _U64P]
    lib.zk_div_linear.argtypes = [_U64P, ctypes.c_int64, _U64P, _U64P]
    lib.zk_phase_count.restype = ctypes.c_int64
    lib.zk_phase_stats.argtypes = [_I64P]
    lib.zk_phase_reset.argtypes = []
    lib.zk_abi_version.restype = ctypes.c_int64
    assert lib.zk_abi_version() == _ABI_VERSION
    _lib = lib
    return lib


def available() -> bool:
    global _lib
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError, AssertionError, AttributeError):
        _lib = False
        return False


def _iptr(arr: np.ndarray):
    return arr.ctypes.data_as(_I64P)


# -- phase attribution -------------------------------------------------


def phase_stats() -> dict[str, dict[str, float]]:
    """Cumulative engine phase table (deep attribution): phase name ->
    ``{"calls": int, "seconds": float}``.  Monotonic since process
    start (or the last :func:`reset_phase_stats`); the prover snapshots
    it around a prove and bridges the delta into the epoch span tree.
    Returns all-zero rows when the native runtime is unavailable, so
    callers need no availability guard."""
    if not available():
        return {p: {"calls": 0, "seconds": 0.0} for p in PHASES}
    lib = _load()
    n = int(lib.zk_phase_count())
    out = np.zeros((n, 2), dtype=np.int64)
    lib.zk_phase_stats(_iptr(out))
    stats: dict[str, dict[str, float]] = {}
    for i, name in enumerate(PHASES):
        calls, ns = (int(out[i, 0]), int(out[i, 1])) if i < n else (0, 0)
        stats[name] = {"calls": calls, "seconds": ns / 1e9}
    return stats


def reset_phase_stats() -> None:
    """Zero the engine phase table (tests and bench harnesses)."""
    if available():
        _load().zk_phase_reset()


def phase_delta(
    before: dict[str, dict[str, float]], after: dict[str, dict[str, float]]
) -> dict[str, dict[str, float]]:
    """Per-phase (calls, seconds) difference between two snapshots —
    the attribution for one timed region (e.g. one SNARK prove)."""
    return {
        name: {
            "calls": after[name]["calls"] - before.get(name, {}).get("calls", 0),
            "seconds": round(
                after[name]["seconds"] - before.get(name, {}).get("seconds", 0.0),
                9,
            ),
        }
        for name in after
    }


# -- public ops --------------------------------------------------------


def ntt(values: list[int], root: int, inverse: bool = False) -> list[int]:
    """In-place radix-2 NTT; `root` must be a primitive len(values)-th
    root of unity in Fr (pass the inverse root with inverse=True)."""
    lib = _load()
    n = len(values)
    assert n & (n - 1) == 0, "NTT size must be a power of two"
    data = to_limbs(values)
    root_l = to_limbs([root])
    lib.zk_ntt(_ptr(data), n, _ptr(root_l), 1 if inverse else 0)
    return from_limbs(data)


def vec_mul(a: list[int], b: list[int]) -> list[int]:
    lib = _load()
    al, bl = to_limbs(a), to_limbs(b)
    out = np.empty_like(al)
    lib.zk_vec_mul(_ptr(al), _ptr(bl), _ptr(out), len(a))
    return from_limbs(out)


def batch_inv(a: list[int]) -> list[int]:
    lib = _load()
    al = to_limbs(a)
    out = np.empty_like(al)
    lib.zk_batch_inv(_ptr(al), _ptr(out), len(a))
    return from_limbs(out)


def _points_to_limbs(points: list[G1]) -> np.ndarray:
    buf = b"".join(
        p.x.to_bytes(32, "little") + p.y.to_bytes(32, "little") for p in points
    )
    return np.frombuffer(buf, dtype=np.uint64).reshape(-1, 8).copy()


def _limbs_to_point(arr: np.ndarray) -> G1:
    vals = arr.astype(object)
    x = int(vals[0]) | int(vals[1]) << 64 | int(vals[2]) << 128 | int(vals[3]) << 192
    y = int(vals[4]) | int(vals[5]) << 64 | int(vals[6]) << 128 | int(vals[7]) << 192
    return G1(x, y)


def msm(scalars: list[int], points: list[G1]) -> G1:
    if len(scalars) != len(points):
        raise ValueError(
            f"msm length mismatch: {len(scalars)} scalars vs "
            f"{len(points)} points"
        )
    lib = _load()
    n = len(scalars)
    s = to_limbs_fast([x % R for x in scalars])
    p = _points_to_limbs(points)
    out = np.zeros(8, dtype=np.uint64)
    lib.zk_msm(_ptr(s), _ptr(p), n, _ptr(out))
    return _limbs_to_point(out)


def msm_limbs(scalars: np.ndarray, point_limbs: np.ndarray) -> G1:
    """MSM with (n,4) canonical scalar limbs and pre-converted (n,8)
    point limbs — the zero-conversion hot path for commitments."""
    if scalars.shape[0] != point_limbs.shape[0]:
        raise ValueError(
            f"msm_limbs length mismatch: {scalars.shape[0]} scalars vs "
            f"{point_limbs.shape[0]} point rows"
        )
    lib = _load()
    n = scalars.shape[0]
    s = np.ascontiguousarray(scalars, dtype=np.uint64)
    out = np.zeros(8, dtype=np.uint64)
    lib.zk_msm(_ptr(s), _ptr(point_limbs), n, _ptr(out))
    return _limbs_to_point(out)


def powers(base: int, n: int) -> np.ndarray:
    """(n,4) canonical limbs of base^0 .. base^(n-1)."""
    lib = _load()
    b = to_limbs([base % R])
    out = np.empty((n, 4), dtype=np.uint64)
    lib.zk_powers(_ptr(b), n, _ptr(out))
    return out


def scale_add(acc: np.ndarray, p: np.ndarray, scalar: int) -> None:
    """acc[i] += scalar * p[i] over min(len) rows, in place (canonical)."""
    lib = _load()
    n = min(acc.shape[0], p.shape[0])
    s = to_limbs([scalar % R])
    lib.zk_scale_add(_ptr(acc), _ptr(np.ascontiguousarray(p[:n])), _ptr(s), n)


def poly_eval_limbs(coeffs: np.ndarray, x: int) -> int:
    lib = _load()
    xl = to_limbs([x % R])
    out = np.empty(4, dtype=np.uint64)
    lib.zk_poly_eval(_ptr(np.ascontiguousarray(coeffs)), coeffs.shape[0], _ptr(xl), _ptr(out))
    return int(out[0]) | int(out[1]) << 64 | int(out[2]) << 128 | int(out[3]) << 192


def div_linear_limbs(coeffs: np.ndarray, z: int) -> np.ndarray:
    """(p - p(z)) / (X - z) on (n,4) canonical limbs -> (n-1,4)."""
    lib = _load()
    n = coeffs.shape[0]
    zl = to_limbs([z % R])
    out = np.empty((max(n - 1, 1), 4), dtype=np.uint64)
    if n <= 1:
        out[:] = 0
        return out
    lib.zk_div_linear(_ptr(np.ascontiguousarray(coeffs)), n, _ptr(zl), _ptr(out))
    return out


def srs_g1_powers(tau: int, n: int) -> list[G1]:
    lib = _load()
    t = to_limbs([tau % R])
    out = np.empty((n, 8), dtype=np.uint64)
    lib.zk_srs_powers(_ptr(t), n, _ptr(out))
    return [_limbs_to_point(out[i]) for i in range(n)]


def eval_program(
    m: int,
    columns: np.ndarray,
    rot_stride: int,
    code: list[int],
    consts: list[int],
) -> list[int]:
    """Run the gate bytecode over all m points.  ``columns`` is an
    (n_cols, m, 4) uint64 array of canonical limbs."""
    lib = _load()
    n_cols = columns.shape[0] if columns.size else 0
    cols = np.ascontiguousarray(columns, dtype=np.uint64)
    code_arr = np.asarray(code, dtype=np.int64)
    consts_arr = to_limbs(consts) if consts else np.zeros((1, 4), dtype=np.uint64)
    out = np.empty((m, 4), dtype=np.uint64)
    rc = lib.zk_eval_program(
        m,
        n_cols,
        _ptr(cols),
        rot_stride,
        _iptr(code_arr),
        len(code_arr),
        _ptr(consts_arr),
        len(consts) if consts else 0,
        _ptr(out),
    )
    if rc != 0:
        raise ValueError(
            "malformed gate program (stack depth, operand index, or truncation)"
        )
    return from_limbs(out)
