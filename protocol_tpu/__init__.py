"""protocol_tpu — a TPU-native EigenTrust reputation framework.

A ground-up rebuild of the capabilities of the `brech1/protocol` reference
("ZK Eigen Trust"): peers sign EdDSA attestations of trust in their
neighbours, a node ingests attestations from an on-chain AttestationStation
registry, computes global trust scores by EigenTrust power iteration each
epoch, and serves a verifiable proof of the result.

Where the reference runs a fixed 5-peer convergence loop on CPU
(circuit/src/circuit.rs:425-470), this framework executes the convergence
loop on TPU through JAX/XLA: dense `jnp` kernels for small sets, sparse
(BCOO / COO segment-sum) kernels for real graphs, and `shard_map`-sharded
SpMV with `lax.psum` collectives over a `jax.sharding.Mesh` for 1M+ peer
graphs — behind a pluggable `TrustBackend`.

Subpackages
-----------
- ``crypto``   — Bn254 Fr field, Poseidon/Rescue-Prime, BabyJubJub EdDSA,
  BLAKE-512 KDF (reference: circuit/src/{poseidon,eddsa,edwards,params}).
- ``trust``    — exact-field native trust kernels and the set-managed
  EigenTrust semantics (reference: circuit/src/circuit.rs::native,
  circuit/src/native.rs::EigenTrustSet).
- ``ops``      — jit'd JAX kernels: dense/sparse power iteration, fixed
  point utilities.
- ``parallel`` — device mesh helpers and sharded SpMV collectives.
- ``models``   — the flagship EigenTrust "model" and graph generators.
- ``zk``       — constraint system, gadget library, EigenTrust circuit and
  a MockProver-equivalent checker (reference: circuit/src/{lib,gadgets}).
- ``node``     — the protocol node: manager, attestation codec, epoch
  loop, HTTP API (reference: server/src).
- ``client``   — CLI wallet: attest / verify / deploy (reference:
  client/src).
"""

__version__ = "0.1.0"
