"""Lock-witness runtime: observe what the static analyzer inferred.

Opt-in debug mode (off by default — the production node never pays for
it): while installed, ``threading.Lock``/``RLock`` allocations return
witness-wrapped locks that record, per allocation site:

- the set of **holder threads** and a per-site **wait-time histogram**
  (contention), exported through ``obs/metrics.py``
  (``eigentrust_lock_wait_seconds{site}``);
- **acquisition-order edges**: when a thread acquires lock B while
  holding lock A, the witness records A→B keyed by allocation site.

:meth:`LockWitness.watch` additionally instruments attribute *writes*
on chosen objects (a per-class ``__setattr__`` shim), recording the
writing thread and the witnessed locks it held — the runtime side of
the static guard map.

:meth:`LockWitness.cross_check` closes the loop against
:class:`~.checker.StaticConcurrencyModel`:

1. observed order edges must be **acyclic**;
2. every observed edge between locks whose allocation sites map to
   statically known locks must appear in the **static order graph**
   (a runtime-only edge means the analyzer's graph is incomplete —
   or a code path acquires locks in an order the tree never declares);
3. for every watched attribute the analyzer inferred as **guarded**,
   no cross-thread write may be observed **bare** (static says
   guarded ⇒ runtime must never see an unguarded write from a second
   thread).

Wrapped locks proxy the private ``Condition`` integration surface
(``_is_owned``/``_release_save``/``_acquire_restore``), so
``threading.Condition`` built on a witnessed lock keeps working.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from pathlib import Path
from typing import Any, Iterable

_REPO_ROOT = str(Path(__file__).resolve().parents[3])


def _allocation_site() -> tuple[str, int]:
    """(repo-relative file, line) of the nearest repo frame allocating
    this lock; ("<external>", 0) when allocation came from outside."""
    import sys

    frame = sys._getframe(2)
    while frame is not None:
        fname = frame.f_code.co_filename
        if fname.startswith(_REPO_ROOT) and "concurrency/witness" not in fname:
            rel = fname[len(_REPO_ROOT) :].lstrip("/")
            return rel, frame.f_lineno
        frame = frame.f_back
    return "<external>", 0


class _WitnessedLock:
    """Wraps one real lock; records holders, waits, and order edges."""

    def __init__(self, witness: "LockWitness", real: Any, site: tuple[str, int]):
        self._witness = witness
        self._real = real
        self._site = site
        self._depth = 0  # RLock reentrancy (single owner at a time)

    # -- core protocol --------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.perf_counter()
        if timeout == -1:
            ok = self._real.acquire(blocking)
        else:
            ok = self._real.acquire(blocking, timeout)
        if ok:
            self._witness._on_acquire(
                self._site, time.perf_counter() - t0, first=self._depth == 0
            )
            self._depth += 1
        return ok

    def release(self) -> None:
        self._depth -= 1
        if self._depth <= 0:
            self._depth = 0
            self._witness._on_release(self._site)
        self._real.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._real.locked() if hasattr(self._real, "locked") else False

    # -- Condition integration (private threading API passthrough) ------

    def _is_owned(self):  # pragma: no cover - Condition internals
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def _release_save(self):  # pragma: no cover - Condition internals
        self._witness._on_release(self._site)
        depth, self._depth = self._depth, 0
        if hasattr(self._real, "_release_save"):
            return depth, self._real._release_save()
        self._real.release()
        return depth, None

    def _acquire_restore(self, state):  # pragma: no cover - Condition internals
        depth, inner = state
        if hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(inner)
        else:
            self._real.acquire()
        self._depth = depth
        self._witness._on_acquire(self._site, 0.0, first=True)

    def __repr__(self) -> str:
        return f"<witnessed {self._real!r} @ {self._site[0]}:{self._site[1]}>"


class LockWitness:
    """Process-global witness; install()/uninstall() bracket a session."""

    def __init__(self) -> None:
        self._installed = False
        self._orig_lock: Any = None
        self._orig_rlock: Any = None
        self._tls = threading.local()
        self._state_lock = threading.Lock()  # guards the tallies below
        #: site -> set of thread idents that held it
        self.holders: dict[tuple[str, int], set[int]] = defaultdict(set)
        #: (outer site, inner site) -> count
        self.order_edges: dict[tuple, int] = defaultdict(int)
        #: site -> [wait seconds] (also mirrored to the obs histogram)
        self.waits: dict[tuple[str, int], list[float]] = defaultdict(list)
        #: (class name, attr) -> list of (thread ident, held sites)
        self.writes: dict[tuple[str, str], list[tuple[int, tuple]]] = defaultdict(
            list
        )
        self._patched_classes: list[type] = []
        self._watched: dict[int, frozenset[str]] = {}

    # -- install/uninstall ----------------------------------------------

    def install(self) -> "LockWitness":
        if self._installed:
            return self
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        witness = self

        def make_lock() -> _WitnessedLock:
            return _WitnessedLock(witness, witness._orig_lock(), _allocation_site())

        def make_rlock() -> _WitnessedLock:
            return _WitnessedLock(witness, witness._orig_rlock(), _allocation_site())

        threading.Lock = make_lock  # type: ignore[misc]
        threading.RLock = make_rlock  # type: ignore[misc]
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = self._orig_lock  # type: ignore[misc]
        threading.RLock = self._orig_rlock  # type: ignore[misc]
        for cls in self._patched_classes:
            orig = cls.__dict__["__witness_orig_setattr__"]
            cls.__setattr__ = orig  # type: ignore[method-assign]
            del cls.__witness_orig_setattr__  # type: ignore[attr-defined]
        self._patched_classes.clear()
        self._watched.clear()
        self._installed = False

    def __enter__(self) -> "LockWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- runtime recording ----------------------------------------------

    def _held(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _on_acquire(self, site: tuple[str, int], wait_s: float, first: bool) -> None:
        if getattr(self._tls, "in_mirror", False):
            return  # instrument-internal acquisition (metrics mirror)
        stack = self._held()
        ident = threading.get_ident()
        with self._state_lock:
            self.holders[site].add(ident)
            self.waits[site].append(wait_s)
            if first:
                for outer in stack:
                    if outer != site:
                        self.order_edges[(outer, site)] += 1
        if first:
            stack.append(site)
        # Contention surface: scrape-able even mid-test.  The mirror is
        # re-entrancy-guarded: when the metrics registry's own lock was
        # allocated under the witness, observing through it would
        # recurse back here and deadlock on the non-reentrant registry
        # lock.
        if getattr(self._tls, "in_mirror", False):
            return
        self._tls.in_mirror = True
        try:
            from ...obs import metrics as obs_metrics

            obs_metrics.LOCK_WAIT_SECONDS.observe(
                wait_s, site=f"{site[0]}:{site[1]}"
            )
        except Exception:  # noqa: BLE001 - observability never throws
            pass
        finally:
            self._tls.in_mirror = False

    def _on_release(self, site: tuple[str, int]) -> None:
        if getattr(self._tls, "in_mirror", False):
            return
        stack = self._held()
        if site in stack:
            stack.reverse()
            stack.remove(site)
            stack.reverse()

    # -- guarded-write observation --------------------------------------

    def watch(self, obj: Any, attrs: Iterable[str]) -> None:
        """Record every write to ``attrs`` on ``obj``: writing thread +
        witnessed locks held.  Class ``__setattr__`` is shimmed once."""
        cls = type(obj)
        self._watched[id(obj)] = frozenset(attrs) | self._watched.get(
            id(obj), frozenset()
        )
        if "__witness_orig_setattr__" in cls.__dict__:
            return
        witness = self
        orig = cls.__setattr__

        def traced_setattr(inst, name, value):
            watched = witness._watched.get(id(inst))
            if watched is not None and name in watched:
                with witness._state_lock:
                    witness.writes[(cls.__name__, name)].append(
                        (threading.get_ident(), tuple(witness._held()))
                    )
            orig(inst, name, value)

        cls.__witness_orig_setattr__ = orig  # type: ignore[attr-defined]
        cls.__setattr__ = traced_setattr  # type: ignore[method-assign]
        self._patched_classes.append(cls)

    # -- reporting + cross-check ----------------------------------------

    def report(self) -> dict:
        with self._state_lock:
            return {
                "locks": {
                    f"{f}:{ln}": {
                        "threads": len(holders),
                        "acquisitions": len(self.waits.get((f, ln), [])),
                        "max_wait_s": max(self.waits.get((f, ln), [0.0]) or [0.0]),
                    }
                    for (f, ln), holders in sorted(self.holders.items())
                },
                "order_edges": {
                    f"{a[0]}:{a[1]} -> {b[0]}:{b[1]}": n
                    for (a, b), n in sorted(self.order_edges.items())
                },
                "watched_writes": {
                    f"{c}.{a}": len(ws) for (c, a), ws in sorted(self.writes.items())
                },
            }

    def cross_check(self, static) -> list[str]:
        """Violations of the static model observed at runtime (empty =
        consistent).  ``static`` is a StaticConcurrencyModel."""
        violations: list[str] = []
        with self._state_lock:
            edges = list(self.order_edges)
            writes = {k: list(v) for k, v in self.writes.items()}

        # 1. acyclicity of the observed graph.  "<external>" sites are
        # excluded: every lock allocated outside the repo shares that
        # one label, so edges through it alias distinct locks and can
        # fabricate cycles the program cannot actually deadlock on.
        graph: dict[tuple, set] = defaultdict(set)
        for a, b in edges:
            if a[0] == "<external>" or b[0] == "<external>":
                continue
            graph[a].add(b)
        visiting: set = set()
        done: set = set()

        def cyclic(node) -> bool:
            if node in done:
                return False
            if node in visiting:
                return True
            visiting.add(node)
            if any(cyclic(nxt) for nxt in graph.get(node, ())):
                return True
            visiting.discard(node)
            done.add(node)
            return False

        if any(cyclic(n) for n in list(graph)):
            violations.append(
                "observed lock-order graph is cyclic: "
                + "; ".join(f"{a}->{b}" for a, b in edges)
            )

        # 2. observed edges between statically known locks must be a
        # subset of the static order graph
        site_to_lock = static.site_to_lock()
        static_edges = set(static.order_edges)
        for a, b in edges:
            la, lb = site_to_lock.get(a), site_to_lock.get(b)
            if la is None or lb is None or la == lb:
                continue
            if (la, lb) not in static_edges:
                violations.append(
                    f"runtime order edge {la} -> {lb} "
                    f"({a[0]}:{a[1]} -> {b[0]}:{b[1]}) absent from the "
                    "static lock-order graph"
                )

        # 3. statically-guarded attrs must never see a bare cross-thread
        # write
        for (cls_name, attr), guard_locks in static.guard_map.items():
            ws = writes.get((cls_name, attr))
            if not ws:
                continue
            threads = {t for t, _ in ws}
            if len(threads) < 2:
                continue
            guard_sites = {
                site
                for lock_id in guard_locks
                for lock_id2, site in static.lock_sites.items()
                if lock_id2 == lock_id
            }
            for ident, held in ws:
                if not guard_sites & set(held):
                    violations.append(
                        f"{cls_name}.{attr}: statically guarded by "
                        f"{sorted(guard_locks)} but thread {ident} wrote it "
                        f"holding {list(held) or 'no witnessed locks'}"
                    )
                    break
        return violations


#: Process-global witness (tests install/uninstall around their run).
WITNESS = LockWitness()


__all__ = ["LockWitness", "WITNESS"]
