"""Pass-7 rules: guard inference, lock-order graph, blocking-under-lock.

Six rules over the :mod:`model` + :mod:`roots` whole-program view.
All of them fire only where concurrency is *provable* from the tree:
a class participates when its methods are reachable from >= 2
execution roots (the call graph resolves ``self.m()`` exactly,
module-level calls exactly, and cross-class ``obj.m()`` by method name
with a fan-out cap so generic names don't connect everything to
everything).

- ``unguarded-shared-attr``: an attribute accessed under a class lock
  in one method but bare in another (outside ``__init__``) — the
  guard discipline exists but has a hole; the bare site is the bug.
- ``unguarded-rmw``: a bare augmented assignment (``self.x += 1``) on
  a multiroot path — a read-modify-write torn across threads loses
  updates even under the GIL.
- ``check-then-act``: a bare branch-test read of an attribute followed
  by a bare write of the same attribute in the same multiroot method —
  the classic racy flag flip (two threads both pass the check).
- ``lock-order-cycle``: a cycle in the static lock-order graph (lock B
  acquired while A held, directly via nested ``with`` or transitively
  through calls) — deadlock potential.
- ``blocking-call-under-lock``: unbounded ``queue.put``/``get``,
  ``time.sleep``, ``subprocess``, socket/HTTP I/O, thread joins, or
  bare ``future.result()`` while holding a lock — every other acquirer
  stalls behind I/O.
- ``native-call-under-lock``: a native ``zk_runtime``/batch-verify/
  Poseidon call or a device sync (``block_until_ready``/
  ``device_get``) under a lock — these release the GIL and run for
  milliseconds-to-seconds, turning the lock into a global stall (the
  GIL-release hazard class).

Helper methods *only ever called with a class lock held* (every
in-class call site guarded by the same lock) inherit that guard, so
``_rotate_locked``-style helpers don't false-positive.

Findings matching the explicit :mod:`waivers` table are downgraded to
the report's waiver list — visible in ANALYSIS.json, never silent.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from ..report import Finding
from .model import (
    Access,
    ClassInfo,
    FuncInfo,
    ModuleModel,
    build_program_model,
)
from .roots import Root, discover_roots
from .waivers import WAIVERS, Waiver

#: Max candidate methods a cross-class ``obj.m()`` call may resolve to;
#: beyond this the name is too generic to carry reachability.
_FANOUT_CAP = 6

#: Trees whose class instances are *thread-confined by design* — each
#: object is constructed and used within a single thread of control
#: (the EVM devchain and client are test/tooling drivers, crypto
#: objects are per-call).  The shared-state rules (mixed-guard / RMW /
#: check-then-act) skip classes defined here; the lock-order and
#: blocking-under-lock rules still apply.  This is a declared policy,
#: recorded in the ANALYSIS.json concurrency section.
#:
#: zk/ left this list at the prover pool (ISSUE 10, closing PR 8's
#: recorded revisit): the proving plane's dispatcher threads, the
#: ingest dispatchers (batch crypto), and the /aggregate executor now
#: all reach the zk bridge — prover *instances* stay confined to one
#: dispatcher (or one worker process), but module state like
#: ``zk/native.py``'s loader globals is genuinely shared and now
#: analyzed (the loader grew its one-time-init lock in this PR).
_CONFINED_TREES = (
    "protocol_tpu/evm/",
    "protocol_tpu/client/",
    "protocol_tpu/crypto/",
    "protocol_tpu/models/",
)

#: Leaves of calls that block while holding the GIL-visible lock.
_SLEEP_CALLS = frozenset({"time.sleep", "sleep"})
_SUBPROCESS_ROOTS = frozenset({"subprocess", "os.system", "os.popen"})
_SOCKET_ROOTS = frozenset({"socket", "requests", "urllib", "http"})
_JOINISH_RECEIVERS = ("thread", "worker", "_writer", "proc")

#: Native / GIL-releasing entry points (the zk runtime's OpenMP
#: regions, batch crypto, and jax device syncs).
_NATIVE_LEAVES = frozenset(
    {
        "eddsa_verify_batch",
        "verify_batch",
        "poseidon_permute_batch",
        "msm",
        "ntt",
        "block_until_ready",
        "device_get",
        "zk_phase_stats",
        "zk_phase_reset",
    }
)
_NATIVE_RECEIVER_TOKENS = ("cnative", "zk_runtime", "native")


@dataclass
class StaticConcurrencyModel:
    """What the lock-witness runtime cross-checks against."""

    #: (class, attr) -> guard lock ids (attrs whose every non-init
    #: access is guarded — the *inferred guarded* set).
    guard_map: dict[tuple[str, str], frozenset[str]] = field(default_factory=dict)
    #: lock id -> (file, line) allocation site.
    lock_sites: dict[str, tuple[str, int]] = field(default_factory=dict)
    #: Static lock-order edges (outer, inner).
    order_edges: set[tuple[str, str]] = field(default_factory=set)
    roots: list[Root] = field(default_factory=list)
    multiroot_classes: set[str] = field(default_factory=set)

    def site_to_lock(self) -> dict[tuple[str, int], str]:
        return {site: lid for lid, site in self.lock_sites.items()}


# ---------------------------------------------------------------------------
# call graph + reachability
# ---------------------------------------------------------------------------


def _method_index(models: dict[str, ModuleModel]) -> dict[str, list[str]]:
    """method leaf name -> [Class.method quals] program-wide."""
    index: dict[str, list[str]] = {}
    for m in models.values():
        for cls in m.classes.values():
            for name, fn in cls.methods.items():
                index.setdefault(name, []).append(fn.qual)
    return index


def _func_index(models: dict[str, ModuleModel]) -> dict[str, list[str]]:
    index: dict[str, list[str]] = {}
    for m in models.values():
        for name in m.functions:
            index.setdefault(name, []).append(name)
    return index


def _all_funcs(models: dict[str, ModuleModel]) -> dict[str, FuncInfo]:
    out: dict[str, FuncInfo] = {}
    for m in models.values():
        out.update(m.functions)
        for cls in m.classes.values():
            for fn in cls.methods.values():
                out[fn.qual] = fn
    return out


def _resolve_call(
    name: str,
    fn: FuncInfo,
    model: ModuleModel,
    methods: dict[str, list[str]],
) -> list[str]:
    leaf = name.rsplit(".", 1)[-1]
    if name.startswith("self.") and name.count(".") == 1 and fn.cls is not None:
        cls = model.classes.get(fn.cls)
        if cls is not None and leaf in cls.methods:
            return [f"{fn.cls}.{leaf}"]
        # inherited / dynamic: fall through to the name index
    if "." not in name:
        if name in model.functions:
            return [name]
        return []
    candidates = methods.get(leaf, [])
    if 0 < len(candidates) <= _FANOUT_CAP:
        return list(candidates)
    return []


def _hook_registry(
    models: dict[str, ModuleModel],
    methods: dict[str, list[str]],
    funcs: dict[str, list[str]],
) -> dict[str, list[str]]:
    """``X.on_foo = <callable>`` registrations anywhere in the tree:
    hook attr name -> registered quals.  Calling through ``self.on_foo``
    (directly or via a local alias) then dispatches to these."""
    import ast as _ast

    from .roots import _entry_specs

    registry: dict[str, list[str]] = {}
    for m in models.values():
        if m.tree is None:
            continue
        for node in _ast.walk(m.tree):
            if not isinstance(node, _ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not (
                isinstance(tgt, _ast.Attribute) and tgt.attr.startswith("on_")
            ):
                continue
            for kind, name in _entry_specs(node.value, None):
                if kind == "qual":
                    registry.setdefault(tgt.attr, []).append(name)
                elif kind == "func":
                    registry.setdefault(tgt.attr, []).extend(funcs.get(name, []))
                elif kind == "leaf":
                    candidates = methods.get(name, []) + funcs.get(name, [])
                    if 0 < len(candidates) <= _FANOUT_CAP:
                        registry.setdefault(tgt.attr, []).extend(candidates)
    return registry


def _build_call_graph(
    models: dict[str, ModuleModel],
) -> dict[str, set[str]]:
    methods = _method_index(models)
    funcs = _func_index(models)
    hooks = _hook_registry(models, methods, funcs)
    graph: dict[str, set[str]] = {}
    for model in models.values():
        fns = list(model.functions.values()) + [
            fn for c in model.classes.values() for fn in c.methods.values()
        ]
        for fn in fns:
            edges = graph.setdefault(fn.qual, set())
            for call in fn.calls:
                leaf = call.name.rsplit(".", 1)[-1]
                if leaf in hooks:
                    edges.update(hooks[leaf])
                for target in _resolve_call(call.name, fn, model, methods):
                    edges.add(target)
    return graph


def _reachable(entries: list[str], graph: dict[str, set[str]]) -> set[str]:
    seen: set[str] = set()
    stack = list(entries)
    while stack:
        qual = stack.pop()
        if qual in seen:
            continue
        seen.add(qual)
        stack.extend(graph.get(qual, ()))
    return seen


def _root_entries(
    root: Root,
    models: dict[str, ModuleModel],
    methods: dict[str, list[str]],
    funcs: dict[str, list[str]],
) -> list[str]:
    out: list[str] = []
    for kind, name in root.entries:
        if kind == "qual":
            out.append(name)
        elif kind == "func":
            out.extend(funcs.get(name, []))
            # a Class name used as a callable -> its __init__ et al: skip
        elif kind == "leaf":
            candidates = methods.get(name, []) + funcs.get(name, [])
            if 0 < len(candidates) <= _FANOUT_CAP:
                out.extend(candidates)
    return out


# ---------------------------------------------------------------------------
# guard inference
# ---------------------------------------------------------------------------


def _inherited_guards(cls: ClassInfo) -> dict[str, frozenset[str]]:
    """method -> guards it always runs under, because every in-class
    call site of it is inside a ``with`` holding those locks."""
    call_guards: dict[str, list[frozenset[str]]] = {}
    for fn in cls.methods.values():
        for call in fn.calls:
            if call.name.startswith("self.") and call.name.count(".") == 1:
                leaf = call.name.split(".", 1)[1]
                if leaf in cls.methods:
                    call_guards.setdefault(leaf, []).append(call.guards)
    out: dict[str, frozenset[str]] = {}
    for method, guard_sets in call_guards.items():
        common = frozenset.intersection(*guard_sets) if guard_sets else frozenset()
        if common:
            out[method] = common
    return out


def _effective_accesses(cls: ClassInfo) -> list[tuple[str, Access]]:
    """(method, access) pairs with helper-inherited guards applied."""
    inherited = _inherited_guards(cls)
    out: list[tuple[str, Access]] = []
    for name, fn in cls.methods.items():
        extra = inherited.get(name, frozenset())
        for acc in fn.accesses:
            if extra:
                acc = Access(
                    acc.name,
                    acc.line,
                    acc.kind,
                    acc.guards | extra,
                    acc.in_test,
                )
            out.append((name, acc))
    return out


_INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------


def _finding(rule: str, message: str, file: str, line: int | None) -> Finding:
    return Finding(
        pass_name="concurrency",
        rule=rule,
        severity="error",
        message=message,
        file=file,
        line=line,
    )


def _is_blocking_call(call) -> str | None:
    """Why this call blocks (short label), or None."""
    name, leaf = call.name, call.name.rsplit(".", 1)[-1]
    root = name.split(".", 1)[0]
    if name in _SLEEP_CALLS:
        return "time.sleep"
    if root in _SUBPROCESS_ROOTS or name in _SUBPROCESS_ROOTS:
        return "subprocess"
    if root in _SOCKET_ROOTS:
        return "socket/HTTP I/O"
    if leaf in ("put", "get") and not call.bounded:
        receiver = name.rsplit(".", 1)[0].lower() if "." in name else ""
        if "queue" in receiver or receiver.endswith("_q"):
            return f"unbounded queue.{leaf}"
    if leaf == "join" and "." in name:
        receiver = name.rsplit(".", 1)[0].lower()
        if any(t in receiver for t in _JOINISH_RECEIVERS):
            return "thread join"
    if leaf == "result" and not call.bounded:
        receiver = name.rsplit(".", 1)[0].lower() if "." in name else ""
        if "future" in receiver or "submit" in receiver:
            return "future.result()"
    return None


def _is_native_call(call) -> bool:
    leaf = call.name.rsplit(".", 1)[-1]
    if leaf in _NATIVE_LEAVES:
        return True
    receiver = call.name.rsplit(".", 1)[0] if "." in call.name else ""
    return any(t in receiver for t in _NATIVE_RECEIVER_TOKENS)


def _find_cycle(edges: set[tuple[str, str]]) -> list[str] | None:
    """A lock-id cycle in the order graph, or None.  Self-edges on
    reentrant locks were already filtered by the caller."""
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict[str, int] = {}
    stack_path: list[str] = []

    def dfs(node: str) -> list[str] | None:
        color[node] = GRAY
        stack_path.append(node)
        for nxt in sorted(graph.get(node, ())):
            c = color.get(nxt, WHITE)
            if c == GRAY:
                i = stack_path.index(nxt)
                return stack_path[i:] + [nxt]
            if c == WHITE:
                cycle = dfs(nxt)
                if cycle is not None:
                    return cycle
        stack_path.pop()
        color[node] = BLACK
        return None

    for node in sorted(graph):
        if color.get(node, WHITE) == WHITE:
            cycle = dfs(node)
            if cycle is not None:
                return cycle
    return None


def analyze_models(
    models: dict[str, ModuleModel],
    waivers: tuple[Waiver, ...] = WAIVERS,
) -> tuple[list[Finding], dict, StaticConcurrencyModel]:
    """Run all six rules.  Returns (unwaived findings, the ANALYSIS.json
    ``concurrency`` section, the static model for the witness)."""
    trees = {rel: m.tree for rel, m in models.items() if m.tree is not None}
    roots = discover_roots(trees)
    graph = _build_call_graph(models)
    methods = _method_index(models)
    funcs = _func_index(models)

    # per-root reachability -> per-method root sets
    method_roots: dict[str, set[str]] = {}
    for i, root in enumerate(roots):
        label = f"{root.name}@{root.file}:{root.line}"
        for qual in _reachable(_root_entries(root, models, methods, funcs), graph):
            method_roots.setdefault(qual, set()).add(label)

    static = StaticConcurrencyModel(roots=roots)
    for m in models.values():
        for cls in m.classes.values():
            for decl in cls.locks.values():
                static.lock_sites[decl.lock_id] = (decl.file, decl.line)
        for decl in m.global_locks.values():
            static.lock_sites[decl.lock_id] = (decl.file, decl.line)

    reentrant = {
        decl.lock_id
        for m in models.values()
        for scope in (
            [d for c in m.classes.values() for d in c.locks.values()],
            list(m.global_locks.values()),
        )
        for decl in scope
        if decl.kind in ("RLock", "Condition")
    }

    findings: list[Finding] = []

    for m in models.values():
        for cls in m.classes.values():
            cls_roots: set[str] = set()
            for name in cls.methods:
                cls_roots |= method_roots.get(f"{cls.name}.{name}", set())
            multiroot = len(cls_roots) >= 2
            if multiroot:
                static.multiroot_classes.add(cls.name)

            confined = any(m.path.startswith(t) for t in _CONFINED_TREES)
            accesses = _effective_accesses(cls)
            lock_ids = {d.lock_id for d in cls.locks.values()}
            by_attr: dict[str, list[tuple[str, Access]]] = {}
            for method, acc in accesses:
                if acc.name in cls.locks or acc.name in cls.methods:
                    continue  # the lock attribute / bound-method reads
                by_attr.setdefault(acc.name, []).append((method, acc))

            for attr, uses in sorted(by_attr.items()):
                live = [
                    (meth, acc) for meth, acc in uses if meth not in _INIT_METHODS
                ]
                if not live:
                    continue
                guarded = [
                    (meth, acc)
                    for meth, acc in live
                    if acc.guards
                    & (lock_ids | {g for g in acc.guards if g.startswith("~")})
                ]
                bare = [(meth, acc) for meth, acc in live if not acc.guards]
                # inferred-guarded attrs feed the witness cross-check
                if guarded and not bare:
                    common = frozenset.intersection(
                        *(acc.guards for _, acc in guarded)
                    )
                    concrete = frozenset(g for g in common if not g.startswith("~"))
                    if concrete:
                        static.guard_map[(cls.name, attr)] = concrete
                if not multiroot or confined:
                    continue
                # rule 1: mixed discipline — only attrs whose binding
                # actually mutates after construction (a never-reassigned
                # reference to a thread-safe object needs no guard)
                mutated = any(
                    acc.kind in ("write", "aug") for _, acc in live
                )
                fired_r1 = False
                if guarded and bare and mutated:
                    guarded_methods = {meth for meth, _ in guarded}
                    all_guards = sorted(
                        frozenset.union(*(a.guards for _, a in guarded))
                    )
                    for meth, acc in bare:
                        if meth in guarded_methods and all(
                            gm == meth for gm, _ in guarded
                        ):
                            continue  # single-method mix: local reasoning
                        findings.append(
                            _finding(
                                "unguarded-shared-attr",
                                f"{cls.name}.{attr} is guarded by "
                                f"{all_guards} in {sorted(guarded_methods)} "
                                f"but accessed bare in {meth}() — a "
                                "cross-thread torn read/write (class "
                                f"reachable from {len(cls_roots)} roots)",
                                m.path,
                                acc.line,
                            )
                        )
                        fired_r1 = True
                        break  # one finding per attr: the first bare site
                # rule 2: bare RMW on a multiroot path
                if not fired_r1:
                    for meth, acc in live:
                        if acc.kind == "aug" and not acc.guards and (
                            len(method_roots.get(f"{cls.name}.{meth}", set())) >= 2
                            or multiroot
                        ):
                            findings.append(
                                _finding(
                                    "unguarded-rmw",
                                    f"{cls.name}.{attr} read-modify-write "
                                    f"({cls.name}.{meth}) without a lock on a "
                                    "multiroot path — concurrent updates are "
                                    "lost even under the GIL",
                                    m.path,
                                    acc.line,
                                )
                            )
                            break
                # rule 3: check-then-act
                if not fired_r1:
                    per_method: dict[str, list[Access]] = {}
                    for meth, acc in live:
                        per_method.setdefault(meth, []).append(acc)
                    for meth, accs in sorted(per_method.items()):
                        if len(method_roots.get(f"{cls.name}.{meth}", set())) < 2:
                            continue
                        test_reads = [
                            a for a in accs if a.in_test and not a.guards
                        ]
                        writes = [
                            a
                            for a in accs
                            if a.kind in ("write", "aug") and not a.guards
                        ]
                        hit = next(
                            (
                                w
                                for r in test_reads
                                for w in writes
                                if w.line > r.line
                            ),
                            None,
                        )
                        if hit is not None:
                            findings.append(
                                _finding(
                                    "check-then-act",
                                    f"{cls.name}.{meth}() tests "
                                    f"{cls.name}.{attr} and later writes it, "
                                    "both bare, on a multi-root path — two "
                                    "threads can both pass the check (racy "
                                    "flag flip)",
                                    m.path,
                                    hit.line,
                                )
                            )
                            break

            # module-level globals: same mixed/RMW logic, function scope
        module_confined = any(m.path.startswith(t) for t in _CONFINED_TREES)
        for fname, fn in m.functions.items():
            if module_confined:
                break
            n_roots = len(method_roots.get(fname, set()))
            for acc in fn.global_accesses:
                if acc.kind == "aug" and not acc.guards and n_roots >= 2:
                    findings.append(
                        _finding(
                            "unguarded-rmw",
                            f"module global {acc.name} read-modify-write in "
                            f"{fname}() without a lock on a multi-root path",
                            m.path,
                            acc.line,
                        )
                    )

    # rules 5+6: blocking / native calls under a lock
    for m in models.values():
        all_fns = list(m.functions.values()) + [
            fn for c in m.classes.values() for fn in c.methods.values()
        ]
        for fn in all_fns:
            for call in fn.calls:
                if not call.guards:
                    continue
                why = _is_blocking_call(call)
                if why is not None:
                    findings.append(
                        _finding(
                            "blocking-call-under-lock",
                            f"{call.name}() ({why}) inside "
                            f"`with {sorted(call.guards)}` in {fn.qual} — "
                            "every other acquirer stalls behind the block; "
                            "move the call outside the critical section or "
                            "bound it",
                            fn.file,
                            call.line,
                        )
                    )
                elif _is_native_call(call):
                    findings.append(
                        _finding(
                            "native-call-under-lock",
                            f"{call.name}() under `with {sorted(call.guards)}` "
                            f"in {fn.qual} — native/batch calls release the "
                            "GIL and run for ms-to-s, turning the lock into "
                            "a global stall (GIL-release hazard)",
                            fn.file,
                            call.line,
                        )
                    )

    # rule 4: lock-order cycles (concrete ids only, reentrant self-edges
    # dropped; one finding per cycle)
    edge_lines: dict[tuple[str, str], tuple[str, int]] = {}
    for m in models.values():
        for fn in list(m.functions.values()) + [
            f for c in m.classes.values() for f in c.methods.values()
        ]:
            for a, b, line in fn.order_edges:
                if a.startswith("~") or b.startswith("~"):
                    continue
                if a == b and a in reentrant:
                    continue
                static.order_edges.add((a, b))
                edge_lines.setdefault((a, b), (fn.file, line))
    # transitive edges through calls made under a held lock — resolved
    # STRICTLY (self-methods and same-module functions only): a
    # leaf-name fan-out here would fabricate edges, and a fabricated
    # edge can fabricate a deadlock cycle.
    def _resolve_strict(name: str, fn: FuncInfo, model: ModuleModel) -> list[str]:
        if name.startswith("self.") and name.count(".") == 1 and fn.cls is not None:
            cls = model.classes.get(fn.cls)
            leaf = name.split(".", 1)[1]
            if cls is not None and leaf in cls.methods:
                return [f"{fn.cls}.{leaf}"]
        if "." not in name and name in model.functions:
            return [name]
        return []

    all_fn_map = _all_funcs(models)
    for m in models.values():
        funcs_here = list(m.functions.values()) + [
            f for c in m.classes.values() for f in c.methods.values()
        ]
        for fn in funcs_here:
            for call in fn.calls:
                if not call.guards:
                    continue
                for target in _resolve_strict(call.name, fn, m):
                    callee = all_fn_map.get(target)
                    if callee is None:
                        continue
                    for inner in callee.acquired:
                        if inner.startswith("~"):
                            continue
                        for outer in call.guards:
                            if outer.startswith("~") or outer == inner:
                                continue
                            if (outer, inner) not in static.order_edges:
                                static.order_edges.add((outer, inner))
                                edge_lines[(outer, inner)] = (fn.file, call.line)
    cycle = _find_cycle(static.order_edges)
    if cycle is not None:
        first_edge = (cycle[0], cycle[1])
        file, line = edge_lines.get(first_edge, (None, None))
        findings.append(
            _finding(
                "lock-order-cycle",
                "lock-order cycle (deadlock potential): "
                + " -> ".join(cycle)
                + " — acquire these locks in one global order",
                file or "<program>",
                line,
            )
        )

    # waivers: explicit, enumerated, never silent
    live_findings: list[Finding] = []
    waived: list[dict] = []
    matched: set[int] = set()
    for f in findings:
        waiver = next(
            (
                (i, w)
                for i, w in enumerate(waivers)
                if w.matches(f.rule, f.file or "", f.message)
            ),
            None,
        )
        if waiver is None:
            live_findings.append(f)
        else:
            matched.add(waiver[0])
            waived.append(
                {
                    "rule": f.rule,
                    "file": f.file,
                    "line": f.line,
                    "symbol": waiver[1].symbol,
                    "reason": waiver[1].reason,
                }
            )

    stale_waivers = [
        {"symbol": w.symbol, "rule": w.rule, "reason": w.reason}
        for i, w in enumerate(waivers)
        if i not in matched
    ]
    # A dead waiver is itself a gate failure, in every run that
    # evaluates the table (the default full run included) — a fixed bug
    # must take its waiver with it, or the entry silently pre-suppresses
    # the NEXT bug in the same file.
    for entry in stale_waivers:
        live_findings.append(
            _finding(
                "stale-waiver",
                f"waiver {entry['symbol']!r} ({entry['rule']}) matches no "
                "live finding; remove it with the fix it documented",
                "protocol_tpu/analysis/concurrency/waivers.py",
                None,
            )
        )

    section = {
        "roots": [r.to_dict() for r in roots],
        "confined_trees": list(_CONFINED_TREES),
        "classes_analyzed": sum(len(m.classes) for m in models.values()),
        "multiroot_classes": sorted(static.multiroot_classes),
        "guarded_attrs": {
            f"{c}.{a}": sorted(locks)
            for (c, a), locks in sorted(static.guard_map.items())
        },
        "lock_graph": {
            "nodes": sorted(static.lock_sites),
            "edges": sorted([a, b] for a, b in static.order_edges),
        },
        "findings": len(live_findings),
        "waived": waived,
        "stale_waivers": stale_waivers,
    }
    return live_findings, section, static


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def analyze_sources(
    sources: dict[str, str], waivers: tuple[Waiver, ...] = ()
) -> list[Finding]:
    """In-memory whole-program run (fixtures/tests) — no waivers by
    default, so seeded violations always surface."""
    findings, _, _ = analyze_models(build_program_model(sources), waivers)
    return findings


def _tree_sources(root: Path) -> dict[str, str]:
    out: dict[str, str] = {}
    for path in sorted((root / "protocol_tpu").rglob("*.py")):
        out[str(path.relative_to(root))] = path.read_text()
    return out


def analyze_tree(
    root: str | Path | None = None,
) -> tuple[list[Finding], dict, StaticConcurrencyModel]:
    """Full run over ``protocol_tpu/`` with the real waiver table."""
    if root is None:
        root = Path(__file__).resolve().parents[3]
    return analyze_models(build_program_model(_tree_sources(Path(root))), WAIVERS)


def build_static_model(root: str | Path | None = None) -> StaticConcurrencyModel:
    """The witness cross-check input: guard map + lock sites + order
    graph for the real tree."""
    return analyze_tree(root)[2]


def run_concurrency_pass(
    root: str | Path | None = None,
) -> tuple[list[Finding], dict]:
    findings, section, _ = analyze_tree(root)
    return findings, section


__all__ = [
    "StaticConcurrencyModel",
    "analyze_models",
    "analyze_sources",
    "analyze_tree",
    "build_static_model",
    "run_concurrency_pass",
]
