"""Explicit pass-7 waivers — every suppression is enumerated and tested.

A waiver is a *documented* decision that a finding describes a design
the code makes safe by other means (GIL-atomic single-opcode ops,
boot-time-only writes, advisory counters).  The checker records every
match in the ANALYSIS.json ``concurrency.waived`` list, and
``tests/test_analysis.py`` asserts two invariants:

- zero **unwaived** findings on the real tree, and
- zero **stale** waivers (every entry below still matches a live
  finding — a fixed bug must take its waiver with it).

Matching is (rule, file substring, message substring) — the symbol
string names the class attribute or call site precisely enough that a
new, different bug in the same file cannot hide behind an old waiver.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Waiver:
    rule: str
    file: str  # substring of the repo-relative path
    symbol: str  # substring of the finding message (Class.attr / call)
    reason: str

    def matches(self, rule: str, file: str, message: str) -> bool:
        return (
            rule == self.rule and self.file in file and self.symbol in message
        )


WAIVERS: tuple[Waiver, ...] = (
    Waiver(
        rule="unguarded-shared-attr",
        file="protocol_tpu/obs/journal.py",
        symbol="FlightRecorder._file",
        reason=(
            "record()/flush() read _file bare by design: the hot path "
            "must never take a lock (doctrine at the top of journal.py). "
            "_file only transitions between None and an open handle "
            "under _io_lock; a torn read sees one of the two valid "
            "states, and flush() re-checks under the lock before "
            "writing.  The witness stress test exercises this exact "
            "interleaving."
        ),
    ),
    Waiver(
        rule="unguarded-rmw",
        file="protocol_tpu/utils/telemetry.py",
        symbol="TimerStats.",
        reason=(
            "TimerStats.record() mutates count/total, but every call "
            "site reaches it as `self.timers[name].record(...)` inside "
            "`with self._lock` on Telemetry — a cross-class guard the "
            "analyzer cannot see through a subscript receiver.  The "
            "lock-witness stress test watches these writes at runtime."
        ),
    ),
    Waiver(
        rule="unguarded-rmw",
        file="protocol_tpu/zk/cs.py",
        symbol="ConstraintSystem.n_rows",
        reason=(
            "zk/ stopped being tree-confined at the prover pool "
            "(ISSUE 10), but a ConstraintSystem is still *instance*-"
            "confined: each one is synthesized and consumed by exactly "
            "one prove path — one plane dispatcher thread, one worker "
            "process, or one /aggregate executor call — and never "
            "escapes it.  The genuinely shared zk state (the "
            "zk/native.py loader globals) grew a real lock instead.  "
            "The pooled-vs-inline bit-equality test would catch any "
            "cross-thread sharing regression as a torn row count."
        ),
    ),
    Waiver(
        rule="check-then-act",
        file="protocol_tpu/zk/plonk.py",
        symbol="_CosetEvaluator._shift_pows",
        reason=(
            "Per-prove lazy memo: a _CosetEvaluator lives inside one "
            "prove() call (one dispatcher thread or worker process); "
            "the flag flip can never race because the instance never "
            "crosses a thread.  Same instance-confinement argument as "
            "ConstraintSystem.n_rows — recorded, not locked, to keep "
            "the MSM-adjacent hot path allocation-free."
        ),
    ),
    Waiver(
        rule="unguarded-shared-attr",
        file="protocol_tpu/obs/lineage.py",
        symbol="LineageTracker._every",
        reason=(
            "maybe_begin() reads _every bare by design: it is the "
            "per-submission intake hot path (ingest plane submit), and "
            "the no-lock contract there mirrors the journal's record() "
            "doctrine.  _every is a single int flipped by configure() "
            "at node boot (and by tests); a torn read samples one "
            "period early or late — the sampled fraction is advisory, "
            "the entry table itself is fully lock-guarded."
        ),
    ),
    Waiver(
        rule="unguarded-rmw",
        file="protocol_tpu/obs/journal.py",
        symbol="FlightRecorder._seq",
        reason=(
            "_seq is an advisory ordering hint (commented 'benign "
            "race'): a lost increment reorders two events' seq numbers "
            "but loses no event — the ring append is the source of "
            "truth.  Locking the hot record() path to fix a cosmetic "
            "counter would invert the recorder's no-block contract."
        ),
    ),
)


__all__ = ["WAIVERS", "Waiver"]
