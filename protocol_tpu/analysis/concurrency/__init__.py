"""graftlint pass 7 — the whole-program concurrency analyzer.

The node is genuinely concurrent: the epoch pipeline's device worker,
the four-stage ingest plane, the journal writer thread, the asyncio
HTTP/event tasks and their executor offloads, signal handlers, and a
metrics registry scraped mid-epoch.  Passes 1–6 pin kernels and
hot-path hygiene; this pass pins the *threading contract*:

- :mod:`model` builds a per-module AST index — classes, methods, lock
  declarations (``self._lock = threading.Lock()`` and friends),
  per-attribute accesses with the set of locks held at each site, and
  call sites with their guard context.
- :mod:`roots` enumerates every execution root: ``threading.Thread``
  targets, thread-pool/executor submits (process pools are excluded —
  no shared memory), ``asyncio`` task/server/signal entry points, and
  ``main`` functions.
- :mod:`checker` runs the six pass-7 rules over the model (guard
  inference, mixed-discipline and RMW hazards, check-then-act flips,
  the lock-order graph with cycle detection, and the two
  blocking-under-lock classes), applies the explicit waiver table in
  :mod:`waivers`, and emits the ``concurrency`` section of
  ANALYSIS.json.
- :mod:`witness` is the runtime counterpart: an opt-in debug mode that
  wraps lock allocation to observe actual holder threads, acquisition
  order, and guarded writes, and cross-checks them against the static
  guard map and lock-order graph.
"""

from __future__ import annotations

from .checker import (
    StaticConcurrencyModel,
    analyze_sources,
    analyze_tree,
    build_static_model,
    run_concurrency_pass,
)
from .roots import Root, discover_roots
from .waivers import WAIVERS, Waiver

__all__ = [
    "Root",
    "StaticConcurrencyModel",
    "WAIVERS",
    "Waiver",
    "analyze_sources",
    "analyze_tree",
    "build_static_model",
    "discover_roots",
    "run_concurrency_pass",
]
