"""Execution-root discovery: where concurrent control flow enters.

A *root* is a call site that hands a callable to another thread of
control: ``threading.Thread(target=...)``, a thread-pool submit
(``executor.submit`` / ``loop.run_in_executor``), an asyncio task or
server handler (``create_task`` / ``ensure_future`` /
``start_server``), a signal handler, an ``atexit`` hook — plus the
synthetic ``main`` root for every module-level ``main`` function (the
interpreter's own thread is a root too).

``ProcessPoolExecutor`` submits are deliberately **not** roots: the
submitted function runs in another *process*, sharing no Python state
with this one; counting it would tag the verify workers' pure-crypto
code as multithreaded.  Modules that import ``ProcessPoolExecutor``
without ``ThreadPoolExecutor`` get their ``.submit`` sites skipped.

Each root carries *entry specs* naming the callables it starts:
``("qual", "Class.method")`` when resolvable from the call site
(``target=self._device_loop`` inside the class), ``("leaf", name)``
when only the method name is known (``target=t.bump``), or
``("func", name)`` for a module-level function.  Lambdas contribute
the calls inside their body as entries.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .model import dotted

EntrySpec = tuple[str, str]  # ("qual" | "leaf" | "func", name)


@dataclass(frozen=True)
class Root:
    name: str  # human label, e.g. "thread:epoch-pipeline-device"
    file: str
    line: int
    entries: tuple[EntrySpec, ...]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "file": self.file,
            "line": self.line,
            "entries": ["::".join(e) for e in self.entries],
        }


_THREAD_NAMES = ("threading.Thread", "Thread")
_TASK_NAMES = (
    "asyncio.create_task",
    "create_task",
    "asyncio.ensure_future",
    "ensure_future",
)


def _entry_specs(expr: ast.expr, cls: str | None) -> list[EntrySpec]:
    """Entry specs for a callable-valued argument expression."""
    if isinstance(expr, ast.Lambda):
        out: list[EntrySpec] = []
        for node in ast.walk(expr.body):
            if isinstance(node, ast.Call):
                out.extend(_entry_specs(node.func, cls))
        return out
    if isinstance(expr, ast.Call):
        # create_task(self._loop(...)) — the coroutine factory is the entry
        return _entry_specs(expr.func, cls)
    name = dotted(expr)
    if name is None:
        return []
    if name.startswith("self.") and cls is not None and name.count(".") == 1:
        return [("qual", f"{cls}.{name.split('.', 1)[1]}")]
    if "." in name:
        return [("leaf", name.rsplit(".", 1)[-1])]
    return [("func", name)]


class _RootVisitor(ast.NodeVisitor):
    def __init__(self, rel_path: str, tree: ast.Module):
        self.rel_path = rel_path
        self.roots: list[Root] = []
        self._class: list[str] = []
        imports = ast.dump(tree)
        self._process_pool_only = (
            "ProcessPoolExecutor" in imports and "ThreadPoolExecutor" not in imports
        )

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    def _add(self, label: str, node: ast.AST, entries: list[EntrySpec]) -> None:
        if entries:
            self.roots.append(
                Root(label, self.rel_path, node.lineno, tuple(entries))
            )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node.name == "main" and not self._class:
            self.roots.append(
                Root("main", self.rel_path, node.lineno, (("func", "main"),))
            )
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted(node.func)
        cls = self._class[-1] if self._class else None
        leaf = name.rsplit(".", 1)[-1] if name else None
        if name in _THREAD_NAMES:
            label = "thread"
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    label = f"thread:{kw.value.value}"
            for kw in node.keywords:
                if kw.arg == "target":
                    self._add(label, node, _entry_specs(kw.value, cls))
        elif leaf == "submit" and name != "self.submit":
            if not self._process_pool_only and node.args:
                self._add("executor-submit", node, _entry_specs(node.args[0], cls))
        elif leaf == "run_in_executor" and len(node.args) >= 2:
            self._add("executor-submit", node, _entry_specs(node.args[1], cls))
        elif name in _TASK_NAMES and node.args:
            self._add("asyncio-task", node, _entry_specs(node.args[0], cls))
        elif leaf == "start_server" and node.args:
            self._add("http-handler", node, _entry_specs(node.args[0], cls))
        elif leaf == "add_signal_handler" and len(node.args) >= 2:
            self._add("signal-handler", node, _entry_specs(node.args[1], cls))
        elif name is not None and name.split(".", 1)[0] == "atexit" and node.args:
            self._add("atexit-hook", node, _entry_specs(node.args[0], cls))
        elif leaf == "add_done_callback" and node.args:
            self._add("future-callback", node, _entry_specs(node.args[0], cls))
        self.generic_visit(node)


def discover_roots(trees: dict[str, ast.Module]) -> list[Root]:
    """{rel_path: parsed module} -> deduplicated root list."""
    roots: list[Root] = []
    seen: set[tuple] = set()
    for rel, tree in trees.items():
        visitor = _RootVisitor(rel, tree)
        visitor.visit(tree)
        for root in visitor.roots:
            key = (root.file, root.line, root.entries)
            if key not in seen:
                seen.add(key)
                roots.append(root)
    return roots


__all__ = ["EntrySpec", "Root", "discover_roots"]
