"""AST extraction for the concurrency analyzer: locks, guards, accesses.

One :class:`ModuleModel` per scanned file, built in two passes:

1. **Lock discovery** — every lock *declaration site*:
   ``self.X = threading.Lock()`` (any method), dataclass fields with a
   lock ``default_factory``, class-level and module-level lock
   assignments.  ``Lock``/``RLock``/``Condition`` all count — a
   ``Condition`` guards exactly like the lock it wraps.  The
   declaration line is the allocation site the lock-witness runtime
   matches against at runtime.
2. **Access attribution** — for every method/function: each
   ``self.<attr>`` access (read / write / augmented RMW, plus whether
   a read sits inside a branch test — the check-then-act shape), each
   module-global access (symtable-aware: local shadowing is not a
   global access; global *writes* require a ``global`` declaration),
   and each call site, all annotated with the **guard set**: the lock
   ids held at that point via enclosing ``with`` scopes.  Nested
   ``with`` scopes also yield static lock-order edges.

Lock identity: ``"Class.attr"`` for instance locks, ``"file::NAME"``
for module-level locks, and ``"~attr"`` for locks reached through a
non-self receiver (``with shard.lock:``) — wildcard guards count for
guard-presence but stay out of the order graph, where an unresolved
identity could fabricate cycles.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

#: Callables whose result is a guard-capable lock.
_LOCK_FACTORIES = frozenset(
    {
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "Lock",
        "RLock",
        "Condition",
    }
)

#: Attribute leaves accepted as wildcard guards on non-self receivers.
_LOCKISH_LEAVES = ("lock", "cv", "mutex", "cond")


def dotted(node: ast.expr) -> str | None:
    """``a.b.c`` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass(frozen=True)
class LockDecl:
    lock_id: str
    file: str
    line: int  # the allocation site (the Lock() call / field() line)
    kind: str  # "Lock" | "RLock" | "Condition"


@dataclass(frozen=True)
class Access:
    name: str  # attribute or global name
    line: int
    kind: str  # "read" | "write" | "aug"
    guards: frozenset[str]
    in_test: bool = False  # read inside an if/while/ternary test


@dataclass(frozen=True)
class CallSite:
    name: str  # dotted receiver chain, e.g. "self._queue.put"
    line: int
    guards: frozenset[str]
    #: True when the call carries ``timeout=``/``block=False`` (or a
    #: positional block arg) — bounded, so not a blocking hazard.
    bounded: bool


@dataclass
class FuncInfo:
    qual: str  # "Class.method" or "function"
    cls: str | None
    file: str
    line: int
    accesses: list[Access] = field(default_factory=list)
    global_accesses: list[Access] = field(default_factory=list)
    calls: list[CallSite] = field(default_factory=list)
    #: Lock ids this function acquires via ``with`` (top level or not).
    acquired: set[str] = field(default_factory=set)
    #: Static order edges (outer held when inner acquired) with lines.
    order_edges: list[tuple[str, str, int]] = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    file: str
    line: int
    methods: dict[str, FuncInfo] = field(default_factory=dict)
    locks: dict[str, LockDecl] = field(default_factory=dict)  # attr -> decl


@dataclass
class ModuleModel:
    path: str  # repo-relative
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    functions: dict[str, FuncInfo] = field(default_factory=dict)
    global_locks: dict[str, LockDecl] = field(default_factory=dict)
    #: Names assigned at module scope (global-state candidates).
    module_globals: set[str] = field(default_factory=set)
    tree: ast.Module | None = None


def _lock_kind(call: ast.expr) -> str | None:
    if not isinstance(call, ast.Call):
        return None
    name = dotted(call.func)
    if name in _LOCK_FACTORIES:
        return name.rsplit(".", 1)[-1]
    # dataclass field(default_factory=threading.Lock)
    if name is not None and name.rsplit(".", 1)[-1] in ("field", "dc_field"):
        for kw in call.keywords:
            if kw.arg == "default_factory":
                factory = dotted(kw.value)
                if factory in _LOCK_FACTORIES:
                    return factory.rsplit(".", 1)[-1]
    return None


class _LockCollector(ast.NodeVisitor):
    """Pass 1: lock declaration sites."""

    def __init__(self, model: ModuleModel):
        self.model = model
        self._class: list[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class.append(node.name)
        info = self.model.classes.setdefault(
            node.name, ClassInfo(node.name, self.model.path, node.lineno)
        )
        # class-level / dataclass-field lock declarations
        for stmt in node.body:
            target: str | None = None
            value: ast.expr | None = None
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                target, value = stmt.target.id, stmt.value
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(
                stmt.targets[0], ast.Name
            ):
                target, value = stmt.targets[0].id, stmt.value
            if target is None or value is None:
                continue
            kind = _lock_kind(value)
            if kind is not None:
                info.locks[target] = LockDecl(
                    f"{node.name}.{target}", self.model.path, value.lineno, kind
                )
        self.generic_visit(node)
        self._class.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        kind = _lock_kind(node.value)
        for tgt in node.targets:
            # self.X = threading.Lock() inside a method
            if (
                kind is not None
                and self._class
                and isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                cls = self.model.classes[self._class[-1]]
                cls.locks[tgt.attr] = LockDecl(
                    f"{cls.name}.{tgt.attr}", self.model.path, node.value.lineno, kind
                )
            # NAME = threading.Lock() at module scope
            if (
                kind is not None
                and not self._class
                and isinstance(tgt, ast.Name)
            ):
                self.model.global_locks[tgt.id] = LockDecl(
                    f"{self.model.path}::{tgt.id}",
                    self.model.path,
                    node.value.lineno,
                    kind,
                )
        self.generic_visit(node)


def _local_names(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> tuple[set[str], set[str]]:
    """(names assigned locally, names declared global) within ``fn``
    (nested functions included — close enough for shadowing)."""
    assigned: set[str] = set()
    declared_global: set[str] = set()
    for a in fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs:
        assigned.add(a.arg)
    if fn.args.vararg:
        assigned.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        assigned.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            declared_global.update(node.names)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            assigned.add(node.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    assigned.add(t.id)
    return assigned - declared_global, declared_global


class _AccessCollector(ast.NodeVisitor):
    """Pass 2: guarded/bare accesses, calls, with-lock order edges."""

    def __init__(self, model: ModuleModel, all_lock_attrs: frozenset[str]):
        self.model = model
        self.all_lock_attrs = all_lock_attrs
        self._class: list[str] = []
        self._func: list[FuncInfo] = []
        self._guards: list[str] = []
        self._in_test = 0
        self._locals: list[tuple[set[str], set[str]]] = []
        #: Per-function map of local names bound from ``v = self.attr``
        #: — calling ``v(...)`` is a call through ``self.attr`` (the
        #: tracer's hook-dispatch pattern).
        self._aliases: list[dict[str, str]] = []

    # -- scope tracking -------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    def _visit_func(self, node: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        nested = bool(self._func)
        if not nested:
            cls = self._class[-1] if self._class else None
            qual = f"{cls}.{node.name}" if cls else node.name
            info = FuncInfo(qual, cls, self.model.path, node.lineno)
            if cls:
                self.model.classes.setdefault(
                    cls, ClassInfo(cls, self.model.path, node.lineno)
                ).methods[node.name] = info
            else:
                self.model.functions[node.name] = info
            self._func.append(info)
            self._locals.append(_local_names(node))
            self._aliases.append({})
        # Nested defs/lambdas fold into the enclosing top-level
        # function: their bodies execute (at the latest) on the same
        # threads that can reach the enclosing function.
        self.generic_visit(node)
        if not nested:
            self._func.pop()
            self._locals.pop()
            self._aliases.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- guards ---------------------------------------------------------

    def _guard_id(self, expr: ast.expr) -> str | None:
        """Lock id acquired by ``with <expr>:``, or None (not a lock)."""
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            if isinstance(recv, ast.Name) and recv.id == "self" and self._class:
                cls = self.model.classes.get(self._class[-1])
                if cls is not None and expr.attr in cls.locks:
                    return cls.locks[expr.attr].lock_id
                if expr.attr in self.all_lock_attrs or any(
                    t in expr.attr.lower() for t in _LOCKISH_LEAVES
                ):
                    # Unknown self lock (declared in a base class or
                    # dynamically): wildcard — a guard, but no identity.
                    return f"~{expr.attr}"
                return None
            leaf = expr.attr
            if leaf in self.all_lock_attrs or any(
                t in leaf.lower() for t in _LOCKISH_LEAVES
            ):
                return f"~{leaf}"
            return None
        if isinstance(expr, ast.Name):
            decl = self.model.global_locks.get(expr.id)
            if decl is not None:
                return decl.lock_id
        return None

    def _visit_with(self, node: ast.With | ast.AsyncWith) -> None:
        pushed = 0
        for item in node.items:
            gid = self._guard_id(item.context_expr)
            if gid is None:
                continue
            if self._func:
                info = self._func[-1]
                info.acquired.add(gid)
                for outer in self._guards:
                    if outer != gid:
                        info.order_edges.append((outer, gid, node.lineno))
            self._guards.append(gid)
            pushed += 1
        self.generic_visit(node)
        for _ in range(pushed):
            self._guards.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # -- test-position tracking (check-then-act reads) ------------------

    def _visit_branch(self, node: ast.If | ast.While | ast.IfExp) -> None:
        self._in_test += 1
        self.visit(node.test)
        self._in_test -= 1
        for child in ast.iter_child_nodes(node):
            if child is not node.test:
                self.visit(child)

    visit_If = _visit_branch
    visit_While = _visit_branch
    visit_IfExp = _visit_branch

    # -- accesses -------------------------------------------------------

    def _record_attr(self, attr: str, line: int, kind: str) -> None:
        if not self._func:
            return
        self._func[-1].accesses.append(
            Access(
                attr,
                line,
                kind,
                frozenset(self._guards),
                in_test=kind == "read" and self._in_test > 0,
            )
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
            self._record_attr(node.attr, node.lineno, kind)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        tgt = node.target
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
        ):
            self._record_attr(tgt.attr, node.lineno, "aug")
            self.visit(node.value)
            return
        if isinstance(tgt, ast.Name) and self._func:
            locals_, globals_ = self._locals[-1]
            if tgt.id in globals_ and tgt.id in self.model.module_globals:
                self._func[-1].global_accesses.append(
                    Access(tgt.id, node.lineno, "aug", frozenset(self._guards))
                )
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if not self._func or node.id not in self.model.module_globals:
            return
        locals_, globals_ = self._locals[-1]
        if isinstance(node.ctx, ast.Load):
            if node.id in locals_:
                return
            self._func[-1].global_accesses.append(
                Access(
                    node.id,
                    node.lineno,
                    "read",
                    frozenset(self._guards),
                    in_test=self._in_test > 0,
                )
            )
        elif isinstance(node.ctx, (ast.Store, ast.Del)) and node.id in globals_:
            self._func[-1].global_accesses.append(
                Access(node.id, node.lineno, "write", frozenset(self._guards))
            )

    # -- calls ----------------------------------------------------------

    @staticmethod
    def _bounded(node: ast.Call) -> bool:
        for kw in node.keywords:
            if kw.arg in ("timeout", "block"):
                return True
        leaf = None
        if isinstance(node.func, ast.Attribute):
            leaf = node.func.attr
        if leaf in ("put", "get") and len(node.args) >= 2:
            return True  # explicit positional block arg
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        # ``v = self.attr`` -> calls through v are calls through the
        # attr (hook dispatch: ``hook = self.on_span_close; hook(sp)``).
        if (
            self._func
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Attribute)
            and isinstance(node.value.value, ast.Name)
            and node.value.value.id == "self"
        ):
            self._aliases[-1][node.targets[0].id] = f"self.{node.value.attr}"
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted(node.func)
        if (
            name is not None
            and self._func
            and isinstance(node.func, ast.Name)
            and name in self._aliases[-1]
        ):
            name = self._aliases[-1][name]
        if name is not None and self._func:
            self._func[-1].calls.append(
                CallSite(
                    name,
                    node.lineno,
                    frozenset(self._guards),
                    bounded=self._bounded(node),
                )
            )
        self.generic_visit(node)


def _collect_module_globals(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            names.add(stmt.target.id)
        elif isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
            names.add(stmt.target.id)
    return names


def build_module_model(source: str, rel_path: str) -> ModuleModel:
    """Parse one file into a :class:`ModuleModel` (both passes).  The
    access pass needs the *program-wide* lock-attr vocabulary for
    wildcard guards, so :func:`build_program_model` re-runs it after
    pass 1 has seen every file; this single-file entry point is for
    fixtures and tests."""
    tree = ast.parse(source, filename=rel_path)
    model = ModuleModel(path=rel_path, tree=tree)
    model.module_globals = _collect_module_globals(tree)
    _LockCollector(model).visit(tree)
    attrs = frozenset(
        a for c in model.classes.values() for a in c.locks
    ) | frozenset(model.global_locks)
    _AccessCollector(model, attrs).visit(tree)
    return model


def build_program_model(sources: dict[str, str]) -> dict[str, ModuleModel]:
    """{rel_path: source} -> {rel_path: ModuleModel} with a shared
    lock-attr vocabulary across all files."""
    models: dict[str, ModuleModel] = {}
    for rel, src in sources.items():
        tree = ast.parse(src, filename=rel)
        model = ModuleModel(path=rel, tree=tree)
        model.module_globals = _collect_module_globals(tree)
        _LockCollector(model).visit(tree)
        models[rel] = model
    attrs = frozenset(
        a for m in models.values() for c in m.classes.values() for a in c.locks
    ) | frozenset(n for m in models.values() for n in m.global_locks)
    for model in models.values():
        assert model.tree is not None
        _AccessCollector(model, attrs).visit(model.tree)
    return models


__all__ = [
    "Access",
    "CallSite",
    "ClassInfo",
    "FuncInfo",
    "LockDecl",
    "ModuleModel",
    "build_module_model",
    "build_program_model",
    "dotted",
]
