"""graftlint pass 12 — the static peak-HBM analyzer (memory wall).

See ``checker.py`` for the rule set, ``liveness.py`` for the
buffer-assignment / live-range machinery, and ``waivers.py`` for the
enumerated, stale-tested suppression table.
"""

from .checker import check_mem_case, run_memory_pass
from .waivers import MEM_WAIVERS

__all__ = ["MEM_WAIVERS", "check_mem_case", "run_memory_pass"]
