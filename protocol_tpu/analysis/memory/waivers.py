"""Explicit pass-12 waivers — same doctrine as the pass-7/pass-8
tables: every suppression is enumerated with its rationale, emitted
into ANALYSIS.json's ``memory.waived`` list, and **stale-tested** in
every run that evaluates the table — a waiver that no longer matches a
live finding is itself an error (``stale-waiver``), so a fixed leak
takes its waiver with it.
"""

from __future__ import annotations

from ..concurrency.waivers import Waiver

#: (rule, file substring, message substring) -> rationale — see
#: :class:`~protocol_tpu.analysis.concurrency.waivers.Waiver`.
MEM_WAIVERS: tuple[Waiver, ...] = (
    Waiver(
        rule="unbounded-cache-growth",
        file="protocol_tpu/node/manager.py",
        symbol="Manager._hash_cache",
        reason=(
            "The Poseidon pk-hash memo is bounded by the PEER SET, not "
            "by time: it holds exactly one entry per public key the "
            "node has ever verified, the same population (and the same "
            "lifetime) as the attestation cache that IS the graph.  "
            "Evicting it would re-pay 68 field-level Poseidon rounds "
            "per ingest of a known sender — the 17x admission-plane "
            "hashing win (PERF.md §13) exists to avoid exactly that.  "
            "The epoch-keyed caches this rule polices (cached_proofs / "
            "cached_results grew ring eviction in this PR) leak with "
            "uptime; this one grows with the graph."
        ),
    ),
)

__all__ = ["MEM_WAIVERS"]
