"""Buffer-liveness view of a compiled module — the pass-12 counterpart
of ``comm/hlo_walk``.

The primary source of truth for peak HBM is the compiler's own buffer
assignment, surfaced through jax's AOT path as
``compiled.memory_analysis()`` (argument / output / alias / temp byte
totals per device) and captured into :class:`~..comm.lowering.CommCase`
at compile time.  This module supplies the two things that view cannot:

- **a conservative live-range fallback** (:func:`live_range_peak`) for
  runtimes whose executables expose no memory analysis: a per-
  computation liveness sweep over the optimized-HLO text (def site to
  last use, parameters excluded — they are the caller's bytes), summed
  across computations because nested computations (fusions, while
  bodies) execute inside their callers' arenas.  A deliberate
  over-estimate: the fallback may fail a budget the real buffer
  assignment would pass, never the reverse.
- **attribution** (:func:`largest_temp_site`): the op defining the
  largest non-parameter buffer in the module, with its jax source
  breadcrumb — so a transient-over-budget finding points at the line
  that materialized the offending temporary, the same ``file:line``
  contract as every other graftlint rule.

Text parsing is deliberate, for the same reason as ``hlo_walk``: the
dump format is the compiler's round-trippable syntax, stable where the
proto bindings churn.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from ..comm.hlo_walk import shape_bytes

#: One op line: ``%name = <type> <op>(<operands>)<attrs>`` — the
#: general form this time, not just collectives.
_ANY_OP = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>\([^=]*?\)|\S+)\s+(?P<op>[\w\-]+)"
    r"\((?P<operands>.*?)\)(?P<attrs>.*)$"
)

#: Operand references inside the parenthesized operand list.
_REF = re.compile(r"%(?P<ref>[\w.\-]+)")

_METADATA = re.compile(
    r'metadata=\{[^}]*?source_file="(?P<file>[^"]+)"'
    r"[^}]*?source_line=(?P<line>\d+)"
)
_OP_NAME = re.compile(r'op_name="(?P<op_name>[^"]+)"')

#: Computation headers: ``%name (params...) -> type {`` or ``ENTRY ...``.
_COMPUTATION = re.compile(r"^(?:ENTRY\s+)?%?[\w.\-]+\s*(?:\([^)]*\))?.*\{\s*$")


@dataclass(frozen=True)
class TempSite:
    """The op that defined one temp buffer, with its size and source."""

    bytes: int
    op: str
    op_name: str
    file: str | None
    line: int | None

    def to_dict(self) -> dict:
        return {
            "bytes": self.bytes,
            "op": self.op,
            "op_name": self.op_name,
            "file": self.file,
            "line": self.line,
        }


def _computation_blocks(text: str) -> list[list[str]]:
    """Split a module dump into computation bodies (lists of lines)."""
    blocks: list[list[str]] = []
    current: list[str] | None = None
    for line in text.splitlines():
        stripped = line.rstrip()
        if current is None:
            if _COMPUTATION.match(stripped):
                current = []
            continue
        if stripped.endswith("}") and not stripped.lstrip().startswith("%"):
            blocks.append(current)
            current = None
            continue
        current.append(stripped)
    if current:
        blocks.append(current)
    return blocks


def live_range_peak(text: str) -> int:
    """Conservative peak live bytes of the module's non-parameter
    buffers: per-computation liveness sweep (def to last use), summed
    over computations — nested computations run inside their callers,
    so their arenas can coexist.  An upper bound on what the buffer
    assignment would allocate as its temp arena."""
    total = 0
    for block in _computation_blocks(text):
        defs: list[tuple[str, int]] = []  # (buffer, bytes) in def order
        last_use: dict[str, int] = {}
        sizes: dict[str, int] = {}
        for i, line in enumerate(block):
            m = _ANY_OP.match(line)
            if m is None:
                continue
            name = m.group("name")
            if m.group("op") != "parameter":
                sizes[name] = shape_bytes(m.group("type"))
                defs.append((name, i))
            for ref in _REF.finditer(m.group("operands")):
                if ref.group("ref") in sizes:
                    last_use[ref.group("ref")] = i
        peak = 0
        live = 0
        expiring: dict[int, list[str]] = {}
        for name, i in defs:
            live += sizes[name]
            expiring.setdefault(last_use.get(name, i), []).append(name)
            peak = max(peak, live)
            for dead in expiring.pop(i, ()):
                live -= sizes[dead]
        total += peak
    return total


def largest_temp_site(text: str) -> TempSite | None:
    """The op defining the largest non-parameter buffer in the module
    (metadata-bearing ops preferred at equal size) — the attribution
    anchor for a transient-over-budget finding."""
    best: TempSite | None = None
    for block in _computation_blocks(text):
        for line in block:
            m = _ANY_OP.match(line)
            if m is None or m.group("op") in ("parameter", "constant"):
                continue
            nbytes = shape_bytes(m.group("type"))
            attrs = m.group("attrs")
            meta = _METADATA.search(attrs)
            op_name = _OP_NAME.search(attrs)
            site = TempSite(
                bytes=nbytes,
                op=m.group("op"),
                op_name=op_name.group("op_name") if op_name else "",
                file=meta.group("file") if meta else None,
                line=int(meta.group("line")) if meta else None,
            )
            if (
                best is None
                or nbytes > best.bytes
                or (nbytes == best.bytes and best.file is None and site.file)
            ):
                best = site
    return best


def measured_view(case) -> tuple[dict[str, int], str]:
    """``(per-device byte view, source)`` for one compiled case: the
    buffer assignment when the executable exposed one (``source =
    "buffer-assignment"``), else the conservative live-range walk over
    the module text (``source = "live-range-walk"``; arguments are then
    estimated from the entry parameters, aliasing is assumed absent)."""
    if case.mem is not None:
        mem = case.mem
        resident = mem["argument_bytes"]
        transient = mem["temp_bytes"] + mem["output_bytes"] - mem["alias_bytes"]
        return (
            {
                "resident_bytes": resident,
                "transient_bytes": transient,
                "peak_bytes": resident + transient,
                **mem,
            },
            "buffer-assignment",
        )
    resident = _entry_parameter_bytes(case.module_text)
    transient = live_range_peak(case.module_text)
    return (
        {
            "resident_bytes": resident,
            "transient_bytes": transient,
            "peak_bytes": resident + transient,
        },
        "live-range-walk",
    )


def _entry_parameter_bytes(text: str) -> int:
    """Total bytes of the module's entry parameters (the resident
    estimate of the fallback path)."""
    total = 0
    for block in _computation_blocks(text):
        block_total = 0
        for line in block:
            m = _ANY_OP.match(line)
            if m is not None and m.group("op") == "parameter":
                block_total += shape_bytes(m.group("type"))
        # Entry parameters dominate; nested computations repeat them as
        # their own parameters, so take the max block, not the sum.
        total = max(total, block_total)
    return total


__all__ = [
    "TempSite",
    "largest_temp_site",
    "live_range_peak",
    "measured_view",
]
