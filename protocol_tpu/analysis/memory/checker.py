"""Pass 12 — the static peak-HBM analyzer.

Pass 8 proved the partitioner keeps the *wire* promise; this pass
proves the backend keeps the *footprint* promise.  ROADMAP item 1
(10M peers / 500M edges across >= 2 hosts) is a memory problem before
it is a comm problem — at scale the footprint of the iteration state,
not the matvec FLOPs, is the ceiling (PERF.md §15, arXiv:2105.03874) —
and nothing before this pass stopped a backend from silently
materializing an O(E) temporary, dropping a donation into a copy, or
replicating the full edge table on every host.

For every registered backend it reuses the pass-8 lowering machinery
(``comm.lowering``: real converge entry points compiled under the
8-device CPU mesh, sharded composites at TWO scales where E grows 4x
vs N's 2x — the executables are compiled once and shared with pass 8),
reads the buffer-assignment view captured at compile time
(``compiled.memory_analysis()``; conservative live-range walk over the
optimized HLO as fallback, ``memory/liveness.py``), and checks the
declarative :data:`~protocol_tpu.analysis.budget.MEM_INVARIANTS`
budget the kernel module declared:

- **shard-replicated-edges** — per-device resident (argument) bytes
  exceed the allowance whose edge term is ``E / n_shards``: an edge
  operand replicated across the mesh busts the formula by
  construction, caught here before ROADMAP item 1 makes it a
  2 GB/host mistake;
- **o-e-live-temporary** — transient live bytes (temp arena +
  unaliased outputs) exceed the N/n_segments/rows-linear allowance.
  The transient budget has NO edge coefficient, so a second O(E)-sized
  live buffer beyond the resident plan arrays is structurally
  inexpressible — and the committed budgets are pinned tight enough
  (slack below 4 B/edge at every compiled scale, enforced by test)
  that one cannot hide in a padded constant either;
- **donation-peak-doubled** — a declared donated seed whose aliasing
  did not materialize in the buffer assignment: the dropped alias
  shows up as a doubled f32[N] carry (4 MB extra at the 1M-peer
  shape, silent until HBM pressure);
- **host-staging-over-cap** — a transfer custom-call (or infeed /
  outfeed / send / recv) carrying more bytes than the per-op staging
  cap: an O(E) host staging copy has no place in a converge module.

Pass 12 also owns two AST rules over the long-lived node trees
(``ast_rules.run_mem_ast_pass``): ``host-materialization-of-edges``
and ``unbounded-cache-growth``.  Registry housekeeping mirrors passes
1/8 (``undeclared-mem-budget`` / ``no-mem-recipe`` /
``stale-mem-budget``), and the enumerated waiver table
(``memory/waivers.py``) is stale-tested in every run that evaluates
it — pass-7 doctrine.
"""

from __future__ import annotations

from typing import Any

from ..budget import MEM_INVARIANTS, NON_JAX_BACKENDS, MemBudget
from ..report import Finding
from ..comm.hlo_walk import parse_module
from ..comm.lowering import COMM_BUILDERS, CommCase, build_cases
from .liveness import largest_temp_site, measured_view
from .waivers import MEM_WAIVERS


def _finding(rule: str, message: str, backend: str | None = None,
             file: str | None = None, line: int | None = None,
             severity: str = "error") -> Finding:
    return Finding(
        pass_name="memory", rule=rule, severity=severity, message=message,
        backend=backend, file=file, line=line,
    )


def pod_budget_view(
    budget: MemBudget,
    *,
    n: int,
    edges: int,
    n_segments: int,
    rows: int,
    n_shards: int,
    n_hosts: int = 1,
) -> dict:
    """The per-shard HBM allowance at a pod scale: ``n_shards`` is the
    GLOBAL shard count (``n_hosts × local devices``), ``n_segments``
    and ``rows`` are the per-host plan's — pod partitioning divides the
    edge set per host before the local device cut, so the resident edge
    term divides by the global shard count while the replicated-vector
    terms stay O(N) per device.  Used by ``check_mem_case`` to record
    the multi-host projection of every sharded backend and by
    ``tools/dryrun_pod.py`` to gate each process's measured peak."""
    resident = budget.max_resident(n, edges, n_segments, rows, n_shards)
    transient = budget.max_transient(n, n_segments, rows)
    return {
        "n_hosts": n_hosts,
        "n_shards": n_shards,
        "resident_bytes": resident,
        "transient_bytes": transient,
        "peak_bytes": resident + transient,
    }


def check_mem_case(budget: MemBudget, case: CommCase) -> tuple[list[Finding], dict]:
    """Evaluate one backend-at-one-scale executable against its memory
    budget.  Returns ``(findings, scale record)`` — the record feeds
    the per-backend ``memory`` section of ANALYSIS.json."""
    findings: list[Finding] = []
    dims = case.dims
    n = dims.get("n", 0)
    edges = dims.get("edges", 0)
    segs = dims.get("n_segments", 0)
    rows = dims.get("n_rows", 0)
    shards = dims.get("n_shards", 1)
    scale = f"N={n}/E={edges}"

    view, source = measured_view(case)
    max_resident = budget.max_resident(n, edges, segs, rows, shards)
    max_transient = budget.max_transient(n, segs, rows)

    if view["resident_bytes"] > max_resident:
        findings.append(_finding(
            "shard-replicated-edges",
            f"per-device resident bytes {view['resident_bytes']} at {scale} "
            f"exceed the E/n_shards-scaled allowance of {max_resident:.0f} B "
            f"(resident_edge_bytes={budget.resident_edge_bytes}/"
            f"{shards} shards, resident_n={budget.resident_n}, "
            f"resident_segments={budget.resident_segments}, "
            f"resident_rows={budget.resident_rows}) — an edge-sized "
            f"operand is replicated instead of sharded, the per-host "
            f"footprint ROADMAP item 1 cannot afford",
            case.backend,
        ))
    if view["transient_bytes"] > max_transient:
        site = largest_temp_site(case.module_text)
        findings.append(_finding(
            "o-e-live-temporary",
            f"transient live bytes {view['transient_bytes']} at {scale} "
            f"exceed the N/n_segments-linear allowance of "
            f"{max_transient:.0f} B (transient_n={budget.transient_n}, "
            f"transient_segments={budget.transient_segments}, "
            f"transient_rows={budget.transient_rows}, "
            f"transient_const={budget.transient_const}) — an edge-scale "
            f"buffer is live beyond the resident plan arrays; largest "
            f"temp: {site.bytes if site else '?'} B "
            f"{site.op if site else ''}",
            case.backend,
            site.file if site else None,
            site.line if site else None,
        ))

    # Donation must materialize as buffer aliasing: each declared
    # donated argument is an f32[N] seed, so the alias total must cover
    # 4*N per entry or the carry is doubled.
    if budget.donated_args:
        expected = 4.0 * n * len(budget.donated_args)
        alias = float(view.get("alias_bytes", 0))
        if alias < expected:
            findings.append(_finding(
                "donation-peak-doubled",
                f"declared donated seed(s) {budget.donated_args} alias only "
                f"{alias:.0f} B of the expected {expected:.0f} B at {scale} "
                f"— the donation died in the buffer assignment and the "
                f"f32[N] carry is doubled (4 MB extra at the 1M-peer "
                f"shape, silent until HBM pressure)",
                case.backend,
            ))

    # Host staging: any transfer op over the per-op cap is an O(E)
    # staging copy that has no place in a converge module.
    cap = budget.staging_cap(n)
    host_calls = parse_module(case.module_text).host_calls
    for call in host_calls:
        if call.bytes > cap:
            findings.append(_finding(
                "host-staging-over-cap",
                f"host transfer {call.target or call.op!r} carries "
                f"{call.bytes} B at {scale}, over the staging cap of "
                f"{cap:.0f} B — edge-scale bytes crossing the host "
                f"boundary outside plan build",
                case.backend, call.file, call.line,
            ))

    record = {
        "scale": scale,
        "dims": dims,
        "source": source,
        "measured": view,
        "budget_resident_bytes": max_resident,
        "budget_transient_bytes": max_transient,
        "budget_peak_bytes": max_resident + max_transient,
        "staging_cap_bytes": cap,
        "host_transfers": [h.to_dict() for h in host_calls],
        "violations": len(findings),
    }
    if shards > 1:
        # Multi-host projection: the same budget evaluated with the
        # shard count a 2-host pod doubles to — the edge term halves
        # per shard, everything O(N) stays — recorded so ANALYSIS.json
        # states the pod's per-shard allowance next to the single-host
        # measurement (the dryrun gates the measured side).
        record["pod_projection"] = pod_budget_view(
            budget, n=n, edges=edges, n_segments=segs, rows=rows,
            n_shards=shards * 2, n_hosts=2,
        )
    return findings, record


def _apply_waivers(findings: list[Finding]) -> tuple[list[Finding], list[dict], list[dict]]:
    """Split findings into (live, waived records, stale records) using
    the enumerated MEM_WAIVERS table — pass-7 doctrine."""
    live: list[Finding] = []
    waived: list[dict] = []
    matched: set[int] = set()
    for f in findings:
        hit = next(
            (
                (i, w)
                for i, w in enumerate(MEM_WAIVERS)
                if w.matches(f.rule, f.file or "", f.message)
            ),
            None,
        )
        if hit is None:
            live.append(f)
        else:
            matched.add(hit[0])
            waived.append({
                "rule": f.rule, "file": f.file, "line": f.line,
                "symbol": hit[1].symbol, "reason": hit[1].reason,
            })
    stale = [
        {"symbol": w.symbol, "rule": w.rule, "reason": w.reason}
        for i, w in enumerate(MEM_WAIVERS)
        if i not in matched
    ]
    return live, waived, stale


def run_memory_pass(
    backends: list[str] | None = None,
    *,
    include_zk: bool = False,
) -> tuple[list[Finding], dict[str, Any]]:
    """Compile (or reuse pass 8's executables for) every registered
    backend and check MEM_INVARIANTS, then run the pass-12 AST rules
    over the long-lived node trees.  ``include_zk`` extends the run to
    the zk.graft proving kernels (``graftlint --zk``), whose EC
    compiles are too slow for the default self-budget.  Returns
    ``(findings, memory section)`` for ANALYSIS.json."""
    # Importing the registry imports the kernel modules, which declare
    # their memory budgets next to their kernel/comm budgets.
    from ...parallel import sharded  # noqa: F401  (declares sharded budgets)
    from ...trust.backend import registered_backends
    from ..zk_lowering import register as _register_zk, zk_kernel_names

    registry = registered_backends()
    zk_names = zk_kernel_names()
    if include_zk or (backends and set(backends) & set(zk_names)):
        _register_zk()
    if backends is None:
        targets = registry + zk_names if include_zk else registry
    else:
        targets = backends
    findings: list[Finding] = []
    section: dict[str, Any] = {"backends": {}}

    for name in targets:
        if name in NON_JAX_BACKENDS:
            section["backends"][name] = {
                "status": "skipped", "reason": "non-jax backend",
            }
            continue
        budget = MEM_INVARIANTS.get(name)
        if budget is None:
            section["backends"][name] = {"status": "undeclared"}
            findings.append(_finding(
                "undeclared-mem-budget",
                f"registered backend {name!r} declares no memory budget; "
                "add a MEM_INVARIANTS declaration next to its kernel (the "
                "same policy as kernel and comm budgets, PERF.md §19)",
                name,
            ))
            continue
        if name not in COMM_BUILDERS:
            section["backends"][name] = {"status": "no-recipe"}
            findings.append(_finding(
                "no-mem-recipe",
                f"memory budget declared for {name!r} but the analyzer has "
                "no lowering recipe; coverage would be vacuous",
                name,
            ))
            continue
        try:
            cases = build_cases(name)
        except Exception as exc:  # noqa: BLE001 - report, don't crash the gate
            section["backends"][name] = {
                "status": "lowering-failed", "error": repr(exc),
            }
            findings.append(_finding(
                "mem-lowering-failure",
                f"compiling the step failed: {exc!r}",
                name,
            ))
            continue
        records = []
        n_violations = 0
        for case in cases:
            case_findings, record = check_mem_case(budget, case)
            findings.extend(case_findings)
            n_violations += len(case_findings)
            records.append(record)
        section["backends"][name] = {
            "status": "checked",
            "scales": records,
            "violations": n_violations,
            "budget": {
                "resident_edge_bytes": budget.resident_edge_bytes,
                "resident_n": budget.resident_n,
                "resident_segments": budget.resident_segments,
                "resident_rows": budget.resident_rows,
                "resident_const": budget.resident_const,
                "transient_n": budget.transient_n,
                "transient_segments": budget.transient_segments,
                "transient_rows": budget.transient_rows,
                "transient_const": budget.transient_const,
                "donated_args": list(budget.donated_args),
                "staging_n": budget.staging_n,
                "staging_const": budget.staging_const,
                "notes": budget.notes,
            },
        }

    # Budgets for names no longer in the registry rot silently.  The zk
    # kernel names are live even when this run excludes them (their
    # budgets register whenever the graft modules import in-process).
    if backends is None:
        known = set(registry) | set(zk_names)
        for name in sorted(set(MEM_INVARIANTS) - known):
            findings.append(_finding(
                "stale-mem-budget",
                f"memory budget declared for {name!r} which is not a "
                "registered backend",
                name, severity="warning",
            ))

    # The pass-12 AST rules: host materialization of edge-scale arrays
    # on the epoch loop's critical path, and unbounded cache growth in
    # long-lived node classes.
    if backends is None:
        from ..ast_rules import run_mem_ast_pass

        ast_findings, n_files = run_mem_ast_pass()
        findings.extend(ast_findings)
        section["files_scanned"] = n_files

    live, waived, stale = _apply_waivers(findings)
    if backends is not None:
        # A backend-subset run never evaluates the AST leg, so the
        # staleness of an AST-rule waiver cannot be judged there —
        # only waivers whose domain this run covered may go stale.
        from ..ast_rules import MEM_AST_RULES

        stale = [s for s in stale if s["rule"] not in MEM_AST_RULES]
    for entry in stale:
        # A dead waiver is itself a gate failure — pass-7 doctrine,
        # enforced in every run that evaluates its table.
        live.append(_finding(
            "stale-waiver",
            f"memory waiver {entry['symbol']!r} ({entry['rule']}) matches "
            "no live finding; a fixed leak must take its waiver with it",
            None,
        ))
    section["waived"] = waived
    section["stale_waivers"] = stale
    return live, section


__all__ = ["check_mem_case", "pod_budget_view", "run_memory_pass"]
