"""protocol_tpu.analysis — graftlint, the two-pass static analyzer.

The invariants that make the trust backends fast — one random gather
per windowed step, streaming boundary reads, no f64 upcasts, no host
callbacks inside the jit'd loop, one psum under shard_map — are
contracts of the *lowered* computation, invisible to ruff and mypy.
This subsystem checks them by machine:

- **Pass 1** (``invariants``): trace every registered backend's step
  function to a closed jaxpr on a synthetic graph and check the
  declarative :data:`~protocol_tpu.analysis.budget.KERNEL_INVARIANTS`
  budgets declared next to each kernel.
- **Pass 2** (``ast_rules``): an ``ast.NodeVisitor`` ruleset over
  ``protocol_tpu/`` catching implicit host syncs and import-time
  device work.
- **Pass 7** (``concurrency``): the whole-program threading-contract
  analyzer with its enumerated, stale-tested waiver table.
- **Pass 8** (``comm``): the SPMD-lowering communication analyzer —
  compiles every backend under the 8-device CPU mesh and checks the
  declarative :data:`~protocol_tpu.analysis.budget.COMM_INVARIANTS`
  budgets (collective kinds/counts, O(boundary + N) byte allowances
  evaluated at two scales, host round-trips, donation aliasing) against
  what the partitioner actually emitted.
- **Pass 12** (``memory``): the static peak-HBM analyzer — reads the
  buffer assignment of the same executables pass 8 compiles and checks
  the declarative :data:`~protocol_tpu.analysis.budget.MEM_INVARIANTS`
  budgets (per-shard resident bytes scaling as E/n_shards, an
  N/n_segments-linear transient allowance in which an O(E) live
  temporary is structurally inexpressible, donation-reduces-peak,
  host-staging byte caps), plus the edge-materialization and
  cache-growth AST rules over the long-lived node trees.
- **Pass 13** (``determinism``): the divergence analyzer — an AST
  taint walk over the trees feeding bit-identity sinks (set-order
  materialization, unsorted directory scans, ``hash()``/``id()``
  keys, unseeded RNGs, wall-clock-in-digest) plus an HLO leg over the
  same executables passes 8/12 compile asserting replay-stability
  (no nondeterministic scatter, no reduce-precision, double-compile
  canonical-diff), with its own stale-tested waiver table.  The
  runtime half is ``tools/divergence_probe.py``.

Run as ``python -m protocol_tpu.analysis``: emits ``ANALYSIS.json``
plus ``file:line`` findings; any error-severity finding exits non-zero
(``scripts/lint.sh`` and CI treat it as a hard gate).  PERF.md §9
documents the pinned invariants and how to declare one for a new
backend.

This ``__init__`` stays dependency-light (the kernel modules import
``.budget`` at their own import time); the tracing passes load jax
only when invoked.
"""

from .budget import (
    COMM_INVARIANTS,
    KERNEL_INVARIANTS,
    MEM_INVARIANTS,
    NON_JAX_BACKENDS,
    CollectiveBudget,
    CommBudget,
    GatherBudget,
    KernelBudget,
    MemBudget,
    declare,
    declare_comm,
    declare_mem,
)
from .report import Finding, Report

__all__ = [
    "COMM_INVARIANTS",
    "CollectiveBudget",
    "CommBudget",
    "Finding",
    "GatherBudget",
    "KERNEL_INVARIANTS",
    "KernelBudget",
    "MEM_INVARIANTS",
    "MemBudget",
    "NON_JAX_BACKENDS",
    "Report",
    "declare",
    "declare_comm",
    "declare_mem",
]
