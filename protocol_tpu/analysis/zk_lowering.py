"""Analyzer recipes for the zk.graft proving kernels (PERF.md §22).

The graft backend's five jit kernels carry KERNEL/COMM/MEM budget
declarations next to their definitions, exactly like the trust rungs —
this module supplies the matching **recipes** so the declarations are
never vacuous:

- trace recipes (pass 1): ``make_jaxpr`` of each kernel at a fixed
  small shape — cheap enough to run in the default gate alongside the
  trust backends;
- lowering recipes (passes 8/12/13): real ``lower().compile()`` of the
  same entry points at two scales, feeding the comm walk, the
  buffer-assignment memory check, and the double-compile drift check.

The lowering leg is **opt-in** (``graftlint --zk``, the zk-graft CI
job, and the slow tests): an EC group add inlines 16 Montgomery
multiplies and XLA:CPU pays tens of seconds per compile, which does
not fit the analyzer's 120 s self-budget.  The MSM fold/carry/bucket
recipes therefore also use a smaller lane count than the field
kernels — the carry scan's compile cost grows with ``log2(n/BLOCK)``
inlined group adds, while the budget coefficients are per-lane and
scale-checked all the same.

Proving-plane kernels are single-device by construction, so every comm
budget is zero collectives and the interesting checks are the memory
footprint, the scatter/gather discipline, and pass 13's determinism
wall over the compiled modules (the bucket scatters must stay
``unique_indices=true`` or two proves could legally disagree).
"""

from __future__ import annotations

from typing import Any

from .comm.lowering import COMM_BUILDERS, CommCase, _mem_stats
from .invariants import TRACE_BUILDERS, TraceCase
from .jaxpr_walk import PSUM_PRIMITIVES, collect_primitives

#: Lane count used by the MSM fold/carry/bucket recipes at each comm
#: scale, derived from the scale's N: small enough that the carry
#: scan's log2(n/BLOCK) inlined group adds compile in seconds, large
#: enough that both scales exercise >1 carry round.
_MSM_LANES_DIVISOR = 8


def _jaxpr_psums(jaxpr: Any) -> int:
    return len(collect_primitives(jaxpr, PSUM_PRIMITIVES))


def _zk_modules():
    """The kernel modules (imported on demand; importing declares the
    KERNEL/COMM/MEM budgets)."""
    from ..zk.graft import field, ntt, pippenger

    return field, ntt, pippenger


# -- shared entry-point builders (trace and lowering reuse these) -----------


def _mulmod_entry(n: int):
    import jax.numpy as jnp

    field, _, _ = _zk_modules()
    a = jnp.zeros((n, field.NLIMBS), jnp.uint32)
    return field.mulmod_fr, (a, a)


def _ntt_stage_entry(n: int):
    import jax.numpy as jnp

    field, ntt, _ = _zk_modules()
    L = 64  # a mid NTT stage: blocks x L butterflies
    x = jnp.zeros((max(n // L, 1), L, field.NLIMBS), jnp.uint32)
    tw = jnp.zeros((L // 2, field.NLIMBS), jnp.uint32)
    return ntt._stage_fn(), (x, tw)


def _msm_window_entry(n: int):
    import jax.numpy as jnp

    field, _, pip = _zk_modules()
    digits = jnp.zeros((pip.WINDOWS, n), jnp.int32)
    points = jnp.zeros((n, 3, field.NLIMBS), jnp.uint32)
    return pip._kernels()["window"], (digits, points)


def _msm_scan_entry(n: int):
    import jax
    import jax.numpy as jnp

    from ..ops.segments import block_boundary_flags

    field, _, pip = _zk_modules()
    k = pip._kernels()
    blk = min(pip.BLOCK, n)
    nb = n // blk
    ptsb = jnp.zeros((pip.WINDOWS, nb, blk, 3, field.NLIMBS), jnp.uint32)
    dsb = jnp.zeros((pip.WINDOWS, nb, blk), jnp.int32)

    @jax.jit
    def scan(ptsb, dsb):
        local, tails = k["fold"](ptsb, dsb)
        return local, k["carry"](tails, block_boundary_flags(dsb))

    return scan, (ptsb, dsb)


def _msm_bucket_entry(n: int):
    import jax.numpy as jnp

    field, _, pip = _zk_modules()
    blk = min(pip.BLOCK, n)
    nb = n // blk
    local = jnp.zeros((pip.WINDOWS, n, 3, field.NLIMBS), jnp.uint32)
    ds = jnp.zeros((pip.WINDOWS, n), jnp.int32)
    dsb = jnp.zeros((pip.WINDOWS, nb, blk), jnp.int32)
    c = jnp.zeros((pip.WINDOWS, nb, 3, field.NLIMBS), jnp.uint32)
    return pip._kernels()["bucket"], (local, ds, dsb, c)


#: backend name -> (entry builder, arg names, lane count from scale N).
_ZK_ENTRIES: dict[str, tuple[Any, tuple[str, ...], Any]] = {
    "zk-graft-mulmod": (_mulmod_entry, ("a", "b"), lambda n: n),
    "zk-graft-ntt-stage": (_ntt_stage_entry, ("x", "tw"), lambda n: n),
    "zk-graft-msm-window": (_msm_window_entry, ("digits", "points"), lambda n: n),
    "zk-graft-msm-scan": (
        _msm_scan_entry,
        ("ptsb", "dsb"),
        lambda n: n // _MSM_LANES_DIVISOR,
    ),
    "zk-graft-msm-bucket": (
        _msm_bucket_entry,
        ("local", "ds", "dsb", "c"),
        lambda n: n // _MSM_LANES_DIVISOR,
    ),
}

#: Trace shape for pass 1 (small: tracing cost rides the default gate).
_TRACE_N = 1024


def _make_trace_builder(name: str):
    entry_builder, _, lanes_of = _ZK_ENTRIES[name]

    def build(_graph) -> TraceCase:
        import jax

        fn, args = entry_builder(lanes_of(_TRACE_N))
        jaxpr = jax.make_jaxpr(fn)(*args)
        return TraceCase(name, jaxpr, dims={"n": lanes_of(_TRACE_N)})

    return build


def _make_comm_builder(name: str):
    entry_builder, arg_names, lanes_of = _ZK_ENTRIES[name]

    def build(n: int, e: int) -> CommCase:
        import jax

        lanes = lanes_of(n)
        fn, args = entry_builder(lanes)
        compiled = fn.lower(*args).compile()
        jaxpr = jax.make_jaxpr(fn)(*args)
        return CommCase(
            backend=name,
            dims={"n": lanes, "n_shards": 1},
            module_text=compiled.as_text(),
            arg_names=arg_names,
            jaxpr_psums=_jaxpr_psums(jaxpr),
            mem=_mem_stats(compiled),
        )

    return build


def zk_kernel_names() -> list[str]:
    """The registry slice this module covers (mirrors
    ``zk.graft.registered_zk_kernels`` — asserted in tests)."""
    from ..zk.graft import registered_zk_kernels

    return registered_zk_kernels()


def ensure_budgets() -> list[str]:
    """Import the kernel modules so their KERNEL/COMM/MEM budget
    declarations are registered; returns the kernel names."""
    _zk_modules()
    return zk_kernel_names()


_REGISTERED = False


def register() -> list[str]:
    """Merge the zk recipes into the shared TRACE/COMM builder tables
    (idempotent) and return the kernel names.  Pass 1 calls this in the
    default gate (traces are cheap); the compile passes call it only
    under ``--zk``."""
    global _REGISTERED
    names = ensure_budgets()
    if not _REGISTERED:
        for name in names:
            TRACE_BUILDERS[name] = _make_trace_builder(name)
            COMM_BUILDERS[name] = (_make_comm_builder(name), True)
        _REGISTERED = True
    return names


__all__ = ["ensure_budgets", "register", "zk_kernel_names"]
