"""Findings and the machine-readable ``ANALYSIS.json`` report.

Both analyzer passes emit :class:`Finding` records with ``file:line``
anchors; :class:`Report` aggregates them, renders the human summary,
and serializes the JSON artifact CI uploads.  Exit-code policy: any
``error``-severity finding fails the gate (``scripts/lint.sh``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

SEVERITIES = ("error", "warning", "info")


@dataclass(frozen=True)
class Finding:
    """One analyzer finding, anchored to source where possible."""

    pass_name: str  # "jaxpr" | "ast"
    rule: str  # stable rule id, e.g. "random-gather-budget"
    severity: str  # "error" | "warning" | "info"
    message: str
    file: str | None = None
    line: int | None = None
    backend: str | None = None

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    @property
    def anchor(self) -> str:
        if self.file is None:
            return "<no source>"
        return f"{self.file}:{self.line}" if self.line else self.file

    def render(self) -> str:
        who = f" [{self.backend}]" if self.backend else ""
        return f"{self.severity}: {self.anchor}{who} {self.rule}: {self.message}"

    def to_dict(self) -> dict[str, Any]:
        return {
            "pass": self.pass_name,
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "file": self.file,
            "line": self.line,
            "backend": self.backend,
        }


@dataclass
class Report:
    """Aggregated two-pass analysis result."""

    findings: list[Finding] = field(default_factory=list)
    #: Per-backend bookkeeping from pass 1: declared budget summary and
    #: how many invariants were actually evaluated.
    backends: dict[str, dict[str, Any]] = field(default_factory=dict)
    #: Files scanned by pass 2.
    files_scanned: int = 0
    #: Pass-7 whole-program concurrency section: execution roots, the
    #: inferred guard map, the lock-order graph, and the enumerated
    #: waiver list (analysis/concurrency/).
    concurrency: dict[str, Any] = field(default_factory=dict)
    #: Pass-8 SPMD-lowering section: per-backend collective tables with
    #: byte volumes at each compiled scale, host round-trips, the
    #: input_output_alias map, and the comm waiver list (analysis/comm/).
    comm: dict[str, Any] = field(default_factory=dict)
    #: Pass-12 peak-HBM section: per-backend resident/transient byte
    #: tables at each compiled scale against the MEM_INVARIANTS
    #: allowances, host-transfer volumes, and the memory waiver list
    #: (analysis/memory/).
    memory: dict[str, Any] = field(default_factory=dict)
    #: Pass-13 determinism section: per-backend HLO replay-stability
    #: records (scatter/reduce-precision counts, double-compile drift),
    #: AST files scanned, and the determinism waiver list
    #: (analysis/determinism/).
    determinism: dict[str, Any] = field(default_factory=dict)

    def extend(self, findings: list[Finding]) -> None:
        self.findings.extend(findings)

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def exit_code(self) -> int:
        return 1 if self.errors else 0

    def to_dict(self) -> dict[str, Any]:
        sev = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            sev[f.severity] += 1
        return {
            "version": 1,
            "tool": "protocol_tpu.analysis (graftlint)",
            "summary": {
                **sev,
                "backends_checked": len(self.backends),
                "files_scanned": self.files_scanned,
            },
            "backends": self.backends,
            "concurrency": self.concurrency,
            "comm": self.comm,
            "memory": self.memory,
            "determinism": self.determinism,
            "findings": [f.to_dict() for f in self.findings],
        }

    def write_json(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(self.to_dict(), indent=2) + "\n")

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        sev = {s: sum(1 for f in self.findings if f.severity == s) for s in SEVERITIES}
        lines.append(
            f"analysis: {len(self.backends)} backends / "
            f"{self.files_scanned} files scanned — "
            f"{sev['error']} error(s), {sev['warning']} warning(s), "
            f"{sev['info']} info"
        )
        return "\n".join(lines)


__all__ = ["Finding", "Report", "SEVERITIES"]
