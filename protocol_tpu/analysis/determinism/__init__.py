"""graftlint pass 13 — the determinism wall.

Static divergence analysis over the bit-identity plane: an AST taint
walk flagging divergence-feasible Python sources (``ast_walk``), an
HLO leg asserting every compiled converge entry is replay-stable
(``checker``), and the enumerated stale-tested waiver table
(``waivers``).  The runtime half is ``tools/divergence_probe.py``.
"""

from .ast_walk import DET_AST_RULES, DET_TREES, run_det_ast_pass, scan_det_source
from .checker import (
    canonicalize_hlo,
    check_recompile,
    diff_canonical,
    run_determinism_pass,
    scan_module_text,
)
from .waivers import DET_WAIVERS

__all__ = [
    "DET_AST_RULES",
    "DET_TREES",
    "DET_WAIVERS",
    "canonicalize_hlo",
    "check_recompile",
    "diff_canonical",
    "run_determinism_pass",
    "run_det_ast_pass",
    "scan_det_source",
    "scan_module_text",
]
