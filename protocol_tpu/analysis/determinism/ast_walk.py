"""Pass 13's AST leg: divergence-feasible sources reaching bit-identity
sinks.

The pod substrate stakes correctness on *bit-identical* state across
hosts (per-epoch score digests before a manifest seals, WAL replay to a
control-identical fixed point, pooled proofs byte-equal to in-process
ones).  Every one of those invariants dies the same way: a Python-level
ordering or randomness source that is legal *within one process* leaks
into serialized state and differs *between* processes.  This walker
polices the source side over the trees that feed the sinks
(:data:`DET_TREES` — node/, parallel/, ingest/, prover/, models/):

- ``set-order-to-state`` — a set/frozenset iterated in hash order and
  materialized into a sequence, array, or accumulated float
  (``list(s)``, ``np.asarray(s)``, ``sum(s)``, a list comprehension or
  accumulating ``for`` over it).  CPython string hashes are salted per
  process (``PYTHONHASHSEED``), so set order is the canonical
  divergence source.  ``sorted(s)`` (or any order-insensitive consumer:
  ``len``/``min``/``max``/``any``/``all``/``set``) is the fix and stays
  quiet.
- ``unsorted-dirscan`` — ``os.listdir``/``os.scandir``/``glob.glob``/
  ``Path.glob``/``iterdir``/``rglob`` results consumed without a
  ``sorted(...)`` wrapper: directory scan order is filesystem- and
  history-dependent, so any state derived from it differs across hosts
  (and across reboots of the same host).
- ``hash-ordering`` — builtin ``hash()``/``id()`` influencing a key,
  index, or ordering.  ``hash(str)`` is salted per process; ``id()`` is
  an allocation address.  Even the currently-stable cases (tuples of
  ints) are CPython implementation details a bit-identity plane must
  not stand on.
- ``unseeded-rng`` — module-level ``random.*`` draws, ``random.Random()``
  with no seed, global ``np.random.*`` draws, or
  ``np.random.default_rng()`` with no seed: every draw diverges across
  hosts by construction.  Seeded constructors
  (``np.random.default_rng(seed)``) are the doctrine and stay quiet.
- ``clock-in-digest`` — a wall-clock / pid / uuid value flowing into a
  digest, a seed, or a name that will be treated as one (function-local
  taint: names assigned from ``time.time()``-family calls,
  ``os.getpid()``, or ``uuid.*`` are tainted; the finding fires when a
  tainted value reaches ``hashlib.*``, ``.update(...)``, an RNG
  constructor/seed, or a ``*seed``/``*digest``/``*nonce`` binding).
  Timing *measurement* (deltas into metrics) never reaches a sink and
  stays quiet.

The walker is deliberately source-side and tree-scoped rather than
whole-program: the trees it covers are exactly the ones whose values
reach the bit-identity sinks (WAL record bytes, checkpoint columns,
pod shard stamps + manifest seal, ProofJob ``job_seed`` fields, churn
draws, partition keys), so a source finding here is a sink finding by
construction — the runtime half (``tools/divergence_probe.py``) closes
the loop end to end.
"""

from __future__ import annotations

import ast
from pathlib import Path

from ..report import Finding

#: Trees whose values reach a bit-identity sink: the node state plane
#: (WAL/checkpoint/pod manifests), the partitioner and pod plan, the
#: admission plane (shard keys, dedup verdicts), the proving plane
#: (job seeds, statement bytes), and the deterministic stream models.
DET_TREES = ("node", "parallel", "ingest", "prover", "models")

#: Rules this leg reports (the pass-12 filtering doctrine: a scoped
#: pass only reports its own rules, so ``--pass all`` never doubles).
DET_AST_RULES = frozenset(
    {
        "set-order-to-state",
        "unsorted-dirscan",
        "hash-ordering",
        "unseeded-rng",
        "clock-in-digest",
    }
)

# -- name helpers -----------------------------------------------------------


def _dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


#: Order-insensitive consumers: feeding a set or a dirscan through one
#: of these launders the ordering dependence away.
_ORDER_INSENSITIVE = frozenset(
    {"sorted", "len", "min", "max", "any", "all", "set", "frozenset"}
)

#: Materializers that freeze an iterable's order into state.
_SEQ_MATERIALIZERS = frozenset({"list", "tuple", "enumerate"})
_NP_MATERIALIZERS = frozenset(
    {"array", "asarray", "fromiter", "stack", "concatenate"}
)

#: Dotted call names that scan a directory in filesystem order.
_DIRSCAN_DOTTED = frozenset(
    {"os.listdir", "os.scandir", "glob.glob", "glob.iglob"}
)
#: Attribute methods (on Path-likes) that do the same.
_DIRSCAN_METHODS = frozenset({"glob", "iglob", "rglob", "iterdir"})

#: Wall-clock / process-identity sources for the clock taint.
_CLOCK_DOTTED = frozenset(
    {
        "time.time",
        "time.time_ns",
        "time.monotonic",
        "time.monotonic_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "os.getpid",
        "uuid.uuid1",
        "uuid.uuid4",
        "datetime.now",
        "datetime.utcnow",
        "datetime.datetime.now",
        "datetime.datetime.utcnow",
    }
)

#: Module-level RNG draws (process-global state, never seeded per use).
_RANDOM_MODULE_FNS = frozenset(
    {
        "random.random",
        "random.randint",
        "random.randrange",
        "random.choice",
        "random.choices",
        "random.shuffle",
        "random.sample",
        "random.uniform",
        "random.gauss",
        "random.getrandbits",
    }
)
_NP_RANDOM_FNS = frozenset(
    {
        "rand",
        "randn",
        "randint",
        "random",
        "choice",
        "permutation",
        "shuffle",
        "random_sample",
        "standard_normal",
        "exponential",
        "integers",
    }
)

#: Digest-ish callables a tainted clock value must not reach.
_DIGEST_CALLS = frozenset(
    {
        "hashlib.sha256",
        "hashlib.sha1",
        "hashlib.sha512",
        "hashlib.sha3_256",
        "hashlib.blake2b",
        "hashlib.blake2s",
        "hashlib.md5",
        "hashlib.new",
    }
)
#: Seed-consuming constructors (a clock-derived seed is divergence).
_SEED_CALLS = frozenset(
    {"random.Random", "random.seed", "np.random.default_rng",
     "numpy.random.default_rng", "np.random.seed", "numpy.random.seed"}
)


def _is_np_random(dotted: str) -> bool:
    for prefix in ("np.random.", "numpy.random.", "jnp.random."):
        if dotted.startswith(prefix):
            return dotted[len(prefix):] in _NP_RANDOM_FNS
    return False


def _seedish_name(name: str) -> bool:
    low = name.rsplit(".", 1)[-1].lower()
    return low.endswith(("seed", "digest", "nonce")) or low in (
        "seed", "digest", "nonce"
    )


class _DetVisitor(ast.NodeVisitor):
    """One file's walk.  Scoping is function-local for taint and
    set-ness (module-level constants are walked in the module 'frame'):
    the rules are source-side, so a cross-function flow is the *next*
    function's finding when it materializes there."""

    def __init__(self, rel_path: str):
        self.rel_path = rel_path
        self.findings: list[Finding] = []
        #: Names (and ``self.x`` dotted attrs) known to hold sets.
        self._setish: set[str] = set()
        #: Names holding clock/pid/uuid-derived values.
        self._clock_tainted: set[str] = set()
        #: Enclosing order-insensitive consumer calls (sorted & co).
        self._insensitive_depth = 0

    # -- emit -------------------------------------------------------------

    def _emit(self, rule: str, message: str, node: ast.AST) -> None:
        self.findings.append(
            Finding(
                pass_name="determinism",
                rule=rule,
                severity="error",
                message=message,
                file=self.rel_path,
                line=getattr(node, "lineno", None),
            )
        )

    # -- set-ness ---------------------------------------------------------

    def _is_setish(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if dotted in ("set", "frozenset"):
                return True
            # s.union(t), s.difference(t), ... on a known set.
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "union", "difference", "intersection", "symmetric_difference",
                "copy",
            ):
                return self._is_setish(node.func.value)
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
        ):
            return self._is_setish(node.left) or self._is_setish(node.right)
        dotted = _dotted(node)
        return dotted is not None and dotted in self._setish

    def _set_annotation(self, ann: ast.AST | None) -> bool:
        if ann is None:
            return False
        base = ann.value if isinstance(ann, ast.Subscript) else ann
        dotted = _dotted(base)
        return dotted in ("set", "frozenset", "Set", "FrozenSet")

    # -- clock taint ------------------------------------------------------

    def _contains_clock(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                dotted = _dotted(sub.func)
                if dotted in _CLOCK_DOTTED:
                    return True
            dotted = _dotted(sub)
            if dotted is not None and dotted in self._clock_tainted:
                return True
        return False

    # -- assignments: track set-ness and taint ----------------------------

    def _record_target(self, target: ast.AST, value: ast.AST) -> None:
        dotted = _dotted(target)
        if dotted is None:
            return
        if self._is_setish(value):
            self._setish.add(dotted)
        else:
            self._setish.discard(dotted)
        if self._contains_clock(value):
            self._clock_tainted.add(dotted)
            if _seedish_name(dotted):
                self._emit(
                    "clock-in-digest",
                    f"wall-clock/pid-derived value bound to {dotted!r} — a "
                    "clock-derived seed/digest/nonce differs on every host "
                    "and replay; derive it from the statement or epoch "
                    "instead",
                    value,
                )
        else:
            self._clock_tainted.discard(dotted)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._record_target(target, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        dotted = _dotted(node.target)
        if dotted is not None and self._set_annotation(node.annotation):
            self._setish.add(dotted)
        if node.value is not None:
            self._record_target(node.target, node.value)
        self.generic_visit(node)

    # -- fresh scopes -----------------------------------------------------

    def _scoped_visit(self, node: ast.AST) -> None:
        saved_set, saved_taint = set(self._setish), set(self._clock_tainted)
        self.generic_visit(node)
        self._setish, self._clock_tainted = saved_set, saved_taint

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scoped_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scoped_visit(node)

    # -- calls: the rule dispatch -----------------------------------------

    def _is_dirscan(self, node: ast.Call) -> bool:
        dotted = _dotted(node.func)
        if dotted is not None and dotted in _DIRSCAN_DOTTED:
            return True
        return (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _DIRSCAN_METHODS
        )

    def _check_materialization(self, node: ast.Call) -> None:
        dotted = _dotted(node.func) or ""
        tail = dotted.rsplit(".", 1)[-1]
        is_join = isinstance(node.func, ast.Attribute) and node.func.attr == "join"
        is_mat = (
            dotted in _SEQ_MATERIALIZERS
            or tail in _NP_MATERIALIZERS
            or dotted in ("json.dumps",)
            or dotted == "sum"
            or is_join
        )
        if not is_mat:
            return
        for arg in node.args:
            probe = arg
            if isinstance(arg, ast.GeneratorExp):
                probe = arg.generators[0].iter
            if self._is_setish(probe):
                what = "sum() over" if dotted == "sum" else f"{tail or 'join'}() of"
                self._emit(
                    "set-order-to-state",
                    f"{what} a set iterates in per-process hash order "
                    "(PYTHONHASHSEED) before freezing it into state — wrap "
                    "the set in sorted(...) so every host materializes the "
                    "same sequence",
                    node,
                )
                return

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)

        # unsorted-dirscan: scan order consumed without sorted(...).
        if self._is_dirscan(node) and self._insensitive_depth == 0:
            self._emit(
                "unsorted-dirscan",
                "directory scan consumed in filesystem order — wrap it in "
                "sorted(...): scan order is inode-history-dependent, so "
                "state derived from it differs across hosts and reboots",
                node,
            )

        # hash-ordering: builtin hash()/id().
        if dotted in ("hash", "id") and node.args:
            self._emit(
                "hash-ordering",
                f"builtin {dotted}() influencing a key or ordering — "
                "hash(str) is salted per process (PYTHONHASHSEED) and id() "
                "is an allocation address; derive keys from a stable mix "
                "(splitmix/sha256) of the value instead",
                node,
            )

        # unseeded-rng.
        if dotted is not None:
            if dotted in _RANDOM_MODULE_FNS or _is_np_random(dotted):
                self._emit(
                    "unseeded-rng",
                    f"module-level RNG draw {dotted}() uses process-global "
                    "state — every host draws a different value; thread a "
                    "seeded np.random.default_rng(seed) through instead",
                    node,
                )
            elif dotted in (
                "random.Random",
                "np.random.default_rng",
                "numpy.random.default_rng",
            ) and not node.args and not node.keywords:
                self._emit(
                    "unseeded-rng",
                    f"{dotted}() constructed without a seed draws from OS "
                    "entropy — a bit-identity plane needs every stream "
                    "derived from the shared protocol seed",
                    node,
                )

        # clock-in-digest: a tainted value reaching a digest/seed sink.
        sink = None
        if dotted is not None and (
            dotted in _DIGEST_CALLS or dotted in _SEED_CALLS
        ):
            sink = dotted
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "update":
            sink = f"{_dotted(node.func) or '.update'}"
        if sink is not None:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if self._contains_clock(arg):
                    self._emit(
                        "clock-in-digest",
                        f"wall-clock/pid value flows into {sink}(...) — the "
                        "digest/seed differs on every host and every "
                        "replay, so nothing downstream can be bit-identical",
                        node,
                    )
                    break

        # set-order-to-state: materializers freezing set order.
        if self._insensitive_depth == 0:
            self._check_materialization(node)

        # Descend; order-insensitive consumers launder their arguments.
        if dotted in _ORDER_INSENSITIVE:
            self._insensitive_depth += 1
            self.generic_visit(node)
            self._insensitive_depth -= 1
        else:
            self.generic_visit(node)

    # -- comprehensions and accumulation loops ----------------------------

    def _iterates_setish(self, comp: ast.ListComp | ast.DictComp | ast.GeneratorExp) -> bool:
        return any(self._is_setish(g.iter) for g in comp.generators)

    def visit_ListComp(self, node: ast.ListComp) -> None:
        if self._insensitive_depth == 0 and self._iterates_setish(node):
            self._emit(
                "set-order-to-state",
                "list comprehension over a set freezes per-process hash "
                "order into a sequence — iterate sorted(...) instead",
                node,
            )
        self.generic_visit(node)

    def visit_DictComp(self, node: ast.DictComp) -> None:
        if self._insensitive_depth == 0 and self._iterates_setish(node):
            self._emit(
                "set-order-to-state",
                "dict comprehension over a set inherits per-process hash "
                "order as insertion order — anything serializing this dict "
                "diverges; iterate sorted(...) instead",
                node,
            )
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if self._insensitive_depth == 0 and self._is_setish(node.iter):
            # Only accumulation bodies freeze the order into state:
            # .append/.add-to-list, augmented assignment, subscript
            # stores.  A pure membership/side-effect loop is quiet.
            accumulates = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ) and sub.func.attr in ("append", "extend", "write"):
                    accumulates = True
                    break
                if isinstance(sub, ast.AugAssign):
                    accumulates = True
                    break
            if accumulates:
                self._emit(
                    "set-order-to-state",
                    "loop over a set accumulates in per-process hash order "
                    "— float sums and appended sequences inherit "
                    "PYTHONHASHSEED; iterate sorted(...) instead",
                    node,
                )
        self.generic_visit(node)


def scan_det_source(source: str, rel_path: str) -> list[Finding]:
    """Scan one file's source with the pass-13 rules; ``rel_path`` is
    repo-relative (it anchors findings and scopes nothing — tree scope
    is the pass walker's job, mirroring pass 12)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [
            Finding(
                pass_name="determinism",
                rule="syntax-error",
                severity="error",
                message=f"unparseable source: {exc.msg}",
                file=rel_path,
                line=exc.lineno,
            )
        ]
    visitor = _DetVisitor(rel_path)
    visitor.visit(tree)
    return visitor.findings


def run_det_ast_pass(root: str | Path | None = None) -> tuple[list[Finding], int]:
    """Pass 13's AST leg over :data:`DET_TREES`; returns
    ``(findings, files scanned)`` — the pass-12 walker shape."""
    if root is None:
        root = Path(__file__).resolve().parent.parent.parent.parent
    root = Path(root)
    findings: list[Finding] = []
    files = [
        path
        for tree in DET_TREES
        for path in sorted((root / "protocol_tpu" / tree).rglob("*.py"))
    ]
    for path in files:
        rel = str(path.relative_to(root))
        found = scan_det_source(path.read_text(), rel)
        findings.extend(f for f in found if f.rule in DET_AST_RULES)
    return findings, len(files)


__all__ = [
    "DET_AST_RULES",
    "DET_TREES",
    "run_det_ast_pass",
    "scan_det_source",
]
